// Jump the system wall clock by a signed delta in milliseconds.
//
// TPU-framework equivalent of the reference's clock-bump fault program
// (jepsen resources/bump-time.c, uploaded and compiled on DB nodes by the
// clock nemesis): reads the current CLOCK_REALTIME, adds the delta, and
// sets it back, so a database under test experiences a step change in
// wall-clock time. Requires CAP_SYS_TIME (run as root).
//
// Usage: bump-time <delta-ms>

#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>

int main(int argc, char **argv) {
  if (argc != 2) {
    std::fprintf(stderr, "usage: %s <delta-ms>\n", argv[0]);
    return 2;
  }
  char *end = nullptr;
  long long delta_ms = std::strtoll(argv[1], &end, 10);
  if (end == argv[1] || *end != '\0') {
    std::fprintf(stderr, "invalid delta: %s\n", argv[1]);
    return 2;
  }

  timespec now{};
  if (clock_gettime(CLOCK_REALTIME, &now) != 0) {
    std::perror("clock_gettime");
    return 1;
  }

  long long ns = now.tv_nsec + (delta_ms % 1000) * 1000000LL;
  now.tv_sec += delta_ms / 1000 + ns / 1000000000LL;
  now.tv_nsec = ns % 1000000000LL;
  if (now.tv_nsec < 0) {
    now.tv_nsec += 1000000000LL;
    now.tv_sec -= 1;
  }

  if (clock_settime(CLOCK_REALTIME, &now) != 0) {
    std::perror("clock_settime");
    return 1;
  }
  std::printf("%lld.%09ld\n", static_cast<long long>(now.tv_sec),
              now.tv_nsec);
  return 0;
}
