// Oscillate the system wall clock between "true" time and true+delta,
// flipping every <period> ms for <duration> seconds.
//
// TPU-framework equivalent of the reference's clock-strobe fault program
// (jepsen resources/strobe-time.c): true time is tracked against
// CLOCK_MONOTONIC so our own writes to CLOCK_REALTIME don't compound — we
// record the (realtime - monotonic) offset once at startup and reconstruct
// true realtime from the monotonic clock on every flip. Requires
// CAP_SYS_TIME (run as root).
//
// Usage: strobe-time <delta-ms> <period-ms> <duration-s>

#include <cstdio>
#include <cstdlib>
#include <ctime>

namespace {

constexpr long long kBillion = 1000000000LL;

long long to_ns(const timespec &t) {
  return t.tv_sec * kBillion + t.tv_nsec;
}

timespec from_ns(long long ns) {
  timespec t;
  t.tv_sec = ns / kBillion;
  t.tv_nsec = ns % kBillion;
  if (t.tv_nsec < 0) {
    t.tv_nsec += kBillion;
    t.tv_sec -= 1;
  }
  return t;
}

long long now(clockid_t clock) {
  timespec t{};
  clock_gettime(clock, &t);
  return to_ns(t);
}

void sleep_ms(long long ms) {
  timespec t = from_ns(ms * 1000000LL);
  nanosleep(&t, nullptr);
}

}  // namespace

int main(int argc, char **argv) {
  if (argc != 4) {
    std::fprintf(stderr, "usage: %s <delta-ms> <period-ms> <duration-s>\n",
                 argv[0]);
    return 2;
  }
  const long long delta_ns = std::atoll(argv[1]) * 1000000LL;
  const long long period_ms = std::atoll(argv[2]);
  const long long duration_ns = std::atoll(argv[3]) * kBillion;

  // True realtime = monotonic + base_offset, immune to our own writes.
  const long long base_offset =
      now(CLOCK_REALTIME) - now(CLOCK_MONOTONIC);
  const long long t_end = now(CLOCK_MONOTONIC) + duration_ns;

  bool skewed = false;
  while (now(CLOCK_MONOTONIC) < t_end) {
    skewed = !skewed;
    long long true_rt = now(CLOCK_MONOTONIC) + base_offset;
    timespec t = from_ns(true_rt + (skewed ? delta_ns : 0));
    if (clock_settime(CLOCK_REALTIME, &t) != 0) {
      std::perror("clock_settime");
      return 1;
    }
    sleep_ms(period_ms);
  }

  // Restore true time.
  timespec t = from_ns(now(CLOCK_MONOTONIC) + base_offset);
  clock_settime(CLOCK_REALTIME, &t);
  return 0;
}
