// Native history packer: the O(R x W) event walk of
// jepsen_tpu/lin/prepare.py::prepare, in C++.
//
// The reference keeps its whole checker on a 32GB JVM (project.clj:22-25);
// our device kernel makes the *search* cheap, which leaves host-side
// packing of 100k-op histories as the visible cost — this library removes
// it. Semantics are bit-identical to the Python walk (slot allocation is
// the same LIFO free list), parity-tested in tests/test_native_pack.py.
//
// C ABI only; loaded via ctypes (jepsen_tpu/native_ext.py).

#include <algorithm>
#include <cstdint>
#include <vector>

extern "C" {

// Returns 0 on success; -1 = concurrency window exceeded max_window
// (*out_window = offending history position); -2 = bad input.
//
// Inputs are per logical op (n_ops of them, pre-sorted by invoke_pos):
//   invoke_pos[i], return_pos[i] (-1 if crashed), f_id/v0/v1[i] (ignored
//   when fill_fv == 0).
// Outputs are caller-allocated with R = #ops having return_pos >= 0:
//   ret_slot[R], ret_op[R], active[R*max_window] (u8),
//   slot_f[R*max_window], slot_v[R*max_window*2], slot_op[R*max_window],
//   *out_window = max slots in use.
int jtpu_pack_events(int32_t n_ops,
                     const int32_t* invoke_pos,
                     const int32_t* return_pos,
                     const int32_t* f_id,
                     const int32_t* v0,
                     const int32_t* v1,
                     int32_t nil_value,
                     int32_t max_window,
                     int32_t fill_fv,
                     int32_t R,
                     int32_t* ret_slot,
                     int32_t* ret_op,
                     uint8_t* active,
                     int32_t* slot_f,
                     int32_t* slot_v,
                     int32_t* slot_op,
                     int32_t* out_window) {
  if (n_ops < 0 || max_window <= 0 || R < 0) return -2;

  // Event stream over op endpoints: (pos, kind, op). kind 0 = invoke
  // sorts before kind 1 = return at equal positions, matching the Python
  // tuple sort (positions are distinct in real histories anyway).
  struct Ev {
    int32_t pos;
    int32_t kind;
    int32_t op;
  };
  std::vector<Ev> events;
  events.reserve(static_cast<size_t>(n_ops) * 2);
  int32_t r_expected = 0;
  for (int32_t i = 0; i < n_ops; ++i) {
    events.push_back({invoke_pos[i], 0, i});
    if (return_pos[i] >= 0) {
      events.push_back({return_pos[i], 1, i});
      ++r_expected;
    }
  }
  if (r_expected != R) return -2;
  std::sort(events.begin(), events.end(), [](const Ev& a, const Ev& b) {
    if (a.pos != b.pos) return a.pos < b.pos;
    if (a.kind != b.kind) return a.kind < b.kind;
    return a.op < b.op;
  });

  // LIFO free list identical to the Python `free` stack: initialized so
  // the first pop yields slot 0, frees push back for immediate reuse.
  std::vector<int32_t> free_slots;
  free_slots.reserve(max_window);
  for (int32_t s = max_window - 1; s >= 0; --s) free_slots.push_back(s);

  std::vector<int32_t> slot_of(n_ops, -1);
  // cur_op[s] = op occupying slot s, or -1. Iterating slots 0..max_used
  // reproduces the Python dict's insertion-order row fill superset: the
  // row contents are identical (order within a row doesn't matter, each
  // slot writes its own column).
  std::vector<int32_t> cur_op(max_window, -1);
  int32_t max_used = 0;
  int32_t r = 0;
  const int32_t W = max_window;

  for (const Ev& e : events) {
    if (e.kind == 0) {
      if (free_slots.empty()) {
        *out_window = e.pos;
        return -1;
      }
      int32_t s = free_slots.back();
      free_slots.pop_back();
      slot_of[e.op] = s;
      cur_op[s] = e.op;
      if (s + 1 > max_used) max_used = s + 1;
    } else {
      int32_t s = slot_of[e.op];
      ret_slot[r] = s;
      ret_op[r] = e.op;
      uint8_t* act_row = active + static_cast<size_t>(r) * W;
      int32_t* f_row = slot_f + static_cast<size_t>(r) * W;
      int32_t* v_row = slot_v + static_cast<size_t>(r) * W * 2;
      int32_t* op_row = slot_op + static_cast<size_t>(r) * W;
      for (int32_t slot = 0; slot < max_used; ++slot) {
        int32_t occ = cur_op[slot];
        if (occ < 0) continue;
        act_row[slot] = 1;
        op_row[slot] = occ;
        if (fill_fv) {
          f_row[slot] = f_id[occ];
          v_row[slot * 2] = v0[occ];
          v_row[slot * 2 + 1] = v1[occ];
        }
      }
      ++r;
      cur_op[s] = -1;
      slot_of[e.op] = -1;
      free_slots.push_back(s);
    }
  }
  (void)nil_value;
  *out_window = max_used;
  return 0;
}

}  // extern "C"
