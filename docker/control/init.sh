#!/bin/sh
# Control-node init: materialize the SSH private key passed via env
# (newlines encoded as "|" by up.sh), trust the five nodes, then idle so
# the operator can `docker exec -it jepsen-tpu-control bash`.
set -e

mkdir -p /root/.ssh && chmod 700 /root/.ssh
if [ -n "$SSH_PRIVATE_KEY" ]; then
    printf '%s' "$SSH_PRIVATE_KEY" | tr '|' '\n' > /root/.ssh/id_rsa
    chmod 600 /root/.ssh/id_rsa
fi

: > /root/.ssh/known_hosts
for n in n1 n2 n3 n4 n5; do
    for i in $(seq 1 60); do
        if ssh-keyscan -T 2 "$n" >> /root/.ssh/known_hosts 2>/dev/null; then
            break
        fi
        sleep 1
    done
done

echo "jepsen-tpu control ready; nodes n1..n5 reachable over ssh as root."
echo "try: python -m jepsen_tpu.cli --help"
exec sleep infinity
