#!/bin/sh
# DB-node init: accept the control node's key, allow root login, start sshd.
set -e

if [ -n "$AUTHORIZED_KEYS" ]; then
    echo "$AUTHORIZED_KEYS" > /root/.ssh/authorized_keys
    chmod 600 /root/.ssh/authorized_keys
fi
if [ -n "$ROOT_PASS" ]; then
    echo "root:$ROOT_PASS" | chpasswd
fi

sed -i 's/^#\?PermitRootLogin.*/PermitRootLogin yes/' /etc/ssh/sshd_config

exec /usr/sbin/sshd -D -e
