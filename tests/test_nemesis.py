"""Nemesis grudge topology property tests, mirroring the reference's
nemesis_test.clj:18-88 (bisect/complete-grudge/bridge/majorities-ring ring
walk), plus partitioner/compose behavior over the dummy transport."""

import pytest

from jepsen_tpu import control as c
from jepsen_tpu import nemesis as n
from jepsen_tpu import net
from jepsen_tpu import tests_support as ts
from jepsen_tpu.history import Op
from jepsen_tpu.util import majority

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick

NODES = ["n1", "n2", "n3", "n4", "n5"]


class TestGrudgeMath:
    def test_bisect(self):
        assert n.bisect([]) == ([], [])
        assert n.bisect([1, 2, 3]) == ([1], [2, 3])
        assert n.bisect([1, 2, 3, 4]) == ([1, 2], [3, 4])

    def test_split_one(self):
        loner, rest = n.split_one(NODES, loner="n3")
        assert loner == ["n3"]
        assert rest == ["n1", "n2", "n4", "n5"]

    def test_complete_grudge(self):
        g = n.complete_grudge(n.bisect(NODES))
        assert g["n1"] == {"n3", "n4", "n5"}
        assert g["n3"] == {"n1", "n2"}
        # symmetric: a grudges b iff b grudges a
        for a, enemies in g.items():
            for b in enemies:
                assert a in g[b]

    def test_bridge(self):
        g = n.bridge(NODES)
        # bridge node (n3) snubs nobody and is snubbed by nobody
        assert "n3" not in g
        for a, enemies in g.items():
            assert "n3" not in enemies
        # halves can't talk: n1/n2 vs n4/n5
        assert g["n1"] == {"n4", "n5"}
        assert g["n5"] == {"n1", "n2"}

    @pytest.mark.parametrize("size", [3, 5, 7, 9])
    def test_majorities_ring_properties(self, size):
        """nemesis_test.clj:39-48: one grudge entry per node; nobody snubs
        themselves; every node sees (= doesn't snub) exactly a majority."""
        nodes = [f"n{i}" for i in range(size)]
        g = n.majorities_ring(nodes)
        m = majority(size)
        assert set(g) == set(nodes)
        for node, snubbed in g.items():
            assert node not in snubbed
            assert len(snubbed) == size - m

    def test_majorities_ring_five_node_palindrome(self):
        """nemesis_test.clj:50-87: with 5 nodes every node talks to its two
        ring neighbors symmetrically — walking the ring one way then back
        yields a palindromic path covering all nodes."""
        g = n.majorities_ring(NODES)
        universe = set(g)
        start = next(iter(g))
        frm, node, returning, path = None, start, False, []
        for _ in range(2 * len(NODES) + 2):
            vis = universe - g[node]
            assert len(vis) == 3
            assert node in vis
            if frm is not None and node == start:
                if returning:
                    path.append(node)
                    break
                frm, node, returning = node, frm, True
                path.append(start)
                continue
            nxt = next(iter(vis - {node, frm}))
            frm, node = node, nxt
            path.append(frm)
        assert set(path) == universe
        assert path == path[::-1]
        assert len(path) == 2 * len(universe) + 1


class TestPartitioner:
    def make_test(self):
        transport = c.DummyTransport()
        return ts.noop_test(transport=transport, net=net.iptables), transport

    def test_start_stop(self):
        test, transport = self.make_test()
        nem = n.partition_halves().setup(test)
        res = nem.invoke(test, Op("info", "start", None))
        assert "Cut off" in res.value
        drops = [cmd for _, cmd in transport.log if "-j DROP" in cmd]
        # complete grudge over 2|3 split: 2*3*2 = 12 directed drops
        assert len(drops) == 12
        res = nem.invoke(test, Op("info", "stop", None))
        assert res.value == "fully connected"
        assert any("-F" in cmd for _, cmd in transport.log)

    def test_unknown_f_raises(self):
        test, _ = self.make_test()
        with pytest.raises(ValueError):
            n.partition_halves().invoke(test, Op("info", "frob", None))


class TestCompose:
    def test_routing_with_rewrite(self):
        test = ts.noop_test(transport=c.DummyTransport(), net=net.noop)
        seen = []

        class Recorder(n.Nemesis):
            def __init__(self, name):
                self.name = name

            def invoke(self, t, op):
                seen.append((self.name, op.f))
                return op

        nem = n.compose([
            (frozenset(["kill"]), Recorder("killer")),
            ({"split-start": "start", "split-stop": "stop"},
             Recorder("splitter")),
        ]).setup(test)

        out = nem.invoke(test, Op("info", "kill", None))
        assert out.f == "kill" and seen[-1] == ("killer", "kill")
        out = nem.invoke(test, Op("info", "split-start", None))
        # inner nemesis saw the rewritten f; outer op keeps its name
        assert seen[-1] == ("splitter", "start") and out.f == "split-start"
        with pytest.raises(ValueError):
            nem.invoke(test, Op("info", "mystery", None))


class TestNodeStartStopper:
    def test_lifecycle(self):
        test = ts.noop_test(transport=c.DummyTransport())
        events = []
        nem = n.node_start_stopper(
            lambda nodes: nodes[0],
            lambda t, node: events.append(("start", node)) or "started",
            lambda t, node: events.append(("stop", node)) or "stopped")
        r = nem.invoke(test, Op("info", "stop", None))
        assert r.value == "not-started"
        r = nem.invoke(test, Op("info", "start", None))
        assert r.value == {"n1": "started"}
        r = nem.invoke(test, Op("info", "start", None))
        assert "already disrupting" in r.value
        r = nem.invoke(test, Op("info", "stop", None))
        assert r.value == {"n1": "stopped"}
        assert events == [("start", "n1"), ("stop", "n1")]


class TestCockroachWrappers:
    """cockroach/nemesis.clj:153-200 slowing/restarting wrappers."""

    def _fixtures(self):
        from jepsen_tpu.suites import cockroachdb as cr

        calls = []

        class FakeNet(net.Net):
            def slow(self, test, mean_ms=50, sigma_ms=10):
                calls.append(("slow", mean_ms))

            def fast(self, test):
                calls.append(("fast",))

        class Inner(n.Nemesis):
            def invoke(self, test, op):
                return op.replace(type="info", value="inner")

        class FakeDB:
            def start(self, test, node):
                calls.append(("restart", node))

        test = ts.noop_test(transport=c.DummyTransport())
        test["net"] = FakeNet()
        return cr, calls, Inner, FakeDB, test

    def test_slowing_wraps_start_stop(self):
        cr, calls, Inner, FakeDB, test = self._fixtures()
        nem = cr.Slowing(Inner(), 0.5).setup(test)
        assert calls == [("fast",)]          # setup restores speed first
        r = nem.invoke(test, Op("info", "start", None))
        assert r.value == "inner"
        assert ("slow", 500.0) in calls
        r = nem.invoke(test, Op("info", "stop", None))
        assert calls[-1] == ("fast",)
        nem.teardown(test)
        assert calls[-1] == ("fast",)

    def test_restarting_restarts_on_stop(self):
        cr, calls, Inner, FakeDB, test = self._fixtures()
        nem = cr.Restarting(Inner(), db=FakeDB()).setup(test)
        r = nem.invoke(test, Op("info", "start", None))
        assert r.value == "inner"            # start passes through
        r = nem.invoke(test, Op("info", "stop", None))
        inner_val, stat = r.value
        assert inner_val == "inner"
        assert set(stat) == set(test["nodes"])
        assert all(v == "started" for v in stat.values())
        assert {c2[1] for c2 in calls if c2[0] == "restart"} \
            == set(test["nodes"])

    def test_registry_wires_wrappers(self):
        from jepsen_tpu.suites import cockroachdb as cr

        reg = cr.nemeses()
        assert isinstance(reg["big-skews"]["nemesis"], cr.Slowing)
        assert isinstance(reg["big-skews"]["nemesis"].nem, cr.Restarting)
        assert isinstance(reg["huge-skews"]["nemesis"], cr.Slowing)
        assert isinstance(reg["small-skews"]["nemesis"], cr.Restarting)
        assert isinstance(reg["strobe-skews"]["nemesis"], cr.Restarting)
        combined = cr.combine_nemeses(reg["big-skews"], reg["parts"])
        assert combined["clocks"] is True
