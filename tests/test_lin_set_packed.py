"""Set model on the packed device engines (ISSUE 9 satellite).

The Set kernel is promoted into models/kernels.py:PACKED_STATE_KERNELS:
a one-word set's state ranges over element-bitmask values, bounded by
the kernel's own ``state_bound`` (packed_state_bound is the shared
definition), so small-window set histories route through the dense
config-space bitmap engine and the sparse engine's packed-u32 sort
keys — parity-fuzzed against the lin/cpu.py spec here.
"""

import random

import pytest

from jepsen_tpu import models as m
from jepsen_tpu.history import Op
from jepsen_tpu.lin import bfs, cpu, dense, prepare
from jepsen_tpu.models import kernels as K

# Quick tier; the engines deliberately compile tiny cached programs.
pytestmark = [pytest.mark.quick, pytest.mark.compiles]


def gen_set_history(n_adds, n_reads, concurrency, seed, corrupt=False):
    """Concurrent adds + reads against an apply-at-invoke store
    (linearizable by construction); ``corrupt`` makes some reads
    observe a wrong set (dropped or phantom element)."""
    rng = random.Random(seed)
    items: set = set()
    hist, inflight = [], []
    procs = list(range(concurrency))
    nv = [0]
    events = ["add"] * n_adds + ["read"] * n_reads
    rng.shuffle(events)
    for ev in events:
        while not procs:
            p, comp = inflight.pop(0)
            hist.append(comp)
            procs.append(p)
        p = procs.pop(rng.randrange(len(procs)))
        if ev == "add":
            nv[0] += 1
            v = nv[0]
            hist.append(Op("invoke", "add", v, p))
            items.add(v)
            comp = Op("ok", "add", v, p)
        else:
            hist.append(Op("invoke", "read", None, p))
            snap = sorted(items)
            if corrupt and rng.random() < 0.6 and snap:
                snap = snap[:-1] + [snap[-1] + 1] \
                    if rng.random() < 0.5 else snap[:-1]
            comp = Op("ok", "read", snap, p)
        if rng.random() < 0.5:
            inflight.append((p, comp))
        else:
            hist.append(comp)
            procs.append(p)
    for _p, comp in inflight:
        hist.append(comp)
    return hist


class TestStateBound:
    def test_set_in_packed_registry_with_bound(self):
        assert "set" in K.PACKED_STATE_KERNELS
        k = K.set_kernel(3)
        assert k.state_bound == 8
        assert K.packed_state_bound(k, 99) == 8

    def test_multiword_set_has_no_bound(self):
        k = K.set_kernel(40)      # 2 words
        assert k.state_bound is None

    def test_register_bound_unchanged(self):
        k = K.cas_register_kernel()
        assert k.state_bound is None
        assert K.packed_state_bound(k, 5) == 5
        assert K.packed_state_bound(k, 0) == 2


class TestDensePlan:
    def test_small_set_plans_dense(self):
        h = gen_set_history(4, 4, 3, 0)
        p = prepare.prepare(m.set_model(), h)
        pl = dense.plan(p)
        assert pl is not None
        w, ns, nil_id, init_id = pl
        # nil_id = 2**n_elements; never a reachable mask.
        assert nil_id == 1 << max(1, len(p.unintern))
        assert ns >= nil_id + 1

    def test_bigger_set_declines_dense_keeps_sparse_keys(self):
        h = gen_set_history(8, 4, 3, 1)
        p = prepare.prepare(m.set_model(), h)
        assert dense.plan(p) is None       # 2**8 states > dense bound
        r = bfs.check_packed(p)
        assert r["valid?"] is cpu.check_packed(p)["valid?"]


class TestParityFuzz:
    @pytest.mark.parametrize("corrupt", [False, True])
    def test_dense_and_sparse_match_cpu(self, corrupt):
        mismatches = []
        dense_ran = 0
        for seed in range(10):
            h = gen_set_history(4, 4, 3, seed, corrupt)
            p = prepare.prepare(m.set_model(), h)
            assert p.kernel is not None and p.kernel.name == "set"
            want = cpu.check_packed(p)["valid?"]
            if dense.plan(p) is not None:
                dense_ran += 1
                got = dense.check_packed(p)["valid?"]
                if got is not want:
                    mismatches.append(("dense", seed, want, got))
            got = bfs.check_packed(p)["valid?"]
            if got is not want:
                mismatches.append(("sparse", seed, want, got))
        assert not mismatches, mismatches
        # Phantom-element corruption can intern a 5th element (33
        # states — past the dense bound) on some seeds; most still
        # plan dense, and every one that does must agree.
        assert dense_ran >= 7

    def test_sparse_packed_wider_sets(self):
        for seed in range(6):
            h = gen_set_history(7, 5, 4, 50 + seed, seed % 2 == 0)
            p = prepare.prepare(m.set_model(), h)
            want = cpu.check_packed(p)["valid?"]
            got = bfs.check_packed(p)["valid?"]
            assert got is want, (seed, want, got)

    def test_device_routing_picks_an_engine(self):
        from jepsen_tpu.lin import device_check_packed

        h = gen_set_history(4, 3, 3, 3)
        p = prepare.prepare(m.set_model(), h)
        r = device_check_packed(p)
        assert r["valid?"] is True
        assert r["analyzer"] in ("tpu-dense", "tpu-bfs")
