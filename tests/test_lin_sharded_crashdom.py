"""The crash-dom MESH band (ISSUE 18 tentpole): the pair-key
crash-dom compact path sharded over the conftest 8-device CPU mesh
must agree with the single-chip engine AND the ``lin/cpu.py`` oracle
on the scaled-down config-5 witness (window 34, pair keys, crashed
mutators) — verdict, violating op, and final-path validity — and the
collective dominance dedup must provably equal the single-chip prune.

Prune equality is the load-bearing invariant: the windowed dominance
CHAIN prune is EXACT (CLAUDE.md architecture invariants), so sharding
it may change LAYOUT but never the surviving SET. The collective
harness tests pin that down directly against ``bfs._dedup_keys2_dom``
on the same candidate multiset, with the per-shard pre-prune both off
(bit-equality) and on (set-equality), plus a forced-skew leg: all
candidates crowded onto device 0 must come back as the balanced
front-packed prefix re-shard.

Round-5 lore holds on the mesh: every dedup here runs the FORCED-LAX
dominance path (never the psort dom kernels), and the closure ceilings
convert a non-terminating prune orbit into an honest
``overflow: budget`` — the budget leg forces that with
``JEPSEN_TPU_MESH_IT_MAX=1``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from jepsen_tpu import models as m, util
from jepsen_tpu.lin import bfs, cpu, prepare, sharded, synth

# quick (seconds-scale once .jax_cache holds the mesh programs) but it
# compiles shard_map programs on a cold cache — exempt from the
# conftest no-compile enforcement via the registered `compiles` marker.
pytestmark = [pytest.mark.quick, pytest.mark.compiles]

N_DEV = 8


def mesh8():
    return Mesh(np.array(jax.devices()[:N_DEV]), ("d",))


def _pair_band_history():
    # The test_lin_crashdom_witness recipe: scaled-down literal config-5
    # shape — window 34 (past the 31-bit single-key bound), crashed
    # mutators, pair keys. The 5k/window-25 shapes do NOT exercise
    # these paths (CLAUDE.md round-5 lore).
    return synth.generate_partitioned_register_history(
        140, concurrency=40, seed=0, partition_every=60,
        partition_len=20, max_crashes=10)


def _mesh_check(p, **kw):
    # cap 512/device (4096 global) fits the witness's 630-config peak
    # and keeps the pair programs seconds-scale on the CPU backend —
    # a 4096/device top cap measured ~9x slower for zero extra
    # coverage.
    return sharded.check_packed(p, mesh=mesh8(), cap_schedule=(64, 512),
                                engine="sparse", **kw)


class TestWitnessParity:
    """Window-34 pair-band witness: mesh == single-chip == cpu oracle."""

    def test_valid_witness(self):
        p = prepare.prepare(m.cas_register(), _pair_band_history())
        # Guard the routing assumptions this test exists for: pair keys
        # (window past the single-key bound) with crashed mutators.
        assert p.window + max(len(p.unintern), 2).bit_length() > 31
        assert len(p.crashed_ops) > 0

        r = _mesh_check(p)
        assert r["dedup"] == "packed-keys2"
        # True is the pinned oracle verdict for this seeded recipe:
        # running cpu.check_packed here costs ~6 min of python frontier
        # walk (valid = full enumeration), which the quick tier cannot
        # afford — the slow-marked TestWitnessParityFull leg holds the
        # live three-way valid parity, and the corrupted twin below
        # runs the oracle cheaply (it dies at op 112).
        assert r["valid?"] is True
        ms = r["mesh-stats"]
        assert ms["devices"] == N_DEV
        assert ms["band"] == "pair"
        assert ms["crash-dom"] is True
        assert ms["dispatches"] >= 1
        assert len(ms["peak-occupancy"]) == N_DEV
        # __graft_entry__ asserts these top-level compatibility keys on
        # every mesh verdict — keep them flowing from the compact path.
        for key in ("chunks", "peak-frontier", "cap-per-device",
                    "shard-occupancy"):
            assert key in r, key

    def test_corrupted_witness_death_row_and_final_paths(self):
        h = synth.corrupt_history(_pair_band_history(), seed=3)
        p = prepare.prepare(m.cas_register(), h)

        want = cpu.check_packed(p, witness=True)
        assert want["valid?"] is False, "corruption must invalidate"
        single = bfs.check_packed(p, cap_schedule=(8,),
                                  host_caps=(64, 4096), explain=True)
        got = _mesh_check(p, explain=True)

        assert got["valid?"] is single["valid?"] is False
        assert got["op"] == want["op"]
        assert got["op"] == single["op"]
        assert got["final-paths"], "mesh violation must carry final-paths"
        # Final-path VALIDITY, not set-equality (test_lin_crashdom_witness
        # precedent): each engine enumerates paths for its own exact
        # alive set, so replay every mesh path through the python step
        # twin (the test_lin_witness replay idiom).
        from jepsen_tpu.lin.prepare import py_step_fn
        from jepsen_tpu.models.kernels import F_IDS, NIL

        step = py_step_fn(p.kernel.name)
        by_index = {o.op_index: o for o in p.ops}
        for fp in got["final-paths"]:
            st = tuple(int(x) for x in p.init_state)
            for od in fp["path"]:
                o = by_index[od["index"]]
                f_id = F_IDS[o.f]
                if o.f == "cas":
                    v = (p.intern.get(o.value[0], int(NIL)),
                         p.intern.get(o.value[1], int(NIL)))
                else:
                    v = (int(NIL) if o.value is None
                         else p.intern.get(o.value, int(NIL)), int(NIL))
                ok, st = step(st, f_id, v)
                assert ok, f"mesh path op {od} illegal at state {st}"


@pytest.mark.slow
class TestWitnessParityFull:
    """The expensive parity legs (run with ``-m slow``): live
    three-way VALID parity on the witness, and the 5k partitioned
    shape (window 25, single-key crash-dom band — the round-5 lore's
    other family) mesh vs single-chip."""

    def test_valid_witness_three_way(self):
        p = prepare.prepare(m.cas_register(), _pair_band_history())
        want = cpu.check_packed(p)["valid?"]
        single = bfs.check_packed(p, cap_schedule=(8,),
                                  host_caps=(64, 4096))["valid?"]
        got = _mesh_check(p)
        assert got["valid?"] is single is want is True

    def test_partitioned_5k_single_key_band(self):
        h = synth.generate_partitioned_register_history(
            5000, seed=7, invoke_bias=0.45)
        p = prepare.prepare(m.cas_register(), h)
        b = max(len(p.unintern), 2).bit_length()
        assert p.window + b <= 31, "5k shape must be single-key band"
        single = bfs.check_packed(p)["valid?"]
        got = sharded.check_packed(p, mesh=mesh8(), engine="sparse")
        assert got["valid?"] == single
        assert got["mesh-stats"]["band"] == "single"
        assert got["mesh-stats"]["crash-dom"] is True


class TestCollectivePruneEquality:
    """_global_dedup_keys_dom vs the single-chip _dedup_keys2_dom on
    the SAME candidate multiset: sharding must not change the prune."""

    B = 6  # key-space state-bit width for the synthetic masks

    def _masks(self):
        # Synthetic key-space masks shaped like a pair-band row's
        # (crash_lo, crash_hi, read_lo, read_hi): disjoint crash and
        # read bit-bands above the state bits.
        c_lo = np.uint32(0x00000FC0)
        c_hi = np.uint32(0x0000000F)
        r_lo = np.uint32(0x003F0000)
        r_hi = np.uint32(0x00000F00)
        return (jnp.uint32(c_lo), jnp.uint32(c_hi),
                jnp.uint32(r_lo), jnp.uint32(r_hi))

    def _candidates(self, seed, n=256):
        # Random keys plus planted structure the prune must collapse:
        # exact duplicates and crash-bit-superset dominators.
        rng = np.random.default_rng(seed)
        lo = rng.integers(0, 1 << 32, size=n, dtype=np.uint32)
        hi = rng.integers(0, 1 << 28, size=n, dtype=np.uint32)
        # duplicates across shard boundaries
        lo[n // 2:n // 2 + 32] = lo[:32]
        hi[n // 2:n // 2 + 32] = hi[:32]
        # dominators: same key with extra crash bits set
        lo[-32:] = lo[32:64] | np.uint32(0x00000040)
        hi[-32:] = hi[32:64]
        valid = rng.random(n) < 0.9
        return lo, hi, valid

    def _single_chip(self, lo, hi, valid, masks, dom_iters):
        c_lo, c_hi, r_lo, r_hi = masks
        n = lo.shape[0]
        hi_p, lo_p, total, _ = bfs._dedup_keys2_dom(
            jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid), n,
            c_hi, c_lo, r_hi, r_lo, use_psort=False, dom_force=True,
            dom_iters=dom_iters)
        return np.asarray(hi_p), np.asarray(lo_p), int(total)

    def _mesh_collective(self, lo, hi, valid, masks, cap_local, *,
                         preprune, dom_iters=2):
        def body(lo_s, hi_s, val_s):
            l, h, cnt, tot, ovf = sharded._global_dedup_keys_dom(
                lo_s, hi_s, val_s, cap_local, "d", key_hi=True,
                crash_dom=True, masks=masks, dom_iters=dom_iters,
                preprune=preprune)
            return l, h, cnt[None], tot[None], ovf[None]

        fn = util.get_shard_map()(
            body, mesh=mesh8(),
            in_specs=(P("d"), P("d"), P("d")),
            out_specs=(P("d"), P("d"), P("d"), P("d"), P("d")),
            check_vma=False)
        lo_o, hi_o, cnt, tot, ovf = fn(
            jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(valid))
        return (np.asarray(lo_o), np.asarray(hi_o),
                np.asarray(cnt), int(tot[0]), bool(np.any(ovf)))

    def test_sharded_prune_bit_equals_single_chip(self):
        # preprune OFF: the collective is ONE global forced-lax dom
        # dedup at cap = gathered length — bit-identical to the
        # single-chip helper on the same multiset, then sliced.
        lo, hi, valid = self._candidates(seed=0)
        masks = self._masks()
        hi_ref, lo_ref, total = self._single_chip(lo, hi, valid, masks,
                                                  dom_iters=2)
        cap_local = lo.shape[0] // N_DEV
        lo_m, hi_m, cnt, tot_m, ovf = self._mesh_collective(
            lo, hi, valid, masks, cap_local, preprune=False)
        assert tot_m == total
        assert not ovf
        # concatenated device slices == the single-chip packed arrays
        np.testing.assert_array_equal(lo_m, lo_ref)
        np.testing.assert_array_equal(hi_m, hi_ref)

    def test_preprune_preserves_surviving_set(self):
        # preprune ON: the per-shard pass may reorder the pre-gather
        # layout but can only remove candidates the global pass would
        # also remove — surviving SET and total unchanged.
        lo, hi, valid = self._candidates(seed=1)
        masks = self._masks()
        hi_ref, lo_ref, total = self._single_chip(lo, hi, valid, masks,
                                                  dom_iters=2)
        cap_local = lo.shape[0] // N_DEV
        lo_m, hi_m, cnt, tot_m, ovf = self._mesh_collective(
            lo, hi, valid, masks, cap_local, preprune=True)
        assert tot_m == total
        assert not ovf
        ref = {(int(h), int(l))
               for h, l in zip(hi_ref[:total], lo_ref[:total])}
        got = {(int(h), int(l)) for h, l in zip(hi_m[:tot_m], lo_m[:tot_m])}
        assert got == ref

    def test_forced_skew_rebalances(self):
        # Every live candidate crowded onto device 0; the collective
        # must hand back the balanced front-packed prefix re-shard:
        # counts = clip(total - d*cap, 0, cap), survivors sorted into
        # the leading devices.
        n = 256
        cap_local = n // N_DEV  # 32 per device
        rng = np.random.default_rng(7)
        lo = rng.integers(0, 1 << 32, size=n, dtype=np.uint32)
        hi = rng.integers(0, 1 << 28, size=n, dtype=np.uint32)
        valid = np.zeros(n, dtype=bool)
        valid[:40] = True  # all live keys on shard 0 (rows 0..31) + 1
        masks = self._masks()
        hi_ref, lo_ref, total = self._single_chip(lo, hi, valid, masks,
                                                  dom_iters=1)
        lo_m, hi_m, cnt, tot_m, ovf = self._mesh_collective(
            lo, hi, valid, masks, cap_local, preprune=True, dom_iters=1)
        assert tot_m == total
        assert total > cap_local, "skew must actually spill device 0"
        want_cnt = np.clip(total - np.arange(N_DEV) * cap_local, 0,
                           cap_local)
        np.testing.assert_array_equal(cnt, want_cnt.astype(cnt.dtype))
        got = {(int(h), int(l)) for h, l in zip(hi_m[:tot_m], lo_m[:tot_m])}
        ref = {(int(h), int(l))
               for h, l in zip(hi_ref[:total], lo_ref[:total])}
        assert got == ref


def test_budget_ceiling_is_honest_overflow(monkeypatch):
    # The in-carry iteration ceiling (round-5 orbit defense): pin the
    # closure budget to 1 so every row "orbits", and the engine must
    # walk the (pinned-short) escalation ladder and return an honest
    # budget unknown — never hang, never flip a verdict.
    monkeypatch.setenv("JEPSEN_TPU_MESH_IT_MAX", "1")
    monkeypatch.setenv("JEPSEN_TPU_MESH_CAPS", "4")
    h = synth.generate_register_history(40, concurrency=4, seed=5,
                                        crash_prob=0.3, max_crashes=4)
    p = prepare.prepare(m.cas_register(), h)
    assert p.crashed.any(), "budget leg needs the crash-dom route"
    r = sharded.check_packed(p, mesh=mesh8(), cap_schedule=(4,),
                             engine="sparse")
    assert r["valid?"] == "unknown"
    assert r["overflow"] == "budget"
    assert r["mesh-stats"]["crash-dom"] is True
    assert r["mesh-stats"]["episodes"] >= 1

