"""txn/device.py — device SCC engine vs the oracle (doc/txn.md).

Parity fuzz over random dependency graphs AND seeded-anomaly corpora:
verdict, anomaly classification, and witness cycles must be identical
(the oracle Tarjans the full graph; the device trims + min-labels and
peels the residue — genuinely different decompositions feeding the
same shared classifier). Plus the fault discipline: iteration-ceiling
overflow, wedge injection, quarantine routing, and the honest-unknown
bound all exercise the supervised fallback ladder.
"""

import json
import random

import numpy as np
import pytest

from jepsen_tpu.lin import supervise
from jepsen_tpu.txn import device, oracle, pack, synth

# Quick tier, but the SCC program is a real (tiny, cached) XLA compile.
pytestmark = [pytest.mark.quick, pytest.mark.compiles]

ALL = oracle.CYCLE_ANOMALIES


def _random_graph(rng, n_max=40, e_max=120):
    n = rng.randrange(2, n_max)
    E = rng.randrange(1, e_max)
    src, dst, typ = [], [], []
    for _ in range(E):
        a, b = rng.randrange(n), rng.randrange(n)
        if a == b:
            continue
        src.append(a)
        dst.append(b)
        typ.append(rng.choice((oracle.WR, oracle.WW, oracle.RW)))
    return oracle.TxnGraph(
        n=n, src=np.asarray(src, np.int32), dst=np.asarray(dst, np.int32),
        typ=np.asarray(typ, np.int8))


def _device_check(g, anomalies=ALL, **kw):
    return device.check_packed(pack.pack(graph=g), anomalies=anomalies,
                               snapshot=False, **kw)


class TestParityFuzz:
    def test_random_graphs(self):
        rng = random.Random(42)
        for i in range(40):
            g = _random_graph(rng)
            want = oracle.check_graph(g, ALL)
            got = _device_check(g)
            assert got["valid?"] == want["valid?"], (i, got, want)
            assert got["anomaly-types"] == want["anomaly-types"], i
            assert got["anomalies"] == want["anomalies"], i
            assert not got.get("fallbacks"), (i, got)

    def test_dense_cyclic_graphs(self):
        # Mostly-cyclic graphs: the min-label/flag phases do the work
        # (the residue peel must stay empty or exact).
        rng = random.Random(7)
        for i in range(15):
            n = rng.randrange(4, 20)
            src, dst, typ = [], [], []
            for v in range(n):            # a ring + random chords
                src.append(v)
                dst.append((v + 1) % n)
                typ.append(oracle.WW)
            for _ in range(n):
                a, b = rng.randrange(n), rng.randrange(n)
                if a != b:
                    src.append(a)
                    dst.append(b)
                    typ.append(rng.choice((oracle.WR, oracle.RW)))
            g = oracle.TxnGraph(n=n, src=np.asarray(src, np.int32),
                                dst=np.asarray(dst, np.int32),
                                typ=np.asarray(typ, np.int8))
            want = oracle.check_graph(g, ALL)
            got = _device_check(g)
            assert got["anomalies"] == want["anomalies"], i

    @pytest.mark.parametrize("kind",
                             ["G0", "G1c", "G-single", "G2-item", "G1a"])
    def test_seeded_corpora(self, kind):
        from jepsen_tpu import txn

        h = synth.seeded_anomaly_history(kind)
        got = txn.check(h, algorithm="tpu")
        want = txn.check(h, algorithm="cpu")
        assert got["valid?"] is False
        assert kind in got["anomaly-types"]
        assert got["anomaly-types"] == want["anomaly-types"]
        assert got["anomalies"] == want["anomalies"]

    def test_spliced_history_parity(self):
        from jepsen_tpu import txn

        h = synth.splice_anomaly(
            synth.generate_list_append_history(300, seed=9),
            "G2-item", seed=9, n=2)
        got = txn.check(h, algorithm="tpu")
        want = txn.check(h, algorithm="cpu")
        assert got["valid?"] is False and want["valid?"] is False
        assert got["anomalies"] == want["anomalies"]

    def test_healthy_short_circuits_forward_order(self):
        from jepsen_tpu import txn

        h = synth.generate_list_append_history(200, seed=1)
        got = txn.check(h, algorithm="tpu")
        assert got["valid?"] is True
        tiers = got["device-stats"]["tiers"]
        assert all(t.get("short_circuit") == "forward-order"
                   for t in tiers.values()), tiers

    def test_realtime_packed_checked_serializable_parity(self):
        # Regression (review finding): a realtime-PACKED history
        # checked as plain serializable must exclude rt edges from the
        # device tiers. Polluted tiers merge extra nodes into the SCC
        # via rt edges; the merged SCC's min node then reaches the real
        # ww cycle only through rt, the rt-blind shared classifier
        # finds no witness, and a genuine G0 silently passes.
        def _t(h, proc, mops, obs=None):
            from jepsen_tpu.history import Op
            h.append(Op("invoke", "txn", [list(m) for m in mops], proc))
            h.append(Op("ok", "txn",
                        [list(m) for m in (obs or mops)], proc))

        h = []
        # Sequential txns => rt chain T0->T1->T2->T3. Reads pin key
        # orders a:[10,20] (ww T1->T2), b:[21,11] (ww T2->T1: the G0
        # cycle), c:[31,30] (ww T2->T0: the back-edge that drags T0
        # into the rt-polluted SCC with no outgoing ww).
        _t(h, 0, [["append", "c", 30]])
        _t(h, 1, [["append", "a", 10], ["append", "b", 11]])
        _t(h, 2, [["append", "a", 20], ["append", "b", 21],
                  ["append", "c", 31]])
        _t(h, 3, [["r", "a", None], ["r", "b", None], ["r", "c", None]],
           [["r", "a", [10, 20]], ["r", "b", [21, 11]],
            ["r", "c", [31, 30]]])
        pt = pack.pack(h, realtime=True)
        got = device.check_packed(pt, consistency="serializable",
                                  snapshot=False)
        want = oracle.check(h, consistency="serializable")
        assert want["valid?"] is False and "G0" in want["anomaly-types"]
        assert got["valid?"] == want["valid?"], got
        assert got["anomaly-types"] == want["anomaly-types"]
        assert got["anomalies"] == want["anomalies"]
        # The same packed history decides strict-serializable too (rt
        # edges now requested AND packed).
        strict = device.check_packed(pt, consistency="strict-serializable",
                                     snapshot=False)
        assert strict["valid?"] is False


class TestAcceptanceScale:
    def _scale_run(self, n_txns):
        from jepsen_tpu import txn

        h = synth.splice_anomaly(
            synth.splice_anomaly(
                synth.generate_list_append_history(
                    n_txns, concurrency=30, keys=32, seed=7,
                    crash_prob=0.0005),
                "G2-item", seed=3, n=2),
            "G-single", seed=5)
        got = txn.check(h, consistency="serializable", algorithm="tpu")
        want = txn.check(h, consistency="serializable", algorithm="cpu")
        assert got["valid?"] is False and want["valid?"] is False
        assert {"G2-item", "G-single"} <= set(got["anomaly-types"])
        # Verdict AND witness-cycle parity (the ISSUE 9 acceptance).
        assert got["anomaly-types"] == want["anomaly-types"]
        assert got["anomalies"] == want["anomalies"]
        assert not got.get("fallbacks"), got.get("fallbacks")
        return got

    def test_5k_txn_parity(self):
        # The tier-1-sized slice of the acceptance shape; the literal
        # 100k-op run is the slow twin below (and bench's txn_c30).
        self._scale_run(2500)

    @pytest.mark.slow
    def test_100k_op_acceptance_parity(self):
        got = self._scale_run(50_000)
        assert got["device-stats"]["edges"] > 100_000


class TestFaultDiscipline:
    def _cyclic_graph(self):
        return oracle.TxnGraph(
            n=6,
            src=np.asarray([0, 3, 1, 4], np.int32),
            dst=np.asarray([3, 0, 4, 1], np.int32),
            typ=np.asarray([oracle.WW] * 4, np.int8))

    def test_iteration_ceiling_overflow_falls_back_honestly(
            self, monkeypatch, tmp_path):
        monkeypatch.setenv("JEPSEN_TPU_TXN_IT_MAX", "1")
        monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                           str(tmp_path / "q.json"))
        g = self._cyclic_graph()
        got = _device_check(g)
        # Verdict still exact (host Tarjan rung), overflow attributed.
        assert got["valid?"] is False
        assert got["anomalies"] == oracle.check_graph(g, ALL)["anomalies"]
        assert got["fallbacks"].get("ww") == "overflow: budget"
        assert all(v == "overflow: budget"
                   for v in got["fallbacks"].values())
        assert got["device-stats"].get("overflows", 0) >= 1

    def test_cpu_bound_reports_honest_unknown(self, monkeypatch,
                                              tmp_path):
        monkeypatch.setenv("JEPSEN_TPU_TXN_IT_MAX", "1")
        monkeypatch.setenv("JEPSEN_TPU_TXN_CPU_MAX", "0")
        monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                           str(tmp_path / "q.json"))
        got = _device_check(self._cyclic_graph())
        assert got["valid?"] == "unknown"
        assert "overflow" in got
        assert "JEPSEN_TPU_TXN_CPU_MAX" in got["error"]

    def test_wedge_injection_retries_then_falls_back(self, monkeypatch,
                                                     tmp_path):
        monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                           str(tmp_path / "q.json"))
        monkeypatch.setenv("JEPSEN_TPU_DISPATCH_RETRIES", "0")
        supervise.inject_wedge("txn-scc", 3, 0.05)
        try:
            g = self._cyclic_graph()
            got = _device_check(g, anomalies=("G0",))
            # The tier wedged -> host rung; verdict exact; watchdog
            # trip + ledger record visible.
            assert got["valid?"] is False
            assert got["fallbacks"] == {"ww": "wedge"}
            assert got["device-stats"]["watchdog_trips"] >= 1
            ledger = supervise.load_ledger(str(tmp_path / "q.json"))
            assert any(k.startswith("txn-scc|") for k in ledger), ledger
        finally:
            supervise._injected.clear()

    def test_quarantined_shape_routes_to_host(self, monkeypatch,
                                              tmp_path):
        qpath = str(tmp_path / "q.json")
        monkeypatch.setenv("JEPSEN_TPU_QUARANTINE", qpath)
        g = self._cyclic_graph()
        key = supervise.shape_key(
            "txn-scc", cap=device.MIN_EDGE_PAD, window=0,
            kernel="txn-ww", rows=device.MIN_NODE_PAD)
        supervise.record_fault(key, "fault", path=qpath)
        got = _device_check(g, anomalies=("G0",))
        assert got["valid?"] is False
        assert got["fallbacks"] == {"ww": "quarantined"}
        assert got["device-stats"]["quarantine_skips"] == 1

    def test_stats_snapshot_written(self, monkeypatch, tmp_path):
        snap_path = tmp_path / "txn_stats.json"
        monkeypatch.setenv("JEPSEN_TPU_TXN_STATS", str(snap_path))
        from jepsen_tpu import txn

        r = txn.check(synth.seeded_anomaly_history("G0"),
                      algorithm="tpu")
        assert r["valid?"] is False
        snap = json.loads(snap_path.read_text())
        assert snap["verdict"] is False
        assert snap["anomaly_counts"].get("G0") == 1
        assert "device" in snap and "edge_counts" in snap


class TestWorkload:
    def test_txn_workload_fake_client_round_trip(self):
        from jepsen_tpu.history import Op
        from jepsen_tpu.suites import fakes, workloads

        store = fakes.FakeTxnStore()
        client = workloads.TxnClient(store)
        op = Op("invoke", "txn", [["append", 0, 1], ["r", 0, None]], 0)
        done = client.invoke(None, op)
        assert done.type == "ok"
        assert done.value == [["append", 0, 1], ["r", 0, [1]]]

    def test_write_skew_store_produces_g2(self):
        import threading

        from jepsen_tpu import txn
        from jepsen_tpu.history import Op
        from jepsen_tpu.suites import fakes, workloads

        store = fakes.FakeTxnStore(faulty="write-skew")
        client = workloads.TxnClient(store)
        h = []
        lock = threading.Lock()

        def run(proc, read_k, append_k):
            op = Op("invoke", "txn",
                    [["r", read_k, None], ["append", append_k, proc + 1]],
                    proc)
            done = client.invoke(None, op)
            with lock:
                h.append(op)
                h.append(done)

        ts = [threading.Thread(target=run, args=(0, "x", "y")),
              threading.Thread(target=run, args=(1, "y", "x"))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # A later reader pins both version orders.
        op = Op("invoke", "txn", [["r", "x", None], ["r", "y", None]], 2)
        h.append(op)
        h.append(client.invoke(None, op))
        r = txn.check(h, consistency="serializable", algorithm="cpu")
        assert r["valid?"] is False
        assert "G2-item" in r["anomaly-types"], r
        # ...and snapshot isolation admits exactly this.
        si = txn.check(h, consistency="snapshot-isolation",
                       algorithm="cpu")
        assert si["valid?"] is True, si

    def test_aborted_read_store_produces_g1a(self):
        from jepsen_tpu import txn
        from jepsen_tpu.history import Op
        from jepsen_tpu.suites import fakes, workloads

        store = fakes.FakeTxnStore(faulty="aborted-read")
        client = workloads.TxnClient(store)
        h = []
        for i in range(5):     # the 5th appending txn aborts-but-applies
            op = Op("invoke", "txn", [["append", "k", i]], i)
            h.append(op)
            h.append(client.invoke(None, op))
        op = Op("invoke", "txn", [["r", "k", None]], 9)
        h.append(op)
        h.append(client.invoke(None, op))
        r = txn.check(h, algorithm="cpu")
        assert r["valid?"] is False
        assert "G1a" in r["anomaly-types"], r

    def test_workload_registry_and_checker_wiring(self):
        from jepsen_tpu.suites import workloads

        wl = workloads.REGISTRY["txn"]()
        assert wl["checker"].is_txn_cycles
        assert wl["model"] is None

    def test_healthy_workload_end_to_end(self):
        import random as random_mod

        from jepsen_tpu import core
        from jepsen_tpu.suites import common, workloads

        random_mod.seed(5)
        wl = workloads.txn_workload(n=40, stagger=0.0, algorithm="cpu")
        t = common.suite_test("txn-fake",
                              {"time-limit": 5, "concurrency": 4,
                               "fake": True},
                              workload=wl)
        t["name"] = None
        res = core.run(t)["results"]
        r = res.get("workload", res)
        assert r.get("valid?") is True, r
