"""Device-resident episode scheduler (the kill-the-tunnel tentpole,
bfs._host_sched_rows): a row QUEUE runs as ONE device program that
commits its clean prefix in-program — an OPTIMIZATION over the proven
per-row/wave ladder that must change dispatch counts, never verdicts.

Coverage split by cost (the test_lin_hostrow_wave precedent): the
window-34 pair-band witness shape carries the acceptance criterion —
verdict/death-row/final-paths parity vs the K=4 wave path AND the CPU
oracle, with STRICTLY FEWER dispatches — while the cheap single-key
crash-dom band carries the mechanics: forced-trip per-row resume,
quarantined-shape routing, and checkpoint/resume mid-episode."""

import os
import threading

import pytest

from jepsen_tpu import models as m
from jepsen_tpu.lin import bfs, cpu, prepare, supervise, synth

quick = pytest.mark.quick
pytestmark = pytest.mark.compiles


@pytest.fixture(autouse=True)
def _ledger(tmp_path, monkeypatch):
    # Isolated quarantine ledger: these tests write real entries.
    monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                       str(tmp_path / "quarantine.json"))


@pytest.fixture(scope="module")
def pair_band_packed():
    # The corrupted window-34 partition shape of the crashdom witness
    # suite (identical params — shared compiled shapes).
    h = synth.generate_partitioned_register_history(
        140, concurrency=40, seed=0, partition_every=60,
        partition_len=20, max_crashes=10)
    return prepare.prepare(m.cas_register(),
                           synth.corrupt_history(h, seed=3))


@pytest.fixture(scope="module")
def small_band_packed():
    h = synth.generate_register_history(60, concurrency=6, seed=1,
                                        crash_prob=0.25)
    return prepare.prepare(m.cas_register(), h)


def _run(monkeypatch, p, *, sched, cap_schedule, host_caps, **kw):
    monkeypatch.setenv("JEPSEN_TPU_HOST_STICKY", "1")
    monkeypatch.setenv("JEPSEN_TPU_HOST_ROWS_K", "4")
    monkeypatch.setenv("JEPSEN_TPU_HOST_SCHED", str(sched))
    return bfs.check_packed(p, cap_schedule=cap_schedule,
                            host_caps=host_caps, **kw)


def _run_pair(monkeypatch, p, *, sched, **kw):
    return _run(monkeypatch, p, sched=sched, cap_schedule=(8,),
                host_caps=(64, 4096), **kw)


def _run_small(monkeypatch, p, *, sched=1, host_caps=(8, 64, 512)):
    return _run(monkeypatch, p, sched=sched, cap_schedule=(1,),
                host_caps=host_caps)


def test_sched_matches_wave_and_oracle_with_fewer_dispatches(
        monkeypatch, pair_band_packed):
    # THE acceptance criterion (ISSUE 14): on the window-34 pair-band
    # witness shape the scheduler decides with strictly fewer
    # dispatches than the K=4 wave path, with verdict / death row /
    # final-paths identical to the wave path and the CPU oracle.
    p = pair_band_packed
    assert p.window + max(len(p.unintern), 2).bit_length() > 31
    assert len(p.crashed_ops) > 0

    wave = _run_pair(monkeypatch, p, sched=0, explain=True)
    assert wave["valid?"] is False and wave["final-paths"]

    got = _run_pair(monkeypatch, p, sched=1, explain=True)
    assert got["valid?"] is False
    assert got["op"] == wave["op"]
    assert got["dead-row"] == wave["dead-row"]
    assert got["final-paths"]

    want = cpu.check_packed(p)
    assert want["valid?"] is False and got["op"] == want["op"]

    s, w = got["host-stats"], wave["host-stats"]
    assert s["sched_dispatches"] >= 1 and s["sched_rows"] >= 1
    assert s["dispatches"] < w["dispatches"], (
        f"scheduler must cut dispatches: sched={s} wave={w}")


@quick
def test_sched_commits_queue_rows_per_dispatch(monkeypatch,
                                               small_band_packed):
    # With a comfortable single cap (no escalation) the scheduler
    # must amortize: strictly fewer closure dispatches than rows.
    got = _run_small(monkeypatch, small_band_packed, host_caps=(512,))
    assert got["valid?"] is True
    s = got["host-stats"]
    assert s["sched_rows"] > 0 and s["sched_trips"] == 0
    assert s["dispatches"] < s["rows"], s


@quick
def test_forced_trip_resumes_per_row(monkeypatch, small_band_packed):
    # A tiny first host cap trips scheduler rows on overflow; the
    # committed prefix must stand and the tripped row must resume on
    # the proven per-row ladder — same verdict as the scheduler-off
    # run, with the trip visible in the stats.
    p = small_band_packed
    off = _run_small(monkeypatch, p, sched=0)
    assert off["valid?"] is True

    on = _run_small(monkeypatch, p, sched=1)
    assert on["valid?"] is True
    s = on["host-stats"]
    assert s["sched_trips"] >= 1, \
        "caps this tiny must trip at least one scheduler row"
    # The tripped row's passes are discarded work; committed rows are
    # not — both visible in the waste observability.
    assert s["wasted_passes"] >= 1
    assert s["rows"] > s["sched_rows"] - s["rows"]  # per-row activity


@quick
def test_quarantined_sched_shape_routes_to_wave(monkeypatch,
                                                small_band_packed):
    # A quarantined scheduler shape must skip the scheduler program
    # entirely (sched_dispatches == 0) and still decide on the proven
    # wave/per-row rungs.
    p = small_band_packed
    for cap in (8, 64, 512):
        for qn in range(2, bfs._sched_queue() + 1):
            supervise.record_fault(
                supervise.shape_key("host-sched", rows=qn, cap=cap,
                                    window=p.window,
                                    kernel="cas-register"), "fault")
    r = _run_small(monkeypatch, p, sched=1)
    assert r["valid?"] is True
    s = r["host-stats"]
    assert s["sched_dispatches"] == 0
    assert s["quarantine_skips"] >= 1
    assert s["rows"] > 0


@quick
def test_wedged_sched_dispatch_falls_back_and_recovers(monkeypatch,
                                                       small_band_packed):
    # A wedged scheduler dispatch costs its detection window, falls to
    # the proven rungs for one row, and the search still decides.
    supervise.inject_wedge("host-sched", 2, deadline_s=0.2)
    try:
        r = _run_small(monkeypatch, small_band_packed, sched=1)
    finally:
        supervise._injected.clear()
    assert r["valid?"] is True
    assert r["host-stats"]["watchdog_trips"] >= 1


def test_ckpt_resume_mid_episode_parity(monkeypatch, pair_band_packed,
                                        tmp_path):
    # Kill the search right after a scheduler-committed episode
    # boundary checkpoint; the resumed run must produce an identical
    # verdict/death-row/final-paths (the test_lin_ckpt_resume
    # invariant, now with the scheduler owning the episode commits).
    p = pair_band_packed
    full = _run_pair(monkeypatch, p, sched=1, explain=True)
    assert full["valid?"] is False and full["final-paths"]

    ck = str(tmp_path / "sched.ckpt.npz")
    ckpt = supervise.Checkpointer(ck, supervise.history_fingerprint(p),
                                  every_s=0)
    cancel = threading.Event()
    saves = []

    def on_save(kind, row):
        saves.append((kind, row))
        if kind == "host":
            cancel.set()

    ckpt.on_save = on_save
    killed = _run_pair(monkeypatch, p, sched=1, cancel=cancel,
                       checkpoint=ckpt, explain=True)
    assert killed["valid?"] == "unknown"
    assert os.path.exists(ck)
    assert any(kind == "host" for kind, _ in saves)

    resumed = _run_pair(monkeypatch, p, sched=1, checkpoint=ck,
                        explain=True)
    assert resumed["valid?"] is False
    assert resumed["resumed-from-row"] == saves[-1][1]
    assert resumed["op"] == full["op"]
    assert resumed["dead-row"] == full["dead-row"]
    assert not os.path.exists(ck)
