"""txn/oracle.py — edge inference, classification, witnesses, and the
adya G2 bridge (doc/txn.md). Pure host: the oracle is the executable
spec the device engine is parity-fuzzed against (test_txn_device.py).
"""

import pytest

from jepsen_tpu.history import Op
from jepsen_tpu.txn import oracle, synth

# Quick tier: no XLA compiles (the oracle never touches jax).
pytestmark = pytest.mark.quick


def _txn(h, proc, inv, done=None, typ="ok"):
    h.append(Op("invoke", "txn", [list(m) for m in inv], proc))
    if typ != "info":
        h.append(Op(typ, "txn",
                    [list(m) for m in (done if done is not None else inv)],
                    proc))


class TestInference:
    def test_wr_ww_rw_edges(self):
        h = []
        _txn(h, 0, [["append", "x", 1]])
        _txn(h, 1, [["append", "x", 2]])
        _txn(h, 2, [["r", "x", None]], [["r", "x", [1]]])
        _txn(h, 3, [["r", "x", None]], [["r", "x", [1, 2]]])
        g = oracle.infer(h)
        edges = {(int(s), int(d), int(t))
                 for s, d, t in zip(g.src, g.dst, g.typ)}
        # ww: writer(1) -> writer(2); wr: writer(1) -> T2 (last elem),
        # writer(2) -> T3; rw: T2 (prefix [1]) -> writer(2).
        assert (0, 1, oracle.WW) in edges
        assert (0, 2, oracle.WR) in edges
        assert (1, 3, oracle.WR) in edges
        assert (2, 1, oracle.RW) in edges
        assert g.anomalies == {}

    def test_empty_read_antidepends_on_first_writer(self):
        h = []
        _txn(h, 0, [["r", "x", None]], [["r", "x", []]])
        _txn(h, 1, [["append", "x", 1]])
        _txn(h, 2, [["r", "x", None]], [["r", "x", [1]]])
        g = oracle.infer(h)
        edges = {(int(s), int(d), int(t))
                 for s, d, t in zip(g.src, g.dst, g.typ)}
        assert (0, 1, oracle.RW) in edges

    def test_info_append_counts_only_when_observed(self):
        # Recoverable-write rule: an :info txn's append constrains the
        # order iff some read observed it.
        h = []
        _txn(h, 0, [["append", "x", 1]], typ="info")   # observed below
        _txn(h, 1, [["append", "y", 7]], typ="info")   # never observed
        _txn(h, 2, [["r", "x", None]], [["r", "x", [1]]])
        g = oracle.infer(h)
        edges = {(int(s), int(d), int(t))
                 for s, d, t in zip(g.src, g.dst, g.typ)}
        assert (0, 2, oracle.WR) in edges
        assert not any(int(s) == 1 or int(d) == 1
                       for s, d in zip(g.src, g.dst))
        assert g.stats["info_txns"] == 2
        assert g.anomalies == {}           # an observed info write is fine

    def test_failed_append_read_is_g1a(self):
        h = []
        _txn(h, 0, [["append", "x", 9]], typ="fail")
        _txn(h, 1, [["r", "x", None]], [["r", "x", [9]]])
        r = oracle.check(h)
        assert r["valid?"] is False
        assert r["anomaly-types"] == ["G1a"]

    def test_incompatible_order(self):
        h = []
        _txn(h, 0, [["append", "x", 1]])
        _txn(h, 1, [["append", "x", 2]])
        _txn(h, 2, [["r", "x", None]], [["r", "x", [1, 2]]])
        _txn(h, 3, [["r", "x", None]], [["r", "x", [2]]])   # not a prefix
        r = oracle.check(h)
        assert r["valid?"] is False
        assert "incompatible-order" in r["anomaly-types"]

    def test_duplicate_elements(self):
        h = []
        _txn(h, 0, [["append", "x", 1]])
        _txn(h, 1, [["append", "x", 1]])    # same (k, v) twice
        r = oracle.check(h)
        assert "duplicate-elements" in r["anomaly-types"]

    def test_garbage_read_convicted(self):
        # Regression (review finding): a read observing a value NO
        # transaction ever appended (not even a failed one — that
        # would be G1a) is store corruption; it maps to no writer and
        # forms no cycle, so it must be reported directly.
        h = []
        _txn(h, 0, [["append", "x", 1]])
        _txn(h, 1, [["r", "x", None]], [["r", "x", [1, 999]]])
        r = oracle.check(h)
        assert r["valid?"] is False
        assert "garbage-read" in r["anomaly-types"]
        w = r["anomalies"]["garbage-read"][0]
        assert w["key"] == "x" and w["value"] == 999
        assert oracle.infer(h).stats["garbage"] == 1

    def test_fail_txn_dropped_from_graph(self):
        h = []
        _txn(h, 0, [["append", "x", 1]])
        _txn(h, 1, [["append", "x", 2]], typ="fail")
        g = oracle.infer(h)
        assert g.n == 1

    def test_unsupported_microop_raises(self):
        h = []
        _txn(h, 0, [["cas", "x", 1]])
        with pytest.raises(oracle.UnsupportedTxnHistory):
            oracle.infer(h)
        assert oracle.check(h)["valid?"] == "unknown"

    def test_realtime_frontier_reduction(self):
        # A completes, then B runs, then C: rt edges A->B, B->C, A->C
        # is implied (A left the frontier when B completed) — the
        # reduction keeps A->B and B->C only.
        h = []
        _txn(h, 0, [["append", "x", 1]])
        _txn(h, 1, [["append", "x", 2]])
        _txn(h, 2, [["r", "x", None]], [["r", "x", [1, 2]]])
        g = oracle.infer(h, realtime=True)
        rt = {(int(s), int(d)) for s, d, t in zip(g.src, g.dst, g.typ)
              if int(t) == oracle.RT}
        assert rt == {(0, 1), (1, 2)}


class TestClassification:
    @pytest.mark.parametrize("kind",
                             ["G0", "G1c", "G-single", "G2-item", "G1a"])
    def test_seeded_anomaly_found(self, kind):
        r = oracle.check(synth.seeded_anomaly_history(kind))
        assert r["valid?"] is False
        assert kind in r["anomaly-types"], r["anomaly-types"]
        w = r["anomalies"][kind][0]
        if kind != "G1a":
            # Witness cycle: nodes + edge types + op summaries.
            assert len(w["nodes"]) == len(w["edges"]) >= 2
            assert "ops" in w and w["ops"]

    def test_witness_rw_counts(self):
        r = oracle.check(synth.seeded_anomaly_history("G-single"))
        assert r["anomalies"]["G-single"][0]["rw-count"] == 1
        r = oracle.check(synth.seeded_anomaly_history("G2-item"))
        assert r["anomalies"]["G2-item"][0]["rw-count"] >= 2
        r = oracle.check(synth.seeded_anomaly_history("G0"))
        assert set(r["anomalies"]["G0"][0]["edges"]) == {"ww"}

    def test_consistency_models(self):
        g2 = synth.seeded_anomaly_history("G2-item")
        assert oracle.check(g2, consistency="serializable")["valid?"] \
            is False
        # SI admits pure write skew...
        assert oracle.check(
            g2, consistency="snapshot-isolation")["valid?"] is True
        # ...but not read skew.
        gs = synth.seeded_anomaly_history("G-single")
        assert oracle.check(
            gs, consistency="snapshot-isolation")["valid?"] is False
        # Read committed admits both anti-dependency shapes.
        assert oracle.check(
            gs, consistency="read-committed")["valid?"] is True
        with pytest.raises(ValueError):
            oracle.check(g2, consistency="nope")

    def test_explicit_anomaly_tuple(self):
        g0 = synth.seeded_anomaly_history("G0")
        assert oracle.check(g0, anomalies=("G1c",))["valid?"] is True
        assert oracle.check(g0, anomalies=("G0",))["valid?"] is False

    def test_rw_only_request_searches_wwr_coincident_scc(self):
        # Regression (review finding): an SCC whose node set exactly
        # equals a wwr SCC still holds rw-bearing cycles; an explicit
        # rw-classes-only request must find them, not skip the SCC as
        # "already explained" by classes nobody requested.
        import numpy as np

        g = oracle.TxnGraph(
            n=2,
            src=np.asarray([0, 1, 0], np.int32),
            dst=np.asarray([1, 0, 1], np.int32),
            typ=np.asarray([oracle.WW, oracle.WR, oracle.RW], np.int8))
        r = oracle.check_graph(g, ("G-single",))
        assert r["valid?"] is False
        assert r["anomaly-types"] == ["G-single"]
        # ...and per Adya a 1-rw cycle is also a G2 (superset class).
        r2 = oracle.check_graph(g, ("G2-item",))
        assert r2["valid?"] is False
        assert r2["anomaly-types"] == ["G2-item"]
        # The strongest-explanation skip still applies when the ww/wr
        # classes ARE requested.
        r3 = oracle.check_graph(g, ("G1c", "G-single"))
        assert r3["anomaly-types"] == ["G1c"]

    def test_skip_requires_covering_class_actually_reported(self):
        # Regression (review finding): the strongest-explanation skip
        # must fire only for SCCs actually REPORTED under G0/G1c. Here
        # the covering wwr SCC is a pure wr cycle — with G0 requested
        # but G1c not, nothing reports it, and the requested G2-item
        # (the rw cycles inside the same node set) must not vanish.
        import numpy as np

        g = oracle.TxnGraph(
            n=2,
            src=np.asarray([0, 1, 0, 1], np.int32),
            dst=np.asarray([1, 0, 1, 0], np.int32),
            typ=np.asarray([oracle.WR, oracle.WR,
                            oracle.RW, oracle.RW], np.int8))
        r = oracle.check_graph(g, ("G0", "G2-item"))
        assert r["valid?"] is False
        assert r["anomaly-types"] == ["G2-item"]

    def test_skip_requires_g1c_witness_not_just_request(self):
        # The covering wwr SCC cycles via ww ONLY (no internal wr), so
        # a G1c request reports nothing for it — its rw cycle must
        # still be searched under the requested rw class.
        import numpy as np

        g = oracle.TxnGraph(
            n=2,
            src=np.asarray([0, 1, 0], np.int32),
            dst=np.asarray([1, 0, 1], np.int32),
            typ=np.asarray([oracle.WW, oracle.WW, oracle.RW], np.int8))
        r = oracle.check_graph(g, ("G1c", "G2-item"))
        assert r["valid?"] is False
        assert r["anomaly-types"] == ["G2-item"]
        # With G0 requested the SCC IS reported there and the skip is
        # legitimate: strongest explanation wins.
        r2 = oracle.check_graph(g, ("G0", "G2-item"))
        assert r2["anomaly-types"] == ["G0"]

    def test_healthy_generator_valid(self):
        h = synth.generate_list_append_history(
            600, concurrency=8, keys=6, seed=11, crash_prob=0.02,
            max_crashes=5)
        r = oracle.check(h, consistency="serializable")
        assert r["valid?"] is True, r
        assert r["stats"]["edges"] > 0

    def test_healthy_strict_serializable_valid(self):
        h = synth.generate_list_append_history(
            300, concurrency=6, keys=4, seed=5)
        r = oracle.check(h, consistency="strict-serializable")
        assert r["valid?"] is True, r
        assert r["stats"]["edge_counts"]["rt"] > 0

    def test_spliced_anomaly_found_in_big_history(self):
        h = synth.splice_anomaly(
            synth.generate_list_append_history(400, seed=2),
            "G-single", seed=2, n=2)
        r = oracle.check(h)
        assert r["valid?"] is False
        assert "G-single" in r["anomaly-types"]

    def test_witness_is_canonical_and_minimal(self):
        # The witness for the 2-cycle seeds is exactly the 2-cycle
        # through the smallest node — deterministic across runs.
        r1 = oracle.check(synth.seeded_anomaly_history("G1c"))
        r2 = oracle.check(synth.seeded_anomaly_history("G1c"))
        w = r1["anomalies"]["G1c"][0]
        assert w["nodes"] == r2["anomalies"]["G1c"][0]["nodes"]
        assert len(w["nodes"]) == 2 and w["nodes"][0] == min(w["nodes"])


class TestTarjan:
    def test_matches_bruteforce_components(self):
        import numpy as np
        import random

        rng = random.Random(4)
        for _ in range(25):
            n = rng.randrange(2, 12)
            edges = {(rng.randrange(n), rng.randrange(n))
                     for _ in range(rng.randrange(1, 3 * n))}
            edges = [(a, b) for a, b in edges if a != b]
            src = np.array([a for a, _ in edges], np.int32)
            dst = np.array([b for _, b in edges], np.int32)
            got = oracle.tarjan(n, src, dst)
            # Brute force: reachability closure.
            reach = [[False] * n for _ in range(n)]
            for a, b in edges:
                reach[a][b] = True
            for k in range(n):
                for i in range(n):
                    for j in range(n):
                        reach[i][j] = reach[i][j] or (reach[i][k]
                                                      and reach[k][j])
            comps = {}
            for v in range(n):
                rep = min([v] + [u for u in range(n)
                                 if reach[v][u] and reach[u][v]])
                comps.setdefault(rep, set()).add(v)
            want = sorted(sorted(c) for c in comps.values()
                          if len(c) > 1)
            assert sorted(got) == want, (edges, got, want)


class TestAdyaBridge:
    def _g2_history(self, both: bool):
        from jepsen_tpu import independent

        kv = independent.tuple_
        h = [Op("invoke", "insert", kv(1, {"key": 1, "id": 0}), 0),
             Op("invoke", "insert", kv(1, {"key": 1, "id": 1}), 1),
             Op("ok", "insert", kv(1, {"key": 1, "id": 0}), 0),
             Op("ok" if both else "fail", "insert",
                kv(1, {"key": 1, "id": 1}), 1)]
        return h

    def test_double_insert_classifies_g2_item(self):
        from jepsen_tpu import adya

        th = adya.history_to_txn(self._g2_history(both=True))
        r = oracle.check(th, consistency="serializable")
        assert r["valid?"] is False
        assert "G2-item" in r["anomaly-types"], r

    def test_serializable_g2_run_converts_valid(self):
        from jepsen_tpu import adya

        th = adya.history_to_txn(self._g2_history(both=False))
        r = oracle.check(th, consistency="serializable")
        assert r["valid?"] is True, r

    def test_workload_fake_parity(self):
        # The fake G2 client's own histories, bridged: faulty="g2"
        # must be a txn G2-item; the serializable fake must convert
        # valid — the 104-line probe and the general checker agree.
        from jepsen_tpu import adya

        for faulty, valid in (("g2", False), (None, True)):
            client = adya._FakeG2Client(faulty=faulty)
            h = []
            for pid in (0, 1):
                c = client.open(None, "n1")
                op = Op("invoke", "insert", {"key": 5, "id": pid}, pid)
                h.append(op)
                h.append(c.invoke(None, op))
            r = oracle.check(adya.history_to_txn(h))
            assert r["valid?"] is valid, (faulty, r)

    def test_bare_values_keep_their_keys(self):
        # Regression (review finding): bare (un-lifted) op values must
        # take their key from the payload — collapsing every key onto
        # the "None:*" namespace aliased different keys' winning rows
        # into fabricated duplicate-elements convictions.
        from jepsen_tpu import adya

        h = []
        for pid, key in ((0, 1), (1, 2)):    # two keys, one winner each
            client = adya._FakeG2Client(faulty=None)
            c = client.open(None, "n1")
            op = Op("invoke", "insert", {"key": key, "id": 0}, pid)
            h.append(op)
            h.append(c.invoke(None, op))
        th = adya.history_to_txn(h)
        assert all(m[1].startswith(("1:", "2:"))
                   for o in th for m in o.value)
        r = oracle.check(th)
        assert r["valid?"] is True, r


class TestG2Coverage:
    def _independent_history(self, outcomes):
        from jepsen_tpu import independent

        kv = independent.tuple_
        h = []
        for k, (a, b) in enumerate(outcomes):
            for pid, typ in ((2 * k, a), (2 * k + 1, b)):
                i = pid % 2
                h.append(Op("invoke", "insert",
                            kv(k, {"key": k, "id": i}), pid))
                h.append(Op(typ, "insert",
                            kv(k, {"key": k, "id": i}), pid))
        return h

    def test_coverage_aggregation(self):
        from jepsen_tpu import adya

        ck = adya.workload()["checker"]
        # key 0: race decided (one winner); key 1: vacuous; key 2: G2.
        r = ck.check(None, None, self._independent_history(
            [("ok", "fail"), ("fail", "fail"), ("ok", "ok")]), {})
        assert r["valid?"] is False
        assert r["keys-total"] == 3
        assert r["keys-exercised"] == 1
        assert r["keys-anomalous"] == 1
        assert r["keys-empty"] == 1

    def test_vacuous_pass_degrades_to_unknown(self):
        from jepsen_tpu import adya

        ck = adya.workload()["checker"]
        r = ck.check(None, None, self._independent_history(
            [("fail", "fail"), ("fail", "fail")]), {})
        assert r["valid?"] == "unknown"
        assert r["keys-exercised"] == 0
        assert "vacuous" in r["error"]

    def test_clean_coverage_stays_valid(self):
        from jepsen_tpu import adya

        ck = adya.workload()["checker"]
        r = ck.check(None, None, self._independent_history(
            [("ok", "fail"), ("fail", "ok")]), {})
        assert r["valid?"] is True
        assert r["keys-exercised"] == 2


class TestFastInferenceParity:
    """pack.infer_fast (the ISSUE 14 numpy vectorization) must be
    BYTE-IDENTICAL to oracle.infer — edge arrays, anomaly witnesses
    (order included), and stats — on every history class; the oracle
    stays the spec and the cpu-algorithm leg never shares the fast
    code."""

    @staticmethod
    def _assert_same(h, realtime=False):
        import numpy as np

        from jepsen_tpu.txn import pack

        a = oracle.infer(h, realtime=realtime)
        b = pack.infer_fast(h, realtime=realtime)
        assert a.n == b.n
        assert np.array_equal(a.src, b.src)
        assert np.array_equal(a.dst, b.dst)
        assert np.array_equal(a.typ, b.typ)
        assert a.anomalies == b.anomalies
        assert a.stats == b.stats

    def test_healthy_fuzz(self):
        for seed in range(4):
            h = synth.generate_list_append_history(
                400, concurrency=8, keys=5, seed=seed,
                crash_prob=0.02)
            self._assert_same(h)
            self._assert_same(h, realtime=True)

    def test_seeded_anomaly_corpora(self):
        h = synth.generate_list_append_history(
            300, concurrency=8, keys=4, seed=11, crash_prob=0.01)
        for kind in ("G0", "G1c", "G-single", "G2-item", "G1a"):
            self._assert_same(synth.splice_anomaly(h, kind, seed=5))

    def test_corrupted_reads_take_the_oracle_path(self):
        # Mutated read heads fail the prefix check (incompatible-order
        # + garbage-read): the fast path must fall to the literal
        # per-element loop and still match exactly.
        h = list(synth.generate_list_append_history(
            200, concurrency=6, keys=3, seed=9))
        mutated = 0
        for op in h:
            if op.type == "ok" and op.value and mutated < 3:
                for m in op.value:
                    if m[0] == "r" and m[2] and len(m[2]) > 1:
                        m[2][0] = 10 ** 6 + mutated
                        mutated += 1
                        break
        assert mutated
        self._assert_same(h)

    def test_float_values_never_truncate_into_false_prefix(self):
        # Regression (review finding): a corrupt store returning 1.5
        # must NOT truncate to 1 in the int columns and pass the
        # prefix check — oracle reports garbage-read +
        # incompatible-order, and the fast path must match exactly.
        h = []
        _txn(h, 0, [["append", "x", 1]])
        _txn(h, 1, [["append", "x", 2]])
        _txn(h, 2, [["r", "x", None]], [["r", "x", [1, 2]]])
        _txn(h, 3, [["r", "x", None]], [["r", "x", [1.5]]])
        g = oracle.infer(h)
        assert "garbage-read" in g.anomalies
        assert "incompatible-order" in g.anomalies
        self._assert_same(h)

    def test_non_int_values_degrade_to_spec(self):
        # String values defeat the int columns: every read takes the
        # oracle's literal path — same answers, no crash.
        h = []
        _txn(h, 0, [["append", "x", "a"]])
        _txn(h, 1, [["append", "x", "b"]])
        _txn(h, 2, [["r", "x", None]], [["r", "x", ["a"]]])
        _txn(h, 3, [["r", "x", None]], [["r", "x", ["a", "b"]]])
        self._assert_same(h)

    def test_duplicate_and_aborted_reads(self):
        # A failed append observed by a read (G1a) plus an in-read
        # duplicate: witness dicts and counts must match exactly.
        h = []
        _txn(h, 0, [["append", "x", 1]])
        _txn(h, 1, [["append", "x", 9]], typ="fail")
        _txn(h, 2, [["r", "x", None]], [["r", "x", [1, 9, 9]]])
        self._assert_same(h)

    def test_pack_uses_fast_inference(self):
        from jepsen_tpu.txn import pack

        h = synth.generate_list_append_history(
            200, concurrency=6, keys=3, seed=4)
        pt = pack.pack(h)
        g = oracle.infer(h)
        import numpy as np

        order = np.lexsort((g.typ, g.dst, g.src))
        assert np.array_equal(pt.edge_src, g.src[order])
        assert np.array_equal(pt.edge_dst, g.dst[order])
        assert np.array_equal(pt.edge_typ, g.typ[order])
