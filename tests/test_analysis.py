"""Static analyzer (round-12 tentpole): the fault lore as rules.

Three layers of coverage:

- per-rule jaxpr units: minimal positive/negative fixtures for every
  ``analysis/jaxpr_lint`` rule, including small-scale reconstructions
  of the round-2 (nested-while gather+reduce_or, 512-row×big-cap
  envelope) and round-3 (6-operand spike-scale sort) fault shapes —
  tracing only, chip-free, no XLA compiles;
- shipped-program regressions: every engine program family (dense
  chunk, sparse chunk, host fixpoint, K-row wave, psort dedups, txn
  SCC tiers) passes un-flagged — via direct ``make_jaxpr`` for the
  un-supervised dense/psort/txn programs and via the gate's
  per-shape-key record during REAL small-band and witness-shape runs
  for the supervised sites;
- gate semantics: ``route`` sends a flagged program down its fallback
  ladder with ZERO device dispatches (span + host-stats counters),
  records a routing-inert ``static`` ledger entry, and ``warn``
  changes nothing; plus the repo contract linter's per-rule units and
  the tier-1 zero-findings gate over this checkout.
"""

import json

import numpy as np
import pytest

from jepsen_tpu import models as m
from jepsen_tpu.analysis import gate, jaxpr_lint, lint as repo_lint
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs import trace as obs_trace

# Everything except the witness-shape run is quick; the tests that
# run real engine checks deliberately compile small .jax_cache-resident
# programs and carry the `compiles` exemption (conftest enforcement).
quick = pytest.mark.quick


@pytest.fixture(autouse=True)
def _fresh_gate(monkeypatch, tmp_path):
    # Every test gets an isolated ledger and a cold analysis cache;
    # the force hook and mode never leak between tests.
    monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                       str(tmp_path / "quarantine.json"))
    monkeypatch.delenv("JEPSEN_TPU_STATIC_FORCE", raising=False)
    gate.reset()
    yield
    gate.reset()


# --- jaxpr rule units -------------------------------------------------------


def _S(shape, dtype=None):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, dtype or jnp.uint32)


def _rules(fn, *args, **kw):
    return [f.rule for f in jaxpr_lint.analyze_fn(fn, *args, **kw)]


@quick
class TestJaxprRules:
    def test_round2_gather_reduce_or_in_nested_while_flags(self):
        import jax.numpy as jnp
        from jax import lax

        def prog(tbl, keys):
            def outer(c):
                r, k = c

                def inner(c2):
                    i, k2 = c2
                    idx = jnp.clip(k2.astype(jnp.int32), 0,
                                   tbl.shape[0] - 1)
                    g = jnp.take_along_axis(tbl, idx, 0)
                    return i + 1, jnp.where(jnp.any(g == 0), k2, g)

                i, k = lax.while_loop(lambda c2: c2[0] < 8, inner,
                                      (0, k))
                return r + 1, k

            return lax.while_loop(lambda c: c[0] < 512, outer,
                                  (0, keys))

        rules = _rules(prog, _S((1 << 18,)), _S((1 << 18,)))
        assert "gather-reduce-while" in rules

    def test_gather_reduce_or_unnested_passes(self):
        import jax.numpy as jnp

        def prog(tbl, keys):
            idx = jnp.clip(keys.astype(jnp.int32), 0, tbl.shape[0] - 1)
            g = jnp.take_along_axis(tbl, idx, 0)
            return jnp.any(g == 0)

        assert _rules(prog, _S((1 << 18,)), _S((1 << 18,))) == []

    def test_round3_wide_sort_flags(self):
        from jax import lax

        def prog(*ops):
            return lax.sort(ops, num_keys=2)

        # The 6-operand pair-dom sort at the 1M spike cap (the probed
        # worker-killer).
        assert _rules(prog, *[_S((1 << 20,))] * 6) == ["wide-sort"]
        # Small 6-operand sorts and spike-scale 4-operand sorts (the
        # dominance-word packing) are the probed-clean shapes.
        assert _rules(prog, *[_S((1024,))] * 6) == []

        def prog4(*ops):
            return lax.sort(ops, num_keys=4)

        assert _rules(prog4, *[_S((1 << 20,))] * 4) == []

    def test_round2_compact_chain_flags_in_loop_only(self):
        import jax.numpy as jnp
        from jax import lax

        def body_of(k):
            mask = k != jnp.roll(k, 1)
            pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
            return k.at[jnp.clip(pos, 0, k.shape[0] - 1)].get()

        def in_loop(keys):
            return lax.while_loop(
                lambda c: c[0] < 4,
                lambda c: (c[0] + 1, body_of(c[1])), (0, keys))

        def standalone(keys):
            return body_of(keys)

        assert "compact-chain" in _rules(in_loop, _S((1 << 18,)))
        # Components standalone are fine (round-2 lore: every
        # component is clean in isolation).
        assert _rules(standalone, _S((1 << 18,))) == []
        assert _rules(in_loop, _S((1024,))) == []

    def test_round5_unbounded_while_flags(self):
        import jax.numpy as jnp
        from jax import lax

        def orbit(keys):
            def body(c):
                k, _ = c
                k2 = jnp.sort(k)
                return k2, jnp.any(k2 != k)

            return lax.while_loop(lambda c: c[1], body, (keys, True))

        assert _rules(orbit, _S((4096,))) == ["unbounded-while"]

    def test_ceilinged_while_and_fori_pass(self):
        import jax.numpy as jnp
        from jax import lax

        def bounded(keys):
            def body(c):
                k, _, it = c
                k2 = jnp.sort(k)
                return k2, jnp.any(k2 != k), it + 1

            return lax.while_loop(lambda c: c[1] & (c[2] < 40), body,
                                  (keys, True, jnp.int32(0)))

        def fori(keys):
            return lax.fori_loop(0, 40, lambda i, k: jnp.sort(k), keys)

        assert _rules(bounded, _S((4096,))) == []
        assert _rules(fori, _S((4096,))) == []

    def test_rows_cap_envelope(self):
        import jax.numpy as jnp
        from jax import lax

        def rows_at(n_rows, keys):
            return lax.while_loop(
                lambda c: c[0] < jnp.int32(n_rows),
                lambda c: (c[0] + 1, jnp.sort(c[1])),
                (jnp.int32(0), keys))

        # 512 rows past cap 131072: the round-2/4 fault frontier.
        flagged = _rules(lambda k: rows_at(512, k), _S((1 << 18,)))
        assert "rows-cap-envelope" in flagged
        # 512 rows at the probed-clean cap, and the spike shape
        # (8 rows × 2^20) pass.
        assert _rules(lambda k: rows_at(512, k), _S((1 << 16,))) == []
        assert _rules(lambda k: rows_at(8, k), _S((1 << 20,))) == []

    def test_shard_map_bodies_are_walked(self):
        # shard_map carries its body as a RAW Jaxpr param (no
        # ClosedJaxpr wrapper); the walker must descend or the
        # mesh-chunk gate is a silent no-op.
        import numpy as np

        import jax
        import jax.numpy as jnp
        from jax import lax

        try:
            from jax.experimental.shard_map import shard_map
        except ImportError:
            pytest.skip("no shard_map in this jax build")
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:2]), ("d",))

        def unbounded(x):
            def orbit(c):
                k, _ = c
                k2 = jnp.sort(k)
                return k2, jnp.any(k2 != k)

            return lax.while_loop(lambda c: c[1], orbit, (x, True))[0]

        f = shard_map(unbounded, mesh=mesh, in_specs=P("d"),
                      out_specs=P("d"), check_rep=False)
        assert _rules(f, _S((256,))) == ["unbounded-while"]

        def bounded(x):
            def step(c):
                k, _, it = c
                k2 = jnp.sort(k)
                return k2, jnp.any(k2 != k), it + 1

            return lax.while_loop(lambda c: c[1] & (c[2] < 40), step,
                                  (x, True, jnp.int32(0)))[0]

        f2 = shard_map(bounded, mesh=mesh, in_specs=P("d"),
                       out_specs=P("d"), check_rep=False)
        assert _rules(f2, _S((256,))) == []

    def test_waive_drops_named_rules(self):
        from jax import lax

        def orbit(flag):
            return lax.while_loop(lambda c: c, lambda c: c, flag)

        import jax.numpy as jnp

        assert _rules(orbit, _S((), jnp.bool_)) == ["unbounded-while"]
        assert jaxpr_lint.analyze_fn(orbit, _S((), jnp.bool_),
                                     waive=("unbounded-while",)) == []


# --- shipped programs pass un-flagged ---------------------------------------


@pytest.fixture(scope="module")
def small_packed():
    from jepsen_tpu.lin import prepare, synth

    h = synth.generate_register_history(60, concurrency=6, seed=1,
                                        crash_prob=0.25)
    return prepare.prepare(m.cas_register(), h)


class TestShippedPrograms:
    @quick
    def test_dense_chunk_unflagged(self, small_packed):
        import jax.numpy as jnp
        from functools import partial

        from jepsen_tpu.lin import dense

        step = small_packed.kernel.step
        vw = int(np.asarray(small_packed.slot_v).shape[2])
        for w, ns in ((16, 8), (20, 32)):
            rules = _rules(
                partial(dense._dense_chunk, w=w, ns=ns, step_fn=step),
                _S((1 << w,)), _S((), jnp.int32), _S((), jnp.int32),
                _S((256,), jnp.int32), _S((256, w), jnp.bool_),
                _S((256, w), jnp.int32), _S((256, w, vw), jnp.int32))
            assert rules == [], f"dense w={w}: {rules}"

    @quick
    def test_psort_dedup_callers_unflagged(self):
        from functools import partial

        from jax.experimental.pallas import tpu as pltpu

        from jepsen_tpu.lin import psort

        if not hasattr(pltpu, "CompilerParams"):
            pytest.skip("this jax build lacks pltpu.CompilerParams "
                        "(sandbox skew — test_lin_psort fails at seed "
                        "here too; the driver env has it)")
        n = 1 << 13   # a real psort pad size (kernel shape family)
        assert _rules(partial(psort._dedup_call, n_pad=n),
                      _S((n,))) == []
        assert _rules(partial(psort._dedup2_call, n_pad=n),
                      _S((n,)), _S((n,))) == []

    @quick
    def test_txn_scc_program_unflagged(self):
        import jax.numpy as jnp
        from functools import partial

        from jepsen_tpu.txn import device as txn_device

        n_pad, e_pad = 1 << 10, 1 << 12
        rules = _rules(
            partial(txn_device._scc_program, n_pad=n_pad),
            _S((e_pad,), jnp.int32), _S((e_pad,), jnp.int32),
            _S((e_pad,), jnp.bool_), _S((), jnp.int32),
            _S((), jnp.int32))
        assert rules == []

    @quick
    def test_pack_dev_program_unflagged(self):
        # The device packer (ISSUE 20): three <=4-operand sorts, no
        # while loops at all (pointer doubling is a fixed unrolled
        # chain), nothing in the fault-lore rule set — single-lane and
        # vmapped alike. Its site routes (the numpy packer is the rung
        # below), so a future flagged variant would skip the chip.
        from jepsen_tpu.lin import pack_dev

        shape = pack_dev.pad_shape(1 << 10, 200, 12, 2)
        assert _rules(pack_dev.pack_traceable(shape)) == []
        assert _rules(pack_dev.pack_traceable(shape, lanes=8)) == []
        assert "pack-dev" in gate.ROUTED_SITES

    @quick
    @pytest.mark.compiles
    def test_supervised_sites_analyze_clean_small_band(
            self, monkeypatch, small_packed):
        # A REAL host-row run under the default warn gate: every shape
        # the engines actually dispatched (chunk, chunk-batch, the
        # episode scheduler, fused fixpoint — and, scheduler off, the
        # K-row wave) was traced by the gate and found clean, and
        # nothing was unanalyzable.
        from jepsen_tpu.lin import bfs

        monkeypatch.setenv("JEPSEN_TPU_STATIC_GATE", "warn")
        monkeypatch.setenv("JEPSEN_TPU_HOST_STICKY", "1")
        monkeypatch.setenv("JEPSEN_TPU_HOST_ROWS_K", "4")
        r = bfs.check_packed(small_packed, cap_schedule=(1,),
                             host_caps=(8, 64, 512))
        assert r["valid?"] is True
        seen = gate.analyzed()
        sites = {k.split("|", 1)[0] for k in seen}
        assert {"chunk", "host-sched"} <= sites, sites
        monkeypatch.setenv("JEPSEN_TPU_HOST_SCHED", "0")
        r = bfs.check_packed(small_packed, cap_schedule=(1,),
                             host_caps=(8, 64, 512))
        assert r["valid?"] is True
        seen = gate.analyzed()
        sites = {k.split("|", 1)[0] for k in seen}
        assert {"chunk", "host-fixpoint", "host-wave",
                "host-sched"} <= sites, sites
        flagged = {k: [str(f) for f in v]
                   for k, v in seen.items() if v}
        assert flagged == {}
        assert gate.unanalyzable() == set()


# The pair-key crash-dom WITNESS shape (the scaled-down literal
# config-5 class) compiles the big-cap programs: default tier, not
# quick — matching test_lin_crashdom_witness's billing.
@pytest.mark.compiles
def test_witness_shape_analyzes_clean(monkeypatch):
    from jepsen_tpu.lin import bfs, prepare, synth

    h = synth.generate_partitioned_register_history(
        140, concurrency=40, seed=0, partition_every=60,
        partition_len=20, max_crashes=10)
    p = prepare.prepare(m.cas_register(),
                        synth.corrupt_history(h, seed=3))
    monkeypatch.setenv("JEPSEN_TPU_STATIC_GATE", "warn")
    monkeypatch.setenv("JEPSEN_TPU_HOST_STICKY", "1")
    monkeypatch.setenv("JEPSEN_TPU_HOST_ROWS_K", "4")
    r = bfs.check_packed(p, cap_schedule=(8,), host_caps=(64, 4096))
    assert r["valid?"] is False
    seen = gate.analyzed()
    assert seen and all(v == [] for v in seen.values()), {
        k: [str(f) for f in v] for k, v in seen.items() if v}
    assert gate.unanalyzable() == set()


# --- gate semantics ---------------------------------------------------------


class TestGate:
    @quick
    def test_unanalyzable_passes_and_is_remembered(self):
        def raises():
            raise RuntimeError("not traceable")

        assert gate.check("k1", raises) == []
        assert "k1" in gate.unanalyzable()

    @quick
    def test_force_hook_and_modes(self, monkeypatch):
        import jax.numpy as jnp

        def clean():
            return jnp.zeros(4) + 1

        monkeypatch.setenv("JEPSEN_TPU_STATIC_FORCE",
                           "host-fixpoint:wide-sort")
        monkeypatch.setenv("JEPSEN_TPU_STATIC_GATE", "route")
        # Routed site + matching key -> StaticallyFlagged, ledger
        # entry, stats bump.
        stats = {}
        flagged = gate.consider("host-fixpoint",
                                "host-fixpoint|rows1|cap8|w15|k",
                                clean, stats=stats)
        assert isinstance(flagged, gate.StaticallyFlagged)
        assert flagged.findings[0].rule == "wide-sort"
        assert stats["static_skips"] == 1
        from jepsen_tpu.lin import supervise

        e = supervise.load_ledger().get(
            "host-fixpoint|rows1|cap8|w15|k")
        assert e and e["reason"] == "static"
        # ...but the entry is NOT quarantine evidence.
        assert supervise.quarantined(
            "host-fixpoint|rows1|cap8|w15|k") is None
        # Base-rung site with the same findings only warns.
        assert gate.consider("chunk", "host-fixpoint|chunk-like",
                             clean, stats=stats) is None
        # warn mode never routes, even at a routed site.
        monkeypatch.setenv("JEPSEN_TPU_STATIC_GATE", "warn")
        assert gate.consider("host-fixpoint",
                             "host-fixpoint|rows1|cap8|w15|k",
                             clean, stats=stats) is None
        # off mode does not even analyze.
        monkeypatch.setenv("JEPSEN_TPU_STATIC_GATE", "off")
        gate.reset()
        assert gate.consider("host-fixpoint",
                             "host-fixpoint|rows1|cap8|w15|k",
                             clean, stats=stats) is None
        assert gate.analyzed() == {}

    @quick
    def test_static_then_real_fault_hardens_entry(self, monkeypatch):
        from jepsen_tpu.lin import supervise

        key = "host-pass|rows1|cap64|w15|k"
        supervise.record_fault(key, "static", "predicted")
        assert supervise.quarantined(key) is None
        supervise.record_fault(key, "fault", "really died")
        e = supervise.quarantined(key)
        assert e is not None and e.get("faulted") is True

    @quick
    def test_static_never_clobbers_wedge_streak(self):
        # A prediction riding on top of real crash evidence must not
        # erase it: a wedge-streak-quarantined shape stays quarantined
        # after a static record (else gate-off would re-dispatch a
        # known-wedging shape).
        from jepsen_tpu.lin import supervise

        key = "host-wave|rows4|cap4096|w34|k"
        supervise.record_fault(key, "wedge")
        supervise.record_fault(key, "wedge")
        assert supervise.quarantined(key) is not None
        e = supervise.record_fault(key, "static", "predicted too")
        assert e["reason"] == "wedge" and e["static_count"] == 1
        assert supervise.quarantined(key) is not None

    @quick
    def test_flag_events_dedupe_per_key(self, monkeypatch):
        # A flagged per-pass shape is considered once per DISPATCH
        # (hundreds per row) but must announce once per KEY on the
        # bounded obs event feed, or it evicts the real fault/wedge
        # events triage depends on.
        import jax.numpy as jnp

        def clean():
            return jnp.zeros(4) + 1

        monkeypatch.setenv("JEPSEN_TPU_STATIC_GATE", "warn")
        monkeypatch.setenv("JEPSEN_TPU_STATIC_FORCE", "host-pass")
        obs_metrics.REGISTRY.reset()
        for _ in range(5):
            assert gate.consider("host-pass", "host-pass|cap64|k",
                                 clean) is None
        kinds = [e.get("kind")
                 for e in obs_metrics.REGISTRY.snapshot()["events"]]
        assert kinds.count("static") == 1

    @quick
    @pytest.mark.compiles
    def test_route_mode_reaches_fallback_with_zero_dispatches(
            self, monkeypatch, small_packed):
        # The ISSUE acceptance shape: a flagged program (forced via
        # the test hook — shipped programs are clean) reaches its
        # fallback rung with ZERO device dispatches, visible in BOTH
        # the span stream and host-stats, plus a `static` ledger
        # entry; the verdict is untouched.
        from jepsen_tpu.lin import bfs, supervise

        monkeypatch.setenv("JEPSEN_TPU_STATIC_GATE", "route")
        monkeypatch.setenv("JEPSEN_TPU_STATIC_FORCE", "host-fixpoint")
        monkeypatch.setenv("JEPSEN_TPU_HOST_ROWS_K", "1")
        monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")
        monkeypatch.setenv("JEPSEN_TPU_TRACE_FILE", "0")
        obs_trace.reset()
        try:
            r = bfs.check_packed(small_packed, cap_schedule=(1,),
                                 host_caps=(8, 64, 512))
        finally:
            events = obs_trace.events()
            obs_trace.reset()
        assert r["valid?"] is True
        s = r["host-stats"]
        assert s["static_skips"] >= 1
        dispatch_sites = {e["args"].get("site") for e in events
                          if e.get("name") == "dispatch"}
        # The flagged fused-fixpoint program NEVER dispatched; its
        # fallback rung (the unfused per-pass program) did the rows.
        assert "host-fixpoint" not in dispatch_sites
        assert "host-pass" in dispatch_sites
        skips = [e for e in events if e.get("name") == "static-skip"]
        assert skips and skips[0]["args"]["est_saved_s"] > 0
        entries = [k for k in supervise.load_ledger()
                   if k.startswith("host-fixpoint")]
        assert entries
        assert all(supervise.load_ledger()[k]["reason"] == "static"
                   for k in entries)
        # Verdict parity with an ungated run of the same shape.
        monkeypatch.setenv("JEPSEN_TPU_STATIC_GATE", "off")
        ref = bfs.check_packed(small_packed, cap_schedule=(1,),
                               host_caps=(8, 64, 512))
        assert ref["valid?"] is r["valid?"]

    @quick
    @pytest.mark.compiles
    def test_warn_mode_changes_nothing_but_records(self, monkeypatch,
                                                   small_packed):
        from jepsen_tpu.lin import bfs

        monkeypatch.setenv("JEPSEN_TPU_STATIC_GATE", "warn")
        monkeypatch.setenv("JEPSEN_TPU_STATIC_FORCE", "host-fixpoint")
        monkeypatch.setenv("JEPSEN_TPU_HOST_ROWS_K", "1")
        obs_metrics.REGISTRY.reset()
        r = bfs.check_packed(small_packed, cap_schedule=(1,),
                             host_caps=(8, 64, 512))
        assert r["valid?"] is True
        assert r["host-stats"]["static_skips"] == 0
        snap = obs_metrics.REGISTRY.snapshot()
        kinds = [e.get("kind") for e in snap.get("events", [])]
        assert "static" in kinds


# --- quarantine CLI + attribution -------------------------------------------


@quick
def test_quarantine_list_distinguishes_static(capsys):
    from jepsen_tpu import cli
    from jepsen_tpu.lin import supervise

    supervise.record_fault("chunk|rows512|cap8|w15|k", "fault", "boom")
    supervise.record_fault("host-wave|rows4|cap64|w15|k", "static",
                           "wide-sort: predicted")
    assert cli.run(cli.standard_commands(),
                   ["quarantine", "list"]) == cli.EXIT_OK
    out = capsys.readouterr().out
    assert "reason=fault" in out
    assert "static (gate-predicted" in out
    assert "host-wave|rows4|cap64|w15|k" in out


@quick
def test_trace_report_prices_static_skips():
    from jepsen_tpu.obs import report

    events = [
        {"name": "check", "ph": "X", "ts": 0.0, "dur": 10.0,
         "args": {}},
        {"name": "dispatch", "ph": "X", "ts": 1.0, "dur": 2.0,
         "args": {"site": "host-pass", "outcome": "ok",
                  "shape": "host-pass|rows1|cap64|w15|k"}},
        {"name": "static-skip", "ph": "i", "ts": 1.5, "dur": 0.0,
         "args": {"site": "host-fixpoint", "est_saved_s": 60.0}},
        {"name": "static-skip", "ph": "i", "ts": 2.5, "dur": 0.0,
         "args": {"site": "host-fixpoint", "est_saved_s": 60.0}},
    ]
    agg = report.attribution(events)
    assert agg["static_skips"] == 2
    assert agg["static_saved_est_s"] == 120.0
    text = report.render(agg)
    assert "avoided (static gate)" in text
    assert report.summary(events)["static_skips"] == 2


# --- repo contract linter ---------------------------------------------------


@quick
class TestRepoLint:
    def test_while_ceiling_rule(self):
        bad = ("import jax.lax as lax\n"
               "def f(c):\n"
               "    return lax.while_loop(lambda c: c[1], b, c)\n")
        fs = repo_lint.lint_while_source(bad, "x.py")
        assert [f.rule for f in fs] == ["while-ceiling"]
        ok = ("def f(c):\n"
              "    return lax.while_loop(\n"
              "        lambda c: c[1] & (c[2] < 40), b, c)\n")
        assert repo_lint.lint_while_source(ok, "x.py") == []
        named = ("def cond(c):\n"
                 "    return c[0] < 10\n"
                 "def f(c):\n"
                 "    return lax.while_loop(cond, b, c)\n")
        assert repo_lint.lint_while_source(named, "x.py") == []
        waived = ("def f(c):\n"
                  "    # lint: unbounded-ok — monotone fixpoint\n"
                  "    return lax.while_loop(lambda c: c[1], b, c)\n")
        assert repo_lint.lint_while_source(waived, "x.py") == []
        fori = ("def f(c):\n"
                "    return lax.fori_loop(0, 8, b, c)\n")
        assert repo_lint.lint_while_source(fori, "x.py") == []

    def test_wire_fail_rule(self):
        bad = ("def invoke(op):\n"
               "    try:\n"
               "        pass\n"
               "    except OSError:\n"
               "        return op.replace(type=\"fail\")\n")
        fs = repo_lint.lint_wire_source(bad, "zwire.py")
        assert [f.rule for f in fs] == ["wire-fail"]
        guarded = ("def invoke(op):\n"
                   "    try:\n"
                   "        pass\n"
                   "    except OSError as e:\n"
                   "        return op.replace(\n"
                   "            type=\"fail\" if op.f == \"read\""
                   " else \"info\")\n")
        assert repo_lint.lint_wire_source(guarded, "zwire.py") == []
        inverted = ("def invoke(op):\n"
                    "    try:\n"
                    "        pass\n"
                    "    except OSError as e:\n"
                    "        return op.replace(\n"
                    "            type=\"info\" if op.f == \"read\""
                    " else \"fail\")\n")
        assert [f.rule for f in repo_lint.lint_wire_source(
            inverted, "zwire.py")] == ["wire-fail"]
        waived = ("def invoke(op):\n"
                  "    try:\n"
                  "        pass\n"
                  "    except OSError:\n"
                  "        # lint: fail-ok — parsed server rejection\n"
                  "        return op.replace(type=\"fail\")\n")
        assert repo_lint.lint_wire_source(waived, "zwire.py") == []
        outside = ("def invoke(op):\n"
                   "    return op.replace(type=\"fail\")\n")
        assert repo_lint.lint_wire_source(outside, "zwire.py") == []

    def test_pallas_const_rule(self):
        bad = ("import jax.numpy as jnp\n"
               "from jax.experimental import pallas as pl\n"
               "MASK = jnp.uint32(7)\n")
        fs = repo_lint.lint_pallas_source(bad, "k.py")
        assert [f.rule for f in fs] == ["pallas-const"]
        ok_int = ("import jax.numpy as jnp\n"
                  "from jax.experimental import pallas as pl\n"
                  "MASK = 7\n"
                  "def kern():\n"
                  "    return jnp.uint32(MASK)\n")
        assert repo_lint.lint_pallas_source(ok_int, "k.py") == []
        no_pallas = ("import jax.numpy as jnp\n"
                     "MASK = jnp.uint32(7)\n")
        assert repo_lint.lint_pallas_source(no_pallas, "k.py") == []

    def test_quick_compiles_rule(self):
        bad = ("import pytest\n"
               "from jepsen_tpu.lin import bfs\n"
               "pytestmark = pytest.mark.quick\n")
        fs = repo_lint.lint_quick_source(bad, "test_x.py")
        assert [f.rule for f in fs] == ["quick-compiles"]
        ok = bad + "also = pytest.mark.compiles\n"
        assert repo_lint.lint_quick_source(ok, "test_x.py") == []
        not_quick = ("import pytest\n"
                     "from jepsen_tpu.lin import bfs\n")
        assert repo_lint.lint_quick_source(not_quick,
                                           "test_x.py") == []

    def test_env_doc_drift_detected(self, tmp_path):
        # Fake knob names are built by concatenation so this test
        # file's own source never trips the real repo scan.
        real = "JEPSEN_TPU_" + "REAL"
        stale = "JEPSEN_TPU_" + "STALE_ROW"
        undoc = "JEPSEN_TPU_" + "UNDOCUMENTED"
        prefix = "JEPSEN_TPU_" + "PREFIX_"
        (tmp_path / "doc").mkdir()
        (tmp_path / "jepsen_tpu").mkdir()
        (tmp_path / "doc" / "env.md").write_text(
            f"| `{real}` | ... |\n| `{stale}` | ... |\n")
        (tmp_path / "jepsen_tpu" / "x.py").write_text(
            f"import os\nA = os.environ.get('{real}')\n"
            f"B = os.environ.get('{undoc}')\nC = '{prefix}'\n")
        fs = repo_lint.lint_env_doc(str(tmp_path))
        msgs = "\n".join(f.msg for f in fs)
        assert undoc in msgs
        assert stale in msgs
        assert real not in msgs
        assert prefix not in msgs

    def test_repo_lint_clean(self):
        # THE tier-1 contract gate: the shipped checkout has zero
        # findings — every future PR that breaks an invariant (a new
        # undocumented knob, an unceilinged loop, an unsound :fail, a
        # Pallas module constant, an unmarked compiling quick test)
        # fails here.
        findings = repo_lint.lint_repo()
        assert findings == [], repo_lint.render(findings)

    def test_cli_lint_drives(self, capsys):
        from jepsen_tpu import cli

        cmds = cli.standard_commands()
        assert cli.run(cmds, ["lint"]) == cli.EXIT_OK
        assert "lint: clean" in capsys.readouterr().out
        assert cli.run(cmds, ["lint", "--json"]) == cli.EXIT_OK
        assert json.loads(capsys.readouterr().out) == []
