"""ZooKeeper jute + IRC line-protocol clients against in-process fake
servers — the zk fake implements a real versioned znode store, so the
version-conditioned setData CAS is exercised end to end."""

from __future__ import annotations

import socket
import struct
import threading

import pytest

from jepsen_tpu.suites.zkwire import (ZBADVERSION, ZNONODE, ZkClient,
                                      ZkError, ZkRegisterClient)

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick

# --- fake ZooKeeper server ---------------------------------------------------


class FakeZkServer:
    """Single-session jute server with a real versioned znode store."""

    def __init__(self):
        self.nodes: dict[str, tuple[bytes, int]] = {}
        # Fault hook: when > 0, the next setData APPLIES server-side
        # and then drops the connection without replying — the
        # indeterminate-outcome case wire clients must complete :info.
        self.drop_after_apply = 0
        self.srv = socket.socket()
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(4)
        self.port = self.srv.getsockname()[1]
        self.threads: list[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self.threads.append(t)

    @staticmethod
    def _read_frame(conn, buf: bytearray) -> bytes:
        while len(buf) < 4:
            chunk = conn.recv(65536)
            if not chunk:
                raise ConnectionError
            buf += chunk
        (n,) = struct.unpack(">i", bytes(buf[:4]))
        while len(buf) < 4 + n:
            chunk = conn.recv(65536)
            if not chunk:
                raise ConnectionError
            buf += chunk
        out = bytes(buf[4:4 + n])
        del buf[:4 + n]
        return out

    @staticmethod
    def _send_frame(conn, payload: bytes):
        conn.sendall(struct.pack(">i", len(payload)) + payload)

    @staticmethod
    def _stat(version: int) -> bytes:
        return (b"\x00" * 32 + struct.pack(">i", version)
                + b"\x00" * (68 - 36))

    def _serve(self, conn):
        buf = bytearray()
        try:
            self._read_frame(conn, buf)          # ConnectRequest
            self._send_frame(conn, struct.pack(">iiq", 0, 10000, 0x1234)
                             + struct.pack(">i", 16) + b"\x00" * 16)
            while True:
                req = self._read_frame(conn, buf)
                xid, op = struct.unpack_from(">ii", req, 0)
                body = req[8:]
                (plen,) = struct.unpack_from(">i", body, 0)
                path = body[4:4 + plen].decode()
                rest = body[4 + plen:]

                def reply(err: int, payload: bytes = b""):
                    self._send_frame(
                        conn, struct.pack(">iqi", xid, 1, err) + payload)

                if op == 1:                      # create
                    if path in self.nodes:
                        reply(-110)
                        continue
                    (dlen,) = struct.unpack_from(">i", rest, 0)
                    self.nodes[path] = (rest[4:4 + max(dlen, 0)], 0)
                    reply(0, struct.pack(">i", plen)
                          + path.encode())
                elif op == 3:                    # exists
                    if path in self.nodes:
                        reply(0, self._stat(self.nodes[path][1]))
                    else:
                        reply(ZNONODE)
                elif op == 4:                    # getData
                    if path not in self.nodes:
                        reply(ZNONODE)
                        continue
                    data, version = self.nodes[path]
                    reply(0, struct.pack(">i", len(data)) + data
                          + self._stat(version))
                elif op == 5:                    # setData
                    if path not in self.nodes:
                        reply(ZNONODE)
                        continue
                    (dlen,) = struct.unpack_from(">i", rest, 0)
                    data = rest[4:4 + max(dlen, 0)]
                    (want,) = struct.unpack_from(">i", rest,
                                                 4 + max(dlen, 0))
                    _, version = self.nodes[path]
                    if want not in (-1, version):
                        reply(ZBADVERSION)
                        continue
                    self.nodes[path] = (data, version + 1)
                    if self.drop_after_apply > 0:
                        self.drop_after_apply -= 1
                        return       # applied, but the reply is lost
                    reply(0, self._stat(version + 1))
                elif op == -11:                  # close
                    return
                else:
                    reply(-6)                    # unimplemented
        except (ConnectionError, OSError, struct.error):
            # struct.error: the client hung up mid-frame (normal at
            # test teardown) — swallow it so a green run stays free of
            # PytestUnhandledThreadExceptionWarnings.
            return
        finally:
            conn.close()

    def close(self):
        self.srv.close()


class TestZkWire:
    def test_create_get_set_cas(self):
        zk = FakeZkServer()
        c = ZkClient("127.0.0.1", zk.port)
        assert not c.exists("/r")
        c.create("/r", b"5")
        assert c.exists("/r")
        data, version = c.get_data("/r")
        assert (data, version) == (b"5", 0)
        v2 = c.set_data("/r", b"7", version=0)
        assert v2 == 1
        with pytest.raises(ZkError) as ei:
            c.set_data("/r", b"9", version=0)   # stale version = CAS fail
        assert ei.value.bad_version
        assert c.get_data("/r")[0] == b"7"
        c.set_data("/r", b"8")                  # unconditional
        assert c.get_data("/r")[0] == b"8"
        c.close()
        zk.close()

    def test_register_client_semantics(self):
        from jepsen_tpu.history import Op

        zk = FakeZkServer()
        # the fake's port is non-standard; connect + create manually
        cl = ZkRegisterClient(ZkClient("127.0.0.1", zk.port))
        cl.conn.create("/jepsen-register", b"")
        assert cl.invoke(None, Op("invoke", "read", None, 0)).value is None
        assert cl.invoke(None, Op("invoke", "write", 3, 0)).is_ok
        assert cl.invoke(None, Op("invoke", "read", None, 0)).value == 3
        assert cl.invoke(None, Op("invoke", "cas", [3, 4], 0)).is_ok
        r = cl.invoke(None, Op("invoke", "cas", [3, 9], 0))
        assert r.is_fail
        assert cl.invoke(None, Op("invoke", "read", None, 0)).value == 4
        cl.close(None)
        zk.close()

    def test_mid_request_drop_completes_info_and_reconnects(self):
        # The server APPLIES a write, then drops the connection before
        # replying. The completion must be :info (indeterminate) —
        # never :fail — and the next op must come back through the
        # bounded-reconnect ladder with a fresh session.
        from jepsen_tpu import models as m
        from jepsen_tpu.history import (History, Op, fail_op, info_op,
                                        invoke_op, ok_op)
        from jepsen_tpu.lin import analysis

        zk = FakeZkServer()
        cl = ZkRegisterClient(ZkClient("127.0.0.1", zk.port))
        cl.conn.create("/jepsen-register", b"")
        zk.drop_after_apply = 1
        r = cl.invoke(None, Op("invoke", "write", 7, 0))
        assert r.type == "info", \
            f"indeterminate write completed {r.type!r}"
        # Reconnect + fresh session handshake on the NEXT op; the
        # applied-but-unacknowledged write is visible.
        r2 = cl.invoke(None, Op("invoke", "read", None, 1))
        assert r2.is_ok and r2.value == 7
        assert cl.conn.io.reconnects >= 2    # initial dial + reconnect

        # Checker soundness of the completion: with :info the observed
        # history is linearizable; completing the SAME op :fail would
        # (correctly) be flagged invalid — the exact unsoundness the
        # :info contract exists to prevent.
        sound = History.of(
            invoke_op(0, "write", 7), invoke_op(1, "read", None),
            ok_op(1, "read", 7), info_op(0, "write", 7))
        assert analysis(m.cas_register(), sound,
                        algorithm="cpu")["valid?"] is True
        unsound = History.of(
            invoke_op(0, "write", 7), invoke_op(1, "read", None),
            ok_op(1, "read", 7), fail_op(0, "write", 7))
        assert analysis(m.cas_register(), unsound,
                        algorithm="cpu")["valid?"] is False
        cl.close(None)
        zk.close()

    def test_reconnect_budget_exhausts_as_info_for_mutators(
            self, monkeypatch):
        # Server gone for good: the bounded backoff ladder runs out.
        # A mutator completes :info (conservative), a read :fail —
        # and the budget bounds the wall cost (no infinite retry).
        from jepsen_tpu.history import Op

        monkeypatch.setenv("JEPSEN_TPU_WIRE_RETRIES", "2")
        monkeypatch.setenv("JEPSEN_TPU_WIRE_BACKOFF_S", "0.01")
        zk = FakeZkServer()
        cl = ZkRegisterClient(ZkClient("127.0.0.1", zk.port))
        cl.conn.create("/jepsen-register", b"")
        zk.drop_after_apply = 1
        assert cl.invoke(None, Op("invoke", "write", 1, 0)).type == "info"
        # Point the reconnect factory at a port nothing listens on
        # (closing the fake's listener is not enough: CPython keeps
        # the fd alive while the accept thread blocks on it).
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        dead_port = probe.getsockname()[1]
        probe.close()
        cl.conn.io._connect = lambda: socket.create_connection(
            ("127.0.0.1", dead_port), timeout=0.5)
        r = cl.invoke(None, Op("invoke", "write", 2, 0))
        assert r.type == "info"
        assert cl.invoke(None, Op("invoke", "read", None, 0)).is_fail
        zk.close()


# --- fake IRC server ---------------------------------------------------------


class TestIrcWire:
    def test_register_join_say_collect(self):
        from jepsen_tpu.suites.ircwire import IrcClient

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(2)
        port = srv.getsockname()[1]

        def run():
            conn, _ = srv.accept()
            buf = b""

            def lines():
                nonlocal buf, conn
                while True:
                    while b"\r\n" not in buf:
                        buf += conn.recv(4096)
                    line, buf = buf.split(b"\r\n", 1)
                    yield line.decode()

            it = lines()
            nick = None
            while nick is None:
                ln = next(it)
                if ln.startswith("NICK "):
                    nick = ln.split()[1]
            conn.sendall(f":srv 001 {nick} :welcome\r\n".encode())
            while True:
                ln = next(it)
                if ln.startswith("JOIN "):
                    chan = ln.split()[1]
                    conn.sendall(
                        f":{nick}!u@h JOIN {chan}\r\n".encode())
                    break
            conn.sendall(f"PING :tok\r\n".encode())
            got_pong = False
            try:
                while True:
                    ln = next(it)
                    if ln.startswith("PONG"):
                        got_pong = True
                    elif ln.startswith("PING"):
                        # the client's per-message ack round-trip
                        tok = ln.partition(" ")[2]
                        conn.sendall(f"PONG {tok}\r\n".encode())
                    elif ln.startswith("PRIVMSG"):
                        # deliver a peer's message (own msgs not echoed)
                        conn.sendall(
                            f":peer!u@h PRIVMSG {chan} :41\r\n".encode())
                    elif ln.startswith("QUIT"):
                        break
            except (ConnectionError, OSError):
                pass
            assert got_pong

        threading.Thread(target=run, daemon=True).start()
        c = IrcClient("127.0.0.1", port, nick="jepsen1")
        c.say("40")        # blocks until the PING ack round-trip
        import time

        deadline = time.time() + 5
        while len(c.seen()) < 2 and time.time() < deadline:
            time.sleep(0.01)
        # own confirmed send (not echoed by the server) + the peer's
        assert sorted(c.seen()) == ["40", "41"]
        c.close()
        srv.close()


def test_zk_and_irc_suites_ungated():
    from jepsen_tpu.suites import common, robustirc, zookeeper

    for mod in (zookeeper, robustirc):
        t = mod.test({})
        assert not isinstance(t["client"], common.GatedClient), mod
