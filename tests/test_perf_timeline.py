"""Perf-math golden tests (ported from checker_test.clj:156-205), plus
timeline/graph artifact generation on a synthetic 10k-op history."""

import random

from jepsen_tpu import checker as c
from jepsen_tpu.checker import perf_graphs as perf
from jepsen_tpu.checker import timeline
from jepsen_tpu.history import Op, invoke_op, ok_op
import pytest

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick


def test_bucket_points():
    # checker_test.clj:156-171
    got = perf.bucket_points(2, [(1, "a"), (7, "g"), (5, "e"), (2, "b"),
                                 (3, "c"), (4, "d"), (6, "f")])
    assert got == {1: [(1, "a")],
                   3: [(2, "b"), (3, "c")],
                   5: [(5, "e"), (4, "d")],
                   7: [(7, "g"), (6, "f")]}


def test_latencies_to_quantiles():
    # checker_test.clj:173-186
    pts = list(zip(range(11), [0, 10, 1, 1, 1, 20, 21, 22, 25, 25, 25]))
    got = perf.latencies_to_quantiles(5, [0, 1], pts)
    assert got == {0: [[2.5, 0], [7.5, 20], [12.5, 25]],
                   1: [[2.5, 10], [7.5, 25], [12.5, 25]]}


def _random_history(n=10000, seed=0):
    # the shape of checker_test.clj:188-205's perf-test history
    rng = random.Random(seed)
    h = []
    for _ in range(n // 2):
        latency = 1e9 / (1 + rng.randrange(1000))
        f = rng.choice(["write", "read"])
        proc = rng.randrange(100)
        t = 1e9 * rng.randrange(100)
        typ = rng.choice(["ok"] * 5 + ["fail"] + ["info"] * 2)
        h.append(Op("invoke", f, None, proc, time=int(t)))
        h.append(Op(typ, f, None, proc, time=int(t + latency)))
    h.append(Op("info", "start", None, "nemesis", time=int(10e9)))
    h.append(Op("info", "stop", None, "nemesis", time=int(30e9)))
    return h


def test_perf_checker_writes_graphs(tmp_path):
    test = {"name": "perf-test", "store-base": str(tmp_path),
            "start-time": "t0"}
    r = c.perf().check(test, None, _random_history(), {})
    assert r[c.VALID] is True
    d = tmp_path / "perf-test" / "t0"
    assert (d / "latency-raw.png").stat().st_size > 1000
    assert (d / "latency-quantiles.png").stat().st_size > 1000
    assert (d / "rate.png").stat().st_size > 1000


def test_rate_math():
    h = [invoke_op(0, "read", None).replace(time=0),
         ok_op(0, "read", 1).replace(time=int(1e9)),
         invoke_op(0, "read", None).replace(time=int(2e9)),
         ok_op(0, "read", 1).replace(time=int(3e9))]
    r = perf.rate(10.0, h)
    assert r[("read", "ok")] == [[5.0, 0.2]]


def test_timeline_html(tmp_path):
    test = {"name": "tl", "store-base": str(tmp_path),
            "start-time": "t0", "concurrency": 2}
    h = [invoke_op(0, "read", None).replace(time=0),
         ok_op(0, "read", 5).replace(time=int(3e6)),
         invoke_op(1, "write", 7).replace(time=int(1e6)),
         # process 1's op never returns
         ]
    r = timeline.checker().check(test, None, h, {})
    assert r[c.VALID] is True
    doc = (tmp_path / "tl" / "t0" / "timeline.html").read_text()
    assert "read" in doc and "write" in doc
    assert "never returned" in doc
    assert doc.count('class="op"') == 2


def test_timeline_pairs():
    h = [invoke_op(0, "read", None).replace(time=0),
         invoke_op(1, "write", 1).replace(time=1),
         ok_op(1, "write", 1).replace(time=2),
         ok_op(0, "read", 9).replace(time=3)]
    ps = timeline.pairs(h)
    assert len(ps) == 2
    assert ps[0][0].process == 0 and ps[0][1].value == 9
