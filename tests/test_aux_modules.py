"""Tests for the auxiliary parity modules: codec, report, repl,
lin.report (SVG counterexamples), os_smartos."""

from __future__ import annotations

import jepsen_tpu.history as h
from jepsen_tpu import codec, models, report, repl, store
from jepsen_tpu.lin import analysis
from jepsen_tpu.lin import report as lin_report
import pytest

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick


class TestCodec:
    def test_roundtrip_scalars(self):
        for v in (None, 0, 1, -5, 1.5, "x", True, False, [1, 2], {"a": 1}):
            assert codec.decode(codec.encode(v)) == v

    def test_roundtrip_tagged(self):
        for v in ((1, 2), {3, 1, 2}, b"\x00\xffbytes",
                  {"k": (1, {"nested": {2, 3}})}):
            assert codec.decode(codec.encode(v)) == v

    def test_none_is_empty(self):
        assert codec.encode(None) == b""
        assert codec.decode(b"") is None
        assert codec.decode(None) is None

    def test_accepts_str(self):
        assert codec.decode(codec.encode([1]).decode()) == [1]

    def test_non_string_dict_keys(self):
        for v in ({1: "a"}, {1: "a", "b": 2}, {(1, 2): {3}}):
            assert codec.decode(codec.encode(v)) == v

    def test_nested_frozenset(self):
        # frozensets survive inside hashable containers (set elements,
        # dict keys) and keep their type through the round trip.
        for v in ({frozenset({1, 2})},
                  {(1, frozenset({2})): "x"},
                  frozenset({3, 4}),
                  [frozenset(), {frozenset({5}), frozenset({6})}]):
            got = codec.decode(codec.encode(v))
            assert got == v
            assert type(got) is type(v)


class TestReport:
    def test_tee_to_file(self, tmp_path, capsys):
        p = tmp_path / "sub" / "report.txt"
        with report.to(p):
            print("hello analysis")
        assert p.read_text() == "hello analysis\n"
        assert "hello analysis" in capsys.readouterr().out

    def test_no_echo(self, tmp_path, capsys):
        p = tmp_path / "quiet.txt"
        with report.to(p, echo=False):
            print("silent")
        assert p.read_text() == "silent\n"
        assert capsys.readouterr().out == ""


def _bad_history():
    """write 1 acknowledged, then a read of 2: non-linearizable."""
    ops = [h.invoke_op(0, "write", 1), h.ok_op(0, "write", 1),
           h.invoke_op(1, "read", None), h.ok_op(1, "read", 2)]
    return h.index(ops)


class TestLinReportSvg:
    def test_render_invalid(self, tmp_path):
        hist = _bad_history()
        a = analysis(models.cas_register(), hist, algorithm="cpu")
        assert a["valid?"] is False
        path = tmp_path / "linear.svg"
        svg = lin_report.render_analysis(hist, a, path)
        text = path.read_text()
        assert text == svg
        assert text.startswith("<svg")
        assert "Non-linearizable" in text
        assert "read 2" in text
        assert "process 0" in text and "process 1" in text

    def test_concurrent_ops_overlap(self, tmp_path):
        """Bars of genuinely concurrent ops share columns — the overlap is
        the point of the counterexample rendering."""
        import re

        hist = h.index([h.invoke_op(0, "write", 1),
                        h.invoke_op(1, "read", None),
                        h.ok_op(0, "write", 1),
                        h.ok_op(1, "read", 2)])
        a = analysis(models.cas_register(), hist, algorithm="cpu")
        svg = lin_report.render_analysis(hist, a, tmp_path / "l.svg")
        rects = [(float(m.group(1)), float(m.group(2)))
                 for m in re.finditer(
                     r'<rect x="(\d+)" y="\d+" width="(\d+)"', svg)]
        assert len(rects) == 2
        (x0, w0), (x1, w1) = sorted(rects)
        assert x0 + w0 > x1, "concurrent bars should overlap horizontally"

    def test_render_handles_empty_analysis(self, tmp_path):
        hist = _bad_history()
        path = tmp_path / "linear.svg"
        svg = lin_report.render_analysis(hist, {}, path)
        assert svg.startswith("<svg")

    def test_checker_writes_svg(self, tmp_path):
        """checker.linearizable renders linear.svg on invalid histories
        (checker.clj:96-103)."""
        from jepsen_tpu import checker as ck

        test = {"name": "svg-test", "store-base": str(tmp_path),
                "start-time": __import__("datetime").datetime(2026, 1, 1)}
        r = ck.check_safe(ck.linearizable("cpu"), test,
                          models.cas_register(), _bad_history())
        assert r["valid?"] is False
        svgs = list(tmp_path.rglob("linear.svg"))
        assert len(svgs) == 1
        assert "Non-linearizable" in svgs[0].read_text()


class TestRepl:
    def test_last_test_empty(self, tmp_path):
        assert repl.last_test(base=tmp_path) is None

    def test_last_test_roundtrip(self, tmp_path):
        import datetime

        test = {"name": "repl-test", "store-base": str(tmp_path),
                "start-time": datetime.datetime(2026, 1, 2),
                "history": _bad_history()}
        store.save_1(test)
        loaded = repl.last_test(base=tmp_path)
        assert loaded is not None
        assert len(loaded["history"]) == 4
        r = repl.recheck(loaded, model=models.cas_register(),
                         algorithm="cpu")
        assert r["valid?"] is False

    def test_recheck_requires_model(self, tmp_path):
        import pytest

        with pytest.raises(ValueError, match="model"):
            repl.recheck({"history": []})


class TestSmartOS:
    def test_setup_commands(self):
        """SmartOS setup drives pkgin over the dummy transport."""
        from jepsen_tpu import control, os_smartos

        test = {"transport": "dummy", "nodes": ["n1"]}
        sess = control.session(test, "n1")
        with control.with_session(sess):
            os_smartos.os.setup(test, "n1")
        cmds = [cmd for _, cmd in sess.log]
        assert any("pkgin" in c and "install" in c for c in cmds)
        assert any("hostname" in c for c in cmds)
        os_smartos.os.teardown(test, "n1")
