"""Wave-aware host-row executor parity (round-7 tentpole): the sticky
cap escalation and the K-row fused wave batches are OPTIMIZATIONS over
the proven round-6 per-row cold ladder — they must change dispatch
counts, never verdicts.

Two shapes split the coverage by cost: the window-34 pair-key
crash-dom WITNESS shape (the scaled-down literal config-5 class; the
5k/window-25 shapes do not exercise these paths at all — CLAUDE.md
round-5 lore, cap shapes matching tests/test_lin_crashdom_witness.py
so the marginal XLA compile cost is just the K-row wave programs)
carries the verdict/death-row parity tests, and the cheap single-key
crash-dom band (tiny windows, second-scale programs) carries the
mechanics: forced-overflow per-row resume, dispatch-per-row
amortization, and the sticky/waste counters."""

import pytest

from jepsen_tpu import models as m
from jepsen_tpu.lin import bfs, prepare, synth

# Only the second-scale small-band tests ride the quick tier
# (CLAUDE.md bills it as the ~1 min no-compile tier); the pair-band
# witness parity test compiles the K-row program at the big caps and
# runs in the default (not-slow) tier instead. The small-band tests
# still compile tiny cached programs on a cold cache, hence the
# `compiles` exemption from the conftest no-compile enforcement.
quick = pytest.mark.quick
pytestmark = pytest.mark.compiles


@pytest.fixture(scope="module")
def pair_band_packed():
    # The corrupted window-34 partition shape of the crashdom witness
    # suite (identical params — shared compiled shapes).
    h = synth.generate_partitioned_register_history(
        140, concurrency=40, seed=0, partition_every=60,
        partition_len=20, max_crashes=10)
    return prepare.prepare(m.cas_register(),
                           synth.corrupt_history(h, seed=3))


@pytest.fixture(scope="module")
def small_band_packed():
    # Single-key crash-dom band: same host-row executor, second-scale
    # programs (window ~15), linearizable by construction.
    h = synth.generate_register_history(60, concurrency=6, seed=1,
                                        crash_prob=0.25)
    return prepare.prepare(m.cas_register(), h)


def _run(monkeypatch, p, *, sticky, k, cap_schedule, host_caps, **kw):
    monkeypatch.setenv("JEPSEN_TPU_HOST_STICKY", str(sticky))
    monkeypatch.setenv("JEPSEN_TPU_HOST_ROWS_K", str(k))
    # These tests cover the WAVE axes specifically; the episode
    # scheduler (default on, its own coverage in test_lin_sched.py)
    # would otherwise absorb every row before the wave path runs.
    monkeypatch.setenv("JEPSEN_TPU_HOST_SCHED", "0")
    return bfs.check_packed(p, cap_schedule=cap_schedule,
                            host_caps=host_caps, **kw)


def _run_pair(monkeypatch, p, *, sticky, k, **kw):
    return _run(monkeypatch, p, sticky=sticky, k=k, cap_schedule=(8,),
                host_caps=(64, 4096), **kw)


def _run_small(monkeypatch, p, *, sticky, k, host_caps=(8, 64, 512)):
    return _run(monkeypatch, p, sticky=sticky, k=k, cap_schedule=(1,),
                host_caps=host_caps)


def test_wave_modes_match_cold_ladder_on_witness(monkeypatch,
                                                 pair_band_packed):
    p = pair_band_packed
    # The shape must land in the pair-key crash-dom band, or the wave
    # machinery is not what decides here.
    assert p.window + max(len(p.unintern), 2).bit_length() > 31
    assert len(p.crashed_ops) > 0

    cold = _run_pair(monkeypatch, p, sticky=0, k=1, explain=True)
    assert cold["valid?"] is False and cold["final-paths"]

    for sticky, k in ((1, 1), (1, 4)):
        got = _run_pair(monkeypatch, p, sticky=sticky, k=k,
                        explain=True)
        assert got["valid?"] is False
        assert got["op"] == cold["op"]
        assert got["dead-row"] == cold["dead-row"]
        # Witness validity (full model replay) is covered in
        # test_lin_crashdom_witness, which runs the default wave
        # config; here the paths must exist and name the same op.
        assert got["final-paths"]
        assert got["host-stats"]["rows"] >= 1


@quick
def test_forced_overflow_resumes_per_row(monkeypatch,
                                         small_band_packed):
    # A tiny first host cap makes wave batches trip on overflow; the
    # executor must resume PER-ROW from the batch entry (the proven
    # round-6 shape, escalation included) — same verdict as the cold
    # ladder, with the discarded batch work visible in the stats.
    p = small_band_packed
    cold = _run_small(monkeypatch, p, sticky=0, k=1)
    assert cold["valid?"] is True

    got = _run_small(monkeypatch, p, sticky=1, k=4)
    assert got["valid?"] is True
    s = got["host-stats"]
    assert s["multi_trips"] >= 1, \
        "caps this tiny must trip at least one wave batch"
    # Tripped batches are discarded work: the waste observability must
    # record them (acceptance: wasted passes read off the artifact).
    assert s["wasted_passes"] >= 1
    # ``rows`` counts both wave-committed and per-row rows; a trip
    # implies per-row activity beyond the committed batches.
    assert s["rows"] > s["multi_rows"]


@quick
def test_wave_batches_cut_dispatches_per_row(monkeypatch,
                                             small_band_packed):
    # With a comfortable single cap (no escalation anywhere) the wave
    # fast path must commit batches: strictly fewer closure dispatches
    # than host rows — the <1 dispatch/row acceptance criterion.
    p = small_band_packed
    got = _run_small(monkeypatch, p, sticky=1, k=4, host_caps=(512,))
    assert got["valid?"] is True
    s = got["host-stats"]
    assert s["multi_rows"] > 0 and s["multi_trips"] == 0
    assert s["dispatches"] < s["rows"], (
        f"wave batches must amortize dispatches: {s}")


@quick
def test_sticky_cap_counters_and_no_extra_waste(monkeypatch,
                                                small_band_packed):
    # Sticky caps change STARTING levels only: verdict parity with the
    # cold ladder, at least one sticky hit on this escalating shape,
    # and never MORE wasted escalation passes than the cold ladder
    # (K=1 on both sides isolates the sticky axis).
    p = small_band_packed
    cold = _run_small(monkeypatch, p, sticky=0, k=1)
    on = _run_small(monkeypatch, p, sticky=1, k=1)
    assert on["valid?"] is cold["valid?"] is True
    assert on["host-stats"]["sticky_hits"] >= 1
    assert on["host-stats"]["wasted_passes"] <= \
        cold["host-stats"]["wasted_passes"]
    # Per-cap wall seconds flow into the verdict for both runs (the
    # residual-cost-profile observability the bench artifact surfaces).
    assert on["host-stats"]["cap_seconds"]
    assert all(v >= 0 for v in on["host-stats"]["cap_seconds"].values())
