"""Hypercube-sharded dense engine parity tests on the 8-device CPU mesh.

The CPU JIT checker is the oracle. The headline case the sparse sharded
path could never run — a 10k-op history with accumulated crashed ops —
must agree with the oracle across mesh shapes and chunk boundaries.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from jepsen_tpu import models as m
from jepsen_tpu.lin import cpu, prepare, sharded, sharded_dense, synth


def mesh_of(n):
    return Mesh(np.array(jax.devices()[:n]), ("d",))


def both(model, history, n_dev=8, chunk=sharded_dense.CHUNK):
    p = prepare.prepare(model, history)
    want = cpu.check_packed(p)["valid?"]
    r = sharded_dense.check_packed(p, mesh=mesh_of(n_dev), chunk=chunk)
    assert r["valid?"] == want, f"sharded-dense={r} cpu={want}"
    return r


@pytest.mark.parametrize("n_dev", [2, 4, 8])
@pytest.mark.parametrize("seed", range(4))
def test_register_parity_valid(n_dev, seed):
    h = synth.generate_register_history(60, concurrency=4, seed=seed,
                                        value_range=3, crash_prob=0.1)
    assert both(m.cas_register(), h, n_dev=n_dev)["valid?"] is True


@pytest.mark.parametrize("seed", range(6))
def test_register_parity_corrupted(seed):
    h = synth.generate_register_history(60, concurrency=4, seed=seed,
                                        value_range=3, crash_prob=0.1)
    both(m.cas_register(), synth.corrupt_history(h, seed=seed))


@pytest.mark.parametrize("seed", range(4))
def test_mutex_parity(seed):
    h = synth.generate_mutex_history(40, concurrency=4, seed=seed,
                                     crash_prob=0.1)
    assert both(m.mutex(), h)["valid?"] is True


def test_chunk_boundary_carry():
    h = synth.generate_register_history(150, concurrency=4, seed=7,
                                        crash_prob=0.1)
    assert both(m.cas_register(), h, chunk=16)["valid?"] is True
    both(m.cas_register(), synth.corrupt_history(h, seed=7), chunk=16)


def test_10k_crashed_history_parity():
    # VERDICT round-1 criterion: a >=10k-op crashed-op history checked on
    # the multi-device mesh agrees with the oracle. (The sparse sharded
    # path could not run this class at all.)
    h = synth.generate_register_history(10_000, concurrency=5, seed=42,
                                        value_range=4, crash_prob=0.002,
                                        max_crashes=8)
    p = prepare.prepare(m.cas_register(), h)
    assert p.window > 5
    r = sharded_dense.check_packed(p, mesh=mesh_of(8))
    assert r["valid?"] is True
    assert r["analyzer"] == "tpu-dense-sharded"
    assert r["n-devices"] == 8


def test_invalid_reports_op_and_row():
    from jepsen_tpu.history import History, invoke_op, ok_op

    h = History.of(invoke_op(0, "write", 1), ok_op(0, "write", 1),
                   invoke_op(0, "read", None), ok_op(0, "read", 0))
    p = prepare.prepare(m.cas_register(), h)
    r = sharded_dense.check_packed(p, mesh=mesh_of(8))
    assert r["valid?"] is False
    assert r["op"]["f"] == "read" and r["op"]["value"] == 0


def test_sharded_router_prefers_dense():
    h = synth.generate_register_history(60, concurrency=4, seed=3,
                                        crash_prob=0.1)
    p = prepare.prepare(m.cas_register(), h)
    r = sharded.check_packed(p, mesh=mesh_of(8))
    assert r["analyzer"] == "tpu-dense-sharded"
    assert r["valid?"] is True


def test_non_power_of_two_mesh_falls_back():
    h = synth.generate_register_history(30, concurrency=3, seed=1)
    p = prepare.prepare(m.cas_register(), h)
    assert sharded_dense.plan(p, 3) is None
    r = sharded.check_packed(p, mesh=mesh_of(3))
    assert r["valid?"] is True
    assert r["analyzer"] == "tpu-bfs-sharded"


def test_window_narrower_than_device_axis_widens():
    # 8 devices need w >= k+2 = 5; a 2-wide window must still shard.
    h = synth.generate_register_history(24, concurrency=2, seed=2)
    p = prepare.prepare(m.cas_register(), h)
    assert p.window <= 3
    r = sharded_dense.check_packed(p, mesh=mesh_of(8))
    assert r["valid?"] is True
