"""Counterexample reconstruction: device-decided violations must carry
knossos-style configs + final-paths (checker.clj:96-107), built by
replaying the failing tail on the CPU oracle from the dense engine's
chunk-entry bitmap snapshots."""

from jepsen_tpu import models as m
from jepsen_tpu.history import History, info_op, invoke_op, ok_op
from jepsen_tpu.lin import cpu, dense, prepare, synth


def _bad_history(n=200, seed=5):
    h = synth.generate_register_history(n, concurrency=4, seed=seed,
                                        value_range=3, crash_prob=0.05,
                                        max_crashes=6)
    return synth.corrupt_history(h, seed=seed)


def _find_invalid(seeds=range(20)):
    for s in seeds:
        h = _bad_history(seed=s)
        p = prepare.prepare(m.cas_register(), h)
        if cpu.check_packed(p)["valid?"] is False:
            return p
    raise RuntimeError("no invalid corrupted history found")


def test_dense_explain_produces_paths():
    p = _find_invalid()
    r = dense.check_packed(p, chunk=32, explain=True)
    assert r["valid?"] is False
    assert r["final-paths"], "device violation must carry final-paths"
    assert r["configs"], "device violation must carry configs"
    fp = r["final-paths"][0]
    assert "model" in fp and isinstance(fp["path"], list)
    # every path op must reference a real op of the history
    idxs = {o.op_index for o in p.ops}
    for path in r["final-paths"]:
        for o in path["path"]:
            assert o["index"] in idxs


def test_dense_explain_agrees_with_cpu_dead_row():
    p = _find_invalid()
    r = dense.check_packed(p, chunk=32, explain=True)
    rc = cpu.check_packed(p, witness=True)
    assert rc["valid?"] is False
    assert r["op"]["index"] == rc["op"]["index"]
    assert rc["final-paths"], "cpu violation must carry final-paths too"


def test_explain_off_keeps_empty_paths():
    p = _find_invalid()
    r = dense.check_packed(p, chunk=32)
    assert r["valid?"] is False
    assert r["final-paths"] == []


def test_cpu_witness_path_replays_to_failure():
    # The witness path from a dying config must be a legal linearization
    # prefix under the model (replayed through the python step twin).
    from jepsen_tpu.lin.prepare import py_step_fn
    from jepsen_tpu.models.kernels import F_IDS, NIL

    p = _find_invalid()
    r = cpu.check_packed(p, witness=True)
    path = r["final-paths"][0]["path"]
    step = py_step_fn(p.kernel.name)
    st = tuple(int(x) for x in p.init_state)
    by_index = {o.op_index: o for o in p.ops}
    for od in path:
        o = by_index[od["index"]]
        f_id = F_IDS[o.f]
        if o.f == "cas":
            v = (p.intern.get(o.value[0], int(NIL)),
                 p.intern.get(o.value[1], int(NIL)))
        else:
            v = (int(NIL) if o.value is None
                 else p.intern.get(o.value, int(NIL)), int(NIL))
        ok, st = step(st, f_id, v)
        assert ok, f"witness path op {od} illegal at state {st}"


def test_svg_renders_path(tmp_path):
    from jepsen_tpu.lin import report

    h = History.of(
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(1, "write", 2), info_op(1, "write", 2),
        invoke_op(2, "read", None), ok_op(2, "read", 2),
        invoke_op(2, "read", None), ok_op(2, "read", 999))
    p = prepare.prepare(m.cas_register(), h)
    r = dense.check_packed(p, explain=True)
    assert r["valid?"] is False
    svg = report.render_analysis(list(h), r, tmp_path / "linear.svg")
    assert "path:" in svg           # the linearization path footer
    assert "circle" in svg          # numbered path badges on op bars
    assert "Non-linearizable" in svg


def test_checker_defaults_paths_on(tmp_path):
    from jepsen_tpu import checker as c

    h = History.of(
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read", None), ok_op(0, "read", 0))
    for algo in ("cpu", "tpu", "competition"):
        r = c.linearizable(algo).check(None, m.cas_register(), h, {})
        assert r["valid?"] is False
        assert r["final-paths"], f"algorithm {algo} lost final-paths"


def test_sparse_engine_explain_produces_paths():
    # Wide-window violations (sparse engine) must carry final-paths too:
    # the 40-slot cas-chain with a read the chain can't explain.
    from jepsen_tpu.lin import bfs

    h = [invoke_op(0, "write", 0), ok_op(0, "write", 0)]
    for i in range(40):
        h.append(invoke_op(i + 1, "cas", [i, i + 1]))
    for i in range(40):
        h.append(ok_op(i + 1, "cas", [i, i + 1]))
    h += [invoke_op(0, "read", None), ok_op(0, "read", 999)]
    p = prepare.prepare(m.cas_register(), History.of(*h))
    assert p.window == 40
    r = bfs.check_packed(p, explain=True)
    assert r["valid?"] is False
    assert r["analyzer"] == "tpu-bfs"
    assert r["final-paths"], "sparse violation must carry final-paths"
    assert r["configs"]
