"""Cross-run perf ledger + regression sentinel (jepsen_tpu/obs/ledger
— doc/observability.md § Perf ledger): append/torn-tail/index units,
every gate rule firing (and a healthy history passing), the cli
report/diff/gate drives, the /perf page render, the bench-artifact
passthrough (every probe rung writes exactly ONE record, and a ledger
write failure can never cost a probe result), and the trace-spill
rotation satellite (JEPSEN_TPU_TRACE_MAX_MB).

Pure host Python — quick tier, no XLA. The bench passthrough tests
load bench.py the way test_bench_artifact does and stub its PROBES
table, so no device is touched.
"""

import importlib.util
import json
import os
import sys

import pytest

from jepsen_tpu import cli, web
from jepsen_tpu.obs import ledger, trace

pytestmark = pytest.mark.quick

_BENCH_PATH = os.path.join(os.path.dirname(__file__), os.pardir,
                           "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_under_perf",
                                                  _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _ledger_sandbox(monkeypatch, tmp_path):
    """Every test writes its own ledger file — the shared
    .jax_cache/perf_ledger.jsonl must never see fabricated evidence
    (the perf-smoke throwaway precedent)."""
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER",
                       str(tmp_path / "ledger.jsonl"))
    monkeypatch.delenv("JEPSEN_TPU_PERF_GATE_FRAC", raising=False)
    monkeypatch.delenv("JEPSEN_TPU_PERF_TAG", raising=False)
    yield


def _fill(path, probe="p", n=3, wall=1.0, verdict=True, **kw):
    for _ in range(n):
        assert ledger.record(probe, path=str(path), wall_s=wall,
                             verdict=verdict, **kw) is not None


# --- append / load / index --------------------------------------------------


def test_append_stamps_git_platform_env_fingerprint(tmp_path):
    p = tmp_path / "l.jsonl"
    rec = ledger.record("probe-a", path=str(p), wall_s=1.5,
                        verdict=True)
    assert rec is not None
    (got,) = ledger.load(str(p))
    # The three stamps the acceptance criteria name: git sha, env-knob
    # fingerprint, platform.
    assert got["git"] and len(got["git"]) == 12
    assert got["env_fp"] and got["env"], "env fingerprint missing"
    assert any(k.startswith("JEPSEN_TPU_") for k in got["env"])
    assert got["platform"]
    assert got["wall_s"] == 1.5 and got["verdict"] is True


def test_torn_tail_costs_one_record_and_heals(tmp_path):
    p = tmp_path / "l.jsonl"
    _fill(p, n=2)
    # A SIGKILL-torn tail: unparseable, unterminated.
    with open(p, "a") as fh:
        fh.write('{"probe": "torn", "wall_s"')
    assert len(ledger.load(str(p))) == 2
    # The next append newline-heals the tail instead of gluing onto it
    # (the service-journal lesson).
    ledger.record("p", path=str(p), wall_s=1.0, verdict=True)
    recs = ledger.load(str(p))
    assert len(recs) == 3
    assert all(r["probe"] == "p" for r in recs)


def test_index_summarizes_per_probe(tmp_path):
    p = tmp_path / "l.jsonl"
    _fill(p, probe="a", n=2, wall=2.0)
    _fill(p, probe="b", n=1, wall=9.0, verdict=False)
    idx = json.loads((tmp_path / "l.jsonl.index.json").read_text())
    assert idx["records"] == 3
    assert idx["probes"]["a"]["n"] == 2
    assert idx["probes"]["b"]["last_verdict"] is False
    assert idx["probes"]["b"]["last_wall_s"] == 9.0


def test_record_never_raises_and_disabled_is_none(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER", "0")
    assert ledger.ledger_path() is None
    assert ledger.record("p", wall_s=1.0, verdict=True) is None
    # An unwritable path: record() swallows (the loss-proof contract);
    # append() raises (unit-testable failure channel).
    assert ledger.record("p", path="/dev/null/nope/l.jsonl",
                         wall_s=1.0, verdict=True) is None
    with pytest.raises(OSError):
        ledger.append({"probe": "p"}, path="/dev/null/nope/l.jsonl")


def test_host_stats_derivatives_lift_to_top_level(tmp_path):
    p = tmp_path / "l.jsonl"
    ledger.record("p", path=str(p), wall_s=1.0, verdict=True,
                  host_stats={"dispatches": 90, "episodes": 30,
                              "wasted_seconds": {"4096": 1.25,
                                                 "8192": 0.25}})
    (r,) = ledger.load(str(p))
    assert r["dispatches"] == 90 and r["episodes"] == 30
    assert r["dispatches_per_episode"] == 3.0
    assert r["wasted_seconds"] == 1.5


# --- gate rules -------------------------------------------------------------


def test_gate_passes_healthy_history(tmp_path):
    p = tmp_path / "l.jsonl"
    for w in (1.0, 1.1, 0.9, 1.05):
        ledger.record("p", path=str(p), wall_s=w, verdict=True)
    assert ledger.gate(ledger.load(str(p))) == []


def test_gate_verdict_flip_fires(tmp_path):
    p = tmp_path / "l.jsonl"
    _fill(p, n=2, verdict=True)
    ledger.record("p", path=str(p), wall_s=1.0, verdict=False)
    rules = [f["rule"] for f in ledger.gate(ledger.load(str(p)))]
    assert rules == ["verdict-flip"]


def test_gate_ok_to_error_is_a_flip(tmp_path):
    # A probe that used to decide and now errors REGRESSED — verdict
    # None counts as changed, not as gate-invisible.
    p = tmp_path / "l.jsonl"
    _fill(p, n=2, verdict=True)
    ledger.record("p", path=str(p), verdict=None, error="kernel fault")
    findings = ledger.gate(ledger.load(str(p)))
    assert [f["rule"] for f in findings] == ["verdict-flip"]
    assert "kernel fault" in findings[0]["detail"]


def test_gate_error_appeared_fires_on_same_verdict(tmp_path):
    # The bench headline's crash-free FALLBACK records verdict True
    # plus the crashed-op failure: same verdict as the healthy tail,
    # degraded run — the sentinel must still fail.
    p = tmp_path / "l.jsonl"
    _fill(p, n=2, verdict=True)
    ledger.record("p", path=str(p), wall_s=1.0, verdict=True,
                  error="crashed-op run failed: kernel fault")
    findings = ledger.gate(ledger.load(str(p)))
    assert [f["rule"] for f in findings] == ["error-appeared"]
    assert "kernel fault" in findings[0]["detail"]
    # The gate is LEVEL-triggered on errors: a second identical
    # failure stays red (a persistently broken probe must not read
    # as PASS after its first trip), and so does every one after.
    for _ in range(2):
        ledger.record("p", path=str(p), wall_s=1.0, verdict=True,
                      error="crashed-op run failed: kernel fault")
        assert [f["rule"] for f in
                ledger.gate(ledger.load(str(p)))] == \
            ["still-erroring"]


def test_git_sha_resolves_linked_worktrees(tmp_path):
    # A linked worktree's .git is a `gitdir: ...` FILE; refs live
    # under the shared commondir. git=None there would strip every
    # record of its which-commit forensics.
    main_git = tmp_path / "main" / ".git"
    (main_git / "refs" / "heads").mkdir(parents=True)
    (main_git / "refs" / "heads" / "work").write_text("a" * 40 + "\n")
    wt_git = main_git / "worktrees" / "wt"
    wt_git.mkdir(parents=True)
    (wt_git / "HEAD").write_text("ref: refs/heads/work\n")
    (wt_git / "commondir").write_text("../..\n")
    wt = tmp_path / "wt"
    wt.mkdir()
    (wt / ".git").write_text(f"gitdir: {wt_git}\n")
    assert ledger._git_sha(str(wt)) == "a" * 12
    # The plain-directory layout still resolves (this checkout).
    (main_git / "HEAD").write_text("ref: refs/heads/work\n")
    assert ledger._git_sha(str(tmp_path / "main")) == "a" * 12
    # No git state at all: None, never a raise.
    assert ledger._git_sha(str(tmp_path / "wt2")) is None


def test_bench_fallback_headline_stamps_error(bench, monkeypatch,
                                              tmp_path):
    # The fallback record must carry the crashed-op error so
    # error-appeared can fire against a healthy history.
    p = tmp_path / "l.jsonl"
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER", str(p))
    ledger.record("headline", path=str(p), wall_s=1.0, verdict=True)
    bench._ledger_headline(
        {"check_seconds": 1.0, "verdict": True,
         "variant": "crash-free fallback"}, 100000.0,
        error="crashed-op run failed: boom")
    rec = ledger.load(str(p))[-1]
    assert rec["probe"] == "headline" and rec["verdict"] is True
    assert "boom" in rec["error"]
    assert rec["variant"] == "crash-free fallback"
    assert [f["rule"] for f in ledger.gate(ledger.load(str(p)))] == \
        ["error-appeared"]


def test_gate_recovery_after_error_is_not_a_flip(tmp_path):
    # True -> errored(None) -> True again: the errored run already
    # failed its own gate; the healthy recovery re-establishing the
    # clean baseline must not fail CI a second time.
    p = tmp_path / "l.jsonl"
    _fill(p, n=2, verdict=True)
    ledger.record("p", path=str(p), verdict=None, error="wedge")
    assert [f["rule"] for f in ledger.gate(ledger.load(str(p)))] == \
        ["verdict-flip"]
    ledger.record("p", path=str(p), wall_s=1.0, verdict=True)
    assert ledger.gate(ledger.load(str(p))) == []
    # But a DEGRADED recovery (clean run, different verdict than the
    # pre-error baseline) is still a flip.
    ledger.record("p", path=str(p), verdict=None, error="wedge")
    ledger.record("p", path=str(p), wall_s=1.0, verdict=False)
    assert [f["rule"] for f in ledger.gate(ledger.load(str(p)))] == \
        ["verdict-flip"]


def test_gate_still_flipped_stays_red_until_recovery(tmp_path):
    # The clean twin of still-erroring: a persistent verdict
    # regression (True baseline -> False forever) must stay red on
    # every run, not just the first flip.
    p = tmp_path / "l.jsonl"
    _fill(p, n=3, verdict=True)
    ledger.record("p", path=str(p), wall_s=1.0, verdict=False)
    assert [f["rule"] for f in ledger.gate(ledger.load(str(p)))] == \
        ["verdict-flip"]
    for _ in range(2):
        ledger.record("p", path=str(p), wall_s=1.0, verdict=False)
        assert [f["rule"] for f in
                ledger.gate(ledger.load(str(p)))] == ["still-flipped"]
    # Recovery goes fully green: a clean flip back TO True (how every
    # smoke records a fix after an errorless False failure) is not a
    # flip — the flip away already fired and still-flipped kept the
    # row red since.
    ledger.record("p", path=str(p), wall_s=1.0, verdict=True)
    assert ledger.gate(ledger.load(str(p))) == []


def test_gate_error_cleared_but_still_flipped_stays_red(tmp_path):
    # True -> False (flip) -> None+error (flip) -> False CLEAN: the
    # recovery carve-out suppresses a flip verdict for returning to
    # the pre-error (flipped) baseline, but the run is still non-True
    # after an established True baseline — still-flipped must fire,
    # not a green pass.
    p = tmp_path / "l.jsonl"
    ledger.record("p", path=str(p), wall_s=1.0, verdict=True)
    ledger.record("p", path=str(p), wall_s=1.0, verdict=False)
    ledger.record("p", path=str(p), verdict=None, error="wedge")
    ledger.record("p", path=str(p), wall_s=1.0, verdict=False)
    assert [f["rule"] for f in ledger.gate(ledger.load(str(p)))] == \
        ["still-flipped"]


def test_gate_never_true_probe_does_not_still_flip(tmp_path):
    # A probe whose verdict was never True has no established good
    # baseline: repeated "unknown" must not hold the gate red.
    p = tmp_path / "l.jsonl"
    _fill(p, n=3, verdict="unknown")
    assert ledger.gate(ledger.load(str(p))) == []


def test_probe_main_quarantine_delta_is_crash_evidence_only(
        bench, monkeypatch, capsys, tmp_path):
    # Single wedges (environmental, sub-streak) and the static gate's
    # predictions must not hard-fail the perf gate as "newly faulted
    # shapes" — only real crash evidence does (the
    # supervise.quarantined() distinction).
    from jepsen_tpu.lin import supervise

    ledger_file = tmp_path / "l.jsonl"
    qfile = tmp_path / "q.json"
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER", str(ledger_file))
    monkeypatch.setenv("JEPSEN_TPU_QUARANTINE", str(qfile))

    def probe():
        # Mid-probe, three quarantine entries appear: a real fault, a
        # single environmental wedge, and a static-gate prediction.
        from jepsen_tpu import util as u

        u.write_json_atomic(str(qfile), {"shapes": {
            "chunk|rows1|cap8|w5|k": {"reason": "fault", "count": 1,
                                      "faulted": True},
            "host-wave|rows4|cap8|w5|k": {"reason": "wedge",
                                          "count": 1, "streak": 1},
            "host-pass|rows1|cap8|w5|k": {"reason": "static",
                                          "count": 1},
        }})
        return {"verdict": True, "seconds": 0.1}

    monkeypatch.setitem(bench.PROBES, "stub", probe)
    with pytest.raises(SystemExit):
        bench._probe_main("stub")
    capsys.readouterr()
    (rec,) = ledger.load(str(ledger_file))
    assert rec["quarantine_new"] == ["chunk|rows1|cap8|w5|k"], \
        "wedge/static entries leaked into the gate's hard-fail rule"
    assert supervise  # imported to assert the policy source exists


def test_gate_first_clean_run_after_errored_start_passes(tmp_path):
    # A NEW tag whose very first ladder attempt faulted and whose
    # second attempt decided: the clean run IS the baseline, not a
    # flip from the faulty attempt.
    p = tmp_path / "l.jsonl"
    ledger.record("new-rung", path=str(p), verdict=None,
                  error="fault")
    ledger.record("new-rung", path=str(p), wall_s=100.0, verdict=True)
    assert ledger.gate(ledger.load(str(p))) == []


def test_gate_wall_regression_fires_and_respects_frac(tmp_path,
                                                      monkeypatch):
    p = tmp_path / "l.jsonl"
    _fill(p, n=3, wall=1.0)
    ledger.record("p", path=str(p), wall_s=1.4, verdict=True)
    # 1.4x the median: under the default 1.5x threshold.
    assert ledger.gate(ledger.load(str(p))) == []
    ledger.record("p", path=str(p), wall_s=2.0, verdict=True)
    rules = [f["rule"] for f in ledger.gate(ledger.load(str(p)))]
    assert rules == ["wall-regression"]
    # The env knob retunes the sentinel (doc/env.md).
    monkeypatch.setenv("JEPSEN_TPU_PERF_GATE_FRAC", "3.0")
    assert ledger.gate(ledger.load(str(p))) == []


def test_gate_wall_needs_trend_history(tmp_path):
    # One prior sample is not a trend on a tunnel with run-to-run
    # variance: the ratio gates need MIN_TREND priors.
    p = tmp_path / "l.jsonl"
    _fill(p, n=1, wall=1.0)
    ledger.record("p", path=str(p), wall_s=100.0, verdict=True)
    assert ledger.gate(ledger.load(str(p))) == []


def test_gate_new_quarantine_fires(tmp_path):
    p = tmp_path / "l.jsonl"
    ledger.record("p", path=str(p), wall_s=1.0, verdict=True,
                  quarantine_new=["host-wave|rows4|cap524288|w49|k"])
    findings = ledger.gate(ledger.load(str(p)))
    assert [f["rule"] for f in findings] == ["new-quarantine"]
    assert "host-wave" in findings[0]["detail"]


def test_gate_dispatch_growth_fires(tmp_path):
    p = tmp_path / "l.jsonl"
    for _ in range(3):
        ledger.record("p", path=str(p), wall_s=1.0, verdict=True,
                      host_stats={"dispatches": 30, "episodes": 30})
    ledger.record("p", path=str(p), wall_s=1.0, verdict=True,
                  host_stats={"dispatches": 300, "episodes": 30})
    rules = [f["rule"] for f in ledger.gate(ledger.load(str(p)))]
    assert rules == ["dispatch-growth"]


def test_resumed_records_are_not_wall_evidence(tmp_path):
    # A checkpoint-resumed run's wall covers only the tail since the
    # checkpoint: it must neither BE judged by the ratio gates nor
    # poison the baseline full runs are judged against.
    p = tmp_path / "l.jsonl"
    _fill(p, n=3, wall=3000.0)
    # Resumed tail (cheap wall): no wall-regression verdict on it...
    ledger.record("p", path=str(p), wall_s=300.0, verdict=True,
                  extra={"resumed_from_row": 90000})
    assert ledger.gate(ledger.load(str(p))) == []
    # ...twice, so the resumed walls could form a fake-cheap median...
    ledger.record("p", path=str(p), wall_s=290.0, verdict=True,
                  extra={"resumed_from_row": 91000})
    # ...and the next healthy FULL run must not false-fail against it.
    ledger.record("p", path=str(p), wall_s=3100.0, verdict=True)
    assert ledger.gate(ledger.load(str(p))) == []
    (row,) = ledger.trend(ledger.load(str(p))).values()
    assert row["median_wall_s"] == 3000.0, \
        "resumed tails leaked into the trend baseline"
    # Verdict rules still apply to resumed runs in full.
    ledger.record("p", path=str(p), wall_s=200.0, verdict=False,
                  extra={"resumed_from_row": 90000})
    assert [f["rule"] for f in ledger.gate(ledger.load(str(p)))] == \
        ["verdict-flip"]


def test_resumed_streak_does_not_evict_the_baseline_window(tmp_path):
    # Filter-then-slice: probe-config5 is resume-heavy, and a streak
    # of >= TRAIL resumed tails inside the trailing window must not
    # make the ratio gates vacuous while valid full-run baselines
    # exist just outside it.
    p = tmp_path / "l.jsonl"
    _fill(p, n=3, wall=1000.0)
    for i in range(ledger.TRAIL + 1):
        ledger.record("p", path=str(p), wall_s=50.0, verdict=True,
                      extra={"resumed_from_row": 1000 * i + 1})
    ledger.record("p", path=str(p), wall_s=2000.0, verdict=True)
    rules = [f["rule"] for f in ledger.gate(ledger.load(str(p)))]
    assert rules == ["wall-regression"], \
        "resumed streak disabled the wall gate"
    (row,) = ledger.trend(ledger.load(str(p))).values()
    assert row["median_wall_s"] == 1000.0


def test_index_is_incremental_and_rebuilds(tmp_path):
    p = tmp_path / "l.jsonl"
    idx_path = tmp_path / "l.jsonl.index.json"
    _fill(p, probe="a", n=2)
    # A deleted/corrupt index rebuilds from the JSONL on next append.
    idx_path.unlink()
    _fill(p, probe="b", n=1)
    idx = json.loads(idx_path.read_text())
    assert idx["records"] == 3 and idx["probes"]["a"]["n"] == 2
    # And the incremental path stays consistent with a full rebuild.
    _fill(p, probe="a", n=1, wall=4.0)
    idx = json.loads(idx_path.read_text())
    assert idx["records"] == 4 and idx["probes"]["a"]["n"] == 3
    assert idx["probes"]["a"]["last_wall_s"] == 4.0


def test_index_self_heals_after_foreign_append(tmp_path):
    # Another producer (or a crash between JSONL write and index
    # write) grows the ledger without updating the index: the stamped
    # byte-size mismatch forces a full rebuild on the next append —
    # the undercount never persists.
    p = tmp_path / "l.jsonl"
    idx_path = tmp_path / "l.jsonl.index.json"
    _fill(p, probe="a", n=2)
    with open(p, "a") as fh:   # bypasses the index entirely
        fh.write('{"probe": "foreign", "wall_s": 1.0}\n')
    _fill(p, probe="a", n=1)
    idx = json.loads(idx_path.read_text())
    assert idx["records"] == 4
    assert idx["probes"]["foreign"]["n"] == 1


def test_cli_diff_unreadable_before_fails_loudly(tmp_path, capsys):
    # exists() is not readability: a directory (or chmod-000 file)
    # must error, not silently diff against an empty snapshot.
    p = tmp_path / "l.jsonl"
    _fill(p, n=2)
    d = tmp_path / "adir"
    d.mkdir()
    assert _cli(["perf", "diff", "--ledger", str(p), "--before",
                 str(d)]) == cli.EXIT_ERROR
    assert "cannot read" in capsys.readouterr().err


def test_errored_walls_are_not_ratio_evidence(tmp_path):
    # A crashed run stops early: its short wall must not become the
    # baseline a recovered full-length run is judged against (the
    # resumed-tail rule, same incomparable-evidence class).
    p = tmp_path / "l.jsonl"
    _fill(p, n=3, wall=1000.0)
    for _ in range(2):
        ledger.record("p", path=str(p), wall_s=60.0, verdict=True,
                      error="crashed early")
    # Recovered full run at a healthy wall: no wall-regression
    # verdict against the 60 s crashed walls.
    ledger.record("p", path=str(p), wall_s=1100.0, verdict=True)
    assert [f["rule"] for f in ledger.gate(ledger.load(str(p)))] == []
    (row,) = ledger.trend(ledger.load(str(p))).values()
    assert row["median_wall_s"] == 1000.0, \
        "crashed walls leaked into the trend baseline"
    # And an errored LAST record is never ratio-judged itself.
    ledger.record("p", path=str(p), wall_s=9000.0, verdict=True,
                  error="crashed late")
    rules = [f["rule"] for f in ledger.gate(ledger.load(str(p)))]
    assert rules == ["error-appeared"]


def test_wide_probes_force_perf_tag_per_child(bench, monkeypatch):
    # An exported JEPSEN_TPU_PERF_TAG (the knob probe-config5 sets)
    # must not collapse every probe's record into one trend row: the
    # generic branch forces tag=key, wave_smoke its own, and the
    # partitioned rungs their per-rung tags.
    monkeypatch.setenv("JEPSEN_TPU_PERF_TAG", "leaked-tag")
    monkeypatch.setattr(bench, "TOTAL_BUDGET_S", 10_000_000)
    seen = []

    def fake_probe(key, timeout, env_extra=None, stall_s=None):
        seen.append((key, dict(env_extra or {})))
        if key == "wave_smoke":
            return {"seconds": 0.1, "host_stats": {"multi_rows": 4},
                    "sched": {"seconds": 0.1,
                              "host_stats": {"sched_rows": 4}}}
        return {"verdict": True, "seconds": 0.1}

    monkeypatch.setattr(bench, "_run_probe", fake_probe)
    detail, out = {}, {"detail": {}}
    bench._wide_probes(detail, out, __import__("time").time())
    tags = {k: e.get("JEPSEN_TPU_PERF_TAG") for k, e in seen}
    for key, tag in tags.items():
        assert tag is not None and tag != "leaked-tag", \
            f"{key} child inherited the exported PERF_TAG"
    assert tags["mutex_c30"] == "mutex_c30"
    assert tags["wave_smoke"] == "wave_smoke"
    assert tags["partitioned_c30"].startswith("partitioned_c30.")


def test_parent_records_for_a_child_that_died_silently(
        bench, monkeypatch, tmp_path):
    # A killed/stalled/crashed child never reaches its own record()
    # (it sits just before the result print): the parent must record
    # the error on its behalf, or a persistently wedging probe reads
    # green to `perf gate` forever.
    p = tmp_path / "l.jsonl"
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER", str(p))

    def fake_sub(key, timeout, env_extra=None, stall_s=None,
                 argv=None):
        return ({"error": "probe stalled: no progress for 2s, killed",
                 "kill": {"why": "stall"},
                 "no_child_result": True}, "stall")

    monkeypatch.setattr(bench, "_run_probe_subprocess", fake_sub)
    r = bench._run_probe("partitioned_c30", 60,
                         env_extra={"JEPSEN_TPU_PERF_TAG":
                                    "partitioned_c30.sched",
                                    "JEPSEN_TPU_HOST_SCHED": "1"})
    assert "error" in r
    (rec,) = ledger.load(str(p))
    assert rec["probe"] == "partitioned_c30.sched"
    assert rec["verdict"] is None
    assert "stalled" in rec["error"]
    assert rec["recorded_by"] == "parent"
    # The record carries the RUNG's forced config, not the parent's
    # environment (the env/env_fp schema promise).
    assert rec["env"]["JEPSEN_TPU_HOST_SCHED"] == "1"
    # A child that PRINTED its result records itself — no parent
    # double-record.
    monkeypatch.setattr(
        bench, "_run_probe_subprocess",
        lambda *a, **k: ({"verdict": True, "seconds": 0.1}, None))
    bench._run_probe("mutex_c30", 60)
    assert len(ledger.load(str(p))) == 1


def test_probe_main_stamps_resumed_from_row(bench, monkeypatch,
                                            capsys, tmp_path):
    p = tmp_path / "l.jsonl"
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER", str(p))
    _drive_probe_main(
        bench, monkeypatch, capsys,
        result={"verdict": True, "seconds": 12.0,
                "resumed_from_row": 88000})
    (rec,) = ledger.load(str(p))
    assert rec["resumed_from_row"] == 88000


def test_gate_groups_by_probe_and_platform(tmp_path):
    # probe b's flip must not hide behind probe a's healthy tail, and
    # --probe filters to one row.
    p = tmp_path / "l.jsonl"
    _fill(p, probe="a", n=3)
    _fill(p, probe="b", n=2, verdict=True)
    ledger.record("b", path=str(p), wall_s=1.0, verdict=False)
    findings = ledger.gate(ledger.load(str(p)))
    assert [(f["probe"], f["rule"]) for f in findings] == \
        [("b", "verdict-flip")]
    assert ledger.gate(ledger.load(str(p)), probe="a") == []


# --- trend / diff -----------------------------------------------------------


def test_trend_rows_and_render(tmp_path):
    p = tmp_path / "l.jsonl"
    for w in (1.0, 2.0, 3.0):
        ledger.record("p", path=str(p), wall_s=w, verdict=True,
                      host_stats={"dispatches": 8, "episodes": 4})
    rows = ledger.trend(ledger.load(str(p)))
    (row,) = rows.values()
    # Median over PRIOR records only — the gate's window, so the
    # report's ratio never dilutes a regression with the regressing
    # run itself: priors [1, 2] -> median 1.5, last 3.0 -> 2.0x.
    assert row["n"] == 3 and row["median_wall_s"] == 1.5
    assert row["last_wall_s"] == 3.0 and row["wall_vs_median"] == 2.0
    assert row["verdicts"] == "TTT"
    assert row["last_dispatches_per_episode"] == 2.0
    text = ledger.render_trend(rows)
    assert "p" in text and "TTT" in text


def test_trend_first_record_has_no_baseline(tmp_path):
    p = tmp_path / "l.jsonl"
    _fill(p, n=1, wall=7.0)
    (row,) = ledger.trend(ledger.load(str(p))).values()
    assert row["median_wall_s"] is None
    assert "wall_vs_median" not in row
    assert "-" in ledger.render_trend({"k": row})


def test_diff_is_the_appended_suffix(tmp_path):
    p = tmp_path / "l.jsonl"
    _fill(p, n=2)
    before = ledger.load(str(p))
    _fill(p, n=1, wall=5.0)
    new = ledger.diff(before, ledger.load(str(p)))
    assert len(new) == 1 and new[0]["wall_s"] == 5.0
    assert "perf delta: 1 new" in ledger.render_diff(
        new, ledger.trend(ledger.load(str(p))))
    # A current ledger SHORTER than the snapshot (cleared/rotated):
    # report everything current, never a bogus empty delta.
    assert len(ledger.diff(before + before, before)) == len(before)


# --- cli drives -------------------------------------------------------------


def _cli(args):
    return cli.run(cli.standard_commands(["perf"]), args)


def test_cli_report_and_gate(tmp_path, capsys):
    p = tmp_path / "l.jsonl"
    _fill(p, probe="cpu-mesh-check", n=3)
    assert _cli(["perf", "report", "--ledger", str(p)]) == cli.EXIT_OK
    out = capsys.readouterr().out
    assert "cpu-mesh-check" in out
    assert _cli(["perf", "gate", "--ledger", str(p)]) == cli.EXIT_OK
    assert "PASS" in capsys.readouterr().out
    ledger.record("cpu-mesh-check", path=str(p), wall_s=1.0,
                  verdict=False)
    assert _cli(["perf", "gate", "--ledger", str(p)]) == \
        cli.EXIT_INVALID
    out = capsys.readouterr().out
    assert "FAIL" in out and "verdict-flip" in out


def test_cli_gate_json_and_probe_filter(tmp_path, capsys):
    p = tmp_path / "l.jsonl"
    _fill(p, probe="a", n=2)
    ledger.record("a", path=str(p), wall_s=1.0, verdict=False)
    _fill(p, probe="b", n=2)
    assert _cli(["perf", "gate", "--ledger", str(p), "--probe", "b",
                 "--json"]) == cli.EXIT_OK
    assert json.loads(capsys.readouterr().out) == []
    assert _cli(["perf", "gate", "--ledger", str(p), "--probe", "a",
                 "--json"]) == cli.EXIT_INVALID
    (f,) = json.loads(capsys.readouterr().out)
    assert f["rule"] == "verdict-flip"


def test_cli_diff_requires_readable_before(tmp_path, capsys):
    # The quarantine-diff precedent: a missing --before must fail
    # loudly, not report the whole ledger as new.
    p = tmp_path / "l.jsonl"
    _fill(p, n=2)
    assert _cli(["perf", "diff", "--ledger", str(p)]) == \
        cli.EXIT_USAGE
    assert _cli(["perf", "diff", "--ledger", str(p), "--before",
                 str(tmp_path / "missing.jsonl")]) == cli.EXIT_ERROR
    before = tmp_path / "before.jsonl"
    before.write_text((tmp_path / "l.jsonl").read_text())
    _fill(p, n=1, wall=7.0)
    capsys.readouterr()
    assert _cli(["perf", "diff", "--ledger", str(p), "--before",
                 str(before)]) == cli.EXIT_OK
    assert "1 new record" in capsys.readouterr().out


def test_cli_gate_malformed_frac_fails_cleanly(tmp_path, capsys,
                                               monkeypatch):
    # A garbage JEPSEN_TPU_PERF_GATE_FRAC must produce a clean error
    # (the gate's output contract), never a traceback — and never a
    # silent fallback to a threshold the operator did not choose.
    p = tmp_path / "l.jsonl"
    _fill(p, n=2)
    monkeypatch.setenv("JEPSEN_TPU_PERF_GATE_FRAC", "1,5")
    assert _cli(["perf", "gate", "--ledger", str(p)]) == \
        cli.EXIT_ERROR
    assert "JEPSEN_TPU_PERF_GATE_FRAC" in capsys.readouterr().err
    # An explicit --frac overrides the broken env and works.
    assert _cli(["perf", "gate", "--ledger", str(p), "--frac",
                 "1.5"]) == cli.EXIT_OK


def test_cli_report_empty_ledger_errors(tmp_path, capsys):
    assert _cli(["perf", "report", "--ledger",
                 str(tmp_path / "none.jsonl")]) == cli.EXIT_ERROR


def test_cli_gate_empty_or_unmatched_fails_loudly(tmp_path, capsys):
    # A wrong --ledger path or a typo'd --probe tag must NOT keep CI
    # green with nothing under guard.
    assert _cli(["perf", "gate", "--ledger",
                 str(tmp_path / "none.jsonl")]) == cli.EXIT_ERROR
    assert "nothing is under guard" in capsys.readouterr().err
    p = tmp_path / "l.jsonl"
    _fill(p, probe="real-probe", n=2)
    assert _cli(["perf", "gate", "--ledger", str(p), "--probe",
                 "typo-probe"]) == cli.EXIT_ERROR
    assert "typo-probe" in capsys.readouterr().err
    assert _cli(["perf", "gate", "--ledger", str(p), "--probe",
                 "real-probe"]) == cli.EXIT_OK


# --- /perf page -------------------------------------------------------------


def test_perf_page_renders_rows_sparklines_and_chips(tmp_path):
    p = tmp_path / "l.jsonl"
    for w in (1.0, 1.1, 1.2):
        ledger.record("partitioned_c30.sched", path=str(p), wall_s=w,
                      verdict=True,
                      host_stats={"dispatches": 9, "episodes": 9})
    ledger.record("serve-smoke", path=str(p), wall_s=9.0,
                  verdict=False, error="boom")
    html = web.perf_html(str(p))
    assert "perf ledger" in html
    assert "partitioned_c30.sched" in html and "serve-smoke" in html
    assert "<svg" in html, "wall sparkline missing"
    assert 'class="chip"' in html, "verdict chips missing"
    assert "boom" in html


def test_perf_page_empty_ledger_says_so(tmp_path):
    html = web.perf_html(str(tmp_path / "none.jsonl"))
    assert "no perf-ledger records" in html


def test_home_page_links_perf_and_run_artifacts(tmp_path):
    run = tmp_path / "demo" / "20260101T000000.000"
    run.mkdir(parents=True)
    (run / "results.json").write_text('{"valid?": true}')
    (run / "timeline.html").write_text("<html></html>")
    # A composed checker's subdirectory artifact must link too (the
    # same subdirectory-aware lookup the backfill skip rule uses).
    (run / "perf").mkdir()
    (run / "perf" / "rate.png").write_bytes(b"png")
    html = web.home_html(tmp_path)
    assert 'href="/perf"' in html
    assert "timeline.html" in html
    assert "perf/rate.png" in html, \
        "subdirectory evidence missing from the home table"
    d = web.dir_html(tmp_path, "demo/20260101T000000.000")
    assert "evidence:" in d and "timeline.html" in d
    assert "perf/rate.png" in d
    # NON-run directories (the test-name dir holding many runs) must
    # not present some arbitrary run's files as their evidence.
    parent = web.dir_html(tmp_path, "demo")
    assert "evidence:" not in parent


# --- bench passthrough ------------------------------------------------------


def _drive_probe_main(bench, monkeypatch, capsys, key="stub",
                      result=None):
    monkeypatch.setitem(bench.PROBES, key,
                        lambda: dict(result if result is not None
                                     else {"verdict": True,
                                           "seconds": 0.1}))
    with pytest.raises(SystemExit) as e:
        bench._probe_main(key)
    assert e.value.code == 0
    out_lines = [ln for ln in capsys.readouterr().out.splitlines()
                 if ln.lstrip().startswith("{")]
    return json.loads(out_lines[-1])


def test_probe_main_writes_exactly_one_record(bench, monkeypatch,
                                              capsys, tmp_path):
    p = tmp_path / "l.jsonl"
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER", str(p))
    r = _drive_probe_main(
        bench, monkeypatch, capsys,
        result={"verdict": True, "seconds": 0.1,
                "host_stats": {"dispatches": 4, "episodes": 2}})
    assert r["verdict"] is True
    recs = ledger.load(str(p))
    assert len(recs) == 1, "exactly one record per probe rung"
    rec = recs[0]
    assert rec["probe"] == "stub" and rec["kind"] == "bench"
    assert rec["git"] and rec["env_fp"], \
        "acceptance: git sha + env fingerprint on every bench record"
    assert rec["verdict"] is True
    assert rec["dispatches_per_episode"] == 2.0
    assert isinstance(rec["wall_s"], float)


def test_probe_main_perf_tag_names_the_rung(bench, monkeypatch,
                                            capsys, tmp_path):
    p = tmp_path / "l.jsonl"
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER", str(p))
    monkeypatch.setenv("JEPSEN_TPU_PERF_TAG", "partitioned_c30.sched")
    _drive_probe_main(bench, monkeypatch, capsys)
    (rec,) = ledger.load(str(p))
    assert rec["probe"] == "partitioned_c30.sched"


def test_probe_main_forwards_mesh_stats(bench, monkeypatch, capsys,
                                        tmp_path):
    # ISSUE 18 acceptance: the mesh probe's per-device mesh-stats
    # sub-dict rides into the perf-ledger record so `perf report`
    # trends the mesh dispatch wall and shard occupancy.
    p = tmp_path / "l.jsonl"
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER", str(p))
    ms = {"devices": 8, "band": "pair", "crash-dom": True,
          "dispatches": 5, "dispatch-wall-s": 12.3,
          "peak-occupancy": [630, 64, 14, 0, 0, 0, 0, 0]}
    _drive_probe_main(bench, monkeypatch, capsys,
                      result={"verdict": True, "seconds": 0.1,
                              "mesh": ms})
    (rec,) = ledger.load(str(p))
    # make_record flattens extra into the record top level.
    assert rec["mesh"] == ms


def test_probe_main_ledger_failure_cannot_cost_the_result(
        bench, monkeypatch, capsys, tmp_path):
    # The acceptance criterion verbatim: a ledger I/O failure can
    # never cost a probe result. append() blowing up must leave the
    # probe's JSON line on stdout untouched.
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER",
                       str(tmp_path / "l.jsonl"))

    def boom(rec, path=None):
        raise OSError("disk full")

    monkeypatch.setattr("jepsen_tpu.obs.ledger.append", boom)
    r = _drive_probe_main(bench, monkeypatch, capsys)
    assert r == {"verdict": True, "seconds": 0.1}
    assert ledger.load(str(tmp_path / "l.jsonl")) == []


def test_probe_main_failed_probe_records_the_error(bench, monkeypatch,
                                                   capsys, tmp_path):
    p = tmp_path / "l.jsonl"
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER", str(p))
    monkeypatch.setitem(bench.PROBES, "stub",
                        lambda: (_ for _ in ()).throw(
                            RuntimeError("kernel fault")))
    with pytest.raises(SystemExit):
        bench._probe_main("stub")
    (rec,) = ledger.load(str(p))
    assert rec["verdict"] is None and "kernel fault" in rec["error"]


def test_probe_main_ping_is_not_evidence(bench, monkeypatch, capsys,
                                         tmp_path):
    # ping is the worker-recovery helper: recording every recovery
    # check would flood the trend rows with non-evidence.
    p = tmp_path / "l.jsonl"
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER", str(p))
    _drive_probe_main(bench, monkeypatch, capsys, key="ping",
                      result={"ok": True})
    assert ledger.load(str(p)) == []


def test_partitioned_rungs_carry_perf_tags(bench):
    # Every ladder rung forces its own trend-row tag so sched/wave/
    # unfused trajectories never mix (the _rung helper contract).
    src = open(_BENCH_PATH).read()
    assert "JEPSEN_TPU_PERF_TAG" in src
    # And the tag rides the env the same way the other forced knobs do
    # — via the rung env_extra (asserted through the live helper).
    import inspect

    assert "PERF_TAG" in inspect.getsource(bench._wide_probes)


# --- trace rotation (JEPSEN_TPU_TRACE_MAX_MB) -------------------------------


def test_trace_spill_rotates_past_cap(monkeypatch, tmp_path):
    spill = tmp_path / "t.jsonl"
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")
    monkeypatch.setenv("JEPSEN_TPU_TRACE_FILE", str(spill))
    # ~2 KB cap: a few hundred events must rotate at least once.
    monkeypatch.setenv("JEPSEN_TPU_TRACE_MAX_MB", "0.002")
    trace.reset()
    try:
        for i in range(3 * trace._SPILL_BATCH):
            trace.instant("ev", i=i, pad="x" * 40)
        trace.flush()
        assert trace.rotations() >= 1
        assert (tmp_path / "t.jsonl.1").exists(), \
            "rotated generation missing"
        # The live path holds the NEWEST events and still parses —
        # trace report reads it unchanged.
        from jepsen_tpu.obs import report

        live = report.load(str(spill))
        assert live, "live spill empty after rotation"
        assert live[-1]["args"]["i"] == 3 * trace._SPILL_BATCH - 1
        assert len(live) < 3 * trace._SPILL_BATCH, \
            "rotation kept every event in the live file"
    finally:
        trace.reset()


def test_trace_no_rotation_under_cap(monkeypatch, tmp_path):
    spill = tmp_path / "t.jsonl"
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")
    monkeypatch.setenv("JEPSEN_TPU_TRACE_FILE", str(spill))
    monkeypatch.delenv("JEPSEN_TPU_TRACE_MAX_MB", raising=False)
    trace.reset()
    try:
        for i in range(8):
            trace.instant("ev", i=i)
        trace.flush()
        assert trace.rotations() == 0
        assert not (tmp_path / "t.jsonl.1").exists()
    finally:
        trace.reset()


# --- store run-artifact backfill --------------------------------------------


def test_write_run_artifacts_backfills_and_respects_existing(tmp_path):
    from jepsen_tpu import store
    from jepsen_tpu.history import Op

    hist = [Op(process=0, type="invoke", f="read", value=None, time=0),
            Op(process=0, type="ok", f="read", value=1, time=int(5e6))]
    test = {"name": "artifact-demo", "store-base": str(tmp_path),
            "start-time": "t1", "history": hist, "concurrency": 1}
    written = store.write_run_artifacts(test)
    assert "timeline.html" in written
    p = store.path(test, "timeline.html")
    assert p.exists() and "timeline" in p.read_text()
    # Idempotent: existing artifacts are the checker's — left alone.
    assert store.write_run_artifacts(test) == []
    # Including ones a composed checker wrote under a SUBDIRECTORY
    # (the independent-checker opts convention): no second copy at
    # the run root.
    test2 = dict(test, name="artifact-subdir")
    sub = store.path(test2, "perf", "timeline.html", make=True)
    sub.write_text("<html>checker's copy</html>")
    written2 = store.write_run_artifacts(test2)
    assert "timeline.html" not in written2
    assert not store.path(test2, "timeline.html").exists()
    # Unnamed tests persist nothing (the timeline.checker contract).
    assert store.write_run_artifacts({"history": hist}) == []
    # RUN_ARTIFACTS is the ONE list web links from (no drift).
    assert web.RUN_ARTIFACTS is store.RUN_ARTIFACTS
    # The cost guard: giant histories keep the opt-in model (a
    # div-per-op timeline over 100k ops is tens of MB of serial work
    # at run completion).
    big = {"name": "big", "store-base": str(tmp_path),
           "start-time": "t2", "concurrency": 1,
           "history": hist * ((store.ARTIFACT_MAX_OPS // 2) + 1)}
    assert store.write_run_artifacts(big) == []


def test_wide_probes_health_row_flips_on_machinery_crash(
        bench, monkeypatch, tmp_path):
    # A _wide_probes machinery crash must not leave the sentinel
    # green: the sweep records a True health row on every completed
    # run, so the crash's errored row FLIPS it.
    p = tmp_path / "l.jsonl"
    monkeypatch.setenv("JEPSEN_TPU_PERF_LEDGER", str(p))
    bench._ledger_wide(10.0, None)
    assert ledger.gate(ledger.load(str(p))) == []
    bench._ledger_wide(0.1, "ImportError: probe machinery broke")
    rules = [f["rule"] for f in ledger.gate(ledger.load(str(p)))]
    assert rules == ["verdict-flip"]
    (bad,) = [r for r in ledger.load(str(p)) if r.get("error")]
    assert bad["probe"] == "wide-probes" and bad["verdict"] is None


def test_perf_smoke_module_importable():
    # The Makefile target's module exists and exposes main() — the
    # smoke itself runs chip-free under `make perf-smoke` (compiles,
    # so not driven here in the quick tier).
    import importlib

    mod = importlib.import_module("jepsen_tpu.obs.perf_smoke")
    assert callable(mod.main)
