"""Dense bitmap engine parity tests (on the virtual CPU mesh backend).

The CPU JIT checker (brute-force-verified in test_lin_cpu.py) is the
oracle; the dense engine must agree on every history it accepts —
especially crashed-op histories, which are its headline case (the sparse
path's frontier-inflating worst case costs the bitmap nothing).
"""

import numpy as np
import pytest

from jepsen_tpu import models as m
from jepsen_tpu.history import History, info_op, invoke_op, ok_op
from jepsen_tpu.lin import cpu, dense, prepare, synth


def both(model, history, chunk=dense.CHUNK):
    p = prepare.prepare(model, history)
    want = cpu.check_packed(p)["valid?"]
    r = dense.check_packed(p, chunk=chunk)
    assert r["valid?"] == want, f"dense={r} cpu={want}"
    return r["valid?"]


class TestBasics:
    def test_empty(self):
        assert both(m.cas_register(), History.of())

    def test_sequential(self):
        assert both(m.cas_register(), History.of(
            invoke_op(0, "write", 1), ok_op(0, "write", 1),
            invoke_op(0, "read", None), ok_op(0, "read", 1)))

    def test_stale_read_invalid(self):
        p = prepare.prepare(m.cas_register(), History.of(
            invoke_op(0, "write", 1), ok_op(0, "write", 1),
            invoke_op(0, "read", None), ok_op(0, "read", 0)))
        r = dense.check_packed(p)
        assert r["valid?"] is False
        assert r["op"]["f"] == "read" and r["op"]["value"] == 0
        assert r["dead-row"] == 1

    def test_crashed_write_observed(self):
        assert both(m.cas_register(), History.of(
            invoke_op(0, "write", 3), info_op(0, "write", 3),
            invoke_op(1, "read", None), ok_op(1, "read", 3)))

    def test_crashed_write_not_observed(self):
        # crashed op may also never linearize
        assert both(m.cas_register(), History.of(
            invoke_op(0, "write", 7), ok_op(0, "write", 7),
            invoke_op(1, "write", 3), info_op(1, "write", 3),
            invoke_op(2, "read", None), ok_op(2, "read", 7)))

    def test_crashed_cas_chain(self):
        # two crashed ops whose effects must BOTH linearize, in order
        assert both(m.cas_register(), History.of(
            invoke_op(0, "write", 1), ok_op(0, "write", 1),
            invoke_op(1, "write", 2), info_op(1, "write", 2),
            invoke_op(2, "cas", [2, 3]), info_op(2, "cas", [2, 3]),
            invoke_op(3, "read", None), ok_op(3, "read", 3)))

    def test_mutex(self):
        assert not both(m.mutex(), History.of(
            invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
            invoke_op(1, "acquire", None), ok_op(1, "acquire", None)))

    def test_unsupported_model_unknown(self):
        p = prepare.prepare(m.noop, History.of(
            invoke_op(0, "add", 1), ok_op(0, "add", 1)))
        assert dense.check_packed(p)["valid?"] == "unknown"

    def test_wide_window_unknown(self):
        h = synth.generate_register_history(
            80, concurrency=dense.MAX_DENSE_WINDOW + 3, seed=2)
        p = prepare.prepare(m.cas_register(), h)
        if p.window > dense.MAX_DENSE_WINDOW:
            assert dense.check_packed(p)["valid?"] == "unknown"

    def test_plan_buckets(self):
        h = synth.generate_register_history(30, concurrency=5, seed=1,
                                            value_range=3)
        p = prepare.prepare(m.cas_register(), h)
        pl = dense.plan(p)
        assert pl is not None
        w, ns, nil_id, init_id = pl
        assert w >= p.window and w in dense._W_BUCKETS
        assert ns >= nil_id + 1 and ns in dense._NS_BUCKETS
        assert init_id == nil_id  # register starts nil


@pytest.mark.parametrize("seed", range(15))
def test_register_parity_valid(seed):
    h = synth.generate_register_history(40, concurrency=4, seed=seed,
                                        value_range=3, crash_prob=0.15)
    assert both(m.cas_register(), h) is True


@pytest.mark.parametrize("seed", range(15))
def test_register_parity_corrupted(seed):
    h = synth.generate_register_history(40, concurrency=4, seed=seed,
                                        value_range=3, crash_prob=0.1)
    h = synth.corrupt_history(h, seed=seed)
    both(m.cas_register(), h)


@pytest.mark.parametrize("seed", range(10))
def test_mutex_parity(seed):
    h = synth.generate_mutex_history(40, concurrency=4, seed=seed,
                                     crash_prob=0.15)
    assert both(m.mutex(), h) is True


@pytest.mark.parametrize("seed", range(6))
def test_many_crashes_wide_window(seed):
    # The flagship shape: live concurrency + accumulated crashed slots.
    h = synth.generate_register_history(300, concurrency=4, seed=seed,
                                        value_range=4, crash_prob=0.05,
                                        max_crashes=10)
    p = prepare.prepare(m.cas_register(), h)
    assert p.window > 4  # crashes actually widened the window
    assert both(m.cas_register(), h) is True


@pytest.mark.parametrize("seed", range(6))
def test_many_crashes_corrupted(seed):
    h = synth.generate_register_history(300, concurrency=4, seed=seed,
                                        value_range=4, crash_prob=0.05,
                                        max_crashes=10)
    both(m.cas_register(), synth.corrupt_history(h, seed=seed))


def test_chunk_boundary_carry():
    # Tiny chunks force the frontier to carry across many dispatches.
    h = synth.generate_register_history(120, concurrency=4, seed=9,
                                        crash_prob=0.1)
    assert both(m.cas_register(), h, chunk=8) is True
    bad = synth.corrupt_history(h, seed=9)
    both(m.cas_register(), bad, chunk=8)


def test_snapshots_decode_matches_oracle_frontier():
    # The entry-bitmap snapshot at base 0 holds exactly the init config.
    h = synth.generate_register_history(60, concurrency=4, seed=4,
                                        crash_prob=0.1)
    p = prepare.prepare(m.cas_register(), h)
    snaps = []
    dense.check_packed(p, chunk=16, snapshots=snaps)
    assert snaps[0][0] == 0
    w, ns, nil_id, init_id = dense.plan(p)
    cfgs = dense.decode_bitmap(snaps[0][1], nil_id)
    assert cfgs == [(0, (int(np.int32(-(2 ** 31))),))] or \
        cfgs == [(0, (init_id,))]
    assert [b for b, _ in snaps] == list(range(0, p.R, 16))


@pytest.mark.parametrize("seed", range(8))
def test_pallas_backend_parity(seed):
    # The pallas chunk kernel (interpreted off-TPU) must agree with the
    # oracle on valid, corrupted, and crash-heavy histories.
    h = synth.generate_register_history(80, concurrency=4, seed=seed,
                                        value_range=3, crash_prob=0.1,
                                        max_crashes=6)
    if seed % 2:
        h = synth.corrupt_history(h, seed=seed)
    p = prepare.prepare(m.cas_register(), h)
    want = cpu.check_packed(p)["valid?"]
    r = dense.check_packed(p, backend="pallas")
    assert r["valid?"] == want, f"pallas={r} cpu={want}"


def test_pallas_chunk_boundary_and_mutex():
    h = synth.generate_mutex_history(60, concurrency=4, seed=3,
                                     crash_prob=0.1)
    p = prepare.prepare(m.mutex(), h)
    want = cpu.check_packed(p)["valid?"]
    assert dense.check_packed(p, backend="pallas",
                              chunk=16)["valid?"] == want


def test_pallas_dead_row_matches_xla():
    h = synth.corrupt_history(
        synth.generate_register_history(120, concurrency=4, seed=7,
                                        crash_prob=0.1), seed=7)
    p = prepare.prepare(m.cas_register(), h)
    rx = dense.check_packed(p, backend="xla")
    rp = dense.check_packed(p, backend="pallas")
    if rx["valid?"] is False:
        assert rp["valid?"] is False
        assert rp["dead-row"] == rx["dead-row"]
        assert rp["op"] == rx["op"]
