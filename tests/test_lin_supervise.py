"""Supervised checker runtime (lin/supervise.py): the dispatch
watchdog, the fault-shape quarantine ledger, the frontier checkpoint
codec, and their integration into the host-row executor's fallback
ladder.

The unit tests are pure host Python (quick, no XLA); the end-to-end
ladder tests drive the real engine on the small crash-dom band and are
marked ``compiles`` (tiny .jax_cache-resident programs, the
test_lin_hostrow_wave precedent)."""

import json
import os
import threading
import time

import numpy as np
import pytest

from jepsen_tpu.lin import supervise

pytestmark = pytest.mark.quick


@pytest.fixture(autouse=True)
def _clean_injections():
    supervise._injected.clear()
    yield
    supervise._injected.clear()


@pytest.fixture(autouse=True)
def _no_sched(monkeypatch):
    # The e2e ladder tests here inject/quarantine at the per-row sites
    # (host-fixpoint / host-pass / host-wave) — force the episode
    # scheduler off so those sites actually dispatch (the scheduler,
    # default on, would absorb every clean row first; its own ladder
    # coverage lives in tests/test_lin_sched.py).
    monkeypatch.setenv("JEPSEN_TPU_HOST_SCHED", "0")


@pytest.fixture()
def ledger(tmp_path, monkeypatch):
    path = str(tmp_path / "quarantine.json")
    monkeypatch.setenv("JEPSEN_TPU_QUARANTINE", path)
    return path


# --- dispatch watchdog ------------------------------------------------------


def test_call_passes_through_value_and_exceptions():
    assert supervise.call("t", lambda: 42, deadline_s=5) == 42
    with pytest.raises(ValueError):
        supervise.call("t", lambda: (_ for _ in ()).throw(ValueError()),
                       deadline_s=5)


def test_wedge_detected_within_deadline_and_retried():
    # One injected wedge: detection takes ~the configured deadline,
    # the retry runs the REAL thunk, the trip is recorded in stats.
    supervise.inject_wedge("t", 1, deadline_s=0.2)
    stats: dict = {}
    t0 = time.monotonic()
    out = supervise.call("t", lambda: "real", deadline_s=9, stats=stats)
    dt = time.monotonic() - t0
    assert out == "real"
    assert 0.15 <= dt < 2.0, f"detection took {dt:.2f}s, not ~0.2s"
    assert stats["watchdog_trips"] == 1
    assert stats["supervise_events"] == [{"site": "t", "kind": "wedge"}]


def test_wedge_budget_exhaustion_raises():
    supervise.inject_wedge("t", 5, deadline_s=0.05)
    stats: dict = {}
    with pytest.raises(supervise.WedgedDispatch):
        supervise.call("t", lambda: "never", deadline_s=9, retries=1,
                       stats=stats)
    assert stats["watchdog_trips"] == 2      # initial attempt + 1 retry


def test_env_wedge_hook_parses_site_count_deadline(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_WEDGE", "a:2:0.05,b")
    supervise._env_wedge_loaded = None
    assert supervise._consume_injection("a") == 0.05
    assert supervise._consume_injection("a") == 0.05
    assert supervise._consume_injection("a") is None
    assert supervise._consume_injection("b") == -1.0
    assert supervise._consume_injection("b") is None


def test_disabled_supervision_runs_inline(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_SUPERVISE", "0")
    supervise.inject_wedge("t", 1)
    # Injection is never consumed when disabled: plain passthrough.
    assert supervise.call("t", lambda: threading.current_thread(),
                          deadline_s=0.01) is threading.main_thread()


# --- quarantine ledger ------------------------------------------------------


def test_ledger_record_load_clear_delta(ledger):
    key = supervise.shape_key("host-wave", rows=4, cap=4096, window=34,
                              kernel="cas-register")
    assert supervise.quarantined(key) is None
    before = dict(supervise.load_ledger())
    e = supervise.record_fault(key, "fault", detail="boom")
    assert e["count"] == 1 and e["reason"] == "fault"
    # Re-record bumps the count, keeps first-seen.
    e2 = supervise.record_fault(key, "wedge")
    assert e2["count"] == 2 and e2["reason"] == "wedge"
    assert e2["first"] == e["first"]
    got = supervise.quarantined(key)
    assert got is not None and got["count"] == 2
    # Delta vs the pre-fault snapshot names the shape.
    delta = supervise.ledger_delta(before)
    assert set(delta) == {key}
    # Clear by key, then fully.
    other = supervise.shape_key("spike", rows=32, cap=262144, window=49,
                                kernel="cas-register")
    supervise.record_fault(other, "fault")
    assert supervise.clear_ledger(keys=[key]) == 1
    assert supervise.quarantined(key) is None
    assert supervise.quarantined(other) is not None
    assert supervise.clear_ledger() == 1
    assert supervise.load_ledger() == {}


def test_single_wedge_tolerated_fault_quarantines(ledger):
    # The quarantine gate: one wedge is environmental-stall tolerance,
    # an in-window STREAK of WEDGE_QUARANTINE_COUNT wedges is
    # evidence, a FAULT quarantines immediately.
    wk = supervise.shape_key("host-wave", rows=4, cap=4096, window=34,
                             kernel="cas-register")
    supervise.record_fault(wk, "wedge")
    assert supervise.quarantined(wk) is None
    supervise.record_fault(wk, "wedge")
    assert supervise.quarantined(wk) is not None
    fk = supervise.shape_key("host-pass", cap=4096, window=34,
                             kernel="cas-register")
    supervise.record_fault(fk, "fault")
    assert supervise.quarantined(fk) is not None


def test_wedge_streak_resets_outside_window(ledger):
    # Two isolated environmental stalls far apart must NOT quarantine:
    # the streak resets when the previous wedge is outside the window.
    import time

    key = "host-wave|rows4|cap4096|w34|cas-register"
    supervise.record_fault(key, "wedge")
    shapes = dict(supervise.load_ledger())
    old = time.strftime(supervise._TS_FMT, time.gmtime(
        time.time() - 2 * supervise.WEDGE_STREAK_WINDOW_S))
    shapes[key] = dict(shapes[key], last=old)
    supervise._write_ledger(supervise.ledger_path(), shapes)
    e = supervise.record_fault(key, "wedge")   # a week/hours later
    assert e["streak"] == 1 and e["count"] == 2
    assert supervise.quarantined(key) is None
    e = supervise.record_fault(key, "wedge")   # back-to-back: evidence
    assert e["streak"] == 2
    assert supervise.quarantined(key) is not None


def test_ledger_corruption_never_blocks(ledger):
    with open(ledger, "w") as fh:
        fh.write("{not json")
    assert supervise.load_ledger() == {}
    # Recording over a corrupt ledger repairs it.
    supervise.record_fault("k", "fault")
    assert supervise.quarantined("k") is not None


def test_ledger_disabled(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_QUARANTINE", "0")
    assert supervise.ledger_path() is None
    assert supervise.record_fault("k", "fault") is None
    assert supervise.quarantined("k") is None


# --- checkpoint codec -------------------------------------------------------


def test_checkpoint_roundtrip_and_fingerprint_gate(tmp_path):
    path = str(tmp_path / "c.npz")
    ck = supervise.Checkpointer(path, "fp1", every_s=0)
    seen = []
    ck.on_save = lambda kind, row: seen.append((kind, row))
    lo = np.arange(7, dtype=np.uint32)
    ck.save("host", 42, 7, {"lo": lo},
            {"key_hi": False, "b": 3, "nil_id": 2, "nw": 1,
             "sticky_lvl": 1})
    assert seen == [("host", 42)]
    rd = supervise.load_checkpoint(path, "fp1")
    assert rd is not None
    assert rd["kind"] == "host" and rd["row"] == 42 and rd["count"] == 7
    assert rd["meta"]["sticky_lvl"] == 1
    np.testing.assert_array_equal(rd["lo"], lo)
    # A different history fingerprint must reject the checkpoint (a
    # resume onto the wrong search input would be unsound).
    assert supervise.load_checkpoint(path, "fp2") is None
    ck.clear()
    assert not os.path.exists(path)
    assert supervise.load_checkpoint(path, "fp1") is None


def test_checkpoint_corruption_degrades_to_fresh(tmp_path):
    path = str(tmp_path / "c.npz")
    with open(path, "wb") as fh:
        fh.write(b"not an npz")
    assert supervise.load_checkpoint(path, "fp") is None


def test_checkpoint_interval_gating(tmp_path):
    ck = supervise.Checkpointer(str(tmp_path / "c.npz"), "fp",
                                every_s=3600)
    assert ck.due()
    ck.save("chunk", 1, 1, {"bits": np.zeros((1, 1), np.uint32),
                            "state": np.zeros((1, 1), np.int32)}, {})
    assert not ck.due()


# --- numpy packed-key codec -------------------------------------------------


@pytest.mark.parametrize("key_hi,b,nw", [(False, 3, 1), (True, 5, 2)])
def test_np_key_codec_roundtrip(key_hi, b, nw):
    nil_state = -1
    nil_id = (1 << b) - 1
    rng = np.random.default_rng(7)
    n = 17
    # The packed form is (bits << b | sid) in 64 (pair) / 32 bits:
    # bitset width is bounded by 64 - b (engine bound: window <= 60).
    width = (64 - b if key_hi else 31 - b)
    bits = np.zeros((n, nw), np.uint32)
    for w in range(nw):
        hi_bits = max(0, min(32, width - 32 * w))
        if hi_bits:
            bits[:, w] = rng.integers(0, 1 << hi_bits, n, np.uint32,
                                      endpoint=False)
    state = rng.integers(0, nil_id, (n, 1)).astype(np.int32)
    state[::5] = nil_state
    lo, hi = supervise.np_pack_keys(bits, state, b, nil_id, key_hi,
                                    nil_state, cap=n + 3)
    assert (lo[n:] == supervise.KEY_FILL).all()
    b2, s2 = supervise.np_unpack_keys(lo, hi, n, b, nil_id, nw, key_hi,
                                      nil_state)
    np.testing.assert_array_equal(b2, bits)
    np.testing.assert_array_equal(s2, state)


# --- cli subcommand ---------------------------------------------------------


def test_cli_quarantine_list_clear_diff(ledger, tmp_path, capsys):
    from jepsen_tpu import cli

    key = supervise.shape_key("host-fixpoint", cap=65536, window=49,
                              kernel="cas-register")
    supervise.record_fault(key, "wedge")
    assert cli.run([cli.quarantine_cmd()], ["quarantine", "list"]) == 0
    out = capsys.readouterr().out
    assert key in out and "reason=wedge" in out

    # diff against a pre-fault snapshot names the new shape; against
    # the current ledger it is empty.
    empty = tmp_path / "before.json"
    empty.write_text(json.dumps({"shapes": {}}))
    assert cli.run([cli.quarantine_cmd()],
                   ["quarantine", "diff", "--before", str(empty)]) == 0
    assert key in capsys.readouterr().out
    now = tmp_path / "now.json"
    now.write_text(open(ledger).read())
    assert cli.run([cli.quarantine_cmd()],
                   ["quarantine", "diff", "--before", str(now)]) == 0
    assert "none" in capsys.readouterr().out

    assert cli.run([cli.quarantine_cmd()], ["quarantine", "clear"]) == 0
    assert "cleared 1" in capsys.readouterr().out
    assert supervise.load_ledger() == {}


# --- end-to-end: the fallback ladder on the real engine ---------------------


@pytest.fixture(scope="module")
def small_band_packed():
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import prepare, synth

    h = synth.generate_register_history(60, concurrency=6, seed=1,
                                        crash_prob=0.25)
    return prepare.prepare(m.cas_register(), h)


def _check(p, **kw):
    from jepsen_tpu.lin import bfs

    return bfs.check_packed(p, cap_schedule=(1,), host_caps=(8, 64, 512),
                            **kw)


@pytest.mark.compiles
def test_wedged_dispatch_detected_retried_and_visible(ledger,
                                                      small_band_packed):
    # Acceptance: a simulated wedged dispatch (test hook) is detected
    # within the configured deadline, retried per the ladder, and the
    # event appears in host-stats — no manual kill required.
    supervise.inject_wedge("host-fixpoint", 1, deadline_s=0.3)
    t0 = time.monotonic()
    r = _check(small_band_packed)
    assert r["valid?"] is True
    s = r["host-stats"]
    assert s["watchdog_trips"] == 1
    assert s["supervise_events"][0] == {"site": "host-fixpoint",
                                        "kind": "wedge"}
    # Detection cost ~one 0.3s deadline, nowhere near a stall window.
    assert time.monotonic() - t0 < 60


@pytest.mark.compiles
def test_exhausted_wedges_quarantine_and_fall_back(ledger,
                                                   small_band_packed):
    # Every fused attempt wedges: the ladder falls to the unfused rung
    # (same verdict), and the fused shape lands in the ledger.
    supervise.inject_wedge("host-fixpoint", 500, deadline_s=0.1)
    r = _check(small_band_packed)
    supervise._injected.clear()
    assert r["valid?"] is True
    assert r["host-stats"]["watchdog_trips"] >= 2
    led = supervise.load_ledger()
    assert any(k.startswith("host-fixpoint|") for k in led)
    assert all(e["reason"] == "wedge" for e in led.values())

    # Wedge-quarantine policy: a SINGLE wedge of a shape is tolerated
    # (tunnel stalls are often environmental); an in-window STREAK is
    # evidence (WEDGE_QUARANTINE_COUNT).
    for k, e in led.items():
        assert (supervise.quarantined(k) is None) == \
            (e.get("streak", 0) < supervise.WEDGE_QUARANTINE_COUNT)

    # Push every shape over the threshold: the next fresh check
    # (fresh-process equivalent: the ledger is re-read from disk)
    # routes straight to the fallback rung without re-wedging.
    for k in list(led):
        supervise.record_fault(k, "wedge")
    r3 = _check(small_band_packed)
    s3 = r3["host-stats"]
    assert r3["valid?"] is True
    assert s3["quarantine_skips"] >= 1
    assert s3["watchdog_trips"] == 0


@pytest.mark.compiles
def test_cpu_oracle_rung_when_everything_is_quarantined(
        ledger, small_band_packed):
    # Quarantine BOTH device rungs at every host cap: rows must decide
    # on the CPU-oracle rung with the same verdict.
    p = small_band_packed
    W = p.window
    for cap in (8, 64, 512):
        for site in ("host-fixpoint", "host-pass"):
            supervise.record_fault(
                supervise.shape_key(site, cap=cap, window=W,
                                    kernel="cas-register"), "fault")
    r = _check(p)
    assert r["valid?"] is True
    s = r["host-stats"]
    assert s["cpu_rows"] >= 1
    assert s["quarantine_skips"] >= 1


@pytest.mark.compiles
def test_dispatch_fault_reports_honest_unknown_and_records(
        ledger, small_band_packed, monkeypatch):
    # A dispatch FAULT (dead worker / XLA runtime error) at the base
    # chunk rung must never escape as a raw exception: honest
    # `overflow: fault` unknown, the shape in the ledger, the event in
    # host-stats.
    from jepsen_tpu.lin import bfs

    def boom(*a, **kw):
        raise RuntimeError("XLA worker died (injected)")

    monkeypatch.setattr(bfs, "_search_chunk", boom)
    r = bfs.check_packed(small_band_packed, cap_schedule=(1,),
                         host_caps=(8, 64, 512))
    assert r["valid?"] == "unknown"
    assert r["overflow"] == "fault"
    assert r["host-stats"]["faults"] >= 1
    assert any(k.startswith("chunk") for k in supervise.load_ledger())


@pytest.mark.compiles
def test_wave_quarantine_routes_to_per_row(ledger, small_band_packed,
                                           monkeypatch):
    # A quarantined K-row wave shape must skip the wave program
    # entirely (multi_dispatches == 0) and still decide per-row.
    monkeypatch.setenv("JEPSEN_TPU_HOST_STICKY", "1")
    monkeypatch.setenv("JEPSEN_TPU_HOST_ROWS_K", "4")
    p = small_band_packed
    for cap in (8, 64, 512):
        for kn in (2, 3, 4):
            supervise.record_fault(
                supervise.shape_key("host-wave", rows=kn, cap=cap,
                                    window=p.window,
                                    kernel="cas-register"), "fault")
    r = _check(p)
    assert r["valid?"] is True
    s = r["host-stats"]
    assert s["multi_dispatches"] == 0
    assert s["quarantine_skips"] >= 1
    assert s["rows"] > 0
