"""Parity tests for the in-VMEM pallas sort-dedup (jepsen_tpu.lin.psort)
against the lax.sort dedup it replaces — interpret mode on the CPU mesh,
so the kernel's semantics are fuzzed without TPU hardware."""

import numpy as np
import pytest


@pytest.fixture()
def interpret_psort(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_PSORT", "interpret")


def _lax_dedup(key, valid, cap):
    """The lax reference, called with use_psort=False."""
    from jepsen_tpu.lin.bfs import _dedup_keys

    return _dedup_keys(key, valid, cap, use_psort=False)


def _psort_dedup(key, valid, cap):
    from jepsen_tpu.lin import psort

    assert psort.backend_ok()
    return psort.dedup_keys(key, valid, cap)


@pytest.mark.parametrize("n,cap", [(1024, 256), (1500, 512),
                                   (4096, 1024), (2048, 2048)])
def test_dedup_parity_fuzz(interpret_psort, n, cap):
    import jax.numpy as jnp

    rng = np.random.default_rng(n * 31 + cap)
    for trial in range(4):
        # Heavy duplication (small key range) + invalid entries.
        keys = rng.integers(0, 1 << 10, n).astype(np.uint32)
        valid = rng.random(n) < (0.2, 0.6, 0.95, 1.0)[trial]
        k1, c1, o1 = _lax_dedup(jnp.asarray(keys), jnp.asarray(valid), cap)
        k2, c2, o2 = _psort_dedup(jnp.asarray(keys), jnp.asarray(valid),
                                  cap)
        assert int(c1) == int(c2)
        assert bool(o1) == bool(o2)
        assert np.array_equal(np.asarray(k1), np.asarray(k2))


def test_dedup_overflow_parity(interpret_psort):
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    # More distinct keys than cap: overflow must be flagged identically.
    keys = rng.permutation(1 << 12).astype(np.uint32)[:2048]
    valid = np.ones(2048, bool)
    k1, c1, o1 = _lax_dedup(jnp.asarray(keys), jnp.asarray(valid), 512)
    k2, c2, o2 = _psort_dedup(jnp.asarray(keys), jnp.asarray(valid), 512)
    assert bool(o1) and bool(o2)
    assert int(c1) == int(c2) == 512
    assert np.array_equal(np.asarray(k1), np.asarray(k2))


def test_dedup_all_invalid(interpret_psort):
    import jax.numpy as jnp

    keys = np.arange(1024, dtype=np.uint32)
    valid = np.zeros(1024, bool)
    k2, c2, o2 = _psort_dedup(jnp.asarray(keys), jnp.asarray(valid), 256)
    assert int(c2) == 0 and not bool(o2)
    assert (np.asarray(k2) == 0xFFFFFFFF).all()


def test_engine_parity_with_psort(interpret_psort):
    """Full sparse-engine run with the pallas dedup (interpret) vs the
    CPU oracle on a window>20 register history (the band psort serves)."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import bfs, cpu, prepare, synth

    h = synth.generate_register_history(
        120, concurrency=24, seed=11, value_range=3, crash_prob=0.0)
    p = prepare.prepare(m.cas_register(), h)
    assert p.window > 20
    r_dev = bfs.check_packed(p)
    r_cpu = cpu.check_packed(p)
    assert r_dev["valid?"] == r_cpu["valid?"]


def test_engine_parity_invalid_with_psort(interpret_psort):
    """A corrupted wide history must stay invalid with the same dead row
    class under the pallas dedup."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import bfs, cpu, prepare, synth

    h = synth.generate_register_history(
        100, concurrency=16, seed=5, value_range=3, crash_prob=0.0)
    h = synth.corrupt_history(h, seed=3)
    p = prepare.prepare(m.cas_register(), h)
    r_dev = bfs.check_packed(p)
    r_cpu = cpu.check_packed(p)
    assert r_dev["valid?"] == r_cpu["valid?"]
