"""Parity tests for the in-VMEM pallas sort-dedup (jepsen_tpu.lin.psort)
against the lax.sort dedup it replaces — interpret mode on the CPU mesh,
so the kernel's semantics are fuzzed without TPU hardware."""

import numpy as np
import pytest


@pytest.fixture()
def interpret_psort(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_PSORT", "interpret")


def _lax_dedup(key, valid, cap):
    """The lax reference, called with use_psort=False."""
    from jepsen_tpu.lin.bfs import _dedup_keys

    return _dedup_keys(key, valid, cap, use_psort=False)


def _psort_dedup(key, valid, cap):
    from jepsen_tpu.lin import psort

    assert psort.backend_ok()
    return psort.dedup_keys(key, valid, cap)


@pytest.mark.parametrize("n,cap", [(1024, 256), (1500, 512),
                                   (4096, 1024), (2048, 2048)])
def test_dedup_parity_fuzz(interpret_psort, n, cap):
    import jax.numpy as jnp

    rng = np.random.default_rng(n * 31 + cap)
    for trial in range(4):
        # Heavy duplication (small key range) + invalid entries.
        keys = rng.integers(0, 1 << 10, n).astype(np.uint32)
        valid = rng.random(n) < (0.2, 0.6, 0.95, 1.0)[trial]
        k1, c1, o1 = _lax_dedup(jnp.asarray(keys), jnp.asarray(valid), cap)
        k2, c2, o2 = _psort_dedup(jnp.asarray(keys), jnp.asarray(valid),
                                  cap)
        assert int(c1) == int(c2)
        assert bool(o1) == bool(o2)
        assert np.array_equal(np.asarray(k1), np.asarray(k2))


def test_dedup_overflow_parity(interpret_psort):
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    # More distinct keys than cap: overflow must be flagged identically.
    keys = rng.permutation(1 << 12).astype(np.uint32)[:2048]
    valid = np.ones(2048, bool)
    k1, c1, o1 = _lax_dedup(jnp.asarray(keys), jnp.asarray(valid), 512)
    k2, c2, o2 = _psort_dedup(jnp.asarray(keys), jnp.asarray(valid), 512)
    assert bool(o1) and bool(o2)
    assert int(c1) == int(c2) == 512
    assert np.array_equal(np.asarray(k1), np.asarray(k2))


def test_dedup_all_invalid(interpret_psort):
    import jax.numpy as jnp

    keys = np.arange(1024, dtype=np.uint32)
    valid = np.zeros(1024, bool)
    k2, c2, o2 = _psort_dedup(jnp.asarray(keys), jnp.asarray(valid), 256)
    assert int(c2) == 0 and not bool(o2)
    assert (np.asarray(k2) == 0xFFFFFFFF).all()


def test_engine_parity_with_psort(interpret_psort):
    """Full sparse-engine run with the pallas dedup (interpret) vs the
    CPU oracle on a window>20 register history (the band psort serves)."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import bfs, cpu, prepare, synth

    h = synth.generate_register_history(
        120, concurrency=24, seed=11, value_range=3, crash_prob=0.0)
    p = prepare.prepare(m.cas_register(), h)
    assert p.window > 20
    r_dev = bfs.check_packed(p)
    r_cpu = cpu.check_packed(p)
    assert r_dev["valid?"] == r_cpu["valid?"]


def test_engine_parity_invalid_with_psort(interpret_psort):
    """A corrupted wide history must stay invalid with the same dead row
    class under the pallas dedup."""
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import bfs, cpu, prepare, synth

    h = synth.generate_register_history(
        100, concurrency=16, seed=5, value_range=3, crash_prob=0.0)
    h = synth.corrupt_history(h, seed=3)
    p = prepare.prepare(m.cas_register(), h)
    r_dev = bfs.check_packed(p)
    r_cpu = cpu.check_packed(p)
    assert r_dev["valid?"] == r_cpu["valid?"]


def test_dedup2_dom_parity_fuzz(interpret_psort):
    """Pair-key dominance dedup: pallas quad kernel vs the lax path of
    bfs._dedup_keys2_dom on random configs with crash/read masks."""
    import jax.numpy as jnp

    from jepsen_tpu.lin.bfs import _dedup_keys2_dom

    rng = np.random.default_rng(42)
    for trial in range(6):
        n = (1024, 2048, 4096)[trial % 3]
        cap = n // 2
        cmask_lo = np.uint32(rng.integers(0, 1 << 12))
        rmask_lo = np.uint32(rng.integers(0, 1 << 12) << 12) & ~cmask_lo
        cmask_hi = np.uint32(rng.integers(0, 1 << 8))
        rmask_hi = np.uint32(rng.integers(0, 1 << 8) << 8) & ~cmask_hi
        hi = rng.integers(0, 1 << 16, n).astype(np.uint32)
        lo = rng.integers(0, 1 << 24, n).astype(np.uint32)
        valid = rng.random(n) < 0.8
        args = (jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid),
                cap, jnp.uint32(cmask_hi), jnp.uint32(cmask_lo),
                jnp.uint32(rmask_hi), jnp.uint32(rmask_lo))
        h1, l1, c1, o1 = _dedup_keys2_dom(*args, use_psort=False)
        h2, l2, c2, o2 = _dedup_keys2_dom(*args, use_psort=True)
        assert int(c1) == int(c2), trial
        assert bool(o1) == bool(o2), trial
        assert np.array_equal(np.asarray(h1), np.asarray(h2)), trial
        assert np.array_equal(np.asarray(l1), np.asarray(l2)), trial


def test_dedup2_dom_forced_chain_parity_fuzz(interpret_psort):
    """FORCED dominance dedup (window + unrolled chain + iterated
    rounds): pallas quad kernel vs the lax fori path at the pallas
    kernel's iteration count."""
    import jax.numpy as jnp

    from jepsen_tpu.lin import psort
    from jepsen_tpu.lin.bfs import _dedup_keys2_dom

    rng = np.random.default_rng(17)
    for trial in range(4):
        n = (1024, 2048, 4096)[trial % 3]
        cap = n // 2
        cmask_lo = np.uint32(rng.integers(0, 1 << 12))
        rmask_lo = np.uint32(rng.integers(0, 1 << 12) << 12) & ~cmask_lo
        cmask_hi = np.uint32(rng.integers(0, 1 << 8))
        rmask_hi = np.uint32(rng.integers(0, 1 << 8) << 8) & ~cmask_hi
        hi = rng.integers(0, 1 << 16, n).astype(np.uint32)
        lo = rng.integers(0, 1 << 24, n).astype(np.uint32)
        valid = rng.random(n) < 0.8
        args = (jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid),
                cap, jnp.uint32(cmask_hi), jnp.uint32(cmask_lo),
                jnp.uint32(rmask_hi), jnp.uint32(rmask_lo))
        h1, l1, c1, o1 = _dedup_keys2_dom(
            *args, use_psort=False, dom_force=True,
            dom_iters=psort.DOM_ITERS)
        h2, l2, c2, o2 = _dedup_keys2_dom(*args, use_psort=True,
                                          dom_force=True)
        assert int(c1) == int(c2), trial
        assert bool(o1) == bool(o2), trial
        assert np.array_equal(np.asarray(h1), np.asarray(h2)), trial
        assert np.array_equal(np.asarray(l1), np.asarray(l2)), trial


def test_dedup_dom_forced_chain_parity_fuzz(interpret_psort):
    """Single-key forced dominance dedup: pallas vs lax."""
    import jax.numpy as jnp

    from jepsen_tpu.lin import psort
    from jepsen_tpu.lin.bfs import _dedup_keys_dom

    rng = np.random.default_rng(23)
    for trial in range(4):
        n = (1024, 2048)[trial % 2]
        cap = n // 2
        cmask = np.uint32(rng.integers(0, 1 << 10))
        rmask = np.uint32(rng.integers(0, 1 << 10) << 10) & ~cmask
        key = rng.integers(0, 1 << 24, n).astype(np.uint32)
        valid = rng.random(n) < 0.8
        args = (jnp.asarray(key), jnp.asarray(valid), cap,
                jnp.uint32(cmask), jnp.uint32(rmask))
        k1, c1, o1 = _dedup_keys_dom(*args, use_psort=False,
                                     dom_force=True,
                                     dom_iters=psort.DOM_ITERS)
        k2, c2, o2 = _dedup_keys_dom(*args, use_psort=True,
                                     dom_force=True)
        assert int(c1) == int(c2), trial
        assert bool(o1) == bool(o2), trial
        assert np.array_equal(np.asarray(k1), np.asarray(k2)), trial


def test_compact_keys_parity(interpret_psort):
    """compact_keys packs distinct non-KEY_FILL entries ascending."""
    import jax.numpy as jnp

    from jepsen_tpu.lin import psort

    rng = np.random.default_rng(9)
    vals = rng.choice(1 << 20, size=700, replace=False).astype(np.uint32)
    keys = np.full(2048, 0xFFFFFFFF, np.uint32)
    keys[rng.choice(2048, size=700, replace=False)] = vals
    out, count = psort.compact_keys(jnp.asarray(keys), 1024)
    assert int(count) == 700
    ref = np.sort(vals)
    assert np.array_equal(np.asarray(out)[:700], ref)
    assert (np.asarray(out)[700:] == 0xFFFFFFFF).all()


def test_compact_keys2_parity(interpret_psort):
    import jax.numpy as jnp

    from jepsen_tpu.lin import psort

    rng = np.random.default_rng(10)
    n = 2048
    hi = rng.integers(0, 1 << 8, n).astype(np.uint32)
    lo = rng.integers(0, 1 << 16, n).astype(np.uint32)
    live = rng.random(n) < 0.3
    # distinct pairs only where live
    flat = (hi.astype(np.uint64) << 32) | lo
    _, first_idx = np.unique(flat, return_index=True)
    keep = np.zeros(n, bool)
    keep[first_idx] = True
    live &= keep
    hi2 = np.where(live, hi, np.uint32(0xFFFFFFFF))
    lo2 = np.where(live, lo, np.uint32(0xFFFFFFFF))
    out_hi, out_lo, count = psort.compact_keys2(
        jnp.asarray(hi2), jnp.asarray(lo2), 1024)
    k = int(count)
    assert k == int(live.sum())
    ref = np.sort(flat[live])
    got = (np.asarray(out_hi)[:k].astype(np.uint64) << 32) | \
        np.asarray(out_lo)[:k]
    assert np.array_equal(got, ref)


def test_dedup_cap_contract_enforced(interpret_psort):
    import jax.numpy as jnp
    import pytest as _pytest

    from jepsen_tpu.lin import psort

    keys = jnp.zeros(1024, jnp.uint32)
    with _pytest.raises(ValueError, match="cap"):
        psort.dedup_keys(keys, jnp.ones(1024, bool), 4096)
