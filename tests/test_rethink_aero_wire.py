"""RethinkDB + Aerospike wire clients against in-process fake servers
with real stores — the ReQL branch-CAS and the generation-conditioned
write are exercised end to end."""

from __future__ import annotations

import json
import socket
import struct
import threading

from jepsen_tpu.history import Op
from jepsen_tpu.suites import aerowire, rethinkwire
import pytest

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick

# --- fake rethinkdb --------------------------------------------------------


class _NullAccess(Exception):
    """Field access on null (real RethinkDB raises a runtime error that
    only r.default catches)."""


class FakeRethink:
    """Single-table store evaluating the exact term shapes the client
    builds (get / insert / branch-replace / db+table admin)."""

    def __init__(self):
        self.rows: dict = {}
        self.dbs = {"test"}
        self.tables = {"test": set()}
        self.srv = socket.socket()
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(4)
        self.port = self.srv.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _eval(self, term, row=None):
        if not isinstance(term, list):
            return term
        tid, args = term[0], term[1] if len(term) > 1 else []
        if tid == rethinkwire.T_TABLE:
            return ("table", args[0])
        if tid == rethinkwire.T_GET:
            self._eval(args[0])
            return self.rows.get(args[1])
        if tid == rethinkwire.T_INSERT:
            self._eval(args[0])
            doc = args[1]
            opt = term[2] if len(term) > 2 else {}
            if doc["id"] in self.rows and opt.get("conflict") != "replace":
                return {"errors": 1, "inserted": 0}
            self.rows[doc["id"]] = dict(doc)
            return {"inserted": 1, "errors": 0}
        if tid == rethinkwire.T_REPLACE:
            cur = self._eval(args[0])
            fn = args[1]
            new = self._eval(fn[1][1], row=cur)
            if new == cur:
                return {"replaced": 0, "unchanged": 1}
            self.rows[new["id"]] = dict(new)
            return {"replaced": 1, "unchanged": 0}
        if tid == rethinkwire.T_BRANCH:
            cond, then, els = args
            return self._eval(then, row) if self._eval(cond, row) \
                else self._eval(els, row)
        if tid == rethinkwire.T_EQ:
            return self._eval(args[0], row) == self._eval(args[1], row)
        if tid == rethinkwire.T_GET_FIELD:
            base = self._eval(args[0], row)
            if base is None:
                # real RethinkDB errors on field access of null; the
                # client wraps these in r.default, evaluated below
                raise _NullAccess()
            return base.get(args[1])
        if tid == rethinkwire.T_DEFAULT:
            try:
                v = self._eval(args[0], row)
                return args[1] if v is None else v
            except _NullAccess:
                return self._eval(args[1], row)
        if tid == rethinkwire.T_VAR:
            return row
        if tid == rethinkwire.T_DB_LIST:
            return sorted(self.dbs)
        if tid == rethinkwire.T_DB_CREATE:
            self.dbs.add(args[0])
            self.tables.setdefault(args[0], set())
            return {"dbs_created": 1}
        if tid == rethinkwire.T_TABLE_LIST:
            return sorted(self.tables.get("jepsen", set()))
        if tid == rethinkwire.T_TABLE_CREATE:
            self.tables.setdefault("jepsen", set()).add(args[0])
            return {"tables_created": 1}
        raise ValueError(f"fake cannot eval term {tid}")

    def _serve(self, conn):
        buf = bytearray()

        def read_exact(n):
            while len(buf) < n:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf.extend(chunk)
            out = bytes(buf[:n])
            del buf[:n]
            return out

        try:
            read_exact(4 + 4 + 4)                 # V0_4 + keylen(0) + JSON
            conn.sendall(b"SUCCESS\x00")
            while True:
                token, n = struct.unpack("<QI", read_exact(12))
                qtype, term, _opts = json.loads(read_exact(n))
                try:
                    r = self._eval(term)
                    if isinstance(r, list):
                        resp = {"t": 2, "r": r}
                    else:
                        resp = {"t": 1, "r": [r]}
                except (ValueError, _NullAccess) as e:
                    resp = {"t": 18, "r": [str(e) or "null access"]}
                out = json.dumps(resp).encode()
                conn.sendall(struct.pack("<QI", token, len(out)) + out)
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def close(self):
        self.srv.close()


class TestRethink:
    def test_register_cas_semantics(self):
        srv = FakeRethink()
        cl = rethinkwire.RegisterClient(
            rethinkwire.RethinkClient("127.0.0.1", srv.port))
        assert cl.invoke(None, Op("invoke", "read", None, 0)).value is None
        assert cl.invoke(None, Op("invoke", "write", 3, 0)).is_ok
        assert cl.invoke(None, Op("invoke", "read", None, 0)).value == 3
        assert cl.invoke(None, Op("invoke", "cas", [3, 4], 0)).is_ok
        assert cl.invoke(None, Op("invoke", "cas", [3, 9], 0)).is_fail
        assert cl.invoke(None, Op("invoke", "read", None, 0)).value == 4
        cl.close(None)
        srv.close()

    def test_cas_on_missing_key_fails_cleanly(self):
        # field access on null must route through r.default -> clean
        # no-match, not a runtime error reported as :info
        srv = FakeRethink()
        cl = rethinkwire.RegisterClient(
            rethinkwire.RethinkClient("127.0.0.1", srv.port))
        r = cl.invoke(None, Op("invoke", "cas", [1, 2], 0))
        assert r.is_fail, r
        cl.close(None)
        srv.close()

    def test_setup_creates_db_and_table(self):
        srv = FakeRethink()
        import jepsen_tpu.suites.rethinkwire as rw

        orig = rw.RethinkClient.__init__

        def patched(self, host, port=srv.port, **kw):
            orig(self, host, srv.port, **kw)

        rw.RethinkClient.__init__ = patched
        try:
            rw.RegisterClient().setup({"nodes": ["127.0.0.1"]})
        finally:
            rw.RethinkClient.__init__ = orig
        assert "jepsen" in srv.dbs
        assert "registers" in srv.tables["jepsen"]
        srv.close()


# --- fake aerospike --------------------------------------------------------


class FakeAerospike:
    """Record store keyed by digest with generations, evaluating
    read-all / write (with generation policy) / incr."""

    def __init__(self):
        self.records: dict[bytes, tuple[dict, int]] = {}
        self.srv = socket.socket()
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(4)
        self.port = self.srv.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        buf = bytearray()

        def read_exact(n):
            while len(buf) < n:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf.extend(chunk)
            out = bytes(buf[:n])
            del buf[:n]
            return out

        try:
            while True:
                (head,) = struct.unpack(">Q", read_exact(8))
                body = read_exact(head & ((1 << 48) - 1))
                info1, info2 = body[1], body[2]
                gen_expect = struct.unpack_from(">I", body, 6)[0]
                n_fields, n_ops = struct.unpack_from(">HH", body, 18)
                off = body[0]
                dig = None
                for _ in range(n_fields):
                    (sz,) = struct.unpack_from(">I", body, off)
                    ftype = body[off + 4]
                    data = body[off + 5:off + 4 + sz]
                    if ftype == aerowire.FIELD_DIGEST:
                        dig = data
                    off += 4 + sz
                ops = []
                for _ in range(n_ops):
                    (sz,) = struct.unpack_from(">I", body, off)
                    op = body[off + 4]
                    nl = body[off + 7]
                    name = body[off + 8:off + 8 + nl].decode()
                    data = body[off + 8 + nl:off + 4 + sz]
                    ops.append((op, name, data))
                    off += 4 + sz

                rc, gen, bins = self._apply(dig, info1, info2,
                                            gen_expect, ops)
                out_ops = b""
                for name, v in bins.items():
                    nb = name.encode()
                    data = struct.pack(">q", v) if isinstance(v, int) \
                        else str(v).encode()
                    btype = aerowire.BIN_INT if isinstance(v, int) \
                        else aerowire.BIN_STR
                    out_ops += (struct.pack(">I", 4 + len(nb) + len(data))
                                + bytes([aerowire.OP_READ, btype, 0,
                                         len(nb)]) + nb + data)
                msg = (bytes([22, 0, 0, 0, 0, rc])
                       + struct.pack(">IIIHH", gen, 0, 0, 0, len(bins))
                       + out_ops)
                conn.sendall(struct.pack(
                    ">Q", (2 << 56) | (3 << 48) | len(msg)) + msg)
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def _apply(self, dig, info1, info2, gen_expect, ops):
        rec = self.records.get(dig)
        if info1 & aerowire.INFO1_READ:
            if rec is None:
                return aerowire.RC_NOT_FOUND, 0, {}
            return aerowire.RC_OK, rec[1], dict(rec[0])
        if info2 & aerowire.INFO2_WRITE:
            bins, gen = rec if rec else ({}, 0)
            if info2 & aerowire.INFO2_GENERATION and gen != gen_expect:
                return aerowire.RC_GENERATION, gen, {}
            bins = dict(bins)
            for op, name, data in ops:
                if op == aerowire.OP_WRITE:
                    bins[name] = struct.unpack(">q", data)[0]
                elif op == aerowire.OP_INCR:
                    bins[name] = bins.get(name, 0) \
                        + struct.unpack(">q", data)[0]
            self.records[dig] = (bins, gen + 1)
            return aerowire.RC_OK, gen + 1, {}
        return 4, 0, {}

    def close(self):
        self.srv.close()


class TestAerospike:
    def test_register_cas_semantics(self):
        srv = FakeAerospike()
        cl = aerowire.RegisterClient(
            aerowire.AerospikeClient("127.0.0.1", srv.port))
        assert cl.invoke(None, Op("invoke", "read", None, 0)).value is None
        assert cl.invoke(None, Op("invoke", "write", 3, 0)).is_ok
        assert cl.invoke(None, Op("invoke", "read", None, 0)).value == 3
        assert cl.invoke(None, Op("invoke", "cas", [3, 4], 0)).is_ok
        assert cl.invoke(None, Op("invoke", "cas", [3, 9], 0)).is_fail
        assert cl.invoke(None, Op("invoke", "read", None, 0)).value == 4
        cl.close(None)
        srv.close()

    def test_generation_race_loses(self):
        srv = FakeAerospike()
        a = aerowire.AerospikeClient("127.0.0.1", srv.port)
        b = aerowire.AerospikeClient("127.0.0.1", srv.port)
        a.put("k", {"value": 1})
        bins, gen = a.get("k")
        b.put("k", {"value": 2})            # interloper bumps generation
        import pytest

        with pytest.raises(aerowire.AerospikeError) as ei:
            a.put("k", {"value": 9}, expect_gen=gen)
        assert ei.value.generation_mismatch
        assert b.get("k")[0]["value"] == 2
        a.close()
        b.close()
        srv.close()

    def test_counter_client(self):
        srv = FakeAerospike()
        cl = aerowire.CounterClient(
            aerowire.AerospikeClient("127.0.0.1", srv.port))
        assert cl.invoke(None, Op("invoke", "read", None, 0)).value == 0
        assert cl.invoke(None, Op("invoke", "add", 1, 0)).is_ok
        assert cl.invoke(None, Op("invoke", "add", 2, 0)).is_ok
        assert cl.invoke(None, Op("invoke", "read", None, 0)).value == 3
        cl.close(None)
        srv.close()


def test_suites_ungated_and_final_count():
    import importlib
    import pkgutil

    import jepsen_tpu.suites as suites_pkg
    from jepsen_tpu.suites import common

    gated = []
    for info in pkgutil.iter_modules(suites_pkg.__path__):
        mod = importlib.import_module(f"jepsen_tpu.suites.{info.name}")
        if not hasattr(mod, "test"):
            continue
        try:
            t = mod.test({})
        except Exception:
            continue
        if isinstance(t.get("client"), common.GatedClient):
            gated.append(info.name)
    # every suite now carries a native wire client
    assert gated == [], gated


def test_ripemd160_fallback_vectors():
    # The pure-python fallback must match the official test vectors (and
    # OpenSSL where available) — the record digest depends on it.
    from jepsen_tpu.suites.aerowire import _rmd160_py

    vectors = {
        b"": "9c1185a5c5e9fc54612808977ee8f548b2258d31",
        b"abc": "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc",
        b"message digest": "5d0689ef49d2fae572b881b123a85ffa21595f36",
        b"abcdefghijklmnopqrstuvwxyz":
            "f71c27109c692c1b56bbdceb5b9d2865b3708dbc",
    }
    for msg, want in vectors.items():
        assert _rmd160_py(msg).hex() == want, msg
