"""Elasticsearch dirty-read workload tests: the classification checker
on hand-built histories, the real HTTP client against an in-process
fake ES server, and fault detection through the fake-mode workload."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from jepsen_tpu.history import Op
from jepsen_tpu.suites import elasticsearch as es

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick


class TestChecker:
    def test_valid(self):
        h = [Op("ok", "write", 1, 0), Op("ok", "read", 1, 1),
             Op("ok", "strong-read", [1], 2),
             Op("ok", "strong-read", [1], 3)]
        r = es.dirty_read_checker().check({}, None, h, {})
        assert r["valid?"] is True and r["nodes-agree?"] is True

    def test_dirty_read_classified(self):
        h = [Op("ok", "write", 1, 0),
             Op("ok", "read", 7, 1),           # observed, never durable
             Op("ok", "strong-read", [1], 2)]
        r = es.dirty_read_checker().check({}, None, h, {})
        assert r["valid?"] is False
        assert r["dirty"] == [7] and r["dirty-count"] == 1

    def test_lost_write_classified(self):
        h = [Op("ok", "write", 1, 0), Op("ok", "write", 2, 0),
             Op("ok", "strong-read", [1], 2),
             Op("ok", "strong-read", [1], 3)]
        r = es.dirty_read_checker().check({}, None, h, {})
        assert r["valid?"] is False
        assert r["lost"] == [2] and r["some-lost"] == [2]

    def test_stale_node_classified(self):
        """A node whose strong read misses an element others have:
        nodes disagree; the element is some-lost but not lost."""
        h = [Op("ok", "write", 1, 0), Op("ok", "write", 2, 0),
             Op("ok", "strong-read", [1, 2], 2),
             Op("ok", "strong-read", [1], 3)]
        r = es.dirty_read_checker().check({}, None, h, {})
        assert r["valid?"] is False and r["nodes-agree?"] is False
        assert r["not-on-all"] == [2]
        assert r["lost-count"] == 0 and r["some-lost"] == [2]

    def test_no_strong_reads_unknown(self):
        r = es.dirty_read_checker().check(
            {}, None, [Op("ok", "write", 1, 0)], {})
        assert r["valid?"] == "unknown"


@pytest.fixture()
def fake_es():
    docs: dict = {}
    refreshed: dict = {}
    lock = threading.Lock()

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code, obj):
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_PUT(self):
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n))
            doc_id = self.path.split("?")[0].rsplit("/", 1)[1]
            with lock:
                docs[doc_id] = body
            self._send(201, {"result": "created"})

        def do_GET(self):
            doc_id = self.path.rsplit("/", 1)[1]
            with lock:
                found = doc_id in docs
            self._send(200 if found else 404,
                       {"found": found,
                        "_source": docs.get(doc_id, {})})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            with lock:
                if self.path.endswith("/_refresh"):
                    refreshed.clear()
                    refreshed.update(docs)
                    self._send(200, {"ok": True})
                    return
                hits = [{"_source": s} for s in refreshed.values()]
            self._send(200, {"hits": {"hits": hits}})

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_port
    srv.shutdown()


class TestEsDirtyReadClient:
    def test_visibility_split(self, fake_es, monkeypatch):
        monkeypatch.setattr(es, "PORT", fake_es)
        c = es.EsDirtyReadClient("127.0.0.1")
        assert c.invoke({}, Op("invoke", "write", 3, 0)).type == "ok"
        # realtime GET sees it; search doesn't until refresh
        assert c.invoke({}, Op("invoke", "read", 3, 0)).type == "ok"
        assert c.invoke({}, Op("invoke", "read", 9, 0)).type == "fail"
        r = c.invoke({}, Op("invoke", "strong-read", None, 0))
        assert r.type == "ok" and r.value == []
        assert c.invoke({}, Op("invoke", "refresh", None, 0)).type == "ok"
        r = c.invoke({}, Op("invoke", "strong-read", None, 0))
        assert r.value == [3]


class TestWorkload:
    def _run(self, faulty):
        from jepsen_tpu import core
        from jepsen_tpu.suites import common

        wl = es.dirty_read_workload(n=120, faulty=faulty)
        t = common.suite_test(
            "es-dirty-read", {"time-limit": 10, "concurrency": 5,
                              "fake": True},
            workload=wl)
        t["name"] = None
        res = core.run(t).get("results", {})
        return res.get("workload", res)

    def test_clean_run_valid(self):
        assert self._run(None)["valid?"] is True

    def test_dirty_read_detected(self):
        r = self._run("dirty-read")
        assert r["valid?"] is False and r["dirty-count"] > 0

    def test_lost_write_detected(self):
        r = self._run("lost")
        assert r["valid?"] is False and r["lost-count"] > 0

    def test_registry_cell(self):
        t = es.test({"fake": False, "workload": "dirty-read"})
        assert isinstance(t["client"], es.EsDirtyReadClient)
        t2 = es.test({"fake": True, "workload": "dirty-read",
                      "time-limit": 1})
        assert t2["transport"] == "dummy"
