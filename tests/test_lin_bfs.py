"""Device BFS kernel parity tests (on the virtual CPU mesh backend).

The CPU JIT checker (itself brute-force-verified in test_lin_cpu.py) is the
oracle; the device kernel must agree on every history, including crashed-op
and corrupted cases, and across frontier-capacity escalation boundaries.
"""

import pytest

from jepsen_tpu import models as m
from jepsen_tpu.history import History, invoke_op, ok_op, info_op
from jepsen_tpu.lin import analysis, prepare
from jepsen_tpu.lin import bfs, cpu, synth


def both(model, history, cap_schedule=bfs.DEFAULT_CAP_SCHEDULE):
    p = prepare.prepare(model, history)
    want = cpu.check_packed(p)["valid?"]
    got = bfs.check_packed(p, cap_schedule=cap_schedule)["valid?"]
    assert got == want, f"device={got} cpu={want}"
    return got


class TestBasics:
    def test_empty(self):
        assert both(m.cas_register(), History.of())

    def test_sequential(self):
        assert both(m.cas_register(), History.of(
            invoke_op(0, "write", 1), ok_op(0, "write", 1),
            invoke_op(0, "read", None), ok_op(0, "read", 1)))

    def test_stale_read_invalid(self):
        p = prepare.prepare(m.cas_register(), History.of(
            invoke_op(0, "write", 1), ok_op(0, "write", 1),
            invoke_op(0, "read", None), ok_op(0, "read", 0)))
        r = bfs.check_packed(p)
        assert r["valid?"] is False
        assert r["op"]["f"] == "read" and r["op"]["value"] == 0

    def test_crashed_write_observed(self):
        assert both(m.cas_register(), History.of(
            invoke_op(0, "write", 3), info_op(0, "write", 3),
            invoke_op(1, "read", None), ok_op(1, "read", 3)))

    def test_mutex(self):
        assert not both(m.mutex(), History.of(
            invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
            invoke_op(1, "acquire", None), ok_op(1, "acquire", None)))

    def test_unsupported_model_unknown(self):
        p = prepare.prepare(m.noop, History.of(
            invoke_op(0, "add", 1), ok_op(0, "add", 1)))
        assert bfs.check_packed(p)["valid?"] == "unknown"

    def test_tiny_cap_escalates(self):
        # capacity-1 schedule forces overflow then escalation
        h = synth.generate_register_history(30, concurrency=5, seed=1,
                                            crash_prob=0.3)
        assert both(m.cas_register(), h, cap_schedule=(1, 4096))

    def test_overflow_returns_unknown(self):
        # With the spike/host executors' caps also exhausted, overflow
        # is an honest unknown (never a truncated-frontier verdict),
        # tagged as a CAPACITY overflow (the frontier genuinely
        # outgrew the last cap — distinct from a closure pass-budget
        # exhaustion, which reports "budget").
        h = synth.generate_register_history(30, concurrency=5, seed=1,
                                            crash_prob=0.3)
        p = prepare.prepare(m.cas_register(), h)
        r = bfs.check_packed(p, cap_schedule=(1,), spike_caps=(2,),
                             host_caps=(2,))
        assert r["valid?"] == "unknown"
        assert r["overflow"] == "capacity"
        assert "frontier exceeded capacity" in r["error"]

    @pytest.mark.parametrize("fused", ["1", "0"])
    def test_pass_budget_exhaustion_reports_budget(self, monkeypatch,
                                                   fused):
        # A 1-pass closure budget cannot settle any real crash-dom
        # wave: the host-row executor must escalate through its caps
        # and then report the exhaustion as a BUDGET overflow (the
        # nontermination class round 5 diagnosed), not a capacity
        # overflow — on both the fused fixpoint program and the
        # per-pass fallback.
        monkeypatch.setenv("JEPSEN_TPU_HOST_IT_MAX", "1")
        monkeypatch.setenv("JEPSEN_TPU_FUSED_CLOSURE", fused)
        h = synth.generate_register_history(30, concurrency=5, seed=1,
                                            crash_prob=0.3)
        p = prepare.prepare(m.cas_register(), h)
        r = bfs.check_packed(p, cap_schedule=(2,), host_caps=(4096,))
        assert r["valid?"] == "unknown"
        assert r["overflow"] == "budget"
        assert "closure pass budget exceeded" in r["error"]
        # The budget taxonomy rides the host-stats observability too.
        assert r["host-stats"]["dispatches"] >= 1

    def test_unfused_closure_fallback_parity(self, monkeypatch):
        # JEPSEN_TPU_FUSED_CLOSURE=0 (the fault-triage fallback: one
        # dispatch per closure pass, the round-5 shape) must decide
        # exactly like the fused fixpoint program.
        monkeypatch.setenv("JEPSEN_TPU_FUSED_CLOSURE", "0")
        h = synth.generate_register_history(30, concurrency=5, seed=1,
                                            crash_prob=0.3)
        p = prepare.prepare(m.cas_register(), h)
        want = cpu.check_packed(p)["valid?"]
        r = bfs.check_packed(p, cap_schedule=(1,), spike_caps=(512, 4096))
        assert r["valid?"] == want

    def test_host_stats_reported(self):
        # Any search that entered the host-row executor reports its
        # episode/dispatch/pass counters (the round-6 acceptance
        # metric: fused dispatches per row ~= capacity escalations,
        # far below the per-pass count).
        h = synth.generate_register_history(30, concurrency=5, seed=1,
                                            crash_prob=0.3)
        p = prepare.prepare(m.cas_register(), h)
        r = bfs.check_packed(p, cap_schedule=(1,), spike_caps=(512, 4096))
        # This shape is KNOWN to route rows through the host executor
        # (cap 1 overflows immediately); host-stats must be attached —
        # a conditional check here would go silently vacuous if the
        # stats wiring broke.
        s = r["host-stats"]
        assert s["episodes"] >= 1 and s["rows"] >= 1
        assert s["passes"] >= s["dispatches"] >= 1

    def test_overflow_spills_to_spike_executor(self):
        # Chunked caps exhausted -> the host-driven executors (host-row
        # mode for this crash-heavy register band) pick the search up
        # at bigger caps and still decide.
        h = synth.generate_register_history(30, concurrency=5, seed=1,
                                            crash_prob=0.3)
        p = prepare.prepare(m.cas_register(), h)
        want = cpu.check_packed(p)["valid?"]
        r = bfs.check_packed(p, cap_schedule=(1,), spike_caps=(512, 4096))
        assert r["valid?"] == want

    def test_overflow_spills_to_host_rows_crash_free_spike(self):
        # A crash-FREE compact-band history keeps the spike executor
        # (host mode only owns crash-dom searches).
        h = synth.generate_register_history(30, concurrency=5, seed=1,
                                            crash_prob=0)
        p = prepare.prepare(m.cas_register(), h)
        want = cpu.check_packed(p)["valid?"]
        r = bfs.check_packed(p, cap_schedule=(1,), spike_caps=(512, 4096))
        assert r["valid?"] == want


@pytest.mark.parametrize("seed", range(15))
def test_register_parity_valid(seed):
    h = synth.generate_register_history(40, concurrency=4, seed=seed,
                                        value_range=3, crash_prob=0.15)
    assert both(m.cas_register(), h) is True


@pytest.mark.parametrize("seed", range(15))
def test_register_parity_corrupted(seed):
    h = synth.generate_register_history(40, concurrency=4, seed=seed,
                                        value_range=3, crash_prob=0.1)
    h = synth.corrupt_history(h, seed=seed)
    both(m.cas_register(), h)


@pytest.mark.parametrize("seed", range(10))
def test_mutex_parity(seed):
    h = synth.generate_mutex_history(40, concurrency=4, seed=seed,
                                     crash_prob=0.15)
    assert both(m.mutex(), h) is True


def test_analysis_tpu_and_competition():
    h = synth.generate_register_history(30, concurrency=4, seed=3)
    assert analysis(m.cas_register(), h, algorithm="tpu")["valid?"]
    assert analysis(m.cas_register(), h, algorithm="competition")["valid?"]
    bad = synth.corrupt_history(h, seed=3)
    assert analysis(m.cas_register(), bad,
                    algorithm="competition")["valid?"] is False


def test_cancel_stops_both_racers():
    # A pre-set cancel event makes either racer bail with an "unknown"
    # cancelled result instead of running the search (the competition
    # loser must die promptly so its thread can be joined).
    import threading

    from jepsen_tpu.lin import bfs, cpu, prepare

    ev = threading.Event()
    ev.set()
    h = synth.generate_register_history(200, concurrency=4, seed=5)
    p = prepare.prepare(m.cas_register(), h)
    for checker in (cpu.check_packed, bfs.check_packed):
        r = checker(p, cancel=ev)
        assert r["valid?"] == "unknown"
        assert r["error"] == "cancelled"


def _cas_chain_history(n_chain, prefix_ops=6, seed=0):
    """A history whose window spikes to n_chain via concurrently-pending
    cas ops with chained preconditions cas(i -> i+1): only prefixes of the
    chain can linearize, so the config set stays O(n_chain) while the
    bitset genuinely spans n_chain slots. (A burst of n independent writes
    would be 2^n configs — exponential for ANY config-set checker; wide
    windows are device-feasible exactly when legality prunes the
    interleavings, as in partitioned-cluster stalls.)"""
    h = [invoke_op(0, "write", 0), ok_op(0, "write", 0)]
    for i in range(n_chain):
        h.append(invoke_op(i + 1, "cas", [i, i + 1]))
    for i in range(n_chain):
        h.append(ok_op(i + 1, "cas", [i, i + 1]))
    h.append(invoke_op(0, "read", None))
    h.append(ok_op(0, "read", n_chain))
    return History.of(*h)


def test_wide_window_40_parity():
    # Windows in 33..64 use the multi-word sparse bitset (the dense engine
    # caps at 20 slots); a 40-wide pending spike must decide on device
    # with oracle parity.
    h = _cas_chain_history(40)
    p = prepare.prepare(m.cas_register(), h)
    assert p.window == 40
    r = bfs.check_packed(p)
    assert r["valid?"] is True
    assert r["analyzer"] == "tpu-bfs"
    assert cpu.check_packed(p)["valid?"] is True


def test_wide_window_40_invalid():
    # Same spike, but the final read observes a value the chain can't
    # reach — the device must find the violation, not just "unknown".
    h = _cas_chain_history(40)
    ops = list(h)
    ops[-1] = ok_op(0, "read", 999)
    p = prepare.prepare(m.cas_register(), History.of(*ops))
    assert p.window == 40
    r = bfs.check_packed(p)
    assert r["valid?"] is False
    assert r["op"]["f"] == "read"
    assert cpu.check_packed(p)["valid?"] is False


def test_window_above_64_unknown():
    h = _cas_chain_history(70)
    p = prepare.prepare(m.cas_register(), h, max_window=80)
    assert p.window == 70
    assert bfs.check_packed(p)["valid?"] == "unknown"
