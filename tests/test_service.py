"""Checker-as-a-service tests (jepsen_tpu.service).

Three layers, mirroring the subsystem's pipeline:

- Unit: shape-bin keys, the batch-decline reasons (lin.batched.Decline),
  worker batch/fault semantics via fabricated requests — no sockets,
  no device (stub check/batch fns), quick tier.
- Wire: in-process daemon over real sockets with stub device paths —
  client drop mid-request, backpressure, wedge-hook injection,
  requeue-once-then-honest-fail — quick tier.
- Device: round-trip verdict parity vs lin/cpu.py for every shipped
  model kernel, and the mixed-shape batching acceptance shape
  (occupancy > 1) — real traces, `compiles`-marked.
"""

from __future__ import annotations

import socket
import threading
import time

import pytest

# Engine modules imported at COLLECTION time: bfs/dense build tiny
# module-level jnp constants whose one-off compiles must land outside
# the quick tier's per-test no-compile window (tests/conftest.py).
import jepsen_tpu.lin.batched   # noqa: F401
import jepsen_tpu.lin.dense     # noqa: F401

pytestmark = pytest.mark.quick


def _mk_service(tmp_path, monkeypatch, **kw):
    from jepsen_tpu.service.daemon import CheckerService

    monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                       str(tmp_path / "quarantine.json"))
    kw.setdefault("stats_file", str(tmp_path / "service_stats.json"))
    kw.setdefault("flush_ms_", 10)
    return CheckerService("127.0.0.1", 0, **kw)


def _stub_check(packed, model, history):
    return {"valid?": True, "analyzer": "stub-single"}


def _stub_batch(model, subs, declines=None):
    return {rid: {"valid?": True, "analyzer": "stub-batch"}
            for rid in subs}


def _hist(n=20, concurrency=3, seed=0, **kw):
    from jepsen_tpu.lin import synth

    return synth.generate_register_history(
        n, concurrency=concurrency, seed=seed, value_range=3, **kw)


class TestBinKey:
    def test_same_shape_same_bin(self):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import prepare
        from jepsen_tpu.service.daemon import bin_key

        k1 = bin_key(prepare.prepare(m.cas_register(), _hist(seed=1)))
        k2 = bin_key(prepare.prepare(m.cas_register(), _hist(seed=2)))
        assert k1 == k2
        assert k1.startswith("svc-dense|")

    def test_shape_axes_split_bins(self):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import prepare
        from jepsen_tpu.service.daemon import bin_key

        base = bin_key(prepare.prepare(m.cas_register(), _hist(seed=1)))
        # Different kernel -> different bin.
        from jepsen_tpu.lin import synth

        mu = bin_key(prepare.prepare(m.mutex(),
                                     synth.generate_mutex_history(
                                         20, concurrency=3, seed=1)))
        assert mu != base and "mutex" in mu
        # Much longer history -> different row bucket.
        long = bin_key(prepare.prepare(m.cas_register(),
                                       _hist(n=400, seed=1)))
        assert long != base
        # Wide window -> sparse route (deterministic window-24
        # cas-chain spike, past the dense bound 20).
        from jepsen_tpu.history import History, invoke_op, ok_op

        ops = [invoke_op(0, "write", 0), ok_op(0, "write", 0)]
        ops += [invoke_op(i + 1, "cas", [i, i + 1]) for i in range(24)]
        ops += [ok_op(i + 1, "cas", [i, i + 1]) for i in range(24)]
        wide = bin_key(prepare.prepare(m.cas_register(),
                                       History.of(*ops)))
        assert wide.startswith("svc-sparse|")


class TestBatchDeclines:
    """lin.batched's structured decline reasons (the satellite): the
    service scheduler must see WHY a bin fell through, not a bare
    None."""

    def test_dense_rows_ceiling_names_axis(self, monkeypatch):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import batched, prepare

        monkeypatch.setattr(batched, "MAX_BATCH_ROWS", 4)
        packed = {k: prepare.prepare(m.cas_register(), _hist(seed=k))
                  for k in range(2)}
        d = batched._try_dense_batch(packed)
        assert isinstance(d, batched.Decline)
        assert not d                       # falsy: `or` chains keep working
        assert d.axis == "rows"
        assert "MAX" not in d.detail or d.detail  # human-readable detail
        assert d.keys == [0, 1]

    def test_no_kernel_declines_per_key(self):
        from jepsen_tpu import models as m
        from jepsen_tpu.history import History, invoke_op, ok_op
        from jepsen_tpu.lin import batched

        # A set history with a None element has no device kernel.
        h = History.of(invoke_op(0, "add", None),
                       ok_op(0, "add", None))
        declines: list = []
        res = batched.try_check_batch(m.SetModel(), {"k": h},
                                      declines=declines)
        assert res is None
        assert [d.axis for d in declines] == ["kernel"]

    def test_unpackable_history_declines(self):
        from jepsen_tpu import models as m
        from jepsen_tpu.history import History, invoke_op
        from jepsen_tpu.lin import batched

        # 70 concurrent pending invokes: window > MAX_WINDOW (64).
        h = History.of(*[invoke_op(i, "write", 1) for i in range(70)])
        declines: list = []
        res = batched.try_check_batch(m.cas_register(), {"k": h},
                                      declines=declines)
        assert res is None
        assert [d.axis for d in declines] == ["prepare"]

    def test_window_overflow_declines_group(self):
        from jepsen_tpu import models as m
        from jepsen_tpu.history import History, invoke_op, ok_op
        from jepsen_tpu.lin import batched

        # Window exactly 64 packs (MAX_WINDOW) but the sparse batch
        # needs window+1 pad slots > MAX_DEVICE_WINDOW: group declines
        # on the window axis before any device work.
        ops = [invoke_op(i, "write", 1) for i in range(64)]
        ops += [ok_op(i, "write", 1) for i in range(64)]
        declines: list = []
        res = batched.try_check_batch(m.cas_register(),
                                      {"k": History.of(*ops)},
                                      declines=declines)
        assert res is None
        assert [d.axis for d in declines] == ["window"]
        assert "dense declined" in declines[0].detail


class TestWorkerSemantics:
    """_process_batch directly, with fabricated requests — the batch/
    fallthrough/fault state machine without socket timing."""

    def _reqs(self, svc, n, out, model=None, **hist_kw):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import prepare, supervise
        from jepsen_tpu.service.daemon import Request, bin_key

        model = model or m.cas_register()
        reqs = []
        for i in range(n):
            h = _hist(seed=i, **hist_kw)
            p = prepare.prepare(model, h)
            reqs.append(Request(
                rid=i, model_name="cas-register", model=model,
                history=h, packed=p, bin=bin_key(p),
                fingerprint=supervise.history_fingerprint(p),
                respond=lambda msg, i=i: out.append((i, msg))))
        return reqs

    def test_same_bin_decides_as_one_batch(self, tmp_path,
                                           monkeypatch):
        calls = []

        def batch_fn(model, subs, declines=None):
            calls.append(dict(subs))
            return _stub_batch(model, subs)

        svc = _mk_service(tmp_path, monkeypatch, batch_fn=batch_fn,
                          check_fn=_stub_check)
        out: list = []
        svc._process_batch(self._reqs(svc, 4, out))
        assert len(calls) == 1, "one vmapped program for the bin"
        assert len(out) == 4
        assert all(msg["result"]["analyzer"] == "stub-batch"
                   for _i, msg in out)
        assert all(msg["timings"]["batch_n"] >= 4 for _i, msg in out)
        st = svc.stats()
        assert st["batches"] == 1 and st["batched_requests"] == 4
        assert st["max_occupancy"] == 4 and st["avg_occupancy"] == 4

    def test_colliding_client_rids_both_answered(self, tmp_path,
                                                 monkeypatch):
        # Two clients' auto-ids collide routinely (each instance
        # counts 1, 2, ...): two same-bin requests with EQUAL rids but
        # different histories must both decide — the batch is keyed by
        # fingerprint, never by the client-chosen rid.
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import prepare, supervise
        from jepsen_tpu.service.daemon import Request, bin_key

        def batch_fn(model, subs, declines=None):
            return {fp: {"valid?": True, "analyzer": "stub-batch",
                         "fp": fp} for fp in subs}

        svc = _mk_service(tmp_path, monkeypatch, batch_fn=batch_fn,
                          check_fn=_stub_check)
        out: list = []
        model = m.cas_register()
        reqs = []
        for i in range(2):
            h = _hist(seed=i)          # different histories...
            p = prepare.prepare(model, h)
            reqs.append(Request(
                rid=1,                 # ...same client-chosen rid
                model_name="cas-register", model=model, history=h,
                packed=p, bin=bin_key(p),
                fingerprint=supervise.history_fingerprint(p),
                respond=lambda msg, i=i: out.append((i, msg))))
        assert reqs[0].bin == reqs[1].bin
        assert reqs[0].fingerprint != reqs[1].fingerprint
        svc._process_batch(reqs)
        assert len(out) == 2, "a rid collision must not drop a request"
        # Each got ITS OWN history's verdict, not the collision twin's.
        answered_fps = {msg["result"]["fp"] for _i, msg in out}
        assert answered_fps == {r.fingerprint for r in reqs}

    def test_batch_pads_key_axis_to_pow2(self, tmp_path, monkeypatch):
        seen = {}

        def batch_fn(model, subs, declines=None):
            seen["n"] = len(subs)
            return _stub_batch(model, subs)

        svc = _mk_service(tmp_path, monkeypatch, batch_fn=batch_fn,
                          check_fn=_stub_check)
        out: list = []
        svc._process_batch(self._reqs(svc, 5, out))
        assert seen["n"] == 8, "key axis padded 5 -> 8 (zero retrace)"
        assert len(out) == 5   # pad keys never answered
        assert svc.stats()["pad_keys"] == 3

    def test_batch_decline_falls_through_with_reason(self, tmp_path,
                                                     monkeypatch):
        from jepsen_tpu.lin.batched import Decline

        def batch_fn(model, subs, declines=None):
            declines.append(Decline("window", "too wide",
                                    keys=list(subs)))
            return None

        svc = _mk_service(tmp_path, monkeypatch, batch_fn=batch_fn,
                          check_fn=_stub_check)
        out: list = []
        svc._process_batch(self._reqs(svc, 3, out))
        assert len(out) == 3
        assert all(msg["result"]["analyzer"] == "stub-single"
                   for _i, msg in out)
        st = svc.stats()
        assert st["decline_axes"] == {"window": 4}  # padded to 4 keys
        assert st["single_requests"] == 3
        assert st.get("batches") is None or st["batches"] == 0

    def test_batch_fault_requeues_once_as_singles(self, tmp_path,
                                                  monkeypatch):
        def batch_fn(model, subs, declines=None):
            raise RuntimeError("kernel fault")

        svc = _mk_service(tmp_path, monkeypatch, batch_fn=batch_fn,
                          check_fn=_stub_check)
        out: list = []
        reqs = self._reqs(svc, 3, out)
        svc._process_batch(reqs)
        # Nothing answered yet: every request rode its one requeue.
        assert out == []
        requeued = []
        while not svc._queue.empty():
            requeued.append(svc._queue.get_nowait())
        assert len(requeued) == 3
        assert all(r.attempts == 1 and r.no_batch for r in requeued)
        assert svc.stats()["requeues"] == 3
        # The requeued batch goes down the SINGLES path (off the
        # suspect batch program) and decides.
        svc._process_batch(requeued)
        assert len(out) == 3
        assert all(msg["result"]["analyzer"] == "stub-single"
                   for _i, msg in out)

    def test_second_fault_fails_honestly(self, tmp_path, monkeypatch):
        def bad_check(packed, model, history):
            raise RuntimeError("still faulting")

        def batch_fn(model, subs, declines=None):
            raise RuntimeError("kernel fault")

        svc = _mk_service(tmp_path, monkeypatch, batch_fn=batch_fn,
                          check_fn=bad_check)
        out: list = []
        svc._process_batch(self._reqs(svc, 2, out))
        requeued = []
        while not svc._queue.empty():
            requeued.append(svc._queue.get_nowait())
        svc._process_batch(requeued)
        assert len(out) == 2
        for _i, msg in out:
            assert msg["result"]["valid?"] == "unknown"
            assert msg["result"]["overflow"] == "fault"
        assert svc.stats()["honest_fails"] == 2

    def test_fault_records_bin_shape_in_ledger(self, tmp_path,
                                               monkeypatch):
        from jepsen_tpu.lin import supervise

        def batch_fn(model, subs, declines=None):
            raise RuntimeError("kernel fault")

        svc = _mk_service(tmp_path, monkeypatch, batch_fn=batch_fn,
                          check_fn=_stub_check)
        out: list = []
        reqs = self._reqs(svc, 2, out)
        svc._process_batch(reqs)
        ledger = supervise.load_ledger()
        assert reqs[0].bin in ledger
        assert ledger[reqs[0].bin]["reason"] == "fault"


class TestWire:
    """Real sockets, stub device paths."""

    def _start(self, tmp_path, monkeypatch, **kw):
        kw.setdefault("check_fn", _stub_check)
        kw.setdefault("batch_fn", _stub_batch)
        svc = _mk_service(tmp_path, monkeypatch, **kw).start()
        return svc

    def test_round_trip_and_stats(self, tmp_path, monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        svc = self._start(tmp_path, monkeypatch)
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            assert c.ping()
            r = c.submit("cas-register", _hist())
            assert r["valid?"] is True
            assert r["_timings"]["batch_n"] >= 1
            st = c.stats()
            assert st["submitted"] == 1 and st["decided"] == 1
            c.close()
        finally:
            svc.stop()

    def test_unknown_model_is_error_not_crash(self, tmp_path,
                                              monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        svc = self._start(tmp_path, monkeypatch)
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            r = c.submit("no-such-model", _hist())
            assert r["valid?"] == "unknown"
            assert "unknown model" in r["error"]
            # The daemon is still serving.
            assert c.submit("cas-register", _hist())["valid?"] is True
            c.close()
        finally:
            svc.stop()

    def test_client_drop_mid_request_daemon_survives(self, tmp_path,
                                                     monkeypatch):
        from jepsen_tpu.service import protocol
        from jepsen_tpu.service.protocol import CheckerClient
        from jepsen_tpu.suites.common import SocketIO

        decided = threading.Event()

        def slow_check(packed, model, history):
            time.sleep(0.3)
            decided.set()
            return {"valid?": True, "analyzer": "stub-single"}

        svc = self._start(tmp_path, monkeypatch, check_fn=slow_check,
                          batch_fn=lambda m, s, declines=None: None)
        try:
            # Raw wire client: submit, then DROP before the verdict.
            io = SocketIO(socket.create_connection(
                ("127.0.0.1", svc.port), timeout=5))
            protocol.send_msg(io, {
                "type": "check", "id": 1, "model": "cas-register",
                "history": protocol.history_to_wire(_hist())})
            io.close()
            assert decided.wait(10), "daemon must still decide"
            # The daemon survived: a fresh client round-trips, and the
            # dropped reply is visible in stats, not a crash.
            c = CheckerClient("127.0.0.1", svc.port)
            assert c.submit("cas-register", _hist())["valid?"] is True
            deadline = time.time() + 5
            while time.time() < deadline:
                if c.stats().get("dropped_responses", 0) >= 1:
                    break
                time.sleep(0.05)
            assert c.stats()["dropped_responses"] >= 1
            c.close()
        finally:
            svc.stop()

    def test_backpressure_overload_response(self, tmp_path,
                                            monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        gate = threading.Event()

        def gated_check(packed, model, history):
            gate.wait(10)
            return {"valid?": True, "analyzer": "stub-single"}

        svc = self._start(tmp_path, monkeypatch, bound=1,
                          check_fn=gated_check,
                          batch_fn=lambda m, s, declines=None: None,
                          flush_ms_=5)
        try:
            results: dict = {}

            def submit(tag):
                c = CheckerClient("127.0.0.1", svc.port)
                results[tag] = c.submit("cas-register", _hist())
                c.close()

            threads = [threading.Thread(target=submit, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
                time.sleep(0.05)
            # With the worker gated and bound=1, some submits must see
            # the overload answer immediately (not hang).
            deadline = time.time() + 5
            while time.time() < deadline and not any(
                    "overload" in str(r.get("error", ""))
                    for r in results.values()):
                time.sleep(0.05)
            gate.set()
            for t in threads:
                t.join(10)
            assert any("overload" in str(r.get("error", ""))
                       for r in results.values())
            assert all(r["valid?"] in (True, "unknown")
                       for r in results.values())
        finally:
            gate.set()
            svc.stop()

    def test_wedge_hook_costs_bin_not_daemon(self, tmp_path,
                                             monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        # The supervise injection fake-wedges the NEXT service-check
        # dispatch (0.2 s injected deadline); retries=0 at the service
        # site => honest `overflow: wedge` unknown for that request,
        # and the daemon keeps serving.
        monkeypatch.setenv("JEPSEN_TPU_WEDGE", "service-check:1:0.2")
        svc = self._start(tmp_path, monkeypatch, deadline_s=0.2,
                          batch_fn=lambda m, s, declines=None: None)
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            r = c.submit("cas-register", _hist())
            assert r["valid?"] == "unknown"
            assert r["overflow"] == "wedge"
            # Injection consumed: the next request decides normally.
            assert c.submit("cas-register", _hist())["valid?"] is True
            st = c.stats()
            assert st["wedged_requests"] == 1
            assert st["watchdog_trips"] >= 1
            c.close()
        finally:
            svc.stop()

    def test_shutdown_message_stops_daemon(self, tmp_path,
                                           monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        svc = self._start(tmp_path, monkeypatch)
        c = CheckerClient("127.0.0.1", svc.port)
        assert c.submit("cas-register", _hist())["valid?"] is True
        c.shutdown()
        c.close()
        deadline = time.time() + 10
        while time.time() < deadline and not svc._stop.is_set():
            time.sleep(0.05)
        assert svc._stop.is_set()
        svc.stop()   # idempotent
        # Stats snapshot written at stop (the /service page's source).
        import json

        snap = json.loads((tmp_path / "service_stats.json").read_text())
        assert "submitted" in snap and "addr" in snap


class TestServiceWebAndCli:
    def test_web_service_page_renders_snapshot(self, tmp_path):
        import json
        import urllib.request

        from jepsen_tpu import web

        stats = tmp_path / "service_stats.json"
        stats.write_text(json.dumps(
            {"submitted": 7, "avg_occupancy": 3.5,
             "bin_depths": {"svc-dense|rows32|cap8|w4|cas-register": 2}}))
        srv = web.make_server(host="127.0.0.1", port=0,
                              base=str(tmp_path),
                              stats_file=str(stats))
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/service"
            with urllib.request.urlopen(url, timeout=5) as r:
                body = r.read().decode()
            assert "avg_occupancy" in body and "3.5" in body
            assert "svc-dense|rows32|cap8|w4|cas-register" in body
            # Home page links to it.
            with urllib.request.urlopen(
                    url.rsplit("/", 1)[0] + "/", timeout=5) as r:
                assert "/service" in r.read().decode()
        finally:
            srv.shutdown()

    def test_web_service_page_without_snapshot(self, tmp_path):
        from jepsen_tpu import web

        html = web.service_html(str(tmp_path / "missing.json"))
        assert "no stats snapshot" in html

    def test_cli_service_stats_snapshot_fallback(self, tmp_path,
                                                 capsys):
        import json

        from jepsen_tpu import cli

        snap = tmp_path / "stats.json"
        snap.write_text(json.dumps({"submitted": 3}))
        rc = cli.run(cli.standard_commands(),
                     ["service-stats", "--file", str(snap)])
        assert rc == cli.EXIT_OK
        out = json.loads(capsys.readouterr().out)
        assert out["source"] == "snapshot"
        assert out["stats"]["submitted"] == 3

    def test_cli_registry_names_and_help(self):
        from jepsen_tpu import cli

        names = [c["name"] for c in cli.standard_commands()]
        assert "serve" in names and "serve-checker" in names
        assert "service-stats" in names and "quarantine" in names
        # The two daemons disambiguate each other in their help text.
        by_name = {c["name"]: c for c in cli.standard_commands()}
        assert "serve-checker" in by_name["serve"]["help"]
        assert "daemon" in by_name["serve-checker"]["help"]
        # Suite command sets carry the registry too.
        suite = [c["name"] for c in cli.suite_commands(lambda o: o)]
        assert "serve-checker" in suite and "quarantine" in suite


@pytest.mark.compiles
class TestDeviceParity:
    """Real engines on the CPU mesh: wire round-trip verdict parity vs
    the lin/cpu.py oracle for every shipped model kernel family, and
    the mixed-shape acceptance shape (>=100 histories, occupancy > 1)."""

    def _cases(self):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import synth

        return [
            ("cas-register", m.cas_register,
             _hist(n=30, seed=1, crash_prob=0.05, max_crashes=2)),
            ("register", m.register,
             synth.corrupt_history(
                 _hist(n=24, seed=2, fs=("read", "write")), seed=2)),
            ("mutex", m.mutex,
             synth.generate_mutex_history(24, concurrency=3, seed=3)),
            ("set", m.set_model,
             synth.generate_set_history(24, concurrency=3, seed=4)),
            ("unordered-queue", m.unordered_queue,
             synth.generate_queue_history(24, concurrency=3, seed=5)),
            ("fifo-queue", m.fifo_queue,
             synth.generate_queue_history(24, concurrency=3, seed=6,
                                          fifo=True)),
        ]

    def test_round_trip_parity_every_kernel(self, tmp_path,
                                            monkeypatch):
        from jepsen_tpu.lin import cpu, prepare
        from jepsen_tpu.service.protocol import CheckerClient

        svc = _mk_service(tmp_path, monkeypatch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            for name, factory, h in self._cases():
                want = cpu.check_packed(
                    prepare.prepare(factory(), h))["valid?"]
                got = c.submit(name, h)
                assert got["valid?"] == want, (name, got)
            c.close()
        finally:
            svc.stop()

    def test_hundred_mixed_histories_batch_with_parity(self, tmp_path,
                                                       monkeypatch):
        """The ISSUE acceptance shape: >=100 queued mixed-shape
        histories, verdicts parity-equal to lin/cpu.py, same-shape
        bins demonstrably batched (occupancy > 1)."""
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import cpu, prepare, synth
        from jepsen_tpu.service.protocol import CheckerClient

        jobs = []
        for i in range(84):
            jobs.append(("cas-register", m.cas_register,
                         _hist(n=24, seed=100 + i, crash_prob=0.02,
                               max_crashes=2)))
        for i in range(12):
            jobs.append(("mutex", m.mutex,
                         synth.generate_mutex_history(
                             20, concurrency=3, seed=i)))
        for i in range(8):
            h = _hist(n=24, seed=200 + i, fs=("read", "write"))
            if i % 2:
                h = synth.corrupt_history(h, seed=i)
            jobs.append(("register", m.register, h))
        assert len(jobs) >= 100

        svc = _mk_service(tmp_path, monkeypatch, flush_ms_=40).start()
        results: dict = {}
        lock = threading.Lock()
        it = iter(list(enumerate(jobs)))

        def client_loop():
            c = CheckerClient("127.0.0.1", svc.port)
            while True:
                with lock:
                    nxt = next(it, None)
                if nxt is None:
                    break
                i, (name, _f, h) = nxt
                r = c.submit(name, h, req_id=i)
                with lock:
                    results[i] = r
            c.close()

        try:
            threads = [threading.Thread(target=client_loop)
                       for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(300)
            stats_client = CheckerClient("127.0.0.1", svc.port)
            st = stats_client.stats()
            stats_client.close()
        finally:
            svc.stop()

        assert len(results) == len(jobs)
        for i, (name, factory, h) in enumerate(jobs):
            want = cpu.check_packed(
                prepare.prepare(factory(), h))["valid?"]
            assert results[i]["valid?"] == want, (i, name, results[i])
        # Same-shape bins demonstrably batched.
        assert st["batches"] >= 1
        assert st["max_occupancy"] > 1, st
        assert st["avg_occupancy"] > 1, st
