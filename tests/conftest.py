"""Test config: force an 8-device virtual CPU mesh BEFORE any backend init.

Multi-chip hardware is not available in CI; sharding tests run on XLA's
forced host platform device count (the same mechanism the driver's
multichip dryrun uses). The TPU plugin in this image force-selects its own
platform via jax config at interpreter start, so the env var alone is not
enough — we must override the config after importing jax, before any
jax.devices()/jit call initializes a backend. conftest is imported by pytest
before all test modules, which guarantees that ordering.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the engines' per-shape programs are
# identical across test runs; caching cuts suite time dramatically.
from jepsen_tpu.util import (  # noqa: E402
    compile_meter,
    enable_compile_cache,
    install_compile_meter,
)

enable_compile_cache()

# Device-resident packing (lin/pack_dev.py) defaults OFF under pytest:
# the daemon's admission tier and the stream settle would otherwise
# compile their (tiny, cached) pack programs inside quick-marked
# service/stream tests — a cold .jax_cache would break the quick
# tier's no-compile promise. The runtime default stays ON
# (doc/env.md § JEPSEN_TPU_PACK_DEV); device-packer coverage lives in
# the compiles-marked tests/test_pack_dev.py (which re-enables it) and
# the chip-free smokes (pack/serve/fleet/stream), which run with the
# offload on.
os.environ.setdefault("JEPSEN_TPU_PACK_DEV", "0")

# --- quick-tier no-compile enforcement --------------------------------------
# The quick tier's promise (pyproject marker, CLAUDE.md) is "no XLA
# compiles": ~1 min wall even on one core. Every true backend compile
# (a persistent-cache MISS reaching XLA — cache hits load in
# milliseconds and keep the promise) is counted by the SHARED
# process-wide meter (util.install_compile_meter — the same wrap the
# checker daemon's stats and the obs registry read), and a
# `quick`-marked test that triggers one FAILS unless it carries the
# registered `compiles` marker (the handful of quick engine tests that
# intentionally compile tiny .jax_cache-resident programs).
# JEPSEN_TPU_QUICK_NO_COMPILE=0 disables;
# JEPSEN_TPU_QUICK_COMPILE_REPORT=1 reports instead of failing (used
# to find offenders).

import pytest  # noqa: E402

install_compile_meter()


@pytest.fixture(autouse=True)
def _quick_no_compile(request):
    before = compile_meter()["xla_compiles"]
    yield
    compiled = compile_meter()["xla_compiles"] - before
    if not compiled:
        return
    if request.node.get_closest_marker("quick") is None:
        return
    if request.node.get_closest_marker("compiles") is not None:
        return
    if os.environ.get("JEPSEN_TPU_QUICK_NO_COMPILE", "1") == "0":
        return
    msg = (f"quick-tier test triggered {compiled} XLA compile(s): the "
           "-m quick tier promises no compiles (CLAUDE.md). Either "
           "shrink the test below compile thresholds, drop the quick "
           "marker, or — for a test that deliberately compiles tiny "
           "cached programs — add @pytest.mark.compiles.")
    if os.environ.get("JEPSEN_TPU_QUICK_COMPILE_REPORT") == "1":
        print(f"\n[quick-compile] {request.node.nodeid}: {msg}")
        return
    pytest.fail(msg)
