"""Test config: force an 8-device virtual CPU mesh BEFORE any backend init.

Multi-chip hardware is not available in CI; sharding tests run on XLA's
forced host platform device count (the same mechanism the driver's
multichip dryrun uses). The TPU plugin in this image force-selects its own
platform via jax config at interpreter start, so the env var alone is not
enough — we must override the config after importing jax, before any
jax.devices()/jit call initializes a backend. conftest is imported by pytest
before all test modules, which guarantees that ordering.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the engines' per-shape programs are
# identical across test runs; caching cuts suite time dramatically.
from jepsen_tpu.util import enable_compile_cache  # noqa: E402

enable_compile_cache()
