"""Flight recorder (jepsen_tpu/obs/ — doc/observability.md): the span
tracer's disabled/enabled contracts, nesting and thread safety, the
Chrome trace-event export, the metrics registry snapshot round trip,
the attribution report, and the supervise-layer integration — plus the
JEPSEN_TPU_WEDGE e2e asserting the wedge/retry/fallback ladder shows
up as dispatch spans with the right outcomes.

The unit tests are pure host Python (quick, no XLA); the e2e ladder
and parity tests drive the real engines on tiny .jax_cache-resident
shapes and carry the registered ``compiles`` marker (the
test_lin_supervise precedent)."""

import json
import os
import threading
import time

import pytest

from jepsen_tpu import util
from jepsen_tpu.obs import metrics, report, trace

pytestmark = pytest.mark.quick


@pytest.fixture(autouse=True)
def _obs_sandbox(monkeypatch):
    """Tracing off, no spill file, no telemetry snapshot file — every
    test opts in explicitly and leaves no state behind."""
    monkeypatch.delenv("JEPSEN_TPU_TRACE", raising=False)
    monkeypatch.setenv("JEPSEN_TPU_TRACE_FILE", "0")
    monkeypatch.setenv("JEPSEN_TPU_OBS_SNAPSHOT", "0")
    trace.reset()
    metrics.REGISTRY.reset()
    yield
    trace.reset()
    metrics.REGISTRY.reset()


def _on(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")


# --- tracer: disabled path --------------------------------------------------


def test_disabled_span_is_one_shared_null_object(monkeypatch):
    # The disabled-path promise (doc/observability.md): span() returns
    # the SAME object every call — no per-span allocation, no buffer
    # write. Identity is the allocation-free proof.
    assert not trace.enabled()
    s1 = trace.span("a", site="x")
    s2 = trace.span("b")
    assert s1 is s2 is trace.NULL_SPAN
    with s1 as sp:
        sp.note(outcome="ok")
    trace.instant("i", x=1)
    trace.complete("c", 0.0, 1.0)
    trace.tail_note(x=2)
    assert trace.events() == []


def test_disabled_span_overhead_is_flat(monkeypatch):
    # 100k disabled spans must stay far under any engine-visible cost
    # (the quick tier's "no measurable slowdown" acceptance bar —
    # generous bound so a loaded CI box cannot flake it).
    t0 = time.monotonic()
    for _ in range(100_000):
        with trace.span("x"):
            pass
    assert time.monotonic() - t0 < 2.0
    assert trace.events() == []


# --- tracer: enabled spans --------------------------------------------------


def test_span_records_event_with_args(monkeypatch):
    _on(monkeypatch)
    with trace.span("dispatch", site="chunk", shape="chunk|cap8") as sp:
        sp.note(outcome="ok", passes=3)
    evs = trace.events()
    assert len(evs) == 1
    ev = evs[0]
    assert ev["name"] == "dispatch" and ev["ph"] == "X"
    assert ev["dur"] >= 0 and ev["ts"] > 0
    assert ev["args"] == {"site": "chunk", "shape": "chunk|cap8",
                          "outcome": "ok", "passes": 3}


def test_span_exception_stamps_error_outcome(monkeypatch):
    _on(monkeypatch)
    with pytest.raises(ValueError):
        with trace.span("dispatch", site="chunk"):
            raise ValueError("boom")
    ev = trace.events()[0]
    assert ev["args"]["outcome"] == "error:ValueError"
    # A site-noted outcome wins over the exception stamp.
    with pytest.raises(RuntimeError):
        with trace.span("dispatch", site="chunk") as sp:
            sp.note(outcome="fault")
            raise RuntimeError("worker died")
    assert trace.events()[1]["args"]["outcome"] == "fault"


def test_span_nesting_depth(monkeypatch):
    _on(monkeypatch)
    with trace.span("check"):
        with trace.span("dispatch"):
            pass
    inner, outer = trace.events()
    assert inner["name"] == "dispatch" and inner["depth"] == 1
    assert outer["name"] == "check" and outer["depth"] == 0


def test_tail_note_annotates_last_completed_event(monkeypatch):
    _on(monkeypatch)
    with trace.span("dispatch", site="host-fixpoint"):
        pass
    trace.tail_note(row=7, count=130)
    ev = trace.events()[0]
    assert ev["args"]["row"] == 7 and ev["args"]["count"] == 130


def test_thread_safety_every_span_lands_once(monkeypatch):
    _on(monkeypatch)
    n_threads, n_spans = 8, 200
    errs: list = []

    def work(tid):
        try:
            for i in range(n_spans):
                with trace.span("dispatch", site=f"t{tid}") as sp:
                    sp.note(i=i)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=work, args=(k,))
          for k in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    evs = trace.events()
    assert len(evs) == n_threads * n_spans
    for k in range(n_threads):
        mine = [e for e in evs if e["args"].get("site") == f"t{k}"]
        assert sorted(e["args"]["i"] for e in mine) == list(range(n_spans))
        # Each thread's spans never nested: depth stays 0.
        assert all(e["depth"] == 0 for e in mine)


def test_ring_buffer_drops_oldest_without_spill_file(monkeypatch):
    _on(monkeypatch)
    monkeypatch.setenv("JEPSEN_TPU_TRACE_BUF", "16")
    for i in range(50):
        trace.instant("tick", i=i)
    evs = trace.events()
    assert len(evs) == 16
    assert [e["args"]["i"] for e in evs] == list(range(34, 50))


def test_spill_file_keeps_everything_and_flushes(tmp_path, monkeypatch):
    _on(monkeypatch)
    spill = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("JEPSEN_TPU_TRACE_FILE", spill)
    for i in range(10):
        with trace.span("dispatch", site="chunk") as sp:
            sp.note(i=i)
    assert trace.flush() == spill
    loaded = report.load(spill)
    assert [e["args"]["i"] for e in loaded] == list(range(10))
    # A killed run's torn last line is skipped, not fatal.
    with open(spill, "a") as fh:
        fh.write('{"name": "torn", "ph"')
    assert len(report.load(spill)) == 10
    # reset + a new run truncates: one process/run per file.
    trace.reset()
    trace.instant("fresh")
    trace.flush()
    loaded = report.load(spill)
    assert len(loaded) == 1 and loaded[0]["name"] == "fresh"


def test_spill_batch_keeps_tail_for_late_notes(tmp_path, monkeypatch):
    # The batch spill leaves the newest _SPILL_KEEP events in memory
    # so an after-the-fact tail_note still reaches the file copy; the
    # final flush writes everything exactly once.
    _on(monkeypatch)
    spill = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("JEPSEN_TPU_TRACE_FILE", spill)
    for i in range(trace._SPILL_BATCH):
        trace.instant("tick", i=i)
    assert trace.spilled() == trace._SPILL_BATCH - trace._SPILL_KEEP
    trace.tail_note(late=True)
    trace.flush()
    loaded = report.load(spill)
    assert len(loaded) == trace._SPILL_BATCH
    assert [e["args"]["i"] for e in loaded] == list(
        range(trace._SPILL_BATCH))
    assert loaded[-1]["args"]["late"] is True


def test_spill_failure_latches_to_in_memory_ring(tmp_path, monkeypatch):
    # An unwritable spill path must degrade ONCE to the ring (the
    # _file_dead latch) — not re-serialize the whole backlog on every
    # later record under the tracer lock.
    _on(monkeypatch)
    blocker = tmp_path / "blocker"
    blocker.write_text("")          # a FILE where a directory must go
    monkeypatch.setenv("JEPSEN_TPU_TRACE_FILE",
                       str(blocker / "trace.jsonl"))
    monkeypatch.setenv("JEPSEN_TPU_TRACE_BUF", "128")
    for i in range(trace._SPILL_BATCH + 200):
        trace.instant("tick", i=i)
    assert trace._file_dead is True
    assert trace.spilled() == 0
    evs = trace.events()
    assert len(evs) == 128          # the ring bound, newest kept
    assert evs[-1]["args"]["i"] == trace._SPILL_BATCH + 199


# --- chrome export ----------------------------------------------------------


def _chrome_is_structurally_valid(chrome):
    assert isinstance(chrome, dict)
    evs = chrome["traceEvents"]
    assert isinstance(evs, list) and evs
    for ev in evs:
        assert ev["ph"] in ("X", "i")
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int) and 0 <= ev["tid"] < 2**31
        assert isinstance(ev["name"], str)
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        else:
            assert ev["s"] == "t"
    # The whole document must survive a JSON round trip (what
    # Perfetto actually parses).
    assert json.loads(json.dumps(chrome))["traceEvents"]


def test_chrome_export_valid_and_rebased(monkeypatch):
    _on(monkeypatch)
    with trace.span("check", engine="sparse"):
        with trace.span("dispatch", site="chunk") as sp:
            sp.note(outcome="ok")
        trace.instant("wasted-rung", cap=8, seconds=0.1)
    chrome = report.to_chrome(trace.events())
    _chrome_is_structurally_valid(chrome)
    # Rebased to t=0 in MICROseconds; site folded into the name.
    assert min(e["ts"] for e in chrome["traceEvents"]) == 0.0
    names = {e["name"] for e in chrome["traceEvents"]}
    assert "dispatch:chunk" in names and "check" in names


# --- attribution ------------------------------------------------------------


def _ev(name, dur, ph="X", **args):
    return {"name": name, "ph": ph, "ts": 1.0, "dur": dur,
            "pid": 1, "tid": 1, "depth": 0, "args": args}


def test_attribution_aggregates_sites_caps_and_waste():
    evs = [
        _ev("check", 10.0, engine="sparse"),
        _ev("dispatch", 2.0, site="chunk", outcome="ok",
            shape="chunk|rows512|cap8|w34|cas-register"),
        _ev("dispatch", 3.0, site="chunk", outcome="ok",
            shape="chunk|rows512|cap64|w34|cas-register"),
        _ev("dispatch", 1.0, site="host-fixpoint", outcome="wedge",
            shape="host-fixpoint|cap4096|w34|cas-register"),
        _ev("xla-compile", 1.5),
        _ev("wasted-rung", 0.0, ph="i", cap=8, seconds=0.4),
        _ev("host-episode", 2.5, row=10),
    ]
    agg = report.attribution(evs)
    assert agg["total_s"] == 10.0 and agg["checks"] == 1
    assert agg["dispatch_s"] == 6.0 and agg["dispatches"] == 3
    assert agg["compile_s"] == 1.5 and agg["compiles"] == 1
    # Wasted = the wedged dispatch's wall + the wasted-rung instant.
    assert agg["wasted_s"] == pytest.approx(1.4)
    assert agg["wasted_events"] == 2
    chunk = agg["sites"]["chunk"]
    assert chunk["n"] == 2 and chunk["ok"] == 2
    assert chunk["caps"] == {8: 2.0, 64: 3.0}
    hf = agg["sites"]["host-fixpoint"]
    assert hf["wedge"] == 1 and hf["caps"] == {4096: 1.0}
    # Tunnel estimate: dispatches x the ~100ms lore constant; the
    # device-busy estimate is the remainder.
    assert agg["tunnel_overhead_est_s"] == pytest.approx(
        3 * report.TUNNEL_S_PER_DISPATCH)
    assert agg["device_busy_est_s"] == pytest.approx(
        6.0 - 3 * report.TUNNEL_S_PER_DISPATCH)
    # host/other closes the books: sites + host_other == check wall.
    assert agg["host_other_s"] == pytest.approx(10.0 - 6.0)
    assert agg["dispatch_s"] + agg["host_other_s"] == pytest.approx(
        agg["total_s"])
    # Non-dispatch spans surface under "other".
    assert agg["other"]["host-episode"] == {"n": 1, "wall_s": 2.5}


def test_render_and_summary():
    evs = [_ev("check", 5.0),
           _ev("dispatch", 2.0, site="chunk", outcome="ok",
               shape="chunk|cap8|w20|k")]
    agg = report.attribution(evs)
    text = report.render(agg)
    assert "check wall total" in text
    assert "chunk" in text and "tunnel overhead est" in text
    s = report.summary(evs)
    assert s["total_s"] == 5.0 and s["site_s"] == {"chunk": 2.0}
    assert "dispatch_s" in s and "compile_s" in s


def test_attribution_episode_dispatch_histogram():
    # The per-episode dispatch histogram (ISSUE 14 satellite): bfs
    # stamps host-stats dispatch/row deltas on each host-episode
    # span; attribution buckets dispatches/episode so the episode
    # scheduler's drop reads straight off a probe-config5 trace.
    evs = [
        _ev("check", 10.0),
        _ev("host-episode", 2.0, row=0, dispatches=1, rows=30),
        _ev("host-episode", 2.0, row=30, dispatches=2, rows=32),
        _ev("host-episode", 2.0, row=62, dispatches=9, rows=12),
    ]
    agg = report.attribution(evs)
    ep = agg["episodes"]
    assert ep["n"] == 3 and ep["dispatches"] == 12
    assert ep["rows"] == 74
    assert ep["dispatches_per_episode"] == 4.0
    assert ep["rows_per_dispatch"] == round(74 / 12, 2)
    assert ep["histogram"] == {"1": 1, "2-3": 1, "8-15": 1}
    text = report.render(agg)
    assert "host episodes" in text and "dispatches/episode" in text
    # Episodes WITHOUT the deltas (pre-ISSUE-14 traces) keep the old
    # "other" row and no episodes block.
    agg2 = report.attribution([_ev("check", 1.0),
                               _ev("host-episode", 0.5, row=0)])
    assert "episodes" not in agg2
    assert agg2["other"]["host-episode"]["n"] == 1


# --- metrics registry -------------------------------------------------------


def test_registry_views_are_live_references():
    stats = {"rows": 0}
    metrics.REGISTRY.view("host-stats", stats)
    stats["rows"] = 7
    snap = metrics.REGISTRY.snapshot()
    assert snap["views"]["host-stats"]["rows"] == 7
    # Re-registering swaps the reference (a fresh check run).
    metrics.REGISTRY.view("host-stats", {"rows": 1})
    assert metrics.REGISTRY.snapshot()["views"]["host-stats"]["rows"] == 1


def test_registry_progress_rates_and_eta():
    r = metrics.REGISTRY
    r.start_run("lin-sparse", total=100, window=34)
    r._samples.append((0.0, 0, 10))      # pin elapsed for determinism
    r._samples.append((2.0, 40, 500))
    snap = r.snapshot()
    assert snap["run"]["total_rows"] == 100
    assert snap["run"]["rows_per_sec"] == pytest.approx(20.0)
    assert snap["run"]["eta_s"] == pytest.approx(3.0)
    assert snap["samples"][-1] == [2.0, 40, 500]


def test_registry_event_feed_is_bounded():
    for i in range(metrics.MAX_EVENTS + 10):
        metrics.REGISTRY.event("wedge", site="chunk", i=i)
    evs = metrics.REGISTRY.snapshot()["events"]
    assert len(evs) == metrics.MAX_EVENTS
    assert evs[-1]["i"] == metrics.MAX_EVENTS + 9
    assert evs[0]["kind"] == "wedge" and evs[0]["site"] == "chunk"


def test_registry_snapshot_round_trip(tmp_path, monkeypatch):
    path = str(tmp_path / "telemetry.json")
    r = metrics.REGISTRY
    r.start_run("lin-sparse", total=50)
    r.view("host-stats", {"rows": 3, "cap_seconds": {8: 1.234567}})
    r.counter("ticks", 2)
    r.gauge("row", 3)
    r.event("quarantine", key="chunk|cap8")
    r.write_snapshot(path=path, force=True)
    snap, err = metrics.load_json_snapshot(path)
    assert err is None
    assert snap["run"]["run"] == "lin-sparse"
    assert snap["run"]["row"] == 3
    # round_stats flowed through the codec (3 digits, nested).
    assert snap["views"]["host-stats"]["cap_seconds"] == {"8": 1.235}
    # Every event bumps a durable event_<kind> counter (the ring
    # holds MAX_EVENTS; the counter survives eviction).
    assert snap["counters"] == {"ticks": 2, "event_quarantine": 1}
    assert snap["events"][0]["key"] == "chunk|cap8"
    assert "xla_compiles" in snap


def test_snapshot_first_write_is_interval_gated(tmp_path, monkeypatch):
    # The "short runs and tests write nothing" promise includes the
    # FIRST write: a run younger than JEPSEN_TPU_OBS_EVERY_S must not
    # touch disk (force=True remains the explicit override).
    path = str(tmp_path / "telemetry.json")
    monkeypatch.setenv("JEPSEN_TPU_OBS_SNAPSHOT", path)
    r = metrics.REGISTRY
    r.start_run("lin-sparse", total=10)
    r.progress(row=1, frontier=5)
    assert not os.path.exists(path)
    r.write_snapshot(force=True)
    assert os.path.exists(path)


def test_load_json_snapshot_error_paths(tmp_path):
    snap, err = metrics.load_json_snapshot(str(tmp_path / "missing"))
    assert snap is None and err
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    snap, err = metrics.load_json_snapshot(str(bad))
    assert snap is None and err


# --- util satellites --------------------------------------------------------


def test_round_stats_recurses_and_preserves_non_numeric():
    # Satellite fix: round_stats used to round only ONE level deep and
    # silently mangled deeper nests; it must now recurse through any
    # depth and preserve every non-float value.
    stats = {
        "wall": 1.23456,
        "n": 7,
        "cap_seconds": {8: 0.123456, 4096: 2.999999},
        "tiers": {"ww": {"edges": 10, "decide_s": 0.55555,
                         "fallback": None}},
        "events": [{"site": "chunk", "s": 1.987654},
                   "plain-string"],
        "pair": (1.23456, "x"),
        "label": "cas-register",
    }
    out = util.round_stats(stats)
    assert out["wall"] == 1.23
    assert out["n"] == 7
    assert out["cap_seconds"] == {8: 0.12, 4096: 3.0}
    assert out["tiers"]["ww"] == {"edges": 10, "decide_s": 0.56,
                                  "fallback": None}
    assert out["events"][0] == {"site": "chunk", "s": 1.99}
    assert out["events"][1] == "plain-string"
    assert out["pair"] == [1.23, "x"]       # tuples -> lists (JSON-bound)
    assert out["label"] == "cas-register"
    # The input is untouched (it is the engine's LIVE stats dict).
    assert stats["cap_seconds"][8] == 0.123456


def test_compile_meter_shape_and_idempotent_install():
    assert util.install_compile_meter() is True
    assert util.install_compile_meter() is True      # idempotent
    m = util.compile_meter()
    assert set(m) == {"xla_compiles", "xla_compile_s",
                      "xla_cache_hits"}
    assert m["xla_compiles"] >= 0


# --- supervise integration --------------------------------------------------


@pytest.fixture()
def _clean_injections():
    from jepsen_tpu.lin import supervise

    supervise._injected.clear()
    yield supervise
    supervise._injected.clear()


def test_supervised_call_emits_dispatch_span(monkeypatch,
                                             _clean_injections):
    supervise = _clean_injections
    _on(monkeypatch)
    assert supervise.call("chunk", lambda: 42, deadline_s=5,
                          shape="chunk|cap8|w20|k") == 42
    ev = trace.events()[0]
    assert ev["name"] == "dispatch"
    assert ev["args"]["site"] == "chunk"
    assert ev["args"]["shape"] == "chunk|cap8|w20|k"
    assert ev["args"]["outcome"] == "ok"


def test_supervised_wedge_retry_visible_in_span(monkeypatch,
                                                _clean_injections):
    supervise = _clean_injections
    _on(monkeypatch)
    supervise.inject_wedge("t", 1, deadline_s=0.1)
    assert supervise.call("t", lambda: "real", deadline_s=9) == "real"
    ev = trace.events()[0]
    assert ev["args"]["outcome"] == "ok"
    assert ev["args"]["wedges"] == 1 and ev["args"]["attempts"] == 2
    assert ev["dur"] >= 0.1      # the span covers the wedged attempt


def test_supervised_exhaustion_and_fault_outcomes(monkeypatch,
                                                  _clean_injections):
    supervise = _clean_injections
    _on(monkeypatch)
    supervise.inject_wedge("t", 5, deadline_s=0.05)
    with pytest.raises(supervise.WedgedDispatch):
        supervise.call("t", lambda: None, deadline_s=9, retries=1)
    ev = trace.events()[0]
    assert ev["args"]["outcome"] == "wedge" and ev["args"]["wedges"] == 2
    supervise._injected.clear()      # drop the unconsumed injections

    def boom():
        raise RuntimeError("worker died")

    with pytest.raises(RuntimeError):
        supervise.call("t", boom, deadline_s=5)
    ev = trace.events()[1]
    assert ev["args"]["outcome"] == "fault"
    assert ev["args"]["error"] == "RuntimeError"


def test_supervise_events_reach_registry_feed(_clean_injections):
    supervise = _clean_injections
    supervise.inject_wedge("t", 1, deadline_s=0.05)
    supervise.call("t", lambda: 1, deadline_s=9)
    evs = metrics.REGISTRY.snapshot()["events"]
    assert any(e["kind"] == "wedge" and e["site"] == "t" for e in evs)


# --- cli / web surfaces -----------------------------------------------------


def _write_trace_file(tmp_path, monkeypatch):
    _on(monkeypatch)
    spill = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("JEPSEN_TPU_TRACE_FILE", spill)
    with trace.span("check", engine="sparse"):
        with trace.span("dispatch", site="chunk",
                        shape="chunk|cap8|w20|k") as sp:
            sp.note(outcome="ok")
    trace.flush()
    return spill


def test_cli_trace_report_and_export(tmp_path, monkeypatch, capsys):
    from jepsen_tpu import cli

    spill = _write_trace_file(tmp_path, monkeypatch)
    cmds = cli.standard_commands()
    assert cli.run(cmds, ["trace", "report", "--file", spill]) == 0
    out = capsys.readouterr().out
    assert "check wall total" in out and "chunk" in out

    assert cli.run(cmds, ["trace", "report", "--file", spill,
                          "--json"]) == 0
    agg = json.loads(capsys.readouterr().out)
    assert agg["checks"] == 1 and "chunk" in agg["sites"]

    out_path = str(tmp_path / "chrome.json")
    assert cli.run(cmds, ["trace", "export", "--chrome",
                          "--file", spill, "-o", out_path]) == 0
    with open(out_path) as fh:
        _chrome_is_structurally_valid(json.load(fh))

    # No events -> loud error, not an empty table.
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert cli.run(cmds, ["trace", "report", "--file", empty]) != 0


def test_cli_host_stats_reads_snapshot(tmp_path, monkeypatch, capsys):
    from jepsen_tpu import cli

    path = str(tmp_path / "telemetry.json")
    r = metrics.REGISTRY
    r.start_run("lin-sparse", total=10)
    r.view("host-stats", {"rows": 4, "wasted_passes": 2})
    r.event("wedge", site="host-fixpoint")
    r.write_snapshot(path=path, force=True)
    cmds = cli.standard_commands()
    assert cli.run(cmds, ["host-stats", "--file", path]) == 0
    out = capsys.readouterr().out
    assert "lin-sparse" in out and "wasted_passes = 2" in out
    assert "wedge" in out

    assert cli.run(cmds, ["host-stats", "--file", path, "--json"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["views"]["host-stats"]["rows"] == 4

    assert cli.run(cmds, ["host-stats", "--file",
                          str(tmp_path / "nope.json")]) != 0


def test_web_run_page_renders_snapshot(tmp_path):
    from jepsen_tpu import web

    path = str(tmp_path / "telemetry.json")
    r = metrics.REGISTRY
    r.start_run("lin-sparse", total=100)
    r.view("host-stats", {"rows": 5})
    for i in range(8):
        r._samples.append((float(i), i * 10, 100 + i))
    r._gauges["row"] = 70
    r.event("quarantine", key="chunk|cap8|w34|k")
    r.write_snapshot(path=path, force=True)
    html = web.run_html(path)
    assert "run telemetry" in html
    assert "lin-sparse" in html
    assert "<svg" in html                    # the frontier sparkline
    assert "quarantine" in html
    assert "host-stats" in html
    # Missing snapshot: an explanatory page, not a traceback.
    html = web.run_html(str(tmp_path / "missing.json"))
    assert "no run-telemetry snapshot" in html


# --- e2e: the ladder as spans on the real engine ----------------------------


@pytest.fixture(scope="module")
def small_band_packed():
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import prepare, synth

    h = synth.generate_register_history(60, concurrency=6, seed=1,
                                        crash_prob=0.25)
    return prepare.prepare(m.cas_register(), h)


@pytest.mark.compiles
def test_e2e_wedge_ladder_appears_as_spans(tmp_path, monkeypatch,
                                           small_band_packed,
                                           _clean_injections):
    # Satellite acceptance: a JEPSEN_TPU_WEDGE-injected engine run,
    # traced, shows the wedge/retry ladder as dispatch spans with the
    # right outcomes — detection + retry on the host-fixpoint site,
    # everything else ok, verdict unchanged.
    supervise = _clean_injections
    from jepsen_tpu.lin import bfs

    _on(monkeypatch)
    monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                       str(tmp_path / "quarantine.json"))
    monkeypatch.setenv("JEPSEN_TPU_WEDGE", "host-fixpoint:1:0.3")
    supervise._env_wedge_loaded = None
    r = bfs.check_packed(small_band_packed, cap_schedule=(1,),
                         host_caps=(8, 64, 512))
    supervise._env_wedge_loaded = None
    assert r["valid?"] is True
    assert r["host-stats"]["watchdog_trips"] == 1

    evs = trace.events()
    disp = [e for e in evs if e["name"] == "dispatch"]
    assert disp, "supervised dispatches must appear as spans"
    fx = [e for e in disp if e["args"].get("site") == "host-fixpoint"]
    assert fx, "the host-row fused fixpoint site must be traced"
    # The wedged dispatch: detected, retried, succeeded — one span
    # whose args carry the whole story.
    wedged = [e for e in fx if e["args"].get("wedges")]
    assert len(wedged) == 1
    assert wedged[0]["args"]["outcome"] == "ok"
    assert wedged[0]["args"]["attempts"] == 2
    assert wedged[0]["args"]["shape"].startswith("host-fixpoint|")
    # Every other dispatch is a clean ok (no faults in this run).
    assert all(e["args"].get("outcome") == "ok" for e in disp)
    # The registry event feed saw the trip too (the /run page's
    # triage column).
    feed = metrics.REGISTRY.snapshot()["events"]
    assert any(e["kind"] == "wedge" and e["site"] == "host-fixpoint"
               for e in feed)


@pytest.mark.compiles
def test_e2e_traced_run_attribution_and_parity(monkeypatch):
    # ISSUE acceptance: with JEPSEN_TPU_TRACE=1 a witness-shape
    # device_check_packed run produces (a) an attribution whose
    # per-site rows sum (with host/other) to within 5% of the measured
    # check wall, (b) a structurally valid Chrome export, and (c) the
    # identical verdict/op/final-paths to the untraced run.
    from jepsen_tpu import models as m
    from jepsen_tpu.lin import device_check_packed, prepare, synth

    h = synth.corrupt_history(
        synth.generate_register_history(300, concurrency=12, seed=5,
                                        crash_prob=0.02), seed=2)
    p = prepare.prepare(m.cas_register(), h)

    want = device_check_packed(p, explain=True)      # untraced
    assert trace.events() == []                      # really untraced

    _on(monkeypatch)
    t0 = time.monotonic()
    got = device_check_packed(p, explain=True)
    wall = time.monotonic() - t0

    # (c) identical result with tracing on — observes, never routes.
    assert got["valid?"] == want["valid?"]
    assert got.get("op") == want.get("op")
    assert got.get("final-paths") == want.get("final-paths")

    evs = trace.events()
    agg = report.attribution(evs)
    # (a) the check span covers the run: its wall (= what every site
    # row sums against, dispatch_s + host_other_s) is within 5% of the
    # measured call wall.
    assert agg["checks"] == 1
    assert agg["total_s"] == pytest.approx(wall, rel=0.05)
    assert agg["dispatch_s"] + agg["host_other_s"] == pytest.approx(
        agg["total_s"], abs=0.01)      # each term rounded to 3 digits
    assert agg["dispatches"] >= 1 and agg["sites"]
    # (b) the export is valid trace-event JSON.
    _chrome_is_structurally_valid(report.to_chrome(evs))
