"""O(n) checker golden tests, ported from the reference's
jepsen/test/jepsen/checker_test.clj (queue :11-30, total-queue pathological
case :58-82, counter interleavings :84-150, compose :152-157)."""

from collections import Counter
from fractions import Fraction

from jepsen_tpu import checker as c
from jepsen_tpu import models as m
from jepsen_tpu.history import invoke_op, ok_op

V = c.VALID


def check(ck, model, history):
    return ck.check(None, model,
                    list(history) if history is not None else None, {})


class TestQueue:
    def test_empty(self):
        assert check(c.queue(), None, [])[V]

    def test_possible_enqueue_no_dequeue(self):
        assert check(c.queue(), m.unordered_queue(),
                     [invoke_op(1, "enqueue", 1)])[V]

    def test_definite_enqueue_no_dequeue(self):
        assert check(c.queue(), m.unordered_queue(),
                     [ok_op(1, "enqueue", 1)])[V]

    def test_concurrent_enqueue_dequeue(self):
        assert check(c.queue(), m.unordered_queue(),
                     [invoke_op(2, "dequeue", None),
                      invoke_op(1, "enqueue", 1),
                      ok_op(2, "dequeue", 1)])[V]

    def test_dequeue_no_enqueue(self):
        assert not check(c.queue(), m.unordered_queue(),
                         [ok_op(1, "dequeue", 1)])[V]


class TestTotalQueue:
    def test_empty(self):
        assert check(c.total_queue(), None, [])[V]

    def test_sane(self):
        r = check(c.total_queue(), None,
                  [invoke_op(1, "enqueue", 1),
                   invoke_op(2, "enqueue", 2),
                   ok_op(2, "enqueue", 2),
                   invoke_op(3, "dequeue", 1),
                   ok_op(3, "dequeue", 1),
                   invoke_op(3, "dequeue", 2),
                   ok_op(3, "dequeue", 2)])
        assert r == {V: True,
                     "duplicated": Counter(),
                     "lost": Counter(),
                     "unexpected": Counter(),
                     "recovered": Counter({1: 1}),
                     "ok-frac": 1,
                     "unexpected-frac": 0,
                     "lost-frac": 0,
                     "duplicated-frac": 0,
                     "recovered-frac": Fraction(1, 2)}

    def test_pathological(self):
        r = check(c.total_queue(), None,
                  [invoke_op(1, "enqueue", "hung"),
                   invoke_op(2, "enqueue", "enqueued"),
                   ok_op(2, "enqueue", "enqueued"),
                   invoke_op(3, "enqueue", "dup"),
                   ok_op(3, "enqueue", "dup"),
                   invoke_op(4, "dequeue", None),  # nope
                   invoke_op(5, "dequeue", None),
                   ok_op(5, "dequeue", "wtf"),
                   invoke_op(6, "dequeue", None),
                   ok_op(6, "dequeue", "dup"),
                   invoke_op(7, "dequeue", None),
                   ok_op(7, "dequeue", "dup")])
        assert r == {V: False,
                     "lost": Counter({"enqueued": 1}),
                     "unexpected": Counter({"wtf": 1}),
                     "recovered": Counter(),
                     "duplicated": Counter({"dup": 1}),
                     "ok-frac": Fraction(1, 3),
                     "lost-frac": Fraction(1, 3),
                     "unexpected-frac": Fraction(1, 3),
                     "duplicated-frac": Fraction(1, 3),
                     "recovered-frac": 0}

    def test_drain_expansion(self):
        r = check(c.total_queue(), None,
                  [invoke_op(1, "enqueue", 1),
                   ok_op(1, "enqueue", 1),
                   invoke_op(2, "drain", None),
                   ok_op(2, "drain", [1])])
        assert r[V]


class TestCounter:
    def test_empty(self):
        assert check(c.counter(), None, []) == \
            {V: True, "reads": [], "errors": []}

    def test_initial_read(self):
        assert check(c.counter(), None,
                     [invoke_op(0, "read", None),
                      ok_op(0, "read", 0)]) == \
            {V: True, "reads": [[0, 0, 0]], "errors": []}

    def test_initial_invalid_read(self):
        assert check(c.counter(), None,
                     [invoke_op(0, "read", None),
                      ok_op(0, "read", 1)]) == \
            {V: False, "reads": [[0, 1, 0]], "errors": [[0, 1, 0]]}

    def test_interleaved_concurrent_reads_writes(self):
        h = [invoke_op(0, "read", None),
             invoke_op(1, "add", 1),
             invoke_op(2, "read", None),
             invoke_op(3, "add", 2),
             invoke_op(4, "read", None),
             invoke_op(5, "add", 4),
             invoke_op(6, "read", None),
             invoke_op(7, "add", 8),
             invoke_op(8, "read", None),
             ok_op(0, "read", 6),
             ok_op(1, "add", 1),
             ok_op(2, "read", 0),
             ok_op(3, "add", 2),
             ok_op(4, "read", 3),
             ok_op(5, "add", 4),
             ok_op(6, "read", 100),
             ok_op(7, "add", 8),
             ok_op(8, "read", 15)]
        assert check(c.counter(), None, h) == \
            {V: False,
             "reads": [[0, 6, 15], [0, 0, 15], [0, 3, 15],
                       [0, 100, 15], [0, 15, 15]],
             "errors": [[0, 100, 15]]}

    def test_rolling_reads_and_writes(self):
        h = [invoke_op(0, "read", None),
             invoke_op(1, "add", 1),
             ok_op(0, "read", 0),
             invoke_op(0, "read", None),
             ok_op(1, "add", 1),
             invoke_op(1, "add", 2),
             ok_op(0, "read", 3),
             invoke_op(0, "read", None),
             ok_op(1, "add", 2),
             ok_op(0, "read", 5)]
        assert check(c.counter(), None, h) == \
            {V: False,
             "reads": [[0, 0, 1], [0, 3, 3], [1, 5, 3]],
             "errors": [[1, 5, 3]]}


class TestSetChecker:
    def test_never_read(self):
        r = check(c.set_checker(), None, [invoke_op(0, "add", 0)])
        assert r[V] == "unknown"

    def test_ok(self):
        r = check(c.set_checker(), None,
                  [invoke_op(0, "add", 0), ok_op(0, "add", 0),
                   invoke_op(1, "add", 1),  # indeterminate, recovered
                   invoke_op(2, "read", None), ok_op(2, "read", [0, 1])])
        assert r[V]
        assert r["recovered"] == "#{1}"
        assert r["ok-frac"] == 1

    def test_lost_and_unexpected(self):
        r = check(c.set_checker(), None,
                  [invoke_op(0, "add", 0), ok_op(0, "add", 0),
                   invoke_op(2, "read", None), ok_op(2, "read", [5])])
        assert not r[V]
        assert r["lost"] == "#{0}"
        assert r["unexpected"] == "#{5}"


class TestUniqueIds:
    def test_unique(self):
        r = check(c.unique_ids(), None,
                  [invoke_op(0, "generate", None), ok_op(0, "generate", 0),
                   invoke_op(1, "generate", None), ok_op(1, "generate", 1)])
        assert r[V] and r["range"] == [0, 1]
        assert r["attempted-count"] == 2 and r["acknowledged-count"] == 2

    def test_dups(self):
        r = check(c.unique_ids(), None,
                  [invoke_op(0, "generate", None), ok_op(0, "generate", 7),
                   invoke_op(1, "generate", None), ok_op(1, "generate", 7)])
        assert not r[V]
        assert r["duplicated"] == {7: 2}


class TestCompose:
    def test_compose(self):
        r = check(c.compose({"a": c.unbridled_optimism(),
                             "b": c.unbridled_optimism()}), None, None)
        assert r == {"a": {V: True}, "b": {V: True}, V: True}

    def test_compose_dominates(self):
        bad = c.FnChecker(lambda t, m_, h, o: {V: False})
        unk = c.FnChecker(lambda t, m_, h, o: {V: "unknown"})
        r = check(c.compose({"a": c.unbridled_optimism(), "b": unk}),
                  None, None)
        assert r[V] == "unknown"
        r = check(c.compose({"a": bad, "b": unk}), None, None)
        assert r[V] is False

    def test_check_safe_wraps_errors(self):
        boom = c.FnChecker(lambda t, m_, h, o: 1 / 0)
        r = c.check_safe(boom, None, None, [], {})
        assert r[V] == "unknown" and "ZeroDivisionError" in r["error"]

    def test_merge_valid_rejects_garbage(self):
        import pytest

        with pytest.raises(ValueError):
            c.merge_valid([True, "nope"])


class TestTimeBudget:
    def test_wide_window_returns_within_budget(self):
        """A pathological window-80 history (past the device bitset, so
        the unbounded host search would grind) must come back "unknown"
        within the checker's time budget instead of hanging the analysis
        phase (knossos truncation rationale, checker.clj:104-107)."""
        import time

        from jepsen_tpu.lin import synth

        h = synth.generate_register_history(
            400, concurrency=80, seed=3, value_range=5)
        ck = c.linearizable(algorithm="cpu", time_budget=2.0)
        t0 = time.time()
        r = ck.check(None, m.cas_register(), h, {})
        dt = time.time() - t0
        assert dt < 30, f"budget did not interrupt the search ({dt:.0f}s)"
        assert r["valid?"] == "unknown"
        assert "time budget" in r["error"]

    def test_budget_does_not_fire_on_fast_histories(self):
        from jepsen_tpu.lin import synth

        h = synth.generate_register_history(60, concurrency=3, seed=1)
        ck = c.linearizable(algorithm="cpu", time_budget=60.0)
        r = ck.check(None, m.cas_register(), h, {})
        assert r["valid?"] is True
