"""Hazelcast Open Client Protocol client against an in-process fake
member with real lock/map/queue/atomic-long state — every suite now has
a native wire client (the round-1 build gated 12 of them)."""

from __future__ import annotations

import socket
import struct
import threading
from collections import deque

from jepsen_tpu.history import Op
from jepsen_tpu.suites import hazelwire
from jepsen_tpu.suites.hazelwire import (HazelcastClient, IdClient,
                                         LockClient, QueueClient,
                                         SetClient)
import pytest

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick

HEADER = 22


class FakeMember:
    def __init__(self):
        self.locks: dict[str, int | None] = {}
        self.maps: dict[str, dict] = {}
        self.queues: dict[str, deque] = {}
        self.longs: dict[str, int] = {}
        self.state_lock = threading.Lock()
        self.srv = socket.socket()
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(4)
        self.port = self.srv.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    @staticmethod
    def _read_s(body, off):
        (n,) = struct.unpack_from("<i", body, off)
        return body[off + 4:off + 4 + n].decode(), off + 4 + n

    @staticmethod
    def _read_data(body, off):
        (n,) = struct.unpack_from("<i", body, off)
        blob = body[off + 4:off + 4 + n]
        return struct.unpack_from(">q", blob, 8)[0], off + 4 + n

    def _serve(self, conn):
        buf = bytearray()

        def read_exact(n):
            while len(buf) < n:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf.extend(chunk)
            out = bytes(buf[:n])
            del buf[:n]
            return out

        def reply(corr, mtype, payload):
            conn.sendall(struct.pack(
                "<iBBHqiH", HEADER + len(payload), 1, 0xC0, mtype, corr,
                -1, HEADER) + payload)

        try:
            assert read_exact(3) == b"CB2"
            while True:
                head = read_exact(HEADER)
                length, _v, _f, mtype, corr, _p, off = struct.unpack(
                    "<iBBHqiH", head)
                body = read_exact(length - HEADER)[off - HEADER:]
                with self.state_lock:
                    self._dispatch(reply, corr, mtype, body)
        except (ConnectionError, OSError, AssertionError):
            return
        finally:
            conn.close()

    def _dispatch(self, reply, corr, mtype, body):
        if mtype == hazelwire.AUTH:
            reply(corr, hazelwire.AUTH_RESPONSE, b"\x00")
        elif mtype == hazelwire.LOCK_TRYLOCK:
            name, off = self._read_s(body, 0)
            (tid,) = struct.unpack_from("<q", body, off)
            got = self.locks.get(name) in (None, tid)
            if got:
                self.locks[name] = tid
            reply(corr, hazelwire.BOOL_RESPONSE,
                  b"\x01" if got else b"\x00")
        elif mtype == hazelwire.LOCK_UNLOCK:
            name, off = self._read_s(body, 0)
            (tid,) = struct.unpack_from("<q", body, off)
            if self.locks.get(name) == tid:
                self.locks[name] = None
                reply(corr, hazelwire.BOOL_RESPONSE, b"\x01")
            else:
                reply(corr, hazelwire.ERROR_RESPONSE, b"")
        elif mtype == hazelwire.MAP_PUT:
            name, off = self._read_s(body, 0)
            k, off = self._read_data(body, off)
            v, off = self._read_data(body, off)
            self.maps.setdefault(name, {})[k] = v
            reply(corr, hazelwire.DATA_RESPONSE, b"\x01")  # null previous
        elif mtype == hazelwire.MAP_GET:
            name, off = self._read_s(body, 0)
            k, off = self._read_data(body, off)
            v = self.maps.get(name, {}).get(k)
            if v is None:
                reply(corr, hazelwire.DATA_RESPONSE, b"\x01")
            else:
                reply(corr, hazelwire.DATA_RESPONSE,
                      b"\x00" + hazelwire._data_long(v))
        elif mtype == hazelwire.MAP_VALUES:
            name, _ = self._read_s(body, 0)
            vals = list(self.maps.get(name, {}).values())
            payload = struct.pack("<i", len(vals)) + b"".join(
                hazelwire._data_long(v) for v in vals)
            reply(corr, hazelwire.LIST_DATA_RESPONSE, payload)
        elif mtype == hazelwire.QUEUE_OFFER:
            name, off = self._read_s(body, 0)
            v, off = self._read_data(body, off)
            self.queues.setdefault(name, deque()).append(v)
            reply(corr, hazelwire.BOOL_RESPONSE, b"\x01")
        elif mtype == hazelwire.QUEUE_POLL:
            name, _ = self._read_s(body, 0)
            q = self.queues.setdefault(name, deque())
            if not q:
                reply(corr, hazelwire.DATA_RESPONSE, b"\x01")
            else:
                reply(corr, hazelwire.DATA_RESPONSE,
                      b"\x00" + hazelwire._data_long(q.popleft()))
        elif mtype == hazelwire.ATOMIC_LONG_INC_GET:
            name, _ = self._read_s(body, 0)
            self.longs[name] = self.longs.get(name, 0) + 1
            reply(corr, hazelwire.LONG_RESPONSE,
                  struct.pack("<q", self.longs[name]))
        else:
            reply(corr, hazelwire.ERROR_RESPONSE, b"")

    def close(self):
        self.srv.close()


def test_lock_mutual_exclusion():
    srv = FakeMember()
    a = LockClient(HazelcastClient("127.0.0.1", srv.port))
    b = LockClient(HazelcastClient("127.0.0.1", srv.port))
    # distinct thread ids per connection are required for exclusion
    b.conn.thread_id = a.conn.thread_id + 1
    assert a.invoke(None, Op("invoke", "acquire", None, 0)).is_ok
    assert b.invoke(None, Op("invoke", "acquire", None, 1)).is_fail
    assert b.invoke(None, Op("invoke", "release", None, 1)).is_fail
    assert a.invoke(None, Op("invoke", "release", None, 0)).is_ok
    assert b.invoke(None, Op("invoke", "acquire", None, 1)).is_ok
    a.close(None)
    b.close(None)
    srv.close()


def test_map_set_semantics():
    srv = FakeMember()
    cl = SetClient(HazelcastClient("127.0.0.1", srv.port))
    assert cl.invoke(None, Op("invoke", "add", 5, 0)).is_ok
    assert cl.invoke(None, Op("invoke", "add", 2, 0)).is_ok
    assert cl.invoke(None, Op("invoke", "read", None, 0)).value == [2, 5]
    cl.close(None)
    srv.close()


def test_queue_and_ids():
    srv = FakeMember()
    q = QueueClient(HazelcastClient("127.0.0.1", srv.port))
    assert q.invoke(None, Op("invoke", "enqueue", 7, 0)).is_ok
    assert q.invoke(None, Op("invoke", "dequeue", None, 0)).value == 7
    assert q.invoke(None, Op("invoke", "dequeue", None, 0)).is_fail
    ids = IdClient(HazelcastClient("127.0.0.1", srv.port))
    got = {ids.invoke(None, Op("invoke", "generate", None, 0)).value
           for _ in range(5)}
    assert got == {1, 2, 3, 4, 5}
    q.close(None)
    ids.close(None)
    srv.close()


def test_no_gated_suites_remain():
    import importlib
    import pkgutil

    import jepsen_tpu.suites as suites_pkg
    from jepsen_tpu.suites import common

    gated = []
    for info in pkgutil.iter_modules(suites_pkg.__path__):
        mod = importlib.import_module(f"jepsen_tpu.suites.{info.name}")
        if not hasattr(mod, "test"):
            continue
        try:
            t = mod.test({})
        except Exception:
            continue
        if isinstance(t.get("client"), common.GatedClient):
            gated.append(info.name)
    assert gated == [], gated
