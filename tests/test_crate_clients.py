"""Crate real-client tests against an in-process fake CrateDB `/_sql`
server (the house pattern for wire clients: every real client gets a
fake-SERVER test exercising real store semantics — here `_version`
optimistic CAS and the realtime-point-read vs refreshed-scan split)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from jepsen_tpu.history import Op
from jepsen_tpu.suites import crate

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick


class FakeCrate:
    """Tiny CrateDB: tables of rows with `_version`, dup-key errors,
    refresh-gated scans. Knobs: drop_cas (silently lose UPDATEs),
    stale_version (serve stale versions on upsert — divergence)."""

    def __init__(self, drop_cas: bool = False,
                 stale_version: bool = False):
        self.tables: dict = {}
        self.refreshed: dict = {}
        self.lock = threading.Lock()
        self.drop_cas = drop_cas
        self.stale_version = stale_version
        self._casn = 0

    def execute(self, stmt: str, args):
        s = " ".join(stmt.split())
        with self.lock:
            if s.startswith("CREATE TABLE IF NOT EXISTS"):
                t = s.split()[5]
                self.tables.setdefault(t, {})
                self.refreshed.setdefault(t, {})
                return {"rows": [], "rowcount": 1}
            if s.startswith("REFRESH TABLE"):
                t = s.split()[2]
                self.refreshed[t] = {k: dict(v) for k, v in
                                     self.tables.get(t, {}).items()}
                return {"rows": [], "rowcount": 1}
            if s.startswith("SELECT"):
                return self._select(s, args)
            if s.startswith("INSERT INTO"):
                return self._insert(s, args)
            if s.startswith("UPDATE"):
                return self._update(s, args)
        raise ValueError(f"unhandled stmt {s!r}")

    def _cols(self, s):
        return [c.strip().strip('"') for c in
                s[len("SELECT "):s.index(" FROM")].split(",")]

    def _select(self, s, args):
        t = s.split(" FROM ")[1].split()[0]
        rows = self.tables.get(t, {})
        cols = self._cols(s)
        if " WHERE id = ?" in s:
            row = rows.get(args[0])
            if row is None:
                return {"rows": [], "rowcount": 0}
            return {"rows": [[row[c] for c in cols]], "rowcount": 1}
        # scan: refreshed snapshot only
        snap = self.refreshed.get(t, {})
        out = [[r[c] for c in cols] for r in snap.values()]
        return {"rows": out, "rowcount": len(out)}

    def _insert(self, s, args):
        t = s.split(" INTO ")[1].split()[0]
        cols = s[s.index("(") + 1:s.index(")")].replace(" ", "").split(",")
        rows = self.tables.setdefault(t, {})
        key = args[cols.index("id")]
        upsert = "ON DUPLICATE KEY" in s
        if key in rows and not upsert:
            raise KeyError("DuplicateKeyException")
        if key in rows:
            row = rows[key]
            if not self.stale_version:
                row["_version"] += 1
            for c, v in zip(cols, args):
                if c != "id":
                    row[c] = v
        else:
            row = {c: v for c, v in zip(cols, args)}
            row["_version"] = 1
            rows[key] = row
        return {"rows": [], "rowcount": 1}

    def _update(self, s, args):
        t = s.split()[1]
        rows = self.tables.get(t, {})
        # UPDATE t SET col = ? WHERE id = ? AND "_version" = ?
        col = s.split(" SET ")[1].split()[0]
        val, key, version = args
        row = rows.get(key)
        if row is None or row["_version"] != version:
            return {"rows": [], "rowcount": 0}
        self._casn += 1
        if self.drop_cas and self._casn % 4 == 0:
            # acked but silently lost (version bumps, write vanishes)
            row["_version"] += 1
            return {"rows": [], "rowcount": 1}
        row[col] = val
        row["_version"] += 1
        return {"rows": [], "rowcount": 1}


@pytest.fixture()
def fake_crate():
    made = []

    def start(**knobs):
        store = FakeCrate(**knobs)

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n))
                try:
                    out = store.execute(body["stmt"],
                                        body.get("args", []))
                    code = 200
                except KeyError as e:
                    out = {"error": {"message": str(e)}}
                    code = 409
                except Exception as e:  # noqa: BLE001
                    out = {"error": {"message": repr(e)}}
                    code = 400
                data = json.dumps(out).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):
                pass

        srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        made.append(srv)
        node = f"127.0.0.1:{srv.server_port}"
        return store, node

    yield start
    for srv in made:
        srv.shutdown()


def _patch_port(monkeypatch, node):
    # sql() builds http://{node}:{PORT}; the fake node carries its own
    # port, so neutralize the suite PORT suffix via a passthrough node.
    host, port = node.rsplit(":", 1)
    monkeypatch.setattr(crate, "PORT", int(port))
    return host


class TestLostUpdatesClient:
    def test_round_trip_and_cas(self, fake_crate, monkeypatch):
        store, node = fake_crate()
        host = _patch_port(monkeypatch, node)
        c = crate.CrateLostUpdatesClient(host)
        c.setup({"nodes": [host]})
        for v in range(6):
            r = c.invoke({}, Op("invoke", "update", v, 0))
            assert r.type == "ok", r
        r = c.invoke({}, Op("invoke", "read", None, 0))
        assert r.type == "ok" and r.value == list(range(6))

    def test_version_conflict_is_fail(self, fake_crate, monkeypatch):
        store, node = fake_crate()
        host = _patch_port(monkeypatch, node)
        c = crate.CrateLostUpdatesClient(host)
        c.setup({"nodes": [host]})
        assert c.invoke({}, Op("invoke", "update", 0, 0)).type == "ok"
        # bump the version behind the client's back mid-read: simulate by
        # racing another writer between SELECT and UPDATE
        orig = store._update

        def racing(s, args):
            row = store.tables["jepsen_sets"][0]
            row["_version"] += 1  # concurrent writer won
            store._update = orig
            return orig(s, args)

        store._update = racing
        r = c.invoke({}, Op("invoke", "update", 1, 0))
        assert r.type == "fail"

    def test_lost_updates_detected_through_real_client(
            self, fake_crate, monkeypatch):
        store, node = fake_crate(drop_cas=True)
        host = _patch_port(monkeypatch, node)
        c = crate.CrateLostUpdatesClient(host)
        c.setup({"nodes": [host]})
        h = []
        for v in range(12):
            r = c.invoke({}, Op("invoke", "update", v, 0))
            if r.type == "ok":
                h.append(Op("ok", "update", v, 0))
        h.append(c.invoke({}, Op("invoke", "read", None, 0)))
        res = crate.lost_updates_checker().check({}, None, h, {})
        assert res["valid?"] is False and res["lost-count"] > 0


class TestVersionDivergenceClient:
    def test_round_trip(self, fake_crate, monkeypatch):
        store, node = fake_crate()
        host = _patch_port(monkeypatch, node)
        c = crate.CrateVersionDivergenceClient(host)
        c.setup({"nodes": [host]})
        h = []
        for v in range(5):
            r = c.invoke({}, Op("invoke", "write", v, 0))
            assert r.type == "ok"
            h.append(c.invoke({}, Op("invoke", "read", None, 0)))
        assert h[-1].value == [4, 5]  # value 4, fifth version
        res = crate.multiversion_checker().check({}, None, h, {})
        assert res["valid?"] is True

    def test_divergence_detected(self, fake_crate, monkeypatch):
        store, node = fake_crate(stale_version=True)
        host = _patch_port(monkeypatch, node)
        c = crate.CrateVersionDivergenceClient(host)
        c.setup({"nodes": [host]})
        h = []
        for v in range(4):
            c.invoke({}, Op("invoke", "write", v, 0))
            h.append(c.invoke({}, Op("invoke", "read", None, 0)))
        res = crate.multiversion_checker().check({}, None, h, {})
        assert res["valid?"] is False and res["multis"]


class TestDirtyReadClient:
    def test_visibility_split(self, fake_crate, monkeypatch):
        store, node = fake_crate()
        host = _patch_port(monkeypatch, node)
        c = crate.CrateDirtyReadClient(host)
        c.setup({"nodes": [host]})
        assert c.invoke({}, Op("invoke", "write", 1, 0)).type == "ok"
        # point read realtime, scan empty until refresh
        assert c.invoke({}, Op("invoke", "read", 1, 0)).type == "ok"
        r = c.invoke({}, Op("invoke", "strong-read", None, 0))
        assert r.type == "ok" and r.value == []
        assert c.invoke({}, Op("invoke", "refresh", None, 0)).type == "ok"
        r = c.invoke({}, Op("invoke", "strong-read", None, 0))
        assert r.value == [1]

    def test_checker_classifies_dirty_and_lost(self):
        h = [Op("ok", "write", 1, 0), Op("ok", "write", 2, 0),
             Op("ok", "read", 3, 1),          # dirty: never durable
             Op("ok", "strong-read", [1], 2)]  # write 2 lost
        res = crate.crate_dirty_read_checker().check({}, None, h, {})
        assert res["valid?"] is False
        assert res["dirty"] == [3] and res["lost"] == [2]

    def test_checker_nodes_disagree(self):
        h = [Op("ok", "write", 1, 0),
             Op("ok", "strong-read", [1], 1),
             Op("ok", "strong-read", [], 2)]
        res = crate.crate_dirty_read_checker().check({}, None, h, {})
        assert res["valid?"] is False and res["nodes-agree?"] is False

    def test_checker_valid(self):
        h = [Op("ok", "write", 1, 0), Op("ok", "read", 1, 1),
             Op("ok", "strong-read", [1], 2),
             Op("ok", "strong-read", [1], 3)]
        res = crate.crate_dirty_read_checker().check({}, None, h, {})
        assert res["valid?"] is True


class TestWorkloadRegistry:
    def test_four_cells_all_real_clients(self):
        for wl, cls in (("set", crate.CrateSetClient),
                        ("dirty-read", crate.CrateDirtyReadClient),
                        ("lost-updates", crate.CrateLostUpdatesClient),
                        ("version-divergence",
                         crate.CrateVersionDivergenceClient)):
            t = crate.test({"fake": False, "workload": wl})
            assert isinstance(t["client"], cls), wl
            t2 = crate.test({"fake": True, "workload": wl,
                             "time-limit": 1})
            assert t2["transport"] == "dummy"
