"""Fleet placement engine tests (doc/service.md § Placement).

Four layers, mirroring the tentpole's pipeline:

- Policy unit: the pure host-side bin->slot policy (service.placement)
  with fabricated queue depths — homing, stickiness, bounded spill,
  device-loss re-homing — no daemon, no jax.
- Daemon routing: a 2-worker daemon with stub check fns — bins home to
  one slot (affinity visible in the placement stats block), workers=1
  never consults the policy (driver-shape bit-compat), injected device
  loss re-homes with zero lost or flipped verdicts.
- svc-stream bins: K concurrent wire sessions' pending increments
  decide through ONE vmapped carried-frontier program (occupancy > 1
  asserted) with verdicts identical to the solo path and the CPU
  oracle; a declined batch falls back per-session with no verdict
  change.
- result-fetch: the journal-backed reconnect frame returns the settled
  record by request fingerprint, or an HONEST pending/unknown — never
  a guess.
"""

from __future__ import annotations

import threading
import time

import pytest

# Engine modules imported at COLLECTION time: bfs/dense build tiny
# module-level jnp constants whose one-off compiles must land outside
# the quick tier's per-test no-compile window (tests/conftest.py).
import jepsen_tpu.lin.batched   # noqa: F401
import jepsen_tpu.lin.dense     # noqa: F401

pytestmark = pytest.mark.quick


def _mk_service(tmp_path, monkeypatch, **kw):
    from jepsen_tpu.service.daemon import CheckerService

    monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                       str(tmp_path / "quarantine.json"))
    kw.setdefault("stats_file", str(tmp_path / "service_stats.json"))
    kw.setdefault("flush_ms_", 10)
    return CheckerService("127.0.0.1", 0, **kw)


def _stub_check(packed, model, history):
    return {"valid?": True, "analyzer": "stub-single"}


def _stub_batch(model, subs, declines=None):
    return {rid: {"valid?": True, "analyzer": "stub-batch"}
            for rid in subs}


def _hist(n=20, concurrency=3, seed=0, **kw):
    from jepsen_tpu.lin import synth

    return synth.generate_register_history(
        n, concurrency=concurrency, seed=seed, value_range=3, **kw)


class TestPlacementPolicy:
    def _mk(self, n=4, spill=4):
        from jepsen_tpu.service.placement import Placement

        return Placement(n, spill_depth_=spill)

    def test_new_key_homes_least_loaded(self):
        p = self._mk()
        slot, route = p.place("bin-a", [3, 1, 2, 5])
        assert (slot, route) == (1, "new")
        # Tie breaks toward the lowest slot (deterministic).
        slot2, route2 = p.place("bin-b", [2, 2, 2, 2])
        assert (slot2, route2) == (0, "new")

    def test_home_is_sticky_under_load_changes(self):
        p = self._mk()
        home, _ = p.place("bin-a", [0, 0, 0, 0])
        for depths in ([1, 0, 0, 0], [2, 0, 1, 0], [4, 1, 1, 1]):
            slot, route = p.place("bin-a", depths)
            assert (slot, route) == (home, "home")

    def test_spill_leaves_home_and_is_bounded(self):
        p = self._mk(spill=2)
        home, _ = p.place("bin-a", [0, 9, 9, 9])
        assert home == 0
        # Home backed up past the spill depth AND a strictly
        # less-loaded alternative exists -> spill there, home KEPT.
        slot, route = p.place("bin-a", [5, 1, 3, 4])
        assert route == "spill" and slot == 1
        assert p.snapshot()["homes"]["bin-a"] == home
        # Next placement with a drained home goes home again.
        slot, route = p.place("bin-a", [0, 1, 3, 4])
        assert (slot, route) == (home, "home")

    def test_no_spill_without_strictly_better_slot(self):
        p = self._mk(spill=2)
        home, _ = p.place("bin-a", [0, 9, 9, 9])
        # Everyone is at least as backed up: stay home (a spill that
        # doesn't help only costs the device cache).
        slot, route = p.place("bin-a", [6, 6, 7, 6])
        assert (slot, route) == (home, "home")

    def test_forget_slot_rehomes_on_next_placement(self):
        p = self._mk()
        p.place("bin-a", [0, 5, 5, 5])
        p.place("bin-b", [0, 5, 5, 5])
        p.place("bin-c", [5, 0, 5, 5])
        dropped = p.forget_slot(0)
        assert sorted(dropped) == ["bin-a", "bin-b"]
        snap = p.snapshot()
        assert snap["re_homes"] == 2
        assert set(snap["homes"]) == {"bin-c"}
        # The orphaned bin re-homes by current load, not history.
        slot, route = p.place("bin-a", [9, 9, 1, 2])
        assert (slot, route) == (2, "new")

    def test_snapshot_counters(self):
        p = self._mk(spill=0)
        p.place("bin-a", [0, 0])
        p.place("bin-a", [0, 0])
        p.place("bin-a", [3, 1])          # spill (home 0 backed up)
        snap = p.snapshot()
        assert snap["placed"] == 3
        assert snap["homed"] == 1
        assert snap["spills"] == 1
        assert snap["spill_depth"] == 0


class TestDaemonPlacement:
    def test_bins_home_and_stats_block(self, tmp_path, monkeypatch):
        from jepsen_tpu.lin import synth
        from jepsen_tpu.service.protocol import CheckerClient

        svc = _mk_service(tmp_path, monkeypatch, workers=2,
                          check_fn=_stub_check,
                          batch_fn=_stub_batch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            for seed in range(3):
                assert c.submit("cas-register",
                                _hist(seed=seed))["valid?"] is True
                assert c.submit(
                    "mutex", synth.generate_mutex_history(
                        20, concurrency=3, seed=seed))["valid?"] is True
            st = c.stats()
            block = st["placement"]
            homes = block["homes"]
            assert any(k.startswith("svc-dense|")
                       and k.endswith("cas-register") for k in homes)
            assert any("mutex" in k for k in homes)
            workers = block["workers"]
            assert len(workers) == 2
            assert {w["slot"] for w in workers} == {0, 1}
            assert sum(w["items"] for w in workers) >= 2
            for w in workers:
                assert {"wid", "queue_depth", "busy", "busy_s",
                        "compiles"} <= set(w)
            c.close()
        finally:
            svc.stop()

    def test_single_worker_never_consults_policy(self, tmp_path,
                                                 monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        svc = _mk_service(tmp_path, monkeypatch,
                          check_fn=_stub_check,
                          batch_fn=_stub_batch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            assert c.submit("cas-register", _hist())["valid?"] is True
            block = c.stats()["placement"]
            # The driver shape: slot 0 takes everything, the policy
            # holds no homes, no device is ever bound.
            assert block["homes"] == {}
            assert block["placed"] == 0
            assert block["workers"][0]["device"] is None
            c.close()
        finally:
            svc.stop()

    def test_device_loss_rehomes_without_losing_verdicts(
            self, tmp_path, monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        svc = _mk_service(tmp_path, monkeypatch, workers=2,
                          check_fn=_stub_check,
                          batch_fn=_stub_batch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            # Seed a home, then lose the next item's device.
            assert c.submit("cas-register",
                            _hist(seed=0))["valid?"] is True
            svc.inject_device_loss(1)
            # Every submit still settles: the dying worker's batch is
            # requeued by the supervisor and re-placed on a survivor.
            for seed in range(1, 5):
                r = c.submit("cas-register", _hist(seed=seed))
                assert r["valid?"] is True, r
            deadline = time.time() + 10
            while time.time() < deadline:
                st = c.stats()
                if st.get("device_losses") and \
                        st["workers"] == 2:
                    break
                time.sleep(0.05)
            assert st["device_losses"] == 1
            assert st.get("worker_respawns", 0) >= 1
            assert st["workers"] == 2          # pool is whole again
            # The loss is visible in the obs event feed.
            from jepsen_tpu.obs import metrics as obs_metrics

            snap = obs_metrics.REGISTRY.snapshot()
            kinds = [e.get("kind") for e in snap.get("events", [])]
            assert "device-loss" in kinds
            c.close()
        finally:
            svc.stop()


class TestStreamBins:
    """K concurrent wire sessions batch their pending increments
    through ONE vmapped carried-frontier program — the acceptance
    shape: occupancy > 1, verdicts identical to solo and the CPU
    oracle."""

    K = 4

    def _histories(self):
        from jepsen_tpu.lin import synth

        # One traced shape shared by every lane: identical op counts
        # and concurrency; distinct seeds keep the search non-trivial
        # per lane.
        return [list(synth.generate_register_history(
            200, concurrency=5, seed=20 + i, value_range=5))
            for i in range(self.K)]

    @pytest.mark.compiles
    def test_concurrent_sessions_batch_with_parity(self, tmp_path,
                                                   monkeypatch):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import cpu, prepare
        from jepsen_tpu.service.protocol import CheckerClient

        monkeypatch.setenv("JEPSEN_TPU_STREAM_SESSIONS", str(self.K))
        svc = _mk_service(tmp_path, monkeypatch,
                          flush_ms_=60).start()
        hists = self._histories()
        oracle = [cpu.check_packed(prepare.prepare(
            m.cas_register(), list(h)))["valid?"] for h in hists]
        rounds = 4
        barrier = threading.Barrier(self.K)
        results: list = [None] * self.K
        errors: list = []

        def lane(i):
            try:
                c = CheckerClient("127.0.0.1", svc.port)
                sid = c.stream_open("cas-register")
                h = hists[i]
                n = max(1, len(h) // rounds)
                for j in range(0, len(h), n):
                    # Co-arrive inside one flush window so the bin
                    # really holds K pending increments.
                    barrier.wait(timeout=30)
                    st = c.stream_append(sid, h[j:j + n])
                    assert st.get("type") == "stream-state", st
                results[i] = c.stream_finalize(sid)
                c.close()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                barrier.abort()

        threads = [threading.Thread(target=lane, args=(i,))
                   for i in range(self.K)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        for i, r in enumerate(results):
            assert r is not None and r["valid?"] == oracle[i], (i, r)
        st = svc.stats()
        assert st.get("stream_batches", 0) >= 1, st
        assert st.get("stream_batch_max_occupancy", 0) > 1, st
        assert st.get("stream_batched_increments", 0) >= 2, st
        svc.stop()

    @pytest.mark.compiles
    def test_declined_batch_falls_back_solo_same_verdict(
            self, tmp_path, monkeypatch):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import batched, cpu, prepare
        from jepsen_tpu.service.protocol import CheckerClient

        def decline_all(jobs):
            return [batched.Decline("stub", "forced decline",
                                    [i for i in range(len(jobs))])
                    for _ in jobs]

        monkeypatch.setenv("JEPSEN_TPU_STREAM_SESSIONS", "2")
        svc = _mk_service(tmp_path, monkeypatch, flush_ms_=60,
                          stream_batch_fn=decline_all).start()
        hists = self._histories()[:2]
        oracle = [cpu.check_packed(prepare.prepare(
            m.cas_register(), list(h)))["valid?"] for h in hists]
        barrier = threading.Barrier(2)
        results: list = [None] * 2
        errors: list = []

        def lane(i):
            try:
                c = CheckerClient("127.0.0.1", svc.port)
                sid = c.stream_open("cas-register")
                h = hists[i]
                n = max(1, len(h) // 2)
                for j in range(0, len(h), n):
                    barrier.wait(timeout=30)
                    c.stream_append(sid, h[j:j + n])
                results[i] = c.stream_finalize(sid)
                c.close()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                barrier.abort()

        threads = [threading.Thread(target=lane, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        for i, r in enumerate(results):
            assert r is not None and r["valid?"] == oracle[i], (i, r)
        st = svc.stats()
        # The decline axis is visible; no batched lanes were counted.
        assert st.get("decline_axes", {}).get("stub", 0) >= 1, st
        assert st.get("stream_batches", 0) == 0, st
        svc.stop()

    @pytest.mark.compiles
    def test_stream_bins_off_keeps_solo_path(self, tmp_path,
                                             monkeypatch):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import cpu, prepare
        from jepsen_tpu.service.protocol import CheckerClient

        monkeypatch.setenv("JEPSEN_TPU_SERVICE_STREAM_BINS", "0")
        svc = _mk_service(tmp_path, monkeypatch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            h = self._histories()[0]
            want = cpu.check_packed(prepare.prepare(
                m.cas_register(), list(h)))["valid?"]
            sid = c.stream_open("cas-register")
            n = len(h) // 3
            for j in range(0, len(h), n):
                c.stream_append(sid, h[j:j + n])
            assert c.stream_finalize(sid)["valid?"] == want
            st = c.stats()
            assert "stream_batches" not in st
            c.close()
        finally:
            svc.stop()


class TestResultFetch:
    def test_settled_round_trip(self, tmp_path, monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        svc = _mk_service(tmp_path, monkeypatch,
                          journal=str(tmp_path / "j.jsonl"),
                          check_fn=_stub_check,
                          batch_fn=_stub_batch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            h = _hist(seed=7)
            r = c.submit("cas-register", list(h))
            assert r["valid?"] is True
            # A "reconnecting" client re-asks by fingerprint.
            f = c.result_fetch("cas-register", list(h))
            assert f.get("fetched") is True
            assert f["valid?"] == r["valid?"]
            st = c.stats()
            assert st.get("result_fetches", 0) >= 1
            assert st.get("result_fetch_hits", 0) >= 1
            c.close()
        finally:
            svc.stop()

    def test_unknown_is_honest(self, tmp_path, monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        svc = _mk_service(tmp_path, monkeypatch,
                          journal=str(tmp_path / "j.jsonl"),
                          check_fn=_stub_check,
                          batch_fn=_stub_batch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            f = c.result_fetch("cas-register", _hist(seed=99))
            assert f["valid?"] == "unknown"
            assert f["fetch_status"] == "unknown"
            assert f.get("fetched") is not True
            c.close()
        finally:
            svc.stop()

    def test_pending_is_honest_not_a_guess(self, tmp_path,
                                           monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        gate = threading.Event()

        def gated_check(packed, model, history):
            gate.wait(timeout=30)
            return {"valid?": True, "analyzer": "stub-gated"}

        svc = _mk_service(tmp_path, monkeypatch,
                          journal=str(tmp_path / "j.jsonl"),
                          check_fn=gated_check,
                          batch_fn=None).start()
        try:
            h = _hist(seed=5)
            done: list = []
            c1 = CheckerClient("127.0.0.1", svc.port)

            def submit():
                done.append(c1.submit("cas-register", list(h)))

            t = threading.Thread(target=submit)
            t.start()
            c2 = CheckerClient("127.0.0.1", svc.port)
            deadline = time.time() + 10
            f = None
            while time.time() < deadline:
                f = c2.result_fetch("cas-register", list(h))
                if f.get("fetch_status") == "pending":
                    break
                time.sleep(0.05)
            assert f and f["fetch_status"] == "pending", f
            assert f["valid?"] == "unknown"
            gate.set()
            t.join(timeout=30)
            assert done and done[0]["valid?"] is True
            f2 = c2.result_fetch("cas-register", list(h))
            assert f2.get("fetched") is True
            c1.close()
            c2.close()
        finally:
            gate.set()
            svc.stop()

    def test_no_journal_is_an_error(self, tmp_path, monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        svc = _mk_service(tmp_path, monkeypatch,
                          check_fn=_stub_check,
                          batch_fn=_stub_batch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            f = c.result_fetch("cas-register", _hist())
            assert f["valid?"] == "unknown"
            assert f["fetch_status"] == "unknown"
            c.close()
        finally:
            svc.stop()
