"""Independent-keys tests, mirroring the reference's independent_test.clj
(sequential/concurrent generator semantics incl. thread-math error
messages, and the sharded checker) plus the batched device path."""

import threading

import pytest

from jepsen_tpu import checker as c
from jepsen_tpu import generator as g
from jepsen_tpu import independent as ind
from jepsen_tpu import models as m
from jepsen_tpu.history import History, Op, invoke_op, ok_op

TEST = {"concurrency": 4, "nodes": ["n1", "n2"]}


class TestSequentialGenerator:
    def test_wraps_values_and_advances(self):
        source = ind.sequential_generator(
            ["a", "b"], lambda k: g.limit(2, Op("invoke", "w", 1)))
        with g.with_threads((0,)):
            vals = []
            while True:
                o = g.op(source, TEST, 0)
                if o is None:
                    break
                vals.append(o.value)
        assert vals == [ind.KV("a", 1)] * 2 + [ind.KV("b", 1)] * 2

    def test_empty_keys(self):
        source = ind.sequential_generator([], lambda k: Op("invoke", "w", 1))
        with g.with_threads((0,)):
            assert g.op(source, TEST, 0) is None


class TestConcurrentGenerator:
    def drain(self, source, threads, test):
        ops = []
        lock = threading.Lock()

        def worker(tid):
            with g.with_threads(threads):
                while True:
                    o = g.op(source, test, tid)
                    if o is None:
                        return
                    with lock:
                        ops.append((tid, o))

        ts = [threading.Thread(target=worker, args=(t,))
              for t in threads if isinstance(t, int)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(20)
        return ops

    def test_groups_stick_to_keys(self):
        source = ind.concurrent_generator(
            2, ["a", "b"], lambda k: g.limit(4, Op("invoke", "w", k)))
        ops = self.drain(source, (0, 1, 2, 3), TEST)
        keys_by_thread = {}
        for tid, o in ops:
            keys_by_thread.setdefault(tid, set()).add(o.value.key)
        # threads 0,1 form group 0; 2,3 group 1; each group one key
        assert keys_by_thread.get(0, set()) | keys_by_thread.get(1, set()) \
            != keys_by_thread.get(2, set()) | keys_by_thread.get(3, set())
        assert len(ops) == 8

    def test_concurrency_mismatch_error(self):
        source = ind.concurrent_generator(
            3, ["a"], lambda k: Op("invoke", "w", 1))
        with g.with_threads((0, 1, 2, 3)):
            with pytest.raises(AssertionError,
                               match="multiple of 3"):
                g.op(source, TEST, 0)

    def test_too_few_threads_error(self):
        source = ind.concurrent_generator(
            9, ["a"], lambda k: Op("invoke", "w", 1))
        test = {"concurrency": 4}
        with g.with_threads((0, 1, 2, 3)):
            with pytest.raises(AssertionError, match="at least 9"):
                g.op(source, test, 0)

    def test_nemesis_rejected(self):
        source = ind.concurrent_generator(
            2, ["a"], lambda k: Op("invoke", "w", 1))
        with g.with_threads((0, 1, 2, 3)):
            g.op(source, TEST, 0)  # initialize
            with pytest.raises(AssertionError, match="numeric"):
                g.op(source, TEST, "nemesis")


class TestSubhistories:
    def history(self):
        return History.of(
            invoke_op(0, "w", ind.KV("a", 1)),
            invoke_op(1, "w", ind.KV("b", 2)),
            Op("info", "start", None, "nemesis"),
            ok_op(0, "w", ind.KV("a", 1)),
            ok_op(1, "w", ind.KV("b", 2)))

    def test_history_keys(self):
        assert ind.history_keys(self.history()) == {"a", "b"}

    def test_subhistory_unwraps_and_keeps_unkeyed(self):
        sub = ind.subhistory("a", self.history())
        assert [o.value for o in sub if o.process != "nemesis"] == [1, 1]
        assert any(o.process == "nemesis" for o in sub)


class TestIndependentChecker:
    def kv_register_history(self, corrupt_key=None):
        # like the reference generators, invocations carry (k, nil) tuples
        h = []
        for k in ("a", "b", "c"):
            h += [invoke_op(0, "write", ind.KV(k, 7)),
                  ok_op(0, "write", ind.KV(k, 7)),
                  invoke_op(0, "read", ind.KV(k, None)),
                  ok_op(0, "read",
                        ind.KV(k, 8 if k == corrupt_key else 7))]
        return History.of(*h)

    def test_all_valid_device_batch(self):
        ck = ind.checker(c.linearizable("tpu"))
        r = ck.check(None, m.cas_register(), self.kv_register_history(), {})
        assert r[c.VALID] is True
        assert set(r["results"]) == {"a", "b", "c"}
        assert all(v["analyzer"] in ("tpu-dense-batch", "tpu-bfs-batch")
                   for v in r["results"].values())
        assert r["failures"] == []

    def test_invalid_key_flagged(self):
        ck = ind.checker(c.linearizable("tpu"))
        r = ck.check(None, m.cas_register(),
                     self.kv_register_history(corrupt_key="b"), {})
        assert r[c.VALID] is False
        assert r["failures"] == ["b"]
        assert r["results"]["b"]["valid?"] is False
        assert r["results"]["a"]["valid?"] is True

    def test_host_fallback_for_generic_model(self):
        h = History.of(
            invoke_op(0, "add", ind.KV("k", 1)),
            ok_op(0, "add", ind.KV("k", 1)),
            invoke_op(0, "read", ind.KV("k", [1])),
            ok_op(0, "read", ind.KV("k", [1])))
        ck = ind.checker(c.linearizable("cpu"))
        r = ck.check(None, m.set_model(), h, {})
        assert r[c.VALID] is True
        # set histories now pack for the device/py-twin path
        assert r["results"]["k"]["analyzer"] == "cpu-jit"

    def test_empty_history(self):
        ck = ind.checker(c.linearizable("tpu"))
        r = ck.check(None, m.cas_register(), [], {})
        assert r[c.VALID] is True


class TestAdya:
    def test_g2_checker(self):
        from jepsen_tpu import adya

        ck = adya.g2_checker()
        ok1 = [invoke_op(0, "insert", {"key": 1, "id": 0}),
               ok_op(0, "insert", {"key": 1, "id": 0}),
               invoke_op(1, "insert", {"key": 1, "id": 1}),
               Op("fail", "insert", {"key": 1, "id": 1}, 1)]
        assert ck.check(None, None, ok1, {})[c.VALID] is True
        both = [invoke_op(0, "insert", {"key": 1, "id": 0}),
                ok_op(0, "insert", {"key": 1, "id": 0}),
                invoke_op(1, "insert", {"key": 1, "id": 1}),
                ok_op(1, "insert", {"key": 1, "id": 1})]
        r = ck.check(None, None, both, {})
        assert r[c.VALID] is False

    def test_g2_gen_pairs(self):
        from jepsen_tpu import adya

        source = adya.g2_gen(keys=iter(["k1", "k2"]))
        with g.with_threads((0, 1)):
            ops = []
            while True:
                o = g.op(source, TEST, len(ops) % 2)
                if o is None:
                    break
                ops.append(o)
        assert len(ops) == 4
        assert {o.value.key for o in ops} == {"k1", "k2"}
        ids = [(o.value.key, o.value.value["id"]) for o in ops]
        assert len(set(ids)) == 4


class TestDenseBatch:
    def test_dense_batch_engages_and_agrees(self):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import batched, cpu, prepare, synth

        subs = {}
        for k in range(6):
            h = synth.generate_register_history(
                40, concurrency=3, seed=k, value_range=3,
                crash_prob=0.1, max_crashes=4)
            if k == 3:
                h = synth.corrupt_history(h, seed=k)
            subs[k] = h
        res = batched.try_check_batch(m.cas_register(), subs)
        assert res is not None
        for k, r in res.items():
            assert r["analyzer"] == "tpu-dense-batch"
            p = prepare.prepare(m.cas_register(), subs[k])
            assert r["valid?"] == cpu.check_packed(p)["valid?"], k

    def test_dense_batch_heterogeneous_lengths(self):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import batched, cpu, prepare, synth

        subs = {"a": synth.generate_register_history(10, concurrency=2,
                                                     seed=1),
                "b": synth.generate_register_history(120, concurrency=4,
                                                     seed=2,
                                                     crash_prob=0.1)}
        res = batched.try_check_batch(m.cas_register(), subs)
        assert res is not None
        for k, r in res.items():
            p = prepare.prepare(m.cas_register(), subs[k])
            assert r["valid?"] == cpu.check_packed(p)["valid?"], k

    def test_wide_window_key_falls_back_to_sparse(self):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import batched, synth

        from jepsen_tpu.history import History, invoke_op, ok_op
        from jepsen_tpu.lin import prepare

        # cas-chain spike: window deterministically 24 (> dense bound 20)
        h = [invoke_op(0, "write", 0), ok_op(0, "write", 0)]
        for i in range(24):
            h.append(invoke_op(i + 1, "cas", [i, i + 1]))
        for i in range(24):
            h.append(ok_op(i + 1, "cas", [i, i + 1]))
        wide = History.of(*h)
        assert prepare.prepare(m.cas_register(), wide).window == 24
        subs = {"w": wide,
                "n": synth.generate_register_history(20, concurrency=3,
                                                     seed=1)}
        res = batched.try_check_batch(m.cas_register(), subs)
        # wide key exceeds dense bounds: sparse batch (or None) takes over
        if res is not None:
            assert all(r["analyzer"] == "tpu-bfs-batch"
                       for r in res.values())
            assert all(r["valid?"] is True for r in res.values())


class TestMixedKernelGroups:
    """Keys with different step functions (history-sized set kernels)
    batch as homogeneous groups instead of de-batching everything."""

    def test_mixed_set_kernels_batch_per_group(self):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import batched, synth

        # Three short keys share a one-word set kernel; one long key
        # (>31 distinct adds) gets a two-word kernel — a different step
        # function, which used to de-batch ALL four keys.
        subs = {}
        for i in range(3):
            subs[f"small{i}"] = synth.generate_set_history(
                24, concurrency=3, seed=i)
        subs["big"] = synth.generate_set_history(60, concurrency=3, seed=9)
        res = batched.try_check_batch(m.SetModel(), subs)
        assert res is not None
        # The homogeneous majority batched; every returned verdict valid.
        assert len(res) >= 3
        assert all(r["valid?"] is True for r in res.values())

    def test_independent_checker_merges_partial_batch(self):
        from jepsen_tpu import checker as c
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import synth
        import jepsen_tpu.independent as ind
        from jepsen_tpu.history import History, Op

        h = []
        for i in range(3):
            sub = synth.generate_set_history(
                20 if i < 2 else 60, concurrency=3, seed=i)
            for op in sub:
                h.append(Op(op.type, op.f, ind.KV(f"k{i}", op.value),
                            op.process))
        r = ind.checker(c.linearizable("tpu")).check(
            None, m.SetModel(), History(h), {})
        assert r["valid?"] is True
        assert r["n-keys"] == 3
        # at least the homogeneous subset rode the device batch
        assert r["batch-engaged"] is True
        assert r["batch-keys"] >= 1


def test_batch_engagement_reported():
    from jepsen_tpu import checker as c
    from jepsen_tpu import models as m
    from jepsen_tpu.history import History, invoke_op, ok_op
    import jepsen_tpu.independent as ind

    h = History.of(invoke_op(0, "write", ind.KV("k", 1)),
                   ok_op(0, "write", ind.KV("k", 1)))
    r = ind.checker(c.linearizable("tpu")).check(
        None, m.cas_register(), h, {})
    assert r["batch-engaged"] is True
    assert r["n-keys"] == 1
    # a lifted NON-linearizable checker must not engage the batch
    r2 = ind.checker(c.unbridled_optimism()).check(
        None, m.cas_register(), h, {})
    assert r2["batch-engaged"] is False
