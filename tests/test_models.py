"""Model semantics tests (reference model.clj) + device-kernel parity.

The Python models are the semantic reference; the JAX kernels in
jepsen_tpu.models.kernels must agree with them on randomized op sequences.
"""

import random

import numpy as np
import pytest

from jepsen_tpu import models as m
from jepsen_tpu.history import Op, invoke_op
from jepsen_tpu.models import kernels as k


def step(model, f, value):
    return model.step(Op("invoke", f, value, 0))


class TestCASRegister:
    def test_write(self):
        assert step(m.cas_register(), "write", 3) == m.CASRegister(3)

    def test_read_nil_matches_anything(self):
        r = m.cas_register(5)
        assert step(r, "read", None) == r

    def test_read_match(self):
        r = m.cas_register(5)
        assert step(r, "read", 5) == r

    def test_read_mismatch(self):
        assert m.is_inconsistent(step(m.cas_register(5), "read", 4))

    def test_cas_ok(self):
        assert step(m.cas_register(5), "cas", [5, 7]) == m.CASRegister(7)

    def test_cas_fail(self):
        assert m.is_inconsistent(step(m.cas_register(5), "cas", [4, 7]))

    def test_initial_nil(self):
        assert m.cas_register().value is None
        assert m.is_inconsistent(step(m.cas_register(), "cas", [0, 1]))


class TestMutex:
    def test_acquire(self):
        assert step(m.mutex(), "acquire", None) == m.Mutex(True)

    def test_double_acquire(self):
        assert m.is_inconsistent(step(m.Mutex(True), "acquire", None))

    def test_release_unheld(self):
        assert m.is_inconsistent(step(m.mutex(), "release", None))

    def test_release(self):
        assert step(m.Mutex(True), "release", None) == m.Mutex(False)


class TestSet:
    def test_add_read(self):
        s = step(step(m.set_model(), "add", 1), "add", 2)
        assert s.step(Op("invoke", "read", [1, 2], 0)) == s

    def test_bad_read(self):
        s = step(m.set_model(), "add", 1)
        assert m.is_inconsistent(s.step(Op("invoke", "read", [1, 2], 0)))


class TestQueues:
    def test_unordered(self):
        q = step(step(m.unordered_queue(), "enqueue", 1), "enqueue", 2)
        q = step(q, "dequeue", 2)  # out of order is fine
        q = step(q, "dequeue", 1)
        assert q == m.unordered_queue()
        assert m.is_inconsistent(step(q, "dequeue", 1))

    def test_fifo(self):
        q = step(step(m.fifo_queue(), "enqueue", 1), "enqueue", 2)
        assert m.is_inconsistent(step(q, "dequeue", 2))
        q = step(q, "dequeue", 1)
        q = step(q, "dequeue", 2)
        assert m.is_inconsistent(step(q, "dequeue", 9))


class TestNoOp:
    def test_noop(self):
        assert step(m.noop, "anything", 42) is m.noop


# ---------------------------------------------------------------------------
# Kernel parity: python model vs JAX kernel on randomized traces.
# Values are small non-negative ints so interning is the identity; NIL maps
# to None.
# ---------------------------------------------------------------------------

def _to_py_value(f, v):
    if f == "cas":
        return [None if x == int(k.NIL) else int(x) for x in v]
    return None if v[0] == int(k.NIL) else int(v[0])


@pytest.mark.parametrize("kern", [k.cas_register_kernel(),
                                  k.register_kernel(), k.mutex_kernel()])
def test_kernel_noop_preserves_state(kern):
    """F_NOOP (identity padding rows in the BFS) must be legal in every
    kernel and leave state untouched."""
    import jax

    for s in ([0], [1], [3]):
        state = np.array(s, np.int32)
        ok, new = jax.jit(kern.step)(state, np.int32(k.F_NOOP),
                                     np.array([7, 7], np.int32))
        assert bool(ok) and np.array_equal(np.asarray(new), state)


def test_kernel_for_carries_mutex_state():
    held = k.kernel_for(m.Mutex(True))
    assert list(held.init_state()) == [1]
    free = k.kernel_for(m.mutex())
    assert list(free.init_state()) == [0]


@pytest.mark.parametrize("model_name", ["cas-register", "register", "mutex"])
def test_kernel_parity(model_name):
    rng = random.Random(42)
    if model_name == "cas-register":
        kern, py0 = k.cas_register_kernel(), m.cas_register()
        fs = ["read", "write", "cas"]
    elif model_name == "register":
        kern, py0 = k.register_kernel(), m.register()
        fs = ["read", "write"]
    else:
        kern, py0 = k.mutex_kernel(), m.mutex()
        fs = ["acquire", "release"]

    import jax

    jit_step = jax.jit(kern.step)
    for _trial in range(50):
        py = py0
        state = np.asarray(kern.init_state())
        for _step_i in range(8):
            f = rng.choice(fs)
            if f == "cas":
                v = np.array([rng.randint(0, 3), rng.randint(0, 3)], np.int32)
            elif f in ("read",):
                v = np.array(
                    [rng.choice([int(k.NIL), 0, 1, 2, 3]), 0], np.int32)
            elif f == "write":
                v = np.array([rng.randint(0, 3), 0], np.int32)
            else:
                v = np.array([0, 0], np.int32)

            ok_dev, new_state = jit_step(state, np.int32(k.F_IDS[f]), v)
            res_py = py.step(Op("invoke", f, _to_py_value(f, v), 0))
            ok_py = not m.is_inconsistent(res_py)

            assert bool(ok_dev) == ok_py, (
                f"{model_name}: step {f} {v} from {state}: "
                f"device ok={bool(ok_dev)} python ok={ok_py}")
            if ok_py:
                py = res_py
                state = np.asarray(new_state)
                # cross-check state agreement for registers
                if model_name in ("cas-register", "register"):
                    expect = int(k.NIL) if py.value is None else py.value
                    assert int(state[0]) == expect
                else:
                    assert bool(state[0]) == py.locked
