"""List-append txn wire clients against in-process fake SQL servers
(the house pattern, test_crdb_sql_clients.py): the pgwire TxnClient
(cockroachdb + postgres-rds) and the mysqlwire TxnAppendClient
(tidb + galera) execute micro-op transactions against a tiny
list-append SQL engine behind the REAL wire protocols — framing,
BEGIN/COMMIT, retry, and the `:info`-on-ambiguous-commit soundness
contract all run for real.
"""

import re
import socket
import struct
import threading

import pytest

from jepsen_tpu.history import Op
from jepsen_tpu.suites import cockroachdb as cr

from test_crdb_sql_clients import PgWireServer
from test_mysqlwire import NONCE, _greeting, _packet, _read_packet

# Quick tier: no XLA compiles (the cpu oracle checks the histories).
pytestmark = pytest.mark.quick


class MiniTxnEngine:
    """List-append SQL in both dialects: INSERT .. ON CONFLICT/ON
    DUPLICATE KEY concat, SELECT vals. Staged writes are visible to
    the transaction's own reads and apply at COMMIT. Knobs:
    ``abort_commits`` raises 40001 on the first N commits (retry
    path); ``ambiguous_commits`` raises XXA00 AFTER applying (the
    commit-fate-unknown path — the client must complete ``:info``)."""

    def __init__(self, abort_commits: int = 0, ambiguous_commits: int = 0):
        self.lists: dict = {}
        self.glock = threading.RLock()
        self.abort_commits = abort_commits
        self.ambiguous_commits = ambiguous_commits

    def execute(self, sql: str, txn):
        s = " ".join(sql.split())
        if s in ("BEGIN", "COMMIT", "ROLLBACK"):
            return self._txn_ctl(s, txn)
        with self.glock:
            if s.startswith("CREATE") or s.startswith("SET TRANSACTION"):
                return []
            m = re.match(r"INSERT INTO (\S+) \(k, vals\) VALUES "
                         r"\((\d+), '(\d+)'\) ON ", s)
            if m:
                _t, k, v = m.groups()
                txn.setdefault("appends", []).append((int(k), int(v)))
                return []
            m = re.match(r"SELECT vals FROM (\S+) WHERE k = (\d+)$", s)
            if m:
                k = int(m.group(2))
                vals = list(self.lists.get(k, []))
                vals += [v for kk, v in txn.get("appends", [])
                         if kk == k]
                return [(",".join(str(v) for v in vals) or None,)]
        raise ValueError(f"unhandled sql {s!r}")

    def _txn_ctl(self, s, txn):
        if s == "BEGIN":
            txn["open"] = True
            txn["appends"] = []
            return []
        if s == "ROLLBACK":
            txn["open"] = False
            txn["appends"] = []
            return []
        with self.glock:
            try:
                if self.abort_commits > 0 and txn.get("appends"):
                    self.abort_commits -= 1
                    raise KeyError("40001", "restart transaction")
                for k, v in txn.get("appends", []):
                    self.lists.setdefault(k, []).append(v)
                if self.ambiguous_commits > 0 and txn.get("appends"):
                    self.ambiguous_commits -= 1
                    # Applied, but the client cannot know that.
                    raise KeyError("XXA00", "ambiguous commit result")
            finally:
                txn["open"] = False
                txn["appends"] = []
            return []


def _pg_client(engine):
    srv = PgWireServer(engine)
    client = cr.TxnClient(port=srv.port).open(None, "127.0.0.1")
    return srv, client


def _txn_op(mops, proc=0):
    return Op("invoke", "txn", [list(m) for m in mops], proc)


class TestPgTxnClient:
    def test_round_trip_and_checker_valid(self):
        srv, c = _pg_client(MiniTxnEngine())
        try:
            h = []
            for mops in ([["append", 1, 1], ["r", 1, None]],
                         [["append", 1, 2]],
                         [["r", 1, None], ["append", 2, 3]],
                         [["r", 1, None], ["r", 2, None]]):
                op = _txn_op(mops)
                h.append(op)
                h.append(c.invoke(None, op))
            done = h[-1]
            assert done.type == "ok"
            assert done.value == [["r", 1, [1, 2]], ["r", 2, [3]]]
            # Own staged append visible to the txn's later read.
            assert h[1].value == [["append", 1, 1], ["r", 1, [1]]]

            from jepsen_tpu import txn

            r = txn.check(h, algorithm="cpu")
            assert r["valid?"] is True, r
        finally:
            c.close(None)
            srv.close()

    def test_serialization_abort_retries(self):
        srv, c = _pg_client(MiniTxnEngine(abort_commits=1))
        try:
            done = c.invoke(None, _txn_op([["append", 5, 9]]))
            assert done.type == "ok"           # retried past the 40001
        finally:
            c.close(None)
            srv.close()

    def test_ambiguous_commit_completes_info_never_fail(self):
        engine = MiniTxnEngine(ambiguous_commits=1)
        srv, c = _pg_client(engine)
        try:
            done = c.invoke(None, _txn_op([["append", 7, 1]]))
            assert done.type == "info"         # applied; fail = unsound
            assert engine.lists[7] == [1]
            # A later read observes it — the checker must stay valid
            # because the :info txn's write is recoverable.
            h = [_txn_op([["append", 7, 1]]),
                 done.replace(type="info"),
                 _txn_op([["r", 7, None]], 1),
                 Op("ok", "txn", [["r", 7, [1]]], 1)]
            from jepsen_tpu import txn

            assert txn.check(h, algorithm="cpu")["valid?"] is True
        finally:
            c.close(None)
            srv.close()


# --- mysql: engine-backed fake server over the real wire protocol -----------


def _my_err(code: str, msg: str) -> bytes:
    return (b"\xff" + struct.pack("<H", 1213)
            + b"#" + code.encode() + msg.encode())


class MyWireServer:
    """Handshake + COM_QUERY dispatch into MiniTxnEngine (auth
    accepted unconditionally; result sets are one string column)."""

    def __init__(self, engine: MiniTxnEngine):
        self.engine = engine
        self.srv = socket.socket()
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(8)
        self.port = self.srv.getsockname()[1]
        self.alive = True
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while self.alive:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        txn: dict = {"open": False, "appends": []}
        buf = bytearray()
        try:
            conn.sendall(_packet(0, _greeting(NONCE)))
            _read_packet(conn, buf)            # handshake response
            conn.sendall(_packet(2, b"\x00\x00\x00\x02\x00\x00\x00"))
            while True:
                cmd = _read_packet(conn, buf)
                if not cmd or cmd[:1] == b"\x01":          # COM_QUIT
                    return
                if cmd[:1] != b"\x03":
                    conn.sendall(_packet(1, b"\x00\x00\x00\x02\x00"
                                         b"\x00\x00"))
                    continue
                sql = cmd[1:].decode()
                try:
                    rows = self.engine.execute(sql, txn)
                except KeyError as e:
                    code, msg = e.args
                    conn.sendall(_packet(1, _my_err(code, msg)))
                    continue
                except ValueError as e:
                    conn.sendall(_packet(1, _my_err("42000", str(e))))
                    continue
                if not rows:
                    conn.sendall(_packet(1, b"\x00\x00\x00\x02\x00"
                                         b"\x00\x00"))
                    continue
                pkts = [b"\x01", b"\x03def",
                        b"\xfe\x00\x00\x02\x00"]
                for row in rows:
                    cell = row[0]
                    if cell is None:
                        pkts.append(b"\xfb")
                    else:
                        cb = str(cell).encode()
                        pkts.append(bytes([len(cb)]) + cb)
                pkts.append(b"\xfe\x00\x00\x02\x00")
                for i, p in enumerate(pkts):
                    conn.sendall(_packet(1 + i, p))
        except OSError:
            pass
        finally:
            conn.close()

    def close(self):
        self.alive = False
        self.srv.close()


def _my_client(engine):
    from jepsen_tpu.suites import mysql_clients

    srv = MyWireServer(engine)
    client = mysql_clients.TxnAppendClient(port=srv.port) \
        .open(None, "127.0.0.1")
    return srv, client


class TestMysqlTxnClient:
    def test_round_trip_and_checker_valid(self):
        srv, c = _my_client(MiniTxnEngine())
        try:
            h = []
            for mops in ([["append", 1, 1]],
                         [["r", 1, None], ["append", 1, 2]],
                         [["r", 1, None]]):
                op = _txn_op(mops)
                h.append(op)
                h.append(c.invoke(None, op))
            assert h[-1].type == "ok"
            assert h[-1].value == [["r", 1, [1, 2]]]

            from jepsen_tpu import txn

            assert txn.check(h, algorithm="cpu")["valid?"] is True
        finally:
            c.close(None)
            srv.close()

    def test_commit_error_completes_info(self):
        engine = MiniTxnEngine(ambiguous_commits=1)
        srv, c = _my_client(engine)
        try:
            done = c.invoke(None, _txn_op([["append", 3, 4]]))
            assert done.type == "info"
            assert engine.lists[3] == [4]      # applied — fail = unsound
        finally:
            c.close(None)
            srv.close()

    def test_statement_error_fails_definitely(self):
        srv, c = _my_client(MiniTxnEngine())
        try:
            done = c.invoke(
                None, Op("invoke", "weird", [["r", 1, None]], 0))
            assert done.type == "fail"
        finally:
            c.close(None)
            srv.close()


class TestSuiteWiring:
    def test_all_four_sql_suites_expose_txn(self):
        from jepsen_tpu.suites import workloads

        # cockroachdb: registry + client factory.
        assert "txn" in cr.tests_registry()
        assert cr.tests_registry()["txn"]()["checker"].is_txn_cycles
        t = cr.test({"workload": "txn", "fake": False, "nodes": ["n1"]})
        assert isinstance(t["client"], cr.TxnClient)
        assert isinstance(t["generator"], object)

        # The fake-mode map carries the workload's fake txn client.
        t = cr.test({"workload": "txn", "fake": True, "nodes": ["n1"]})
        assert isinstance(t["client"], workloads.TxnClient)

        # tidb routes the mysql-dialect client.
        from jepsen_tpu.suites import mysql_clients, tidb

        t = tidb.test({"workload": "txn", "fake": False,
                       "nodes": ["n1"]})
        assert isinstance(t["client"], mysql_clients.TxnAppendClient)

        # galera via the shared registry helper.
        wl, client = mysql_clients.bank_or_dirty_reads("txn")
        assert wl["checker"].is_txn_cycles
        assert isinstance(client, mysql_clients.TxnAppendClient)

        # postgres-rds txn reuses the pgwire client with RDS params.
        from jepsen_tpu.suites import postgres_rds

        t = postgres_rds.test({"workload": "txn", "fake": False,
                               "nodes": ["n1"], "host": "db.example",
                               "dbname": "jep"})
        assert isinstance(t["client"], cr.TxnClient)
        assert t["client"].host == "db.example"
        assert t["client"].admin_database == "jep"

    def test_txn_setup_ddl_is_dialect_aware(self):
        # Regression (review finding): stock PostgreSQL has no
        # `CREATE DATABASE IF NOT EXISTS`, no db-qualified table names
        # (they parse as schemas), and no STRING type — the RDS-shaped
        # client must emit unqualified TEXT DDL, while the CockroachDB
        # default keeps its dialect.
        crdb = cr.TxnClient()
        stmts = crdb._setup_stmts()
        assert any("CREATE DATABASE" in s for s in stmts)
        assert any("jepsen.jepsen_txn" in s and "STRING" in s
                   for s in stmts)

        rds = cr.TxnClient(user="jepsen", database="jep",
                           admin_database="jep", host="db.example")
        (stmt,) = rds._setup_stmts()
        assert "CREATE TABLE IF NOT EXISTS jepsen_txn" in stmt
        assert "TEXT" in stmt and "." not in stmt.split("EXISTS")[1]
