"""Sharded frontier search on the virtual 8-device CPU mesh: parity with
the single-device kernel and the CPU oracle, including frontier sizes that
force real cross-device dedup."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from jepsen_tpu import models as m
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.lin import cpu, prepare, sharded, synth


def mesh(n):
    devs = jax.devices()[:n]
    return Mesh(np.array(devs), ("d",))


@pytest.mark.parametrize("n_dev", [2, 8])
def test_parity_valid(n_dev):
    h = synth.generate_register_history(60, concurrency=4, seed=5,
                                        crash_prob=0.15)
    p = prepare.prepare(m.cas_register(), h)
    want = cpu.check_packed(p)["valid?"]
    got = sharded.check_packed(p, mesh=mesh(n_dev))
    assert got["valid?"] == want is True


def test_parity_invalid():
    h = synth.corrupt_history(
        synth.generate_register_history(60, concurrency=4, seed=6,
                                        crash_prob=0.1), seed=6)
    p = prepare.prepare(m.cas_register(), h)
    want = cpu.check_packed(p)
    got = sharded.check_packed(p, mesh=mesh(8))
    assert got["valid?"] == want["valid?"]
    if got["valid?"] is False:
        assert got["op"]["index"] == want["op"]["index"]


def test_big_frontier_spans_devices():
    """Many crashed writes inflate the frontier beyond one device's
    capacity: with cap_local=8 on 8 devices (64 global), a 2^5-config
    frontier must spill across shards and still agree with the oracle."""
    h = synth.generate_register_history(40, concurrency=6, seed=9,
                                        crash_prob=0.5, max_crashes=5)
    p = prepare.prepare(m.cas_register(), h)
    want = cpu.check_packed(p)["valid?"]
    got = sharded.check_packed(p, mesh=mesh(8), cap_schedule=(8, 1024))
    assert got["valid?"] == want


def test_overflow_escalates_per_device(monkeypatch):
    # Pin the episode cap ladder down to 1 as well: the compact band
    # otherwise rescues an exhausted chunk cap_schedule by re-sharding
    # at the JEPSEN_TPU_MESH_CAPS episode rungs and deciding anyway.
    monkeypatch.setenv("JEPSEN_TPU_MESH_CAPS", "1")
    h = synth.generate_register_history(40, concurrency=6, seed=9,
                                        crash_prob=0.5, max_crashes=5)
    p = prepare.prepare(m.cas_register(), h)
    r = sharded.check_packed(p, mesh=mesh(2), cap_schedule=(1,),
                             engine="sparse")
    assert r["valid?"] == "unknown"
    assert r["overflow"] == "capacity"
    assert r["mesh-stats"]["episodes"] >= 1


def test_mutex_sharded():
    h = History.of(invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
                   invoke_op(1, "acquire", None), ok_op(1, "acquire", None))
    p = prepare.prepare(m.mutex(), h)
    assert sharded.check_packed(p, mesh=mesh(2))["valid?"] is False


def test_multiword_mesh_rejects_unchunked_long_history():
    # the MULTIWORD mesh path runs the whole history as one program;
    # past the bound it must refuse rather than risk a watchdog kill.
    # (The packed-key mesh path chunks and has no length bound.)
    from jepsen_tpu.lin import sharded

    # a >=32-element set packs its state as TWO words (S=2), which keeps
    # it outside the packed-key gate => multiword mesh path
    p = prepare.prepare(m.set_model(), synth.generate_set_history(
        50, concurrency=4, seed=2))
    assert p.init_state.shape[0] > 1  # guard the routing assumption
    import dataclasses

    big = dataclasses.replace(p, R=sharded.MAX_SHARDED_ROWS + 1)
    r = sharded.check_packed(big, mesh=mesh(2), engine="sparse")
    assert r["valid?"] == "unknown"
    assert "exceeds" in r["error"]


def test_packed_mesh_chunks_long_history():
    # ~1.3k return events at chunk 512: three carried-frontier chunk
    # dispatches on the mesh, parity with the oracle.
    h = synth.generate_register_history(2600, concurrency=4, seed=6,
                                        value_range=3, crash_prob=0.02,
                                        max_crashes=3)
    p = prepare.prepare(m.cas_register(), h)
    want = cpu.check_packed(p)["valid?"]
    r = sharded.check_packed(p, mesh=mesh(8), engine="sparse")
    assert r["dedup"] == "packed-keys"
    assert r["valid?"] == want
    hb = synth.corrupt_history(h, seed=6)
    pb = prepare.prepare(m.cas_register(), hb)
    rb = sharded.check_packed(pb, mesh=mesh(8), engine="sparse")
    assert rb["valid?"] == cpu.check_packed(pb)["valid?"]


class TestPackedKeyDedup:
    """The packed-u32-key collective dedup (one all_gather of keys over
    ICI instead of bits+state columns). Register/mutex families route
    packed; multiword states (sets) keep the column dedup."""

    def test_register_routes_packed(self):
        h = synth.generate_register_history(80, concurrency=5, seed=3,
                                            value_range=3, crash_prob=0.1)
        p = prepare.prepare(m.cas_register(), h)
        r = sharded.check_packed(p, mesh=mesh(8), engine="sparse")
        assert r["dedup"] == "packed-keys"
        assert r["valid?"] is cpu.check_packed(p)["valid?"] is True

    @pytest.mark.parametrize("seed", range(5))
    def test_packed_parity_corrupted(self, seed):
        h = synth.generate_register_history(70, concurrency=5, seed=seed,
                                            value_range=3, crash_prob=0.1)
        hb = synth.corrupt_history(h, seed=seed)
        p = prepare.prepare(m.cas_register(), hb)
        want = cpu.check_packed(p)
        r = sharded.check_packed(p, mesh=mesh(8), engine="sparse")
        assert r["valid?"] == want["valid?"]
        if want["valid?"] is False:
            assert r["op"] == want["op"]

    def test_set_model_routes_multiword(self):
        h = synth.generate_set_history(50, concurrency=4, seed=2)
        p = prepare.prepare(m.set_model(), h)
        r = sharded.check_packed(p, mesh=mesh(8), engine="sparse")
        assert r["dedup"] == "multiword"
        assert r["valid?"] is cpu.check_packed(p)["valid?"] is True

    def test_mutex_packed_parity(self):
        h = synth.generate_mutex_history(50, concurrency=4, seed=1,
                                         crash_prob=0.1)
        p = prepare.prepare(m.mutex(), h)
        r = sharded.check_packed(p, mesh=mesh(4), engine="sparse")
        assert r["dedup"] == "packed-keys"
        assert r["valid?"] == cpu.check_packed(p)["valid?"]


def test_mesh_explain_final_paths():
    # Both mesh engines must explain device-decided violations like the
    # single-chip engines: configs + final-paths from a CPU tail replay.
    h = synth.corrupt_history(
        synth.generate_register_history(60, concurrency=4, seed=5,
                                        value_range=3, crash_prob=0.1),
        seed=5)
    p = prepare.prepare(m.cas_register(), h)
    want = cpu.check_packed(p)
    assert want["valid?"] is False  # keep this test's coverage honest
    r = sharded.check_packed(p, mesh=mesh(8), explain=True)
    assert r["valid?"] is False
    assert r["op"] == want["op"]
    assert r["final-paths"], r
    rs = sharded.check_packed(p, mesh=mesh(8), engine="sparse",
                              explain=True)
    assert rs["valid?"] is False and rs["final-paths"], rs
    # multiword mesh path explains too (replay from the initial config);
    # the >=32-element set carries a 2-word state vector, which keeps it
    # off the packed-key route
    hs = list(synth.generate_set_history(50, concurrency=4, seed=2))
    for i in range(len(hs) - 1, -1, -1):
        if hs[i].is_ok and hs[i].f == "read" and hs[i].value is not None:
            hs[i] = hs[i].replace(value=list(hs[i].value) + [9999])
            break
    ps = prepare.prepare(m.set_model(), hs)
    rm = sharded.check_packed(ps, mesh=mesh(8), engine="sparse",
                              explain=True)
    assert rm["valid?"] is False and rm["dedup"] == "multiword"
    assert rm["final-paths"], rm
