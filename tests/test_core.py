"""Runner integration tests with the in-memory fake DB — parity with the
reference's core_test.clj basic-cas-test (:18-28, real CAS checking against
an atom register through the full run lifecycle) and worker-recovery-test
(:86-101, crashing clients consume exactly n ops)."""

from jepsen_tpu import checker as c
from jepsen_tpu import core
from jepsen_tpu import generator as g
from jepsen_tpu import models as m
from jepsen_tpu import tests_support as ts
from jepsen_tpu.history import Op


def test_basic_cas():
    reg = ts.AtomRegister()
    test = ts.noop_test(
        client=ts.AtomClient(reg, latency=0.001),
        generator=g.clients(g.limit(60, g.stagger(0.001, g.cas(5)))),
        model=m.cas_register(),
        checker=c.linearizable("cpu"),
    )
    result = core.run(test)
    assert result["results"][c.VALID] is True
    hist = result["history"]
    assert len(hist) >= 120  # invoke + completion per op
    invokes = [o for o in hist if o.is_invoke]
    completions = [o for o in hist if not o.is_invoke]
    assert len(invokes) == 60
    assert len(invokes) == len(completions)
    # indices are assigned
    assert [o.index for o in hist] == list(range(len(hist)))
    # every op carries a relative timestamp
    assert all(isinstance(o.time, int) for o in hist)


def test_basic_cas_device_checker():
    reg = ts.AtomRegister()
    test = ts.noop_test(
        client=ts.AtomClient(reg),
        generator=g.clients(g.limit(40, g.cas(5))),
        model=m.cas_register(),
        checker=c.linearizable("tpu"),
    )
    result = core.run(test)
    assert result["results"][c.VALID] is True
    assert result["results"]["analyzer"] in ("tpu-dense", "tpu-bfs")


def test_lying_client_detected():
    """A client that acks writes but drops them must produce an invalid
    history."""

    class LyingClient(ts.AtomClient):
        def invoke(self, test, op):
            if op.f == "write":
                return op.replace(type="ok")  # ack without applying
            return super().invoke(test, op)

        def open(self, test, node):
            return LyingClient(self.register)

    reg = ts.AtomRegister()
    reg.write(99)  # writes can never change this value: reads must see 99
    test = ts.noop_test(
        client=LyingClient(reg),
        generator=g.clients(g.limit(40, g.mix(
            [Op("invoke", "read", None), lambda:
             Op("invoke", "write", 1)]))),
        model=m.cas_register(99),
        checker=c.linearizable("cpu"),
    )
    result = core.run(test)
    assert result["results"][c.VALID] is False


def test_worker_recovery():
    """Crashing clients must re-incarnate processes and consume exactly n
    generator ops (core_test.clj:86-101)."""
    test = ts.noop_test(
        client=ts.CrashyClient(),
        generator=g.clients(g.limit(20, Op("invoke", "read", None))),
        checker=c.unbridled_optimism(),
    )
    result = core.run(test)
    hist = result["history"]
    invokes = [o for o in hist if o.is_invoke]
    infos = [o for o in hist if o.is_info]
    assert len(invokes) == 20
    assert len(infos) == 20
    # every process id appears at most once among invokes (re-incarnation)
    procs = [o.process for o in invokes]
    assert len(set(procs)) == len(procs)


def test_nemesis_ops_reach_history():
    from jepsen_tpu import nemesis as n

    class MarkerNemesis(n.Nemesis):
        def invoke(self, test, op):
            return op.replace(value="marked")

    test = ts.noop_test(
        client=ts.AtomClient(ts.AtomRegister()),
        nemesis=MarkerNemesis(),
        generator=g.nemesis(
            g.limit(2, Op("info", "start", None)),
            g.limit(10, g.cas(5))),
    )
    result = core.run(test)
    nem_ops = [o for o in result["history"] if o.process == "nemesis"]
    assert len(nem_ops) == 4  # 2 invocations + 2 completions
    assert [o.value for o in nem_ops].count("marked") == 2


def test_generator_sees_test_and_process():
    seen = []

    def source(test, process):
        if len(seen) >= 5:
            return None
        seen.append(process)
        return Op("invoke", "read", None)

    test = ts.noop_test(
        client=ts.AtomClient(ts.AtomRegister()),
        concurrency=2,
        generator=g.clients(source),
    )
    core.run(test)
    assert len(seen) == 5
