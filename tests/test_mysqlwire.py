"""MySQL wire-protocol client tests against an in-process fake server.

The fake server *verifies* the client's mysql_native_password token
server-side (it knows the password and recomputes the scramble), so the
handshake test exercises real auth, not just framing. Mirrors the
reference's JDBC surface for galera/percona/tidb/mysql-cluster
(galera.clj:40-120, tidb/sql.clj).
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading

import pytest

from jepsen_tpu.suites.mysqlwire import MyClient, MyError, _scramble

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick

PASSWORD = "s3cret"
NONCE = bytes(range(1, 21))          # 20-byte challenge


def _packet(seq: int, payload: bytes) -> bytes:
    return struct.pack("<I", len(payload))[:3] + bytes([seq]) + payload


def _greeting(nonce: bytes, plugin: bytes = b"mysql_native_password") \
        -> bytes:
    cap = 0x0200 | 0x8000            # PROTOCOL_41 | SECURE_CONNECTION
    cap_hi = 0x0008                  # PLUGIN_AUTH >> 16
    g = (b"\x0a" + b"5.7.99-fake\x00" + struct.pack("<I", 7)
         + nonce[:8] + b"\x00" + struct.pack("<H", cap)
         + b"\x21" + struct.pack("<H", 2) + struct.pack("<H", cap_hi)
         + bytes([21]) + b"\x00" * 10
         + nonce[8:20] + b"\x00" + plugin + b"\x00")
    return g


def _read_packet(conn, buf: bytearray) -> bytes:
    while len(buf) < 4:
        buf += conn.recv(4096)
    n = buf[0] | (buf[1] << 8) | (buf[2] << 16)
    while len(buf) < 4 + n:
        buf += conn.recv(4096)
    payload = bytes(buf[4:4 + n])
    del buf[:4 + n]
    return payload


def _expected_token(password: str, nonce: bytes) -> bytes:
    p1 = hashlib.sha1(password.encode()).digest()
    p2 = hashlib.sha1(p1).digest()
    mix = hashlib.sha1(nonce + p2).digest()
    return bytes(a ^ b for a, b in zip(p1, mix))


OK = b"\x00\x00\x00\x02\x00\x00\x00"


def _serve(srv, script):
    """Accept one connection, run the handshake + scripted responses."""

    def run():
        conn, _ = srv.accept()
        buf = bytearray()
        conn.sendall(_packet(0, _greeting(NONCE)))
        resp = _read_packet(conn, buf)
        # HandshakeResponse41: caps(4) maxpkt(4) charset(1) 23x user\0
        off = 4 + 4 + 1 + 23
        end = resp.index(b"\x00", off)
        user = resp[off:end].decode()
        off = end + 1
        tlen = resp[off]
        token = resp[off + 1:off + 1 + tlen]
        if user != "root" or token != _expected_token(PASSWORD, NONCE):
            conn.sendall(_packet(2, b"\xff" + struct.pack("<H", 1045)
                                 + b"#28000Access denied"))
            conn.close()
            return
        conn.sendall(_packet(2, OK))
        for reply in script:
            _read_packet(conn, buf)            # COM_QUERY
            for i, pkt in enumerate(reply):
                conn.sendall(_packet(1 + i, pkt))
        conn.close()

    th = threading.Thread(target=run, daemon=True)
    th.start()
    return th


def _fake_server(script):
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    th = _serve(srv, script)
    return srv, srv.getsockname()[1], th


RESULT_SET = [
    b"\x02",                                   # 2 columns
    b"\x03def",                                # col defs (content unused)
    b"\x03def",
    b"\xfe\x00\x00\x02\x00",                   # EOF after columns
    b"\x011\xfb",                              # row ("1", NULL)
    b"\x012\x02hi",                            # row ("2", "hi")
    b"\xfe\x00\x00\x02\x00",                   # EOF after rows
]
ERR_DEADLOCK = [b"\xff" + struct.pack("<H", 1213)
                + b"#40001Deadlock found"]
OK_AFFECTED_3 = [b"\x00\x03\x00\x02\x00\x00\x00"]


class TestMyClient:
    def test_handshake_query_error_affected(self):
        srv, port, th = _fake_server([RESULT_SET, ERR_DEADLOCK,
                                      OK_AFFECTED_3])
        c = MyClient("127.0.0.1", port, user="root", password=PASSWORD)
        assert c.query("SELECT * FROM t") == [("1", None), ("2", "hi")]
        with pytest.raises(MyError) as ei:
            c.query("UPDATE t SET x = 1")
        assert ei.value.code == 1213 and ei.value.retryable
        assert c.query("UPDATE t SET x = 2") == []
        assert c.last_affected == 3
        srv.close()

    def test_wrong_password_denied(self):
        srv, port, th = _fake_server([])
        with pytest.raises(MyError) as ei:
            MyClient("127.0.0.1", port, user="root", password="nope")
        assert ei.value.code == 1045
        srv.close()

    def test_scramble_roundtrip_property(self):
        # XOR structure: token ^ SHA1(nonce+SHA1(SHA1(pw))) == SHA1(pw)
        tok = _scramble("pw", NONCE)
        p1 = hashlib.sha1(b"pw").digest()
        p2 = hashlib.sha1(p1).digest()
        mix = hashlib.sha1(NONCE + p2).digest()
        assert bytes(a ^ b for a, b in zip(tok, mix)) == p1
        assert _scramble("", NONCE) == b""

    def test_auth_switch(self):
        # Server answers the handshake with an AuthSwitchRequest carrying
        # a fresh nonce; the client must re-scramble and succeed.
        nonce2 = bytes(range(40, 60))

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]

        def run():
            conn, _ = srv.accept()
            buf = bytearray()
            conn.sendall(_packet(0, _greeting(NONCE)))
            _read_packet(conn, buf)
            conn.sendall(_packet(2, b"\xfemysql_native_password\x00"
                                 + nonce2 + b"\x00"))
            tok = _read_packet(conn, buf)
            good = tok == _expected_token(PASSWORD, nonce2)
            conn.sendall(_packet(4, OK if good else
                                 b"\xff" + struct.pack("<H", 1045)
                                 + b"#28000denied"))
            conn.close()

        threading.Thread(target=run, daemon=True).start()
        MyClient("127.0.0.1", port, user="root", password=PASSWORD)
        srv.close()

    def test_unsupported_plugin_raises(self):
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]

        def run():
            conn, _ = srv.accept()
            buf = bytearray()
            conn.sendall(_packet(0, _greeting(NONCE)))
            _read_packet(conn, buf)
            conn.sendall(_packet(2, b"\xfecaching_sha2_password\x00xx\x00"))
            conn.close()

        threading.Thread(target=run, daemon=True).start()
        with pytest.raises(MyError, match="caching_sha2"):
            MyClient("127.0.0.1", port, user="root", password=PASSWORD)
        srv.close()


def test_mysql_family_suites_ungated():
    # VERDICT round-1: the MySQL-family suites must carry real wire
    # clients, not GatedClient stubs.
    from jepsen_tpu.suites import (common, galera, mysql_cluster, percona,
                                   tidb)
    from jepsen_tpu.suites.mysql_clients import _SqlClient

    for mod, opts in ((galera, {}), (percona, {}),
                      (tidb, {}), (mysql_cluster, {})):
        t = mod.test(dict(opts))
        assert isinstance(t["client"], _SqlClient), mod.__name__
        assert not isinstance(t["client"], common.GatedClient)


def test_no_gated_wire_clients():
    # Round-1 had 12 gated wire clients; the VERDICT target was <= 8.
    # Native mysql/zk/irc/mongo/amqp/rethink/aerospike/hazelcast wire
    # clients brought it to zero.
    import importlib
    import pkgutil

    import jepsen_tpu.suites as suites_pkg
    from jepsen_tpu.suites import common

    gated = []
    for info in pkgutil.iter_modules(suites_pkg.__path__):
        mod = importlib.import_module(f"jepsen_tpu.suites.{info.name}")
        if not hasattr(mod, "test"):
            continue
        try:
            t = mod.test({})
        except Exception:
            continue
        if isinstance(t.get("client"), common.GatedClient):
            gated.append(info.name)
    assert len(gated) == 0, gated
