"""Fused in-VMEM closure-fixpoint kernel parity
(lin/psort_fused.py, the kill-the-tunnel stage-floor half): one fused
fixpoint must equal the unfused pass chain
(bfs._closure_pass_keys_compact iterated to convergence) bit for bit —
keys, count, convergence/overflow flags — in interpreter mode (the
psort parity precedent; the real Mosaic backend rides the bench).

The engine-level tests drive bfs.check_packed fused-on vs fused-off
over the compact register band, single-key AND pair-key widths; the
kernel-level test compares one fixpoint against the literal unfused
loop on real per-row tables.

Only the chip-free gate test rides the quick tier: the parity tests
compile interpret-mode kernels at several (cap, M) shapes — minutes
on a cold cache (the pair-band wave-parity precedent: compile-heavy
parity stays in the default tier)."""

import numpy as np
import pytest

from jepsen_tpu import models as m
from jepsen_tpu.lin import bfs, prepare, psort_fused, synth

quick = pytest.mark.quick
pytestmark = pytest.mark.compiles


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    # The kernel's own gate (decoupled from JEPSEN_TPU_PSORT, whose
    # pallas kernels need a newer pltpu API than some sandboxes have).
    monkeypatch.setenv("JEPSEN_TPU_PSORT_FUSED", "interpret")


@quick
def test_fits_gate():
    assert psort_fused.fits(1024, 8, 3)
    assert not psort_fused.fits(8, 8, 3)          # below LANE
    assert not psort_fused.fits(1000, 8, 3)       # not a power of two
    assert not psort_fused.fits(1 << 17, 32, 3)   # past the VMEM bound
    assert not psort_fused.fits(1024, 8, 7)       # state id past 6 bits


@quick
def test_max_n_knob(monkeypatch):
    # Default = the proven psort bound; the env exponent raises it;
    # the clamp refuses anything past the proven 2^21 sort envelope.
    from jepsen_tpu.lin import psort

    monkeypatch.delenv("JEPSEN_TPU_PSORT_FUSED_MAX_N", raising=False)
    assert psort_fused.max_n() == psort.PSORT_MAX_N
    monkeypatch.setenv("JEPSEN_TPU_PSORT_FUSED_MAX_N", "20")
    assert psort_fused.max_n() == 1 << 20
    monkeypatch.setenv("JEPSEN_TPU_PSORT_FUSED_MAX_N", "25")
    assert psort_fused.max_n() == 1 << psort_fused.FUSED_MAX_EXP
    # fits() honors the raised bound only when the caller passes it —
    # the default stays the proven envelope (bfs plumbs max_n() in as
    # the static use_fused arg; an env change alone must never flip a
    # traced gate).
    # cap 2^14 x (1+40) columns pads to 2^20: past the default bound,
    # inside a raised one.
    assert not psort_fused.fits(1 << 14, 40, 3)
    assert psort_fused.fits(1 << 14, 40, 3, max_pad=1 << 20)
    assert not psort_fused.fits(1 << 14, 40, 3, max_pad=1 << 19)


def _packed(n, concurrency, seed, value_range=5):
    h = synth.generate_register_history(
        n, concurrency=concurrency, seed=seed,
        value_range=value_range, crash_prob=0)
    return prepare.prepare(m.cas_register(), h)


def _parity(monkeypatch, p, cap_schedule):
    monkeypatch.setenv("JEPSEN_TPU_PSORT_FUSED", "0")
    off = bfs.check_packed(p, cap_schedule=cap_schedule)
    monkeypatch.setenv("JEPSEN_TPU_PSORT_FUSED", "interpret")
    on = bfs.check_packed(p, cap_schedule=cap_schedule)
    assert on["valid?"] is off["valid?"]
    assert on.get("final-frontier-size") == \
        off.get("final-frontier-size")
    return on


def test_engine_parity_single_key(monkeypatch):
    # Window ~20 + 3 state bits: single-u32 keys, compact tables.
    # Shapes kept SMALL: the interpret-mode bitonic chain is
    # O(n log^2 n) per pass per row on the CPU mesh.
    p = _packed(60, 16, 7)
    assert p.window + max(len(p.unintern), 2).bit_length() <= 31
    r = _parity(monkeypatch, p, (256,))
    assert r["valid?"] is True


@pytest.mark.slow
def test_engine_parity_pair_key(monkeypatch):
    # Wider window pushes past 31 bits: (hi, lo) pair keys — the
    # cockroach-class band the fused kernel exists for. SLOW tier:
    # ~70 rows each paying the interpret-mode pair-bitonic chain run
    # minutes on the CPU mesh; tier-1 pair coverage is the one-row
    # kernel-level parity below (the 100k-txn acceptance-twin
    # precedent).
    p = _packed(140, 40, 3)
    assert p.window + max(len(p.unintern), 2).bit_length() > 31
    r = _parity(monkeypatch, p, (128,))
    assert r["valid?"] is True


def test_kernel_fixpoint_matches_unfused_chain_pair(monkeypatch):
    # One PAIR-KEY fused fixpoint vs the literal unfused loop on a
    # real wide-window row: keys (both words), count, flags.
    import jax.numpy as jnp

    p = _packed(140, 40, 3)
    b = max(len(p.unintern), 2).bit_length()
    nil_id = max(len(p.unintern), 2)
    W = p.window
    assert W + b > 31
    exp_h = bfs.expansion_tables(p, b)
    pure_h, _ = bfs.reduction_bit_tables(p, (W + 31) // 32)
    r = next(i for i in range(p.R)
             if np.asarray(exp_h[4])[i].any())
    act = jnp.asarray(np.asarray(p.active)[r])
    v_row = jnp.asarray(np.asarray(p.slot_v)[r])
    pure_row = jnp.asarray(pure_h[r])
    exp_r = tuple(jnp.asarray(t[r]) for t in exp_h)
    M = int(exp_h[0].shape[-1])
    cap = 128
    assert psort_fused.fits(cap, M, b)
    it_max = W + 12

    fill = np.full(cap, 0xFFFFFFFF, np.uint32)
    lo0, hi0 = fill.copy(), fill.copy()
    lo0[0] = nil_id       # initial config: empty bitset, nil state
    hi0[0] = 0
    lo = jnp.asarray(lo0)
    hi = jnp.asarray(hi0)
    count = jnp.int32(1)

    ulo, uhi, ucnt = lo, hi, count
    passes = 0
    while True:
        ulo, uhi, ucnt, changed, ovf = bfs._closure_pass_keys_compact(
            ulo, uhi, ucnt, act, v_row, pure_row, exp_r, cap=cap,
            W=W, b=b, nil_id=nil_id, step_fn=p.kernel.step,
            use_psort=False, crash_dom=False)
        passes += 1
        assert not bool(ovf)
        if not bool(changed):
            break
        assert passes < it_max

    cols, sats = bfs._fused_row_tables(exp_r, act, v_row, pure_row,
                                       W=W, b=b, nil_id=nil_id)
    flo, fhi, fcnt, conv, fovf = psort_fused.fixpoint(
        lo, hi, count, cols, sats, cap=cap, b=b, it_max=it_max)
    assert bool(conv) and not bool(fovf)
    assert int(fcnt) == int(ucnt)
    assert np.array_equal(np.asarray(flo), np.asarray(ulo))
    assert np.array_equal(np.asarray(fhi), np.asarray(uhi))


@pytest.mark.slow
def test_kernel_fixpoint_pair_raised_bound(monkeypatch):
    # The PAIR-KEY fused tier at a BIG cap: a (cap, M) shape whose
    # candidate space pads past the default PSORT_MAX_N bound — only
    # reachable through the JEPSEN_TPU_PSORT_FUSED_MAX_N raise — must
    # still equal the unfused chain bit for bit. SLOW tier: each
    # interpret-mode pass runs two 2^20-element pair-bitonic chains
    # (~seconds each jitted on the CPU mesh).
    import jax.numpy as jnp

    p = _packed(140, 40, 3)
    b = max(len(p.unintern), 2).bit_length()
    nil_id = max(len(p.unintern), 2)
    W = p.window
    assert W + b > 31
    exp_h = bfs.expansion_tables(p, b)
    pure_h, _ = bfs.reduction_bit_tables(p, (W + 31) // 32)
    r = next(i for i in range(p.R)
             if np.asarray(exp_h[4])[i].any())
    act = jnp.asarray(np.asarray(p.active)[r])
    v_row = jnp.asarray(np.asarray(p.slot_v)[r])
    pure_row = jnp.asarray(pure_h[r])
    exp_r = tuple(jnp.asarray(t[r]) for t in exp_h)
    M = int(exp_h[0].shape[-1])
    # Smallest power-of-two cap whose padded candidate space exceeds
    # the default bound — the raised-bound band, as small as it gets.
    from jepsen_tpu.lin import psort
    cap = 128
    while psort.pad_size(cap * (1 + M)) <= psort.PSORT_MAX_N:
        cap *= 2
    assert not psort_fused.fits(cap, M, b)
    assert psort_fused.fits(cap, M, b, max_pad=1 << 21)
    it_max = W + 12

    fill = np.full(cap, 0xFFFFFFFF, np.uint32)
    lo0, hi0 = fill.copy(), fill.copy()
    lo0[0] = nil_id
    hi0[0] = 0
    lo = jnp.asarray(lo0)
    hi = jnp.asarray(hi0)
    count = jnp.int32(1)

    ulo, uhi, ucnt = lo, hi, count
    passes = 0
    while True:
        ulo, uhi, ucnt, changed, ovf = bfs._closure_pass_keys_compact(
            ulo, uhi, ucnt, act, v_row, pure_row, exp_r, cap=cap,
            W=W, b=b, nil_id=nil_id, step_fn=p.kernel.step,
            use_psort=False, crash_dom=False)
        passes += 1
        assert not bool(ovf)
        if not bool(changed):
            break
        assert passes < it_max

    cols, sats = bfs._fused_row_tables(exp_r, act, v_row, pure_row,
                                       W=W, b=b, nil_id=nil_id)
    flo, fhi, fcnt, conv, fovf = psort_fused.fixpoint(
        lo, hi, count, cols, sats, cap=cap, b=b, it_max=it_max)
    assert bool(conv) and not bool(fovf)
    assert int(fcnt) == int(ucnt)
    assert np.array_equal(np.asarray(flo), np.asarray(ulo))
    assert np.array_equal(np.asarray(fhi), np.asarray(uhi))


def test_engine_parity_on_corrupted_history(monkeypatch):
    # An invalid history must die at the same row fused and unfused.
    h = synth.corrupt_history(
        synth.generate_register_history(60, concurrency=16, seed=7,
                                        value_range=5, crash_prob=0),
        seed=2)
    p = prepare.prepare(m.cas_register(), h)
    monkeypatch.setenv("JEPSEN_TPU_PSORT_FUSED", "0")
    off = bfs.check_packed(p, cap_schedule=(256,))
    monkeypatch.setenv("JEPSEN_TPU_PSORT_FUSED", "interpret")
    on = bfs.check_packed(p, cap_schedule=(256,))
    assert on["valid?"] is off["valid?"]
    if off["valid?"] is False:
        assert on["op"] == off["op"]
        assert on["dead-row"] == off["dead-row"]


def test_kernel_fixpoint_matches_unfused_chain(monkeypatch):
    # One fused fixpoint vs the literal unfused pass loop on real
    # per-row tables: keys, count, and flags must match exactly.
    import jax.numpy as jnp

    p = _packed(60, 16, 7)
    b = max(len(p.unintern), 2).bit_length()
    nil_id = max(len(p.unintern), 2)
    W = p.window
    exp_h = bfs.expansion_tables(p, b)
    pure_h, _ = bfs.reduction_bit_tables(p, (W + 31) // 32)
    active_h = np.asarray(p.active)
    slot_v_h = np.asarray(p.slot_v)
    step_fn = p.kernel.step
    cap = 256
    it_max = W + 12

    # A mid-history row with live mutator columns.
    r = next(i for i in range(p.R)
             if np.asarray(exp_h[4])[i].any())
    act = jnp.asarray(active_h[r])
    v_row = jnp.asarray(slot_v_h[r])
    pure_row = jnp.asarray(pure_h[r])
    exp_r = tuple(jnp.asarray(t[r]) for t in exp_h)
    M = int(exp_h[0].shape[-1])
    assert psort_fused.fits(cap, M, b)

    # Entry frontier: the initial config.
    lo0 = np.full(cap, 0xFFFFFFFF, np.uint32)
    lo0[0] = nil_id if int(p.init_state[0]) < 0 else int(p.init_state[0])
    lo = jnp.asarray(lo0)
    count = jnp.int32(1)

    # Unfused chain to convergence.
    ulo, ucnt = lo, count
    passes = 0
    while True:
        ulo, _, ucnt, changed, ovf = bfs._closure_pass_keys_compact(
            ulo, None, ucnt, act, v_row, pure_row, exp_r, cap=cap,
            W=W, b=b, nil_id=nil_id, step_fn=step_fn, use_psort=False,
            crash_dom=False)
        passes += 1
        assert not bool(ovf)
        if not bool(changed):
            break
        assert passes < it_max

    cols, sats = bfs._fused_row_tables(exp_r, act, v_row, pure_row,
                                       W=W, b=b, nil_id=nil_id)
    flo, fhi, fcnt, conv, fovf = psort_fused.fixpoint(
        lo, None, count, cols, sats, cap=cap, b=b, it_max=it_max)
    assert fhi is None
    assert bool(conv) and not bool(fovf)
    assert int(fcnt) == int(ucnt)
    assert np.array_equal(np.asarray(flo), np.asarray(ulo))


def test_kernel_reports_budget_exhaustion(monkeypatch):
    # it_max=1 on a row needing several passes: the kernel must report
    # non-convergence (the engine's honest overflow signal), never
    # loop or lie.
    import jax.numpy as jnp

    p = _packed(60, 16, 7)
    b = max(len(p.unintern), 2).bit_length()
    nil_id = max(len(p.unintern), 2)
    W = p.window
    exp_h = bfs.expansion_tables(p, b)
    pure_h, _ = bfs.reduction_bit_tables(p, (W + 31) // 32)
    r = next(i for i in range(p.R)
             if np.asarray(exp_h[4])[i].sum() >= 2)
    act = jnp.asarray(np.asarray(p.active)[r])
    v_row = jnp.asarray(np.asarray(p.slot_v)[r])
    pure_row = jnp.asarray(pure_h[r])
    exp_r = tuple(jnp.asarray(t[r]) for t in exp_h)
    cap = 256
    lo0 = np.full(cap, 0xFFFFFFFF, np.uint32)
    lo0[0] = nil_id
    cols, sats = bfs._fused_row_tables(exp_r, act, v_row, pure_row,
                                       W=W, b=b, nil_id=nil_id)
    _, _, _, conv, ovf = psort_fused.fixpoint(
        jnp.asarray(lo0), None, jnp.int32(1), cols, sats, cap=cap,
        b=b, it_max=1)
    assert not bool(conv) and not bool(ovf)
