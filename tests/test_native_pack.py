"""Parity tests: native C++ packing walk (native/history_pack.cc) vs the
pure-Python walk in jepsen_tpu/lin/prepare.py."""

import numpy as np
import pytest

from jepsen_tpu import models as m
from jepsen_tpu import native_ext
from jepsen_tpu.history import History, invoke_op, ok_op, info_op
from jepsen_tpu.lin import prepare, synth

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick

needs_native = pytest.mark.skipif(
    not native_ext.available(), reason="native library unavailable")


def _prepare_both(model, h):
    p_native = prepare.prepare(model, h)
    import jepsen_tpu.lin.prepare as prep

    orig = prep._pack_events_native
    prep._pack_events_native = lambda *a, **k: None
    try:
        p_py = prepare.prepare(model, h)
    finally:
        prep._pack_events_native = orig
    return p_native, p_py


def _assert_packed_equal(a, b):
    assert a.window == b.window
    assert a.R == b.R
    np.testing.assert_array_equal(a.ret_slot, b.ret_slot)
    np.testing.assert_array_equal(a.ret_op, b.ret_op)
    np.testing.assert_array_equal(a.active, b.active)
    np.testing.assert_array_equal(a.slot_f, b.slot_f)
    np.testing.assert_array_equal(a.slot_v, b.slot_v)
    np.testing.assert_array_equal(a.slot_op, b.slot_op)
    np.testing.assert_array_equal(a.init_state, b.init_state)
    assert [o.op_index for o in a.crashed_ops] == \
        [o.op_index for o in b.crashed_ops]


@needs_native
def test_native_available_builds():
    assert native_ext.available()


@needs_native
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_parity_random_histories(seed):
    h = synth.generate_register_history(
        2000, concurrency=7, seed=seed, crash_prob=0.01, max_crashes=6)
    a, b = _prepare_both(m.cas_register(), h)
    _assert_packed_equal(a, b)


@needs_native
def test_parity_with_crashes_and_tail_invokes():
    h = History.of(
        invoke_op(0, "write", 1),
        invoke_op(1, "read", None),
        ok_op(0, "write", 1),
        invoke_op(2, "cas", [1, 2]),
        info_op(1, "read", None),      # crashed read: elided
        ok_op(2, "cas", [1, 2]),
        invoke_op(3, "write", 9),      # dangling: crashed
    )
    a, b = _prepare_both(m.cas_register(), h)
    _assert_packed_equal(a, b)
    assert len(a.crashed_ops) == 1 and a.crashed_ops[0].value == 9


@needs_native
def test_parity_empty_and_trivial():
    a, b = _prepare_both(m.cas_register(), History.of())
    _assert_packed_equal(a, b)
    h = History.of(invoke_op(0, "write", 5), ok_op(0, "write", 5))
    a, b = _prepare_both(m.cas_register(), h)
    _assert_packed_equal(a, b)


@needs_native
def test_window_overflow_same_error():
    ops = [invoke_op(i, "write", i) for i in range(70)]
    h = History.of(*ops)
    with pytest.raises(prepare.UnsupportedHistory):
        prepare.prepare(m.cas_register(), h)


def test_python_fallback_when_disabled(monkeypatch):
    monkeypatch.setattr(native_ext, "_lib", None)
    monkeypatch.setattr(native_ext, "_load_failed", True)
    h = synth.generate_register_history(500, concurrency=5, seed=9)
    p = prepare.prepare(m.cas_register(), h)
    assert p.R > 0
