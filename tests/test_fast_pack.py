"""Bit-parity fuzz: the vectorized packing pipeline (JEPSEN_TPU_FAST_PACK,
lin/prepare.py) vs the Python spec loops — every PACKED_STATE_KERNELS
family, crashed ops, :info completions, error parity, and the
reduction_tables chain core on the same corpora (ISSUE 16 tentpole a)."""

import numpy as np
import pytest

from jepsen_tpu import models as m
from jepsen_tpu.history import History, invoke_op, ok_op, info_op, fail_op
from jepsen_tpu.lin import prepare, synth
from jepsen_tpu.lin.prepare import UnsupportedHistory
from jepsen_tpu.lin.supervise import history_fingerprint

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick


def _pack_one(model, h, monkeypatch, fast):
    monkeypatch.setenv("JEPSEN_TPU_FAST_PACK", "1" if fast else "0")
    try:
        p = prepare.prepare(model, h)
    except UnsupportedHistory as e:
        return ("error", str(e), getattr(e, "kind", None))
    red = prepare.reduction_tables(p)
    return ("ok", p, red)


def _assert_parity(model, h, monkeypatch):
    """prepare() + reduction_tables() under FAST_PACK=1 vs =0 must be
    bit-identical: same tables, interns, ops, fingerprint, reduction
    tables — or the same error."""
    fast = _pack_one(model, h, monkeypatch, True)
    spec = _pack_one(model, h, monkeypatch, False)
    assert fast[0] == spec[0], (fast, spec)
    if fast[0] == "error":
        assert fast[1:] == spec[1:]
        return None
    a, ra = fast[1], fast[2]
    b, rb = spec[1], spec[2]
    assert a.window == b.window and a.R == b.R
    for name in ("ret_slot", "ret_op", "active", "slot_f", "slot_v",
                 "slot_op", "crashed", "init_state"):
        va, vb = getattr(a, name), getattr(b, name)
        assert np.asarray(va).dtype == np.asarray(vb).dtype, name
        np.testing.assert_array_equal(va, vb, err_msg=name)
    assert (a.kernel.name if a.kernel else None) == \
        (b.kernel.name if b.kernel else None)
    assert a.intern == b.intern
    assert a.unintern == b.unintern
    assert a.ops == b.ops                       # LinOp dataclass equality
    assert a.crashed_ops == b.crashed_ops
    assert history_fingerprint(a) == history_fingerprint(b)
    np.testing.assert_array_equal(ra[0], rb[0], err_msg="pure")
    np.testing.assert_array_equal(ra[1], rb[1], err_msg="pred")
    return a


# --- fuzz across kernel families --------------------------------------------


@pytest.mark.parametrize("seed", range(6))
def test_parity_register_crash_mix(seed, monkeypatch):
    h = synth.generate_register_history(
        1500, concurrency=7, seed=seed, crash_prob=0.02, max_crashes=9)
    _assert_parity(m.cas_register(), h, monkeypatch)


@pytest.mark.parametrize("seed", range(4))
def test_parity_partitioned_cas(seed, monkeypatch):
    h = synth.generate_partitioned_register_history(
        3000, seed=seed, max_crashes=12, invoke_bias=0.5)
    p = _assert_parity(m.cas_register(), h, monkeypatch)
    assert p is not None and len(p.crashed_ops) > 0


@pytest.mark.parametrize("seed", range(3))
def test_parity_register_model(seed, monkeypatch):
    h = synth.generate_register_history(
        800, concurrency=5, seed=seed, crash_prob=0.01, max_crashes=4)
    _assert_parity(m.register(), h, monkeypatch)


@pytest.mark.parametrize("seed", range(3))
def test_parity_mutex(seed, monkeypatch):
    h = synth.generate_mutex_history(
        600, concurrency=5, seed=seed, crash_prob=0.02, max_crashes=6)
    _assert_parity(m.mutex(), h, monkeypatch)


@pytest.mark.parametrize("seed", range(3))
def test_parity_set_spec_kernelize_fallback(seed, monkeypatch):
    # Set histories take the spec _kernelize (vec form covers the
    # register/mutex band only) but still the vectorized pair + walk.
    h = synth.generate_set_history(400, concurrency=3, seed=seed)
    _assert_parity(m.set_model(), h, monkeypatch)


@pytest.mark.parametrize("seed", range(3))
def test_parity_queue(seed, monkeypatch):
    h = synth.generate_queue_history(
        500, concurrency=3, seed=seed, crash_prob=0.02, max_crashes=4)
    _assert_parity(m.fifo_queue(), h, monkeypatch)


# --- edge cases --------------------------------------------------------------


def test_parity_empty_and_trivial(monkeypatch):
    _assert_parity(m.cas_register(), History.of(), monkeypatch)
    _assert_parity(m.cas_register(), History.of(
        invoke_op(0, "write", 5), ok_op(0, "write", 5)), monkeypatch)


def test_parity_info_fail_nemesis_mix(monkeypatch):
    h = History.of(
        invoke_op("nemesis", "start", None),
        invoke_op(0, "write", 1),
        invoke_op(1, "read", None),
        ok_op(0, "write", 1),
        info_op(1, "read", None),          # crashed read: elided
        invoke_op(2, "cas", [1, 2]),
        invoke_op(3, "write", 7),
        fail_op(3, "write", 7),            # failed: dropped entirely
        ok_op(2, "cas", [1, 2]),
        invoke_op(0, "read", None),
        ok_op(0, "read", 2),
        invoke_op(1, "write", 9),          # dangling: crashed
        invoke_op("nemesis", "stop", None),
    )
    p = _assert_parity(m.cas_register(), h, monkeypatch)
    assert len(p.crashed_ops) == 1 and p.crashed_ops[0].value == 9


def test_parity_double_invoke_error(monkeypatch):
    h = History.of(
        invoke_op(0, "write", 1),
        invoke_op(0, "write", 2),
        ok_op(0, "write", 2),
    )
    _assert_parity(m.cas_register(), h, monkeypatch)


def test_parity_window_overflow_error(monkeypatch):
    ops = [invoke_op(i, "write", i) for i in range(70)]
    ops += [ok_op(i, "write", i) for i in range(70)]
    h = History.of(*ops)
    fast = _pack_one(m.cas_register(), h, monkeypatch, True)
    spec = _pack_one(m.cas_register(), h, monkeypatch, False)
    assert fast[0] == spec[0] == "error"
    assert fast[1:] == spec[1:]
    assert fast[2] == "window"


def test_parity_cas_bad_pair_error(monkeypatch):
    h = History.of(invoke_op(0, "cas", 7), ok_op(0, "cas", 7))
    fast = _pack_one(m.cas_register(), h, monkeypatch, True)
    spec = _pack_one(m.cas_register(), h, monkeypatch, False)
    assert fast == spec and fast[0] == "error"


@pytest.mark.parametrize("vals", [
    ("a", "b", "c"),                       # strings
    (True, False, 1),                      # bools must not silently be ints
    (1 << 62, -(1 << 62) - 1, 3),          # beyond the int gate
    (1.5, 2.5, 1.5),                       # floats
])
def test_parity_non_int_value_domains(vals, monkeypatch):
    # The vec interner covers the plain-int domain; anything else must
    # fall back to the spec interner per call — and stay bit-identical.
    h = History.of(
        invoke_op(0, "write", vals[0]), ok_op(0, "write", vals[0]),
        invoke_op(1, "write", vals[1]), ok_op(1, "write", vals[1]),
        invoke_op(0, "read", None), ok_op(0, "read", vals[2]),
    )
    p = _assert_parity(m.cas_register(), h, monkeypatch)
    assert p is not None


def test_parity_dequeue_value_semantics(monkeypatch):
    h = History.of(
        invoke_op(0, "enqueue", 4), ok_op(0, "enqueue", 4),
        invoke_op(1, "dequeue", None), ok_op(1, "dequeue", 4),
        invoke_op(0, "enqueue", 5), ok_op(0, "enqueue", 5),
        invoke_op(1, "dequeue", None), info_op(1, "dequeue", None),
    )
    _assert_parity(m.fifo_queue(), h, monkeypatch)


# --- incremental packer: vectorized settle vs the spec loop ------------------


def _stream_pack(model, events, step, monkeypatch, fast,
                 flip_at=None):
    """Feed/settle in `step`-sized chunks; `flip_at` flips the packer
    mode at that chunk (exercises the spec->vec backfill)."""
    from jepsen_tpu.stream import IncrementalPacker

    monkeypatch.setenv("JEPSEN_TPU_FAST_PACK", "1" if fast else "0")
    pk = IncrementalPacker(model)
    fps = []
    for ci, i in enumerate(range(0, len(events), step)):
        if flip_at is not None and ci == flip_at:
            monkeypatch.setenv("JEPSEN_TPU_FAST_PACK",
                               "0" if fast else "1")
        pk.feed_many(events[i:i + step])
        pk.settle()
        fps.append(pk.prefix_fingerprint(pk.R))
    pk.settle(final=True)
    fps.append(pk.prefix_fingerprint(pk.R))
    return pk, fps


def _assert_stream_parity(model, events, step, monkeypatch,
                          flip_at=None):
    a, fa = _stream_pack(model, list(events), step, monkeypatch, True,
                         flip_at)
    b, fb = _stream_pack(model, list(events), step, monkeypatch, False)
    assert fa == fb                       # per-increment fingerprints
    pa, pb = a.packed(), b.packed()
    assert pa.window == pb.window and pa.R == pb.R
    for name in ("ret_slot", "ret_op", "active", "slot_f", "slot_v",
                 "slot_op", "crashed"):
        va, vb = getattr(pa, name), getattr(pb, name)
        assert np.asarray(va).dtype == np.asarray(vb).dtype, name
        np.testing.assert_array_equal(va, vb, err_msg=name)
    assert pa.intern == pb.intern and pa.unintern == pb.unintern
    assert a.ops == b.ops
    np.testing.assert_array_equal(pa._reduction_tables[0],
                                  pb._reduction_tables[0])
    np.testing.assert_array_equal(pa._reduction_tables[1],
                                  pb._reduction_tables[1])
    assert a.max_used == b.max_used and a._free == b._free
    assert a._slot_of == b._slot_of and a._cur_active == b._cur_active


@pytest.mark.parametrize("seed,step", [(0, 17), (1, 50), (2, 1),
                                       (3, 999), (4, 7)])
def test_stream_settle_parity(seed, step, monkeypatch):
    h = synth.generate_register_history(
        900, concurrency=8, seed=seed, crash_prob=0.03, max_crashes=7)
    _assert_stream_parity(m.cas_register(), h, step, monkeypatch)


@pytest.mark.parametrize("seed", range(2))
def test_stream_settle_parity_mutex(seed, monkeypatch):
    h = synth.generate_mutex_history(
        400, concurrency=6, seed=seed, crash_prob=0.03, max_crashes=5)
    _assert_stream_parity(m.mutex(), h, 23, monkeypatch)


def test_stream_settle_parity_mode_flip(monkeypatch):
    # Flip FAST_PACK mid-stream: the vec settle backfills the growing
    # per-op arrays from the spec-walked prefix and stays bit-exact.
    h = synth.generate_register_history(
        600, concurrency=7, seed=11, crash_prob=0.02, max_crashes=5)
    _assert_stream_parity(m.cas_register(), h, 41, monkeypatch,
                          flip_at=5)
    _assert_stream_parity(m.cas_register(), h, 41, monkeypatch,
                          flip_at=2)


def test_stream_settle_vs_oneshot(monkeypatch):
    # Vec incremental vs vec one-shot: the cross-check test_stream.py
    # runs at default mode, pinned here explicitly.
    monkeypatch.setenv("JEPSEN_TPU_FAST_PACK", "1")
    from jepsen_tpu.stream import IncrementalPacker

    h = list(synth.generate_register_history(
        700, concurrency=8, seed=3, crash_prob=0.04, max_crashes=6))
    one = prepare.prepare(m.cas_register(), list(h))
    r1 = prepare.reduction_tables(one)
    pk = IncrementalPacker(m.cas_register())
    for i in range(0, len(h), 29):
        pk.feed_many(h[i:i + 29])
        pk.settle()
    pk.settle(final=True)
    p2 = pk.packed()
    assert p2.R == one.R and p2.window == one.window
    for name in ("ret_slot", "ret_op", "active", "slot_f", "slot_v",
                 "slot_op", "crashed"):
        np.testing.assert_array_equal(
            getattr(one, name), getattr(p2, name), err_msg=name)
    np.testing.assert_array_equal(r1[1], p2._reduction_tables[1])


def test_fast_pack_stats_and_mode(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_FAST_PACK", "1")
    prepare.reset_pack_stats()
    h = synth.generate_register_history(300, concurrency=4, seed=0)
    p = prepare.prepare(m.cas_register(), h)
    prepare.reduction_tables(p)
    st = prepare.pack_stats()
    assert st["mode"] == "vec"
    assert st["prepare_calls"] == 1 and st["reduction_calls"] == 1
    assert st["prepare_s"] > 0.0 and st["reduction_s"] >= 0.0
    prepare.reset_pack_stats()
    assert prepare.pack_stats()["prepare_calls"] == 0
