"""Cockroach analytic-workload clients against an in-process fake
pgwire SERVER (the house pattern): a tiny SQL engine behind the real
postgres wire protocol, so PgClient framing, txn-retry, and each
client's SQL all run for real — monotonic / sets / sequential /
comments / g2 (monotonic.clj, sets.clj, sequential.clj, comments.clj,
adya.clj:85)."""

import re
import socket
import struct
import threading

import pytest

from jepsen_tpu.history import Op
from jepsen_tpu.suites import cockroachdb as cr
from jepsen_tpu.suites import workloads

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick


class MiniCrdb:
    """Single-lock serializable mini SQL engine for the statements the
    five clients issue. Knobs: abort_commits (raise 40001 on the first
    N COMMITs — exercises txn retry), skew_ts (logical timestamps run
    backwards — monotonic anomaly), no_predicate_lock (G2: predicate
    reads miss uncommitted peers, letting both inserts commit)."""

    def __init__(self, abort_commits: int = 0, skew_ts: bool = False,
                 no_predicate_lock: bool = False):
        self.tables: dict = {}
        self.glock = threading.RLock()
        self.ts = 1000
        self.abort_commits = abort_commits
        self.skew_ts = skew_ts
        self.no_predicate_lock = no_predicate_lock

    def _rows(self, t):
        return self.tables.setdefault(t, [])

    def execute(self, sql: str, txn):
        s = " ".join(sql.split())
        if s in ("BEGIN", "COMMIT", "ROLLBACK"):
            return self._txn_ctl(s, txn)
        with self.glock:
            if s.startswith("CREATE DATABASE"):
                return []
            m = re.match(r"CREATE TABLE IF NOT EXISTS (\S+) ", s)
            if m:
                self._rows(m.group(1).split(".")[-1])
                return []
            if s == "SELECT cluster_logical_timestamp()":
                self.ts += -7 if self.skew_ts and self.ts % 5 == 0 else 13
                return [(f"{self.ts}.0000000001",)]
            m = re.match(r"SELECT max\((\w+)\) FROM (\S+)$", s)
            if m:
                col, t = m.groups()
                vals = [r[col] for r in self._rows(t) if col in r]
                return [(str(max(vals)),)] if vals else [(None,)]
            m = re.match(r"INSERT INTO (\S+) \(([^)]*)\) VALUES "
                         r"\(([^)]*)\)$", s)
            if m:
                t, cols, vals = m.groups()
                cols = [c.strip() for c in cols.split(",")]
                vals = [int(v) for v in vals.split(",")]
                row = dict(zip(cols, vals))
                key = row.get("id", row.get("val", row.get("key")))
                pk = "id" if "id" in row else ("val" if "val" in row
                                               else "key")
                if any(r.get(pk) == key for r in self._rows(t)):
                    raise KeyError("23505", "duplicate key")
                (txn["staged"] if txn["open"] else self._rows(t)) \
                    .append((t, row) if txn["open"] else row)
                return []
            m = re.match(r"SELECT id FROM (\S+) WHERE key = (-?\d+) "
                         r"AND value % 3 = 0$", s)
            if m:
                t, k = m.group(1), int(m.group(2))
                out = [(str(r["id"]),) for r in self._rows(t)
                       if r.get("key") == k and r.get("value", 1) % 3 == 0]
                if not self.no_predicate_lock and txn["open"]:
                    out += [(str(r["id"]),) for tt, r in txn["staged"]
                            if tt == t and r.get("key") == k]
                return out
            m = re.match(r"SELECT (\w+) FROM (\S+?)( ORDER BY (\w+))?$", s)
            if m:
                col, t, _, order = m.groups()
                rows = list(self._rows(t))
                if order:
                    rows.sort(key=lambda r: r[order])
                return [(str(r[col]),) for r in rows if col in r]
        raise ValueError(f"unhandled sql {s!r}")

    def _txn_ctl(self, s, txn):
        if s == "BEGIN":
            txn["open"] = True
            txn["staged"] = []
            return []
        if s == "ROLLBACK":
            txn["open"] = False
            txn["staged"] = []
            return []
        with self.glock:
            if self.abort_commits > 0 and txn["staged"]:
                self.abort_commits -= 1
                txn["open"] = False
                txn["staged"] = []
                raise KeyError("40001", "restart transaction")
            for t, row in txn["staged"]:
                self._rows(t).append(row)
            txn["open"] = False
            txn["staged"] = []
            return []


def _msg(t: bytes, payload: bytes) -> bytes:
    return t + struct.pack("!I", len(payload) + 4) + payload


class PgWireServer:
    """Just enough postgres wire protocol for PgClient: trust auth +
    simple Query."""

    def __init__(self, engine: MiniCrdb):
        self.engine = engine
        self.srv = socket.socket()
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(8)
        self.port = self.srv.getsockname()[1]
        self.alive = True
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while self.alive:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        txn = {"open": False, "staged": []}
        try:
            head = self._read(conn, 4)
            (n,) = struct.unpack("!I", head)
            self._read(conn, n - 4)          # startup params
            conn.sendall(_msg(b"R", struct.pack("!I", 0)))
            conn.sendall(_msg(b"Z", b"I"))
            while True:
                t = self._read(conn, 1)
                (n,) = struct.unpack("!I", self._read(conn, 4))
                body = self._read(conn, n - 4)
                if t == b"X":
                    return
                if t != b"Q":
                    continue
                sql = body.split(b"\x00", 1)[0].decode()
                try:
                    rows = self.engine.execute(sql, txn)
                    out = b""
                    for row in rows:
                        cells = b""
                        for cell in row:
                            if cell is None:
                                cells += struct.pack("!i", -1)
                            else:
                                cb = str(cell).encode()
                                cells += struct.pack("!i", len(cb)) + cb
                        out += _msg(b"D", struct.pack("!H", len(row))
                                    + cells)
                    out += _msg(b"C", b"OK\x00")
                    out += _msg(b"Z", b"T" if txn["open"] else b"I")
                    conn.sendall(out)
                except KeyError as e:
                    code, m = e.args
                    fields = (b"SERROR\x00" + b"C" + code.encode()
                              + b"\x00M" + m.encode() + b"\x00\x00")
                    conn.sendall(_msg(b"E", fields)
                                 + _msg(b"Z", b"I"))
        except OSError:
            pass
        finally:
            conn.close()

    def _read(self, conn, n):
        data = b""
        while len(data) < n:
            part = conn.recv(n - len(data))
            if not part:
                raise OSError("closed")
            data += part
        return data

    def close(self):
        self.alive = False
        self.srv.close()


@pytest.fixture()
def pg_server(monkeypatch):
    made = []

    def start(**knobs):
        srv = PgWireServer(MiniCrdb(**knobs))
        made.append(srv)
        monkeypatch.setattr(cr, "PORT", srv.port)
        return srv

    yield start
    for s in made:
        s.close()


def _test_map():
    return {"nodes": ["127.0.0.1"]}


class TestMonotonicClient:
    def test_inserts_monotonic_and_checker_valid(self, pg_server):
        srv = pg_server()
        c = cr.MonotonicClient().open(_test_map(), "127.0.0.1")
        cr.MonotonicClient().setup(_test_map())
        h = []
        for i in range(6):
            r = c.invoke({}, Op("invoke", "insert", None, 0))
            assert r.type == "ok", r
            h.append(r)
        vals = [r.value for r in h]
        assert [v[0] for v in vals] == list(range(1, 7))
        res = workloads.monotonic_checker().check({}, None, h, {})
        assert res["valid?"] is True
        c.close({})

    def test_ts_skew_detected(self, pg_server):
        srv = pg_server(skew_ts=True)
        cr.MonotonicClient().setup(_test_map())
        c = cr.MonotonicClient().open(_test_map(), "127.0.0.1")
        h = [c.invoke({}, Op("invoke", "insert", None, 0))
             for _ in range(10)]
        res = workloads.monotonic_checker().check({}, None, h, {})
        assert res["valid?"] is False and res["anomaly-count"] > 0
        c.close({})

    def test_txn_retry_on_serialization_abort(self, pg_server):
        srv = pg_server(abort_commits=1)
        cr.MonotonicClient().setup(_test_map())
        c = cr.MonotonicClient().open(_test_map(), "127.0.0.1")
        r = c.invoke({}, Op("invoke", "insert", None, 0))
        assert r.type == "ok"        # first COMMIT aborted, retry won
        c.close({})


class TestSetsClient:
    def test_add_read_round_trip(self, pg_server):
        pg_server()
        cr.CrdbSetsClient().setup(_test_map())
        c = cr.CrdbSetsClient().open(_test_map(), "127.0.0.1")
        for v in (3, 1, 2):
            assert c.invoke({}, Op("invoke", "add", v, 0)).type == "ok"
        r = c.invoke({}, Op("invoke", "read", None, 0))
        assert r.type == "ok" and r.value == [1, 2, 3]
        # duplicate insert is a definite fail
        assert c.invoke({}, Op("invoke", "add", 3, 0)).type == "fail"
        c.close({})


class TestSequentialClient:
    def test_contiguous_sequence_and_prefix_reads(self, pg_server):
        pg_server()
        cr.SequentialClient().setup(_test_map())
        c = cr.SequentialClient().open(_test_map(), "127.0.0.1")
        h = []
        for _ in range(5):
            r = c.invoke({}, Op("invoke", "write", None, 0))
            assert r.type == "ok"
        r = c.invoke({}, Op("invoke", "read", None, 0))
        assert r.value == [0, 1, 2, 3, 4]
        h.append(r)
        res = workloads.sequential_checker().check({}, None, h, {})
        assert res["valid?"] is True
        c.close({})


class TestCommentsClient:
    def test_visibility_across_tables(self, pg_server):
        pg_server()
        cr.CommentsClient().setup(_test_map())
        c = cr.CommentsClient().open(_test_map(), "127.0.0.1")
        for v in range(5):
            assert c.invoke({}, Op("invoke", "insert", v, 0)).type == "ok"
        r = c.invoke({}, Op("invoke", "read", None, 0))
        assert r.type == "ok" and r.value == [0, 1, 2, 3, 4]
        c.close({})


class TestG2Client:
    def test_second_insert_too_late(self, pg_server):
        pg_server()
        cr.G2Client().setup(_test_map())
        c = cr.G2Client().open(_test_map(), "127.0.0.1")
        r0 = c.invoke({}, Op("invoke", "insert", {"key": 4, "id": 0}, 0))
        assert r0.type == "ok"
        r1 = c.invoke({}, Op("invoke", "insert", {"key": 4, "id": 1}, 0))
        assert r1.type == "fail" and "too-late" in str(r1.get("error"))
        res = cr.adya.g2_checker().check({}, None, [r0, r1], {})
        assert res["valid?"] is True
        c.close({})

    def test_g2_anomaly_detected(self, pg_server):
        pg_server(no_predicate_lock=True)
        cr.G2Client().setup(_test_map())
        c = cr.G2Client().open(_test_map(), "127.0.0.1")
        # interleave: both BEGIN-check before either COMMITs is the real
        # anomaly; the no_predicate_lock engine admits both even
        # serially because staged rows are invisible to the predicate.
        import threading as thr

        c2 = cr.G2Client().open(_test_map(), "127.0.0.1")
        barrier = thr.Barrier(2)
        out = [None, None]

        def go(i, cc):
            barrier.wait()
            out[i] = cc.invoke({}, Op(
                "invoke", "insert", {"key": 9, "id": i}, i))

        ts = [thr.Thread(target=go, args=(i, cc))
              for i, cc in ((0, c), (1, c2))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        oks = [o for o in out if o.type == "ok"]
        if len(oks) == 2:     # anomaly admitted
            res = cr.adya.g2_checker().check({}, None, list(out), {})
            assert res["valid?"] is False
        c.close({})
        c2.close({})


class TestRegistryWiring:
    def test_all_nine_cells_have_real_clients(self):
        for wl, cls in (("register", cr.RegisterClient),
                        ("bank", cr.BankClient),
                        ("bank-multitable", cr.MultiBankClient),
                        ("monotonic", cr.MonotonicClient),
                        ("monotonic-multitable", cr.MonotonicClient),
                        ("sets", cr.CrdbSetsClient),
                        ("sequential", cr.SequentialClient),
                        ("comments", cr.CommentsClient),
                        ("g2", cr.G2Client)):
            t = cr.test({"fake": False, "workload": wl})
            assert isinstance(t["client"], cls), wl
        t = cr.test({"fake": False, "workload": "monotonic-multitable"})
        assert t["client"].tables == 2
