"""CLI contract tests (exit codes, concurrency parsing, full demo run —
cli.clj:103-138) and web results-browser tests over a real HTTP socket."""

import json
import threading
import urllib.request

import pytest

from jepsen_tpu import cli

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick


class TestConcurrency:
    def test_plain(self):
        assert cli.parse_concurrency("10", 5) == 10

    def test_multiplier(self):
        assert cli.parse_concurrency("3n", 5) == 15

    def test_bare_n(self):
        assert cli.parse_concurrency("n", 5) == 5

    def test_garbage(self):
        with pytest.raises(cli.UsageError):
            cli.parse_concurrency("lots", 5)


class TestCliDispatch:
    def commands(self):
        return [cli.single_test_cmd(cli._demo_test_fn),
                cli.serve_cmd(), cli.analyze_cmd()]

    def test_no_subcommand_is_usage_error(self):
        assert cli.run(self.commands(), []) == cli.EXIT_USAGE

    def test_unknown_flag_is_usage_error(self):
        assert cli.run(self.commands(),
                       ["test", "--frobnicate"]) == cli.EXIT_USAGE

    def test_demo_run_and_analyze(self, tmp_path, monkeypatch):
        store = str(tmp_path / "store")
        code = cli.run(self.commands(),
                       ["test", "--transport", "dummy",
                        "--concurrency", "1n",
                        "--time-limit", "2", "--store", store])
        assert code == cli.EXIT_OK
        # artifacts exist
        runs = list((tmp_path / "store" / "demo-cas").iterdir())
        run_dir = [d for d in runs if d.name != "latest"][0]
        names = {p.name for p in run_dir.iterdir()}
        assert {"history.jsonl", "results.json", "test.json",
                "timeline.html", "latency-raw.png",
                "rate.png"} <= names
        # offline re-analysis of the saved history on the cpu engine
        code = cli.run(self.commands(),
                       ["analyze", "demo-cas", "--store", store,
                        "--algorithm", "cpu"])
        assert code == cli.EXIT_OK

    def test_analyze_missing_test(self, tmp_path):
        code = cli.run(self.commands(),
                       ["analyze", "nope", "--store", str(tmp_path)])
        assert code == cli.EXIT_ERROR


class TestWeb:
    @pytest.fixture()
    def server(self, tmp_path):
        from jepsen_tpu import web

        run = tmp_path / "t" / "20260101T000000.000"
        run.mkdir(parents=True)
        (run / "results.json").write_text(json.dumps({"valid?": True}))
        (run / "history.txt").write_text("0 invoke read None\n")
        srv = web.make_server(host="127.0.0.1", port=0, base=str(tmp_path))
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        yield f"http://127.0.0.1:{srv.server_address[1]}"
        srv.shutdown()

    def get(self, url):
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.status, r.read()

    def test_home_lists_runs(self, server):
        status, body = self.get(server + "/")
        assert status == 200
        assert b"20260101T000000.000" in body
        assert b"True" in body

    def test_file_preview(self, server):
        status, body = self.get(
            server + "/files/t/20260101T000000.000/history.txt")
        assert status == 200 and b"invoke" in body

    def test_dir_listing(self, server):
        status, body = self.get(server + "/files/t/20260101T000000.000/")
        assert status == 200 and b"results.json" in body

    def test_zip_download(self, server):
        import io
        import zipfile

        status, body = self.get(server + "/zip/t/20260101T000000.000")
        assert status == 200
        z = zipfile.ZipFile(io.BytesIO(body))
        assert any("results.json" in n for n in z.namelist())

    def test_traversal_blocked(self, server):
        import urllib.error

        try:
            status, _ = self.get(server + "/files/../../../etc/passwd")
            assert status in (403, 404)
        except urllib.error.HTTPError as e:
            assert e.code in (403, 404)

    def test_txn_panel_renders_snapshot(self, tmp_path):
        from jepsen_tpu import web

        snap = tmp_path / "txn_stats.json"
        snap.write_text(json.dumps({
            "verdict": False, "consistency": "serializable",
            "anomaly_counts": {"G2-item": 2},
            "edge_counts": {"wr": 10, "ww": 5, "rw": 3, "rt": 0},
            "device": {"seconds": 0.2}, "updated": "2026-01-01"}))
        html = web.txn_html(str(snap))
        assert "G2-item" in html and "serializable" in html
        assert "False" in html

    def test_txn_panel_missing_snapshot_degrades(self, tmp_path):
        from jepsen_tpu import web

        html = web.txn_html(str(tmp_path / "missing.json"))
        assert "txn-smoke" in html       # points at the habit command

    def test_txn_panel_served_and_linked(self, server):
        status, body = self.get(server + "/txn")
        assert status == 200
        status, home = self.get(server + "/")
        assert b"/txn" in home

    def test_missing_file_404(self, server):
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as ei:
            self.get(server + "/files/t/nope.txt")
        assert ei.value.code == 404
