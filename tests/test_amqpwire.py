"""AMQP 0-9-1 client against an in-process fake broker with a real
queue store, exercising negotiation, publish framing (method + header +
body), synchronous get, and the queue/mutex workload clients."""

from __future__ import annotations

import socket
import struct
import threading
from collections import deque

from jepsen_tpu.history import Op
from jepsen_tpu.suites.amqpwire import (AmqpClient, MutexClient,
                                        QueueClient)
import pytest

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick

FRAME_END = 0xCE


def _shortstr(s: str) -> bytes:
    b = s.encode()
    return bytes([len(b)]) + b


class FakeBroker:
    def __init__(self):
        self.queues: dict[str, deque] = {}
        self.srv = socket.socket()
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(4)
        self.port = self.srv.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        buf = bytearray()

        def read_exact(n):
            while len(buf) < n:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf.extend(chunk)
            out = bytes(buf[:n])
            del buf[:n]
            return out

        def read_frame():
            t, ch, size = struct.unpack(">BHI", read_exact(7))
            payload = read_exact(size)
            assert read_exact(1) == bytes([FRAME_END])
            return t, ch, payload

        def send_frame(t, ch, payload):
            conn.sendall(struct.pack(">BHI", t, ch, len(payload))
                         + payload + bytes([FRAME_END]))

        def send_method(ch, cid, mid, args=b""):
            send_frame(1, ch, struct.pack(">HH", cid, mid) + args)

        unacked: dict[int, tuple[str, bytes]] = {}
        next_tag = [0]
        try:
            assert read_exact(8) == b"AMQP\x00\x00\x09\x01"
            send_method(0, 10, 10,                      # Start
                        b"\x00\x09" + b"\x00\x00\x00\x00"
                        + _longstr(b"PLAIN") + _longstr(b"en_US"))
            _, _, start_ok = read_frame()
            assert b"PLAIN" in start_ok and b"guest" in start_ok
            send_method(0, 10, 30,                      # Tune
                        struct.pack(">HIH", 0, 131072, 0))
            read_frame()                                # Tune-Ok
            read_frame()                                # Open
            send_method(0, 10, 41, _shortstr(""))       # Open-Ok
            read_frame()                                # Channel.Open
            send_method(1, 20, 11, b"\x00\x00\x00\x00")  # Open-Ok

            while True:
                t, ch, payload = read_frame()
                cid, mid = struct.unpack_from(">HH", payload, 0)
                if (cid, mid) == (50, 10):              # queue.declare
                    qn = payload[7:7 + payload[6]].decode()
                    self.queues.setdefault(qn, deque())
                    send_method(1, 50, 11, _shortstr(qn)
                                + struct.pack(">II", 0, 0))
                elif (cid, mid) == (60, 40):            # basic.publish
                    off = 6 + 1 + payload[6]            # skip exchange
                    qn = payload[off + 1:off + 1 + payload[off]].decode()
                    _, _, header = read_frame()
                    (size,) = struct.unpack_from(">Q", header, 4)
                    body = b""
                    while len(body) < size:
                        _, _, part = read_frame()
                        body += part
                    self.queues.setdefault(qn, deque()).append(body)
                    send_method(1, 60, 80,              # Basic.Ack
                                struct.pack(">QB", 1, 0))
                elif (cid, mid) == (85, 10):            # confirm.select
                    send_method(1, 85, 11)
                elif (cid, mid) == (60, 70):            # basic.get
                    qn = payload[7:7 + payload[6]].decode()
                    q = self.queues.setdefault(qn, deque())
                    if not q:
                        send_method(1, 60, 72, _shortstr(""))
                    else:
                        body = q.popleft()
                        next_tag[0] += 1
                        unacked[next_tag[0]] = (qn, body)
                        send_method(1, 60, 71,
                                    struct.pack(">QB", next_tag[0], 0)
                                    + _shortstr("") + _shortstr(qn)
                                    + struct.pack(">I", len(q)))
                        send_frame(2, 1, struct.pack(
                            ">HHQH", 60, 0, len(body), 0))
                        send_frame(3, 1, body)
                elif (cid, mid) == (60, 80):            # client Basic.Ack
                    (tag,) = struct.unpack_from(">Q", payload, 4)
                    unacked.pop(tag, None)
                elif (cid, mid) == (60, 90):            # Basic.Reject
                    (tag,) = struct.unpack_from(">Q", payload, 4)
                    requeue = payload[12]
                    qn, body = unacked.pop(tag)
                    if requeue:
                        self.queues[qn].append(body)
                elif (cid, mid) == (10, 50):            # Connection.Close
                    return
        except (ConnectionError, OSError, AssertionError):
            return
        finally:
            # a dead connection's unacked deliveries are redelivered
            for qn, body in unacked.values():
                self.queues.setdefault(qn, deque()).append(body)
            conn.close()

    def close(self):
        self.srv.close()


def _longstr(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def test_negotiate_publish_get_roundtrip():
    srv = FakeBroker()
    c = AmqpClient("127.0.0.1", srv.port)
    c.queue_declare("q1")
    c.confirm_select()
    assert c.get("q1") is None
    c.publish("q1", b"41")
    c.publish("q1", b"42")
    assert c.get("q1")[1] == b"41"
    assert c.get("q1")[1] == b"42"
    assert c.get("q1") is None
    c.close()
    srv.close()


def test_queue_client_semantics():
    srv = FakeBroker()
    # connect directly: the fake's port is non-standard
    conn = AmqpClient("127.0.0.1", srv.port)
    conn.queue_declare(QueueClient.QUEUE)
    cl = QueueClient(conn)
    assert cl.invoke(None, Op("invoke", "enqueue", 7, 0)).is_ok
    assert cl.invoke(None, Op("invoke", "enqueue", 9, 0)).is_ok
    d = cl.invoke(None, Op("invoke", "dequeue", None, 0))
    assert d.is_ok and d.value == 7
    dr = cl.invoke(None, Op("invoke", "drain", None, 0))
    assert dr.is_ok and dr.value == [9]
    assert cl.invoke(None, Op("invoke", "dequeue", None, 0)).is_fail
    cl.close(None)
    srv.close()


def test_mutex_client_token_semantics():
    srv = FakeBroker()

    def make():
        conn = AmqpClient("127.0.0.1", srv.port)
        conn.queue_declare(MutexClient.QUEUE)
        conn.confirm_select()
        return MutexClient(conn)

    a, b = make(), make()
    a.conn.publish(MutexClient.QUEUE, b"token")   # seed one token
    assert a.invoke(None, Op("invoke", "acquire", None, 0)).is_ok
    assert b.invoke(None, Op("invoke", "acquire", None, 1)).is_fail
    assert b.invoke(None, Op("invoke", "release", None, 1)).is_fail
    assert a.invoke(None, Op("invoke", "release", None, 0)).is_ok
    # publish is async on a's connection; b's broker thread may race it
    import time

    deadline = time.time() + 5
    while True:
        r = b.invoke(None, Op("invoke", "acquire", None, 1))
        if r.is_ok or time.time() > deadline:
            break
        time.sleep(0.01)
    assert r.is_ok
    a.close(None)
    b.close(None)
    srv.close()


def test_rabbitmq_suite_ungated():
    from jepsen_tpu.suites import common, rabbitmq

    for opts in ({}, {"workload": "mutex"}):
        t = rabbitmq.test(dict(opts))
        assert not isinstance(t["client"], common.GatedClient)


def test_crashed_holder_redelivers_token():
    # The held token is an unacked delivery: the holder's death must
    # return it to the queue (the property the reference's design needs).
    import time

    srv = FakeBroker()
    a = AmqpClient("127.0.0.1", srv.port)
    a.queue_declare(MutexClient.QUEUE)
    a.confirm_select()
    a.publish(MutexClient.QUEUE, b"token")
    ma = MutexClient(a)
    assert ma.invoke(None, Op("invoke", "acquire", None, 0)).is_ok
    a.io.sock.close()                    # holder dies without releasing

    b = AmqpClient("127.0.0.1", srv.port)
    b.queue_declare(MutexClient.QUEUE)
    b.confirm_select()
    mb = MutexClient(b)
    deadline = time.time() + 5
    while True:
        r = mb.invoke(None, Op("invoke", "acquire", None, 1))
        if r.is_ok or time.time() > deadline:
            break
        time.sleep(0.01)
    assert r.is_ok
    b.close()
    srv.close()
