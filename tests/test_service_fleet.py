"""Fleet-grade checker service tests (ISSUE 13, doc/service.md § Fleet).

Four layers, mirroring the robustness axes:

- Journal units: append/settle/replay bookkeeping, torn-tail
  tolerance (the SIGKILL-torn last line costs one record, never the
  journal), gc compaction, the atomic index — no sockets, no device.
- Worker pool: the kill hook -> death detection -> requeue-once ->
  respawn state machine, the wedged-worker backstop, and the
  second-loss honest failure — stub engines, real daemon threads.
- Restart recovery: the `test_lin_ckpt_resume.py` pattern promoted to
  the daemon — a service "killed" mid-batch (``crash()``, the
  in-process SIGKILL approximation: no drain, no settles; `make
  fleet-smoke` does the real SIGKILL) restarts on the same journal,
  replays, and every request re-decides with verdict parity vs the
  CPU oracle, zero lost or double-settled answers; an open stream
  session's carried frontier survives via its per-sid checkpoint and
  re-adoption.
- Chaos gate (the ISSUE acceptance): seeded schedules of >= 20
  wedge/fault/worker-death events over >= 60 mixed histories, at
  1-worker AND 4-worker pools — only oracle-matching verdicts or
  honest unknowns, with the degradations visible in service stats.

Plus the txn satellite: the protocol-v2 ``txn-check`` frame with
fake-store (``fakes.FakeTxnStore``) histories over a real socket.
"""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

# Engine modules imported at COLLECTION time: bfs/dense build tiny
# module-level jnp constants whose one-off compiles must land outside
# the quick tier's per-test no-compile window (tests/conftest.py).
import jepsen_tpu.lin.batched   # noqa: F401
import jepsen_tpu.lin.dense     # noqa: F401

pytestmark = pytest.mark.quick


def _hist(n=20, concurrency=3, seed=0, **kw):
    from jepsen_tpu.lin import synth

    return synth.generate_register_history(
        n, concurrency=concurrency, seed=seed, value_range=3, **kw)


def _mk_service(tmp_path, monkeypatch, **kw):
    from jepsen_tpu.service.daemon import CheckerService

    monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                       str(tmp_path / "quarantine.json"))
    kw.setdefault("stats_file", str(tmp_path / "service_stats.json"))
    kw.setdefault("flush_ms_", 10)
    return CheckerService("127.0.0.1", 0, **kw)


def _stub_check(packed, model, history):
    return {"valid?": True, "analyzer": "stub-single"}


def _stub_batch(model, subs, declines=None):
    return {fp: {"valid?": True, "analyzer": "stub-batch"}
            for fp in subs}


class TestJournal:
    def _wire(self, h):
        from jepsen_tpu.service import protocol

        return protocol.history_to_wire(h)

    def test_admit_settle_depth(self, tmp_path):
        from jepsen_tpu.service.journal import Journal

        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        s1 = j.admit("check", "fp-1", {"model": "cas-register",
                                       "history": self._wire(_hist())})
        s2 = j.admit("check", "fp-2", {"model": "mutex",
                                       "history": []})
        assert j.depth() == 2 and s2 == s1 + 1
        j.settle(s1, "fp-1", {"valid?": True})
        assert j.depth() == 1
        assert [r["fp"] for r in j.unsettled()] == ["fp-2"]
        # A fresh reader (the restarted daemon) sees the same state.
        j2 = Journal(path)
        assert j2.depth() == 1
        assert j2.unsettled()[0]["seq"] == s2
        st = j2.stats()
        assert st["journal_settles"] == 1 and st["journal_depth"] == 1

    def test_history_round_trips_exactly(self, tmp_path):
        from jepsen_tpu.service import protocol
        from jepsen_tpu.service.journal import Journal

        h = _hist(seed=4, crash_prob=0.1, max_crashes=2)
        path = str(tmp_path / "j.jsonl")
        Journal(path).admit("check", "fp", {
            "model": "cas-register", "history": self._wire(h)})
        rec = Journal(path).unsettled()[0]
        got = protocol.history_from_wire(rec["history"])
        assert [o.to_dict() for o in got] == [o.to_dict() for o in h]

    def test_torn_tail_costs_one_record(self, tmp_path):
        from jepsen_tpu.service.journal import Journal

        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        j.admit("check", "fp-1", {"model": "m", "history": []})
        j.admit("check", "fp-2", {"model": "m", "history": []})
        j.close()
        raw = open(path, "rb").read()
        # SIGKILL mid-write: the LAST line is torn mid-JSON.
        open(path, "wb").write(raw[:-9])
        j2 = Journal(path)
        assert j2.depth() == 1           # the torn admit is gone...
        assert j2.stats()["journal_torn_lines"] == 1
        # ...and appending again works (the file stays a journal).
        j2.admit("check", "fp-3", {"model": "m", "history": []})
        assert Journal(path).depth() == 2

    def test_gc_keeps_unsettled_and_open_streams(self, tmp_path):
        from jepsen_tpu.service.journal import Journal

        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        s1 = j.admit("check", "fp-1", {"model": "m", "history": []})
        j.admit("check", "fp-2", {"model": "m", "history": []})
        j.settle(s1, "fp-1", {"valid?": False})
        j.stream_event("stream-open", "sid-a", model="cas-register")
        j.stream_event("stream-append", "sid-a", ops=[{"f": "x"}])
        j.stream_event("stream-open", "sid-b", model="mutex")
        j.stream_event("stream-close", "sid-b", how="finalize")
        r = j.gc()
        assert r["dropped"] == 4     # settled pair + closed session
        j2 = Journal(path)
        assert j2.depth() == 1
        sess = j2.stream_sessions()
        assert set(sess) == {"sid-a"}
        assert sess["sid-a"]["appends"] == [[{"f": "x"}]]
        # The atomic index exists and agrees.
        idx = json.loads(open(path + ".index.json").read())
        assert idx["journal_depth"] == 1

    def test_freeze_drops_late_writes(self, tmp_path):
        # crash() semantics: a worker's settle landing AFTER the
        # simulated SIGKILL must be dropped, not lazily reopen the
        # file — a real kill could never produce that record.
        from jepsen_tpu.service.journal import Journal

        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        j.admit("check", "fp-1", {"model": "m", "history": []})
        j.freeze()
        assert j.settle(1, "fp-1", {"valid?": True}) is None
        assert j.admit("check", "fp-2", {"model": "m",
                                         "history": []}) == -1
        j2 = Journal(path)
        assert j2.depth() == 1            # still owed: replay re-decides
        assert j2.stats()["journal_settles"] == 0

    def test_gc_by_second_process_does_not_orphan_writer(self,
                                                         tmp_path):
        # `cli.py journal gc` while the daemon is up swaps the inode
        # under the daemon's append handle; the next append must
        # detect it and land in the NEW file, never the unlinked one.
        from jepsen_tpu.service.journal import Journal

        path = str(tmp_path / "j.jsonl")
        j1 = Journal(path)
        s1 = j1.admit("check", "fp-1", {"model": "m", "history": []})
        j1.settle(s1, "fp-1", {"valid?": True})
        Journal(path).gc()                # the "other process"
        j1.admit("check", "fp-2", {"model": "m", "history": []})
        fresh = Journal(path)
        assert fresh.depth() == 1
        assert fresh.unsettled()[0]["fp"] == "fp-2"

    def test_index_written_at_stop(self, tmp_path, monkeypatch):
        path = str(tmp_path / "j.jsonl")
        svc = _mk_service(tmp_path, monkeypatch, journal=path,
                          check_fn=_stub_check,
                          batch_fn=_stub_batch).start()
        from jepsen_tpu.service.protocol import CheckerClient

        c = CheckerClient("127.0.0.1", svc.port)
        assert c.submit("cas-register", _hist())["valid?"] is True
        c.close()
        svc.stop()
        idx = json.loads(open(path + ".index.json").read())
        assert idx["journal_depth"] == 0
        assert idx["journal_settles"] == 1


class TestWorkerPool:
    def test_pool_size_in_stats(self, tmp_path, monkeypatch):
        svc = _mk_service(tmp_path, monkeypatch, workers=4,
                          check_fn=_stub_check,
                          batch_fn=_stub_batch).start()
        try:
            from jepsen_tpu.service.protocol import CheckerClient

            c = CheckerClient("127.0.0.1", svc.port)
            assert c.submit("cas-register", _hist())["valid?"] is True
            st = c.stats()
            assert st["workers"] == 4
            c.close()
        finally:
            svc.stop()

    def test_worker_kill_requeues_once_and_respawns(self, tmp_path,
                                                    monkeypatch):
        from jepsen_tpu.lin import supervise
        from jepsen_tpu.service.protocol import CheckerClient

        svc = _mk_service(tmp_path, monkeypatch, workers=1,
                          check_fn=_stub_check,
                          batch_fn=_stub_batch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            svc.inject_worker_kill(1)
            # The killed worker's batch requeues once and still
            # decides — the client sees a verdict, not an error.
            r = c.submit("cas-register", _hist())
            assert r["valid?"] is True
            deadline = time.time() + 10
            while time.time() < deadline:
                st = c.stats()
                if st.get("worker_deaths", 0) >= 1:
                    break
                time.sleep(0.05)
            assert st["worker_deaths"] == 1
            assert st["worker_kills"] == 1
            assert st["worker_respawns"] >= 1
            assert st["requeues"] >= 1
            # The bin shape is ledger-recorded (fault reason).
            ledger = supervise.load_ledger()
            assert any(v.get("detail", "").startswith("service worker")
                       for v in ledger.values())
            # The pool still serves.
            assert c.submit("cas-register", _hist())["valid?"] is True
            c.close()
        finally:
            svc.stop()

    def test_double_loss_fails_honestly(self, tmp_path, monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        svc = _mk_service(tmp_path, monkeypatch, workers=1,
                          check_fn=_stub_check,
                          batch_fn=_stub_batch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            # Both the first decide AND its requeue lose their worker:
            # the request must answer an honest unknown, never hang,
            # never a guessed verdict.
            svc.inject_worker_kill(2)
            r = c.submit("cas-register", _hist())
            assert r["valid?"] == "unknown"
            assert r.get("overflow") == "fault"
            st = c.stats()
            assert st["honest_fails"] >= 1
            assert st["worker_deaths"] == 2
            c.close()
        finally:
            svc.stop()

    def test_wedged_worker_backstop(self, tmp_path, monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        monkeypatch.setenv("JEPSEN_TPU_SERVICE_WORKER_DEADLINE_S",
                           "0.4")
        gate = threading.Event()
        calls = []

        def sticky_check(packed, model, history):
            calls.append(1)
            if len(calls) == 1:
                gate.wait(30)    # the first decide hangs (a
                #                  non-dispatch hang the in-batch
                #                  watchdog can't see)
            return {"valid?": True, "analyzer": "stub-single"}

        svc = _mk_service(tmp_path, monkeypatch, workers=1,
                          check_fn=sticky_check,
                          batch_fn=lambda m, s, declines=None: None,
                          deadline_s=30).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            r = c.submit("cas-register", _hist())
            # The supervisor abandoned the wedged worker, requeued the
            # request, and the respawned worker decided it.
            assert r["valid?"] is True
            st = c.stats()
            assert st["worker_wedges"] >= 1
            assert st["worker_respawns"] >= 1
            c.close()
        finally:
            gate.set()
            svc.stop()


class TestTxnWire:
    """The protocol-v2 txn-check frame, with fake-store histories
    (suites.fakes.FakeTxnStore — the workload the SQL suites run)."""

    def _fake_store_history(self, faulty=None, n=12):
        from jepsen_tpu.history import Op
        from jepsen_tpu.suites import fakes, workloads

        store = fakes.FakeTxnStore(faulty=faulty)
        client = workloads.TxnClient(store)
        h = []
        if faulty == "write-skew":
            # The guaranteed-G2 rendezvous pair (txn/device test
            # pattern): two snapshot txns each read the other's key
            # then append its own.
            lock = threading.Lock()

            def run(proc, read_k, append_k):
                op = Op("invoke", "txn",
                        [["r", read_k, None],
                         ["append", append_k, proc + 1]], proc)
                done = client.invoke(None, op)
                with lock:
                    h.append(op)
                    h.append(done)

            t1 = threading.Thread(target=run, args=(0, 0, 1))
            t2 = threading.Thread(target=run, args=(1, 1, 0))
            t1.start(); t2.start(); t1.join(10); t2.join(10)
            return h
        for i in range(n):
            op = Op("invoke", "txn",
                    [["append", i % 3, i + 1], ["r", i % 3, None]], 0)
            done = client.invoke(None, op)
            h.append(op)
            h.append(done)
        return h

    def test_txn_check_round_trip_cpu(self, tmp_path, monkeypatch):
        from jepsen_tpu import txn
        from jepsen_tpu.service.protocol import CheckerClient

        svc = _mk_service(tmp_path, monkeypatch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            good = self._fake_store_history()
            want = txn.check(good, algorithm="cpu")
            got = c.txn_check(good, algorithm="cpu")
            assert got["valid?"] == want["valid?"] is True
            assert got["_timings"]["batch_n"] == 1   # txn never bins
            bad = self._fake_store_history(faulty="write-skew")
            wantb = txn.check(bad, algorithm="cpu")
            gotb = c.txn_check(bad, algorithm="cpu")
            assert gotb["valid?"] == wantb["valid?"] is False
            assert gotb.get("anomaly-types") \
                == wantb.get("anomaly-types")
            assert "G2-item" in gotb["anomaly-types"]
            st = c.stats()
            assert st["txn_submitted"] == 2
            c.close()
        finally:
            svc.stop()

    def test_txn_check_bad_algorithm_is_error(self, tmp_path,
                                              monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        svc = _mk_service(tmp_path, monkeypatch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            r = c.txn_check(self._fake_store_history(n=2),
                            algorithm="no-such")
            assert r["valid?"] == "unknown"
            assert "algorithm" in r["error"]
            c.close()
        finally:
            svc.stop()

    @pytest.mark.compiles
    def test_txn_check_device_parity(self, tmp_path, monkeypatch):
        from jepsen_tpu import txn
        from jepsen_tpu.service.protocol import CheckerClient
        from jepsen_tpu.txn import synth as tsynth

        svc = _mk_service(tmp_path, monkeypatch).start()
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            bad = tsynth.seeded_anomaly_history("G2-item")
            want = txn.check(bad, algorithm="cpu")
            got = c.txn_check(bad, algorithm="tpu")
            assert got["valid?"] is False
            assert got.get("anomaly-types") == want.get("anomaly-types")
            c.close()
        finally:
            svc.stop()


@pytest.mark.compiles
class TestRestartRecovery:
    """The ISSUE acceptance: daemon killed mid-batch with journaled
    in-flight requests -> restart -> replay -> verdict/witness parity
    vs the CPU oracle, zero lost or double-settled answers. In-process
    ``crash()`` here (deterministic; the journal state is identical to
    a SIGKILL's because admits flush before queueing); the real
    SIGKILL twin runs in ``make fleet-smoke``."""

    def test_kill_midbatch_replay_parity(self, tmp_path, monkeypatch):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import cpu, prepare
        from jepsen_tpu.service import journal as journal_mod
        from jepsen_tpu.service.protocol import CheckerClient

        path = str(tmp_path / "j.jsonl")
        gate = threading.Event()

        def gated_check(packed, model, history):
            gate.wait(60)
            return {"valid?": True}

        # Histories with known oracle verdicts — one INVALID, so a
        # flip would be visible in the witness audit.
        from jepsen_tpu.lin import synth

        hs = [
            _hist(n=24, seed=1, crash_prob=0.02, max_crashes=2),
            list(synth.corrupt_history(
                _hist(n=24, seed=2), seed=2)),
            _hist(n=24, seed=3),
        ]
        from jepsen_tpu.lin import pack_dev

        # Oracle keyed by the WIRE fingerprint (pre-pack columns) —
        # the key the daemon journals and settles under.
        oracle = {}
        for h in hs:
            p = prepare.prepare(m.cas_register(), list(h))
            oracle[pack_dev.prepack_fingerprint(pack_dev.prepack(
                m.cas_register(), list(h)))] = cpu.check_packed(p)
        svc1 = _mk_service(tmp_path, monkeypatch, journal=path,
                           check_fn=gated_check,
                           batch_fn=lambda mo, s, declines=None: None
                           ).start()
        threads = []
        for i, h in enumerate(hs):
            def sub(i=i, h=h):
                c = CheckerClient("127.0.0.1", svc1.port, timeout=120)
                c.submit("cas-register", h, req_id=i)
                c.close()
            t = threading.Thread(target=sub, daemon=True)
            t.start()
            threads.append(t)
        deadline = time.time() + 20
        while time.time() < deadline \
                and journal_mod.Journal(path).depth() < len(hs):
            time.sleep(0.05)
        assert journal_mod.Journal(path).depth() == len(hs)
        svc1.crash()       # SIGKILL semantics: no drain, no settles
        gate.set()
        time.sleep(0.2)
        # Post-crash, nothing settled: the journal still owes 3.
        assert journal_mod.Journal(path).depth() == len(hs)

        # Restart on the same journal, REAL engines: replay re-decides.
        svc2 = _mk_service(tmp_path, monkeypatch, journal=path).start()
        try:
            deadline = time.time() + 120
            while time.time() < deadline \
                    and svc2._journal.depth() > 0:
                time.sleep(0.1)
            assert svc2._journal.depth() == 0
            assert svc2.stats()["journal_replays"] == len(hs)
        finally:
            svc2.stop()

        # Audit: every admit settled EXACTLY once, each verdict (and
        # the invalid one's witness op) parity-equal to the oracle.
        j = journal_mod.Journal(path)
        recs = j.load()
        admits = [r for r in recs if r["kind"] == "check"]
        settles = [r for r in recs if r["kind"] == "settle"]
        assert len(admits) == len(hs)
        assert sorted(s["of"] for s in settles) \
            == sorted(a["seq"] for a in admits)   # none lost, none
        #                                           double-settled
        for s in settles:
            want = oracle[s["fp"]]
            assert s["verdict"] == want["valid?"]
            if want["valid?"] is False:
                assert s["result"]["op"]["index"] \
                    == want["op"]["index"]

    def test_stream_session_survives_crash(self, tmp_path,
                                           monkeypatch):
        from jepsen_tpu import models as m
        from jepsen_tpu.lin import cpu, prepare, synth
        from jepsen_tpu.service.protocol import CheckerClient

        monkeypatch.setenv("JEPSEN_TPU_STREAM_CKPT",
                           str(tmp_path / "stream.ckpt"))
        path = str(tmp_path / "j.jsonl")
        h = list(synth.generate_register_history(
            120, concurrency=4, seed=21, value_range=5))
        want = cpu.check_packed(
            prepare.prepare(m.cas_register(), list(h)))["valid?"]

        svc1 = _mk_service(tmp_path, monkeypatch,
                           journal=path).start()
        c1 = CheckerClient("127.0.0.1", svc1.port)
        sid = c1.stream_open("cas-register")
        half, step = len(h) // 2, 20
        for i in range(0, half, step):
            st = c1.stream_append(sid, h[i:i + step])
            assert st.get("type") == "stream-state"
        row_before = st["row"]
        assert row_before > 0
        svc1.crash()
        c1.close()

        svc2 = _mk_service(tmp_path, monkeypatch,
                           journal=path).start()
        try:
            c2 = CheckerClient("127.0.0.1", svc2.port)
            opened = c2.stream_open("cas-register", session=sid)
            assert opened.get("resumed") is True
            assert opened.get("replayed_appends") >= 1
            # The per-sid checkpoint fast-forwarded the re-fed prefix.
            assert opened.get("row") == row_before
            for i in range(half, len(h), step):
                c2.stream_append(sid, h[i:i + step])
            r = c2.stream_finalize(sid)
            assert r["valid?"] == want
            assert r["stream"].get("resumed_from_row") == row_before
            # A foreign/unknown sid still answers like unknown.
            with pytest.raises(RuntimeError):
                c2.stream_open("cas-register", session="nope")
            c2.close()
        finally:
            svc2.stop()


@pytest.mark.compiles
class TestChaosGate:
    """The ISSUE chaos-soundness acceptance: >= 20 injected events
    over >= 60 mixed histories, 1-worker and 4-worker pools — only
    oracle-matching verdicts or honest unknowns, degradations visible
    in stats."""

    @pytest.mark.parametrize("workers", [1, 4])
    def test_seeded_schedule_sound(self, tmp_path, workers):
        from jepsen_tpu.service.chaos import run_chaos

        report = run_chaos(histories=60, events=20, workers=workers,
                           seed=13 + workers,
                           journal=str(tmp_path
                                       / f"chaos{workers}.jsonl"))
        assert report["sound"], report
        assert report["n"] >= 60
        assert sum(report["injected"].values()) >= 20
        assert report["verdicts"]["missing"] == 0
        assert report["journal_unsettled"] == 0
        # Every degradation is visible: whatever was injected shows up
        # in the corresponding stats counters.
        st = report["stats"]
        inj = report["injected"]
        wedges = inj.get("wedge-check", 0) + inj.get("wedge-batch", 0)
        if wedges:
            assert (st.get("watchdog_trips") or 0) >= 1
        # A worker-kill is visible as a death the moment it is
        # CONSUMED (an event armed after the last batch stays inert —
        # it lands on the drain, where the hook is deliberately off).
        if st.get("worker_kills"):
            assert (st.get("worker_deaths") or 0) \
                >= st["worker_kills"]
        assert st.get("journal_depth") == 0

    def test_chaos_events_reach_obs_feed(self, tmp_path):
        from jepsen_tpu.obs import metrics as obs_metrics
        from jepsen_tpu.service.chaos import run_chaos

        report = run_chaos(histories=8, events=4, workers=2, seed=3,
                           journal=str(tmp_path / "obs.jsonl"),
                           event_kinds=("worker-kill",))
        assert report["sound"], report
        kinds = {e.get("kind")
                 for e in obs_metrics.REGISTRY.snapshot()["events"]}
        assert "worker-death" in kinds
