"""MongoDB wire client against an in-process fake mongod.

The fake implements a real document store behind both wire modes
(OP_QUERY/$cmd for old servers, OP_MSG for modern), so find / upsert /
findAndModify semantics — including the document-CAS conditional — are
exercised end to end, and the same client transparently drives either
mode via the handshake's maxWireVersion."""

from __future__ import annotations

import socket
import struct
import threading

import pytest

from jepsen_tpu.history import Op
from jepsen_tpu.suites.mongowire import (BankClient, DocumentCasClient,
                                         MongoClient, MongoError,
                                         TableClient, bson_decode,
                                         bson_encode)

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick

OP_QUERY = 2004
OP_REPLY = 1
OP_MSG = 2013


class FakeMongod:
    """Document store speaking both wire modes."""

    def __init__(self, wire_version: int = 8):
        self.wire_version = wire_version
        self.colls: dict[str, dict] = {}     # coll -> {_id: doc}
        self.srv = socket.socket()
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(4)
        self.port = self.srv.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    # --- command evaluation over the store ---------------------------------

    def _matches(self, doc, query):
        for k, cond in query.items():
            v = doc.get(k)
            if isinstance(cond, dict) and "$gte" in cond:
                if v is None or v < cond["$gte"]:
                    return False
            elif v != cond:
                return False
        return True

    def _apply(self, doc, update):
        for k, v in update.get("$set", {}).items():
            doc[k] = v
        for k, v in update.get("$inc", {}).items():
            doc[k] = doc.get(k, 0) + v

    def _run(self, cmd: dict) -> dict:
        if "ismaster" in cmd:
            return {"ok": 1.0, "ismaster": True,
                    "maxWireVersion": self.wire_version}
        if "find" in cmd:
            coll = self.colls.setdefault(cmd["find"], {})
            docs = [dict(d) for d in coll.values()
                    if self._matches(d, cmd.get("filter", {}))]
            if cmd.get("limit"):
                docs = docs[:cmd["limit"]]
            return {"ok": 1.0, "cursor": {"id": 0, "firstBatch": docs}}
        if "insert" in cmd:
            coll = self.colls.setdefault(cmd["insert"], {})
            for d in cmd["documents"]:
                if d["_id"] in coll:
                    return {"ok": 1.0, "writeErrors": [
                        {"code": 11000, "errmsg": "duplicate key"}]}
                coll[d["_id"]] = dict(d)
            return {"ok": 1.0, "n": len(cmd["documents"])}
        if "findAndModify" in cmd:     # before "update": fAM carries one
            coll = self.colls.setdefault(cmd["findAndModify"], {})
            hit = [d for d in coll.values()
                   if self._matches(d, cmd["query"])]
            if not hit:
                return {"ok": 1.0, "value": None}
            pre = dict(hit[0])
            self._apply(hit[0], cmd["update"])
            return {"ok": 1.0, "value": pre}
        if "update" in cmd:
            coll = self.colls.setdefault(cmd["update"], {})
            for u in cmd["updates"]:
                hit = [d for d in coll.values()
                       if self._matches(d, u["q"])]
                if hit:
                    self._apply(hit[0], u["u"])
                elif u.get("upsert"):
                    doc = dict(u["q"])
                    self._apply(doc, u["u"])
                    coll[doc["_id"]] = doc
            return {"ok": 1.0}
        return {"ok": 0.0, "errmsg": f"unknown command {list(cmd)[:1]}"}

    # --- wire framing -------------------------------------------------------

    def _serve(self, conn):
        buf = bytearray()

        def read_exact(n):
            while len(buf) < n:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf.extend(chunk)
            out = bytes(buf[:n])
            del buf[:n]
            return out

        try:
            while True:
                head = read_exact(16)
                length, req_id, _, opcode = struct.unpack("<iiii", head)
                body = read_exact(length - 16)
                if opcode == OP_QUERY:
                    # flags, cstring name, skip, nret, doc
                    off = 4 + body.index(b"\x00", 4) + 1 - 4 + 4
                    off = body.index(b"\x00", 4) + 1 + 8
                    reply = self._run(bson_decode(body[off:]))
                    payload = (struct.pack("<iqii", 0, 0, 0, 1)
                               + bson_encode(reply))
                    conn.sendall(struct.pack(
                        "<iiii", len(payload) + 16, 1, req_id, OP_REPLY)
                        + payload)
                elif opcode == OP_MSG:
                    cmd = bson_decode(body[5:])
                    cmd.pop("$db", None)
                    reply = self._run(cmd)
                    payload = (struct.pack("<I", 0) + b"\x00"
                               + bson_encode(reply))
                    conn.sendall(struct.pack(
                        "<iiii", len(payload) + 16, 1, req_id, OP_MSG)
                        + payload)
                else:
                    return
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def close(self):
        self.srv.close()


def test_bson_roundtrip():
    doc = {"i": 3, "big": 2 ** 40, "f": 1.5, "s": "héllo", "b": True,
           "n": None, "d": {"x": [1, 2, {"y": "z"}]}, "oid": bytes(12)}
    assert bson_decode(bson_encode(doc)) == doc


@pytest.mark.parametrize("wire_version", [4, 8],
                         ids=["op_query", "op_msg"])
def test_crud_both_wire_modes(wire_version):
    srv = FakeMongod(wire_version)
    c = MongoClient("127.0.0.1", srv.port)
    assert c.use_msg == (wire_version >= 6)
    c.insert("jepsen", "t", {"_id": 1, "value": 10})
    with pytest.raises(MongoError):               # duplicate key
        c.insert("jepsen", "t", {"_id": 1, "value": 11})
    assert c.find_one("jepsen", "t", {"_id": 1})["value"] == 10
    c.upsert("jepsen", "t", {"_id": 2}, {"$set": {"value": 5}})
    assert len(c.find_all("jepsen", "t")) == 2
    pre = c.find_and_modify("jepsen", "t", {"_id": 1, "value": 10},
                            {"$set": {"value": 20}})
    assert pre["value"] == 10
    assert c.find_and_modify("jepsen", "t", {"_id": 1, "value": 10},
                             {"$set": {"value": 99}}) is None
    assert c.find_one("jepsen", "t", {"_id": 1})["value"] == 20
    c.close()
    srv.close()


def test_document_cas_client_semantics():
    srv = FakeMongod()
    cl = DocumentCasClient(MongoClient("127.0.0.1", srv.port))
    assert cl.invoke(None, Op("invoke", "read", None, 0)).value is None
    assert cl.invoke(None, Op("invoke", "write", 3, 0)).is_ok
    assert cl.invoke(None, Op("invoke", "read", None, 0)).value == 3
    assert cl.invoke(None, Op("invoke", "cas", [3, 4], 0)).is_ok
    assert cl.invoke(None, Op("invoke", "cas", [3, 9], 0)).is_fail
    assert cl.invoke(None, Op("invoke", "read", None, 0)).value == 4
    cl.close(None)
    srv.close()


def test_bank_client_conserves_on_fake():
    srv = FakeMongod()
    proto = BankClient()
    cl = BankClient(MongoClient("127.0.0.1", srv.port))
    # seed accounts through the same store
    for i in range(5):
        cl.conn.insert("jepsen", "accounts", {"_id": i, "balance": 10})
    r = cl.invoke(None, Op("invoke", "transfer",
                           {"from": 0, "to": 1, "amount": 4}, 0))
    assert r.is_ok
    r = cl.invoke(None, Op("invoke", "transfer",
                           {"from": 0, "to": 1, "amount": 100}, 0))
    assert r.is_fail                                # insufficient funds
    read = cl.invoke(None, Op("invoke", "read", None, 0))
    assert sum(read.value) == 50 and read.value[0] == 6
    cl.close(None)
    srv.close()


def test_table_client_and_suites_ungated():
    srv = FakeMongod()
    cl = TableClient(MongoClient("127.0.0.1", srv.port))
    assert cl.invoke(None, Op("invoke", "insert", 7, 0)).is_ok
    assert cl.invoke(None, Op("invoke", "insert", 2, 0)).is_ok
    assert cl.invoke(None, Op("invoke", "read", None, 0)).value == [2, 7]
    cl.close(None)
    srv.close()

    from jepsen_tpu.suites import common, mongodb_rocks, mongodb_smartos
    for mod in (mongodb_smartos, mongodb_rocks):
        assert not isinstance(mod.test({})["client"], common.GatedClient)
