"""Bit-parity fuzz: the device packer (lin/pack_dev.py) vs prepare's
spec walk — same tables, same fingerprints, same errors — plus the
supervision discipline (wedge -> honest numpy fallback with zero
verdict cost, quarantine routing) and the batched vmapped entry
(ISSUE 20 tentpole)."""

import numpy as np
import pytest

from jepsen_tpu import models as m
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.lin import pack_dev, prepare, supervise, synth
from jepsen_tpu.lin.prepare import UnsupportedHistory
from jepsen_tpu.lin.supervise import history_fingerprint

pytestmark = [pytest.mark.quick, pytest.mark.compiles]

TABLES = ("ret_slot", "ret_op", "active", "slot_f", "slot_v",
          "slot_op", "crashed", "init_state")


@pytest.fixture(autouse=True)
def _isolate(monkeypatch, tmp_path):
    # Keep the wedge tests' ledger records out of the real quarantine
    # file, and leaked injections out of the next test. conftest
    # defaults the device packer OFF for the quick tier's no-compile
    # promise — this file is the compiles-marked coverage, so turn it
    # back on.
    monkeypatch.setenv("JEPSEN_TPU_QUARANTINE", str(tmp_path / "q.json"))
    monkeypatch.setenv("JEPSEN_TPU_PACK_DEV", "1")
    pack_dev.reset_dev_stats()
    yield
    supervise.reset_injections()


def _spec(model, h):
    p = prepare.prepare(model, list(h))
    return p, prepare.reduction_tables(p)


def _assert_tables_equal(a, b):
    assert a.window == b.window and a.R == b.R
    for name in TABLES:
        va, vb = getattr(a, name), getattr(b, name)
        assert np.asarray(va).dtype == np.asarray(vb).dtype, name
        np.testing.assert_array_equal(va, vb, err_msg=name)
    assert (a.kernel.name if a.kernel else None) == \
        (b.kernel.name if b.kernel else None)
    assert a.intern == b.intern and a.unintern == b.unintern
    assert a.ops == b.ops and a.crashed_ops == b.crashed_ops
    assert history_fingerprint(a) == history_fingerprint(b)


def _assert_dev_parity(model, h, expect_device=True):
    spec, rspec = _spec(model, list(h))
    pre = pack_dev.prepack(model, list(h))
    before = pack_dev.dev_stats()["dev_packs"]
    got = pack_dev.materialize(pre)
    if expect_device:
        assert pack_dev.dev_stats()["dev_packs"] == before + 1
    _assert_tables_equal(got, spec)
    rdev = prepare.reduction_tables(got)   # the device-built tables
    np.testing.assert_array_equal(rdev[0], rspec[0], err_msg="pure")
    assert rdev[0].dtype == rspec[0].dtype
    np.testing.assert_array_equal(rdev[1], rspec[1], err_msg="pred")
    assert rdev[1].dtype == rspec[1].dtype
    return got


# --- single-history parity across families ----------------------------------


@pytest.mark.parametrize("seed", range(4))
def test_dev_parity_partitioned_cas(seed):
    h = synth.generate_partitioned_register_history(
        3000, seed=seed, max_crashes=12, invoke_bias=0.5)
    p = _assert_dev_parity(m.cas_register(), h)
    assert len(p.crashed_ops) > 0


@pytest.mark.parametrize("seed", range(3))
def test_dev_parity_register_crash_mix(seed):
    h = synth.generate_register_history(
        1500, concurrency=7, seed=seed, crash_prob=0.02, max_crashes=9)
    _assert_dev_parity(m.cas_register(), h)


@pytest.mark.parametrize("seed", range(3))
def test_dev_parity_mutex(seed):
    h = synth.generate_mutex_history(
        600, concurrency=5, seed=seed, crash_prob=0.02, max_crashes=6)
    _assert_dev_parity(m.mutex(), h)


def test_dev_parity_tiny_and_single_op():
    _assert_dev_parity(m.cas_register(), History.of(
        invoke_op(0, "write", 5), ok_op(0, "write", 5)))
    # Empty history: R == 0 is host-path by design, still identical.
    _assert_dev_parity(m.cas_register(), History.of(),
                       expect_device=False)


def test_dev_parity_all_crashed():
    # R == 0 but n > 0: nothing to paint, host path, identical.
    h = History.of(invoke_op(0, "write", 1), invoke_op(1, "write", 2))
    _assert_dev_parity(m.cas_register(), h, expect_device=False)


def test_dev_parity_kernelless_set_model():
    # Set histories have kernel=None here (generic CPU search):
    # ineligible for the device program, identical via host path.
    h = synth.generate_set_history(200, concurrency=3, seed=0)
    _assert_dev_parity(m.set_model(), h, expect_device=False)


# --- prepack: error + fingerprint contract -----------------------------------


def test_prepack_window_overflow_error_parity():
    ops = [invoke_op(i, "write", i) for i in range(70)]
    ops += [ok_op(i, "write", i) for i in range(70)]
    h = History.of(*ops)
    with pytest.raises(UnsupportedHistory) as de:
        pack_dev.prepack(m.cas_register(), list(h))
    with pytest.raises(UnsupportedHistory) as se:
        prepare.prepare(m.cas_register(), list(h))
    assert str(de.value) == str(se.value)
    assert de.value.kind == se.value.kind == "window"


def test_prepack_double_invoke_error_parity():
    h = History.of(invoke_op(0, "write", 1), invoke_op(0, "write", 2),
                   ok_op(0, "write", 2))
    with pytest.raises(UnsupportedHistory) as de:
        pack_dev.prepack(m.cas_register(), list(h))
    with pytest.raises(UnsupportedHistory) as se:
        prepare.prepare(m.cas_register(), list(h))
    assert str(de.value) == str(se.value)


def test_prepack_fingerprint_mode_invariant(monkeypatch):
    # The service-wire fingerprint must not depend on the host packer
    # mode: client (protocol.request_fingerprint) and daemon admission
    # must agree even when their FAST_PACK knobs differ.
    h = synth.generate_partitioned_register_history(
        800, seed=3, max_crashes=6, invoke_bias=0.5)
    monkeypatch.setenv("JEPSEN_TPU_FAST_PACK", "1")
    fast = pack_dev.prepack_fingerprint(
        pack_dev.prepack(m.cas_register(), list(h)))
    monkeypatch.setenv("JEPSEN_TPU_FAST_PACK", "0")
    spec = pack_dev.prepack_fingerprint(
        pack_dev.prepack(m.cas_register(), list(h)))
    assert fast == spec
    h2 = synth.generate_partitioned_register_history(
        800, seed=4, max_crashes=6, invoke_bias=0.5)
    assert fast != pack_dev.prepack_fingerprint(
        pack_dev.prepack(m.cas_register(), list(h2)))


def test_prepack_exposes_bin_attributes():
    # bin_key/dense.plan read these without materializing the grids.
    h = synth.generate_register_history(400, concurrency=5, seed=1)
    pre = pack_dev.prepack(m.cas_register(), list(h))
    p = prepare.prepare(m.cas_register(), list(h))
    assert pre.kernel.name == p.kernel.name
    assert pre.window == p.window and pre.R == p.R
    assert pre.state_width == p.state_width
    assert pre.unintern == p.unintern
    np.testing.assert_array_equal(pre.init_state, p.init_state)
    from jepsen_tpu.lin import dense

    assert dense.plan(pre) == dense.plan(p)


# --- knobs + supervision discipline ------------------------------------------


def test_disabled_knob_takes_host_path(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_PACK_DEV", "0")
    h = synth.generate_register_history(500, concurrency=5, seed=2)
    _assert_dev_parity(m.cas_register(), h, expect_device=False)
    assert pack_dev.dev_stats()["dev_packs"] == 0


def test_wedge_falls_back_to_numpy_with_zero_verdict_cost(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_DISPATCH_RETRIES", "0")
    h = synth.generate_register_history(
        600, concurrency=6, seed=5, crash_prob=0.02, max_crashes=4)
    spec, rspec = _spec(m.cas_register(), h)
    supervise.inject_wedge("pack-dev", 1, deadline_s=0.05)
    got = pack_dev.materialize(
        pack_dev.prepack(m.cas_register(), list(h)))
    st = pack_dev.dev_stats()
    assert st["wedges"] == 1 and st["host_fallbacks"] == 1
    assert st["dev_packs"] == 0
    _assert_tables_equal(got, spec)
    np.testing.assert_array_equal(
        prepare.reduction_tables(got)[1], rspec[1])


def test_repeat_wedges_quarantine_the_shape(monkeypatch, tmp_path):
    qpath = str(tmp_path / "q.json")
    monkeypatch.setenv("JEPSEN_TPU_QUARANTINE", qpath)
    monkeypatch.setenv("JEPSEN_TPU_DISPATCH_RETRIES", "0")
    h = synth.generate_register_history(
        400, concurrency=5, seed=6, crash_prob=0.02, max_crashes=3)
    spec, _ = _spec(m.cas_register(), h)
    supervise.inject_wedge("pack-dev", 2, deadline_s=0.05)
    for _ in range(2):                      # 2 wedges -> quarantined
        pack_dev.materialize(
            pack_dev.prepack(m.cas_register(), list(h)))
    before = pack_dev.dev_stats()["quarantine_skips"]
    got = pack_dev.materialize(
        pack_dev.prepack(m.cas_register(), list(h)))
    assert pack_dev.dev_stats()["quarantine_skips"] == before + 1
    _assert_tables_equal(got, spec)
    ledger = supervise.load_ledger(qpath)
    assert any(k.startswith("pack-dev|") for k in ledger), ledger


# --- batched entry ------------------------------------------------------------


def test_batch_parity_same_bucket(monkeypatch):
    # Same-shape histories ride one vmapped dispatch. MIN_K=1 so a
    # stray pad-bucket singleton devices too (waves below MIN_K —
    # where the batch amortization buys nothing — host-pack).
    monkeypatch.setenv("JEPSEN_TPU_PACK_DEV_MIN_K", "1")
    hs = [synth.generate_register_history(
        700, concurrency=6, seed=s, crash_prob=0.02, max_crashes=5)
        for s in range(4)]
    specs = [_spec(m.cas_register(), h) for h in hs]
    pres = [pack_dev.prepack(m.cas_register(), list(h)) for h in hs]
    got = pack_dev.materialize_batch(pres)
    st = pack_dev.dev_stats()
    assert st["dev_lanes"] == 4             # every lane went device
    assert st["dev_packs"] < 4              # ...in < K dispatches
    for g, (s, rs) in zip(got, specs):
        _assert_tables_equal(g, s)
        np.testing.assert_array_equal(
            prepare.reduction_tables(g)[1], rs[1])


def test_batch_mixed_eligibility_preserves_order(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_PACK_DEV_MIN_K", "2")
    model = m.cas_register()
    hs = [
        synth.generate_register_history(300, concurrency=4, seed=0),
        History.of(),                                   # host (R == 0)
        synth.generate_register_history(300, concurrency=4, seed=1),
        synth.generate_partitioned_register_history(
            900, seed=2, max_crashes=5, invoke_bias=0.5),
    ]
    pres = [pack_dev.prepack(model, list(h)) for h in hs]
    got = pack_dev.materialize_batch(pres)
    for g, h in zip(got, hs):
        s, _ = _spec(model, h)
        _assert_tables_equal(g, s)


def test_batch_below_min_k_takes_host(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_PACK_DEV_MIN_K", "64")
    hs = [synth.generate_register_history(
        300, concurrency=4, seed=s) for s in range(2)]
    pres = [pack_dev.prepack(m.cas_register(), list(h)) for h in hs]
    got = pack_dev.materialize_batch(pres)
    assert pack_dev.dev_stats()["dev_packs"] == 0
    for g, h in zip(got, hs):
        s, _ = _spec(m.cas_register(), h)
        _assert_tables_equal(g, s)


# --- the streaming paint helper ----------------------------------------------


def test_stream_paint_matches_numpy_reference():
    rng = np.random.default_rng(0)
    n1, n_new, W = 40, 12, 6
    # Painters: each op j paints rows [r0, r1) at a fixed column; build
    # non-overlapping per-column intervals the way the settle does.
    p_gid, p_slot, r0, r1 = [], [], [], []
    for col in range(W):
        row = 0
        while row < n_new:
            span = int(rng.integers(1, 4))
            gid = int(rng.integers(0, n1))
            p_gid.append(gid)
            p_slot.append(col)
            r0.append(row)
            r1.append(min(n_new, row + span))
            row += span + int(rng.integers(0, 3))
    p_gid = np.asarray(p_gid, np.int32)
    p_slot = np.asarray(p_slot, np.int32)
    r0 = np.asarray(r0, np.int32)
    r1 = np.asarray(r1, np.int32)
    op_f = rng.integers(0, 3, n1).astype(np.int32)
    op_v = rng.integers(-5, 5, (n1, 2)).astype(np.int32)
    op_crashed = rng.random(n1) < 0.3
    got = pack_dev.paint_tables_dev(
        p_slot, r0, r1, p_gid + 1, op_f, op_v, op_crashed,
        n1, n_new, W, kernel="test")
    assert got is not None
    grid = np.zeros((n_new, W), np.int32)
    for g, c, a, b in zip(p_gid, p_slot, r0, r1):
        grid[a:b, c] = g + 1
    active = grid != 0
    slot_op = grid - 1
    np.testing.assert_array_equal(got[0], grid)
    np.testing.assert_array_equal(got[1], active)
    np.testing.assert_array_equal(
        got[2], np.where(active, op_f[np.clip(slot_op, 0, None)], 0))
    np.testing.assert_array_equal(got[4], slot_op)
    np.testing.assert_array_equal(
        got[5], np.where(active,
                         op_crashed[np.clip(slot_op, 0, None)], False))


# --- the daemon's admission offload (doc/service.md § Device packing) --------


def test_daemon_wave_packs_on_device_with_oracle_parity(monkeypatch,
                                                        tmp_path):
    # One flushed bin wave through the REAL worker path: admission
    # prepacks, _process_batch materializes the wave as one vmapped
    # pack-dev dispatch, and the verdicts match the CPU oracle.
    from jepsen_tpu.lin import cpu
    from jepsen_tpu.service.daemon import CheckerService, Request

    monkeypatch.setenv("JEPSEN_TPU_PACK_DEV_MIN_K", "2")
    monkeypatch.setenv("JEPSEN_TPU_SERVICE_STATS",
                       str(tmp_path / "stats.json"))
    svc = CheckerService("127.0.0.1", 0,
                         stats_file=str(tmp_path / "stats.json"))
    model = m.cas_register()
    # Window/cap (and so the bin) vary with the synth draw — scan
    # seeds for four histories sharing one bin so the wave is a
    # single flush.
    by_bin: dict = {}
    for s in range(32):
        h = list(synth.generate_register_history(
            60, concurrency=4, seed=s, value_range=3, crash_prob=0.02,
            max_crashes=2))
        _, key, _ = svc._pack_admission(model, h)
        by_bin.setdefault(key, []).append(h)
        if len(by_bin[key]) == 4:
            hs = by_bin[key]
            break
    else:
        pytest.fail(f"no bin reached 4 histories: {by_bin.keys()}")
    # Corrupt one lane for verdict diversity — only with a corruption
    # that keeps the bin (it can change the cap bucket).
    for cs in range(8):
        hc = list(synth.corrupt_history(list(hs[2]), seed=cs))
        if svc._pack_admission(model, hc)[1] == key:
            hs[2] = hc
            break
    out: list = []
    reqs = []
    for i, h in enumerate(hs):
        pre, key, fp = svc._pack_admission(model, list(h))
        assert pre is not None and fp is not None
        reqs.append(Request(
            rid=i, model_name="cas-register", model=model,
            history=list(h), packed=None, prepack=pre, bin=key,
            fingerprint=fp,
            respond=lambda msg, i=i: out.append((i, msg))))
    assert len({r.bin for r in reqs}) == 1
    svc._process_batch(reqs)
    st = pack_dev.dev_stats()
    assert st["dev_lanes"] == 4 and st["dev_packs"] == 1
    assert len(out) == 4
    for i, msg in out:
        want = cpu.check_packed(
            prepare.prepare(model, list(hs[i])))["valid?"]
        assert msg["result"]["valid?"] == want, i
    # Satellite 1: the admission pack wall is surfaced per bin.
    assert reqs[0].bin in svc.stats()["bin_pack_s"]


def test_wire_fingerprint_matches_admission(monkeypatch, tmp_path):
    # protocol.request_fingerprint (client-side) must equal the
    # daemon's admission fingerprint bit for bit — the result-fetch
    # contract now rides the pre-pack columns.
    from jepsen_tpu.service import protocol
    from jepsen_tpu.service.daemon import CheckerService

    svc = CheckerService("127.0.0.1", 0,
                         stats_file=str(tmp_path / "stats.json"))
    h = synth.generate_register_history(
        80, concurrency=4, seed=9, value_range=3)
    _, _, fp = svc._pack_admission(m.cas_register(), list(h))
    assert fp == protocol.request_fingerprint("cas-register", list(h))


# --- the stream settle's device paint (doc/streaming.md § Device packing) ----


def _stream_pack_rows(model, events, step, monkeypatch, rows):
    """Feed/settle in `step`-sized chunks with the stream device
    threshold pinned to `rows` (1 = every settle paints on device,
    huge = pure numpy)."""
    from jepsen_tpu.stream import IncrementalPacker

    monkeypatch.setenv("JEPSEN_TPU_PACK_DEV_STREAM_ROWS", str(rows))
    pk = IncrementalPacker(model)
    fps = []
    for i in range(0, len(events), step):
        pk.feed_many(events[i:i + step])
        pk.settle()
        fps.append(pk.prefix_fingerprint(pk.R))
    pk.settle(final=True)
    fps.append(pk.prefix_fingerprint(pk.R))
    return pk, fps


def _assert_stream_dev_parity(model, events, step, monkeypatch):
    a, fa = _stream_pack_rows(model, list(events), step, monkeypatch, 1)
    assert pack_dev.dev_stats()["dev_packs"] > 0
    pack_dev.reset_dev_stats()
    b, fb = _stream_pack_rows(model, list(events), step, monkeypatch,
                              1 << 30)
    assert pack_dev.dev_stats()["dev_packs"] == 0
    assert fa == fb                       # per-increment fingerprints
    pa, pb = a.packed(), b.packed()
    assert pa.window == pb.window and pa.R == pb.R
    for name in ("ret_slot", "ret_op", "active", "slot_f", "slot_v",
                 "slot_op", "crashed"):
        va, vb = getattr(pa, name), getattr(pb, name)
        assert np.asarray(va).dtype == np.asarray(vb).dtype, name
        np.testing.assert_array_equal(va, vb, err_msg=name)
    np.testing.assert_array_equal(pa._reduction_tables[0],
                                  pb._reduction_tables[0])
    np.testing.assert_array_equal(pa._reduction_tables[1],
                                  pb._reduction_tables[1])
    assert a.max_used == b.max_used and a._free == b._free
    assert a._slot_of == b._slot_of and a._cur_active == b._cur_active


@pytest.mark.parametrize("seed,step", [(0, 37), (1, 120)])
def test_stream_paint_dev_parity(seed, step, monkeypatch):
    h = synth.generate_register_history(
        500, concurrency=6, seed=seed, crash_prob=0.03, max_crashes=5)
    _assert_stream_dev_parity(m.cas_register(), h, step, monkeypatch)


def test_stream_paint_dev_parity_mutex(monkeypatch):
    h = synth.generate_mutex_history(
        300, concurrency=5, seed=2, crash_prob=0.03, max_crashes=4)
    _assert_stream_dev_parity(m.mutex(), h, 60, monkeypatch)


def test_stream_paint_wedge_falls_back(monkeypatch):
    # A wedged stream paint must degrade to the numpy path with the
    # increments' fingerprints unchanged — never a verdict cost.
    monkeypatch.setenv("JEPSEN_TPU_DISPATCH_RETRIES", "0")
    h = synth.generate_register_history(
        400, concurrency=6, seed=7, crash_prob=0.02, max_crashes=3)
    b, fb = _stream_pack_rows(m.cas_register(), list(h), 80,
                              monkeypatch, 1 << 30)
    pack_dev.reset_dev_stats()
    supervise.inject_wedge("pack-dev", 99, deadline_s=0.05)
    a, fa = _stream_pack_rows(m.cas_register(), list(h), 80,
                              monkeypatch, 1)
    st = pack_dev.dev_stats()
    assert st["dev_packs"] == 0 and st["host_fallbacks"] > 0
    assert fa == fb
