"""Generator DSL tests, modeled on the reference's
jepsen/test/jepsen/generator_test.clj: a real multithreaded harness drains
the generator from one thread per logical process (generator_test.clj:9-25),
plus combinator semantics."""

import threading
import time

from jepsen_tpu import generator as g
from jepsen_tpu.history import Op
import pytest

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick

TEST = {"concurrency": 3, "nodes": ["n1", "n2", "n3"]}


def drain(source, threads=(0, 1, 2), test=TEST, max_ops=10000):
    """Spin one thread per logical thread id; each drains the generator
    until it yields None. Returns ops in completion order."""
    ops = []
    lock = threading.Lock()

    def worker(thread_id):
        with g.with_threads(tuple(sorted([t for t in threads
                                          if isinstance(t, int)])) +
                            tuple(t for t in threads
                                  if not isinstance(t, int))):
            n = 0
            while n < max_ops:
                o = g.op_and_validate(source, test, thread_id)
                if o is None:
                    return
                with lock:
                    ops.append((thread_id, o))
                n += 1

    ts = [threading.Thread(target=worker, args=(t,)) for t in threads]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    return ops


class TestBasicProtocol:
    def test_none_terminates(self):
        assert g.op(None, TEST, 0) is None

    def test_op_yields_itself(self):
        o = Op("invoke", "read", None)
        assert g.op(o, TEST, 0) is o

    def test_fn_as_generator(self):
        assert g.op(lambda: Op("invoke", "read", None), TEST, 0).f == "read"
        assert g.op(lambda test, process: Op("invoke", "w", process),
                    TEST, 5).value == 5

    def test_validate_rejects_garbage(self):
        import pytest

        with pytest.raises(AssertionError):
            g.op_and_validate(lambda: 42, TEST, 0)

    def test_process_to_thread(self):
        assert g.process_to_thread(TEST, 7) == 1
        assert g.process_to_thread(TEST, "nemesis") == "nemesis"

    def test_process_to_node(self):
        assert g.process_to_node(TEST, 0) == "n1"
        assert g.process_to_node(TEST, 4) == "n2"
        assert g.process_to_node(TEST, "nemesis") is None


class TestCombinators:
    def test_once(self):
        ops = drain(g.once(Op("invoke", "read", None)))
        assert len(ops) == 1

    def test_limit(self):
        ops = drain(g.limit(5, Op("invoke", "read", None)))
        assert len(ops) == 5

    def test_seq_advances_on_nil(self):
        source = g.seq([g.once(Op("invoke", "a", None)),
                        g.once(Op("invoke", "b", None)),
                        g.once(Op("invoke", "c", None))])
        ops = drain(source, threads=(0,))
        assert [o.f for _, o in ops] == ["a", "b", "c"]

    def test_concat(self):
        source = g.concat(g.once(Op("invoke", "a", None)),
                          g.once(Op("invoke", "b", None)))
        ops = drain(source, threads=(0,))
        assert [o.f for _, o in ops] == ["a", "b"]

    def test_mix_produces_all(self):
        source = g.limit(200, g.mix([Op("invoke", "a", None),
                                     Op("invoke", "b", None)]))
        fs = {o.f for _, o in drain(source, threads=(0,))}
        assert fs == {"a", "b"}

    def test_filter(self):
        source = g.limit(10, g.filter_gen(lambda o: o.f == "read",
                                          g.cas(5)))
        assert all(o.f == "read" for _, o in drain(source, threads=(0,)))

    def test_time_limit(self):
        source = g.time_limit(0.2, Op("invoke", "read", None))
        t0 = time.monotonic()
        ops = drain(source, threads=(0,), max_ops=10 ** 6)
        assert time.monotonic() - t0 < 5
        assert len(ops) > 0

    def test_stagger_delays(self):
        source = g.limit(5, g.stagger(0.01, Op("invoke", "read", None)))
        t0 = time.monotonic()
        drain(source, threads=(0,))
        assert time.monotonic() - t0 > 0.005

    def test_drain_queue(self):
        source = g.drain_queue(g.limit(10, g.queue_gen()))
        ops = [o for _, o in drain(source, threads=(0,))]
        enq = sum(1 for o in ops if o.f == "enqueue")
        deq = sum(1 for o in ops if o.f == "dequeue")
        assert deq >= enq

    def test_each_per_process(self):
        source = g.each(lambda: g.once(Op("invoke", "read", None)))
        ops = drain(source)
        assert len(ops) == 3  # one per thread

    def test_start_stop(self):
        source = g.start_stop(0.0, 0.0)
        seen = []
        with g.with_threads((0,)):
            for _ in range(4):
                seen.append(g.op(source, TEST, 0))
        # ops interleaved with None sleeps
        fs = [o.f for o in seen if o is not None]
        assert fs[:2] == ["start", "stop"]


class TestRouting:
    def test_nemesis_routing(self):
        source = g.limit(20, g.nemesis(Op("info", "n", None),
                                       Op("invoke", "c", None)))
        ops = drain(source, threads=(0, 1, "nemesis"))
        for tid, o in ops:
            if tid == "nemesis":
                assert o.f == "n"
            else:
                assert o.f == "c"

    def test_clients_blocks_nemesis(self):
        source = g.limit(5, g.clients(Op("invoke", "c", None)))
        ops = drain(source, threads=(0, "nemesis"))
        assert all(tid != "nemesis" for tid, _ in ops)

    def test_reserve(self):
        source = g.reserve(1, Op("invoke", "w", None),
                           1, Op("invoke", "c", None),
                           Op("invoke", "r", None))
        with g.with_threads((0, 1, 2)):
            assert g.op(source, TEST, 0).f == "w"
            assert g.op(source, TEST, 1).f == "c"
            assert g.op(source, TEST, 2).f == "r"


class TestSynchronization:
    def test_phases(self):
        source = g.phases(g.limit(3, Op("invoke", "a", None)),
                          g.limit(3, Op("invoke", "b", None)))
        ops = drain(source)
        fs = [o.f for _, o in ops]
        # all a's must precede all b's
        last_a = max(i for i, f in enumerate(fs) if f == "a")
        first_b = min(i for i, f in enumerate(fs) if f == "b")
        assert last_a < first_b

    def test_then(self):
        source = g.then(g.limit(2, Op("invoke", "b", None)),
                        g.limit(2, Op("invoke", "a", None)))
        ops = drain(source, threads=(0,))
        assert [o.f for _, o in ops] == ["a", "a", "b", "b"]

    def test_barrier(self):
        source = g.barrier(g.limit(3, Op("invoke", "a", None)))
        ops = drain(source)
        assert len(ops) == 3

    def test_await(self):
        called = []
        source = g.await_fn(lambda: called.append(1),
                            g.limit(2, Op("invoke", "a", None)))
        ops = drain(source, threads=(0,))
        assert called == [1]
        assert len(ops) == 2
