"""Search-space reduction tests: pure-op saturation + canonical chains.

The reductions (prepare.reduction_tables; engines consume them via
bfs.reduction_bit_tables) are EXACT: verdict and death row must match the
plain search on every history. The plain CPU search is the spec; the
reduced CPU search is fuzzed against it here, and the device engines
(which always run reduced) are fuzzed against the reduced CPU oracle in
their own test files. These reductions are what make the wide-window band
(windows 21..64, e.g. cockroach's concurrency-30 registers,
cockroachdb/src/jepsen/cockroach.clj:40-41) tractable where the
reference's knossos search DNFs.
"""

import numpy as np
import pytest

from jepsen_tpu import models as m
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.lin import bfs, cpu, prepare, synth
from jepsen_tpu.models.kernels import F_READ


def verdict(p, reduce):
    init = (0, tuple(int(x) for x in p.init_state))
    try:
        cpu.search_rows(p, {init}, None, 0, p.R, reduce=reduce)
        return (True, None)
    except cpu.Dead as d:
        return (False, d.r)


class TestReductionTables:
    def test_pure_marks_reads_only(self):
        h = synth.generate_register_history(60, concurrency=4, seed=0,
                                            value_range=3, crash_prob=0.1)
        p = prepare.prepare(m.cas_register(), h)
        pure, pred = prepare.reduction_tables(p)
        assert pure.shape == p.active.shape
        # Pure exactly where an active slot holds a read.
        want = p.active & (p.slot_f == F_READ)
        assert (pure == want).all()

    def test_pred_chains_identical_live_ops_by_return(self):
        h = synth.generate_register_history(80, concurrency=6, seed=3,
                                            value_range=2, crash_prob=0.1)
        p = prepare.prepare(m.cas_register(), h)
        pure, pred = prepare.reduction_tables(p)
        ret_row = {int(p.ret_op[r]): r for r in range(p.R)}
        chained = 0
        for r in range(p.R):
            for j in range(p.window):
                q = pred[r, j]
                if q < 0 or p.crashed[r, j]:
                    continue
                chained += 1
                # Both ends active, same (f, value), both live, and the
                # predecessor returns strictly earlier.
                assert p.active[r, j] and p.active[r, q]
                assert p.slot_f[r, j] == p.slot_f[r, q]
                assert (p.slot_v[r, j] == p.slot_v[r, q]).all()
                oj, oq = int(p.slot_op[r, j]), int(p.slot_op[r, q])
                assert oj in ret_row and oq in ret_row
                assert ret_row[oq] < ret_row[oj]
                # Neither end is pure or crashed.
                assert not pure[r, j] and not pure[r, q]
                assert not p.crashed[r, j] and not p.crashed[r, q]
        assert chained > 0  # value_range=2 must produce identical ops

    def test_crashed_ops_chain_among_crashed_by_invoke(self):
        """Identical crashed ops chain in invoke order — among
        themselves only, never to/from live ops."""
        h = synth.generate_register_history(
            120, concurrency=6, seed=1, value_range=1, crash_prob=0.3,
            fs=("write",))
        p = prepare.prepare(m.cas_register(), h)
        _, pred = prepare.reduction_tables(p)
        invoke_of = {i: o.invoke_pos for i, o in enumerate(p.ops)}
        crashed_chains = 0
        for r in range(p.R):
            for j in range(p.window):
                q = pred[r, j]
                if q < 0:
                    continue
                # Chain families never cross.
                assert bool(p.crashed[r, j]) == bool(p.crashed[r, q])
                if p.crashed[r, j]:
                    crashed_chains += 1
                    assert p.slot_f[r, j] == p.slot_f[r, q]
                    assert (p.slot_v[r, j] == p.slot_v[r, q]).all()
                    oj, oq = int(p.slot_op[r, j]), int(p.slot_op[r, q])
                    assert invoke_of[oq] < invoke_of[oj]
        assert crashed_chains > 0  # value_range=1 writes must collide

    def test_cached_on_packed_history(self):
        h = synth.generate_register_history(30, concurrency=3, seed=0)
        p = prepare.prepare(m.cas_register(), h)
        a = prepare.reduction_tables(p)
        b = prepare.reduction_tables(p)
        assert a[0] is b[0] and a[1] is b[1]


class TestReducedCpuExactness:
    """Verdict AND death row of the reduced search == plain search."""

    @pytest.mark.parametrize("seed", range(12))
    def test_register_fuzz(self, seed):
        h = synth.generate_register_history(50, concurrency=5, seed=seed,
                                            value_range=3, crash_prob=0.1)
        for hh in (h, synth.corrupt_history(h, seed=seed)):
            p = prepare.prepare(m.cas_register(), hh)
            assert verdict(p, False) == verdict(p, True)

    @pytest.mark.parametrize("seed", range(8))
    def test_crash_dominance_device_parity(self, seed):
        """The device engine's crashed-subset dominance prune
        (bfs._dedup_keys_dom) must preserve verdict and death row
        against the (unpruned) CPU oracle — crash-heavy histories with
        DISTINCT crashed values, where chains alone can't collapse the
        2^crashes blowup."""
        h = synth.generate_register_history(
            60, concurrency=6, seed=seed, value_range=5, crash_prob=0.3,
            max_crashes=8)
        for hh in (h, synth.corrupt_history(h, seed=seed)):
            p = prepare.prepare(m.cas_register(), hh)
            want = cpu.check_packed(p)
            got = bfs.check_packed(p)
            assert got["valid?"] == want["valid?"], (seed, got, want)
            if want["valid?"] is False:
                assert got["op"] == want["op"]

    @pytest.mark.parametrize("seed", range(2))
    def test_crash_dominance_pair_band_parity(self, seed):
        """Same, through the pair-key band (window past 31-b bits) —
        partition-shaped histories land there. Sizes are small: the
        unpruned Python oracle pays the full 2^crashes blowup that the
        device prune removes."""
        h = synth.generate_partitioned_register_history(
            100, concurrency=30, seed=seed, partition_every=50,
            partition_len=15, max_crashes=4)
        p = prepare.prepare(m.cas_register(), h)
        want = cpu.check_packed(p)
        got = bfs.check_packed(p)
        assert got["valid?"] == want["valid?"] is True, (seed, got)

    @pytest.mark.parametrize("seed", range(2))
    def test_crash_dominance_pair_band_invalid_parity(self, seed):
        """Invalid-verdict parity on the pair-key dominance band: the
        corrupted partitioned history must stay invalid AND name the
        same violating op as the CPU oracle (death-row exactness of the
        prune on the band partition histories actually use)."""
        h = synth.generate_partitioned_register_history(
            100, concurrency=30, seed=seed, partition_every=50,
            partition_len=15, max_crashes=4)
        hh = synth.corrupt_history(h, seed=seed + 1)
        p = prepare.prepare(m.cas_register(), hh)
        want = cpu.check_packed(p)
        got = bfs.check_packed(p)
        assert got["valid?"] == want["valid?"], (seed, got, want)
        if want["valid?"] is False:
            assert got["op"] == want["op"], (seed, got, want)

    @pytest.mark.parametrize("seed", range(10))
    def test_crash_heavy_register_fuzz(self, seed):
        """The crashed-chain reduction's home turf: many identical
        crashed mutators (partition-shaped histories, BASELINE
        config 5)."""
        h = synth.generate_register_history(
            40, concurrency=5, seed=seed, value_range=2, crash_prob=0.35,
            max_crashes=12)
        for hh in (h, synth.corrupt_history(h, seed=seed)):
            p = prepare.prepare(m.cas_register(), hh)
            assert verdict(p, False) == verdict(p, True)

    @pytest.mark.parametrize("seed", range(8))
    def test_mutex_fuzz(self, seed):
        h = synth.generate_mutex_history(40, concurrency=4, seed=seed,
                                         crash_prob=0.1)
        for hh in (h, synth.corrupt_history(h, seed=seed)):
            p = prepare.prepare(m.mutex(), hh)
            assert verdict(p, False) == verdict(p, True)

    @pytest.mark.parametrize("seed", range(6))
    def test_set_fuzz(self, seed):
        # Set reads are pure too — the oracle runs reduced by default,
        # so the reduction must be exact for the set kernel as well.
        # (corrupt_history can't rewrite collection-valued reads, so the
        # invalid side is a surgical wrong-membership read instead.)
        h = list(synth.generate_set_history(50, concurrency=4, seed=seed))
        p = prepare.prepare(m.set_model(), h)
        if p.kernel is not None:
            assert verdict(p, False) == verdict(p, True)
        bad = list(h)
        for i in range(len(bad) - 1, -1, -1):
            op = bad[i]
            if op.is_ok and op.f == "read" and op.value is not None:
                bad[i] = op.replace(value=list(op.value) + [9999])
                break
        p = prepare.prepare(m.set_model(), bad)
        if p.kernel is not None:
            assert verdict(p, False) == verdict(p, True)

    @pytest.mark.parametrize("seed", range(6))
    def test_queue_fuzz(self, seed):
        h = synth.generate_queue_history(40, concurrency=4, seed=seed)
        for hh in (h, synth.corrupt_history(h, seed=seed)):
            p = prepare.prepare(m.unordered_queue(), hh)
            if p.kernel is None:
                continue
            assert verdict(p, False) == verdict(p, True)

    def test_read_saturation_filters_at_return(self):
        # A read of a value never written must still die at its return.
        h = History.of(
            invoke_op(0, "write", 1), ok_op(0, "write", 1),
            invoke_op(1, "read", None), ok_op(1, "read", 2))
        p = prepare.prepare(m.cas_register(), h)
        assert verdict(p, True) == (False, 1)

    @pytest.mark.parametrize("seed", range(6))
    def test_reduced_witness_is_a_valid_linearization(self, seed):
        """Witness tracking now rides the REDUCED search (saturated
        reads join the path at their absorption point). The emitted
        order must replay cleanly through the Python model."""
        h = synth.generate_register_history(40, concurrency=5, seed=seed,
                                            value_range=3, crash_prob=0.1)
        p = prepare.prepare(m.cas_register(), h)
        r = cpu.check_packed(p, witness=True)
        assert r["valid?"] is True and r["reduced"] is True
        path = r.get("witness")
        assert path is not None
        # Replay: every step must be legal in sequence, every returning
        # op must appear, and no op twice.
        from jepsen_tpu.lin.prepare import py_step_fn

        step = py_step_fn(p.kernel.name)
        st = tuple(int(x) for x in p.init_state)
        seen = set()
        op_f = {}
        op_v = {}
        for rr in range(p.R):
            for j in range(p.window):
                if p.active[rr, j] and p.slot_op[rr, j] >= 0:
                    oi = int(p.slot_op[rr, j])
                    op_f[oi] = int(p.slot_f[rr, j])
                    op_v[oi] = tuple(int(x) for x in p.slot_v[rr, j])
        idx_of = {o.op_index: i for i, o in enumerate(p.ops)}
        for d in path:
            oi = idx_of[d["index"]]
            assert oi not in seen
            seen.add(oi)
            ok, st = step(st, op_f[oi], op_v[oi])
            assert ok, (seed, d)
        returners = {int(x) for x in p.ret_op}
        assert returners <= seen


class TestBeyondDeviceWindow:
    def test_window_past_64_falls_back_to_cpu(self):
        # 70 concurrent identical writes: window 70 exceeds the device
        # bitset, but analysis() re-packs wide and the reduced host
        # search (canonical chains collapse the identical writes to
        # prefixes) decides it instantly.
        from jepsen_tpu.lin import analysis

        evs = [invoke_op(pr, "write", 1) for pr in range(70)]
        evs += [ok_op(pr, "write", 1) for pr in range(70)]
        evs += [invoke_op(0, "read", None), ok_op(0, "read", 1)]
        r = analysis(m.cas_register(), History.of(*evs))
        assert r["valid?"] is True
        assert r["analyzer"] == "cpu-jit"
        bad = evs[:-1] + [ok_op(0, "read", 2)]
        r = analysis(m.cas_register(), History.of(*bad))
        assert r["valid?"] is False

    def test_device_alone_still_reports_window_overflow(self):
        from jepsen_tpu.lin import analysis

        evs = [invoke_op(pr, "write", 1) for pr in range(70)]
        evs += [ok_op(pr, "write", 1) for pr in range(70)]
        r = analysis(m.cas_register(), History.of(*evs),
                     algorithm="tpu")
        assert r["valid?"] == "unknown"


class TestWideWindowDevice:
    """The reduction payoff: windows past the dense bound decide on
    device where the plain frontier would drown the cap schedule."""

    def test_concurrency_16_register_decides(self):
        h = synth.generate_register_history(300, concurrency=16, seed=5,
                                            value_range=4,
                                            crash_prob=0.01, max_crashes=3)
        p = prepare.prepare(m.cas_register(), h)
        r = bfs.check_packed(p)
        assert r["valid?"] is cpu.check_packed(p)["valid?"] is True

    @pytest.mark.parametrize("seed", range(4))
    def test_multiword_spike_parity(self, seed):
        # packed_keys=False forces the multiword formulation (the one
        # wide windows and set/queue states use) through tiny chunked
        # caps into the multiword spike executor.
        h = synth.generate_register_history(80, concurrency=6, seed=seed,
                                            value_range=3, crash_prob=0.1)
        for hh in (h, synth.corrupt_history(h, seed=seed)):
            p = prepare.prepare(m.cas_register(), hh)
            want = cpu.check_packed(p)["valid?"]
            r = bfs.check_packed(p, cap_schedule=(8,),
                                 spike_caps=(1024, 16384),
                                 spike_dropback=4, packed_keys=False)
            assert r["valid?"] == want, (seed, r, want)

    def test_multiword_spike_set_model(self):
        h = synth.generate_set_history(60, concurrency=5, seed=1)
        p = prepare.prepare(m.set_model(), h)
        want = cpu.check_packed(p)["valid?"]
        r = bfs.check_packed(p, cap_schedule=(8,),
                             spike_caps=(1024, 16384), spike_dropback=4)
        assert r["valid?"] == want

    @pytest.mark.parametrize("seed", range(3))
    def test_host_row_mode_parity(self, seed):
        """Host-row executor parity (single-key crash-dom band): tiny
        chunked caps force every breathing row through the
        host-sequenced single-pass dispatches (bfs._host_rows) with the
        dominance window forced on at every capacity."""
        h = synth.generate_register_history(
            60, concurrency=6, seed=seed, value_range=3, crash_prob=0.3,
            max_crashes=8)
        for hh in (h, synth.corrupt_history(h, seed=seed)):
            p = prepare.prepare(m.cas_register(), hh)
            want = cpu.check_packed(p)
            got = bfs.check_packed(p, cap_schedule=(8,),
                                   host_caps=(64, 4096))
            assert got["valid?"] == want["valid?"], (seed, got, want)
            if want["valid?"] is False:
                assert got["op"] == want["op"]

    @pytest.mark.parametrize("seed", range(2))
    def test_host_row_mode_pair_band_parity(self, seed):
        """Host-row executor on the pair-key crash-dom band — the 100k
        partitioned class's exact shape, scaled down."""
        h = synth.generate_partitioned_register_history(
            100, concurrency=30, seed=seed, partition_every=50,
            partition_len=15, max_crashes=4)
        for hh in (h, synth.corrupt_history(h, seed=seed + 3)):
            p = prepare.prepare(m.cas_register(), hh)
            want = cpu.check_packed(p)
            got = bfs.check_packed(p, cap_schedule=(8,),
                                   host_caps=(64, 4096))
            assert got["valid?"] == want["valid?"], (seed, got, want)
            if want["valid?"] is False:
                assert got["op"] == want["op"]

    def test_host_row_mode_overflow_unknown(self):
        """Host caps exhausted mid-wave: honest unknown, never a
        truncated-frontier verdict."""
        h = synth.generate_register_history(
            60, concurrency=6, seed=1, value_range=3, crash_prob=0.3,
            max_crashes=8)
        p = prepare.prepare(m.cas_register(), h)
        r = bfs.check_packed(p, cap_schedule=(2,), host_caps=(4,))
        assert r["valid?"] == "unknown"
        # Taxonomy: a genuine frontier-size overflow reports
        # "capacity"; closure pass-budget exhaustion would report
        # "budget" (see test_lin_bfs).
        assert r["overflow"] == "capacity"
        assert "frontier exceeded capacity" in r["error"]

    def test_explain_through_host_row_death(self):
        """A death decided inside host-row mode must still produce
        final-paths via the dead row's entry snapshot."""
        h = synth.corrupt_history(
            synth.generate_register_history(120, concurrency=8, seed=4,
                                            value_range=3,
                                            crash_prob=0.2,
                                            max_crashes=6), seed=4)
        p = prepare.prepare(m.cas_register(), h)
        want = cpu.check_packed(p)
        got = bfs.check_packed(p, cap_schedule=(2,),
                               host_caps=(64, 4096), explain=True)
        assert got["valid?"] == want["valid?"]
        if want["valid?"] is False:
            assert got["op"] == want["op"]
            assert got["final-paths"], got

    def test_explain_through_spike_death(self):
        # A death decided inside spike mode must still produce
        # final-paths, via the dead ROW's entry snapshot (bounded
        # one-row CPU replay).
        h = synth.corrupt_history(
            synth.generate_register_history(120, concurrency=8, seed=4,
                                            value_range=3,
                                            crash_prob=0.05), seed=4)
        p = prepare.prepare(m.cas_register(), h)
        want = cpu.check_packed(p)
        got = bfs.check_packed(p, cap_schedule=(2,),
                               spike_caps=(1024, 16384),
                               spike_dropback=2, explain=True)
        assert got["valid?"] == want["valid?"]
        if want["valid?"] is False:
            assert got["op"] == want["op"]
            assert got["final-paths"], got

    def test_spike_executor_death_row_matches_oracle(self):
        h = synth.corrupt_history(
            synth.generate_register_history(120, concurrency=8, seed=2,
                                            value_range=3,
                                            crash_prob=0.05), seed=2)
        p = prepare.prepare(m.cas_register(), h)
        want = cpu.check_packed(p)
        got = bfs.check_packed(p, cap_schedule=(2,),
                               spike_caps=(1024, 16384), spike_dropback=2)
        assert got["valid?"] == want["valid?"]
        if want["valid?"] is False:
            assert got["op"] == want["op"]


class TestJitLinearization:
    """The just-in-time linearization gate (bfs.expansion_tables
    exp_jit/exp_rv): expansions fire only for the returner, its
    precondition chain, or read absorption. EXACT — fuzzed for verdict
    and death-row parity against the CPU oracle and the eager device
    search."""

    @pytest.mark.parametrize("seed", range(10))
    def test_cas_chain_fuzz(self, seed):
        """cas-heavy histories (long precondition chains) with crashes:
        the shape where lazy gating could soonest lose a needed
        excursion."""
        h = synth.generate_register_history(
            60, concurrency=8, seed=seed, value_range=4, crash_prob=0.25,
            max_crashes=6, fs=("cas", "cas", "write", "read"))
        for hh in (h, synth.corrupt_history(h, seed=seed)):
            p = prepare.prepare(m.cas_register(), hh)
            want = cpu.check_packed(p)
            got = bfs.check_packed(p)
            assert got["valid?"] == want["valid?"], (seed, got, want)
            if want["valid?"] is False:
                assert got["op"] == want["op"]

    @pytest.mark.parametrize("seed", range(6))
    def test_lazy_eager_device_parity(self, seed):
        """Same verdict with the gate on and off (eager device path)."""
        h = synth.generate_partitioned_register_history(
            150, concurrency=20, seed=seed, partition_every=60,
            partition_len=20, max_crashes=5, value_range=4)
        for hh in (h, synth.corrupt_history(h, seed=seed + 7)):
            p = prepare.prepare(m.cas_register(), hh)
            lazy = bfs.check_packed(p, lazy=True)
            p2 = prepare.prepare(m.cas_register(), hh)
            eager = bfs.check_packed(p2, lazy=False)
            assert lazy["valid?"] == eager["valid?"], (seed, lazy, eager)
            if eager["valid?"] is False:
                assert lazy["op"] == eager["op"]

    @pytest.mark.parametrize("seed", range(8))
    def test_wide_window_read_heavy_fuzz(self, seed):
        """Read-heavy wide windows: the per-config rv clause must keep
        every read satisfiable."""
        h = synth.generate_register_history(
            80, concurrency=16, seed=seed, value_range=3, crash_prob=0.1,
            max_crashes=4, fs=("read", "read", "write", "cas"))
        for hh in (h, synth.corrupt_history(h, seed=seed)):
            p = prepare.prepare(m.cas_register(), hh)
            want = cpu.check_packed(p)
            got = bfs.check_packed(p)
            assert got["valid?"] == want["valid?"], (seed, got, want)
