"""Search-space reduction tests: pure-op saturation + canonical chains.

The reductions (prepare.reduction_tables; engines consume them via
bfs.reduction_bit_tables) are EXACT: verdict and death row must match the
plain search on every history. The plain CPU search is the spec; the
reduced CPU search is fuzzed against it here, and the device engines
(which always run reduced) are fuzzed against the reduced CPU oracle in
their own test files. These reductions are what make the wide-window band
(windows 21..64, e.g. cockroach's concurrency-30 registers,
cockroachdb/src/jepsen/cockroach.clj:40-41) tractable where the
reference's knossos search DNFs.
"""

import numpy as np
import pytest

from jepsen_tpu import models as m
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.lin import bfs, cpu, prepare, synth
from jepsen_tpu.models.kernels import F_READ


def verdict(p, reduce):
    init = (0, tuple(int(x) for x in p.init_state))
    try:
        cpu.search_rows(p, {init}, None, 0, p.R, reduce=reduce)
        return (True, None)
    except cpu.Dead as d:
        return (False, d.r)


class TestReductionTables:
    def test_pure_marks_reads_only(self):
        h = synth.generate_register_history(60, concurrency=4, seed=0,
                                            value_range=3, crash_prob=0.1)
        p = prepare.prepare(m.cas_register(), h)
        pure, pred = prepare.reduction_tables(p)
        assert pure.shape == p.active.shape
        # Pure exactly where an active slot holds a read.
        want = p.active & (p.slot_f == F_READ)
        assert (pure == want).all()

    def test_pred_chains_identical_live_ops_by_return(self):
        h = synth.generate_register_history(80, concurrency=6, seed=3,
                                            value_range=2, crash_prob=0.1)
        p = prepare.prepare(m.cas_register(), h)
        pure, pred = prepare.reduction_tables(p)
        ret_row = {int(p.ret_op[r]): r for r in range(p.R)}
        chained = 0
        for r in range(p.R):
            for j in range(p.window):
                q = pred[r, j]
                if q < 0:
                    continue
                chained += 1
                # Both ends active, same (f, value), both live, and the
                # predecessor returns strictly earlier.
                assert p.active[r, j] and p.active[r, q]
                assert p.slot_f[r, j] == p.slot_f[r, q]
                assert (p.slot_v[r, j] == p.slot_v[r, q]).all()
                oj, oq = int(p.slot_op[r, j]), int(p.slot_op[r, q])
                assert oj in ret_row and oq in ret_row
                assert ret_row[oq] < ret_row[oj]
                # Neither end is pure or crashed.
                assert not pure[r, j] and not pure[r, q]
                assert not p.crashed[r, j] and not p.crashed[r, q]
        assert chained > 0  # value_range=2 must produce identical ops

    def test_crashed_ops_never_chain(self):
        h = synth.generate_register_history(80, concurrency=5, seed=1,
                                            value_range=1, crash_prob=0.3)
        p = prepare.prepare(m.cas_register(), h)
        _, pred = prepare.reduction_tables(p)
        for r in range(p.R):
            for j in range(p.window):
                if pred[r, j] >= 0:
                    assert not p.crashed[r, pred[r, j]]
                    assert not p.crashed[r, j]

    def test_cached_on_packed_history(self):
        h = synth.generate_register_history(30, concurrency=3, seed=0)
        p = prepare.prepare(m.cas_register(), h)
        a = prepare.reduction_tables(p)
        b = prepare.reduction_tables(p)
        assert a[0] is b[0] and a[1] is b[1]


class TestReducedCpuExactness:
    """Verdict AND death row of the reduced search == plain search."""

    @pytest.mark.parametrize("seed", range(12))
    def test_register_fuzz(self, seed):
        h = synth.generate_register_history(50, concurrency=5, seed=seed,
                                            value_range=3, crash_prob=0.1)
        for hh in (h, synth.corrupt_history(h, seed=seed)):
            p = prepare.prepare(m.cas_register(), hh)
            assert verdict(p, False) == verdict(p, True)

    @pytest.mark.parametrize("seed", range(8))
    def test_mutex_fuzz(self, seed):
        h = synth.generate_mutex_history(40, concurrency=4, seed=seed,
                                         crash_prob=0.1)
        for hh in (h, synth.corrupt_history(h, seed=seed)):
            p = prepare.prepare(m.mutex(), hh)
            assert verdict(p, False) == verdict(p, True)

    def test_read_saturation_filters_at_return(self):
        # A read of a value never written must still die at its return.
        h = History.of(
            invoke_op(0, "write", 1), ok_op(0, "write", 1),
            invoke_op(1, "read", None), ok_op(1, "read", 2))
        p = prepare.prepare(m.cas_register(), h)
        assert verdict(p, True) == (False, 1)

    def test_witness_requires_unreduced(self):
        h = synth.generate_register_history(20, concurrency=3, seed=0)
        p = prepare.prepare(m.cas_register(), h)
        init = (0, tuple(int(x) for x in p.init_state))
        with pytest.raises(ValueError):
            cpu.search_rows(p, {init}, {init: None}, 0, p.R, reduce=True)


class TestWideWindowDevice:
    """The reduction payoff: windows past the dense bound decide on
    device where the plain frontier would drown the cap schedule."""

    def test_concurrency_16_register_decides(self):
        h = synth.generate_register_history(300, concurrency=16, seed=5,
                                            value_range=4,
                                            crash_prob=0.01, max_crashes=3)
        p = prepare.prepare(m.cas_register(), h)
        r = bfs.check_packed(p)
        assert r["valid?"] is cpu.check_packed(p)["valid?"] is True

    def test_spike_executor_death_row_matches_oracle(self):
        h = synth.corrupt_history(
            synth.generate_register_history(120, concurrency=8, seed=2,
                                            value_range=3,
                                            crash_prob=0.05), seed=2)
        p = prepare.prepare(m.cas_register(), h)
        want = cpu.check_packed(p)
        got = bfs.check_packed(p, cap_schedule=(2,),
                               spike_caps=(1024, 16384), spike_dropback=2)
        assert got["valid?"] == want["valid?"]
        if want["valid?"] is False:
            assert got["op"] == want["op"]
