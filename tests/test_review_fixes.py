"""Regression tests for reviewed failure modes: competition on kernel-less
models, worker open failure, independent batch gating, CLI exit severity,
client setup lifecycle."""

import threading

import pytest

from jepsen_tpu import checker as c
from jepsen_tpu import cli
from jepsen_tpu import core
from jepsen_tpu import generator as g
from jepsen_tpu import independent as ind
from jepsen_tpu import models as m
from jepsen_tpu import tests_support as ts
from jepsen_tpu.history import History, Op, invoke_op, ok_op
from jepsen_tpu.lin import analysis


def test_competition_decides_generic_models():
    """The device racer instantly returns 'unknown' for models without a
    kernel; competition must still wait for the host's definite verdict.
    The noop model is permanently kernel-less (set models gained device
    kernels, so they now legitimately route to the kernelized cpu-jit)."""
    h = History.of(invoke_op(0, "add", 1), ok_op(0, "add", 1),
                   invoke_op(0, "read", [1]), ok_op(0, "read", [1]))
    for _ in range(5):
        r = analysis(m.noop, h, algorithm="competition")
        assert r["valid?"] is True
        assert r["analyzer"] == "cpu-generic"
    r = analysis(m.set_model(), h, algorithm="competition")
    assert r["valid?"] is True
    # either racer may win the race; both must agree on the verdict
    assert r["analyzer"] in ("cpu-jit", "tpu-bfs")


def test_competition_detects_violation_on_generic_model():
    h = History.of(invoke_op(0, "add", 1), ok_op(0, "add", 1),
                   invoke_op(0, "read", [2]), ok_op(0, "read", [2]))
    r = analysis(m.set_model(), h, algorithm="competition")
    assert r["valid?"] is False


def test_failed_client_open_does_not_deadlock():
    class BadOpenClient(ts.AtomClient):
        opens = [0]

        def open(self, test, node):
            self.opens[0] += 1
            if self.opens[0] == 2:  # second worker's open explodes
                raise RuntimeError("connection refused")
            return super().open(test, node)

    test = ts.noop_test(
        client=BadOpenClient(ts.AtomRegister()),
        concurrency=3,
        generator=g.clients(g.limit(10, g.cas(3))))
    done = []

    def run():
        with pytest.raises(RuntimeError):
            core.run(test)
        done.append(True)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(20)
    assert done, "run deadlocked on a failed client open"


def test_independent_batch_only_replaces_linearizable():
    """A lifted non-linearizable checker must actually run, not be swapped
    for device linearizability verdicts."""
    ran = []

    def spy(test, model, history, opts):
        ran.append(opts.get("history-key"))
        return {c.VALID: False, "spy": True}

    h = History.of(invoke_op(0, "write", ind.KV("k", 1)),
                   ok_op(0, "write", ind.KV("k", 1)))
    r = ind.checker(c.FnChecker(spy)).check(None, m.cas_register(), h, {})
    assert ran == ["k"]
    assert r[c.VALID] is False
    assert r["results"]["k"].get("spy") is True


def test_independent_batch_runs_for_linearizable():
    h = History.of(invoke_op(0, "write", ind.KV("k", 1)),
                   ok_op(0, "write", ind.KV("k", 1)))
    r = ind.checker(c.linearizable("tpu")).check(
        None, m.cas_register(), h, {})
    assert r["results"]["k"]["analyzer"] in ("tpu-dense-batch",
                                              "tpu-bfs-batch")


def test_cli_exit_severity_invalid_dominates_unknown():
    calls = []

    def test_fn(options):
        calls.append(1)
        verdict = False if len(calls) == 1 else "unknown"
        return ts.noop_test(
            client=ts.AtomClient(ts.AtomRegister()),
            generator=g.clients(g.limit(2, g.cas(3))),
            checker=c.FnChecker(
                lambda t, mo, h, o, v=verdict: {c.VALID: v}))

    cmd = cli.single_test_cmd(test_fn)
    import argparse

    p = argparse.ArgumentParser()
    cmd["parser"](p)
    opts = p.parse_args(["--transport", "dummy", "--test-count", "2"])
    assert cmd["run"](opts) == cli.EXIT_INVALID


def test_client_setup_teardown_called_once():
    events = []

    class LifecycleClient(ts.AtomClient):
        def setup(self, test):
            events.append("setup")

        def teardown(self, test):
            events.append("teardown")

    test = ts.noop_test(
        client=LifecycleClient(ts.AtomRegister()),
        generator=g.clients(g.limit(6, g.cas(3))))
    core.run(test)
    assert events == ["setup", "teardown"]
