"""Frontier checkpoint/resume parity (the round-8 tentpole acceptance
criterion): a search killed at an episode boundary and resumed from its
checkpoint must produce a verdict, death row, AND final-paths identical
to the uninterrupted run — fuzzed against the lin/cpu.py oracle on the
window-34 pair-band witness shape (the scaled-down literal config-5
class; the 5k/window-25 shapes do not exercise the host-row machinery
at all, CLAUDE.md round-5 lore).

Soundness rests on the checkpoint carrying an EXACT committed frontier
at a row boundary: the continuation re-runs the identical deterministic
dispatch sequence, so nothing about the search tree changes — these
tests are the executable form of that argument."""

import os
import threading

import pytest

from jepsen_tpu import models as m
from jepsen_tpu.lin import bfs, cpu, prepare, supervise, synth

# Same compiled shapes as tests/test_lin_crashdom_witness.py (shared
# .jax_cache programs); `compiles` exempts the cold-cache compile from
# the quick tier's no-compile enforcement.
pytestmark = [pytest.mark.quick, pytest.mark.compiles]


@pytest.fixture(scope="module")
def witness_packed():
    h = synth.generate_partitioned_register_history(
        140, concurrency=40, seed=0, partition_every=60,
        partition_len=20, max_crashes=10)
    return prepare.prepare(m.cas_register(),
                           synth.corrupt_history(h, seed=3))


KW = dict(cap_schedule=(8,), host_caps=(64, 4096), explain=True)


def _paths_key(result):
    return sorted(repr(sorted(od["index"] for od in fp["path"]))
                  for fp in result["final-paths"])


def test_resume_parity_on_witness_shape(witness_packed, tmp_path):
    p = witness_packed
    # The shape must land in the pair-key crash-dom band, or the
    # host-row machinery (whose episode boundaries are what we
    # checkpoint) is not what decides here.
    assert p.window + max(len(p.unintern), 2).bit_length() > 31
    assert len(p.crashed_ops) > 0

    full = bfs.check_packed(p, **KW)
    assert full["valid?"] is False and full["final-paths"]

    # Kill the search right after the first HOST episode-boundary
    # checkpoint write (the on_save hook is the simulated kill; a real
    # kill -9 leaves exactly this file state, since writes are atomic).
    ck = str(tmp_path / "witness.ckpt.npz")
    ckpt = supervise.Checkpointer(ck, supervise.history_fingerprint(p),
                                  every_s=0)
    cancel = threading.Event()
    saves = []

    def on_save(kind, row):
        saves.append((kind, row))
        if kind == "host":
            cancel.set()

    ckpt.on_save = on_save
    killed = bfs.check_packed(p, cancel=cancel, checkpoint=ckpt, **KW)
    assert killed["valid?"] == "unknown"
    assert os.path.exists(ck), "interrupted run must keep its checkpoint"
    assert any(kind == "host" for kind, _ in saves)

    resumed = bfs.check_packed(p, checkpoint=ck, **KW)
    assert resumed["valid?"] is False
    assert resumed["resumed-from-row"] == saves[-1][1]
    # Verdict + death row + final-paths equal the uninterrupted run.
    assert resumed["op"] == full["op"]
    assert resumed["dead-row"] == full["dead-row"]
    assert _paths_key(resumed) == _paths_key(full)
    # ... and both agree with the CPU oracle (the executable spec).
    want = cpu.check_packed(p)
    assert want["valid?"] is False and resumed["op"] == want["op"]
    # A definite verdict deletes the checkpoint: a later fresh run
    # must not resume a finished search.
    assert not os.path.exists(ck)


def test_chunk_kind_resume_on_valid_history(tmp_path):
    # The chunk-loop checkpoint kind, on a history that DECIDES VALID:
    # resume mid-history and the verdict + frontier size must match.
    h = synth.generate_register_history(400, concurrency=5, seed=11,
                                        value_range=5)
    p = prepare.prepare(m.cas_register(), h)
    full = bfs.check_packed(p, chunk=64)
    assert full["valid?"] is True

    ck = str(tmp_path / "chunk.ckpt.npz")
    ckpt = supervise.Checkpointer(ck, supervise.history_fingerprint(p),
                                  every_s=0)
    cancel = threading.Event()
    saves = []

    def on_save(kind, row):
        saves.append((kind, row))
        if len(saves) == 2:
            cancel.set()

    ckpt.on_save = on_save
    killed = bfs.check_packed(p, chunk=64, cancel=cancel,
                              checkpoint=ckpt)
    assert killed["valid?"] == "unknown" and os.path.exists(ck)
    assert saves and all(kind == "chunk" for kind, _ in saves)

    resumed = bfs.check_packed(p, chunk=64, checkpoint=ck)
    assert resumed["valid?"] is True
    assert resumed["resumed-from-row"] == saves[-1][1] > 0
    assert resumed["final-frontier-size"] == full["final-frontier-size"]
    assert not os.path.exists(ck)


def test_mismatched_history_rejects_checkpoint(tmp_path):
    # A checkpoint from one history must NEVER seed another: the
    # fingerprint gate degrades the resume to a fresh (correct) run.
    h1 = synth.generate_register_history(200, concurrency=5, seed=1,
                                         value_range=5)
    h2 = synth.generate_register_history(200, concurrency=5, seed=2,
                                         value_range=5)
    p1 = prepare.prepare(m.cas_register(), h1)
    p2 = prepare.prepare(m.cas_register(), h2)
    ck = str(tmp_path / "mismatch.ckpt.npz")
    ckpt = supervise.Checkpointer(ck, supervise.history_fingerprint(p1),
                                  every_s=0)
    cancel = threading.Event()
    ckpt.on_save = lambda kind, row: cancel.set()
    bfs.check_packed(p1, chunk=64, cancel=cancel, checkpoint=ckpt)
    assert os.path.exists(ck)

    r = bfs.check_packed(p2, chunk=64, checkpoint=ck)
    assert r["valid?"] is True
    assert "resumed-from-row" not in r
