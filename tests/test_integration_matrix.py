"""Cluster integration matrix — the analogue of the reference's per-suite
deftest grids that drive a real cluster
(cockroachdb/test/jepsen/cockroach_test.clj:17-52 builds a
workload x nemesis deftest matrix; aerospike/disque/... ship similar).

Skipped by default: these tests need the 1-control + 5-node environment
(``docker/up.sh``, or any five SSH-reachable nodes). Opt in with::

    JEPSEN_NODES=n1,n2,n3,n4,n5 python -m pytest \\
        tests/test_integration_matrix.py -q

or, from the repo root with docker available::

    make integration

Each cell runs a short real test through the FULL stack — SSH control
plane, OS provisioning, DB install, workload clients over the wire
protocols, nemesis faults — and asserts the checker verdict.
"""

from __future__ import annotations

import os

import pytest

NODES = [n for n in os.environ.get("JEPSEN_NODES", "").split(",") if n]

pytestmark = pytest.mark.skipif(
    not NODES,
    reason="cluster matrix needs JEPSEN_NODES=n1,...,n5 (see docker/)")


def _run(test_map: dict) -> dict:
    from jepsen_tpu import core

    return core.run(test_map)


def _opts(**kw) -> dict:
    base = {
        "fake": False,
        "nodes": NODES,
        "time-limit": int(os.environ.get("JEPSEN_MATRIX_TIME", "30")),
        "concurrency": 5,
        "username": os.environ.get("JEPSEN_USERNAME", "root"),
    }
    base.update(kw)
    return base


# The matrix: (cell id, suite module, suite opts, nemesis-off) — the
# analogue of cockroach_test.clj:17-52's workload x nemesis grid. etcd
# and zookeeper registers are the canonical cells (etcd.clj is the
# reference's template suite; zookeeper.clj its tutorial target);
# cockroach register+bank, hazelcast lock, rabbitmq queue, and galera
# (mysql-family) bank cover the registry breadth. Each workload runs
# with its suite's nemesis live and replaced by the noop (the generator
# still schedules start/stop ops; with test["nemesis"]=None they no-op
# in the runner).
MATRIX = [
    ("etcd", "etcd", {}, False),
    ("etcd-calm", "etcd", {}, True),
    ("zookeeper", "zookeeper", {}, False),
    ("zookeeper-calm", "zookeeper", {}, True),
    ("cockroach-register", "cockroachdb", {"workload": "register"}, False),
    ("cockroach-register-calm", "cockroachdb",
     {"workload": "register"}, True),
    ("cockroach-bank", "cockroachdb", {"workload": "bank"}, False),
    ("cockroach-bank-calm", "cockroachdb", {"workload": "bank"}, True),
    ("hazelcast-lock", "hazelcast", {"workload": "lock"}, False),
    ("hazelcast-lock-calm", "hazelcast", {"workload": "lock"}, True),
    ("rabbitmq-queue", "rabbitmq", {}, False),
    ("rabbitmq-queue-calm", "rabbitmq", {}, True),
    ("galera-bank", "galera", {}, False),
    ("galera-bank-calm", "galera", {}, True),
]


@pytest.mark.parametrize("cell,suite_name,extra,calm", MATRIX,
                         ids=[m[0] for m in MATRIX])
def test_matrix(cell, suite_name, extra, calm):
    import importlib

    suite = importlib.import_module(f"jepsen_tpu.suites.{suite_name}")
    opts = _opts(**extra)
    t = suite.test(opts)
    if calm:
        t["nemesis"] = None
    result = _run(t)
    analysis = result.get("results") or {}
    assert analysis.get("valid?") is not False, analysis
