"""Cluster integration matrix — the analogue of the reference's per-suite
deftest grids that drive a real cluster
(cockroachdb/test/jepsen/cockroach_test.clj:17-52 builds a
workload x nemesis deftest matrix; aerospike/disque/... ship similar).

Skipped by default: these tests need the 1-control + 5-node environment
(``docker/up.sh``, or any five SSH-reachable nodes). Opt in with::

    JEPSEN_NODES=n1,n2,n3,n4,n5 python -m pytest \\
        tests/test_integration_matrix.py -q

or, from the repo root with docker available::

    make integration

Each cell runs a short real test through the FULL stack — SSH control
plane, OS provisioning, DB install, workload clients over the wire
protocols, nemesis faults — and asserts the checker verdict.
"""

from __future__ import annotations

import os

import pytest

NODES = [n for n in os.environ.get("JEPSEN_NODES", "").split(",") if n]

pytestmark = pytest.mark.skipif(
    not NODES,
    reason="cluster matrix needs JEPSEN_NODES=n1,...,n5 (see docker/)")


def _run(test_map: dict) -> dict:
    from jepsen_tpu import core

    return core.run(test_map)


def _opts(**kw) -> dict:
    base = {
        "fake": False,
        "nodes": NODES,
        "time-limit": int(os.environ.get("JEPSEN_MATRIX_TIME", "30")),
        "concurrency": 5,
        "username": os.environ.get("JEPSEN_USERNAME", "root"),
    }
    base.update(kw)
    return base


# The matrix: (suite module, extra opts) — etcd and zookeeper registers
# are the canonical cells (etcd.clj is the reference's template suite;
# zookeeper.clj its tutorial target), each with the partition nemesis
# live and with it replaced by the noop (the generator still schedules
# start/stop ops; with test["nemesis"]=None they no-op in the runner).
MATRIX = [
    ("etcd", {}),
    ("etcd", {"nemesis-off": True}),
    ("zookeeper", {}),
    ("zookeeper", {"nemesis-off": True}),
]


@pytest.mark.parametrize("suite_name,extra", MATRIX,
                         ids=[f"{s}{'-calm' if e else ''}"
                              for s, e in MATRIX])
def test_register_matrix(suite_name, extra):
    import importlib

    suite = importlib.import_module(f"jepsen_tpu.suites.{suite_name}")
    opts = _opts()
    if extra.get("nemesis-off"):
        opts["nemesis"] = None
    t = suite.test(opts)
    result = _run(t)
    analysis = result.get("results") or {}
    assert analysis.get("valid?") is not False, analysis
