"""Control plane tests: escaping, sudo/cd scoping, the local transport
(real subprocesses — the analogue of the reference's control_test.clj
whoami check over real SSH), clock-tool compilation, and store round-trip.
"""

import getpass
import os
import subprocess

import pytest

from jepsen_tpu import control as c
from jepsen_tpu.control import util as cu

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick


class TestEscape:
    def test_plain(self):
        assert c.escape("ls") == "ls"

    def test_spaces(self):
        assert c.escape("hello world") == "'hello world'"

    def test_quotes(self):
        assert c.escape("it's") == '\'it\'"\'"\'s\''

    def test_empty(self):
        assert c.escape("") == "''"

    def test_sequence_joins(self):
        assert c.escape(["a", "b c"]) == "a 'b c'"

    def test_lit_passthrough(self):
        assert c.escape(c.Lit("a | b")) == "a | b"

    def test_numbers(self):
        assert c.build_cmd("sleep", 5) == "sleep 5"


class TestDummyTransport:
    def test_records_and_cans(self):
        t = c.DummyTransport(results={"whoami": "root"})
        sess = t.connect("n1", {})
        with c.with_session(sess):
            assert c.exec_("whoami") == "root"
            assert c.exec_("other") == ""
        assert t.log == [("n1", "whoami"), ("n1", "other")]

    def test_no_session_raises(self):
        with pytest.raises(c.RemoteError):
            c.exec_("ls")


class TestLocalTransport:
    """Real command execution on localhost — control_test.clj:5-8 runs
    `(c/on "n1" (c/exec :whoami))` over real SSH; the local transport is
    the no-SSH equivalent surface."""

    def session(self):
        return c.LocalTransport().connect("local", {})

    def test_whoami(self):
        with c.with_session(self.session()):
            assert c.exec_("whoami") == getpass.getuser()

    def test_exit_code_raises(self):
        with c.with_session(self.session()):
            with pytest.raises(c.RemoteError) as ei:
                c.exec_("false")
            assert ei.value.exit_code == 1

    def test_may_fail(self):
        with c.with_session(self.session()):
            assert c.exec_("false", may_fail=True) == ""

    def test_cd_scope(self):
        with c.with_session(self.session()):
            with c.cd("/tmp"):
                assert c.exec_("pwd") == "/tmp"
            assert c.exec_("pwd") != "/tmp"

    def test_stdin(self):
        with c.with_session(self.session()):
            out = c.exec_("cat", stdin="hello")
            assert out == "hello"

    def test_escaping_prevents_injection(self):
        with c.with_session(self.session()):
            out = c.exec_("echo", "$(rm -rf /tmp/nope); true")
            assert "$(rm" in out  # not executed, printed verbatim

    def test_upload_download(self, tmp_path):
        src = tmp_path / "src.txt"
        src.write_text("payload")
        with c.with_session(self.session()):
            c.upload(str(src), str(tmp_path / "up.txt"))
            c.download(str(tmp_path / "up.txt"), str(tmp_path / "down.txt"))
        assert (tmp_path / "down.txt").read_text() == "payload"

    def test_control_util_tmpdir_and_exists(self):
        with c.with_session(self.session()):
            d = cu.tmp_dir()
            try:
                assert cu.exists(d)
                assert not cu.exists(d + "/nope")
            finally:
                c.exec_("rm", "-rf", d)

    def test_grepkill_noop_on_no_match(self):
        with c.with_session(self.session()):
            cu.grepkill("definitely-not-a-process-name-xyz")


class TestOnNodes:
    def test_parallel_fanout(self):
        t = c.DummyTransport()
        test = {"nodes": ["n1", "n2", "n3"], "transport": t}
        out = c.on_nodes(test, lambda tst, node: c.exec_("hostname"))
        assert set(out) == {"n1", "n2", "n3"}
        assert len(t.log) == 3


def test_native_clock_tools_compile(tmp_path):
    """The C++ clock fault programs must compile with the node toolchain
    (the clock nemesis compiles them remotely; here: local g++)."""
    native = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native")
    for src, name in (("bump_time.cc", "bump-time"),
                      ("strobe_time.cc", "strobe-time")):
        out = tmp_path / name
        r = subprocess.run(["g++", "-O2", "-Wall", "-o", str(out),
                            os.path.join(native, src)],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        # usage errors exit 2 without touching the clock
        u = subprocess.run([str(out)], capture_output=True, text=True)
        assert u.returncode == 2
        assert "usage" in u.stderr


def test_store_round_trip(tmp_path):
    import datetime

    from jepsen_tpu import store
    from jepsen_tpu.history import invoke_op, ok_op

    test = {"name": "rt", "store-base": str(tmp_path),
            "start-time": datetime.datetime(2026, 7, 29, 12, 0, 0),
            "nodes": ["n1"], "history":
            [invoke_op(0, "read", None).replace(index=0, time=1),
             ok_op(0, "read", 5).replace(index=1, time=2)],
            "results": {"valid?": True},
            "client": object()}  # nonserializable, must be dropped
    store.save_1(test)
    store.save_2(test)
    runs = store.tests("rt", base=tmp_path)
    assert len(runs) == 1
    loaded = next(iter(runs.values()))()
    assert loaded["results"]["valid?"] is True
    assert len(loaded["history"]) == 2
    assert loaded["history"][1].value == 5
    assert "client" not in loaded
    latest = tmp_path / "rt" / "latest"
    assert latest.is_symlink()
