"""Loss-proof bench artifact + probe stall watchdog (round-6
satellites; BENCH_r05 recorded ``parsed: null`` because one external
timeout erased every number, and the shared-chip tunnel has wedged
single dispatches ~25 min).

These tests exercise bench.py's parent-side machinery with scripted
child processes and stubbed probes — no jax, no device — so they run
in the quick tier and in any environment.
"""

import importlib.util
import json
import os
import sys
import textwrap
import time

import pytest

pytestmark = pytest.mark.quick

_BENCH_PATH = os.path.join(os.path.dirname(__file__), os.pardir,
                           "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_under_test",
                                                  _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _child(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return [sys.executable, str(p)]


def test_partitioned_budget_derives_from_time_spent(bench, monkeypatch):
    monkeypatch.setattr(bench, "TOTAL_BUDGET_S", 7000)
    t0 = time.time()
    # Nothing spent yet: the full ceiling fits.
    assert bench._partitioned_budget(t0, 5300) == 5300
    # 3000 s already burned by earlier probes: the budget shrinks so
    # the bench total stays inside the driver's window.
    assert bench._partitioned_budget(t0 - 3000, 5300) == pytest.approx(
        4000, abs=2)
    # Never below the floor, even when the clock is exhausted.
    assert bench._partitioned_budget(t0 - 9000, 5300) == \
        bench.PARTITIONED_MIN_S


def test_probe_child_result_parses(bench, tmp_path):
    argv = _child(tmp_path, "ok.py", """
        import json
        print("HB 1", flush=True)
        print(json.dumps({"verdict": True, "seconds": 0.1}))
    """)
    r, why = bench._run_probe_subprocess("x", timeout=30, argv=argv,
                                         stall_s=20)
    assert why is None
    assert r == {"verdict": True, "seconds": 0.1}


def test_watchdog_kills_stalled_child(bench, tmp_path):
    # A child whose heartbeat VALUE stops advancing is a wedged
    # dispatch: the watchdog must kill it after ~stall_s, not wait out
    # the probe budget (a wedged probe costs its detection window).
    argv = _child(tmp_path, "stall.py", """
        import time
        print("HB 7", flush=True)
        while True:
            time.sleep(0.3)
            print("HB 7", flush=True)   # alive but NOT progressing
    """)
    t0 = time.time()
    r, why = bench._run_probe_subprocess("x", timeout=60, argv=argv,
                                         stall_s=2)
    dt = time.time() - t0
    assert why == "stall"
    assert "stalled" in r["error"]
    assert dt < 30, f"stall detection took {dt:.1f}s, not ~2s"


def test_stall_kill_escalates_and_records(bench, tmp_path,
                                          monkeypatch):
    # A child that ignores SIGTERM (wedged inside the TPU runtime)
    # must be SIGKILLed after the grace window, with the escalation
    # AND the last heartbeat progress recorded in the probe JSON —
    # the old bare kill() could race a wedged teardown and leave the
    # child alive, the event invisible.
    monkeypatch.setattr(bench, "KILL_GRACE_S", 1)
    argv = _child(tmp_path, "unkillable.py", """
        import signal, time
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        print("HB 41", flush=True)
        while True:
            time.sleep(0.2)
            print("HB 41", flush=True)   # alive but NOT progressing
    """)
    r, why = bench._run_probe_subprocess("x", timeout=60, argv=argv,
                                         stall_s=2)
    assert why == "stall"
    k = r["kill"]
    assert k["why"] == "stall"
    assert k["sigkill"] is True, "SIGTERM-immune child needs SIGKILL"
    assert k["last_hb"] == 41
    assert "unkillable" not in k


def test_stall_kill_records_sigterm_sufficient(bench, tmp_path):
    # A stalled child that honors SIGTERM: the record shows no SIGKILL
    # was needed, and the last progress value is preserved.
    argv = _child(tmp_path, "stall2.py", """
        import time
        print("HB 7", flush=True)
        while True:
            time.sleep(0.3)
            print("HB 7", flush=True)
    """)
    r, why = bench._run_probe_subprocess("x", timeout=60, argv=argv,
                                         stall_s=2)
    assert why == "stall"
    assert r["kill"]["sigkill"] is False
    assert r["kill"]["last_hb"] == 7


def test_completed_result_recovered_from_wedged_teardown(bench,
                                                        tmp_path):
    # A child that PRINTS its result and then wedges in teardown:
    # the answer wins over the kill, and the teardown kill is
    # recorded on it instead of an error replacing it.
    argv = _child(tmp_path, "teardown.py", """
        import json, time
        print("HB 1", flush=True)
        print(json.dumps({"verdict": True, "seconds": 0.5}), flush=True)
        while True:
            time.sleep(0.3)   # wedged teardown, HB thread gone
    """)
    r, why = bench._run_probe_subprocess("x", timeout=60, argv=argv,
                                         stall_s=2)
    assert why is None
    assert r["verdict"] is True
    assert r["teardown_kill"]["why"] == "stall"


def test_watchdog_spares_progressing_child(bench, tmp_path):
    # Advancing heartbeat values reset the stall clock: a slow but
    # progressing probe survives a stall_s shorter than its runtime.
    argv = _child(tmp_path, "slowok.py", """
        import json, time
        for i in range(8):
            time.sleep(0.5)
            print(f"HB {i}", flush=True)
        print(json.dumps({"verdict": True}))
    """)
    r, why = bench._run_probe_subprocess("x", timeout=60, argv=argv,
                                         stall_s=2)
    assert why is None
    assert r == {"verdict": True}


def test_stall_retries_once_and_records(bench, tmp_path, monkeypatch):
    # First attempt wedges; the retry runs with the remaining budget
    # and the artifact records both the retry count and the first
    # attempt's error.
    marker = tmp_path / "ran_once"
    argv = _child(tmp_path, "flaky.py", f"""
        import json, os, time
        marker = {str(marker)!r}
        if not os.path.exists(marker):
            open(marker, "w").close()
            print("HB 0", flush=True)
            while True:               # wedge forever on the first run
                time.sleep(0.3)
                print("HB 0", flush=True)
        print(json.dumps({{"verdict": True, "attempt": 2}}))
    """)
    real = bench._run_probe_subprocess

    def fake(key, timeout, env_extra=None, stall_s=bench.STALL_S):
        return real(key, timeout, env_extra=env_extra, stall_s=2,
                    argv=argv)

    monkeypatch.setattr(bench, "_run_probe_subprocess", fake)
    r = bench._run_probe("x", timeout=60)
    assert r["verdict"] is True and r["attempt"] == 2
    assert r["stall_retries"] == 1
    assert "stalled" in r["first_attempt"]["error"]


def test_txn_probe_in_order_and_registry(bench):
    # The txn probe contract (ISSUE 9): registered, ordered BEFORE the
    # long/dangerous partitioned probe so a txn fault (or a config-5
    # fault) can never shadow the other's number.
    keys = [k for k, _t in bench.PROBE_ORDER]
    assert "txn_c30" in keys
    assert keys.index("txn_c30") < keys.index("partitioned_c30")
    assert "txn_c30" in bench.PROBES


def test_stream_probe_in_order_and_registry(bench):
    # The stream probe contract (ISSUE 11): registered, fault-isolated
    # like every probe, and ordered BEFORE the long/dangerous
    # partitioned probe so a stream fault can never shadow the
    # headline.
    keys = [k for k, _t in bench.PROBE_ORDER]
    assert "stream_c30" in keys
    assert keys.index("stream_c30") < keys.index("partitioned_c30")
    assert "stream_c30" in bench.PROBES


def test_mesh_probe_in_order_and_registry(bench):
    # The mesh probe contract (ISSUE 18): registered, fault-isolated
    # in its own child, and ordered BEFORE the long/dangerous
    # partitioned probe so a mesh fault can never cost the proven
    # single-chip config-5 number.
    keys = [k for k, _t in bench.PROBE_ORDER]
    assert "mesh_c30" in keys
    assert keys.index("mesh_c30") < keys.index("partitioned_c30")
    assert "mesh_c30" in bench.PROBES


def test_txn_probe_stats_pass_through(bench, monkeypatch, capsys):
    # edges/s, verdict, anomaly counts, and the device tier stats must
    # reach detail verbatim and be re-emitted the moment the probe
    # completes (loss-proof: an external kill during partitioned keeps
    # the txn numbers).
    monkeypatch.setattr(bench, "PROBE_ORDER",
                        (("txn_c30", 60), ("partitioned_c30", 100)))
    txn_result = {
        "n_ops": 99984, "edges": 180876, "edges_per_sec": 61234.5,
        "healthy_verdict": True, "seeded_verdict": False,
        "anomaly_types": ["G-single", "G2-item"],
        "anomaly_counts": {"G2-item": 2, "G-single": 1},
        "witness_parity": True, "verdict": True,
        "device_stats": {"tiers": {"full": {"core": 4}}}}

    def fake_probe(key, timeout, env_extra=None, stall_s=None):
        if key == "txn_c30":
            return dict(txn_result)
        return {"verdict": True, "probe": key}

    monkeypatch.setattr(bench, "_run_probe", fake_probe)
    out = {"metric": "m", "value": 1, "detail": {}}
    bench._wide_probes(out["detail"], out, time.time())
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.splitlines() if ln.strip()]
    assert "txn_c30" in lines[0]["detail"]
    got = out["detail"]["txn_c30"]
    assert got["edges_per_sec"] == 61234.5
    assert got["anomaly_counts"] == {"G2-item": 2, "G-single": 1}
    assert got["witness_parity"] is True
    assert got["device_stats"]["tiers"]["full"]["core"] == 4


def test_txn_probe_fault_cannot_shadow_headline(bench, monkeypatch):
    # FAULT ISOLATION: a txn probe error must recover the worker and
    # still run the remaining probes — the partitioned headline (and
    # every later number) survives a txn fault, and vice versa.
    monkeypatch.setattr(bench, "PROBE_ORDER",
                        (("txn_c30", 60), ("partitioned_c30", 100)))
    recoveries = []

    def fake_probe(key, timeout, env_extra=None, stall_s=None):
        if key == "txn_c30":
            return {"error": "probe exited rc=1: kernel fault"}
        return {"verdict": True, "probe": key}

    monkeypatch.setattr(bench, "_run_probe", fake_probe)
    monkeypatch.setattr(bench, "_verify_recovery",
                        lambda: recoveries.append(1) or True)
    detail = {}
    bench._wide_probes(detail, {"metric": "m", "value": 1,
                                "detail": detail}, time.time())
    assert "error" in detail["txn_c30"]
    assert detail["txn_c30"]["worker_recovered"] is True
    assert recoveries == [1]
    assert detail["partitioned_c30"]["verdict"] is True


def test_service_probe_in_order_and_registry(bench):
    # The checker-service probe is a first-class artifact citizen:
    # registered, and ordered BEFORE the long/dangerous partitioned
    # probe (safe-first) so a config-5 fault can never shadow the
    # service throughput number.
    keys = [k for k, _t in bench.PROBE_ORDER]
    assert "service_c30" in keys
    assert keys.index("service_c30") < keys.index("partitioned_c30")
    assert "service_c30" in bench.PROBES


def test_service_probe_result_passes_through_with_kill_record(
        bench, monkeypatch, capsys):
    # The artifact contract for service_c30: the parent re-emits after
    # the probe (loss-proof), and the probe's throughput/latency keys
    # and any teardown kill record reach detail verbatim — the parent
    # must never strip or reshape them.
    monkeypatch.setattr(bench, "PROBE_ORDER",
                        (("service_c30", 60),
                         ("partitioned_c30", 100)))
    service_result = {
        "n_histories": 120, "histories_per_sec": 41.7,
        "latency_p50_s": 0.12, "latency_p99_s": 1.9,
        "verdict": True,
        "service_stats": {"avg_occupancy": 3.9, "batches": 27},
        "teardown_kill": {"why": "stall", "sigkill": False,
                          "last_hb": 9}}

    def fake_probe(key, timeout, env_extra=None, stall_s=None):
        if key == "service_c30":
            return dict(service_result)
        return {"verdict": True, "probe": key}

    monkeypatch.setattr(bench, "_run_probe", fake_probe)
    out = {"metric": "m", "value": 1, "detail": {}}
    bench._wide_probes(out["detail"], out, time.time())
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.splitlines() if ln.strip()]
    # Re-emitted the moment the service probe completed: the FIRST
    # line already carries it (an external kill before the partitioned
    # probe keeps the service numbers).
    assert "service_c30" in lines[0]["detail"]
    got = out["detail"]["service_c30"]
    assert got["histories_per_sec"] == 41.7
    assert got["latency_p50_s"] == 0.12 and got["latency_p99_s"] == 1.9
    assert got["service_stats"]["avg_occupancy"] == 3.9
    assert got["teardown_kill"]["why"] == "stall"


def test_wide_probes_reemit_after_every_probe(bench, monkeypatch,
                                              capsys):
    # The loss-proof contract: the full result line is re-printed after
    # EVERY completed probe, so killing the bench at any point leaves
    # the probes completed so far on stdout's last JSON line.
    monkeypatch.setattr(bench, "PROBE_ORDER",
                        (("alpha", 10), ("beta", 10),
                         ("partitioned_c30", 100)))
    monkeypatch.setattr(
        bench, "_run_probe",
        lambda key, timeout, env_extra=None, stall_s=None:
        {"verdict": True, "probe": key,
         "sched": {"verdict": True}})
    out = {"metric": "m", "value": 1, "detail": {}}
    bench._wide_probes(out["detail"], out, time.time())
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.splitlines() if ln.strip()]
    # One emission per probe, plus one for the wave smoke pre-probe.
    assert len(lines) == 4
    # Each successive line strictly grows the completed-probe set, and
    # the LAST line carries all of them (what an external kill leaves).
    assert set(lines[0]["detail"]) == {"alpha"}
    assert set(lines[1]["detail"]) == {"alpha", "beta"}
    assert set(lines[2]["detail"]) == {"alpha", "beta", "wave_smoke"}
    assert set(lines[3]["detail"]) == {"alpha", "beta", "wave_smoke",
                                       "partitioned_c30"}
    # The partitioned probe ran the episode-scheduler rung (the
    # kill-the-tunnel tentpole: scheduler + sticky caps + K=4
    # fallback at the conservative queue depth) first and recorded
    # the gating evidence + its derived budget.
    part = lines[3]["detail"]["partitioned_c30"]
    assert part["sync_chunks"] == 2 and part["fused_closure"] == 1
    assert part["host_sticky"] == 1 and part["host_rows_k"] == 4
    assert part["host_sched"] == 1
    # Experimental (non-final) rungs get the remaining clock capped by
    # the ceiling, NOT the PARTITIONED_MIN_S floor (the floor is
    # reserved for the final proven rung).
    assert 0 < part["budget_seconds"] <= 100


def test_partitioned_attempt_ladder_preserves_headline(bench,
                                                       monkeypatch):
    # Every rung failing must still leave detail["partitioned_c30"]
    # populated (no KeyError for artifact consumers), archive each
    # failed rung under its suffixed key, and END the ladder on the
    # proven round-5 shape (SYNC_CHUNKS=2, FUSED_CLOSURE=0) so a fault
    # in the fused program alone cannot cost the headline number.
    monkeypatch.setattr(bench, "PROBE_ORDER", (("partitioned_c30", 100),))
    monkeypatch.setattr(bench, "_verify_recovery", lambda: True)
    seen = []

    def fake_probe(key, timeout, env_extra=None, stall_s=None):
        seen.append(dict(env_extra))
        return {"error": "boom"}

    monkeypatch.setattr(bench, "_run_probe", fake_probe)
    detail: dict = {}
    out = {"detail": detail}
    bench._wide_probes(detail, out, time.time())
    # The failed smoke pre-probe (first call, SYNC 2 / K 4) gates the
    # sched + wave rungs off (probe-small-first): only the K=1 rungs
    # run, and the ladder ends on the round-5 per-pass shape proven
    # on this chip.
    assert [e["JEPSEN_TPU_HOST_ROWS_K"] for e in seen] == \
        ["4", "1", "1", "1"]
    assert [e["JEPSEN_TPU_FUSED_CLOSURE"] for e in seen] == \
        ["1", "1", "1", "0"]
    assert [e["JEPSEN_TPU_HOST_STICKY"] for e in seen] == \
        ["1", "1", "0", "0"]
    assert "error" in detail["wave_smoke"]
    for tag in ("sched", "wave8", "wave"):
        assert "probe-small-first" in \
            detail[f"partitioned_c30_{tag}"]["error"]
    for tag in ("sticky", "r6", "unfused"):
        assert detail[f"partitioned_c30_{tag}"]["error"] == "boom"
    final = detail["partitioned_c30"]
    assert final["fused_closure"] == 0 and final["sync_chunks"] == 2
    assert final["host_sticky"] == 0 and final["host_rows_k"] == 1
    assert final["host_sched"] == 0

    # A passing smoke admits the wave rungs; a success mid-ladder
    # stops escalation: the wave rung at the conservative queue depth
    # winning means the later fallback rungs never run.
    seen.clear()
    detail.clear()

    def flaky_probe(key, timeout, env_extra=None, stall_s=None):
        seen.append(dict(env_extra))
        if env_extra["JEPSEN_TPU_SYNC_CHUNKS"] == "8":
            return {"error": "boom"}
        return {"verdict": True}

    monkeypatch.setattr(bench, "_run_probe", flaky_probe)
    bench._wide_probes(detail, out, time.time())
    # smoke (passes, but carries no clean sched leg so the sched rung
    # is skipped), wave8 (fails), wave (wins).
    assert len(seen) == 3
    assert [e["JEPSEN_TPU_SYNC_CHUNKS"] for e in seen] == \
        ["2", "8", "2"]
    assert detail["partitioned_c30"]["verdict"] is True
    assert detail["partitioned_c30"]["fused_closure"] == 1
    assert detail["partitioned_c30"]["host_rows_k"] == 4
    assert "partitioned_c30_sched" in detail
    assert "partitioned_c30_wave8" in detail
    assert "partitioned_c30_sticky" not in detail
    assert "partitioned_c30_unfused" not in detail


def test_sched_rung_wins_when_both_smoke_legs_pass(bench, monkeypatch):
    # A clean two-leg smoke admits the episode-scheduler rung, which
    # runs FIRST (most experimental) and — succeeding — ends the
    # ladder with the scheduler configuration in the headline slot.
    monkeypatch.setattr(bench, "PROBE_ORDER", (("partitioned_c30", 100),))
    monkeypatch.setattr(bench, "_verify_recovery", lambda: True)
    seen = []

    def fake_probe(key, timeout, env_extra=None, stall_s=None):
        seen.append((key, dict(env_extra or {})))
        if key == "wave_smoke":
            return {"verdict": True, "sched": {"verdict": True}}
        return {"verdict": True}

    monkeypatch.setattr(bench, "_run_probe", fake_probe)
    detail: dict = {}
    bench._wide_probes(detail, {"detail": detail}, time.time())
    assert [k for k, _ in seen] == ["wave_smoke", "partitioned_c30"]
    final = detail["partitioned_c30"]
    assert final["host_sched"] == 1 and final["host_rows_k"] == 4
    assert final["sync_chunks"] == 2
    # The scheduler rung's env was forced explicitly, fused psort off
    # (inert on the crash-dom band; the artifact records the config).
    env = seen[1][1]
    assert env["JEPSEN_TPU_HOST_SCHED"] == "1"
    assert env["JEPSEN_TPU_PSORT_FUSED"] == "0"
    assert "partitioned_c30_wave8" not in detail


def test_wave_rungs_skip_honestly_when_smoke_has_no_budget(
        bench, monkeypatch):
    # Budget window where the rungs could still run but the smoke
    # can't fit before them: the smoke is skipped and the wave rungs
    # must record a NO-BUDGET reason, never a smoke verdict that was
    # never produced (false gating evidence in the artifact).
    monkeypatch.setattr(bench, "PROBE_ORDER", (("partitioned_c30", 100),))
    monkeypatch.setattr(bench, "_verify_recovery", lambda: True)
    monkeypatch.setattr(
        bench, "TOTAL_BUDGET_S",
        2 * bench.PARTITIONED_MIN_S + bench.WAVE_SMOKE_BUDGET_S / 2)
    seen = []

    def fake_probe(key, timeout, env_extra=None, stall_s=None):
        seen.append(key)
        return {"verdict": True}

    monkeypatch.setattr(bench, "_run_probe", fake_probe)
    detail: dict = {}
    bench._wide_probes(detail, {"detail": detail}, time.time())
    assert "wave_smoke" not in seen and "wave_smoke" not in detail
    for tag in ("sched", "wave8", "wave"):
        err = detail[f"partitioned_c30_{tag}"]["error"]
        assert "no budget to smoke-probe" in err
        assert "failed" not in err
    # The K=1 rung still ran and won.
    assert detail["partitioned_c30"]["verdict"] is True
    assert detail["partitioned_c30"]["host_rows_k"] == 1


def test_ladder_abandoned_when_smoke_kills_worker_for_good(
        bench, monkeypatch):
    # A smoke fault with NO worker recovery must abandon the ladder
    # (dispatching rungs at a dead worker burns their stall windows)
    # while still populating detail["partitioned_c30"] for artifact
    # consumers.
    monkeypatch.setattr(bench, "PROBE_ORDER", (("partitioned_c30", 100),))
    monkeypatch.setattr(bench, "_verify_recovery", lambda: False)
    seen = []

    def fake_probe(key, timeout, env_extra=None, stall_s=None):
        seen.append(key)
        return {"error": "kernel fault"}

    monkeypatch.setattr(bench, "_run_probe", fake_probe)
    detail: dict = {}
    bench._wide_probes(detail, {"detail": detail}, time.time())
    assert seen == ["wave_smoke"], "no rung may run on a dead worker"
    assert detail["wave_smoke"]["worker_recovered"] is False
    assert "abandoned" in detail["partitioned_c30"]["error"]


def test_partitioned_ladder_reserves_floor_for_fallback(bench,
                                                        monkeypatch):
    # With the wall clock nearly exhausted, the experimental rungs are
    # SKIPPED (recorded as such) and the whole remaining floor goes to
    # the proven round-5 fallback rung — the budget floor is spent
    # once, not once per rung.
    monkeypatch.setattr(bench, "PROBE_ORDER", (("partitioned_c30", 100),))
    monkeypatch.setattr(bench, "_verify_recovery", lambda: True)
    monkeypatch.setattr(bench, "TOTAL_BUDGET_S",
                        bench.PARTITIONED_MIN_S * 1.5)
    seen = []

    def fake_probe(key, timeout, env_extra=None, stall_s=None):
        seen.append(dict(env_extra))
        return {"verdict": True}

    monkeypatch.setattr(bench, "_run_probe", fake_probe)
    detail: dict = {}
    bench._wide_probes(detail, {"detail": detail}, time.time())
    assert len(seen) == 1
    assert seen[0]["JEPSEN_TPU_FUSED_CLOSURE"] == "0"
    assert seen[0]["JEPSEN_TPU_HOST_ROWS_K"] == "1"
    # No clock for experiments: even the wave smoke pre-probe is
    # skipped, and the skips record the BUDGET reason, not a smoke
    # verdict that never existed.
    assert "wave_smoke" not in detail
    for tag in ("sched", "wave8", "wave", "sticky", "r6"):
        assert "budget" in detail[f"partitioned_c30_{tag}"]["error"]
    assert detail["partitioned_c30"]["verdict"] is True
    assert detail["partitioned_c30"]["budget_seconds"] == \
        bench.PARTITIONED_MIN_S
