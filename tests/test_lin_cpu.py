"""CPU linearizability checker tests: hand-built histories + randomized
parity against an independent brute-force search (testing the testers)."""

import pytest

from jepsen_tpu import models as m
from jepsen_tpu.history import History, invoke_op, ok_op, info_op, fail_op
from jepsen_tpu.lin import analysis, prepare
from jepsen_tpu.lin import brute, cpu, synth


def H(*ops):
    return History.of(*ops)


def cpu_check(model, history, **kw):
    return cpu.check_packed(prepare.prepare(model, history), **kw)


class TestRegisterHistories:
    def test_empty(self):
        assert cpu_check(m.cas_register(), H())["valid?"]

    def test_sequential_ok(self):
        h = H(invoke_op(0, "write", 1), ok_op(0, "write", 1),
              invoke_op(0, "read", None), ok_op(0, "read", 1))
        assert cpu_check(m.cas_register(), h)["valid?"]

    def test_stale_read(self):
        h = H(invoke_op(0, "write", 1), ok_op(0, "write", 1),
              invoke_op(0, "read", None), ok_op(0, "read", 0))
        r = cpu_check(m.cas_register(), h)
        assert r["valid?"] is False
        assert r["op"]["f"] == "read" and r["op"]["value"] == 0

    def test_concurrent_read_either_value(self):
        # read overlaps the write: may see old or new
        for seen in (None, 7):
            h = H(invoke_op(0, "write", 7),
                  invoke_op(1, "read", None),
                  ok_op(1, "read", seen),
                  ok_op(0, "write", 7))
            assert cpu_check(m.cas_register(), h)["valid?"], seen

    def test_cas_chain(self):
        h = H(invoke_op(0, "write", 1), ok_op(0, "write", 1),
              invoke_op(0, "cas", [1, 2]), ok_op(0, "cas", [1, 2]),
              invoke_op(0, "read", None), ok_op(0, "read", 2))
        assert cpu_check(m.cas_register(), h)["valid?"]

    def test_impossible_cas(self):
        h = H(invoke_op(0, "write", 1), ok_op(0, "write", 1),
              invoke_op(0, "cas", [5, 2]), ok_op(0, "cas", [5, 2]))
        assert cpu_check(m.cas_register(), h)["valid?"] is False

    def test_crashed_write_observed(self):
        # write crashes (indeterminate) but its value is later read: legal
        h = H(invoke_op(0, "write", 3), info_op(0, "write", 3),
              invoke_op(1, "read", None), ok_op(1, "read", 3))
        assert cpu_check(m.cas_register(), h)["valid?"]

    def test_crashed_write_unobserved(self):
        # write crashes and is never seen: also legal (never linearized)
        h = H(invoke_op(0, "write", 3), info_op(0, "write", 3),
              invoke_op(1, "read", None), ok_op(1, "read", None))
        assert cpu_check(m.cas_register(), h)["valid?"]

    def test_failed_write_observed_is_invalid(self):
        # a :fail op definitely did not happen; reading its value is a bug
        h = H(invoke_op(0, "write", 3), fail_op(0, "write", 3),
              invoke_op(1, "read", None), ok_op(1, "read", 3))
        assert cpu_check(m.cas_register(), h)["valid?"] is False

    def test_crashed_op_stays_concurrent_forever(self):
        # crashed write may linearize arbitrarily late — even after
        # intervening completed ops (core.clj:185-217 semantics)
        h = H(invoke_op(0, "write", 3), info_op(0, "write", 3),
              invoke_op(1, "write", 5), ok_op(1, "write", 5),
              invoke_op(2, "read", None), ok_op(2, "read", 5),
              invoke_op(3, "read", None), ok_op(3, "read", 3))
        assert cpu_check(m.cas_register(), h)["valid?"]

    def test_witness(self):
        h = H(invoke_op(0, "write", 1),
              invoke_op(1, "read", None),
              ok_op(1, "read", 1),
              ok_op(0, "write", 1))
        r = cpu_check(m.cas_register(), h, witness=True)
        assert r["valid?"]
        fs = [(o["f"], o["value"]) for o in r["witness"]]
        assert fs == [("write", 1), ("read", 1)]


class TestMutexHistories:
    def test_ok(self):
        h = H(invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
              invoke_op(0, "release", None), ok_op(0, "release", None),
              invoke_op(1, "acquire", None), ok_op(1, "acquire", None))
        assert cpu_check(m.mutex(), h)["valid?"]

    def test_double_acquire(self):
        h = H(invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
              invoke_op(1, "acquire", None), ok_op(1, "acquire", None))
        assert cpu_check(m.mutex(), h)["valid?"] is False

    def test_concurrent_handoff(self):
        h = H(invoke_op(0, "release", None),
              invoke_op(1, "acquire", None),
              ok_op(1, "acquire", None),
              ok_op(0, "release", None))
        assert cpu_check(m.Mutex(True), h)["valid?"]


class TestGenericModels:
    def test_set_model_packed_path(self):
        h = H(invoke_op(0, "add", 1), ok_op(0, "add", 1),
              invoke_op(1, "read", [1]), ok_op(1, "read", [1]))
        p = prepare.prepare(m.set_model(), h)
        assert p.kernel is not None and p.kernel.name == "set"
        assert cpu.check_packed(p)["valid?"]

    def test_noop_model_generic_path(self):
        h = H(invoke_op(0, "add", 1), ok_op(0, "add", 1))
        p = prepare.prepare(m.noop, h)
        assert p.kernel is None
        assert cpu.check_packed(p)["valid?"]

    def test_fifo_generic(self):
        h = H(invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
              invoke_op(0, "enqueue", 2), ok_op(0, "enqueue", 2),
              invoke_op(1, "dequeue", None), ok_op(1, "dequeue", 2))
        assert cpu.check_packed(
            prepare.prepare(m.fifo_queue(), h))["valid?"] is False


class TestAnalysisFrontend:
    def test_cpu_algorithm(self):
        h = H(invoke_op(0, "write", 1), ok_op(0, "write", 1))
        r = analysis(m.cas_register(), h, algorithm="cpu")
        assert r["valid?"] and r["analyzer"] == "cpu-jit"


# ---------------------------------------------------------------------------
# Randomized parity: cpu JIT search vs independent brute force.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(40))
def test_register_parity_valid(seed):
    h = synth.generate_register_history(
        8, concurrency=3, seed=seed, value_range=3, crash_prob=0.2)
    expect = brute.check(m.cas_register(), h)
    got = cpu_check(m.cas_register(), h)["valid?"]
    assert got == expect
    assert expect is True  # valid by construction


@pytest.mark.parametrize("seed", range(40))
def test_register_parity_corrupted(seed):
    h = synth.generate_register_history(
        8, concurrency=3, seed=seed, value_range=3, crash_prob=0.1)
    h = synth.corrupt_history(h, seed=seed)
    expect = brute.check(m.cas_register(), h)
    got = cpu_check(m.cas_register(), h)["valid?"]
    assert got == expect


@pytest.mark.parametrize("seed", range(30))
def test_mutex_parity(seed):
    h = synth.generate_mutex_history(8, concurrency=3, seed=seed,
                                     crash_prob=0.2)
    expect = brute.check(m.mutex(), h)
    got = cpu_check(m.mutex(), h)["valid?"]
    assert got == expect
    assert expect is True


@pytest.mark.parametrize("seed", range(20))
def test_random_garbage_histories(seed):
    """Fully random op soup — exercises invalid shapes the simulator never
    produces."""
    import random

    rng = random.Random(seed + 999)
    h = []
    procs = {}
    for _ in range(10):
        proc = rng.randrange(3)
        if proc not in procs:
            f = rng.choice(["read", "write", "cas"])
            v = {"read": None, "write": rng.randrange(2),
                 "cas": [rng.randrange(2), rng.randrange(2)]}[f]
            procs[proc] = (f, v)
            h.append(invoke_op(proc, f, v))
        else:
            f, v = procs.pop(proc)
            typ = rng.choice(["ok", "ok", "fail", "info"])
            if f == "read" and typ == "ok":
                v = rng.choice([None, 0, 1])
            h.append({"type": typ, "f": f, "value": v, "process": proc})
    h = History.of(*h)
    expect = brute.check(m.cas_register(), h)
    got = cpu_check(m.cas_register(), h)["valid?"]
    assert got == expect
