"""Tests for the DB suites layer (SURVEY §2.3-2.8).

Three tiers, mirroring the reference's no-cluster affordances:

1. every suite's test map constructs (and carries the right components);
2. representative suites run end-to-end through the real runner on their
   in-memory fakes (the atom-db pattern of core_test.clj) and come back
   valid;
3. every injected-bug mode is caught by its checker — the suite-level
   analogue of checker_test.clj's pathological histories.
"""

from __future__ import annotations

import random

import pytest

from jepsen_tpu import adya, core
from jepsen_tpu import suites
from jepsen_tpu.suites import common, workloads

# Quick tier: no XLA compiles (make test-quick / pytest -m quick).
pytestmark = pytest.mark.quick


def run_fake(test_map: dict) -> dict:
    test_map["name"] = None  # no store writes from unit tests
    result = core.run(test_map)
    return result.get("results", {})


def wl_result(res: dict) -> dict:
    return res.get("workload", res)


# --- tier 1: every suite constructs -----------------------------------------

@pytest.mark.parametrize("name", sorted(suites.SUITES))
def test_suite_constructs(name):
    mod = suites.load(name)
    t = mod.test({"fake": True, "time-limit": 1})
    assert t["name"]
    assert t["transport"] == "dummy"
    assert t["generator"] is not None
    assert t["checker"] is not None
    assert callable(getattr(mod, "main"))


def test_unknown_suite():
    with pytest.raises(KeyError):
        suites.load("nope")


# --- tier 2: fake runs come back valid --------------------------------------

# `compiles`: the end-to-end fake runs hand real histories to the
# checker stack, which compiles a few tiny cached XLA programs on a
# cold cache — exempt from the conftest quick no-compile enforcement.
@pytest.mark.compiles
@pytest.mark.parametrize("name,opts", [
    ("etcd", {}),
    ("consul", {}),
    ("raftis", {}),
    ("disque", {}),
    ("hazelcast", {"workload": "lock"}),
    ("hazelcast", {"workload": "queue"}),
    ("galera", {"workload": "bank"}),
    ("crate", {"workload": "lost-updates"}),
    ("cockroachdb", {"workload": "monotonic"}),
    ("cockroachdb", {"workload": "sequential"}),
    ("cockroachdb", {"workload": "comments"}),
    ("cockroachdb", {"workload": "g2"}),
])
def test_suite_fake_run_valid(name, opts):
    random.seed(7)
    mod = suites.load(name)
    t = mod.test({"fake": True, "time-limit": 2, **opts})
    res = run_fake(t)
    assert res.get("valid?") is True, res


# --- tier 3: checkers catch injected bugs -----------------------------------

def run_workload(wl: dict, time_limit: float = 3,
                 concurrency: int = 5) -> dict:
    random.seed(11)
    t = common.suite_test("faulty", {"time-limit": time_limit,
                                     "concurrency": concurrency,
                                     "fake": True},
                          workload=wl)
    t["name"] = None
    return wl_result(run_fake(t))


FAULTY_CASES = [
    ("set lost-add",
     lambda: workloads.set_workload(n=60, stagger=0.001,
                                    faulty="lost-add")),
    ("queue lost-enqueue",
     lambda: workloads.queue_workload(n=60, stagger=0.001,
                                      faulty="lost-enqueue")),
    ("bank non-atomic",
     lambda: workloads.bank_workload(n=300, stagger=0.001,
                                     faulty="non-atomic")),
    ("lock double-grant",
     lambda: workloads.lock_workload(n=60, faulty="double-grant")),
    ("ids duplicate",
     lambda: workloads.ids_workload(n=60, stagger=0.001,
                                    faulty="duplicate")),
    ("dirty-read",
     lambda: workloads.dirty_read_workload(n=200, stagger=0.001,
                                           faulty="dirty-read")),
    ("monotonic ts-skew",
     lambda: workloads.monotonic_workload(n=60, stagger=0.001,
                                          faulty="ts-skew")),
    ("sequential skip",
     lambda: workloads.sequential_workload(n=100, stagger=0.001,
                                           faulty="skip")),
    ("comments stale",
     lambda: workloads.comments_workload(n=200, stagger=0.001,
                                         faulty="stale")),
]


@pytest.mark.parametrize("label,factory", FAULTY_CASES,
                         ids=[c[0] for c in FAULTY_CASES])
def test_checker_catches_injected_bug(label, factory):
    res = run_workload(factory())
    assert res.get("valid?") is False, (label, res)


def test_g2_checker_catches_double_insert():
    random.seed(3)
    t = common.suite_test("g2-faulty",
                          {"time-limit": 2, "concurrency": 4,
                           "fake": True},
                          workload=adya.workload(faulty="g2"))
    t["name"] = None
    res = run_fake(t)
    assert res.get("workload", res).get("valid?") is False, res


def test_crate_lost_updates_checker():
    from jepsen_tpu.suites import crate

    res = run_workload(crate.lost_updates_workload(n=60,
                                                   faulty="lost-update"))
    assert res.get("valid?") is False, res


# --- chronos: targets, matching, end-to-end ---------------------------------

class TestChronos:
    def test_job_targets_truncated_by_read_time(self):
        from jepsen_tpu.suites import chronos

        job = {"start": 0.0, "interval": 10, "count": 5,
               "epsilon": 2, "duration": 1}
        targets = chronos.job_targets(25.0, job)
        # finish = 25-2-1 = 22: targets at 0, 10, 20 began before it
        assert [t[0] for t in targets] == [0.0, 10.0, 20.0]
        assert targets[0][1] == 2 + chronos.EPSILON_FORGIVENESS

    def test_match_targets_perfect(self):
        from jepsen_tpu.suites import chronos

        targets = [(0, 5), (10, 15), (20, 25)]
        assert chronos.match_targets(targets, [1.0, 11.0, 21.0])

    def test_match_targets_needs_distinct_runs(self):
        from jepsen_tpu.suites import chronos

        # One run can't satisfy two targets even if windows overlap.
        targets = [(0, 10), (5, 15)]
        assert chronos.match_targets(targets, [7.0]) is None
        assert chronos.match_targets(targets, [7.0, 8.0]) is not None

    def test_match_targets_augmenting_path(self):
        from jepsen_tpu.suites import chronos

        # Greedy would bind run 5 to target (0,10) and fail (0,6);
        # matching must reassign.
        targets = [(0, 10), (0, 6)]
        assert chronos.match_targets(targets, [5.0, 9.0]) is not None

    def test_job_solution_invalid_on_missed_run(self):
        from jepsen_tpu.suites import chronos

        job = {"start": 0.0, "interval": 10, "count": 2,
               "epsilon": 1, "duration": 0}
        sol = chronos.job_solution(30.0, job, [0.5])  # missed t=10
        assert sol["valid?"] is False

    def test_chronos_db_setup_over_dummy_transport(self):
        """ChronosDB's real-cluster bring-up sequences ZK -> Mesos ->
        Chronos (mesosphere.clj + chronos.clj db layers), verified by
        the commands it issues over the dummy transport."""
        from jepsen_tpu import control as c
        from jepsen_tpu.suites import chronos

        nodes = ["n1", "n2", "n3", "n4", "n5"]
        test = {"nodes": nodes}
        db = chronos.ChronosDB()
        t = c.DummyTransport()
        for node, master in (("n1", True), ("n5", False)):
            t.log.clear()
            with c.with_session(t.connect(node, {})):
                db.setup(test, node)
            cmds = " ;; ".join(cmd for _, cmd in t.log)
            assert "zookeeper" in cmds              # ZK layer first
            assert "mesosphere" in cmds             # repo added
            assert "/etc/mesos/zk" in cmds          # zk URI configured
            assert "/etc/mesos-master/quorum" in cmds
            daemon = "mesos-master" if master else "mesos-slave"
            assert daemon in cmds, (node, cmds)
            assert "schedule_horizon" in cmds       # chronos config
            assert "chronos start" in cmds
        # teardown stops everything and clears state
        t.log.clear()
        with c.with_session(t.connect("n1", {})):
            db.teardown(test, "n1")
        cmds = " ;; ".join(cmd for _, cmd in t.log)
        assert "chronos stop" in cmds
        assert "mesos-master" in cmds
        lf = db.log_files(test, "n1")
        assert any("zookeeper" in f for f in lf)
        assert any("mesos" in f for f in lf)

    def test_chronos_test_map_has_db_layer(self):
        from jepsen_tpu.suites import chronos

        t = chronos.test({"fake": False})
        assert isinstance(t["db"], chronos.ChronosDB)
        t_fake = chronos.test({"fake": True})
        assert t_fake["transport"] == "dummy"

    def test_fake_scheduler_end_to_end(self):
        import time

        from jepsen_tpu.suites import chronos

        random.seed(5)
        sched = chronos.FakeScheduler()
        now = time.time()
        sched.add({"name": "j1", "start": now + 0.1, "interval": 0.5,
                   "count": 3, "epsilon": 1, "duration": 0})
        time.sleep(2.5)
        read = sched.read()
        sol = chronos.job_solution(read["time"],
                                   {"name": "j1", "start": now + 0.1,
                                    "interval": 0.5, "count": 3,
                                    "epsilon": 1, "duration": 0},
                                   read["runs"]["j1"])
        assert sol["valid?"] is True, sol

    def test_dropped_runs_fail(self):
        import time

        from jepsen_tpu.suites import chronos

        random.seed(5)
        sched = chronos.FakeScheduler(drop_prob=1.0)
        now = time.time()
        job = {"name": "j1", "start": now + 0.05, "interval": 0.3,
               "count": 2, "epsilon": 0.1, "duration": 0}
        sched.add(job)
        time.sleep(1.2)
        read = sched.read()
        sol = chronos.job_solution(read["time"], job,
                                   read["runs"].get("j1", []))
        assert sol["valid?"] is False


# --- wire protocol clients ---------------------------------------------------

class TestResp:
    def test_roundtrip_against_fake_server(self):
        import socket
        import threading

        from jepsen_tpu.suites.resp import RespClient

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]

        def serve():
            conn, _ = srv.accept()
            data = b""
            while b"\r\n" not in data:
                data += conn.recv(4096)
            # reply: simple string, then int, bulk, array, error
            conn.sendall(b"+OK\r\n")
            conn.recv(4096)
            conn.sendall(b":42\r\n")
            conn.recv(4096)
            conn.sendall(b"$5\r\nhello\r\n")
            conn.recv(4096)
            conn.sendall(b"*2\r\n$1\r\na\r\n$-1\r\n")
            conn.recv(4096)
            conn.sendall(b"-ERR boom\r\n")
            conn.close()

        th = threading.Thread(target=serve, daemon=True)
        th.start()
        c = RespClient("127.0.0.1", port)
        assert c.call("PING") == "OK"
        assert c.call("X") == 42
        assert c.call("X") == "hello"
        assert c.call("X") == ["a", None]
        from jepsen_tpu.suites.resp import RespError

        with pytest.raises(RespError):
            c.call("X")
        c.close()
        srv.close()


class TestPgWire:
    def test_error_fields_and_retryable(self):
        from jepsen_tpu.suites.pgwire import PgError

        e = PgError({"C": "40001", "M": "restart transaction"})
        assert e.retryable
        assert not PgError({"C": "23505", "M": "dup"}).retryable


class TestCockroachDepth:
    """Round-3 additions: multitable bank client, tcpdump DB hook, and
    the ubuntu OS variant (bank.clj:160-249, auto.clj:67-75,
    os/ubuntu.clj)."""

    class StubConn:
        def __init__(self, txn_results=None, balances=None):
            self.stmts = []
            self.queries = []
            self.txn_results = txn_results
            self.balances = balances or {}

        def txn(self, stmts):
            self.stmts.append(list(stmts))
            if isinstance(self.txn_results, Exception):
                raise self.txn_results
            if self.txn_results is not None:
                return self.txn_results
            return [[] for _ in stmts]

        def query(self, sql):
            self.queries.append(sql)
            if isinstance(self.txn_results, Exception) and \
                    sql.startswith("UPDATE"):
                raise self.txn_results
            if sql.startswith("SELECT"):
                for tbl, bal in self.balances.items():
                    if tbl in sql:
                        return [(bal,)]
                return [(10,)]
            return []

        def close(self):
            pass

    def test_multibank_read_spans_all_tables_in_one_txn(self):
        from jepsen_tpu.history import invoke_op
        from jepsen_tpu.suites.cockroachdb import MultiBankClient

        conn = self.StubConn(txn_results=[[(10,)], [(7,)], [(13,)],
                                          [(10,)], [(10,)]])
        cl = MultiBankClient(conn, n=5, total=50)
        out = cl.invoke({}, invoke_op(0, "read", None))
        assert out.type == "ok" and out.value == [10, 7, 13, 10, 10]
        (stmts,) = conn.stmts
        assert len(stmts) == 5
        assert all(f"jepsen_accounts{i}" in stmts[i] for i in range(5))

    def test_multibank_transfer_reads_checks_updates(self):
        from jepsen_tpu.history import invoke_op
        from jepsen_tpu.suites.cockroachdb import MultiBankClient

        conn = self.StubConn(balances={"jepsen_accounts1": 10})
        cl = MultiBankClient(conn, n=5, total=50)
        out = cl.invoke({}, invoke_op(
            0, "transfer", {"from": 1, "to": 3, "amount": 4}))
        assert out.type == "ok"
        q = conn.queries
        assert q[0] == "BEGIN" and q[-1] == "COMMIT"
        assert any("SELECT" in s and "jepsen_accounts1" in s for s in q)
        assert any("jepsen_accounts1" in s and "balance - 4" in s
                   for s in q)
        assert any("jepsen_accounts3" in s and "balance + 4" in s
                   for s in q)

    def test_multibank_transfer_insufficient_funds_fails_clean(self):
        """The credit must NOT happen when the debit would go negative
        (bank.clj:193-225) — a conjured credit would make the checker
        blame a correct database."""
        from jepsen_tpu.history import invoke_op
        from jepsen_tpu.suites.cockroachdb import MultiBankClient

        conn = self.StubConn(balances={"jepsen_accounts1": 3})
        cl = MultiBankClient(conn, n=5, total=50)
        out = cl.invoke({}, invoke_op(
            0, "transfer", {"from": 1, "to": 3, "amount": 4}))
        assert out.type == "fail"
        assert not any(s.startswith("UPDATE") for s in conn.queries)
        assert conn.queries[-1] == "ROLLBACK"

    def test_multibank_txn_error_fails_transfer(self):
        from jepsen_tpu.history import invoke_op
        from jepsen_tpu.suites.cockroachdb import MultiBankClient
        from jepsen_tpu.suites.pgwire import PgError

        conn = self.StubConn(
            txn_results=PgError({"C": "40001", "M": "restart"}),
            balances={"jepsen_accounts0": 10})
        cl = MultiBankClient(conn, n=5, total=50)
        out = cl.invoke({}, invoke_op(
            0, "transfer", {"from": 0, "to": 1, "amount": 1}))
        assert out.type == "fail"

    def test_tcpdump_hook_commands(self):
        from jepsen_tpu import control as c
        from jepsen_tpu.suites import cockroachdb as cr

        t = c.DummyTransport(
            results={"env": "HOME=/root\nSSH_CLIENT=10.0.0.9 51022 22"})
        with c.with_session(t.connect("n1", {})):
            db = cr.CockroachDB(tcpdump=True)
            db.packet_capture("n1")
            db.stop_packet_capture()
        cmds = " ;; ".join(cmd for _, cmd in t.log)
        assert "tcpdump" in cmds
        assert "10.0.0.9" in cmds           # filters on the control addr
        assert str(cr.PORT) in cmds
        assert cr.PCAP_LOG in db.log_files({}, "n1")

    def test_registry_and_os_wiring(self):
        from jepsen_tpu import os_ubuntu
        from jepsen_tpu.suites import cockroachdb as cr

        t = cr.test({"fake": False, "workload": "bank-multitable",
                     "tcpdump": True})
        assert isinstance(t["client"], cr.MultiBankClient)
        assert t["db"].tcpdump is True
        assert isinstance(t["os"], os_ubuntu.UbuntuOS)
        t2 = cr.test({"fake": False, "os": "debian"})
        from jepsen_tpu import os_debian

        assert isinstance(t2["os"], os_debian.DebianOS)

    def test_ubuntu_os_setup_over_dummy(self):
        from jepsen_tpu import control as c
        from jepsen_tpu import os_ubuntu

        t = c.DummyTransport()
        with c.with_session(t.connect("n2", {})):
            os_ubuntu.os.setup({"nodes": ["n1", "n2"]}, "n2")
        cmds = " ;; ".join(cmd for _, cmd in t.log)
        assert "tcpdump" in cmds            # package list
        assert "ntp stop" in cmds
