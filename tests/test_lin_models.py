"""Parity tests for the set / fifo-queue / unordered-queue device kernels.

Three implementations must agree on every history: the generic CPU search
over the Python models (the semantic reference, check_generic), the packed
CPU search over the py_step_fn twins, and the device BFS kernel. Mirrors
the reference's model semantics at model.clj:58-105.
"""

import pytest

from jepsen_tpu import models as m
from jepsen_tpu.history import History, invoke_op, ok_op, info_op
from jepsen_tpu.lin import batched, bfs, cpu, prepare, synth


def verdicts(model, history):
    """(generic, packed-cpu, device) verdicts for one history."""
    p = prepare.prepare(model, history)
    assert p.kernel is not None, "expected a device kernel"
    generic = cpu.check_generic(p)["valid?"]
    packed = cpu.check_packed(p)["valid?"]
    device = bfs.check_packed(p)["valid?"]
    assert generic == packed == device, \
        f"generic={generic} packed={packed} device={device}"
    return device


class TestSetKernel:
    def test_sequential_valid(self):
        assert verdicts(m.set_model(), History.of(
            invoke_op(0, "add", "a"), ok_op(0, "add", "a"),
            invoke_op(0, "add", "b"), ok_op(0, "add", "b"),
            invoke_op(0, "read", None), ok_op(0, "read", ["a", "b"])))

    def test_read_missing_element_invalid(self):
        assert not verdicts(m.set_model(), History.of(
            invoke_op(0, "add", "a"), ok_op(0, "add", "a"),
            invoke_op(0, "read", None), ok_op(0, "read", [])))

    def test_read_phantom_element_invalid(self):
        assert not verdicts(m.set_model(), History.of(
            invoke_op(0, "add", "a"), ok_op(0, "add", "a"),
            invoke_op(0, "read", None), ok_op(0, "read", ["a", "z"])))

    def test_concurrent_add_read_either_way(self):
        # read concurrent with an add may or may not observe it
        assert verdicts(m.set_model(), History.of(
            invoke_op(0, "add", "a"), ok_op(0, "add", "a"),
            invoke_op(1, "add", "b"),
            invoke_op(2, "read", None), ok_op(2, "read", ["a"]),
            ok_op(1, "add", "b")))
        assert verdicts(m.set_model(), History.of(
            invoke_op(0, "add", "a"), ok_op(0, "add", "a"),
            invoke_op(1, "add", "b"),
            invoke_op(2, "read", None), ok_op(2, "read", ["a", "b"]),
            ok_op(1, "add", "b")))

    def test_crashed_add_observed_or_not(self):
        assert verdicts(m.set_model(), History.of(
            invoke_op(0, "add", "a"), info_op(0, "add", "a"),
            invoke_op(1, "read", None), ok_op(1, "read", ["a"]),
            invoke_op(1, "read", None), ok_op(1, "read", ["a"])))
        # once unobserved after observed => invalid (sets only grow)
        assert not verdicts(m.set_model(), History.of(
            invoke_op(0, "add", "a"), info_op(0, "add", "a"),
            invoke_op(1, "read", None), ok_op(1, "read", ["a"]),
            invoke_op(1, "read", None), ok_op(1, "read", [])))

    def test_initial_elements(self):
        assert verdicts(m.SetModel(frozenset(["x"])), History.of(
            invoke_op(0, "read", None), ok_op(0, "read", ["x"])))
        assert not verdicts(m.SetModel(frozenset(["x"])), History.of(
            invoke_op(0, "read", None), ok_op(0, "read", [])))

    def test_read_with_none_element_never_matches(self):
        assert not verdicts(m.set_model(), History.of(
            invoke_op(0, "add", 1), ok_op(0, "add", 1),
            invoke_op(0, "read", None), ok_op(0, "read", [1, None])))

    def test_none_in_initial_set_falls_back(self):
        p = prepare.prepare(m.SetModel(frozenset([None])), History.of(
            invoke_op(0, "read", None), ok_op(0, "read", [None])))
        assert p.kernel is None
        assert cpu.check_packed(p)["valid?"] is True

    def test_nil_add_falls_back(self):
        p = prepare.prepare(m.set_model(), History.of(
            invoke_op(0, "add", None), ok_op(0, "add", None)))
        assert p.kernel is None  # generic CPU handles it
        assert cpu.check_packed(p)["valid?"] is True

    @pytest.mark.parametrize("seed", range(4))
    def test_random_parity(self, seed):
        h = synth.generate_set_history(40, concurrency=3, seed=seed)
        assert verdicts(m.set_model(), h)

    @pytest.mark.parametrize("seed", range(4))
    def test_random_corrupted_parity(self, seed):
        h = synth.generate_set_history(40, concurrency=3, seed=seed,
                                       read_prob=0.4)
        bad = [o if not (o.is_ok and o.f == "read" and o.value)
               else o.replace(value=list(o.value) + [9999])
               for o in h]
        verdicts(m.set_model(), History(bad))


class TestFifoQueueKernel:
    def test_fifo_order_valid(self):
        assert verdicts(m.fifo_queue(), History.of(
            invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
            invoke_op(0, "enqueue", 2), ok_op(0, "enqueue", 2),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 1),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 2)))

    def test_fifo_reorder_invalid(self):
        assert not verdicts(m.fifo_queue(), History.of(
            invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
            invoke_op(0, "enqueue", 2), ok_op(0, "enqueue", 2),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 2)))

    def test_concurrent_enqueues_either_order(self):
        assert verdicts(m.fifo_queue(), History.of(
            invoke_op(0, "enqueue", 1),
            invoke_op(1, "enqueue", 2),
            ok_op(0, "enqueue", 1), ok_op(1, "enqueue", 2),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 2),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 1)))

    def test_dequeue_never_enqueued_invalid(self):
        assert not verdicts(m.fifo_queue(), History.of(
            invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 7)))

    def test_crashed_enqueue_dequeued(self):
        assert verdicts(m.fifo_queue(), History.of(
            invoke_op(0, "enqueue", 1), info_op(0, "enqueue", 1),
            invoke_op(1, "dequeue", None), ok_op(1, "dequeue", 1)))

    def test_initial_pending(self):
        assert verdicts(m.FIFOQueue((7,)), History.of(
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 7)))
        assert not verdicts(m.FIFOQueue((7,)), History.of(
            invoke_op(0, "enqueue", 8), ok_op(0, "enqueue", 8),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 8)))

    @pytest.mark.parametrize("seed", range(4))
    def test_random_parity(self, seed):
        h = synth.generate_queue_history(36, concurrency=3, seed=seed,
                                         fifo=True, crash_prob=0.05)
        assert verdicts(m.fifo_queue(), h)

    @pytest.mark.parametrize("seed", range(4))
    def test_random_lifo_vs_fifo_parity(self, seed):
        # histories from a *random-order* queue checked against FIFO:
        # verdict may go either way; the three checkers must agree
        h = synth.generate_queue_history(24, concurrency=3,
                                         seed=seed, fifo=False)
        verdicts(m.fifo_queue(), h)


class TestUnorderedQueueKernel:
    def test_any_order_valid(self):
        assert verdicts(m.unordered_queue(), History.of(
            invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
            invoke_op(0, "enqueue", 2), ok_op(0, "enqueue", 2),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 2),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 1)))

    def test_double_dequeue_invalid(self):
        assert not verdicts(m.unordered_queue(), History.of(
            invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 1),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 1)))

    def test_equal_values_distinct_repr_not_unique(self):
        # 1 == True, so these enqueues are NOT distinct values; the
        # bitmask specialization must not fire (regression: repr-based
        # uniqueness chose it and gave a wrong invalid verdict)
        assert verdicts(m.unordered_queue(), History.of(
            invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
            invoke_op(0, "enqueue", True), ok_op(0, "enqueue", True),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 1),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", True)))

    def test_duplicate_values_multiset(self):
        assert verdicts(m.unordered_queue(), History.of(
            invoke_op(0, "enqueue", 5), ok_op(0, "enqueue", 5),
            invoke_op(0, "enqueue", 5), ok_op(0, "enqueue", 5),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 5),
            invoke_op(0, "dequeue", None), ok_op(0, "dequeue", 5)))

    @pytest.mark.parametrize("seed", range(4))
    def test_random_parity(self, seed):
        h = synth.generate_queue_history(36, concurrency=3, seed=seed,
                                         fifo=False, crash_prob=0.05)
        assert verdicts(m.unordered_queue(), h)


class TestDecodeAndBatch:
    def test_decode_states(self):
        p = prepare.prepare(m.set_model(), History.of(
            invoke_op(0, "add", "a"), ok_op(0, "add", "a")))
        r = cpu.check_packed(p)
        assert r["valid?"] is True
        assert r["configs"][0]["model"] == frozenset(["a"])

        p = prepare.prepare(m.fifo_queue(), History.of(
            invoke_op(0, "enqueue", 3), ok_op(0, "enqueue", 3),
            invoke_op(0, "enqueue", 4), ok_op(0, "enqueue", 4)))
        r = cpu.check_packed(p)
        assert r["configs"][0]["model"] == (3, 4)

        p = prepare.prepare(m.unordered_queue(), History.of(
            invoke_op(0, "enqueue", 3), ok_op(0, "enqueue", 3)))
        r = cpu.check_packed(p)
        assert r["configs"][0]["model"] == (3,)

    def test_batch_mixed_kernel_sizes_groups(self):
        # per-key FIFO kernels sized differently -> no common step fn;
        # each key batches in its own homogeneous group (the old
        # behavior de-batched everything on the first mismatch).
        subs = {
            1: History.of(invoke_op(0, "enqueue", 1),
                          ok_op(0, "enqueue", 1)),
            2: History.of(invoke_op(0, "enqueue", 1),
                          ok_op(0, "enqueue", 1),
                          invoke_op(0, "enqueue", 2),
                          ok_op(0, "enqueue", 2)),
        }
        r = batched.try_check_batch(m.fifo_queue(), subs)
        assert r is not None and set(r) == {1, 2}
        assert all(v["valid?"] is True for v in r.values())

    def test_batch_same_sized_queue_keys(self):
        subs = {
            k: History.of(invoke_op(0, "enqueue", 1),
                          ok_op(0, "enqueue", 1),
                          invoke_op(0, "dequeue", None),
                          ok_op(0, "dequeue", 1))
            for k in (1, 2)
        }
        r = batched.try_check_batch(m.unordered_queue(), subs)
        assert r is not None
        assert all(v["valid?"] is True for v in r.values())
