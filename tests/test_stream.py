"""Streaming incremental checker (jepsen_tpu.stream, doc/streaming.md).

Four layers, mirroring the subsystem's vertical slice:

- Packer: the settled-row incremental pack is BIT-IDENTICAL to the
  one-shot prepare() of the same events (the foundation of the parity
  argument), including the position-keyed reduction tables.
- Session: a history checked in K >= 3 increments returns verdict,
  death row, and final-paths identical to the one-shot engine AND the
  lin/cpu.py oracle on the witness shapes; an injected violation
  aborts the stream within one increment of the offending completion;
  a killed mid-stream session resumes from its carried-frontier
  checkpoint with an identical verdict; a wedged increment degrades to
  the exact post-hoc check instead of guessing.
- Wire: daemon stream sessions round-trip with parity; a client drop
  mid-session is reaped (slot freed); :info-only completions decide
  vacuously valid (the indeterminate contract); a v1 frame gets a
  readable version-mismatch error, not an opaque codec failure.
- Runner: the abort latch stops the generator loop; with
  JEPSEN_TPU_STREAM=1 the stream verdict rides in results["stream"].
"""

from __future__ import annotations

import os
import socket
import time

import numpy as np
import pytest

from jepsen_tpu import models as m
from jepsen_tpu.history import Op
from jepsen_tpu.lin import bfs, cpu, prepare, synth
from jepsen_tpu.stream import IncrementalPacker, StreamChecker

# Same compiled shapes as tests/test_lin_ckpt_resume.py (shared
# .jax_cache programs); `compiles` exempts the cold-cache compile from
# the quick tier's no-compile enforcement.
pytestmark = [pytest.mark.quick, pytest.mark.compiles]

KW = dict(cap_schedule=(8,), host_caps=(64, 4096), explain=True)


@pytest.fixture(scope="module")
def witness_events():
    h = synth.generate_partitioned_register_history(
        140, concurrency=40, seed=0, partition_every=60,
        partition_len=20, max_crashes=10)
    return list(synth.corrupt_history(h, seed=3))


@pytest.fixture(scope="module")
def witness_full(witness_events):
    p = prepare.prepare(m.cas_register(), list(witness_events))
    r = bfs.check_packed(p, **KW)
    assert r["valid?"] is False
    return p, r


def _paths_key(result):
    return sorted(repr(sorted(od["index"] for od in fp["path"]))
                  for fp in result["final-paths"])


def _stream(events, k=4, min_rows=4, **kw):
    sc = StreamChecker(m.cas_register(), min_rows=min_rows,
                       check_kw=KW, **kw)
    n = max(1, len(events) // k)
    for i in range(0, len(events), n):
        sc.append(events[i:i + n])
    return sc, sc.finalize()


class TestPacker:
    SHAPES = [
        lambda: synth.generate_register_history(
            300, concurrency=8, seed=2, crash_prob=0.05,
            max_crashes=6),
        lambda: synth.generate_register_history(
            200, concurrency=4, seed=5, fs=("read", "write")),
        lambda: synth.generate_mutex_history(
            200, concurrency=6, seed=3, crash_prob=0.03),
    ]

    @pytest.mark.parametrize("shape", range(len(SHAPES)))
    def test_final_tables_bit_identical(self, shape):
        events = list(self.SHAPES[shape]())
        mk = m.mutex if shape == 2 else m.cas_register
        one = prepare.prepare(mk(), list(events))
        pk = IncrementalPacker(mk())
        step = max(1, len(events) // 7)
        for i in range(0, len(events), step):
            pk.feed_many(events[i:i + step])
            pk.settle()
        pk.settle(final=True)
        p2 = pk.packed()
        assert p2.window == one.window and p2.R == one.R
        for k in ("ret_slot", "ret_op", "active", "slot_f", "slot_v",
                  "slot_op", "crashed"):
            a1 = np.asarray(getattr(one, k))
            a2 = np.asarray(getattr(p2, k))
            assert a1.shape == a2.shape and (a1 == a2).all(), k
        assert one.unintern == p2.unintern
        assert one.init_state.tolist() == p2.init_state.tolist()
        r1 = prepare.reduction_tables(one)
        r2 = p2._reduction_tables
        assert (r1[0] == r2[0]).all() and (r1[1] == r2[1]).all()

    def test_witness_shape_tables_bit_identical(self, witness_events,
                                                witness_full):
        one, _ = witness_full
        pk = IncrementalPacker(m.cas_register())
        for i in range(0, len(witness_events), 50):
            pk.feed_many(witness_events[i:i + 50])
            pk.settle()
        pk.settle(final=True)
        p2 = pk.packed()
        for k in ("ret_slot", "active", "slot_v", "crashed"):
            assert (np.asarray(getattr(one, k))
                    == np.asarray(getattr(p2, k))).all(), k
        r1 = prepare.reduction_tables(one)
        assert (r1[1] == p2._reduction_tables[1]).all()

    def test_settled_rows_are_final(self):
        """Mid-stream reduction rows are a PREFIX of the final tables:
        a settled row is never revised by later events (the invariant
        that makes carried-frontier increments sound)."""
        h = list(synth.generate_register_history(
            300, concurrency=8, seed=2, crash_prob=0.05,
            max_crashes=6))
        one = prepare.prepare(m.cas_register(), list(h))
        r1 = prepare.reduction_tables(one)
        pk = IncrementalPacker(m.cas_register())
        for i in range(0, len(h), 37):
            pk.feed_many(h[i:i + 37])
            pk.settle()
            if pk.R:
                r2 = pk.reduction_tables()
                w2 = r2[0].shape[1]
                assert (r1[0][:pk.R, :w2] == r2[0][:pk.R]).all()
                assert (r1[1][:pk.R, :w2] == r2[1][:pk.R]).all()
                # cols past the current window are inactive so far
                assert not r1[0][:pk.R, w2:].any()
                assert (r1[1][:pk.R, w2:] == -1).all()

    def test_history_sized_kernels_run_in_buffer_mode(self):
        # Set/queue kernels are sized from the data: no stable frontier
        # layout to carry, so the session buffers and checks post-hoc.
        h = list(synth.generate_set_history(40, concurrency=3, seed=4))
        sc = StreamChecker(m.set_model(), min_rows=4)
        assert not sc.packer.incremental
        for i in range(0, len(h), 20):
            sc.append(h[i:i + 20])
        r = sc.finalize()
        want = cpu.check_packed(
            prepare.prepare(m.set_model(), list(h)))["valid?"]
        assert r["valid?"] == want
        assert r["stream"]["mode"] == "buffer"


class TestSessionParity:
    def test_witness_shape_matches_oneshot_and_oracle(
            self, witness_events, witness_full):
        p, full = witness_full
        sc, r = _stream(list(witness_events), k=5)
        assert r["valid?"] is False
        assert r["dead-row"] == full["dead-row"]
        assert r["op"] == full["op"]
        assert _paths_key(r) == _paths_key(full)
        assert r["stream"]["increments"] >= 3
        assert not r["stream"].get("degraded")
        want = cpu.check_packed(p)
        assert want["valid?"] is False and r["op"] == want["op"]

    def test_valid_history_matches_oneshot(self):
        from jepsen_tpu.lin import device_check_packed

        h = list(synth.generate_register_history(
            400, concurrency=5, seed=11, value_range=5))
        sc = StreamChecker(m.cas_register(), min_rows=8)
        for i in range(0, len(h), 100):
            sc.append(h[i:i + 100])
        r = sc.finalize()
        full = device_check_packed(
            prepare.prepare(m.cas_register(), list(h)))
        assert r["valid?"] is True is full["valid?"]
        assert r["stream"]["increments"] >= 3
        assert not r["stream"].get("degraded")

    def test_info_only_completions_decide_vacuously_valid(self):
        # Every completion indeterminate: nothing may be checked as
        # absent, so there are zero return-event rows and the stream
        # (like the oracle) decides True.
        h = [Op("invoke", "write", 1, 0), Op("invoke", "write", 2, 1),
             Op("info", "write", 1, 0), Op("info", "write", 2, 1)]
        want = cpu.check_packed(
            prepare.prepare(m.cas_register(), list(h)))["valid?"]
        sc = StreamChecker(m.cas_register(), min_rows=1)
        sc.append(h)
        r = sc.finalize()
        assert r["valid?"] is True is want
        assert r["stream"]["rows_settled"] == 0


    def test_unpackable_event_downgrades_without_dropping_events(self):
        # A double invoke (unpackable) must not raise out of append or
        # silently drop the rest of the batch: the session downgrades
        # to buffer mode and finalize surfaces the one-shot verdict
        # (honest unknown) over the COMPLETE fed history.
        h = [Op("invoke", "write", 1, 0),
             Op("invoke", "write", 2, 0),      # same process, no completion
             Op("ok", "write", 2, 0)]
        sc = StreamChecker(m.cas_register(), min_rows=1)
        sc.append(h)                           # must not raise
        assert len(sc.packer.history) == 3, "no event may be dropped"
        r = sc.finalize()
        assert r["valid?"] == "unknown"
        assert "invoked twice" in str(r.get("stream-fallback", "")) \
            or "invoked twice" in str(r.get("error", ""))


class TestAbort:
    def test_abort_within_one_increment_of_offending_completion(self):
        h = list(synth.generate_register_history(
            400, concurrency=5, seed=11, value_range=5))
        bad = list(synth.corrupt_history(
            synth.generate_register_history(
                400, concurrency=5, seed=11, value_range=5), seed=3))
        bad_at = next(i for i, (a, b) in enumerate(zip(h, bad))
                      if a.value != b.value or a.type != b.type)
        n = 50
        sc = StreamChecker(m.cas_register(), min_rows=8)
        fed = None
        for i in range(0, len(bad), n):
            sc.append(bad[i:i + n])
            if sc.aborted:
                fed = i + n
                break
        assert fed is not None, "stream never aborted"
        # Within one increment of the offending completion (plus the
        # settling slack of the <= concurrency ops pending across it).
        assert fed - bad_at <= 2 * n
        assert fed < len(bad), "abort must save remaining traffic"
        # The latched witness IS the final verdict.
        r = sc.finalize()
        assert r["valid?"] is False and sc.verdict["valid?"] is False
        assert r["stream"]["aborted"] is True

    def test_wedged_increment_degrades_to_exact_posthoc(
            self, witness_events, witness_full, monkeypatch,
            tmp_path):
        from jepsen_tpu.lin import supervise

        monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                           str(tmp_path / "q.json"))
        _, full = witness_full
        # Wedge every attempt of the first increment (budget = 1 retry
        # by default -> 2 attempts); injected attempts never touch the
        # device (supervise._consume_injection).
        supervise.inject_wedge("stream-incr", 2, 0.1)
        sc, r = _stream(list(witness_events), k=4)
        assert r["stream"].get("degraded"), "wedge must degrade"
        assert r["valid?"] is False
        assert r.get("stream-fallback") or r["stream"]["degraded"]
        assert r["op"] == full["op"]


class TestCheckpointResume:
    def _feed(self, events, sc, k=6, stop_after=None):
        n = max(1, len(events) // k)
        fed = 0
        for i in range(0, len(events), n):
            sc.append(events[i:i + n])
            fed += 1
            if stop_after is not None and fed >= stop_after:
                return False
        return True

    def test_killed_session_resumes_identical_verdict(
            self, witness_events, witness_full, tmp_path):
        _, full = witness_full
        ck = str(tmp_path / "stream.ckpt.npz")
        sc1 = StreamChecker(m.cas_register(), min_rows=4,
                            checkpoint=ck, check_kw=KW)
        self._feed(list(witness_events), sc1, stop_after=3)
        assert sc1._row > 0 and os.path.exists(ck), \
            "mid-stream session must have checkpointed progress"
        # The killed session is simply dropped (a real kill -9 leaves
        # exactly this file state — writes are atomic); the producer
        # replays the same events into a fresh session.
        sc2 = StreamChecker(m.cas_register(), min_rows=4,
                            checkpoint=ck, check_kw=KW)
        self._feed(list(witness_events), sc2)
        r = sc2.finalize()
        assert r["valid?"] is False
        assert r["dead-row"] == full["dead-row"]
        assert r["op"] == full["op"]
        assert _paths_key(r) == _paths_key(full)
        assert r["stream"]["resumed_from_row"] == sc1._row
        # Definite verdict clears the checkpoint (PR 5 contract).
        assert not os.path.exists(ck)

    def test_foreign_events_reject_checkpoint(self, witness_events,
                                              tmp_path):
        ck = str(tmp_path / "foreign.ckpt.npz")
        sc1 = StreamChecker(m.cas_register(), min_rows=4,
                            checkpoint=ck, check_kw=KW)
        self._feed(list(witness_events), sc1, stop_after=3)
        assert os.path.exists(ck)
        other = list(synth.generate_register_history(
            200, concurrency=5, seed=1, value_range=5))
        sc2 = StreamChecker(m.cas_register(), min_rows=8,
                            checkpoint=ck)
        n = max(1, len(other) // 4)
        for i in range(0, len(other), n):
            sc2.append(other[i:i + n])
        r = sc2.finalize()
        # Fingerprint mismatch: fresh correct run, no resume stamp.
        assert r["valid?"] is True
        assert "resumed_from_row" not in r["stream"]


class TestWire:
    def _svc(self, tmp_path, monkeypatch):
        from jepsen_tpu.service.daemon import CheckerService

        monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                           str(tmp_path / "quarantine.json"))
        return CheckerService(
            "127.0.0.1", 0, flush_ms_=10,
            stats_file=str(tmp_path / "svc.json")).start()

    def test_round_trip_parity_and_abort_surfaces_witness(
            self, tmp_path, monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        svc = self._svc(tmp_path, monkeypatch)
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            h = list(synth.generate_register_history(
                200, concurrency=5, seed=11, value_range=5))
            want = cpu.check_packed(
                prepare.prepare(m.cas_register(), list(h)))["valid?"]
            sid = c.stream_open("cas-register")
            n = len(h) // 4
            for i in range(0, len(h), n):
                st = c.stream_append(sid, h[i:i + n])
                assert st.get("type") == "stream-state", st
            r = c.stream_finalize(sid)
            assert r["valid?"] == want
            assert (r.get("stream") or {}).get("increments", 0) >= 3

            bad = list(synth.corrupt_history(
                synth.generate_register_history(
                    200, concurrency=5, seed=11, value_range=5),
                seed=3))
            sid2 = c.stream_open("cas-register")
            aborted = None
            for i in range(0, len(bad), n):
                st = c.stream_append(sid2, bad[i:i + n])
                if st.get("aborted"):
                    aborted = st
                    break
            assert aborted is not None, "append must surface the abort"
            assert aborted["result"]["valid?"] is False
            assert c.stream_finalize(sid2)["valid?"] is False
            c.shutdown()
            c.close()
        finally:
            svc.stop()

    def test_client_drop_mid_session_reaps_and_frees_slot(
            self, tmp_path, monkeypatch):
        from jepsen_tpu.service import protocol
        from jepsen_tpu.service.protocol import CheckerClient
        from jepsen_tpu.suites.common import SocketIO

        monkeypatch.setenv("JEPSEN_TPU_STREAM_SESSIONS", "1")
        svc = self._svc(tmp_path, monkeypatch)
        try:
            io = SocketIO(socket.create_connection(
                ("127.0.0.1", svc.port), timeout=5))
            protocol.send_msg(io, {"type": "stream-open", "id": 1,
                                   "model": "cas-register"})
            assert protocol.read_msg(io)["type"] == "stream-opened"
            c = CheckerClient("127.0.0.1", svc.port)
            assert c.stats()["stream_sessions_open"] == 1
            # At the bound: a second open must backpressure.
            with pytest.raises(RuntimeError, match="overload"):
                c.stream_open("cas-register")
            # DROP mid-session: the daemon reaps it and frees the slot.
            io.close()
            deadline = time.time() + 10
            while time.time() < deadline and \
                    c.stats().get("stream_sessions_open"):
                time.sleep(0.05)
            st = c.stats()
            assert st["stream_sessions_open"] == 0
            assert st.get("stream_reaped", 0) >= 1
            # Slot actually reusable.
            sid = c.stream_open("cas-register")
            c.stream_abort(sid)
            c.close()
        finally:
            svc.stop()

    def test_info_only_completions_over_the_wire(self, tmp_path,
                                                 monkeypatch):
        from jepsen_tpu.service.protocol import CheckerClient

        svc = self._svc(tmp_path, monkeypatch)
        try:
            c = CheckerClient("127.0.0.1", svc.port)
            sid = c.stream_open("cas-register")
            h = [Op("invoke", "write", 1, 0),
                 Op("invoke", "write", 2, 1),
                 Op("info", "write", 1, 0),
                 Op("info", "write", 2, 1)]
            st = c.stream_append(sid, h)
            assert st["type"] == "stream-state"
            # Indeterminate ops never become checkable rows.
            assert st["settled"] == 0 and st["pending"] == 0
            r = c.stream_finalize(sid)
            assert r["valid?"] is True
            c.close()
        finally:
            svc.stop()

    def test_v1_frame_gets_readable_version_error(self, tmp_path,
                                                  monkeypatch):
        from jepsen_tpu.service import protocol
        from jepsen_tpu.suites.common import SocketIO

        svc = self._svc(tmp_path, monkeypatch)
        try:
            io = SocketIO(socket.create_connection(
                ("127.0.0.1", svc.port), timeout=5))
            # A v1 client's frame (no version field -> v1).
            protocol.send_msg(io, {"type": "check", "id": 7,
                                   "model": "cas-register",
                                   "history": [], "v": 1})
            resp = protocol.read_msg(io)
            assert resp["type"] == "error"
            assert "version mismatch" in resp["error"]
            assert resp["daemon_version"] == protocol.PROTOCOL_VERSION
            io.close()
            from jepsen_tpu.service.protocol import CheckerClient

            c = CheckerClient("127.0.0.1", svc.port)
            assert c.stats().get("version_mismatches", 0) >= 1
            c.close()
        finally:
            svc.stop()


class TestRunner:
    def test_abort_latch_stops_generation(self):
        from jepsen_tpu import checker as c
        from jepsen_tpu import core
        from jepsen_tpu import generator as g
        from jepsen_tpu import tests_support as ts

        class AbortedStub:
            def offer(self, op):
                pass

            def should_abort(self):
                return True

        reg = ts.AtomRegister()
        test = ts.noop_test(
            client=ts.AtomClient(reg),
            generator=g.clients(g.limit(40, g.cas(5))),
            model=m.cas_register(),
            checker=c.unbridled_optimism(),
        )
        test["stream-live"] = AbortedStub()
        result = core.run(test)
        # Every worker saw the latch before drawing its first op.
        assert not [o for o in result["history"] if o.is_invoke]

    def test_live_run_attaches_stream_verdict(self, monkeypatch):
        from jepsen_tpu import checker as c
        from jepsen_tpu import core
        from jepsen_tpu import generator as g
        from jepsen_tpu import tests_support as ts

        monkeypatch.setenv("JEPSEN_TPU_STREAM", "1")
        monkeypatch.setenv("JEPSEN_TPU_STREAM_ROWS", "8")
        reg = ts.AtomRegister()
        test = ts.noop_test(
            client=ts.AtomClient(reg),
            generator=g.clients(g.limit(40, g.cas(5))),
            model=m.cas_register(),
            checker=c.linearizable("cpu"),
        )
        result = core.run(test)
        assert result["results"][c.VALID] is True
        assert result["results"]["stream"]["valid?"] is True

    def test_live_run_streams_over_wire(self, tmp_path, monkeypatch):
        # JEPSEN_TPU_STREAM_WIRE: the live checker becomes a daemon
        # stream-session client; the verdict still rides in
        # results["stream"], now stamped transport=wire.
        from jepsen_tpu import checker as c
        from jepsen_tpu import core
        from jepsen_tpu import generator as g
        from jepsen_tpu import tests_support as ts
        from jepsen_tpu.service.daemon import CheckerService

        monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                           str(tmp_path / "q.json"))
        svc = CheckerService(
            "127.0.0.1", 0, flush_ms_=10,
            stats_file=str(tmp_path / "svc.json")).start()
        try:
            monkeypatch.setenv("JEPSEN_TPU_STREAM", "1")
            monkeypatch.setenv("JEPSEN_TPU_STREAM_WIRE",
                               f"127.0.0.1:{svc.port}")
            reg = ts.AtomRegister()
            test = ts.noop_test(
                client=ts.AtomClient(reg),
                generator=g.clients(g.limit(40, g.cas(5))),
                model=m.cas_register(),
                checker=c.linearizable("cpu"),
            )
            result = core.run(test)
            assert result["results"][c.VALID] is True
            stream = result["results"]["stream"]
            assert stream["valid?"] is True
            assert stream.get("transport") == "wire"
            assert svc.stats().get("stream_opens", 0) >= 1
        finally:
            svc.stop()

    def test_wire_loss_degrades_to_local_same_verdict(
            self, tmp_path, monkeypatch):
        # Daemon dies mid-session: the buffered feed replays into an
        # in-process StreamChecker — verdict kept, loss annotated.
        from jepsen_tpu.service.daemon import CheckerService
        from jepsen_tpu.stream import runner

        monkeypatch.setenv("JEPSEN_TPU_QUARANTINE",
                           str(tmp_path / "q.json"))
        svc = CheckerService(
            "127.0.0.1", 0, flush_ms_=10,
            stats_file=str(tmp_path / "svc.json")).start()
        monkeypatch.setenv("JEPSEN_TPU_STREAM_WIRE",
                           f"127.0.0.1:{svc.port}")
        h = list(synth.generate_register_history(
            120, concurrency=4, seed=13, value_range=4))
        want = cpu.check_packed(
            prepare.prepare(m.cas_register(), list(h)))["valid?"]
        sess = runner._open_session(m.cas_register())
        assert isinstance(sess, runner._WireSession)
        n = len(h) // 3
        sess.append(h[:n])
        svc.stop()                      # the wire goes away mid-feed
        sess.append(h[n:])
        r = sess.finalize()
        assert r["valid?"] == want
        assert r.get("transport") == "local"
        assert "wire_degraded" in r

    def test_dead_target_falls_back_in_process(self, monkeypatch):
        from jepsen_tpu.stream import runner
        from jepsen_tpu.stream.session import StreamChecker

        # Nothing listens there: the session factory returns the
        # plain in-process checker (a down daemon never blocks a run).
        monkeypatch.setenv("JEPSEN_TPU_STREAM_WIRE",
                           "127.0.0.1:9")
        sess = runner._open_session(m.cas_register())
        assert isinstance(sess, StreamChecker)

    def test_live_run_flags_lying_client(self, monkeypatch):
        from jepsen_tpu import checker as c
        from jepsen_tpu import core
        from jepsen_tpu import generator as g
        from jepsen_tpu import tests_support as ts

        class LyingClient(ts.AtomClient):
            def invoke(self, test, op):
                if op.f == "write":
                    return op.replace(type="ok")   # ack, don't apply
                return super().invoke(test, op)

            def open(self, test, node):
                return LyingClient(self.register)

        monkeypatch.setenv("JEPSEN_TPU_STREAM", "1")
        monkeypatch.setenv("JEPSEN_TPU_STREAM_ROWS", "8")
        reg = ts.AtomRegister()
        reg.write(99)   # writes never land: reads must keep seeing 99
        test = ts.noop_test(
            client=LyingClient(reg),
            generator=g.clients(g.limit(60, g.mix(
                [Op("invoke", "read", None),
                 lambda: Op("invoke", "write", 1)]))),
            model=m.cas_register(99),
            checker=c.linearizable("cpu"),
        )
        result = core.run(test)
        assert result["results"][c.VALID] is False
        assert result["results"]["stream"]["valid?"] is False


def test_run_page_renders_stream_lag_and_abort(tmp_path):
    from jepsen_tpu import web

    snap = {"updated": "t", "pid": 1,
            "run": {"run": "lin-sparse", "row": 60, "total_rows": 100},
            "samples": [], "events": [],
            "views": {"stream": {
                "rows_settled": 100, "rows_checked": 60,
                "lag_rows": 40, "ops_ingested": 300,
                "aborted": True, "aborted_row": 61}}}
    path = tmp_path / "telemetry.json"
    import json

    path.write_text(json.dumps(snap))
    html = web.run_html(snapshot_file=str(path))
    assert "stream checker" in html
    assert "checked 60 / settled 100" in html
    assert "lag 40" in html
    assert "ABORTED" in html and "61" in html
