"""Counterexample parity on the crash-dom band (VERDICT r5 "Next
round" #4): the newest engine path — the pair-key crash-dom band with
its host-row executor and fused closure fixpoint — must report the SAME
violating op as the ``lin/cpu.py`` oracle on a corrupted
partition-shaped wide-window history, and every final-path it emits
must be a legal linearization prefix under the model. The 5k/window-25
shapes do not exercise these paths at all (CLAUDE.md round-5 lore);
this is a scaled-down literal config-5 shape (window 34, pair keys,
crashed mutators) with the chunk caps forced tiny so the search runs
through the host-row machinery.

Final-paths are checked for VALIDITY (replay through the python step
twin, the test_lin_witness precedent), not set-equality against the
oracle: both engines are exact on the verdict and the violating op,
but the alive-config set at death differs legitimately between them —
the device's dominance pruning keeps an exact-but-smaller frontier, so
each engine enumerates paths for its own alive set.
"""

import pytest

from jepsen_tpu import models as m
from jepsen_tpu.lin import bfs, cpu, prepare, synth

# quick (seconds-scale, .jax_cache-resident programs) but it DOES
# compile tiny XLA programs on a cold cache — exempt from the
# conftest no-compile enforcement via the registered `compiles` marker.
pytestmark = [pytest.mark.quick, pytest.mark.compiles]


def _pair_band_history():
    h = synth.generate_partitioned_register_history(
        140, concurrency=40, seed=0, partition_every=60,
        partition_len=20, max_crashes=10)
    return synth.corrupt_history(h, seed=3)


def test_crash_dom_counterexample_matches_oracle():
    p = prepare.prepare(m.cas_register(), _pair_band_history())
    # The corruption must land in the pair-key crash-dom band for the
    # test to mean anything: wide window (pair keys past 31-b bits)
    # with crashed mutators.
    assert p.window + max(len(p.unintern), 2).bit_length() > 31
    assert len(p.crashed_ops) > 0

    want = cpu.check_packed(p, witness=True)
    assert want["valid?"] is False, "corruption must invalidate"

    got = bfs.check_packed(p, cap_schedule=(8,), host_caps=(64, 4096),
                           explain=True)
    assert got["valid?"] is False
    assert got["op"] == want["op"]
    assert got["final-paths"], "device violation must carry final-paths"
    assert want["final-paths"], "oracle violation must carry them too"
    # The tiny caps must actually have routed rows through the host-row
    # executor (the fused closure fixpoint) — otherwise this test is
    # not covering the path it exists for.
    assert got["host-stats"]["rows"] >= 1
    assert got["host-stats"]["passes"] >= got["host-stats"]["dispatches"]


def test_crash_dom_final_paths_replay_legally():
    # Every device final-path must be a legal linearization prefix
    # under the model (replayed through the python step twin — the
    # test_lin_witness precedent for witness validity).
    from jepsen_tpu.lin.prepare import py_step_fn
    from jepsen_tpu.models.kernels import F_IDS, NIL

    p = prepare.prepare(m.cas_register(), _pair_band_history())
    got = bfs.check_packed(p, cap_schedule=(8,), host_caps=(64, 4096),
                           explain=True)
    assert got["valid?"] is False and got["final-paths"]
    step = py_step_fn(p.kernel.name)
    by_index = {o.op_index: o for o in p.ops}
    idxs = set(by_index)
    for fp in got["final-paths"]:
        st = tuple(int(x) for x in p.init_state)
        for od in fp["path"]:
            assert od["index"] in idxs
            o = by_index[od["index"]]
            f_id = F_IDS[o.f]
            if o.f == "cas":
                v = (p.intern.get(o.value[0], int(NIL)),
                     p.intern.get(o.value[1], int(NIL)))
            else:
                v = (int(NIL) if o.value is None
                     else p.intern.get(o.value, int(NIL)), int(NIL))
            ok, st = step(st, f_id, v)
            assert ok, f"witness path op {od} illegal at state {st}"
