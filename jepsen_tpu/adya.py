"""Adya G2 anti-dependency-cycle test harness.

Re-design of `jepsen/src/jepsen/adya.clj` (83 LoC): a workload probing for
G2 phantom anomalies — pairs of transactions that each check the *other*
row doesn't exist, then insert their own. Serializability admits at most
one of each pair's inserts; both succeeding is a G2 cycle.

- :func:`g2_gen` emits per-key paired ``insert`` ops (one per process,
  distinguished by which row each writes) wrapped in independent tuples
  (adya.clj:14-56).
- :func:`g2_checker` validates that at most one insert per key succeeded
  (adya.clj:58-83).
"""

from __future__ import annotations

import threading

from jepsen_tpu import checker as checker_ns
from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.history import Op


def g2_gen(keys=None) -> gen.Generator:
    """For each key, the first two processes to arrive get the paired
    insert ops (:value {key, id}); others skip (adya.clj:14-56)."""
    keys = keys if keys is not None else iter(range(10 ** 9))

    def fgen(k):
        state = {"n": 0}
        lock = threading.Lock()

        def go(test, process):
            with lock:
                i = state["n"]
                if i >= 2:
                    return None
                state["n"] += 1
            return Op("invoke", "insert", {"key": k, "id": i})

        return gen.gen(go)

    return independent.sequential_generator(keys, fgen)


def g2_checker() -> checker_ns.Checker:
    """At most one insert per key may succeed (adya.clj:58-83)."""

    def check(test, model, history, opts):
        oks = [op for op in history if op.is_ok and op.f == "insert"]
        if len(oks) > 1:
            return {checker_ns.VALID: False,
                    "insert-count": len(oks),
                    "error": f"Both inserts completed: "
                             f"{[op.value for op in oks]}"}
        # Like the reference: a key where *neither* insert succeeded tells
        # us nothing — flag it so the composed result can report coverage.
        return {checker_ns.VALID: True,
                "insert-count": len(oks)}

    return checker_ns.FnChecker(check)


def g2_coverage_checker(inner: checker_ns.Checker) -> checker_ns.Checker:
    """Compose the per-key G2 results into a coverage-aware top-level
    verdict. The independent lift reports per-key ``insert-count``
    only, so a run where NO key's race was ever exercised (every pair
    failed, or the generator starved) reads as a clean pass — invisibly
    vacuous. This wrapper aggregates: how many keys decided the race
    (exactly one insert won), how many saw the anomaly (both won), how
    many said nothing (no insert committed) — and degrades a
    zero-coverage "valid" to an honest ``"unknown"``."""

    def check(test, model, history, opts):
        r = dict(checker_ns.check_safe(inner, test, model, history,
                                       opts or {}))
        results = r.get("results") or {}
        counts = [v.get("insert-count", 0) for v in results.values()
                  if isinstance(v, dict)]
        exercised = sum(1 for c in counts if c == 1)
        anomalous = sum(1 for c in counts if c > 1)
        r["keys-total"] = len(counts)
        r["keys-exercised"] = exercised
        r["keys-anomalous"] = anomalous
        r["keys-empty"] = sum(1 for c in counts if c == 0)
        from jepsen_tpu.util import fraction

        r["coverage"] = fraction(exercised + anomalous,
                                 max(1, len(counts)))
        if r.get(checker_ns.VALID) is True and not exercised:
            r[checker_ns.VALID] = "unknown"
            r["error"] = ("no key exercised the G2 race (no insert "
                          "ever committed) — the pass is vacuous")
        return r

    return checker_ns.FnChecker(check)


class _FakeG2Client:
    """Serializable fake: each transaction checks the other row's absence
    before inserting, under one lock — so exactly one insert per key can
    succeed (faulty="g2" admits both, the anomaly the checker flags)."""

    def __init__(self, faulty=None, _rows=None, _lock=None):
        self.faulty = faulty
        self.rows = _rows if _rows is not None else {}
        self.lock = _lock if _lock is not None else threading.Lock()

    def open(self, test, node):
        return _FakeG2Client(self.faulty, self.rows, self.lock)

    def setup(self, test):
        pass

    def invoke(self, test, op):
        v = op.value
        k, payload = (v[0], v[1]) if independent.is_tuple(v) else (None, v)
        with self.lock:
            taken = self.rows.setdefault(k, set())
            other = 1 - payload["id"]
            if other in taken and self.faulty != "g2":
                return op.replace(type="fail")
            taken.add(payload["id"])
            return op.replace(type="ok")

    def teardown(self, test):
        pass

    def close(self, test):
        pass


def workload(keys=None, faulty=None) -> dict:
    """Generator + checker + fake client for a G2 test over independent
    keys (the workload-map shape of jepsen_tpu.suites.workloads). The
    independent lift is wrapped in :func:`g2_coverage_checker` so the
    top-level verdict carries race coverage, not just per-key counts."""
    return {"generator": gen.clients(g2_gen(keys)),
            "client": _FakeG2Client(faulty=faulty),
            "checker": g2_coverage_checker(
                independent.checker(g2_checker(), batch_device=False))}


def history_to_txn(history) -> list[Op]:
    """Express a G2 history in the txn checker's list-append dialect —
    the parity witness wiring of jepsen_tpu.txn.oracle: each insert is
    a transaction that read the OTHER row's list (observing it empty —
    the precondition its commit asserted) and appended its own row. A
    history where both inserts of a pair committed becomes a 2-cycle of
    anti-dependencies, which the txn checker must classify G2-item; a
    serializable history converts to a valid one (parity-tested in
    tests/test_txn_oracle.py)."""
    out: list[Op] = []
    for op in history:
        if op.f != "insert":
            continue
        v = op.value
        k, payload = (v[0], v[1]) if independent.is_tuple(v) else (None, v)
        if k is None:
            # Bare (un-lifted) values carry their key in the payload;
            # collapsing every key onto the "None:*" namespace would
            # alias different keys' rows into fabricated
            # duplicate-elements convictions.
            k = payload.get("key")
        i = payload["id"]
        own, other = f"{k}:{i}", f"{k}:{1 - i}"
        invoked = [["r", other, None], ["append", own, i]]
        if op.is_ok:
            # The commit asserted the other row's absence: its read
            # observed the empty list at the serialization point.
            out.append(op.replace(f="txn",
                                  value=[["r", other, []],
                                         ["append", own, i]]))
        else:
            out.append(op.replace(f="txn", value=invoked))
    return out
