"""Adya G2 anti-dependency-cycle test harness.

Re-design of `jepsen/src/jepsen/adya.clj` (83 LoC): a workload probing for
G2 phantom anomalies — pairs of transactions that each check the *other*
row doesn't exist, then insert their own. Serializability admits at most
one of each pair's inserts; both succeeding is a G2 cycle.

- :func:`g2_gen` emits per-key paired ``insert`` ops (one per process,
  distinguished by which row each writes) wrapped in independent tuples
  (adya.clj:14-56).
- :func:`g2_checker` validates that at most one insert per key succeeded
  (adya.clj:58-83).
"""

from __future__ import annotations

import threading

from jepsen_tpu import checker as checker_ns
from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.history import Op


def g2_gen(keys=None) -> gen.Generator:
    """For each key, the first two processes to arrive get the paired
    insert ops (:value {key, id}); others skip (adya.clj:14-56)."""
    keys = keys if keys is not None else iter(range(10 ** 9))

    def fgen(k):
        state = {"n": 0}
        lock = threading.Lock()

        def go(test, process):
            with lock:
                i = state["n"]
                if i >= 2:
                    return None
                state["n"] += 1
            return Op("invoke", "insert", {"key": k, "id": i})

        return gen.gen(go)

    return independent.sequential_generator(keys, fgen)


def g2_checker() -> checker_ns.Checker:
    """At most one insert per key may succeed (adya.clj:58-83)."""

    def check(test, model, history, opts):
        oks = [op for op in history if op.is_ok and op.f == "insert"]
        if len(oks) > 1:
            return {checker_ns.VALID: False,
                    "error": f"Both inserts completed: "
                             f"{[op.value for op in oks]}"}
        # Like the reference: a key where *neither* insert succeeded tells
        # us nothing — flag it so the composed result can report coverage.
        return {checker_ns.VALID: True,
                "insert-count": len(oks)}

    return checker_ns.FnChecker(check)


class _FakeG2Client:
    """Serializable fake: each transaction checks the other row's absence
    before inserting, under one lock — so exactly one insert per key can
    succeed (faulty="g2" admits both, the anomaly the checker flags)."""

    def __init__(self, faulty=None, _rows=None, _lock=None):
        self.faulty = faulty
        self.rows = _rows if _rows is not None else {}
        self.lock = _lock if _lock is not None else threading.Lock()

    def open(self, test, node):
        return _FakeG2Client(self.faulty, self.rows, self.lock)

    def setup(self, test):
        pass

    def invoke(self, test, op):
        v = op.value
        k, payload = (v[0], v[1]) if independent.is_tuple(v) else (None, v)
        with self.lock:
            taken = self.rows.setdefault(k, set())
            other = 1 - payload["id"]
            if other in taken and self.faulty != "g2":
                return op.replace(type="fail")
            taken.add(payload["id"])
            return op.replace(type="ok")

    def teardown(self, test):
        pass

    def close(self, test):
        pass


def workload(keys=None, faulty=None) -> dict:
    """Generator + checker + fake client for a G2 test over independent
    keys (the workload-map shape of jepsen_tpu.suites.workloads)."""
    return {"generator": gen.clients(g2_gen(keys)),
            "client": _FakeG2Client(faulty=faulty),
            "checker": independent.checker(g2_checker(),
                                           batch_device=False)}
