"""Client protocol: applies operations to the system under test.

Re-design of `jepsen/src/jepsen/client.clj` (65 LoC). The open/close vs
setup/teardown split (client.clj:7-22): ``open`` acquires a connection for
one process; ``setup`` performs one-time data installation; ``invoke``
applies one op and returns its completion; workers re-open clients when a
process crashes (core.clj:168-217).
"""

from __future__ import annotations

from jepsen_tpu.history import Op


class Client:
    def open(self, test, node) -> "Client":
        """Return a client bound to a connection to node. Called once per
        process (client.clj:9-12)."""
        return self

    def setup(self, test) -> None:
        """One-time database setup (client.clj:13-14)."""

    def invoke(self, test, op: Op) -> Op:
        """Apply op, returning its completion: type ok/fail/info
        (client.clj:15-18)."""
        raise NotImplementedError

    def teardown(self, test) -> None:
        """One-time cleanup (client.clj:19-20)."""

    def close(self, test) -> None:
        """Release this client's connection (client.clj:21-22)."""


class NoopClient(Client):
    """Does nothing (client.clj:24-31)."""

    def invoke(self, test, op):
        return op.replace(type="ok")


noop = NoopClient()


def closable(client) -> bool:
    """Whether the client supports close (client.clj:48-55). All
    jepsen_tpu clients do; kept for protocol parity."""
    return isinstance(client, Client)
