"""Jaxpr rules: the round 1-5 fault classes as shape predicates.

Each rule walks the closed jaxpr of a program about to be dispatched
(``jax.make_jaxpr`` on the engine's traceable thunk — tracing is
host-side and never compiles or touches the chip) and flags the
lowering patterns the axon TPU runtime is known to kill the worker
over. The thresholds are the probed lore constants, not guesses; each
rule's provenance is tabled in doc/analysis.md.

Rules (finding ``rule`` ids):

- ``gather-reduce-while`` — round 1: slot-batched
  ``take_along_axis``-class gathers combined with a
  ``lax.reduce(bitwise_or)`` inside nested loops kernel-fault the
  runtime (dense.py's reshape/concat bit algebra exists to avoid it).
  Fires when a loop body at nesting depth >= 2 contains both a
  ``gather`` over >= :data:`GATHER_ELEMS_MIN` elements and a
  ``reduce_or``.
- ``wide-sort`` — round 3: the 6-operand pair-dom ``lax.sort`` at the
  1M spike cap CRASHED the worker while the 4-operand dominance-word
  packing runs clean there (probed). Fires on a ``sort`` with more
  than :data:`SORT_MAX_OPERANDS` operands of >=
  :data:`SORT_ELEMS_MIN` elements.
- ``compact-chain`` — round 2: dedup compaction by
  cumsum+searchsorted+gather faults at spike sizes (bfs compacts with
  a second sort instead). Fires when a loop body contains both a
  ``cumsum`` and a ``gather`` over >= :data:`COMPACT_ELEMS_MIN`
  elements.
- ``unbounded-while`` — round 5: the group-cycled closure fixpoint
  ORBITED forever (observed 4124<->4110), and inside a nested
  ``lax.while_loop`` an infinite loop presents exactly like a kernel
  fault. Post-round-5 convention: every closure loop carries an
  iteration ceiling. Fires on any ``while`` whose cond contains no
  integer bound comparison (``lt``/``le``/``gt``/``ge``).
- ``rows-cap-envelope`` — rounds 2/4: the runtime objects to rows×cap
  PROGRAM complexity, not capacity (512-row chunks fault past cap
  131072 while 8-row chunks of the same program run clean at 2^20).
  Fires when a sort-bearing loop has a resolvable trip bound >
  :data:`ENVELOPE_ROWS_MAX` and carries arrays of leading dimension >
  :data:`ENVELOPE_CAP_MAX`.

The walker is conservative where it cannot resolve (an unknown trip
bound never fires the envelope rule), and every finding carries the
rule id + a human-readable detail for the ledger/event feed.
"""

from __future__ import annotations

from dataclasses import dataclass

# --- lore thresholds --------------------------------------------------------
# Round 3: 4-operand dominance-word sorts probed clean at cap 1048576
# x 32 rows; the 6-operand pair-dom sort crashed there.
SORT_MAX_OPERANDS = 4
# Spike-scale operand size: the crash was at the 1M spike cap; psort
# tops out at 2^19 pads and the host dedups at ~2^19-2^21 are clean
# with <=4 operands, so the operand-count rule engages at 2^19.
SORT_ELEMS_MIN = 1 << 19
# Round 2: compaction faults "at those sizes" = past the 131072 chunk
# cap; engage at 2^17 for margin.
COMPACT_ELEMS_MIN = 1 << 17
# Round 1's faulting gathers were slot-batched frontier-sized
# operands; tiny per-row index gathers are everywhere and harmless.
GATHER_ELEMS_MIN = 2048
# Rounds 2/4 envelope: 512-row chunks at cap 131072 are the probed
# fault frontier — flag a sort-bearing loop strictly past BOTH axes.
ENVELOPE_ROWS_MAX = 256
ENVELOPE_CAP_MAX = 131072

RULES = ("gather-reduce-while", "wide-sort", "compact-chain",
         "unbounded-while", "rows-cap-envelope")

_CMP_PRIMS = ("lt", "le", "gt", "ge")


@dataclass(frozen=True)
class Finding:
    """One rule violation on one program."""

    rule: str
    detail: str

    def __str__(self):
        return f"{self.rule}: {self.detail}"


def _elems(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    if not shape:
        return 1 if aval is not None else 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except (TypeError, ValueError):
            return 0      # symbolic dim: unresolvable, stay quiet
    return n


def _dim0(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    if not shape:
        return 0
    try:
        return int(shape[0])
    except (TypeError, ValueError):
        return 0


def _scalar_int(val):
    """A known Python int from a numpy/jax scalar, else None."""
    try:
        import numpy as np

        if hasattr(val, "shape") and np.size(val) != 1:
            return None
        v = np.asarray(val).reshape(())
        if v.dtype.kind not in "iu":
            return None
        return int(v)
    except Exception:  # noqa: BLE001 - resolution is best-effort
        return None


def _known(v, env: dict):
    """Resolve a jaxpr atom to a known scalar int (Literal or
    env-tracked const), else None."""
    if hasattr(v, "val"):                  # Literal
        return _scalar_int(v.val)
    return env.get(id(v))


def _is_jaxpr_like(v) -> bool:
    # ClosedJaxpr (has .jaxpr) or a raw Jaxpr (shard_map and some
    # pallas params carry the body UNclosed — has .eqns but no
    # .jaxpr); skipping raw bodies would make the mesh-chunk gate a
    # silent no-op.
    return hasattr(v, "jaxpr") or hasattr(v, "eqns")


def _raw(v):
    """The underlying Jaxpr of a ClosedJaxpr-or-Jaxpr param."""
    return v.jaxpr if hasattr(v, "jaxpr") else v


def _sub_jaxprs(eqn):
    """Every sub-program (ClosedJaxpr or raw Jaxpr) of a non-while
    eqn's params."""
    out = []
    for v in eqn.params.values():
        if _is_jaxpr_like(v):
            out.append(v)
        elif isinstance(v, (list, tuple)):
            out.extend(w for w in v if _is_jaxpr_like(w))
    return out


def _closed_env(closed) -> dict:
    env = {}
    if not hasattr(closed, "consts"):   # raw Jaxpr: no const values
        return env
    for var, val in zip(closed.jaxpr.constvars, closed.consts):
        k = _scalar_int(val)
        if k is not None:
            env[id(var)] = k
    return env


class _Scope:
    """Aggregate facts about one jaxpr scope INCLUDING its sub-scopes
    (what the loop-body rules test against)."""

    __slots__ = ("max_dim0", "gather_elems", "cumsum_elems",
                 "has_sort", "has_reduce_or")

    def __init__(self):
        self.max_dim0 = 0
        self.gather_elems = 0
        self.cumsum_elems = 0
        self.has_sort = False
        self.has_reduce_or = False

    def absorb(self, other: "_Scope") -> None:
        self.max_dim0 = max(self.max_dim0, other.max_dim0)
        self.gather_elems = max(self.gather_elems, other.gather_elems)
        self.cumsum_elems = max(self.cumsum_elems, other.cumsum_elems)
        self.has_sort = self.has_sort or other.has_sort
        self.has_reduce_or = self.has_reduce_or or other.has_reduce_or


def _cond_bound(eqn, env: dict):
    """(bounded, trip) for a while eqn: bounded = the cond contains an
    integer comparison (the iteration-ceiling convention); trip = the
    compared-against constant when it resolves (via a Literal, a cond
    const, or the carry's init value), else None."""
    cond = eqn.params["cond_jaxpr"]
    n_cc = eqn.params["cond_nconsts"]
    n_bc = eqn.params["body_nconsts"]
    cenv = _closed_env(cond)
    cond_invars = cond.jaxpr.invars

    def resolve(v):
        k = _known(v, cenv)
        if k is not None:
            return k
        # A cond invar: position < n_cc is a cond const, else carry —
        # both resolvable from the while eqn's operands when those are
        # Literals/known consts of the ENCLOSING scope.
        for i, iv in enumerate(cond_invars):
            if iv is v:
                j = i if i < n_cc else n_bc + i
                if j < len(eqn.invars):
                    return _known(eqn.invars[j], env)
                return None
        return None

    bounded = False
    trip = None
    for ce in cond.jaxpr.eqns:
        if ce.primitive.name not in _CMP_PRIMS:
            continue
        ints = [v for v in ce.invars
                if getattr(getattr(v, "aval", None), "dtype", None)
                is not None
                and getattr(v.aval.dtype, "kind", "") in "iu"]
        if len(ints) < 2:
            continue
        bounded = True
        for v in ce.invars:
            k = resolve(v)
            if k is not None and k > 1:
                trip = max(trip or 0, k)
    return bounded, trip


def _scan(jaxpr, env: dict, loop_depth: int,
          findings: list) -> _Scope:
    scope = _Scope()
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        for v in eqn.invars:
            scope.max_dim0 = max(scope.max_dim0, _dim0(v))
        if name == "sort":
            scope.has_sort = True
            nops = len(eqn.invars)
            elems = max((_elems(v) for v in eqn.invars), default=0)
            if nops > SORT_MAX_OPERANDS and elems >= SORT_ELEMS_MIN:
                findings.append(Finding(
                    "wide-sort",
                    f"{nops}-operand sort over {elems} elements "
                    f"(>{SORT_MAX_OPERANDS} operands at >="
                    f"{SORT_ELEMS_MIN}: the round-3 worker-killer; "
                    f"pack into <=4 operands)"))
        elif name == "gather":
            scope.gather_elems = max(
                scope.gather_elems,
                max((_elems(v) for v in eqn.invars), default=0))
        elif name == "cumsum":
            scope.cumsum_elems = max(
                scope.cumsum_elems,
                max((_elems(v) for v in eqn.invars), default=0))
        elif name == "reduce_or":
            scope.has_reduce_or = True

        if name == "while":
            body = eqn.params["body_jaxpr"]
            n_cc = eqn.params["cond_nconsts"]
            benv = _closed_env(body)
            # Body consts map 1:1 onto eqn operands after the cond
            # consts; carry values mutate per iteration — never
            # propagated.
            n_bc = eqn.params["body_nconsts"]
            for i in range(n_bc):
                k = _known(eqn.invars[n_cc + i], env)
                if k is not None and i < len(body.jaxpr.invars):
                    benv[id(body.jaxpr.invars[i])] = k
            sub = _scan(body.jaxpr, benv, loop_depth + 1, findings)
            cond_scope = _scan(eqn.params["cond_jaxpr"].jaxpr, {},
                               loop_depth + 1, findings)
            sub.absorb(cond_scope)
            bounded, trip = _cond_bound(eqn, env)
            if not bounded:
                findings.append(Finding(
                    "unbounded-while",
                    f"while at loop depth {loop_depth + 1} carries no "
                    f"iteration ceiling (no integer bound comparison "
                    f"in its cond — the round-5 orbit class: a "
                    f"nonterminating fixpoint presents as a kernel "
                    f"fault)"))
            _loop_rules(sub, loop_depth, trip, findings)
            scope.absorb(sub)
        elif name == "scan":
            sub_closed = eqn.params.get("jaxpr")
            if sub_closed is not None:
                sub = _scan(_raw(sub_closed), {}, loop_depth + 1,
                            findings)
                trip = eqn.params.get("length")
                _loop_rules(sub, loop_depth,
                            int(trip) if trip else None, findings)
                scope.absorb(sub)
        else:
            for sub_closed in _sub_jaxprs(eqn):
                senv = {}
                sub_invars = _raw(sub_closed).invars
                if name == "pjit" and len(sub_invars) == len(eqn.invars):
                    for iv, ov in zip(sub_invars, eqn.invars):
                        k = _known(ov, env)
                        if k is not None:
                            senv[id(iv)] = k
                scope.absorb(_scan(_raw(sub_closed), senv, loop_depth,
                                   findings))
    return scope


def _loop_rules(sub: _Scope, outer_depth: int, trip,
                findings: list) -> None:
    """Rules tested against one loop BODY scope (while or scan).
    ``outer_depth`` is the loop nesting around this loop."""
    if outer_depth >= 1 and sub.has_reduce_or \
            and sub.gather_elems >= GATHER_ELEMS_MIN:
        findings.append(Finding(
            "gather-reduce-while",
            f"gather over {sub.gather_elems} elements + reduce_or "
            f"inside a depth-{outer_depth + 1} nested loop (round-1 "
            f"kernel-faulter; prefer reshape/concat bit algebra)"))
    if sub.cumsum_elems >= COMPACT_ELEMS_MIN \
            and sub.gather_elems >= COMPACT_ELEMS_MIN:
        findings.append(Finding(
            "compact-chain",
            f"cumsum ({sub.cumsum_elems}) + gather "
            f"({sub.gather_elems}) compaction inside a loop (round-2 "
            f"faulter at dedup sizes; compact with a second sort)"))
    if trip is not None and trip > ENVELOPE_ROWS_MAX and sub.has_sort \
            and sub.max_dim0 > ENVELOPE_CAP_MAX:
        findings.append(Finding(
            "rows-cap-envelope",
            f"sort-bearing loop with trip bound {trip} over arrays of "
            f"leading dim {sub.max_dim0} — past the rows×cap fault "
            f"frontier ({ENVELOPE_ROWS_MAX} rows × {ENVELOPE_CAP_MAX} "
            f"cap, rounds 2/4); shrink the chunk (spike mode) or "
            f"route to host rows"))


def analyze_jaxpr(closed, waive=()) -> list[Finding]:
    """All findings for one ``ClosedJaxpr``, deduplicated by rule
    (one program either has a fault class or it does not — per-eqn
    multiplicity is noise). ``waive`` drops the named rules."""
    findings: list[Finding] = []
    _scan(closed.jaxpr, _closed_env(closed), 0, findings)
    out, seen = [], set()
    for f in findings:
        if f.rule in waive or f.rule in seen:
            continue
        seen.add(f.rule)
        out.append(f)
    return out


def analyze_fn(fn, *args, waive=(), **kwargs) -> list[Finding]:
    """``analyze_jaxpr(jax.make_jaxpr(fn)(*args, **kwargs))`` —
    tracing only: no compile, no device dispatch. Accepts
    ``jax.ShapeDtypeStruct`` args so callers never materialize
    spike-scale operands."""
    import jax

    return analyze_jaxpr(jax.make_jaxpr(fn)(*args, **kwargs),
                         waive=waive)
