"""Repo contract linter: the CLAUDE.md invariants as CI.

``cli.py lint`` / ``make lint`` run :func:`lint_repo` over the
checkout; a quick-tier test asserts zero findings so every contract
below gates every future PR through tier-1. Pure ``ast`` + regex —
jax-free, sub-second, chip-free.

Rules (finding ``rule`` ids):

- ``while-ceiling`` — every ``lax.while_loop`` in ``lin/`` + ``txn/``
  must carry an iteration ceiling: its cond function contains an
  ordered comparison (``<``/``<=``/``>``/``>=``). The round-5 orbit
  lesson (a nonterminating fixpoint inside a nested while presents as
  a kernel fault) as a source-level invariant; ``fori_loop`` is
  bounded by construction. Waiver: ``# lint: unbounded-ok`` (for the
  provably-monotone closure fixpoints that predate the convention).
- ``env-doc`` — every ``JEPSEN_TPU_*`` knob referenced in code is
  tabled in doc/env.md and vice versa (drift both ways). Tokens
  ending in ``_`` (f-string prefixes) are exempt.
- ``wire-fail`` — wire suites (``suites/*wire*.py``) never complete
  an op as ``"fail"`` from inside an ``except`` handler unless the
  completion is read-guarded (``"fail" if op.f == "read" else
  "info"`` — reads never apply). A ``:fail`` for a mutator that may
  have applied makes the checker unsound. Waiver: ``# lint: fail-ok``
  with the soundness argument (e.g. a parsed server error response is
  a definite rejection).
- ``pallas-const`` — modules importing Pallas hold no module-level
  ``jnp`` constants (Mosaic illegal-captured-const lore: module-level
  jnp values become illegal captured consts in kernels; use Python
  ints). Waiver: ``# lint: jnp-const-ok``.
- ``quick-compiles`` — a quick-marked test file importing a
  compile-triggering engine module carries at least one ``compiles``
  marker (the conftest no-compile enforcement's exemption), so the
  quick tier's no-compile promise stays auditable. Waiver:
  ``# lint: compiles-ok``.

Waiver syntax: the comment goes on the offending line or the line
directly above it. Waivers are greppable (``grep -rn 'lint:'``) so
every exemption stays reviewable.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass

ENV_RE = re.compile(r"JEPSEN_TPU_[A-Z0-9_]+")

# Modules whose import (module-level jnp constants) or first use
# triggers XLA compiles — the conftest enforcement's usual suspects.
COMPILE_TRIGGER_MODULES = (
    "jepsen_tpu.lin.bfs", "jepsen_tpu.lin.dense",
    "jepsen_tpu.lin.dense_pallas", "jepsen_tpu.lin.batched",
    "jepsen_tpu.lin.psort", "jepsen_tpu.lin.sharded",
    "jepsen_tpu.lin.sharded_dense", "jepsen_tpu.txn.device",
    "jepsen_tpu.lin.pack_dev",
)


@dataclass(frozen=True)
class LintFinding:
    rule: str
    path: str
    line: int
    msg: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}"


def repo_root() -> str:
    """The checkout root: the parent of the ``jepsen_tpu`` package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _waived(lines: list[str], lineno: int, tag: str) -> bool:
    """``# lint: <tag>-ok`` on the finding's line or anywhere in the
    contiguous comment block directly above it — justifications are
    encouraged, so a waiver may open a multi-line comment."""
    pat = f"lint: {tag}-ok"
    if 1 <= lineno <= len(lines) and pat in lines[lineno - 1]:
        return True
    ln = lineno - 1
    while 1 <= ln <= len(lines) \
            and lines[ln - 1].strip().startswith("#"):
        if pat in lines[ln - 1]:
            return True
        ln -= 1
    return False


def _py_files(root: str, *subdirs: str) -> list[str]:
    out = []
    for sub in subdirs:
        d = os.path.join(root, sub)
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            if name.endswith(".py"):
                out.append(os.path.join(d, name))
    return out


def _rel(root: str, path: str) -> str:
    return os.path.relpath(path, root)


# --- while-ceiling ----------------------------------------------------------


def _has_ordered_compare(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Compare) and any(
                isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE))
                for op in n.ops):
            return True
    return False


def lint_while_source(src: str, path: str) -> list[LintFinding]:
    findings: list[LintFinding] = []
    lines = src.splitlines()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [LintFinding("while-ceiling", path, e.lineno or 0,
                            f"unparseable: {e.msg}")]
    defs: dict[str, list[ast.FunctionDef]] = {}
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(n.name, []).append(n)
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        fname = None
        if isinstance(n.func, ast.Attribute):
            fname = n.func.attr
        elif isinstance(n.func, ast.Name):
            fname = n.func.id
        if fname != "while_loop":
            continue
        cond = None
        if n.args:
            cond = n.args[0]
        else:
            for kw in n.keywords:
                if kw.arg == "cond_fun":
                    cond = kw.value
        ok = False
        if isinstance(cond, ast.Lambda):
            ok = _has_ordered_compare(cond.body)
        elif isinstance(cond, ast.Name) and cond.id in defs:
            # All same-named defs must carry a compare (shadowed
            # helpers must not vouch for each other).
            ok = all(_has_ordered_compare(d) for d in defs[cond.id])
        if ok or _waived(lines, n.lineno, "unbounded"):
            continue
        findings.append(LintFinding(
            "while-ceiling", path, n.lineno,
            "lax.while_loop without an iteration ceiling (no ordered "
            "comparison in its cond — the round-5 orbit class); add "
            "an in-carry counter bound or '# lint: unbounded-ok' "
            "with the termination argument"))
    return findings


# --- env-doc drift ----------------------------------------------------------


def _env_tokens(text: str):
    return {t for t in ENV_RE.findall(text) if not t.endswith("_")}


def lint_env_doc(root: str) -> list[LintFinding]:
    doc_path = os.path.join(root, "doc", "env.md")
    try:
        with open(doc_path) as fh:
            doc_tokens = _env_tokens(fh.read())
    except OSError:
        return [LintFinding("env-doc", "doc/env.md", 0,
                            "doc/env.md missing (the every-knob table, "
                            "CLAUDE.md)")]
    code_where: dict[str, tuple[str, int]] = {}
    files = _py_files(root, "jepsen_tpu", "jepsen_tpu/lin",
                      "jepsen_tpu/txn", "jepsen_tpu/obs",
                      "jepsen_tpu/service", "jepsen_tpu/stream",
                      "jepsen_tpu/suites", "jepsen_tpu/analysis",
                      "jepsen_tpu/models", "jepsen_tpu/checker",
                      "jepsen_tpu/control", "tests")
    for extra in ("bench.py", "__graft_entry__.py", "Makefile"):
        p = os.path.join(root, extra)
        if os.path.exists(p):
            files.append(p)
    for path in files:
        try:
            with open(path) as fh:
                text = fh.read()
        except OSError:
            continue
        for i, ln in enumerate(text.splitlines(), 1):
            for t in _env_tokens(ln):
                code_where.setdefault(t, (_rel(root, path), i))
    findings = []
    for t in sorted(set(code_where) - doc_tokens):
        p, ln = code_where[t]
        findings.append(LintFinding(
            "env-doc", p, ln,
            f"{t} referenced in code but not tabled in doc/env.md "
            f"(the every-knob rule, CLAUDE.md)"))
    for t in sorted(doc_tokens - set(code_where)):
        findings.append(LintFinding(
            "env-doc", "doc/env.md", 0,
            f"{t} tabled in doc/env.md but referenced nowhere in "
            f"code (stale row)"))
    return findings


# --- wire-fail --------------------------------------------------------------


def _is_read_guard(test: ast.AST) -> bool:
    """``op.f == "read"``-shaped test (possibly inside or/and)."""
    for n in ast.walk(test):
        if isinstance(n, ast.Compare) and isinstance(n.ops[0], ast.Eq):
            vals = [n.left] + list(n.comparators)
            has_f = any(isinstance(v, ast.Attribute) and v.attr == "f"
                        for v in vals)
            has_read = any(isinstance(v, ast.Constant)
                           and v.value == "read" for v in vals)
            if has_f and has_read:
                return True
    return False


def lint_wire_source(src: str, path: str) -> list[LintFinding]:
    findings: list[LintFinding] = []
    lines = src.splitlines()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [LintFinding("wire-fail", path, e.lineno or 0,
                            f"unparseable: {e.msg}")]
    for handler in (n for n in ast.walk(tree)
                    if isinstance(n, ast.ExceptHandler)):
        for call in (n for n in ast.walk(handler)
                     if isinstance(n, ast.Call)):
            for kw in call.keywords:
                if kw.arg != "type":
                    continue
                v = kw.value
                bad = None
                if isinstance(v, ast.Constant) and v.value == "fail":
                    bad = 'completes type="fail" inside an except ' \
                          "handler"
                elif isinstance(v, ast.IfExp):
                    body_fail = isinstance(v.body, ast.Constant) \
                        and v.body.value == "fail"
                    orelse_fail = isinstance(v.orelse, ast.Constant) \
                        and v.orelse.value == "fail"
                    if orelse_fail:
                        bad = 'conditional completion falls back to ' \
                              '"fail" inside an except handler'
                    elif body_fail and not _is_read_guard(v.test):
                        bad = '"fail" branch of an except-handler ' \
                              "completion is not read-guarded"
                if bad is None:
                    continue
                if _waived(lines, call.lineno, "fail") \
                        or _waived(lines, kw.value.lineno, "fail"):
                    continue
                findings.append(LintFinding(
                    "wire-fail", path, call.lineno,
                    f"{bad}: an op that may have APPLIED must "
                    f"complete :info, never :fail (checker "
                    f"soundness). Guard with op.f == \"read\", "
                    f"complete :info, or waiver '# lint: fail-ok' "
                    f"with the definite-rejection argument"))
    return findings


# --- pallas-const -----------------------------------------------------------


def lint_pallas_source(src: str, path: str) -> list[LintFinding]:
    findings: list[LintFinding] = []
    lines = src.splitlines()
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return findings
    imports_pallas = any(
        (isinstance(n, ast.ImportFrom)
         and ("pallas" in (n.module or "")
              or any("pallas" in a.name for a in n.names)))
        or (isinstance(n, ast.Import)
            and any("pallas" in a.name for a in n.names))
        for n in ast.walk(tree))
    if not imports_pallas:
        return findings
    for stmt in tree.body:
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            continue
        value = stmt.value
        if value is None:
            continue
        jnp_use = next(
            (n for n in ast.walk(value)
             if isinstance(n, ast.Attribute)
             and isinstance(n.value, ast.Name)
             and n.value.id == "jnp"), None)
        if jnp_use is None or _waived(lines, stmt.lineno, "jnp-const"):
            continue
        findings.append(LintFinding(
            "pallas-const", path, stmt.lineno,
            "module-level jnp constant in a Pallas kernel module: "
            "Mosaic rejects captured jnp consts (round-3 lore) — use "
            "Python ints/tuples and build arrays inside the kernel"))
    return findings


# --- quick-compiles ---------------------------------------------------------


def _marker_attrs(tree: ast.AST) -> set[str]:
    """Names used as pytest marker attributes anywhere in the file
    (``pytest.mark.quick``, ``pytest.mark.compiles(...)``, ...)."""
    out = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Attribute) \
                and isinstance(n.value, ast.Attribute) \
                and n.value.attr == "mark":
            out.add(n.attr)
    return out


def _imported_modules(tree: ast.AST) -> set[str]:
    mods = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            mods.update(a.name for a in n.names)
        elif isinstance(n, ast.ImportFrom) and n.module:
            mods.add(n.module)
            mods.update(f"{n.module}.{a.name}" for a in n.names)
    return mods


def lint_quick_source(src: str, path: str) -> list[LintFinding]:
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    markers = _marker_attrs(tree)
    if "quick" not in markers or "compiles" in markers:
        return []
    lines = src.splitlines()
    mods = _imported_modules(tree)
    hits = sorted(m for m in mods if m in COMPILE_TRIGGER_MODULES)
    if not hits:
        return []
    if any("lint: compiles-ok" in ln for ln in lines):
        return []
    return [LintFinding(
        "quick-compiles", path, 1,
        f"quick-marked test file imports compile-triggering "
        f"module(s) {', '.join(hits)} but carries no 'compiles' "
        f"marker: mark the compiling tests @pytest.mark.compiles (the "
        f"conftest no-compile enforcement's exemption) or waiver "
        f"'# lint: compiles-ok' if nothing in the file ever "
        f"dispatches them")]


# --- driver -----------------------------------------------------------------


def lint_repo(root: str | None = None) -> list[LintFinding]:
    """Run every rule over the checkout; findings sorted by path."""
    root = root or repo_root()
    findings: list[LintFinding] = []

    for path in _py_files(root, "jepsen_tpu/lin", "jepsen_tpu/txn"):
        with open(path) as fh:
            src = fh.read()
        findings.extend(lint_while_source(src, _rel(root, path)))

    findings.extend(lint_env_doc(root))

    for path in _py_files(root, "jepsen_tpu/suites"):
        if "wire" not in os.path.basename(path):
            continue
        with open(path) as fh:
            src = fh.read()
        findings.extend(lint_wire_source(src, _rel(root, path)))

    for path in _py_files(root, "jepsen_tpu", "jepsen_tpu/lin",
                          "jepsen_tpu/txn", "jepsen_tpu/models"):
        with open(path) as fh:
            src = fh.read()
        findings.extend(lint_pallas_source(src, _rel(root, path)))

    for path in _py_files(root, "tests"):
        if not os.path.basename(path).startswith("test_"):
            continue
        with open(path) as fh:
            src = fh.read()
        findings.extend(lint_quick_source(src, _rel(root, path)))

    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def render(findings: list[LintFinding]) -> str:
    if not findings:
        return "lint: clean (0 findings)"
    by_rule: dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    head = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items()))
    return "\n".join([f"lint: {len(findings)} finding(s) ({head})"]
                     + [str(f) for f in findings])
