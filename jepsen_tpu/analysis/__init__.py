"""Static analysis: the fault lore as machine-checked rules.

Five rounds of fault lore (CLAUDE.md) and PR 5's quarantine ledger
encode the TPU runtime's failure envelope *reactively* — a shape must
fault or wedge once (killing the worker for ~a minute, or stalling a
3217 s config-5 run) before the runtime routes around it. This package
makes the knowledge *predictive*, in two layers:

- :mod:`jepsen_tpu.analysis.jaxpr_lint` — pure rules over a traced
  program's closed jaxpr: the catalogued fault classes (round-1
  gather+reduce_or in nested loops, round-3 wide sorts, round-2
  cumsum/searchsorted/gather compaction, the round-5 unbounded-loop
  orbit class, the rows×cap program-complexity envelope) as shape
  predicates. No jax import cost until a jaxpr is actually analyzed.
- :mod:`jepsen_tpu.analysis.gate` — the pre-dispatch gate
  :func:`jepsen_tpu.lin.supervise.run_guarded` consults: trace the
  program about to launch (cached per traced shape key), flag it
  against the rules, and — under ``JEPSEN_TPU_STATIC_GATE=route`` —
  send a predicted-faulty program down its existing fallback ladder
  *before* it ever touches the chip, recording a ``static`` entry in
  the quarantine ledger (distinct from ``fault``/``wedge``).
- :mod:`jepsen_tpu.analysis.lint` — the repo contract linter
  (``cli.py lint``, ``make lint``): AST-level checks that the
  CLAUDE.md architecture invariants hold in source — iteration
  ceilings on ``lax.while_loop``s in ``lin/``+``txn/``, two-way
  ``JEPSEN_TPU_*``/doc/env.md drift, the wire suites'
  ``:info``-never-``:fail`` exception contract, no module-level
  ``jnp`` constants in Pallas kernel modules, and the quick tier's
  ``compiles``-marker discipline. Pure ``ast``; jax-free at import.

Rule catalog, thresholds, and waiver syntax: doc/analysis.md.
"""
