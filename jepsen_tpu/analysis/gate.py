"""The pre-dispatch static gate (``JEPSEN_TPU_STATIC_GATE``).

:func:`jepsen_tpu.lin.supervise.run_guarded` calls :func:`consider`
with the engine's *traceable* — the pure-jax half of the dispatch
thunk (no host fetches) — right before dispatching it. The gate traces
the program (``jax.make_jaxpr``: host-side, no compile, no chip) and
checks it against the :mod:`jepsen_tpu.analysis.jaxpr_lint` fault
rules, cached per traced shape key so each program shape is analyzed
once per process.

Modes (read per call, the env-knob convention):

- ``warn`` (default): a flagged program emits a ``static`` event on
  the obs feed and a ``static-flag`` trace instant, then dispatches
  normally. Attribution and triage see the prediction; behaviour is
  unchanged.
- ``route``: at the sites that HAVE a fallback rung
  (:data:`ROUTED_SITES` — the same set that consults the quarantine
  ledger), a flagged program is sent down the ladder *before touching
  the chip*: ``run_guarded`` returns ``("static", StaticallyFlagged)``
  without dispatching, the shape is recorded in the quarantine ledger
  with reason ``static`` (distinct from ``fault``/``wedge`` in
  ``cli.py quarantine list``; it does NOT quarantine the shape — turn
  the gate off and the entry is routing-inert), and a ``static-skip``
  trace instant carries the estimated seconds saved (a fault costs
  ~a minute of dead worker, CLAUDE.md). Base-rung sites (chunk,
  chunk-batch, spike, mesh-chunk) have no alternative rung and only
  ever warn — exactly the ledger's routing split.
- ``off``: no tracing, no analysis, zero overhead.

A program the gate cannot trace (host fetches in the traceable, exotic
control flow) is treated as unanalyzable and passes — the gate must
never take a healthy run down, and the watchdog/ledger reactive layer
still stands behind it.

Test hook: ``JEPSEN_TPU_STATIC_FORCE="substr[:rule]"`` force-flags any
key containing ``substr`` (comma-separable), so route-mode plumbing is
testable without constructing a genuinely faulty program — the
``JEPSEN_TPU_WEDGE`` precedent.
"""

from __future__ import annotations

import os
import threading

from jepsen_tpu import util
from jepsen_tpu.analysis import jaxpr_lint
from jepsen_tpu.obs import metrics as _obs_metrics
from jepsen_tpu.obs import trace as _obs_trace

MODES = ("off", "warn", "route")

# The sites with a proven fallback rung below them — the same set that
# consults the quarantine ledger for routing (supervise docstring).
ROUTED_SITES = frozenset(
    {"host-sched", "host-wave", "host-fixpoint", "host-pass",
     "txn-scc", "pack-dev"})

# Per-site rule waivers: the jaxpr twin of the source-level
# `# lint: unbounded-ok` comments. Empty since the mesh closure
# fixpoints (sharded.py) gained in-carry iteration ceilings — every
# supervised site's loops now carry an ordered-compare bound the
# jaxpr walker can see; add entries only with a written termination
# argument at the waived loop.
SITE_WAIVERS: dict = {}

# What one avoided fault is worth: a kernel fault kills the TPU worker
# for ~a minute (CLAUDE.md round-1 lore) before the retry even starts.
FAULT_RECOVERY_EST_S = 60.0


class StaticallyFlagged(Exception):
    """run_guarded's ``("static", exc)`` payload: the program was
    routed to its fallback rung by prediction, not by a crash."""

    def __init__(self, site: str, key: str, findings):
        self.site, self.key, self.findings = site, key, list(findings)
        super().__init__(
            f"static gate flagged {key!r}: "
            + "; ".join(str(f) for f in self.findings))


def mode() -> str:
    v = os.environ.get("JEPSEN_TPU_STATIC_GATE", "warn").strip().lower()
    return v if v in MODES else "warn"


_lock = threading.Lock()
# key -> list[Finding] ([] = analyzed clean, or unanalyzable).
_cache: dict[str, list] = {}
_unanalyzable: set[str] = set()
# Keys already ledger-recorded this process: a flagged per-row shape
# is considered once per ROW, but the ledger write happens once.
_recorded: set[str] = set()
# Keys whose flagging was already announced on the bounded obs event
# feed / warn-mode trace: a per-pass site dispatches hundreds of times
# per row, and per-dispatch `static` events would evict the real
# fault/wedge events triage depends on. (Route-mode `static-skip`
# instants stay per-dispatch — they ARE the avoided-dispatch count the
# attribution report prices.)
_noted: set[str] = set()


def reset() -> None:
    """Tests: drop the per-process analysis cache (e.g. after flipping
    engine env knobs that change the program behind a key)."""
    with _lock:
        _cache.clear()
        _unanalyzable.clear()
        _recorded.clear()
        _noted.clear()


def analyzed() -> dict:
    """Snapshot of key -> findings analyzed so far (tests; the
    shipped-programs-pass regression reads this)."""
    with _lock:
        return {k: list(v) for k, v in _cache.items()}


def unanalyzable() -> set:
    with _lock:
        return set(_unanalyzable)


def _forced(key: str):
    env = os.environ.get("JEPSEN_TPU_STATIC_FORCE", "")
    if not env:
        return []
    out = []
    for part in env.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":", 1)
        if bits[0] and bits[0] in key:
            out.append(jaxpr_lint.Finding(
                bits[1] if len(bits) > 1 and bits[1] else "forced",
                f"JEPSEN_TPU_STATIC_FORCE={part!r} (test hook)"))
    return out


def check(key: str, traceable, waive=()) -> list:
    """Findings for ``traceable`` (a no-arg pure-jax callable), cached
    per shape key. Unanalyzable programs return [] and are remembered
    so the (possibly expensive) failed trace is never repeated."""
    with _lock:
        if key in _cache:
            return list(_cache[key])
    try:
        import jax

        findings = jaxpr_lint.analyze_jaxpr(
            jax.make_jaxpr(traceable)(), waive=waive)
        bad = False
    except Exception:  # noqa: BLE001 - unanalyzable must pass, never raise
        findings = []
        bad = True
    with _lock:
        # Cache findings only — a ClosedJaxpr pins its closed-over
        # device arrays; dropping it here keeps the cache O(keys).
        _cache[key] = list(findings)
        if bad:
            _unanalyzable.add(key)
    return findings


def consider(site: str, key: str, traceable,
             stats: dict | None = None):
    """The run_guarded hook. Returns None to proceed with the
    dispatch, or a :class:`StaticallyFlagged` when the program is
    flagged AND the mode/site combination routes."""
    m = mode()
    if m == "off":
        return None
    findings = check(key, traceable,
                     waive=SITE_WAIVERS.get(site, ())) + _forced(key)
    if not findings:
        return None
    rules = [f.rule for f in findings]
    route = m == "route" and site in ROUTED_SITES
    with _lock:
        first = key not in _noted
        _noted.add(key)
    if first:
        _obs_metrics.REGISTRY.event("static", site=site, key=key,
                                    rules=rules, routed=route)
    if not route:
        if first:
            _obs_trace.instant("static-flag", site=site, key=key,
                               rules=rules)
        return None
    # Routed: ledger entry (reason "static" — observability, not
    # quarantine), stats counter, and the attribution instant pricing
    # the dispatch-and-fault this prediction avoided.
    from jepsen_tpu.lin import supervise

    if stats is not None:
        util.stat_bump(stats, "static_skips")
    with _lock:
        record = key not in _recorded
        _recorded.add(key)
    if record:
        supervise.record_fault(key, "static",
                               "; ".join(str(f) for f in findings))
    _obs_trace.instant("static-skip", site=site, key=key, rules=rules,
                       est_saved_s=FAULT_RECOVERY_EST_S)
    return StaticallyFlagged(site, key, findings)
