"""Ubuntu OS provisioning — the CockroachDB boxes' variant.

Re-design of `cockroachdb/src/jepsen/os/ubuntu.clj` (40 LoC): the same
apt machinery as :mod:`jepsen_tpu.os_debian` with the cockroach-box
package list (tcpdump for the suite's packet capture, faketime/ntpdate
for the clock nemeses), NTP stopped so the clock nemesis owns the
clock, and the network healed on the way in.
"""

from __future__ import annotations

from jepsen_tpu import control as c
from jepsen_tpu import os_ as os_ns
from jepsen_tpu import os_debian

PACKAGES = ["wget", "curl", "vim", "man-db", "faketime", "unzip",
            "ntpdate", "iptables", "iputils-ping", "rsyslog", "tcpdump",
            "logrotate"]


class UbuntuOS(os_ns.OS):
    """Ubuntu setup: hostfile, packages, stop ntp, heal the net
    (os/ubuntu.clj:13-39)."""

    def setup(self, test, node):
        os_debian.setup_hostfile(test, node)
        os_debian.install(PACKAGES)
        with c.su():
            c.exec_("service", "ntp", "stop", may_fail=True)
        net = test.get("net") if isinstance(test, dict) else None
        if net is not None:
            try:
                net.heal(test)
            except Exception:  # noqa: BLE001 - heal is best-effort here
                pass

    def teardown(self, test, node):
        pass


os = UbuntuOS()
