"""The test runner: coordinates setup, workload, fault injection, history
collection, persistence, and checking.

Re-design of `jepsen/src/jepsen/core.clj` (491 LoC). A test is a plain dict
(schema documented at core.clj:382-403): nodes, concurrency, ssh, os, db,
client, nemesis, generator, model, checker, name...

Lifecycle (core.clj:404-430):

1. OS setup on all nodes; 2. DB cycle (teardown+setup, plus Primary setup);
3. nemesis setup + nemesis thread; 4. one worker thread per logical process,
each driving a client with ops from the generator; 5. log capture;
6. teardown; 7. index the history and run the checker.

Key invariants preserved from the reference:

- Each process is logically single-threaded; an op with indeterminate
  outcome hangs its process forever, so the worker re-incarnates as
  ``process + concurrency`` with a fresh client (core.clj:168-217).
- Op timestamps come from the monotonic relative-time clock
  (util.clj:235-252), so clock nemeses can't corrupt the history.
- The nemesis is a dedicated thread writing to all active histories
  (core.clj:267-309).
"""

from __future__ import annotations

import datetime
import logging
import threading
import traceback
from typing import Any

from jepsen_tpu import checker as checker_ns
from jepsen_tpu import client as client_ns
from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import generator
from jepsen_tpu import history as history_mod
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu import os_ as os_ns
from jepsen_tpu import store
from jepsen_tpu.history import Op
from jepsen_tpu.util import (real_pmap, relative_time_nanos,
                             relative_time_context)

log = logging.getLogger("jepsen.core")


def synchronize(test: dict) -> None:
    """Block until all nodes arrive at the same point (core.clj:36-41)."""
    barrier = test.get("barrier")
    if barrier is not None and barrier != "no-barrier":
        barrier.wait()


def conj_op(test: dict, op: Op) -> Op:
    """Append an op to the test's history (core.clj:43-47). With the
    streaming checker enabled (``JEPSEN_TPU_STREAM=1``), every append
    also feeds the live checker thread — an op enters a check increment
    only once its completion lands here, which is exactly the ``:info``
    contract (an op that may have applied is never checked as absent)."""
    live = test.get("stream-live")
    with test["history-lock"]:
        test["history"].append(op)
        if live is not None:
            # INSIDE the lock: the stream feed must see client events
            # in exactly the recorded history order, or an increment
            # could check against real-time constraints the true
            # history does not have (offer is O(1) — a deque append).
            live.offer(op)
    return op


def primary(test: dict):
    """The primary node = first node (core.clj:49-52)."""
    return test["nodes"][0] if test.get("nodes") else None


def _log_op(op: Op) -> None:
    log.info("%s\t%s\t%s\t%s", op.process, op.type, op.f, op.value)


def setup_primary(test: dict) -> None:
    """Primary-specific DB setup on the first node (core.clj:86-92)."""
    db = test.get("db")
    if isinstance(db, db_ns.Primary) and test.get("nodes"):
        node = primary(test)
        control.on(test, node, lambda: db.setup_primary(test, node))


def snarf_logs(test: dict) -> None:
    """Download DB log files from every node into the store directory
    (core.clj:94-125)."""
    db = test.get("db")
    if not isinstance(db, db_ns.LogFiles):
        return

    def snarf(t, node):
        for remote in db.log_files(t, node) or []:
            local = store.path(t, str(node), remote.lstrip("/"), make=True)
            try:
                control.download(remote, str(local))
            except Exception as e:  # noqa: BLE001 - logs are best-effort
                log.info("couldn't download %s from %s: %s", remote, node, e)

    control.on_nodes(test, snarf)


def invoke_and_complete(node, process, client, test, op):
    """Apply op via the client; append its completion; return the (possibly
    re-incarnated) process and client (core.clj:143-217)."""
    try:
        completion = client.invoke(test, op)
        assert completion is not None and completion.type in \
            ("ok", "fail", "info"), \
            f"Expected invoke to return ok/fail/info, got {completion!r}"
        assert completion.process == op.process
        assert completion.f == op.f
        completion = completion.replace(time=relative_time_nanos())
        _log_op(completion)
        conj_op(test, completion)

        if completion.type in ("ok", "fail"):
            return process, client
        # Indeterminate: this process is done; re-incarnate.
        return _reincarnate(node, process, client, test)
    except Exception as e:  # noqa: BLE001 - synthetic :info completion
        # The op may or may not have been applied: record an :info
        # completion and hang this process (core.clj:185-217).
        info = op.replace(type="info", time=relative_time_nanos(),
                          error=f"indeterminate: {e}")
        conj_op(test, info)
        log.warning("invocation on process %s indeterminate: %s", process, e)
        return _reincarnate(node, process, client, test)


def _reincarnate(node, process, client, test):
    new_process = process + test["concurrency"]
    try:
        client.close(test)
    except Exception:  # noqa: BLE001
        pass
    new_client = test["client"].open(test, node)
    return new_process, new_client


def worker(test: dict, setup_barrier: threading.Barrier, process: int,
           node) -> threading.Thread:
    """One worker thread per initial process (core.clj:219-265)."""

    def run():
        threading.current_thread().name = f"jepsen-worker-{process}"
        ctx_threads = tuple(range(test["concurrency"])) + ("nemesis",)
        with generator.with_threads(ctx_threads):
            _worker_loop(test, setup_barrier, process, node)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def _worker_loop(test, setup_barrier, process, node):
    gen = test.get("generator")
    client = None
    exception = None
    try:
        client = test["client"].open(test, node)
    except Exception as e:  # noqa: BLE001
        # A failed open must not leave the other workers parked on the
        # setup barrier forever: poison it so everyone unblocks.
        exception = e
        log.warning("client open for process %s on %s failed:\n%s",
                    process, node, traceback.format_exc())
        setup_barrier.abort()
    if client is not None:
        try:
            setup_barrier.wait()
            live = test.get("stream-live")
            while True:
                if live is not None and live.should_abort():
                    # Streaming early abort: an increment proved the
                    # history invalid — stop drawing ops; the witness
                    # is already latched (doc/streaming.md).
                    log.warning(
                        "stream checker aborted the run (invalid "
                        "increment); worker %s stops generating",
                        process)
                    break
                op = generator.op_and_validate(gen, test, process)
                if op is None:
                    break
                op = history_mod.op(op).replace(process=process,
                                                time=relative_time_nanos())
                _log_op(op)
                conj_op(test, op)
                process, client = invoke_and_complete(
                    node, process, client, test, op)
        except threading.BrokenBarrierError as e:
            exception = exception or e
        except Exception as e:  # noqa: BLE001
            exception = e
            log.warning("worker for process %s threw:\n%s", process,
                        traceback.format_exc())
        finally:
            # All ops complete before any worker tears down
            # (core.clj:258-261).
            try:
                setup_barrier.wait()
            except threading.BrokenBarrierError:
                pass
            try:
                client.close(test)
            except Exception:  # noqa: BLE001
                pass
    if exception is not None:
        test.setdefault("worker-errors", []).append(exception)


def nemesis_worker(test: dict, nemesis) -> threading.Thread:
    """The nemesis thread: draws fault ops from the generator, applies
    them, and logs invocation+completion into every active history
    (core.clj:267-309)."""

    def run():
        threading.current_thread().name = "jepsen-nemesis"
        ctx_threads = tuple(range(test["concurrency"])) + ("nemesis",)
        with generator.with_threads(ctx_threads):
            while True:
                op = generator.op_and_validate(test.get("generator"), test,
                                               "nemesis")
                if op is None:
                    break
                op = history_mod.op(op).replace(process="nemesis",
                                                time=relative_time_nanos())
                for hist, lock in list(test["active-histories"]):
                    with lock:
                        hist.append(op)
                try:
                    _log_op(op)
                    completion = nemesis.invoke(test, op)
                    completion = completion.replace(
                        time=relative_time_nanos())
                    assert op.type == "info"
                    assert completion.f == op.f
                    assert completion.process == op.process
                    _log_op(completion)
                    for hist, lock in list(test["active-histories"]):
                        with lock:
                            hist.append(completion)
                except Exception as e:  # noqa: BLE001
                    crashed = op.replace(time=relative_time_nanos(),
                                         error=f"crashed: {e!r}")
                    for hist, lock in list(test["active-histories"]):
                        with lock:
                            hist.append(crashed)
                    log.warning("nemesis crashed evaluating %s:\n%s", op,
                                traceback.format_exc())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def run_case(test: dict) -> list[Op]:
    """Spawn nemesis + workers, run the workload, snarf logs, return the
    history (core.clj:331-365)."""
    history: list[Op] = []
    lock = threading.Lock()
    test = dict(test)
    test["history"] = history
    test["history-lock"] = lock
    test["active-histories"].append((history, lock))

    nemesis = (test.get("nemesis") or nemesis_ns.noop).setup(test) \
        or test.get("nemesis") or nemesis_ns.noop
    try:
        # One-time client data setup (client.clj:13-14), before any worker
        # opens per-process connections; torn down after the workload.
        test["client"].setup(test)
        try:
            nem_thread = nemesis_worker(test, nemesis)
            concurrency = test["concurrency"]
            setup_barrier = threading.Barrier(concurrency)
            nodes = test.get("nodes") or []
            client_nodes = ([None] * concurrency if not nodes else
                            [nodes[i % len(nodes)]
                             for i in range(concurrency)])
            workers = [worker(test, setup_barrier, process, node)
                       for process, node in enumerate(client_nodes)]
            for w in workers:
                w.join()
            log.info("waiting for nemesis to complete")
            nem_thread.join()
        finally:
            test["client"].teardown(test)
    finally:
        nemesis.teardown(test)

    snarf_logs(test)
    test["active-histories"].remove((history, lock))
    if test.get("worker-errors"):
        raise test["worker-errors"][0]
    return history


def _open_sessions(test: dict) -> dict:
    """Open all node sessions in parallel; on any failure, close the ones
    that opened and raise (`with-resources`, core.clj:54-75)."""
    nodes = list(test.get("nodes") or [])

    def open_one(node):
        try:
            return node, control.session(test, node)
        except Exception as e:  # noqa: BLE001
            return node, e

    sessions = dict(real_pmap(open_one, nodes))
    errors = {n: s for n, s in sessions.items() if isinstance(s, Exception)}
    if errors:
        for s in sessions.values():
            if not isinstance(s, Exception):
                s.disconnect()
        raise RemoteSetupError(f"couldn't open sessions: {errors}")
    return sessions


class RemoteSetupError(Exception):
    pass


def run(test: dict) -> dict:
    """Run a test (core.clj:381-491). Returns the test dict with :history
    and :results."""
    test = dict(test)
    test.setdefault("start-time", datetime.datetime.now())
    test["concurrency"] = test.get("concurrency") or len(test["nodes"])
    n_nodes = len(test.get("nodes") or [])
    test["barrier"] = threading.Barrier(n_nodes) if n_nodes else "no-barrier"
    test["active-histories"] = []
    test.setdefault("os", os_ns.noop)
    test.setdefault("db", db_ns.noop)
    test.setdefault("client", client_ns.noop)
    test.setdefault("nemesis", nemesis_ns.noop)
    test.setdefault("checker", checker_ns.unbridled_optimism())

    if test.get("name"):
        store.start_logging(test)
    try:
        log.info("Running test: %s", store.serializable_test(test))
        sessions = _open_sessions(test)
        test["sessions"] = sessions
        try:
            # OS setup (core.clj:77-84)
            control.on_nodes(test,
                             lambda t, n: t["os"].setup(t, n))
            try:
                # DB cycle + primary (core.clj:127-141)
                try:
                    control.on_nodes(
                        test, lambda t, n: db_ns.cycle(t["db"], t, n))
                    setup_primary(test)

                    # Streaming incremental checker (env-gated,
                    # JEPSEN_TPU_STREAM* — doc/streaming.md): a live
                    # checker thread fed by conj_op during the run,
                    # with early abort plumbed into the worker loops.
                    from jepsen_tpu.stream import live_checker_for

                    live = live_checker_for(test)
                    if live is not None:
                        test["stream-live"] = live
                    try:
                        with relative_time_context():
                            test["history"] = run_case(test)
                    finally:
                        if live is not None:
                            try:
                                test["stream-results"] = live.finish()
                            except Exception:  # noqa: BLE001 - the
                                # live verdict is an extra, earlier
                                # view; losing it must not lose the
                                # run or the post-hoc check.
                                log.warning("stream checker finalize "
                                            "failed:\n%s",
                                            traceback.format_exc())
                except Exception:
                    snarf_logs(test)  # emergency log dump
                    if test.get("name"):
                        store.update_symlinks(test)
                    raise
                finally:
                    control.on_nodes(
                        test, lambda t, n: t["db"].teardown(t, n))
            finally:
                control.on_nodes(test,
                                 lambda t, n: t["os"].teardown(t, n))
        finally:
            for s in sessions.values():
                s.disconnect()

        log.info("Run complete, writing")
        if test.get("name"):
            store.save_1(test)

        log.info("Analyzing")
        test["history"] = history_mod.index(test["history"])
        test["results"] = checker_ns.check_safe(
            test["checker"], test, test.get("model"), test["history"])
        if test.get("stream-results") is not None:
            # The stream verdict rides NEXT TO the configured checker's
            # (same history, decided earlier — equal by the parity
            # argument in doc/streaming.md); it never overrides it.
            test["results"] = dict(test["results"])
            test["results"]["stream"] = test["stream-results"]
        log.info("Analysis complete")
        if test.get("name"):
            store.save_2(test)
            # Evidence backfill (doc/observability.md § Perf ledger):
            # the run directory always carries its latency/rate/
            # timeline artifacts, whether or not the configured
            # checker composed perf()/timeline — web.py links them
            # from the home and dir pages. Best-effort by contract.
            store.write_run_artifacts(test)
        _log_results(test)
        return test
    finally:
        store.stop_logging()


def _log_results(test: dict) -> None:
    results = test.get("results", {})
    if results.get(checker_ns.VALID) is True:
        log.info("Everything looks good! (valid)")
    else:
        log.info("Analysis invalid! %s", results)
