"""Kitchen-sink utilities.

TPU-native re-design of the reference's ``jepsen/src/jepsen/util.clj`` (686
LoC): parallel map over unbounded workers (util.clj:44-50), majority
(util.clj:57-60), relative-time clock (util.clj:235-252), high-resolution
sleep (util.clj:254-260), timeout (util.clj:275-286), retry
(util.clj:288-327), compact integer-set rendering (util.clj:487-512),
latency extraction (util.clj:557-591) and nemesis intervals
(util.clj:593-610).
"""

from __future__ import annotations

import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor
from fractions import Fraction
from typing import Any, Callable, Iterable, Sequence


def real_pmap(f: Callable, xs: Iterable) -> list:
    """Like map, but with one thread per element (reference util.clj:44-50:
    unbounded futures, used for SSH fan-out to all nodes at once)."""
    xs = list(xs)
    if not xs:
        return []
    with ThreadPoolExecutor(max_workers=len(xs)) as pool:
        return list(pool.map(f, xs))


def majority(n: int) -> int:
    """Given a cluster size, return the smallest majority: 1 for 0 or 1 nodes,
    2 for 3, 3 for 4 or 5 (reference util.clj:57-60)."""
    return max(1, n // 2 + 1)


def fraction(a: int, b: int):
    """a/b, but 1 when b is zero (reference util.clj `fraction`). Returns an
    exact :class:`fractions.Fraction` to mirror Clojure ratios."""
    if b == 0:
        return 1
    f = Fraction(a, b)
    return int(f) if f.denominator == 1 else f


def integer_interval_set_str(xs: Iterable[int]) -> str:
    """Render a set of integers as compact sorted intervals, e.g.
    ``#{1..3 5 7..9}`` (reference util.clj:487-512)."""
    xs = sorted(set(xs))
    parts: list[str] = []
    i = 0
    while i < len(xs):
        j = i
        while j + 1 < len(xs) and xs[j + 1] == xs[j] + 1:
            j += 1
        if j == i:
            parts.append(str(xs[i]))
        elif j == i + 1:
            parts.append(str(xs[i]))
            parts.append(str(xs[j]))
        else:
            parts.append(f"{xs[i]}..{xs[j]}")
        i = j + 1
    return "#{" + " ".join(parts) + "}"


# ---------------------------------------------------------------------------
# Relative time (reference util.clj:235-252). All op :time stamps are
# nanoseconds relative to an anchor established once per test run, so clock
# nemeses that scramble the wall clock cannot corrupt the history's
# timestamps (SURVEY.md §5 last bullet of fault-injection).
# ---------------------------------------------------------------------------

_relative_time_origin: float | None = None
_relative_time_lock = threading.Lock()


class relative_time_context:
    """Context manager anchoring the relative-time clock at entry
    (reference ``with-relative-time``, util.clj:243-247)."""

    def __enter__(self):
        global _relative_time_origin
        with _relative_time_lock:
            _relative_time_origin = _time.monotonic()
        return self

    def __exit__(self, *exc):
        return False


def relative_time_nanos() -> int:
    """Nanoseconds since the relative-time origin (util.clj:249-252). If no
    origin was anchored, anchors one now."""
    global _relative_time_origin
    if _relative_time_origin is None:
        with _relative_time_lock:
            if _relative_time_origin is None:
                _relative_time_origin = _time.monotonic()
    return int((_time.monotonic() - _relative_time_origin) * 1e9)


def sleep_nanos(ns: float) -> None:
    """Sleep for a number of nanoseconds (reference's high-res `sleep`,
    util.clj:254-260 — ops granularity is often sub-millisecond)."""
    if ns > 0:
        _time.sleep(ns / 1e9)


class TimeoutError_(Exception):
    pass


def timeout(seconds: float, f: Callable[[], Any], on_timeout: Any = TimeoutError_):
    """Run f in a worker thread; if it exceeds the deadline return
    ``on_timeout`` (or raise if it is an exception class). The worker is
    abandoned, mirroring the reference's interrupt-based `timeout`
    (util.clj:275-286) as closely as Python threading allows."""
    result: list = []
    err: list = []

    def run():
        try:
            result.append(f())
        except BaseException as e:  # noqa: BLE001 - report through the channel
            err.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(seconds)
    if t.is_alive():
        if isinstance(on_timeout, type) and issubclass(on_timeout, BaseException):
            raise on_timeout(f"timed out after {seconds}s")
        return on_timeout
    if err:
        raise err[0]
    return result[0]


def with_retry(f: Callable[[], Any], retries: int = 5, backoff: float = 0.2,
               exceptions: tuple = (Exception,)):
    """Call f, retrying on failure with linear backoff (reference
    `with-retry`/`retry`, util.clj:288-327)."""
    attempt = 0
    while True:
        try:
            return f()
        except exceptions:
            attempt += 1
            if attempt > retries:
                raise
            _time.sleep(backoff * attempt)


def longest_common_prefix(seqs: Sequence[Sequence]) -> list:
    """Longest prefix shared by all sequences (reference util.clj:612-625)."""
    if not seqs:
        return []
    out = []
    for vals in zip(*seqs):
        if all(v == vals[0] for v in vals[1:]):
            out.append(vals[0])
        else:
            break
    return out


def history_latencies(history) -> list:
    """Annotate invoke ops with :latency (ns between invoke and completion)
    and completion type, like reference util.clj:557-591. Returns a list of
    ``(invoke_op, latency_ns_or_None, completion_type_or_None)``."""
    pending: dict[Any, Any] = {}
    out = []
    for op in history:
        if op.type == "invoke":
            pending[op.process] = op
        elif op.process in pending:
            inv = pending.pop(op.process)
            out.append((inv, (op.time or 0) - (inv.time or 0), op.type))
    for inv in pending.values():
        out.append((inv, None, None))
    return out


def nemesis_intervals(history) -> list[tuple]:
    """Pair up nemesis start/stop ops into [start, stop] op intervals,
    FIFO — first start pairs with first stop, like the reference's
    queue-based pairing (util.clj:593-610)."""
    starts: list = []
    intervals = []
    for op in history:
        if op.process != "nemesis":
            continue
        if op.f == "start":
            starts.append(op)
        elif op.f == "stop" and starts:
            intervals.append((starts.pop(0), op))
    for s in starts:
        intervals.append((s, None))
    return intervals


# ---------------------------------------------------------------------------
# Liveness progress counter (bench probe watchdog).
#
# The device engines tick this at every host-visible step (chunk batch,
# host-row closure dispatch, spike mini-chunk, dense chunk, batched key
# group). A monitoring thread (bench.py probe children) samples it: the
# counter advancing proves dispatches are completing, so a stalled value
# discriminates a WEDGED tunnel dispatch (observed ~25 min on the shared
# chip) from a merely long-running but progressing search. Monotonic,
# process-local, monitoring-grade (GIL-atomic increments; no lock).

_progress = 0


def progress_tick() -> None:
    """Record one unit of engine forward progress (see above)."""
    global _progress
    _progress += 1


def progress() -> int:
    """Current progress counter value (monotonic within a process)."""
    return _progress


def env_int(name: str, default: int) -> int:
    """Integer env knob with a default (empty/unset -> default). The
    engines re-read knobs per check so monkeypatch.setenv and
    ``env VAR=...`` always take effect — doc/env.md tables them all."""
    import os

    v = os.environ.get(name, "")
    return int(v) if v else default


def env_float(name: str, default: float) -> float:
    """Float twin of :func:`env_int`."""
    import os

    v = os.environ.get(name, "")
    return float(v) if v else default


def file_needs_newline_heal(path: str) -> bool:
    """True when an append-only JSONL file's last byte exists and is
    not a newline — a SIGKILL-torn tail that would glue the next
    record onto the torn line and corrupt BOTH. The one crash-recovery
    rule shared by the service journal and the perf ledger (their
    append paths must never drift). Missing/empty files need no
    heal."""
    import os

    try:
        with open(path, "rb") as fh:
            fh.seek(-1, os.SEEK_END)
            return fh.read(1) not in (b"\n", b"")
    except OSError:
        return False


def write_json_atomic(path: str, obj, default=None) -> None:
    """Atomic JSON file write: pid-suffixed tmp + ``os.replace`` (the
    quarantine-ledger / service-stats / txn-snapshot pattern — last
    writer wins, readers never see a torn file). Raises on failure;
    observability-grade callers swallow at their own site."""
    import json
    import os

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(obj, fh, indent=1, sort_keys=True, default=default)
    os.replace(tmp, path)


def stat_bump(stats: dict, key: str, n: int = 1) -> None:
    """Accumulate an integer observability counter in a stats dict
    (host-row executor episode/dispatch/pass/waste counters — see
    bfs._host_rows). Missing keys start at 0, so call sites never need
    setdefault choreography."""
    stats[key] = stats.get(key, 0) + n


def stat_time(stats: dict, key: str, bucket, seconds: float) -> None:
    """Accumulate wall seconds into a per-bucket timing histogram
    ``stats[key][bucket]`` (e.g. per-capacity closure wall time,
    bucket = the cap). Raw float accumulation — round at reporting
    time (round_stats), not per sample."""
    d = stats.setdefault(key, {})
    d[bucket] = d.get(bucket, 0.0) + seconds


def round_stats(stats: dict, ndigits: int = 2) -> dict:
    """Artifact-ready copy of a stats dict: floats rounded recursively
    through ANY depth of nested dicts/lists (the timing histograms, the
    supervise event trip log, the obs registry views), every other
    value preserved as-is. The engines accumulate raw floats so
    precision is not lost sample by sample; verdicts, bench JSON, and
    registry snapshots carry the rounded copy. Tuples come back as
    lists (the copy is JSON-bound anyway)."""

    def rec(v):
        if isinstance(v, dict):
            return {k: rec(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [rec(x) for x in v]
        if isinstance(v, float):
            return round(v, ndigits)
        return v

    return {k: rec(v) for k, v in stats.items()}


# ---------------------------------------------------------------------------
# Process-wide XLA compile meter.
#
# One shared wrap of jax's ``backend_compile`` (a TRUE compile: a
# persistent-cache MISS reaching XLA — cache hits load in milliseconds
# and never reach it). Three consumers used to keep divergent private
# copies counting the same thing: tests/conftest.py's quick-tier
# no-compile enforcement, the checker daemon's service stats, and now
# the obs metrics registry. ``add_compile_hook`` lets the flight
# recorder (jepsen_tpu.obs.trace) record each compile as a trace event
# without util importing obs (the hook is registered from obs side).

_compile_meter = {"installed": False, "n": 0, "seconds": 0.0,
                  "gets": 0, "gets_wrapped": False}
_compile_hooks: list = []


def add_compile_hook(fn) -> None:
    """Register ``fn(t0_monotonic, dur_s)`` to run after every true
    XLA compile (exceptions swallowed — hooks are observability)."""
    if fn not in _compile_hooks:
        _compile_hooks.append(fn)


def install_compile_meter() -> bool:
    """Idempotently wrap ``jax._src.compiler.backend_compile`` with the
    count/seconds meter. Returns False on jax version skew (the meter
    then reads zeros — consumers degrade, never crash)."""
    import time

    if _compile_meter["installed"]:
        return True
    try:
        import jax._src.compiler as _jc

        real = _jc.backend_compile
    except (ImportError, AttributeError):  # pragma: no cover - jax skew
        return False
    _compile_meter["installed"] = True

    def metered(*a, **kw):
        t0 = time.monotonic()
        try:
            return real(*a, **kw)
        finally:
            dur = time.monotonic() - t0
            _compile_meter["n"] += 1
            _compile_meter["seconds"] += dur
            for fn in list(_compile_hooks):
                try:
                    fn(t0, dur)
                except Exception:  # noqa: BLE001 - observability hook
                    pass

    _jc.backend_compile = metered
    # Best-effort cache-hit meter: calls that resolve without reaching
    # backend_compile are persistent-cache hits. Module-attr patching
    # only sees call sites that resolve the name at call time, so this
    # can undercount — compile_meter() reports None rather than a
    # negative when the evidence is inconsistent.
    try:
        real_get = _jc.compile_or_get_cached

        def counted_get(*a, **kw):
            _compile_meter["gets"] += 1
            return real_get(*a, **kw)

        _jc.compile_or_get_cached = counted_get
        _compile_meter["gets_wrapped"] = True
    except AttributeError:  # pragma: no cover - jax skew
        pass
    return True


def compile_meter() -> dict:
    """Snapshot of the process-wide XLA compile meter (zeros when the
    wrap never installed)."""
    n = _compile_meter["n"]
    hits = None
    if _compile_meter["gets_wrapped"] and _compile_meter["gets"] >= n:
        hits = _compile_meter["gets"] - n
    return {"xla_compiles": n,
            "xla_compile_s": round(_compile_meter["seconds"], 2),
            "xla_cache_hits": hits}


def get_shard_map():
    """The ``shard_map`` entry point across jax versions: newer builds
    export ``jax.shard_map`` (kwarg ``check_vma``); this image's jax
    (0.4.x) only has ``jax.experimental.shard_map.shard_map`` (the
    same knob spelled ``check_rep``). One shim — callers pass
    ``check_vma`` and the old-jax path renames it — so the mesh
    engines (lin/sharded.py, lin/sharded_dense.py) and their tests run
    on BOTH; before this, every sharded test was driver-env-only (the
    standing ROADMAP caveat)."""
    import functools

    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map as fn_exp

    @functools.wraps(fn_exp)
    def shim(f, *args, check_vma=None, **kw):
        if check_vma is not None:
            kw.setdefault("check_rep", check_vma)
        return fn_exp(f, *args, **kw)

    return shim


def axis_size(axis):
    """``lax.axis_size`` across jax versions (absent in 0.4.x): the
    fallback counts the axis with a psum of ones — a traced scalar,
    which every mesh-engine use (capacity products, overflow tests)
    accepts."""
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    import jax.numpy as jnp

    return lax.psum(jnp.int32(1), axis)


def cache_dir() -> str:
    """``<repo>/.jax_cache`` — the one anchor for every on-disk
    artifact (compile cache, quarantine ledger, service stats, trace
    spills, telemetry snapshots). Not created here; writers makedirs
    on first use."""
    import os

    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache")


def enable_compile_cache(path: str | None = None) -> str | None:
    """Enable JAX's persistent compilation cache rooted in the repo.

    The linearizability engines compile one program per (cap, window,
    state-bucket) shape; each costs tens of seconds of XLA time on first
    use and is bit-identical across processes. The reference has no
    analogue (the JVM JITs per run); here the cache turns every cold
    start after the first into a warm one — bench, CLI, tests, and the
    driver's compile checks all share it. Safe to call multiple times;
    returns the cache dir, or None if the config is unavailable.
    """
    import os

    import jax

    if path is None:
        path = os.environ.get("JEPSEN_TPU_JAX_CACHE") or cache_dir()
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
    except Exception:  # pragma: no cover - older jax without the knobs
        return None
    return path
