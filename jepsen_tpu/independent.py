"""Lifting single-key tests to maps of independent keys.

Re-design of `jepsen/src/jepsen/independent.clj` (296 LoC): expensive
checkers (linearizability) need short histories, so a test of one register
is lifted to a *map* of keys to registers (independent.clj:2-7). The
generator side shards worker threads into per-key groups
(independent.clj:65-219); the checker side partitions the history into
per-key subhistories and checks each (independent.clj:246-296).

The TPU twist: per-key subhistories are a *batch axis*. `checker` runs the
device path by packing every key's subhistory into one stacked array set
and vmapping the BFS frontier search over keys
(:mod:`jepsen_tpu.lin.batched`) — thousands of independent searches in one
device program — falling back to per-key host checking for models without
kernels.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, NamedTuple

from jepsen_tpu import checker as checker_ns
from jepsen_tpu import generator as gen
from jepsen_tpu import history as history_mod
from jepsen_tpu.history import Op

DIR = "independent"


class KV(NamedTuple):
    """A key-value tuple marking an op as belonging to an independent key
    (independent.clj:20-28)."""

    key: object
    value: object


def tuple_(k, v) -> KV:
    return KV(k, v)


def is_tuple(v) -> bool:
    return isinstance(v, KV) or (isinstance(v, (list, tuple))
                                 and len(v) == 2
                                 and getattr(v, "_is_kv", False))


def sequential_generator(keys: Iterable, fgen: Callable) -> gen.Generator:
    """Work through keys one at a time: build (fgen k), drain it (wrapping
    each op value in a [k v] tuple), move to the next key
    (independent.clj:30-63)."""
    it = iter(keys)
    state: dict = {"key": None, "gen": None, "done": False}
    lock = threading.Lock()

    def advance():
        try:
            k = next(it)
            state["key"], state["gen"] = k, fgen(k)
        except StopIteration:
            state["done"] = True

    def go(test, process):
        while True:
            with lock:
                if state["done"]:
                    return None
                if state["gen"] is None:
                    advance()
                    continue
                k, g = state["key"], state["gen"]
            o = gen.op(g, test, process)
            if o is not None:
                return o.replace(value=KV(k, o.value))
            with lock:
                if state["gen"] is g:
                    advance()

    return gen.gen(go)


def concurrent_generator(n: int, keys: Iterable,
                         fgen: Callable) -> gen.Generator:
    """Run independent keys concurrently with n threads per key
    (independent.clj:65-219): worker threads split into contiguous groups
    of n; each group drives one key's generator (with the thread set
    rebound so barriers work per-key); exhausted groups pull the next key.
    Nemesis ops never enter subgenerators."""
    if not (isinstance(n, int) and n > 0):
        raise ValueError("threads-per-key must be a positive integer")
    key_iter = iter(keys)
    state: dict = {"init": False, "active": [], "group_threads": []}
    lock = threading.Lock()

    def next_key():
        try:
            k = next(key_iter)
            return [k, fgen(k)]
        except StopIteration:
            return None

    def initialize(test):
        threads = [t for t in gen.current_threads() if isinstance(t, int)]
        thread_count = len(threads)
        if sorted(threads) != list(range(thread_count)):
            raise AssertionError(
                f"expected integer threads 0..{thread_count - 1}, "
                f"got {threads}")
        if test["concurrency"] != thread_count:
            raise AssertionError(
                f"Expected test concurrency ({test['concurrency']}) to be "
                f"equal to number of integer threads ({thread_count})")
        group_count = thread_count // n
        if n > thread_count:
            raise AssertionError(
                f"With {thread_count} worker threads, this "
                f"concurrent-generator cannot run a key with {n} threads "
                f"concurrently. Consider raising your test's concurrency "
                f"to at least {n}.")
        if thread_count != n * group_count:
            raise AssertionError(
                f"This concurrent-generator has {thread_count} threads to "
                f"work with, but can only use {n * group_count} of those "
                f"threads to run {group_count} concurrent keys with {n} "
                f"threads apiece. Consider raising or lowering the test's "
                f"concurrency to a multiple of {n}.")
        state["active"] = [next_key() for _ in range(group_count)]
        state["group_threads"] = [
            tuple(sorted(threads)[i * n:(i + 1) * n])
            for i in range(group_count)]
        state["init"] = True

    def go(test, process):
        with lock:
            if not state["init"]:
                initialize(test)
        thread = gen.process_to_thread(test, process)
        if not isinstance(thread, int):
            raise AssertionError(
                "Only worker threads with numeric ids can ask for "
                f"operations from concurrent-generator, but we received a "
                f"request from {thread!r}.")
        group = thread // n
        while True:
            with lock:
                pair = state["active"][group]
            if pair is None:
                return None
            k, g = pair
            with gen.with_threads(state["group_threads"][group]):
                o = gen.op(g, test, process)
            if o is not None:
                # The generator protocol admits plain dicts as ops
                # (generator.clj:25-38); normalize before tupling.
                o = history_mod.op(o)
                return o.replace(value=KV(k, o.value))
            with lock:
                if state["active"][group] is pair:
                    state["active"][group] = next_key()

    return gen.gen(go)


def history_keys(history) -> set:
    """The set of independent keys in a history (independent.clj:221-231)."""
    return {op.value.key for op in history if isinstance(op.value, KV)}


def subhistory(k, history) -> list[Op]:
    """Ops for key k (tuples unwrapped) plus every un-keyed op — nemesis
    ops appear in every subhistory (independent.clj:233-244)."""
    out = []
    for op in history:
        v = op.value
        if not isinstance(v, KV):
            out.append(op)
        elif v.key == k:
            out.append(op.replace(value=v.value))
    return out


def checker(inner: checker_ns.Checker,
            batch_device: bool = True) -> checker_ns.Checker:
    """Lift a checker over values to a checker over [k v] histories
    (independent.clj:246-296): valid iff the inner checker is valid for
    every key's subhistory. Results per key under "results"; invalid keys
    under "failures".

    When the inner checker is device linearizability and every subhistory
    packs onto the device, all keys are checked in ONE vmapped search
    (jepsen_tpu.lin.batched) instead of key-at-a-time.
    """

    def check(test, model, history, opts):
        ks = sorted(history_keys(history), key=repr)
        subs = {k: subhistory(k, history) for k in ks}
        opts = opts or {}

        results: dict = {}
        batched = None
        # The batched device search may only stand in for a checker that IS
        # device linearizability — substituting it for an arbitrary lifted
        # checker would silently skip that checker's semantics.
        inner_is_lin = getattr(inner, "is_linearizable", False) and \
            getattr(inner, "algorithm", None) in ("tpu", "competition")
        if batch_device and inner_is_lin and model is not None:
            from jepsen_tpu.lin import batched as batched_mod

            batched = batched_mod.try_check_batch(model, subs)
        # The batch may cover a subset (homogeneous groups batch; odd
        # keys fall back per key below).
        results = dict(batched or {})
        for k in ks:
            if k in results:
                continue
            sub_opts = {**opts,
                        "subdirectory": _subdir(opts, k),
                        "history-key": k}
            results[k] = checker_ns.check_safe(
                inner, test, model, subs[k], sub_opts)

        _write_artifacts(test, opts, subs, results)
        failures = [k for k in ks
                    if results[k].get(checker_ns.VALID) is not True]
        return {checker_ns.VALID:
                checker_ns.merge_valid(
                    [results[k].get(checker_ns.VALID) for k in ks])
                if ks else True,
                "results": results,
                "failures": failures,
                # Visibility into whether the vmapped device batch
                # engaged or the per-key fallback ran (round-1 review:
                # the silent fallback was unmeasurable).
                "batch-engaged": batched is not None,
                "batch-keys": len(batched or {}),
                "n-keys": len(ks)}

    return checker_ns.FnChecker(check)


def _subdir(opts, k):
    sub = opts.get("subdirectory")
    parts = [sub] if isinstance(sub, str) else list(sub or [])
    return parts + [DIR, str(k)]


def _write_artifacts(test, opts, subs, results):
    """Per-key results + history files (independent.clj:274-282)."""
    if not (isinstance(test, dict) and test.get("name")):
        return
    try:
        import json

        from jepsen_tpu import history as history_mod
        from jepsen_tpu import store

        for k, sub in subs.items():
            subdir = _subdir(opts or {}, k)
            rpath = store.path(test, *subdir, "results.json", make=True)
            with open(rpath, "w") as fh:
                json.dump(results.get(k), fh, default=repr, indent=2)
            history_mod.write_history(
                store.path(test, *subdir, "history.jsonl", make=True), sub)
    except Exception:  # noqa: BLE001 - artifacts are best-effort
        pass
