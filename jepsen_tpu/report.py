"""Redirect analysis output to a report file.

The analogue of `jepsen/src/jepsen/report.clj` (16 LoC): ``to`` is a
context manager that tees stdout to a file in the test's store directory
(report.clj:7-16), so ad-hoc analysis printed at the REPL lands next to
the run's other artifacts.
"""

from __future__ import annotations

import contextlib
import io
import sys
from pathlib import Path


class _Tee(io.TextIOBase):
    def __init__(self, *streams):
        self.streams = streams

    def write(self, s):
        for st in self.streams:
            st.write(s)
        return len(s)

    def flush(self):
        for st in self.streams:
            st.flush()


@contextlib.contextmanager
def to(path, echo: bool = True):
    """Within the block, stdout is copied to ``path`` (report.clj:7-16).
    With ``echo=False`` output goes only to the file."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as fh:
        tee = _Tee(fh, sys.stdout) if echo else fh
        old = sys.stdout
        sys.stdout = tee
        try:
            yield path
        finally:
            sys.stdout = old
