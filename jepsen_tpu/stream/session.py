"""StreamChecker: the carried-frontier incremental checking session.

One session = one history being checked WHILE it is produced. The
session owns an :class:`jepsen_tpu.stream.incr.IncrementalPacker` and
the sparse-engine frontier between increments — the multiword
``bits``/``state``/``count`` arrays of the PR 5 chunk-kind checkpoint
codec, held in memory (and, with a checkpoint path, on disk, so a
killed session resumes mid-stream). Each increment is ONE call to
``lin.device_check_packed(packed, frontier=, frontier_row=, partial=)``:
the engine re-enters at the carried row exactly like the proven
checkpoint-resume path, walks only the NEW settled rows, and hands the
committed frontier back.

Soundness is inherited, not re-argued: the carried frontier is an exact
committed frontier at a row boundary (the same invariant PR 5's resume
rests on), the settled-row tables are final when packed (incr.py), and
at finalize the packed tables are bit-identical to the one-shot pack —
so the streamed verdict, death row, and final-paths provably equal the
post-hoc check (parity-fuzzed in tests/test_stream.py).

Increment dispatches run SUPERVISED under the ``stream-incr`` site
(:func:`jepsen_tpu.lin.supervise.run_guarded`: watchdog deadline,
fault taxonomy, quarantine-ledger recording) and TRACED (one
``stream-incr`` span per increment). A wedged/faulted/overflowed
increment DEGRADES the session — incremental checking stops, and
finalize runs one exact post-hoc check instead — it never corrupts the
verdict and never hangs the producer.

**Early abort.** The moment an increment returns ``valid? False`` the
session latches the witness verdict; ``aborted`` flips, the
``on_abort`` hook fires, and a ``stream-abort`` event lands in the obs
feed — the producer (core.py's generator loop, a wire client) learns
within one increment of the offending completion instead of at the end
of the run.

The ``stream`` metrics view (:mod:`jepsen_tpu.obs.metrics`) carries
ops-ingested vs rows-checked lag, per-increment wall time, and abort
state — rendered by ``web.py /run`` and snapshotted like every other
view. Knobs in doc/env.md § Streaming; lifecycle in doc/streaming.md.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable

import numpy as np

from jepsen_tpu import util
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs import trace as obs_trace
from jepsen_tpu.stream.incr import IncrementalPacker

# kind tag of stream checkpoints (supervise.Checkpointer codec).
CKPT_KIND = "stream"


def default_min_rows() -> int:
    """Settled rows buffered before an increment dispatches: smaller =
    lower abort latency, larger = better row-loop amortization (each
    increment pays fixed packing + dispatch entry costs)."""
    return util.env_int("JEPSEN_TPU_STREAM_ROWS", 256)


def stream_ckpt_path() -> str | None:
    return os.environ.get("JEPSEN_TPU_STREAM_CKPT", "") or None


class StreamChecker:
    """Open → ``append``\\ ×N → ``finalize`` (or ``abort``).

    ``append`` takes an iterable of history events (:class:`Op`) —
    invocations AND completions, in history order; an op only enters an
    increment once its completion is recorded (the packer's settled-row
    rule enforces the ``:info`` contract structurally). ``finalize``
    settles everything and returns the full-history verdict with the
    session's ``stream`` stats attached.

    Not thread-safe by itself — one producer at a time (the live run
    wrapper :class:`jepsen_tpu.stream.runner.LiveChecker` and the
    service daemon each serialize access).
    """

    def __init__(self, model, *, min_rows: int | None = None,
                 checkpoint: str | None = None, explain: bool = True,
                 check_kw: dict | None = None,
                 on_abort: Callable[[dict], None] | None = None,
                 view_name: str = "stream", defer: bool = False):
        self.model = model
        self.packer = IncrementalPacker(model)
        self.min_rows = min_rows if min_rows is not None \
            else default_min_rows()
        # defer=True (the daemon's svc-stream bins): append() settles
        # but never dispatches increments — the owner collects
        # increment_job()s across sessions, batches them into one
        # vmapped program, and commits each lane via
        # commit_increment() (or falls back per-session via drive()).
        self.defer = defer
        self.explain = explain
        self.check_kw = dict(check_kw or {})
        self.on_abort = on_abort
        self.ckpt_path = checkpoint if checkpoint is not None \
            else stream_ckpt_path()
        self._ckpt = None
        self._tried_resume = False

        self._frontier = None          # (bits u32[n,nw], state i32[n,S])
        self._count = 0
        self._row = 0                  # rows checked (frontier row)
        self._verdict: dict | None = None   # latched definite False
        self._degraded: str | None = None
        self._final: dict | None = None
        self._t0 = time.monotonic()
        self.stats: dict = {
            "mode": "incremental" if self.packer.incremental
            else "buffer", "ops_ingested": 0, "ops_pending": 0,
            "rows_settled": 0, "rows_checked": 0, "lag_rows": 0,
            "increments": 0, "increment_s": 0.0, "aborted": False}
        # One registry view per session name: concurrent daemon
        # sessions register under per-sid names (release_view() on
        # close so the registry does not accumulate dead sessions);
        # in-process/live-run sessions keep the canonical "stream"
        # name web.py /run renders with its lag gauge.
        self.view_name = view_name
        obs_metrics.REGISTRY.view(view_name, self.stats)

    def release_view(self) -> None:
        """Swap this session's registry view for an empty dict (empty
        views are skipped by snapshots) — called when a daemon session
        closes so per-sid views do not leak."""
        obs_metrics.REGISTRY.view(self.view_name, {})

    # --- state --------------------------------------------------------------

    @property
    def aborted(self) -> bool:
        """True once an increment returned a definite INVALID verdict
        (the early-abort latch the generator loop polls)."""
        return self._verdict is not None \
            and self._verdict.get("valid?") is False

    @property
    def verdict(self) -> dict | None:
        """The latched abort verdict (None while the stream is clean)."""
        return self._verdict

    def status(self) -> dict:
        return {"row": self._row, "settled": self.packer.R,
                "ops": self.stats["ops_ingested"],
                "pending": self.packer.unresolved,
                "aborted": self.aborted, "degraded": self._degraded,
                "frontier": self._count}

    # --- producing ----------------------------------------------------------

    def append(self, events) -> dict:
        """Feed events; advance the checker when enough rows settled.
        Returns :meth:`status` (carrying the latched witness verdict
        under ``"result"`` once aborted)."""
        if self._final is not None:
            raise RuntimeError("stream session already finalized")
        n = self.packer.feed_many(events)
        self.stats["ops_ingested"] += n
        self._advance(final=False)
        out = self.status()
        if self._verdict is not None:
            out["result"] = self._verdict
        return out

    def finalize(self) -> dict:
        """Settle everything, run the last increment (or the post-hoc
        fallback), and return the full-history verdict. Idempotent."""
        if self._final is not None:
            return self._final
        if self._verdict is not None:
            out = dict(self._verdict)
        elif not self.packer.incremental:
            out = self._posthoc_check(
                f"unpackable event: {self.packer.broken}"
                if self.packer.broken else "buffer mode")
        else:
            self._advance(final=True)
            if self._verdict is not None:
                out = dict(self._verdict)
            elif self._degraded is not None:
                out = self._posthoc_check(self._degraded)
            else:
                # Every settled row checked clean.
                out = {"valid?": True, "analyzer": "tpu-bfs-stream",
                       "configs": [],
                       "final-frontier-size": int(self._count)}
        out["stream"] = self._stream_summary()
        self._final = out
        if self._ckpt is not None and out.get("valid?") in (True, False):
            self._ckpt.clear()
        return out

    def abort(self) -> None:
        """Producer-side cancel: drop the session state (no verdict)."""
        if self._final is None:
            self._final = {"valid?": "unknown",
                           "analyzer": "tpu-bfs-stream",
                           "error": "stream aborted by producer",
                           "stream": self._stream_summary()}

    # --- the increment loop -------------------------------------------------

    def _advance(self, final: bool) -> None:
        from jepsen_tpu.lin.prepare import UnsupportedHistory

        try:
            self.packer.settle(final=final)
        except UnsupportedHistory as e:
            self._degrade(f"settle: {e}")
            return
        self.stats["rows_settled"] = self.packer.R
        self.stats["ops_pending"] = self.packer.unresolved
        self.stats["lag_rows"] = self.packer.R - self._row
        if self.packer.broken and self.stats.get("mode") != "buffer":
            # Feed-time downgrade (incr.feed docstring): keep buffering,
            # stop incrementing, surface the reason.
            self.stats["mode"] = "buffer"
            self.stats["degraded"] = \
                f"unpackable event: {self.packer.broken}"[:200]
        if not self.packer.incremental or self._degraded is not None \
                or self._verdict is not None:
            return
        if not self._maybe_resume(final):
            return   # resume decision pending: settle only, check later
        if self.defer and not final:
            return   # deferred: the owner batches/drives increments
        self._run_increments(final)
        obs_metrics.REGISTRY.write_snapshot()

    def _run_increments(self, final: bool) -> None:
        while self._verdict is None and self._degraded is None:
            todo = self.packer.R - self._row
            if todo <= 0 or (not final and todo < self.min_rows):
                break
            self._increment()

    def increment_job(self) -> dict | None:
        """The pending increment as DATA (deferred sessions): packed
        tables, start row, carried frontier — what
        ``lin.batched.try_stream_batch`` needs to run this session's
        increment as one lane of a shared vmapped program. None when
        nothing is pending (or the session cannot increment). The
        session state is NOT advanced — the caller commits the lane's
        result via :meth:`commit_increment`, or runs :meth:`drive`."""
        if self._final is not None or self._verdict is not None \
                or self._degraded is not None \
                or not self.packer.incremental:
            return None
        if not self._maybe_resume(False):
            return None
        todo = self.packer.R - self._row
        if todo <= 0 or todo < self.min_rows:
            return None
        p = self.packer.packed()
        if p.kernel is None:
            self._degrade("no device kernel")
            return None
        return {"packed": p, "row0": self._row,
                "rows": p.R - self._row,
                "frontier": self._frontier_arg(), "checker": self}

    def drive(self) -> dict:
        """Run any pending increments NOW on the calling thread (the
        deferred session's solo path: single-session flushes and
        batch-declined lanes fall back here — same supervised
        ``stream-incr`` dispatch as a non-deferred session). Returns
        :meth:`status` (plus the latched verdict under ``result``)."""
        if self._final is None and self.packer.incremental \
                and self._verdict is None and self._degraded is None \
                and self._maybe_resume(False):
            self._run_increments(False)
            obs_metrics.REGISTRY.write_snapshot()
        out = self.status()
        if self._verdict is not None:
            out["result"] = self._verdict
        return out

    def _increment(self) -> None:
        from jepsen_tpu import lin
        from jepsen_tpu.lin import supervise

        p = self.packer.packed()
        if p.kernel is None:
            self._degrade("no device kernel")
            return
        row0, rows = self._row, p.R - self._row
        kname = p.kernel.name
        key = supervise.shape_key("stream-incr", rows=rows,
                                  cap=self._count or 1,
                                  window=int(p.window), kernel=kname)
        cancel = threading.Event()

        def thunk():
            kw = dict(self.check_kw)
            kw.setdefault("explain", self.explain)
            return lin.device_check_packed(
                p, cancel=cancel, frontier=self._frontier_arg(),
                frontier_row=row0, partial=True, **kw)

        t0 = time.monotonic()
        with obs_trace.span("stream-incr", row0=row0, rows=rows,
                            window=int(p.window)) as sp:
            # The watchdog deadline scales with the increment (rows /
            # CHUNK dispatches, each owed a base deadline) — a healthy
            # long increment must not false-trip, a wedged one must
            # cost its detection window, not the producer.
            outcome, r = supervise.run_guarded(
                "stream-incr", key, thunk,
                scale=max(3.0, rows / 512), stats=self.stats)
            if outcome != "ok":
                cancel.set()   # stop the abandoned increment's chunks
                sp.note(outcome=outcome)
                self._degrade(f"increment {outcome} at row {row0}: {r}")
                return
            sp.note(verdict=str(r.get("valid?")))
        self.commit_increment(r, row0=row0,
                              dt=time.monotonic() - t0)

    def commit_increment(self, r: dict, *, row0: int,
                         dt: float) -> None:
        """Adopt one increment result — from the solo dispatch above
        or from one LANE of a shared vmapped stream-batch program
        (the daemon's svc-stream bins). Latches the early-abort
        verdict, degrades on undecided, else carries the committed
        frontier forward and checkpoints."""
        self.stats["increments"] += 1
        self.stats["increment_s"] = round(
            self.stats["increment_s"] + dt, 4)
        v = r.get("valid?")
        if v is False:
            self._abort_with(r)
            return
        if v is not True or "stream-frontier" not in r:
            self._degrade(f"increment undecided at row {row0}: "
                          f"{r.get('error', r.get('overflow', v))!r}")
            return
        sf = r["stream-frontier"]
        self._frontier = (np.asarray(sf["bits"], np.uint32),
                          np.asarray(sf["state"], np.int32))
        self._count = int(sf["count"])
        self._row = int(sf["row"])
        self.stats["rows_checked"] = self._row
        self.stats["lag_rows"] = self.packer.R - self._row
        self.stats["frontier"] = self._count
        obs_metrics.REGISTRY.progress(row=self._row,
                                      frontier=self._count)
        self._save_ckpt()

    def _frontier_arg(self):
        if self._frontier is None:
            return None
        return (self._frontier[0], self._frontier[1], self._count)

    def _abort_with(self, r: dict) -> None:
        self._verdict = dict(r)
        self.stats["aborted"] = True
        self.stats["aborted_row"] = r.get("dead-row")
        self.stats["rows_checked"] = self._row
        obs_metrics.REGISTRY.event("stream-abort",
                                   row=r.get("dead-row"),
                                   op=str((r.get("op") or {}).get("f")))
        obs_metrics.REGISTRY.write_snapshot(force=True)
        if self.on_abort is not None:
            try:
                self.on_abort(self._verdict)
            except Exception:  # noqa: BLE001 - observer must not
                pass           # poison the verdict

    def _degrade(self, reason: str) -> None:
        """Incremental checking is an OPTIMIZATION of the post-hoc
        check; anything it cannot decide exactly (wedge, fault,
        capacity, unpackable tail) hands the whole verdict back to the
        one-shot path at finalize. Never guess, never hang."""
        self._degraded = reason
        self._frontier = None
        self.stats["degraded"] = reason[:200]
        obs_metrics.REGISTRY.event("stream-degrade", reason=reason[:120])

    def _posthoc_check(self, why: str) -> dict:
        from jepsen_tpu import lin

        out = dict(lin.analysis(self.model, list(self.packer.history),
                                explain=self.explain))
        out["stream-fallback"] = why
        return out

    # --- checkpoint / resume ------------------------------------------------

    def _checkpointer(self):
        from jepsen_tpu.lin import supervise

        if self._ckpt is None and self.ckpt_path:
            self._ckpt = supervise.Checkpointer(self.ckpt_path, "",
                                                every_s=0.0)
        return self._ckpt

    def _save_ckpt(self) -> None:
        ck = self._checkpointer()
        if ck is None or self._frontier is None:
            return
        # The fingerprint is the settled-prefix identity at THIS row —
        # recomputable by any session fed the same events, wherever its
        # increment boundaries fall.
        ck.fingerprint = self.packer.prefix_fingerprint(self._row)
        n = max(self._count, 1)
        ck.save(CKPT_KIND, self._row, self._count,
                {"bits": self._frontier[0][:n],
                 "state": self._frontier[1][:n]},
                {"kernel": self.packer.kernel.name})

    def _maybe_resume(self, final: bool = False) -> bool:
        """First advances of a session with a checkpoint path: adopt a
        prior session's frontier when its settled-prefix fingerprint
        matches ours at the checkpointed row (same client events in the
        same order — anything else is rejected and checking starts at
        row 0, degraded to a fresh-but-correct run). Returns False
        while the decision is PENDING (the checkpoint row lies past the
        settled prefix — checking must hold off, or the session would
        re-check from row 0 and orphan the resume); at ``final`` a
        still-unreachable checkpoint row is rejected for good."""
        if self._tried_resume or not self.ckpt_path or self._row:
            return True
        status, rd = self._load_ckpt()
        if status == "wait" and not final:
            return False   # not settled as far as the checkpoint row
        self._tried_resume = True
        if rd is None:
            return True
        self._frontier = (np.asarray(rd["bits"], np.uint32),
                          np.asarray(rd["state"], np.int32))
        self._count = int(rd["count"])
        self._row = int(rd["row"])
        self.stats["rows_checked"] = self._row
        self.stats["resumed_from_row"] = self._row
        return True

    def _load_ckpt(self) -> tuple[str, dict | None]:
        """("ok", payload) | ("none", None) — reject, stop looking |
        ("wait", None) — the checkpoint row lies past our settled
        prefix, so the fingerprint cannot be judged yet (the next
        settle retries)."""
        from jepsen_tpu.lin import supervise

        path = self.ckpt_path
        if not path or not os.path.exists(path):
            return "none", None
        try:
            with np.load(path) as z:
                meta = json.loads(bytes(z["__meta__"]).decode())
                if meta.get("version") != supervise.CKPT_VERSION \
                        or meta.get("kind") != CKPT_KIND:
                    return "none", None
                row = int(meta["row"])
                if row > self.packer.R:
                    return "wait", None
                if meta.get("fingerprint") != \
                        self.packer.prefix_fingerprint(row):
                    return "none", None
                return "ok", {"bits": z["bits"], "state": z["state"],
                              "row": row, "count": int(meta["count"])}
        except Exception:  # noqa: BLE001 - damage means no checkpoint
            return "none", None

    # --- reporting ----------------------------------------------------------

    def _stream_summary(self) -> dict:
        out = dict(self.stats)
        out["wall_s"] = round(time.monotonic() - self._t0, 3)
        return util.round_stats(out)
