"""`make stream-smoke`: open -> append xN -> finalize, twice over.

A FRESH-process, chip-free proof (forced CPU mesh, like serve-smoke)
that streaming incremental checking round-trips real verdicts both
IN-PROCESS and OVER THE WIRE:

1. In-process: a register history streamed through
   :class:`jepsen_tpu.stream.StreamChecker` in increments decides with
   verdict parity vs the CPU oracle; its corrupted twin ABORTS the
   stream mid-feed with the witness latched.
2. Wire: the same open -> append xN -> finalize lifecycle through an
   ephemeral-port daemon session (``stream-open``/``stream-append``/
   ``stream-finalize`` frames), verdict parity again, clean shutdown.

Prints one JSON result line and exits 0/1 — timeout-guarded by the
Makefile so a wedge cannot hold the shell.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    import time

    t_start = time.time()
    # CPU mesh BEFORE any jax backend init (CLAUDE.md: the TPU plugin
    # force-selects its platform; the smoke must never take the chip).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from jepsen_tpu import models as m
    from jepsen_tpu import util
    from jepsen_tpu.lin import cpu, prepare, synth
    from jepsen_tpu.service.daemon import CheckerService
    from jepsen_tpu.service.protocol import CheckerClient
    from jepsen_tpu.stream import StreamChecker

    util.enable_compile_cache()
    out: dict = {"checks": []}
    ok = True

    h = list(synth.generate_register_history(
        300, concurrency=5, seed=11, value_range=5, crash_prob=0.01,
        max_crashes=3))
    bad = list(synth.corrupt_history(
        synth.generate_register_history(300, concurrency=5, seed=11,
                                        value_range=5), seed=3))
    want_ok = cpu.check_packed(
        prepare.prepare(m.cas_register(), list(h)))["valid?"]
    want_bad = cpu.check_packed(
        prepare.prepare(m.cas_register(), list(bad)))["valid?"]
    step = max(1, len(h) // 5)

    # --- in-process ---------------------------------------------------------
    sc = StreamChecker(m.cas_register(), min_rows=16)
    for i in range(0, len(h), step):
        sc.append(h[i:i + step])
    r = sc.finalize()
    rec = {"leg": "in-process", "want": want_ok,
           "got": r.get("valid?"),
           "increments": (r.get("stream") or {}).get("increments")}
    out["checks"].append(rec)
    ok = ok and r.get("valid?") == want_ok

    sc2 = StreamChecker(m.cas_register(), min_rows=16)
    fed = len(bad)
    for i in range(0, len(bad), step):
        sc2.append(bad[i:i + step])
        if sc2.aborted:
            fed = i + step
            break
    r2 = sc2.finalize()
    rec = {"leg": "in-process-abort", "want": want_bad,
           "got": r2.get("valid?"), "aborted_early": fed < len(bad),
           "ops_unfed": len(bad) - fed}
    out["checks"].append(rec)
    ok = ok and r2.get("valid?") == want_bad and fed < len(bad)

    # --- increment scaling (packer-only, chip-free) -------------------------
    # Acceptance gate for the vectorized settle (doc/streaming.md): the
    # per-increment pack wall must stay ~flat as the settled prefix
    # grows — late increments no worse than ~early ones. The spec loop
    # (JEPSEN_TPU_FAST_PACK=0) re-concatenates and re-scans the prefix,
    # so only the default vec mode is held to the bound.
    from jepsen_tpu.stream import IncrementalPacker

    prepare.reset_pack_stats()
    hs = list(synth.generate_register_history(
        40000, concurrency=8, seed=7, crash_prob=0.005, max_crashes=8))
    pk = IncrementalPacker(m.cas_register())
    walls = []
    for i in range(0, len(hs), 1000):
        pk.feed_many(hs[i:i + 1000])
        t0 = time.perf_counter()
        pk.settle()
        walls.append(time.perf_counter() - t0)
    pk.settle(final=True)
    q = len(walls) // 4
    early = sum(walls[q:2 * q]) / q
    late = sum(walls[-q:]) / q
    ratio = late / early
    vec_mode = prepare.fast_pack_enabled()
    scale_ok = (ratio < 1.8) or not vec_mode
    rec = {"leg": "increment-scaling", "ops": len(hs),
           "increments": len(walls), "rows": pk.R,
           "early_ms": round(early * 1e3, 2),
           "late_ms": round(late * 1e3, 2),
           "late_over_early": round(ratio, 2),
           "packer_mode": "vec" if vec_mode else "spec",
           "pack_incr_s": round(prepare.pack_stats()["incr_s"], 3)}
    out["checks"].append(rec)
    ok = ok and scale_ok

    # --- over the wire ------------------------------------------------------
    svc = CheckerService("127.0.0.1", 0, flush_ms_=20).start()
    out["port"] = svc.port
    try:
        client = CheckerClient("127.0.0.1", svc.port)
        sid = client.stream_open("cas-register")
        appends = 0
        for i in range(0, len(h), step):
            st = client.stream_append(sid, h[i:i + step])
            appends += 1
            if st.get("type") != "stream-state":
                ok = False
                out["checks"].append({"leg": "wire", "error": st})
                break
        rw = client.stream_finalize(sid)
        rec = {"leg": "wire", "want": want_ok, "got": rw.get("valid?"),
               "appends": appends,
               "increments": (rw.get("stream") or {}).get("increments")}
        out["checks"].append(rec)
        ok = ok and rw.get("valid?") == want_ok
        out["stats"] = {k: v for k, v in client.stats().items()
                        if k in ("stream_opens", "stream_appends",
                                 "stream_finalizes",
                                 "stream_sessions_open",
                                 "xla_compiles")}
        client.shutdown()
        client.close()
    finally:
        svc.stop()
    out["ok"] = ok
    # Cross-run perf ledger (doc/observability.md § Perf ledger):
    # record() never raises — a ledger failure cannot cost the smoke.
    from jepsen_tpu.obs import ledger as perf_ledger

    perf_ledger.record("stream-smoke", kind="smoke",
                       wall_s=time.time() - t_start, verdict=ok,
                       extra={"stats": out.get("stats"),
                              "increment_scaling": rec})
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
