"""Streaming incremental checking — verdicts while traffic flows.

Before this package, checking was strictly post-hoc: :mod:`jepsen_tpu.core`
buffers the whole history, the run ends, then :mod:`jepsen_tpu.lin` decides
— so a multi-hour soak under nemesis faults holds an unbounded history in
memory and learns of a linearizability violation hours after it happened.
Here the machinery the checker stack already built becomes ONLINE:

- :mod:`jepsen_tpu.stream.incr` — :class:`IncrementalPacker`: extends the
  packed history (prepare.py's slot walk, interner, reduction tables) in
  SETTLED-ROW increments instead of re-packing from op 0. A return-event
  row is *settled* once every op concurrent with it has resolved
  (ok / fail / :info), so the row's tables — including the crashed flags
  and canonical chains the exact reductions depend on — are final the
  moment it is packed. The finalized tables are bit-identical to a
  one-shot ``prepare.prepare`` of the same events (parity-tested).
- :mod:`jepsen_tpu.stream.session` — :class:`StreamChecker`: accepts
  completed ops in windowed increments, carries the sparse-engine
  frontier between increments (the multiword ``bits``/``state`` arrays of
  the PR 5 chunk-kind checkpoint codec, held in memory and optionally on
  disk for kill/resume), dispatches each increment through
  ``lin.device_check_packed(..., frontier=, partial=)`` under a
  ``stream-incr`` supervision site, and ABORTS the stream the moment an
  increment goes invalid — surfacing the witness seconds after the
  offending completion instead of hours after the run.
- :mod:`jepsen_tpu.stream.runner` — :class:`LiveChecker`: the
  ``JEPSEN_TPU_STREAM``-gated checker thread :mod:`jepsen_tpu.core` feeds
  during a run, with early abort plumbed into the generator loop.
- The daemon side lives in :mod:`jepsen_tpu.service` (``stream-open`` /
  ``stream-append`` / ``stream-finalize`` / ``stream-abort`` frames), so
  a remote process can stream a run at a warm chip.

Lifecycle, increment semantics, and the early-abort contract are in
doc/streaming.md; every ``JEPSEN_TPU_STREAM_*`` knob is tabled in
doc/env.md. ``make stream-smoke`` is the chip-free habit check.
"""

from jepsen_tpu.stream.incr import IncrementalPacker
from jepsen_tpu.stream.session import StreamChecker
from jepsen_tpu.stream.runner import LiveChecker, live_checker_for

__all__ = ["IncrementalPacker", "StreamChecker", "LiveChecker",
           "live_checker_for"]
