"""Incremental history packing: prepare.py's walk in settled-row steps.

One-shot :func:`jepsen_tpu.lin.prepare.prepare` is a function of the
COMPLETE history: slot assignment walks every endpoint event, crashed
flags need to know which ops never return, and failed ops are removed
before the walk ever sees them. Streaming cannot wait for the end — but
it does not have to re-pack from op 0 either, because every per-row
quantity is determined by a finite prefix of events:

**Settled rows.** A return-event row ``r`` (at event position ``pos_r``)
depends exactly on the ops invoked before ``pos_r``: which are active,
their interned ``(f, value)``, and whether each eventually returns
(the ``crashed`` flag the exact reductions and the dominance prune
consume) or eventually fails (removed from the history entirely). So
row ``r`` is *settled* — final, never to be revised — as soon as every
op invoked before ``pos_r`` has a recorded completion (ok / fail /
:info). With ``q_min`` = the smallest invoke position among still
unresolved ops, the settled prefix is exactly the rows with
``pos_r < q_min``; at finalize the dangling invokes become crashed
(core.clj:185-217 semantics) and everything settles.

The packer therefore holds the ``prepare._pack_events_py`` walk state
(free slots, active map, interner) across increments and replays the
endpoint-event stream in position order, never past ``q_min``. Because
the walk and the interner see the same events in the same order as the
one-shot pack, the finalized tables are BIT-IDENTICAL to
``prepare.prepare`` of the same events (fuzzed in tests/test_stream.py)
— which is what makes the streamed verdict provably equal the post-hoc
one.

**Reduction tables.** ``prepare.reduction_tables`` orders canonical
chains by return ROW index, which is not yet assigned for an op whose
return event lies past ``q_min``. Return rows are monotone in return
POSITIONS, which *are* known for every resolved op — so the per-row
chain computation here keys on positions instead, yielding the
identical ``pred`` table (order is all the lexsort consumes). Settled
rows' tables are final, so they are computed once per new block and
cached; the cache is injected into each :meth:`packed` view so
``prepare.reduction_tables`` (and everything downstream —
``expansion_tables``, ``reduction_bit_tables``) never recomputes or,
worse, misclassifies a live-but-unreturned op as crashed.

Incremental packing (and the frontier carry that rides on it) is
supported for the fixed-state-layout kernel families — register /
cas-register / mutex, the streaming band that matters (the cockroach
class). History-sized kernels (set / queue: their state layout is a
function of the data) fall back to BUFFER mode: events accumulate and
:class:`jepsen_tpu.stream.session.StreamChecker` runs one exact
post-hoc check at finalize.

numpy-only at import time (like :mod:`jepsen_tpu.obs`): the service
protocol layer loads this without dragging a jax backend in.
"""

from __future__ import annotations

import hashlib
import heapq
import time

import numpy as np

from jepsen_tpu import models as model_ns
from jepsen_tpu.history import Op
from jepsen_tpu.lin import prepare
from jepsen_tpu.lin.prepare import LinOp, PackedHistory, UnsupportedHistory

# Chain-order sentinel for ops that never return, far past any event
# position (positions are per-session counters, bounded by fed events).
_NEVER = np.int64(1) << 40


class IncrementalPacker:
    """Grow a :class:`PackedHistory` in settled-row increments.

    ``feed`` raw history events (invoke / ok / fail / info, any
    interleaving, nemesis lines ignored); ``settle`` extends the packed
    row tables to the current settled prefix; ``packed`` returns a
    PackedHistory view of the settled rows with the reduction-table
    cache pre-injected. ``incremental`` is False in buffer mode (see
    module docstring) — then only ``history`` accumulates.
    """

    def __init__(self, model, max_window: int = prepare.MAX_WINDOW):
        self.model = model
        self.max_window = max_window
        self.intern = prepare._Interner()
        self.kernel, self.init_state = self._stream_kernel(model)
        self.incremental = self.kernel is not None
        self.broken: str | None = None  # feed-time UnsupportedHistory
        self.history: list[Op] = []     # every fed event, in feed order
        self.ops: list[LinOp] = []      # resolved ops, invoke order
        self.R = 0                      # settled return-event rows
        self.events_processed = 0       # endpoint events walked
        self.finalized = False

        self._pos = 0                   # next event position
        self._pending: dict = {}        # process -> (pos, invoke Op)
        self._heap: list = []           # (pos, kind, seq, LinOp)
        self._seq = 0
        # prepare._pack_events_py walk state, carried across settles.
        self._free = list(range(max_window))[::-1]
        self._slot_of: dict[int, int] = {}     # op id -> slot
        self._cur_active: dict[int, int] = {}  # slot -> op id
        self.max_used = 0
        # Per-op interned tables (grow in op order).
        self._op_f: list[int] = []
        self._op_v: list[list[int]] = []
        self._vw = self.kernel.value_width if self.kernel is not None \
            else int(prepare.VALUE_WIDTH)
        # Growing per-op arrays for the vectorized settle: gathers and
        # chain ordkeys without re-scanning self.ops each increment.
        # One sentinel slot past the live count lets slot_op = -1
        # fancy-index the inactive fill values (one-shot walk idiom).
        self._n_arr = 0
        self._op_f_a = np.zeros(0, np.int32)
        self._op_v_a = np.zeros((0, self._vw), np.int32)
        self._inv_pos_a = np.zeros(0, np.int64)
        self._ret_pos_a = np.zeros(0, np.int64)
        # Row blocks at full alloc width (sliced to the live window in
        # packed()); block lists amortize the per-settle concatenation.
        self._blocks: dict[str, list[np.ndarray]] = {
            k: [] for k in ("ret_slot", "ret_op", "active", "slot_f",
                            "slot_v", "slot_op", "crashed")}
        self._tables: dict[str, np.ndarray] | None = None
        self._red_blocks: list[tuple[np.ndarray, np.ndarray]] = []
        self._red_cache: tuple | None = None

    # --- kernel selection ---------------------------------------------------

    def _stream_kernel(self, model):
        """Fixed-state-layout kernels only: a set/queue kernel is SIZED
        from the history (element count, depth bound), so its packed
        state — and any carried frontier — would change layout between
        increments. Those models run in buffer mode instead."""
        from jepsen_tpu.models.kernels import kernel_for

        if isinstance(model, (model_ns.CASRegister, model_ns.Register)):
            kernel = kernel_for(model)
            return kernel, np.array([self.intern(model.value)], np.int32)
        if isinstance(model, model_ns.Mutex):
            kernel = kernel_for(model)
            return kernel, kernel.init_state()
        return None, None

    # --- feeding ------------------------------------------------------------

    def feed(self, op: Op) -> None:
        """Record one history event. Endpoint bookkeeping mirrors
        prepare.pair_ops exactly: failed ops are dropped, crashed reads
        elided, an :info completion stays concurrent forever.

        An unpackable event (double invoke without completion) DOWN-
        GRADES the packer to buffer mode instead of raising: the full
        history keeps accumulating, the session stops incrementing, and
        the post-hoc check at finalize reports whatever the one-shot
        pack would (same exception, honestly surfaced) — an exception
        here would silently drop the rest of the caller's batch."""
        self.history.append(op)
        pos = self._pos
        self._pos += 1
        if not self.incremental:
            return
        try:
            self._feed_endpoint(op, pos)
        except UnsupportedHistory as e:
            self.broken = str(e)
            self.incremental = False

    def _feed_endpoint(self, op: Op, pos: int) -> None:
        if op.process == "nemesis" or op.f in ("start", "stop"):
            return
        if op.is_invoke:
            if op.process in self._pending:
                raise UnsupportedHistory(
                    f"process {op.process} invoked twice without "
                    f"completing (positions "
                    f"{self._pending[op.process][0]} and {pos})")
            self._pending[op.process] = (pos, op)
        elif op.process in self._pending:
            ipos, inv = self._pending.pop(op.process)
            if op.is_fail:
                return            # failed ops definitely did not happen
            ok = op.is_ok
            if not ok and inv.f == "read":
                return            # crashed reads constrain nothing
            self._resolve(inv, ipos, op, pos if ok else None)

    def feed_many(self, events) -> int:
        n = 0
        for op in events:
            self.feed(op)
            n += 1
        return n

    def _resolve(self, inv: Op, ipos: int, completion: Op | None,
                 return_pos: int | None) -> None:
        o = LinOp(op_index=inv.index if inv.index is not None else ipos,
                  process=inv.process, f=inv.f,
                  value=prepare._semantic_value(inv.f, inv, completion),
                  ok=return_pos is not None, invoke_pos=ipos,
                  return_pos=return_pos)
        heapq.heappush(self._heap, (ipos, 0, self._seq, o))
        self._seq += 1
        if return_pos is not None:
            heapq.heappush(self._heap, (return_pos, 1, self._seq, o))
            self._seq += 1

    @property
    def unresolved(self) -> int:
        return len(self._pending)

    # --- the settled-prefix walk --------------------------------------------

    def settle(self, final: bool = False) -> int:
        """Walk every endpoint event in the settled prefix (position
        < q_min; everything once ``final``), extending the row tables.
        Returns the number of NEW return-event rows.

        Under JEPSEN_TPU_FAST_PACK (default) the batch goes through the
        vectorized walk — prepare's sort/cumsum bracket passes with the
        carried free stack as the virgin slot region — so per-increment
        cost is O(new events + new rows x W), never a re-scan of the
        settled prefix. Bit-identical to the per-event spec loop, which
        stays behind ``=0`` as the executable reference."""
        if not self.incremental:
            return 0
        if final and not self.finalized:
            self.finalized = True
            # Dangling invokes = crashed (:info semantics); crashed
            # reads elide, like pair_ops.
            for proc, (ipos, inv) in list(self._pending.items()):
                if inv.f != "read":
                    self._resolve(inv, ipos, None, None)
            self._pending.clear()
        q_min = _NEVER if not self._pending else \
            min(pos for pos, _ in self._pending.values())
        evs = []
        heap = self._heap
        while heap and heap[0][0] < q_min:
            evs.append(heapq.heappop(heap))
        if not evs:
            return 0
        from jepsen_tpu.obs import trace as obs_trace

        t0 = time.perf_counter()
        with obs_trace.span("pack-incr", events=len(evs)) as sp:
            # A batch that would overflow the window defers to the spec
            # loop, which raises mid-walk exactly like the one-shot pack.
            if prepare.fast_pack_enabled() and not self._overflows(evs):
                n_new = self._settle_vec(evs)
                sp.note(rows=n_new, walk="vec")
            else:
                n_new = self._settle_spec(evs)
                sp.note(rows=n_new, walk="spec")
        st = prepare._pack_stats
        st["incr_s"] += time.perf_counter() - t0
        st["incr_calls"] += 1
        return n_new

    def _overflows(self, evs) -> bool:
        d = np.fromiter((1 - 2 * e[1] for e in evs), np.int64, len(evs))
        return len(self._cur_active) + int(
            np.cumsum(d).max(initial=0)) > self.max_window

    def _settle_spec(self, evs) -> int:
        """The per-event reference walk (JEPSEN_TPU_FAST_PACK=0):
        prepare._pack_events_py's loop with carried state."""
        rows = {k: [] for k in self._blocks}
        W = self.max_window
        vw = self._vw
        for pos, kind, _, o in evs:
            self.events_processed += 1
            if kind == 0:                                   # invoke
                if not self._free:
                    raise UnsupportedHistory(
                        f"concurrency window exceeds {W} pending ops "
                        f"at history position {pos}", kind="window")
                i = len(self.ops)
                o._id = i
                self.ops.append(o)
                f_id, v = prepare._op_f_and_values(o, self.intern)
                self._op_f.append(f_id)
                self._op_v.append(v[:vw] + [0] * (vw - len(v)))
                s = self._free.pop()
                self._slot_of[i] = s
                self._cur_active[s] = i
                self.max_used = max(self.max_used, s + 1)
            else:                                           # ok return
                i = o._id
                s = self._slot_of[i]
                active = np.zeros(W, bool)
                slot_f = np.zeros(W, np.int32)
                slot_v = np.full((W, vw), int(prepare.NIL), np.int32)
                slot_op = np.full(W, -1, np.int32)
                crashed = np.zeros(W, bool)
                for slot, op_id in self._cur_active.items():
                    active[slot] = True
                    slot_op[slot] = op_id
                    slot_f[slot] = self._op_f[op_id]
                    slot_v[slot] = self._op_v[op_id]
                    # Every op active at a settled row is RESOLVED, so
                    # the crashed flag is final — the invariant the
                    # exact reductions and the dominance prune need.
                    crashed[slot] = self.ops[op_id].return_pos is None
                rows["ret_slot"].append(np.int32(s))
                rows["ret_op"].append(np.int32(i))
                rows["active"].append(active)
                rows["slot_f"].append(slot_f)
                rows["slot_v"].append(slot_v)
                rows["slot_op"].append(slot_op)
                rows["crashed"].append(crashed)
                self.R += 1
                del self._cur_active[s]
                del self._slot_of[i]
                self._free.append(s)
        n_new = len(rows["ret_slot"])
        if n_new:
            for k, items in rows.items():
                self._blocks[k].append(np.stack(items) if items[0].ndim
                                       else np.asarray(items))
            self._tables = None
            block = self._tables_concat()
            lo = self.R - n_new
            self._red_blocks.append(self._reduce_rows(block, lo, self.R))
            self._red_cache = None
        return n_new

    # --- the vectorized settle (JEPSEN_TPU_FAST_PACK) -----------------------

    def _ensure_op_capacity(self, need: int) -> None:
        cap = self._op_f_a.shape[0]
        if need <= cap:
            return
        new_cap = max(need, 2 * cap, 256)
        pad = new_cap - cap
        self._op_f_a = np.concatenate(
            [self._op_f_a, np.zeros(pad, np.int32)])
        self._op_v_a = np.concatenate(
            [self._op_v_a, np.zeros((pad, self._vw), np.int32)])
        self._inv_pos_a = np.concatenate(
            [self._inv_pos_a, np.zeros(pad, np.int64)])
        self._ret_pos_a = np.concatenate(
            [self._ret_pos_a, np.zeros(pad, np.int64)])

    def _materialize_ops(self, n0, n1, new_f, new_v, new_ip, new_rp):
        """Batch-append the new ops' interned tables and endpoint
        positions to the growing arrays (+ the sentinel slot at n1).
        Backfills ops packed by earlier spec-mode settles, so flipping
        JEPSEN_TPU_FAST_PACK mid-stream stays correct."""
        self._ensure_op_capacity(n1 + 1)
        if self._n_arr < n0:
            lo = self._n_arr
            self._op_f_a[lo:n0] = np.asarray(self._op_f[lo:n0], np.int32)
            self._op_v_a[lo:n0] = np.asarray(self._op_v[lo:n0], np.int32)
            self._inv_pos_a[lo:n0] = np.fromiter(
                (o.invoke_pos for o in self.ops[lo:n0]),
                np.int64, n0 - lo)
            self._ret_pos_a[lo:n0] = np.fromiter(
                (int(_NEVER) if o.return_pos is None else o.return_pos
                 for o in self.ops[lo:n0]), np.int64, n0 - lo)
        if n1 > n0:
            self._op_f_a[n0:n1] = np.asarray(new_f, np.int32)
            self._op_v_a[n0:n1] = np.asarray(new_v, np.int32)
            self._inv_pos_a[n0:n1] = np.asarray(new_ip, np.int64)
            self._ret_pos_a[n0:n1] = np.asarray(new_rp, np.int64)
        self._n_arr = n1
        self._op_f_a[n1] = 0
        self._op_v_a[n1] = int(prepare.NIL)
        self._inv_pos_a[n1] = 0
        self._ret_pos_a[n1] = 0

    def _settle_vec(self, evs) -> int:
        """One batched pass over the settled events: the same
        sort/cumsum bracket walk as prepare._pack_events_vec — returns
        are opens, invokes are closes — with two carry twists: fresh
        invokes (running-min records of the return-minus-invoke sum)
        pop the CARRIED free stack top-down instead of the virgin
        0,1,2... region, and the carried actives paint as row intervals
        from batch row 0. No per-row Python snapshot, no per-settle
        re-concatenation, no re-scan of settled ops. Bit-identical to
        _settle_spec (fuzzed in tests/test_stream.py)."""
        E = len(evs)
        self.events_processed += E
        W = self.max_window
        vw = self._vw
        n0 = len(self.ops)
        kind_ret = np.empty(E, bool)
        ev_pos = np.empty(E, np.int64)
        ev_gid = np.empty(E, np.int64)
        new_f, new_v, new_ip, new_rp = [], [], [], []
        for e, (pos, kind, _, o) in enumerate(evs):
            ev_pos[e] = pos
            if kind == 0:                                   # invoke
                o._id = len(self.ops)
                self.ops.append(o)
                f_id, v = prepare._op_f_and_values(o, self.intern)
                vv = v[:vw] + [0] * (vw - len(v))
                self._op_f.append(f_id)
                self._op_v.append(vv)
                new_f.append(f_id)
                new_v.append(vv)
                new_ip.append(o.invoke_pos)
                new_rp.append(int(_NEVER) if o.return_pos is None
                              else o.return_pos)
                kind_ret[e] = False
            else:                                           # ok return
                kind_ret[e] = True
            ev_gid[e] = o._id
        k = len(new_f)
        n1 = n0 + k
        self._materialize_ops(n0, n1, new_f, new_v, new_ip, new_rp)

        # Fresh invokes: the batch recycle stack is empty exactly when
        # the return-minus-invoke running sum hits a new minimum; they
        # take the carried free-stack slots top-down, in order.
        sigma = np.cumsum(np.where(kind_ret, 1, -1))
        runmin = np.minimum.accumulate(np.minimum(sigma, 0))
        prev_runmin = np.empty_like(runmin)
        prev_runmin[0] = 0
        prev_runmin[1:] = runmin[:-1]
        fresh = (~kind_ret) & (sigma < prev_runmin)
        n_fresh = int(fresh.sum())

        # Local op table: batch-invoked ops [0, k) in invoke order, then
        # the carried ops that return in this batch.
        ret_gids = ev_gid[kind_ret]
        carried = ret_gids < n0
        carried_gids = ret_gids[carried]
        n_car = len(carried_gids)
        L = k + n_car
        lop = np.empty(E, np.int64)
        lop[~kind_ret] = ev_gid[~kind_ret] - n0
        c_idx = np.cumsum(carried) - 1
        lop[np.flatnonzero(kind_ret)] = np.where(
            carried, k + c_idx, ret_gids - n0)
        slot_root = np.full(max(1, L), -1, np.int32)
        if n_fresh:
            reserve = np.asarray(self._free[::-1][:n_fresh], np.int32)
            slot_root[ev_gid[fresh] - n0] = reserve
            self.max_used = max(self.max_used, int(reserve.max()) + 1)
        if n_car:
            slot_root[k:L] = [self._slot_of[int(g)]
                              for g in carried_gids.tolist()]
        # Bracket-match recycled invokes to the return they reuse, then
        # propagate slots along reuse chains by pointer doubling (roots:
        # fresh batch ops and carried ops).
        sub = kind_ret | ((~kind_ret) & ~fresh)
        si = np.flatnonzero(sub)
        lev = sigma - runmin
        lv = np.where(kind_ret[si], lev[si], lev[si] + 1)
        so = np.argsort(lv, kind="stable")
        ss = si[so]
        lvs = lv[so]
        parent = np.arange(max(1, L), dtype=np.int64)
        if len(ss):
            run_first = np.empty(len(ss), bool)
            run_first[0] = True
            run_first[1:] = lvs[1:] != lvs[:-1]
            base = np.maximum.accumulate(
                np.where(run_first, np.arange(len(ss)), 0))
            rank = np.arange(len(ss)) - base
            mpair = rank % 2 == 1
            parent[lop[ss[mpair]]] = lop[ss[np.flatnonzero(mpair) - 1]]
            while True:
                pp = parent[parent]
                if np.array_equal(pp, parent):
                    break
                parent = pp
        slot_l = slot_root[parent]

        n_new = int(kind_ret.sum())
        if n_new:
            rlop = lop[kind_ret]
            # Row intervals in batch-row space: carried actives from row
            # 0, batch ops from their invoke; still-active ops paint
            # through the last row (next batch re-paints them from 0).
            n_car0 = len(self._cur_active)
            ca_slots = np.fromiter(self._cur_active.keys(), np.int64,
                                   n_car0)
            ca_gids = np.fromiter(self._cur_active.values(), np.int64,
                                  n_car0)
            p_gid = np.concatenate([ca_gids, np.arange(n0, n1)])
            p_slot = np.concatenate(
                [ca_slots, slot_l[:k].astype(np.int64, copy=False)])
            ret_pos_sorted = ev_pos[kind_ret]
            r0 = np.concatenate([
                np.zeros(n_car0, np.int64),
                np.searchsorted(ret_pos_sorted, self._inv_pos_a[n0:n1])])
            r1 = np.full(n_car0 + k, n_new, np.int64)
            rows_idx = np.arange(n_new, dtype=np.int64)
            bm = ~carried
            r1[n_car0 + (ret_gids[bm] - n0)] = rows_idx[bm] + 1
            if n_car:
                ca_pos = {int(g): j for j, g in
                          enumerate(ca_gids.tolist())}
                for rr, g in zip(rows_idx[carried].tolist(),
                                 carried_gids.tolist()):
                    r1[ca_pos[g]] = rr + 1
            # Column-major paint (cumsum along the contiguous axis) of
            # op id + 1, as in the one-shot walk. Settle batches at or
            # above the stream device threshold run the O(rows x W)
            # grid tail as one supervised jitted program
            # (lin/pack_dev.py, doc/streaming.md § Device packing);
            # the crashed flag crosses as a host bool column because
            # the int64 _NEVER sentinel never fits the int32 device
            # tables. Any non-ok outcome (wedge / fault / quarantine /
            # static rule) returns None and the numpy paint below runs
            # instead — same tables, no verdict cost.
            ids1 = (p_gid + 1).astype(np.int32)
            dev = None
            from jepsen_tpu.lin import pack_dev
            if (pack_dev.pack_dev_enabled()
                    and n_new >= pack_dev.stream_min_rows()):
                dev = pack_dev.paint_tables_dev(
                    p_slot, r0, r1, ids1,
                    self._op_f_a[:n1], self._op_v_a[:n1],
                    self._ret_pos_a[:n1] >= _NEVER,
                    n1, n_new, W, kernel=self.kernel.name)
            if dev is not None:
                grid, active, slot_f, slot_v, slot_op, crashed = dev
            else:
                occ = np.zeros((W, n_new + 1), np.int32)
                flat = occ.reshape(-1)
                np.add.at(flat, p_slot * (n_new + 1) + r0, ids1)
                np.subtract.at(flat, p_slot * (n_new + 1) + r1, ids1)
                np.cumsum(occ, axis=1, out=occ)
                grid = np.ascontiguousarray(occ[:, :n_new].T)
                active = grid != 0
                slot_op = grid - 1
                fview = self._op_f_a[:n1 + 1]
                vview = self._op_v_a[:n1 + 1]
                rview = self._ret_pos_a[:n1 + 1]
                slot_f = fview[slot_op]
                slot_v = vview[slot_op]
                crashed = (rview[slot_op] >= _NEVER) & active
            b = self._blocks
            b["ret_slot"].append(slot_l[rlop].astype(np.int32,
                                                     copy=False))
            b["ret_op"].append(ret_gids.astype(np.int32, copy=False))
            b["active"].append(active)
            b["slot_f"].append(slot_f)
            b["slot_v"].append(slot_v)
            b["slot_op"].append(slot_op)
            b["crashed"].append(crashed)
            self._tables = None
            self.R += n_new
            self._red_blocks.append(self._reduce_rows_vec(
                active, slot_f, slot_v, slot_op, grid))
            self._red_cache = None

        # Replay the walk-state bookkeeping (dicts + the LIFO free
        # list) — pure O(new events) Python, no per-row numpy.
        free = self._free
        sl = slot_l[lop].tolist()
        kl = kind_ret.tolist()
        gl = ev_gid.tolist()
        for e in range(E):
            g = gl[e]
            s = sl[e]
            if kl[e]:
                del self._cur_active[s]
                del self._slot_of[g]
                free.append(s)
            else:
                free.pop()
                self._slot_of[g] = s
                self._cur_active[s] = g
        return n_new

    def _reduce_rows_vec(self, active, slot_f, slot_v, slot_op, grid):
        """(pure, pred) for a fresh block via the shared vectorized
        chain core (prepare._chain_tables_vec) with position-based
        ordkeys, restricted to the ops the block references — O(block),
        never a re-scan of all settled ops. Restriction preserves both
        class equality (per-op values) and pairwise ordkey order, so
        the result is bit-identical to _reduce_rows."""
        part = np.unique(grid)
        part_g = part[part > 0].astype(np.int64) - 1
        pr = self._ret_pos_a[part_g]
        pi = self._inv_pos_a[part_g]
        p_crashed = pr >= _NEVER
        p_ord = np.where(p_crashed, _NEVER + 2 + pi, pr)
        loc = np.searchsorted(part_g, np.clip(slot_op, 0, None))
        slot_op_l = np.where(slot_op >= 0, loc, -1).astype(np.int32)
        return prepare._chain_tables_vec(
            active, slot_f, slot_v, slot_op_l, p_ord, p_crashed,
            op_f_ops=self._op_f_a[part_g],
            op_v_ops=self._op_v_a[part_g])

    def _tables_concat(self) -> dict[str, np.ndarray]:
        if self._tables is None:
            out = {}
            for k, blocks in self._blocks.items():
                if blocks:
                    out[k] = np.concatenate(blocks, axis=0)
                else:
                    shape = {"ret_slot": (0,), "ret_op": (0,),
                             "active": (0, self.max_window),
                             "slot_f": (0, self.max_window),
                             "slot_v": (0, self.max_window, self._vw),
                             "slot_op": (0, self.max_window),
                             "crashed": (0, self.max_window)}[k]
                    dt = bool if k in ("active", "crashed") else np.int32
                    out[k] = np.zeros(shape, dt)
            self._tables = out
        return self._tables

    # --- reduction tables on return POSITIONS -------------------------------

    def _reduce_rows(self, t: dict, lo: int, hi: int):
        """(pure, pred) for rows [lo, hi): the exact twin of
        prepare.reduction_tables with return-position ordkeys (see
        module docstring — positions are order-isomorphic to return
        rows, and order is all the chain lexsort consumes). Settled
        rows' inputs are final, so the result is final."""
        from jepsen_tpu.models import kernels as K

        active = t["active"][lo:hi]
        slot_f = t["slot_f"][lo:hi]
        slot_op = t["slot_op"][lo:hi]
        n_rows, W = active.shape
        if n_rows == 0:
            return (np.zeros((0, W), bool), np.full((0, W), -1, np.int32))
        pure_fs = {int(K.F_IDS[f]) for f in ("read",) if f in K.F_IDS}
        pure = active & np.isin(slot_f, list(pure_fs))

        n_ops = len(self.ops)
        ret_pos = np.fromiter(
            (_NEVER if o.return_pos is None else o.return_pos
             for o in self.ops), np.int64, n_ops)
        inv_pos = np.fromiter((o.invoke_pos for o in self.ops),
                              np.int64, n_ops)
        slot_ret = np.where(slot_op >= 0,
                            ret_pos[np.clip(slot_op, 0, None)], _NEVER)
        slot_inv = np.where(slot_op >= 0,
                            inv_pos[np.clip(slot_op, 0, None)], 0)
        is_crashed = slot_ret >= _NEVER
        ordkey = np.where(is_crashed, _NEVER + 2 + slot_inv, slot_ret)

        slot_v = t["slot_v"][lo:hi]
        chainable = active & ~pure & (slot_op >= 0)
        sent = -1 - np.arange(W, dtype=np.int64)
        f_key = np.where(chainable,
                         (slot_f.astype(np.int64) << 1) | is_crashed,
                         sent[None, :])
        v_keys = [slot_v[:, :, k].astype(np.int64)
                  for k in range(slot_v.shape[2])]
        order = np.lexsort(tuple([ordkey] + v_keys[::-1] + [f_key]),
                           axis=1)
        f_s = np.take_along_axis(f_key, order, axis=1)
        same = f_s[:, 1:] == f_s[:, :-1]
        for vk in v_keys:
            v_s = np.take_along_axis(vk, order, axis=1)
            same &= v_s[:, 1:] == v_s[:, :-1]
        pred = np.full((n_rows, W), -1, np.int32)
        cols = order[:, 1:]
        prev = order[:, :-1]
        np.put_along_axis(pred, cols,
                          np.where(same, prev, -1).astype(np.int32),
                          axis=1)
        return pure, pred

    def reduction_tables(self):
        if self._red_cache is None:
            W = max(1, self.max_used)
            if self._red_blocks:
                pure = np.concatenate(
                    [b[0][:, :W] for b in self._red_blocks], axis=0)
                pred = np.concatenate(
                    [b[1][:, :W] for b in self._red_blocks], axis=0)
            else:
                pure = np.zeros((0, W), bool)
                pred = np.full((0, W), -1, np.int32)
            self._red_cache = (pure, pred)
        return self._red_cache

    # --- views --------------------------------------------------------------

    def packed(self) -> PackedHistory:
        """A PackedHistory of the settled prefix (fresh object — per-
        object caches like expansion tables rebuild against the grown
        window/interner; the reduction-table cache is injected)."""
        if not self.incremental:
            raise UnsupportedHistory(
                f"model {type(self.model).__name__} has no streaming "
                f"kernel formulation (buffer mode)")
        t = self._tables_concat()
        W = max(1, self.max_used)
        p = PackedHistory(
            model=self.model, kernel=self.kernel, ops=self.ops,
            window=W, R=self.R, ret_slot=t["ret_slot"],
            ret_op=t["ret_op"], active=t["active"][:, :W],
            slot_f=t["slot_f"][:, :W], slot_v=t["slot_v"][:, :W],
            slot_op=t["slot_op"][:, :W], crashed=t["crashed"][:, :W],
            init_state=self.init_state, intern=self.intern.ids,
            unintern=self.intern.values,
            crashed_ops=[o for o in self.ops if o.return_pos is None])
        # Inject the position-keyed reduction cache: recomputing via
        # prepare.reduction_tables here would misclassify a resolved-
        # but-later-returning op as crashed (its return row is not yet
        # assigned), silently corrupting the canonical chains.
        p._reduction_tables = self.reduction_tables()
        return p

    def prefix_fingerprint(self, row: int) -> str:
        """Identity of the settled row prefix [0, row) for stream
        checkpoint resume: deterministic for any session fed the same
        client events in the same order, REGARDLESS of where its
        increment boundaries fell (rows are hashed at full alloc width,
        which later window growth never rewrites)."""
        t = self._tables_concat()
        h = hashlib.sha256()
        kname = self.kernel.name if self.kernel is not None else None
        h.update(f"stream|{kname}|{row}".encode())
        h.update(np.ascontiguousarray(self.init_state).tobytes())
        for k in ("ret_slot", "ret_op", "active", "slot_f", "slot_v",
                  "crashed"):
            arr = np.ascontiguousarray(t[k][:row])
            h.update(str(arr.shape).encode())
            h.update(arr.tobytes())
        return h.hexdigest()
