"""LiveChecker: the run loop's streaming-checker thread.

:mod:`jepsen_tpu.core` feeds every history append (``conj_op``) to this
wrapper; a dedicated daemon thread drains the queue into a
:class:`jepsen_tpu.stream.session.StreamChecker` so increment checks
never block a worker's op loop. The generator loop polls
``should_abort()`` between ops — the moment an increment goes invalid,
every worker stops drawing ops and the run ends with the witness in
hand instead of generating hours more traffic against a system already
proven wrong.

Gated by ``JEPSEN_TPU_STREAM=1`` (doc/env.md § Streaming); the final
verdict rides in ``test["results"]["stream"]`` next to whatever checker
the test configured (the post-hoc checker still runs — the stream
verdict is an additional, earlier view of the same history, equal by
the parity argument in doc/streaming.md).

With ``JEPSEN_TPU_STREAM_WIRE=host:port`` additionally set, the live
checker targets a checker-daemon STREAM SESSION over the wire instead
of an in-process :class:`StreamChecker`: appends ride
``CheckerClient.stream_*`` and the daemon's svc-stream bins batch this
run's increments with other tenants'. Any wire loss (connect failure,
socket error, daemon error reply) degrades to the in-process session —
the buffered feed replays locally, so the verdict is never lost and
``results["stream"]`` keeps its shape either way (a ``transport`` key
says which path decided).
"""

from __future__ import annotations

import os
import threading
from collections import deque


def enabled() -> bool:
    return os.environ.get("JEPSEN_TPU_STREAM", "0") == "1"


def wire_target() -> tuple[str, int] | None:
    """``JEPSEN_TPU_STREAM_WIRE=host:port`` — the daemon the live
    checker should stream through (unset/empty/bad = in-process)."""
    v = os.environ.get("JEPSEN_TPU_STREAM_WIRE", "").strip()
    if not v or ":" not in v:
        return None
    host, _, port = v.rpartition(":")
    try:
        return host or "127.0.0.1", int(port)
    except ValueError:
        return None


def _wire_model_name(model) -> str | None:
    """A model instance's wire name (the daemon speaks names, the run
    carries instances)."""
    from jepsen_tpu.service import protocol

    for name in protocol.MODEL_NAMES:
        try:
            if type(protocol.model_by_name(name)) is type(model):
                return name
        except Exception:  # noqa: BLE001 - unknown model: no wire name
            pass
    return None


class _WireSession:
    """StreamChecker-shaped adapter over a daemon stream session.

    Implements the three members LiveChecker consumes — ``append`` /
    ``aborted`` / ``finalize`` — and buffers every offered event so a
    mid-run wire loss can replay the whole feed into a local
    :class:`StreamChecker` (degrade, never lose the verdict)."""

    def __init__(self, model, model_name: str, host: str, port: int,
                 **session_kw):
        from jepsen_tpu.service.protocol import CheckerClient

        self._model = model
        self._kw = session_kw
        self._client = CheckerClient(host, port, timeout=60)
        self._sid = self._client.stream_open(model_name)
        self._events: list = []
        self._aborted = False
        self._degraded_from_wire: str | None = None
        self._local = None          # in-process StreamChecker after loss

    def _degrade(self, why: str):
        """Replay the buffered feed into an in-process session; all
        later calls go there."""
        from jepsen_tpu.stream.session import StreamChecker

        if self._local is None:
            self._degraded_from_wire = why
            self._local = StreamChecker(self._model, **self._kw)
            if self._events:
                self._local.append(list(self._events))
            try:
                self._client.close()
            except Exception:  # noqa: BLE001 - already torn down
                pass
        return self._local

    def append(self, events) -> dict:
        events = list(events)
        self._events.extend(events)
        if self._local is not None:
            return self._local.append(events)
        try:
            st = self._client.stream_append(self._sid, events)
        except Exception as e:  # noqa: BLE001 - any wire loss degrades
            return self._degrade(f"append: {e!r}").status()
        if st.get("type") == "error":
            return self._degrade(f"append error: {st.get('error')}") \
                .status()
        if st.get("aborted"):
            self._aborted = True
        return st

    @property
    def aborted(self) -> bool:
        if self._local is not None:
            return self._local.aborted
        return self._aborted

    def finalize(self) -> dict:
        if self._local is None:
            try:
                r = self._client.stream_finalize(self._sid)
                if r.get("valid?") in (True, False, "unknown"):
                    r.setdefault("transport", "wire")
                    self._client.close()
                    return r
                self._degrade(f"finalize reply: {r!r}")
            except Exception as e:  # noqa: BLE001 - degrade, not lose
                self._degrade(f"finalize: {e!r}")
        r = self._local.finalize()
        r.setdefault("transport", "local")
        if self._degraded_from_wire:
            r["wire_degraded"] = self._degraded_from_wire
        return r


def _open_session(model, **session_kw):
    """The LiveChecker's session factory: a daemon-backed wire session
    when ``JEPSEN_TPU_STREAM_WIRE`` names a reachable daemon and the
    model has a wire name; the in-process StreamChecker otherwise
    (including on any open failure — wire loss degrades, never
    blocks a run)."""
    from jepsen_tpu.stream.session import StreamChecker

    target = wire_target()
    if target is not None:
        name = _wire_model_name(model)
        if name is not None:
            try:
                return _WireSession(model, name, target[0], target[1],
                                    **session_kw)
            except Exception:  # noqa: BLE001 - daemon down: go local
                pass
    return StreamChecker(model, **session_kw)


def abort_enabled() -> bool:
    """``JEPSEN_TPU_STREAM_ABORT=0`` keeps checking live but lets the
    run complete (observe-only mode: the abort latency numbers without
    the abort)."""
    return os.environ.get("JEPSEN_TPU_STREAM_ABORT", "1") != "0"


class LiveChecker:
    """Queue-fed, thread-driven StreamChecker for a live run."""

    def __init__(self, model, **session_kw):
        self.session = _open_session(model, **session_kw)
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._aborted = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="jepsen-stream-checker")
        self._thread.start()

    def offer(self, op) -> None:
        """Called from worker threads under the history append path —
        must stay O(1): enqueue and wake the checker thread."""
        with self._cv:
            self._q.append(op)
            self._cv.notify()

    def should_abort(self) -> bool:
        return abort_enabled() and self._aborted.is_set()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(0.5)
                batch = list(self._q)
                self._q.clear()
                stopping = self._stop
            if batch:
                try:
                    self.session.append(batch)
                except Exception:  # noqa: BLE001 - the checker thread
                    pass           # must never take the run down
                if self.session.aborted:
                    self._aborted.set()
            if stopping and not batch:
                return

    def finish(self) -> dict:
        """Drain, finalize, and return the stream verdict (joins the
        checker thread; called once after the workload completes)."""
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=600)
        return self.session.finalize()


def live_checker_for(test: dict) -> LiveChecker | None:
    """The core.run() gate: a LiveChecker when streaming is enabled and
    the test carries a model, else None (zero overhead)."""
    if not enabled():
        return None
    model = test.get("model")
    if model is None:
        return None
    # min_rows defaults via session.default_min_rows() (the one
    # JEPSEN_TPU_STREAM_ROWS definition).
    return LiveChecker(model)
