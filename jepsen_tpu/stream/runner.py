"""LiveChecker: the run loop's streaming-checker thread.

:mod:`jepsen_tpu.core` feeds every history append (``conj_op``) to this
wrapper; a dedicated daemon thread drains the queue into a
:class:`jepsen_tpu.stream.session.StreamChecker` so increment checks
never block a worker's op loop. The generator loop polls
``should_abort()`` between ops — the moment an increment goes invalid,
every worker stops drawing ops and the run ends with the witness in
hand instead of generating hours more traffic against a system already
proven wrong.

Gated by ``JEPSEN_TPU_STREAM=1`` (doc/env.md § Streaming); the final
verdict rides in ``test["results"]["stream"]`` next to whatever checker
the test configured (the post-hoc checker still runs — the stream
verdict is an additional, earlier view of the same history, equal by
the parity argument in doc/streaming.md).
"""

from __future__ import annotations

import os
import threading
from collections import deque


def enabled() -> bool:
    return os.environ.get("JEPSEN_TPU_STREAM", "0") == "1"


def abort_enabled() -> bool:
    """``JEPSEN_TPU_STREAM_ABORT=0`` keeps checking live but lets the
    run complete (observe-only mode: the abort latency numbers without
    the abort)."""
    return os.environ.get("JEPSEN_TPU_STREAM_ABORT", "1") != "0"


class LiveChecker:
    """Queue-fed, thread-driven StreamChecker for a live run."""

    def __init__(self, model, **session_kw):
        from jepsen_tpu.stream.session import StreamChecker

        self.session = StreamChecker(model, **session_kw)
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._aborted = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="jepsen-stream-checker")
        self._thread.start()

    def offer(self, op) -> None:
        """Called from worker threads under the history append path —
        must stay O(1): enqueue and wake the checker thread."""
        with self._cv:
            self._q.append(op)
            self._cv.notify()

    def should_abort(self) -> bool:
        return abort_enabled() and self._aborted.is_set()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(0.5)
                batch = list(self._q)
                self._q.clear()
                stopping = self._stop
            if batch:
                try:
                    self.session.append(batch)
                except Exception:  # noqa: BLE001 - the checker thread
                    pass           # must never take the run down
                if self.session.aborted:
                    self._aborted.set()
            if stopping and not batch:
                return

    def finish(self) -> dict:
        """Drain, finalize, and return the stream verdict (joins the
        checker thread; called once after the workload completes)."""
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=600)
        return self.session.finalize()


def live_checker_for(test: dict) -> LiveChecker | None:
    """The core.run() gate: a LiveChecker when streaming is enabled and
    the test carries a model, else None (zero overhead)."""
    if not enabled():
        return None
    model = test.get("model")
    if model is None:
        return None
    # min_rows defaults via session.default_min_rows() (the one
    # JEPSEN_TPU_STREAM_ROWS definition).
    return LiveChecker(model)
