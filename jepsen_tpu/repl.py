"""Interactive-analysis conveniences.

The analogue of `jepsen/src/jepsen/repl.clj` (13 LoC): ``last_test``
loads the most recent run from the store (repl.clj:6-13) so a recorded
history can be re-checked interactively — e.g. rerun the device
linearizability search with a different model or algorithm.
"""

from __future__ import annotations

from jepsen_tpu import store


def last_test(base=store.BASE) -> dict | None:
    """Load the most recently-run test from the store (repl.clj:6-13)."""
    newest = None
    for name, runs in store.all_tests(base=base).items():
        for ts, loader in runs.items():
            if newest is None or ts > newest[0]:
                newest = (ts, loader)
    return newest[1]() if newest else None


def recheck(test: dict, model=None, algorithm: str = "tpu") -> dict:
    """Re-run the linearizability analysis on a loaded test's history —
    the record-once / re-check-on-device seam (SURVEY.md §5).

    ``model`` must be supplied for store-loaded tests: models are runtime
    objects the store never persists (store.serializable_test)."""
    from jepsen_tpu import lin

    model = model or test.get("model")
    if model is None:
        raise ValueError(
            "no model: store-loaded tests don't carry one; pass model=")
    return lin.analysis(model, test["history"], algorithm=algorithm)
