"""Performance graphs from histories.

Re-design of `jepsen/src/jepsen/checker/perf.clj` (343 LoC): latency
point/quantile graphs and throughput rate graphs, with nemesis-active
regions shaded. matplotlib replaces the reference's gnuplot subprocess
(perf.clj:231-247 shells out to gnuplot; this keeps everything in-process).

Pure helpers (bucketing perf.clj:16-44, quantiles :46-56,
latencies->quantiles :58-80, rate :114-128) are exposed for tests.
"""

from __future__ import annotations

import logging
from typing import Iterable

from jepsen_tpu.util import history_latencies, nemesis_intervals

log = logging.getLogger("jepsen.perf")

TYPE_COLORS = {"ok": "#81BFFC", "info": "#FFA400", "fail": "#FF1E90"}


def bucket_scale(dt: float, b: float) -> float:
    """The center point of bucket b with width dt (perf.clj:16-24)."""
    return b * dt + dt / 2


def bucket_time(dt: float, t: float) -> float:
    """Map a time to its bucket's center (perf.clj:26-31)."""
    return bucket_scale(dt, t // dt)


def buckets(dt: float, t_max: float) -> list[float]:
    """Bucket centers covering [0, t_max] (perf.clj:33-37)."""
    out = []
    t = dt / 2
    while t <= t_max + dt / 2:
        out.append(t)
        t += dt
    return out


def bucket_points(dt: float, points: Iterable[tuple]) -> dict:
    """Group [t, x] points into buckets of width dt keyed by bucket center
    (perf.clj:39-44)."""
    out: dict = {}
    for t, x in points:
        out.setdefault(bucket_time(dt, t), []).append((t, x))
    return out


def quantiles(qs: Iterable[float], points: list) -> dict:
    """Exact quantiles of a sample by sorted-rank (perf.clj:46-56)."""
    points = sorted(points)
    out = {}
    for q in qs:
        if not points:
            continue
        if not 0 <= q <= 1:
            raise ValueError(f"quantile must be in [0,1]: {q}")
        k = min(len(points) - 1, int(q * len(points)))
        out[q] = points[k]
    return out


def latencies_to_quantiles(dt: float, qs: Iterable[float],
                           points: Iterable[tuple]) -> dict:
    """{quantile: [[bucket-time, latency] ...]} (perf.clj:58-80)."""
    qs = list(qs)
    by_bucket = bucket_points(dt, points)
    centers = sorted(by_bucket)
    out: dict = {q: [] for q in qs}
    for center in centers:
        lats = sorted(x for _, x in by_bucket[center])
        qmap = quantiles(qs, lats)
        for q in qs:
            if q in qmap:
                out[q].append([center, qmap[q]])
    return out


def rate(dt: float, history) -> dict:
    """{(f, type): [[bucket-time, ops/sec] ...]} from completion events
    (perf.clj:114-128)."""
    counts: dict = {}
    t_max = 0.0
    for op in history:
        if op.is_invoke or op.time is None:
            continue
        t = op.time / 1e9
        t_max = max(t_max, t)
        key = (op.f, op.type)
        counts.setdefault(key, {})
        b = bucket_time(dt, t)
        counts[key][b] = counts[key].get(b, 0) + 1
    return {key: [[b, c / dt] for b, c in sorted(m.items())]
            for key, m in counts.items()}


def _nemesis_spans(history) -> list[tuple[float, float]]:
    spans = []
    t_max = max((op.time or 0) for op in history) / 1e9 if history else 0
    for start, stop in nemesis_intervals(history):
        t0 = (start.time or 0) / 1e9
        t1 = (stop.time or 0) / 1e9 if stop is not None else t_max
        spans.append((t0, t1))
    return spans


def _setup_plot(title, ylabel):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(10, 5))
    ax.set_title(title)
    ax.set_xlabel("time (s)")
    ax.set_ylabel(ylabel)
    return fig, ax


def _shade_nemesis(ax, history):
    for t0, t1 in _nemesis_spans(history):
        ax.axvspan(t0, t1, color="#F3F3F3", zorder=0)


def _save(fig, test, opts, filename):
    import matplotlib.pyplot as plt

    from jepsen_tpu import store

    if not (isinstance(test, dict) and test.get("name")):
        # Unnamed tests persist nothing (tests_support.noop_test contract;
        # the runner gates save_1/save_2 the same way).
        plt.close(fig)
        return None
    path = store.path(test, (opts or {}).get("subdirectory"), filename,
                      make=True)
    fig.savefig(path, dpi=110, bbox_inches="tight")
    plt.close(fig)
    return path


def point_graph(test, history, opts=None):
    """Latency scatter colored by completion type (perf.clj:221-249)."""
    fig, ax = _setup_plot(f"{(test or {}).get('name', '')} latency (raw)",
                          "latency (ms)")
    _shade_nemesis(ax, history)
    series: dict = {}
    for inv, latency, ctype in history_latencies(history):
        if latency is None or inv.time is None:
            continue
        series.setdefault(ctype, []).append(
            (inv.time / 1e9, latency / 1e6))
    for ctype, pts in sorted(series.items(), key=lambda kv: str(kv[0])):
        xs, ys = zip(*pts)
        ax.scatter(xs, ys, s=4, label=str(ctype),
                   color=TYPE_COLORS.get(ctype, "#888888"))
    ax.set_yscale("log")
    if series:
        ax.legend(loc="upper right", fontsize=7)
    return _save(fig, test, opts, "latency-raw.png")


def quantiles_graph(test, history, opts=None,
                    qs=(0.5, 0.95, 0.99, 1.0), dt=10.0):
    """Latency quantiles over time (perf.clj:251-291)."""
    pts = [(inv.time / 1e9, latency / 1e6)
           for inv, latency, _ in history_latencies(history)
           if latency is not None and inv.time is not None]
    by_q = latencies_to_quantiles(dt, qs, pts)
    fig, ax = _setup_plot(
        f"{(test or {}).get('name', '')} latency (quantiles)",
        "latency (ms)")
    _shade_nemesis(ax, history)
    for q, series in sorted(by_q.items()):
        if series:
            xs, ys = zip(*series)
            ax.plot(xs, ys, marker="o", markersize=3, label=f"q={q}")
    ax.set_yscale("log")
    if any(by_q.values()):
        ax.legend(loc="upper right", fontsize=7)
    return _save(fig, test, opts, "latency-quantiles.png")


def rate_graph(test, history, opts=None, dt=10.0):
    """Throughput by (f, completion-type) over time (perf.clj:300-342)."""
    series = rate(dt, [op for op in history if op.process != "nemesis"])
    fig, ax = _setup_plot(f"{(test or {}).get('name', '')} rate",
                          "throughput (hz)")
    _shade_nemesis(ax, history)
    for (f, ctype), pts in sorted(series.items(),
                                  key=lambda kv: str(kv[0])):
        if pts:
            xs, ys = zip(*pts)
            ax.plot(xs, ys, marker="o", markersize=3,
                    label=f"{f} {ctype}",
                    color=None if ctype not in TYPE_COLORS
                    else TYPE_COLORS[ctype],
                    linestyle={"ok": "-", "info": "--",
                               "fail": ":"}.get(ctype, "-"))
    if series:
        ax.legend(loc="upper right", fontsize=7)
    return _save(fig, test, opts, "rate.png")
