"""HTML timeline of a history: one swimlane per process.

Re-design of `jepsen/src/jepsen/checker/timeline.clj` (179 LoC): pairs
invocations with completions (:33-53), renders each op as a positioned div
colored by completion type (:97-121), emits timeline.html through the
store (:159-179). No external templating — plain string HTML.
"""

from __future__ import annotations

import html as _html

from jepsen_tpu import checker as checker_ns
from jepsen_tpu.history import Op

TYPE_COLORS = {"ok": "#B3F3B5", "info": "#FFE0B3", "fail": "#F3B3B3",
               None: "#DDDDDD"}

NS_PER_PX = 1e6  # 1 ms per pixel vertically


def pairs(history) -> list[tuple[Op, Op | None]]:
    """Match invocations with their completions; unmatched invocations pair
    with None (timeline.clj:33-53)."""
    out = []
    pending: dict = {}
    for op in history:
        if op.is_invoke:
            pending[op.process] = op
        elif op.process in pending:
            out.append((pending.pop(op.process), op))
    for inv in pending.values():
        out.append((inv, None))
    out.sort(key=lambda p: p[0].time or 0)
    return out


def _op_div(inv: Op, completion: Op | None, lane: int) -> str:
    t0 = inv.time or 0
    t1 = completion.time if completion is not None and \
        completion.time is not None else t0 + int(5e6)
    ctype = completion.type if completion is not None else None
    color = TYPE_COLORS.get(ctype, "#DDDDDD")
    top = t0 / NS_PER_PX
    height = max(1.0, (t1 - t0) / NS_PER_PX)
    completed_value = repr(completion.value) if completion is not None else ""
    title = _html.escape(
        f"process {inv.process} | {inv.f} {inv.value!r} -> "
        f"{ctype or 'never returned'} {completed_value} | "
        f"{t0 / 1e6:.2f}ms +{(t1 - t0) / 1e6:.2f}ms")
    label = _html.escape(f"{inv.f} {inv.value!r}"[:28])
    return (f'<div class="op" title="{title}" style="top:{top:.1f}px;'
            f'height:{height:.1f}px;left:{lane * 110}px;'
            f'background:{color}">{label}</div>')


def html(test, history, opts=None) -> str:
    """Render the timeline document (timeline.clj:159-179)."""
    ps = pairs(op for op in history if op.process != "nemesis")
    lanes: dict = {}
    for inv, _ in ps:
        thread = inv.process if not isinstance(inv.process, int) else \
            inv.process % max(1, (test or {}).get("concurrency", 1) or 1)
        lanes.setdefault(thread, len(lanes))
    divs = [_op_div(inv, comp, lanes[
        inv.process if not isinstance(inv.process, int)
        else inv.process % max(1, (test or {}).get("concurrency", 1) or 1)])
        for inv, comp in ps]
    headers = "".join(
        f'<div class="lane-h" style="left:{i * 110}px">thread {t}</div>'
        for t, i in lanes.items())
    name = (test or {}).get("name", "")
    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{_html.escape(str(name))} timeline</title>
<style>
body {{ font-family: monospace; margin: 0; }}
.lanes {{ position: relative; margin-top: 30px; }}
.lane-h {{ position: fixed; top: 0; width: 105px; background: #eee;
           padding: 4px; font-weight: bold; z-index: 2; }}
.op {{ position: absolute; width: 105px; overflow: hidden;
       font-size: 9px; border: 1px solid #999; box-sizing: border-box; }}
</style></head>
<body>{headers}<div class="lanes">{"".join(divs)}</div></body></html>"""


def checker() -> checker_ns.Checker:
    """A checker that writes timeline.html and always passes
    (timeline.clj:159-179)."""

    def check(test, model, history, opts):
        doc = html(test, history, opts)
        try:
            from jepsen_tpu import store

            if test is not None and test.get("name"):
                path = store.path(test, (opts or {}).get("subdirectory"),
                                  "timeline.html", make=True)
                path.write_text(doc)
        except Exception:  # noqa: BLE001 - artifact is best-effort
            pass
        return {checker_ns.VALID: True}

    return checker_ns.FnChecker(check)
