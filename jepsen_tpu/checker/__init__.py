"""History validators.

TPU-native re-design of the reference's `jepsen/src/jepsen/checker.clj`
(411 LoC). A checker validates a recorded history against a model and
returns a result map with a ``"valid?"`` key — ``True``, ``False`` or
``"unknown"`` (checker.clj:46-61). ``linearizable`` is the expensive one:
in the reference it delegates to the external knossos solver
(checker.clj:82-107); here it dispatches to :mod:`jepsen_tpu.lin` — the
device BFS kernel (``algorithm="tpu"``) or the CPU reference
(``algorithm="cpu"``), with ``"competition"`` racing both like
knossos.competition (checker.clj:90-93). The rest are O(n) scans.
"""

from __future__ import annotations

import traceback
from collections import Counter
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable

from jepsen_tpu import models as model_ns
from jepsen_tpu.history import Op
from jepsen_tpu.util import fraction, integer_interval_set_str

VALID = "valid?"

# Larger numbers dominate when checkers are composed (checker.clj:23-28).
_VALID_PRIORITIES = {True: 0, False: 1, "unknown": 0.5}


def merge_valid(valids) -> Any:
    """Merge valid? values, yielding the highest-priority one
    (checker.clj:30-44). Raises on unknown values, like the reference."""
    out = True
    for v in valids:
        for x in (out, v):
            if x not in _VALID_PRIORITIES:
                raise ValueError(f"{x!r} is not a known valid? value")
        if _VALID_PRIORITIES[v] > _VALID_PRIORITIES[out]:
            out = v
    return out


class Checker:
    """Verify a history is correct (checker.clj:46-61). Returns a map like
    ``{"valid?": True}`` or ``{"valid?": False, ...details}``. ``opts`` may
    carry ``subdirectory`` for file-emitting checkers."""

    def check(self, test, model, history, opts=None) -> dict:
        raise NotImplementedError


class FnChecker(Checker):
    def __init__(self, fn: Callable):
        self.fn = fn

    def check(self, test, model, history, opts=None):
        return self.fn(test, model, history, opts or {})


def check_safe(checker: Checker, test, model, history, opts=None) -> dict:
    """Like check, but wraps exceptions into
    ``{"valid?": "unknown", "error": ...}`` (checker.clj:63-74)."""
    try:
        return checker.check(test, model, history, opts or {})
    except Exception:
        return {VALID: "unknown", "error": traceback.format_exc()}


def unbridled_optimism() -> Checker:
    """Everything is awesoooommmmme! (checker.clj:76-80)"""
    return FnChecker(lambda test, model, history, opts: {VALID: True})


def linearizable(algorithm: str = "competition",
                 time_budget: float | None = None, **kw) -> Checker:
    """Validates linearizability (checker.clj:82-107).

    ``algorithm`` is one of:

    - ``"tpu"``  — the device BFS frontier kernel (:mod:`jepsen_tpu.lin.bfs`)
    - ``"cpu"``  — the host reference search (:mod:`jepsen_tpu.lin.cpu`)
    - ``"competition"`` — race both, first verdict wins (knossos.competition)

    ``time_budget`` (seconds) caps the search: when it fires, the host
    and device searches are cancelled between rows/chunks and the result
    is an honest ``"unknown"`` with the reason — a hostile wide-window
    history in a suite run degrades to "unknown" instead of hanging the
    analysis phase (knossos truncates output for the same reason,
    checker.clj:104-107).

    Like the reference, the analysis result is truncated (writing full
    configs "can take *hours*", checker.clj:104-107).
    """

    def check(test, model, history, opts):
        import threading

        from jepsen_tpu import lin

        # Counterexample paths by default, like knossos: the host racer
        # tracks witness order; the device racer replays the failing tail
        # (checker.clj:96-107 renders :final-paths from these).
        kw2 = dict(kw)
        if algorithm in ("cpu", "competition"):
            kw2.setdefault("witness", True)
        if algorithm in ("tpu", "competition"):
            kw2.setdefault("explain", True)
        timer = None
        timed_out = None
        if time_budget is not None:
            cancel = kw2.setdefault("cancel", threading.Event())
            timed_out = threading.Event()

            def fire():
                # Separate flag: the competition race also sets the
                # shared cancel event to stop the losing racer, which
                # must not read as a budget overrun.
                timed_out.set()
                cancel.set()

            timer = threading.Timer(time_budget, fire)
            timer.daemon = True
            timer.start()
        try:
            a = lin.analysis(model, history, algorithm=algorithm, **kw2)
        finally:
            if timer is not None:
                timer.cancel()
        a = dict(a)
        if timed_out is not None and timed_out.is_set() \
                and a.get(VALID) not in (True, False):
            a[VALID] = "unknown"
            a["error"] = (f"time budget {time_budget}s exceeded: "
                          f"{a.get('error', 'search cancelled')}")
        if not a.get(VALID, False):
            try:
                from jepsen_tpu.lin import report as lin_report
                from jepsen_tpu import store

                if test is not None and isinstance(test, dict) \
                        and test.get("name"):
                    path = store.path(test, (opts or {}).get("subdirectory"),
                                      "linear.svg", make=True)
                    lin_report.render_analysis(history, a, path)
            except Exception:
                pass  # rendering is best-effort, like checker.clj:96-103
        a["final-paths"] = list(a.get("final-paths", []))[:10]
        a["configs"] = list(a.get("configs", []))[:10]
        return a

    ck = FnChecker(check)
    # Marker consumed by jepsen_tpu.independent: only a pure linearizable
    # checker may be replaced by the batched device search.
    ck.is_linearizable = True
    ck.algorithm = algorithm
    return ck


def txn_cycles(anomalies=None, consistency: str = "serializable",
               algorithm: str = "tpu", realtime: bool | None = None) \
        -> Checker:
    """Validates transactional isolation of list-append histories by
    dependency-graph cycle search (:mod:`jepsen_tpu.txn` — Elle's
    analysis in Adya's formalization; the SQL suites' checker).

    ``anomalies`` — explicit anomaly tuple (e.g. ``("G0", "G1c")``), or
    None to derive from ``consistency`` ("serializable",
    "snapshot-isolation", "strict-serializable", "read-committed").
    ``algorithm`` — ``"tpu"`` (the device SCC engine with its host
    fallback ladder) or ``"cpu"`` (the oracle).
    ``realtime`` — force realtime edges on/off (default: on exactly for
    strict-serializable)."""

    def check(test, model, history, opts):
        from jepsen_tpu import txn

        return txn.check(list(history), anomalies=anomalies,
                         consistency=consistency, realtime=realtime,
                         algorithm=algorithm)

    ck = FnChecker(check)
    ck.is_txn_cycles = True
    ck.algorithm = algorithm
    return ck


def queue() -> Checker:
    """Every dequeue must come from somewhere: assume every non-failing
    enqueue succeeded and only OK dequeues succeeded, then fold the model
    over that history (checker.clj:109-129). O(n)."""

    def check(test, model, history, opts):
        final = model
        for op in history:
            take = (op.is_invoke if op.f == "enqueue"
                    else op.is_ok if op.f == "dequeue" else False)
            if take:
                final = final.step(op)
                if model_ns.is_inconsistent(final):
                    return {VALID: False, "error": final.msg}
        return {VALID: True, "final-queue": final}

    return FnChecker(check)


def set_checker() -> Checker:
    """Adds followed by a final read: every successful add must be present,
    and nothing never-attempted may appear (checker.clj:131-178)."""

    def check(test, model, history, opts):
        attempts = {op.value for op in history
                    if op.is_invoke and op.f == "add"}
        adds = {op.value for op in history if op.is_ok and op.f == "add"}
        final_read = None
        for op in history:
            if op.is_ok and op.f == "read":
                final_read = op.value
        if final_read is None:
            return {VALID: "unknown", "error": "Set was never read"}

        final_read = set(final_read)
        ok = final_read & attempts             # read values we tried to add
        unexpected = final_read - attempts     # never-attempted records
        lost = adds - final_read               # definitely added, not read
        recovered = ok - adds                  # indeterminate adds that won

        return {VALID: not lost and not unexpected,
                "ok": integer_interval_set_str(ok),
                "lost": integer_interval_set_str(lost),
                "unexpected": integer_interval_set_str(unexpected),
                "recovered": integer_interval_set_str(recovered),
                "ok-frac": fraction(len(ok), len(attempts)),
                "unexpected-frac": fraction(len(unexpected), len(attempts)),
                "lost-frac": fraction(len(lost), len(attempts)),
                "recovered-frac": fraction(len(recovered), len(attempts))}

    return FnChecker(check)


def expand_queue_drain_ops(history) -> list[Op]:
    """Expand successful :drain ops (value = collection of elements) into
    :dequeue invoke/ok pairs (checker.clj:180-212)."""
    out: list[Op] = []
    for op in history:
        if op.f != "drain":
            out.append(op)
        elif op.is_invoke or op.is_fail:
            continue
        elif op.is_ok:
            for element in op.value or []:
                out.append(op.replace(type="invoke", f="dequeue", value=None))
                out.append(op.replace(type="ok", f="dequeue", value=element))
        else:
            raise ValueError(
                f"Not sure how to handle a crashed drain operation: {op}")
    return out


def total_queue() -> Checker:
    """What goes in *must* come out; requires the history to drain the queue
    (checker.clj:214-271). O(n)."""

    def check(test, model, history, opts):
        history = expand_queue_drain_ops(history)
        attempts = Counter(op.value for op in history
                           if op.is_invoke and op.f == "enqueue")
        enqueues = Counter(op.value for op in history
                           if op.is_ok and op.f == "enqueue")
        dequeues = Counter(op.value for op in history
                           if op.is_ok and op.f == "dequeue")

        ok = dequeues & attempts
        # Dequeues of values never even attempted (checker.clj:243-246).
        unexpected = Counter({v: n for v, n in dequeues.items()
                              if v not in attempts})
        # Dequeued more times than attempted, but attempted at least once.
        duplicated = dequeues - attempts - unexpected
        lost = enqueues - dequeues
        # Dequeues whose enqueue was indeterminate but present.
        recovered = ok - enqueues

        def total(ms: Counter) -> int:
            return sum(ms.values())

        n = total(attempts)
        return {VALID: not lost and not unexpected,
                "lost": lost, "unexpected": unexpected,
                "duplicated": duplicated, "recovered": recovered,
                "ok-frac": fraction(total(ok), n),
                "unexpected-frac": fraction(total(unexpected), n),
                "duplicated-frac": fraction(total(duplicated), n),
                "lost-frac": fraction(total(lost), n),
                "recovered-frac": fraction(total(recovered), n)}

    return FnChecker(check)


def unique_ids() -> Checker:
    """A unique-id generator must emit unique IDs: :generate invocations
    matched by :ok responses with distinct values (checker.clj:273-318)."""

    def check(test, model, history, opts):
        attempted = sum(1 for op in history
                        if op.is_invoke and op.f == "generate")
        acks = [op.value for op in history
                if op.is_ok and op.f == "generate"]
        counts = Counter(acks)
        dups = {k: v for k, v in counts.items() if v > 1}
        rng = [min(acks), max(acks)] if acks else [None, None]
        top_dups = dict(sorted(dups.items(), key=lambda kv: -kv[1])[:48])
        return {VALID: not dups,
                "attempted-count": attempted,
                "acknowledged-count": len(acks),
                "duplicated-count": len(dups),
                "duplicated": top_dups,
                "range": rng}

    return FnChecker(check)


def counter() -> Checker:
    """A monotonically-increasing counter: each read must fall between the
    sum of :ok increments and the sum of attempted increments at that point
    (checker.clj:321-374)."""

    def check(test, model, history, opts):
        from jepsen_tpu.history import complete

        lower = 0            # sum of definite (ok) increments
        upper = 0            # sum of attempted increments
        pending_reads: dict[Any, list] = {}   # process -> [lower, read-val]
        reads: list[list] = []                # completed [lower val upper]
        for op in complete(list(history)):
            key = (op.type, op.f)
            if key == ("invoke", "read"):
                pending_reads[op.process] = [lower, op.value]
            elif key == ("ok", "read"):
                r = pending_reads.pop(op.process, None)
                if r is not None:
                    reads.append(r + [upper])
            elif key == ("invoke", "add"):
                upper += op.value
            elif key == ("ok", "add"):
                lower += op.value
        errors = [r for r in reads if not (r[0] <= r[1] <= r[2])]
        return {VALID: not errors, "reads": reads, "errors": errors}

    return FnChecker(check)


def compose(checker_map: dict) -> Checker:
    """Run each named checker (in parallel, like the reference's pmap at
    checker.clj:382-388) and merge their valid? verdicts."""

    def check(test, model, history, opts):
        items = list(checker_map.items())
        with ThreadPoolExecutor(max_workers=max(1, len(items))) as pool:
            rs = list(pool.map(
                lambda kv: (kv[0], check_safe(kv[1], test, model, history,
                                              opts)),
                items))
        results = dict(rs)
        results[VALID] = merge_valid([r[VALID] for _, r in rs])
        return results

    return FnChecker(check)


def latency_graph() -> Checker:
    """Latency point + quantile graphs (checker.clj:390-397); matplotlib
    replaces the reference's gnuplot subprocess."""

    def check(test, model, history, opts):
        from jepsen_tpu.checker import perf_graphs as perf_mod

        perf_mod.point_graph(test, history, opts)
        perf_mod.quantiles_graph(test, history, opts)
        return {VALID: True}

    return FnChecker(check)


def rate_graph() -> Checker:
    """Throughput-over-time graph (checker.clj:399-405)."""

    def check(test, model, history, opts):
        from jepsen_tpu.checker import perf_graphs as perf_mod

        perf_mod.rate_graph(test, history, opts)
        return {VALID: True}

    return FnChecker(check)


def perf() -> Checker:
    """Assorted performance statistics (checker.clj:407-411)."""
    return compose({"latency-graph": latency_graph(),
                    "rate-graph": rate_graph()})
