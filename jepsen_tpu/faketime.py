"""Per-process clock-rate skew via libfaketime wrappers.

Re-design of `jepsen/src/jepsen/faketime.clj` (31 LoC): replaces a DB
binary with a shell wrapper that launches it under ``faketime`` with a
random rate, so one node's process experiences accelerated/dilated time.
"""

from __future__ import annotations

import random

from jepsen_tpu import control as c


def script(binary: str, rate: float) -> str:
    """A wrapper script running binary under faketime at a given rate
    (faketime.clj:8-19)."""
    return ("#!/bin/bash\n"
            f"exec faketime -m -f \"+0s x{rate:.4f}\" "
            f"{binary}.real \"$@\"\n")


def wrap(binary: str, rate: float | None = None) -> None:
    """Move binary to binary.real and install the faketime wrapper in its
    place; idempotent (faketime.clj:21-31)."""
    rate = rate if rate is not None else random.uniform(0.5, 1.5)
    real = f"{binary}.real"
    with c.su():
        c.exec_(c.Lit(
            f"test -f {real} || mv {binary} {real}"))
        c.exec_("tee", binary, stdin=script(binary, rate))
        c.exec_("chmod", "a+x", binary)


def unwrap(binary: str) -> None:
    """Restore the original binary."""
    real = f"{binary}.real"
    with c.su():
        c.exec_(c.Lit(
            f"test -f {real} && mv {real} {binary} || true"))
