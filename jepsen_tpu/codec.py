"""Serialization of client payloads to bytes.

The analogue of `jepsen/src/jepsen/codec.clj` (29 LoC): the reference
round-trips op values through EDN strings (`encode` :10-16, `decode`
:18-29) so clients can ship arbitrary structures over DB wire protocols
that only carry bytes/strings. Here the wire form is JSON with a small
tagging scheme for the non-JSON types Jepsen values actually use (tuples,
sets, bytes), chosen because every DB client library in the Python
ecosystem can carry JSON strings.
"""

from __future__ import annotations

import base64
import json
from typing import Any

_TAG = "__jepsen__"


def _encode_value(v: Any):
    if isinstance(v, tuple):
        return {_TAG: "tuple", "v": [_encode_value(x) for x in v]}
    if isinstance(v, frozenset):
        # Distinct tag: a frozenset may sit inside another hashable
        # container (set element, dict key) where a mutable set can't.
        return {_TAG: "fset", "v": sorted((_encode_value(x) for x in v),
                                          key=repr)}
    if isinstance(v, set):
        return {_TAG: "set", "v": sorted((_encode_value(x) for x in v),
                                         key=repr)}
    if isinstance(v, bytes):
        return {_TAG: "bytes", "v": base64.b64encode(v).decode("ascii")}
    if isinstance(v, dict):
        if all(isinstance(k, str) for k in v) and _TAG not in v:
            return {k: _encode_value(x) for k, x in v.items()}
        # Non-string keys would be coerced by JSON; carry as pairs.
        return {_TAG: "dict",
                "v": [[_encode_value(k), _encode_value(x)]
                      for k, x in v.items()]}
    if isinstance(v, list):
        return [_encode_value(x) for x in v]
    return v


def _decode_value(v: Any):
    if isinstance(v, dict):
        tag = v.get(_TAG)
        if tag == "tuple":
            return tuple(_decode_value(x) for x in v["v"])
        if tag == "set":
            return set(_decode_value(x) for x in v["v"])
        if tag == "fset":
            return frozenset(_decode_value(x) for x in v["v"])
        if tag == "bytes":
            return base64.b64decode(v["v"])
        if tag == "dict":
            return {_decode_value(k): _decode_value(x) for k, x in v["v"]}
        return {k: _decode_value(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode_value(x) for x in v]
    return v


def encode(obj: Any) -> bytes:
    """Serialize an object to bytes (codec.clj:10-16). ``None`` encodes to
    the empty byte string, mirroring the reference's nil handling."""
    if obj is None:
        return b""
    return json.dumps(_encode_value(obj),
                      separators=(",", ":")).encode("utf-8")


def decode(data: bytes | None) -> Any:
    """Deserialize bytes produced by :func:`encode` (codec.clj:18-29).
    Empty/None input decodes to ``None``."""
    if not data:
        return None
    if isinstance(data, str):
        data = data.encode("utf-8")
    return _decode_value(json.loads(data.decode("utf-8")))
