"""Persistence: test run directories, histories, results, symlinks, logs.

Re-design of `jepsen/src/jepsen/store.clj` (345 LoC). Layout matches the
reference: ``store/<test-name>/<timestamp>/`` holding history + test +
results, with ``latest`` symlinks (store.clj:235-247) and two-phase saves
(`save_1` after the run, store.clj:279-290; `save_2` after analysis,
store.clj:292-302). JSON/JSONL replaces Fressian/EDN as the portable
serialization; runtime objects are excluded via nonserializable keys
(store.clj:155-163).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import shutil
from pathlib import Path
from typing import Any

from jepsen_tpu import history as history_mod

BASE = Path("store")

NONSERIALIZABLE_KEYS = (
    # Runtime objects (store.clj:155-163): barriers, sessions, live handles
    "db", "os", "net", "client", "checker", "nemesis", "generator", "model",
    "sessions", "barrier", "active-histories", "transport", "remote",
)


def _sanitize(v: Any):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        if isinstance(v, dict):
            return {str(k): _sanitize(x) for k, x in v.items()}
        if isinstance(v, (list, tuple, set, frozenset)):
            return [_sanitize(x) for x in v]
        if isinstance(v, history_mod.Op):
            return _sanitize(v.to_dict())
        return repr(v)


def serializable_test(test: dict) -> dict:
    return {k: _sanitize(v) for k, v in test.items()
            if k not in NONSERIALIZABLE_KEYS and k != "history"}


def dir_name(test: dict) -> str:
    t = test.get("start-time") or _dt.datetime.now()
    if isinstance(t, _dt.datetime):
        return t.strftime("%Y%m%dT%H%M%S.%f")[:-3]
    return str(t)


def path(test: dict, *components, make: bool = False) -> Path:
    """Path within a test's store directory (store.clj:113-142); with
    make=True, creates parent directories (`path!`)."""
    flat: list = []
    for c in components:
        if c is None:
            continue
        if isinstance(c, (list, tuple)):
            flat.extend(str(x) for x in c if x is not None)
        else:
            flat.append(c)
    components = flat
    base = Path(test.get("store-base", BASE))
    p = base / str(test.get("name", "noname")) / dir_name(test)
    for comp in components:
        p = p / str(comp)
    if make:
        target_dir = p if not components else p.parent
        target_dir.mkdir(parents=True, exist_ok=True)
    return p


def update_symlinks(test: dict) -> None:
    """Point store/<name>/latest and store/latest at this run
    (store.clj:235-247)."""
    run_dir = path(test, make=True)
    base = Path(test.get("store-base", BASE))
    for link, target in ((base / str(test.get("name", "noname")) / "latest",
                          run_dir),
                         (base / "latest", run_dir)):
        try:
            if link.is_symlink() or link.exists():
                link.unlink()
            link.symlink_to(target.resolve())
        except OSError:
            pass


def write_history(test: dict) -> None:
    """history.txt (human-readable) + history.jsonl (machine)
    (store.clj:265-277); parallel chunked writing in the reference
    (util.clj:149-170) is replaced by buffered streaming."""
    hist = test.get("history") or []
    p = path(test, "history.jsonl", make=True)
    history_mod.write_history(p, hist)
    with open(path(test, "history.txt"), "w") as fh:
        for op in hist:
            fh.write(f"{op.process!r:<12} {op.type:<8} {op.f!r:<16} "
                     f"{op.value!r}\n")


def write_results(test: dict) -> None:
    with open(path(test, "results.json", make=True), "w") as fh:
        json.dump(_sanitize(test.get("results", {})), fh, indent=2)


def write_test(test: dict) -> None:
    with open(path(test, "test.json", make=True), "w") as fh:
        json.dump(serializable_test(test), fh, indent=2)


def save_1(test: dict) -> dict:
    """Phase 1: after the run, before analysis — history + test
    (store.clj:279-290)."""
    write_history(test)
    write_test(test)
    update_symlinks(test)
    return test


def save_2(test: dict) -> dict:
    """Phase 2: after analysis — results (store.clj:292-302)."""
    write_results(test)
    write_test(test)
    update_symlinks(test)
    return test


# Evidence artifacts the run-directory flow guarantees (and web.py's
# home/dir pages link): the perf graphs + timeline next to
# history/results, whether or not the test composed the
# checker.perf()/timeline checkers.
RUN_ARTIFACTS = ("timeline.html", "latency-raw.png",
                 "latency-quantiles.png", "rate.png")

# Backfill ceiling: past this many ops the timeline's div-per-op HTML
# reaches tens of MB and the matplotlib renders take seconds of
# serial wall at run completion — big runs keep the OPT-IN cost model
# (compose checker.perf()/timeline.checker() explicitly).
ARTIFACT_MAX_OPS = 20_000


def find_artifacts(run_dir: Path) -> dict[str, Path]:
    """First match of each evidence artifact in a run dir's root or
    ONE subdirectory level down (a composed checker's
    ``opts["subdirectory"]``), root winning. Deliberately NOT a full
    tree walk: deeper matches (e.g. the independent checker's per-KEY
    ``independent/<key>/timeline.html``) are a key's evidence, not
    the run's, and web's home page pays this scan per run per
    request. THE lookup shared by the backfill's skip rule and
    web.py's evidence links, so what the backfill counts as present
    is exactly what the pages link."""
    out: dict[str, Path] = {}
    if not run_dir.is_dir():
        return out
    try:
        entries = sorted(os.scandir(run_dir), key=lambda e: e.name)
    except OSError:
        return out
    subdirs = []
    for e in entries:
        if e.is_dir(follow_symlinks=False):
            subdirs.append(e.path)
        elif e.name in RUN_ARTIFACTS and e.name not in out:
            out[e.name] = run_dir / e.name
    for sd in subdirs:
        try:
            for e in sorted(os.scandir(sd), key=lambda e: e.name):
                if not e.is_dir(follow_symlinks=False) \
                        and e.name in RUN_ARTIFACTS \
                        and e.name not in out:
                    out[e.name] = Path(e.path)
        except OSError:
            continue
    return out


def write_run_artifacts(test: dict) -> list[str]:
    """Backfill a run directory's latency/rate/timeline evidence
    (checker/perf_graphs.py + checker/timeline.py) after analysis:
    artifacts a composed checker already wrote are left alone; missing
    ones are rendered best-effort per file (matplotlib or an empty
    history must never fail a run — the timeline.checker() contract).
    Histories past ``ARTIFACT_MAX_OPS`` are skipped entirely (cost
    guard; see the constant). Returns the filenames written. Called
    from ``core.run`` as part of the store flow, so every named run's
    evidence is one click from its perf-ledger row (web.py home/dir
    pages, doc/observability.md § Perf ledger)."""
    written: list[str] = []
    if not isinstance(test, dict):
        return written
    hist = test.get("history") or []
    if not (test.get("name") and hist
            and len(hist) <= ARTIFACT_MAX_OPS):
        return written

    present = find_artifacts(path(test))

    def missing(fname: str) -> bool:
        return fname not in present

    try:
        if missing("timeline.html"):
            from jepsen_tpu.checker import timeline as timeline_mod

            p = path(test, "timeline.html", make=True)
            p.write_text(timeline_mod.html(test, hist))
            written.append("timeline.html")
    except Exception:  # noqa: BLE001 - artifacts are best-effort
        pass
    try:
        from jepsen_tpu.checker import perf_graphs as perf_mod

        for fname, fn in (("latency-raw.png", perf_mod.point_graph),
                          ("latency-quantiles.png",
                           perf_mod.quantiles_graph),
                          ("rate.png", perf_mod.rate_graph)):
            if not missing(fname):
                continue
            try:
                fn(test, hist)
                written.append(fname)
            except Exception:  # noqa: BLE001 - per-graph isolation
                pass
    except Exception:  # noqa: BLE001 - no matplotlib, no graphs
        pass
    return written


def load(name: str, ts: str, base=BASE) -> dict:
    """Reload a saved test for re-analysis (store.clj:165-171)."""
    d = Path(base) / name / ts
    test = json.loads((d / "test.json").read_text())
    hist_path = d / "history.jsonl"
    if hist_path.exists():
        test["history"] = history_mod.read_history(hist_path)
    results = d / "results.json"
    if results.exists():
        test["results"] = json.loads(results.read_text())
    return test


def tests(name: str, base=BASE) -> dict:
    """{timestamp: loader} for each saved run of a test
    (store.clj:214-233)."""
    d = Path(base) / name
    out = {}
    if d.is_dir():
        for ts in sorted(os.listdir(d)):
            if ts != "latest" and (d / ts).is_dir():
                out[ts] = (lambda t=ts: load(name, t, base))
    return out


def all_tests(base=BASE) -> dict:
    base = Path(base)
    out = {}
    if base.is_dir():
        for name in sorted(os.listdir(base)):
            if name != "latest" and (base / name).is_dir():
                out[name] = tests(name, base)
    return out


def delete(name: str, ts: str | None = None, base=BASE) -> None:
    """Delete a run, or every run of a test (store.clj:337-345)."""
    d = Path(base) / name
    if ts:
        d = d / ts
    if d.exists():
        shutil.rmtree(d)


# --- logging (store.clj:304-326: unilog console + per-test jepsen.log) ------

_handler: logging.Handler | None = None


def start_logging(test: dict) -> None:
    global _handler
    stop_logging()
    p = path(test, "jepsen.log", make=True)
    _handler = logging.FileHandler(p)
    _handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(threadName)s %(name)s - %(message)s"))
    root = logging.getLogger()
    root.addHandler(_handler)
    if root.level > logging.INFO or root.level == logging.NOTSET:
        root.setLevel(logging.INFO)


def stop_logging() -> None:
    global _handler
    if _handler is not None:
        logging.getLogger().removeHandler(_handler)
        _handler.close()
        _handler = None
