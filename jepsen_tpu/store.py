"""Persistence: test run directories, histories, results, symlinks, logs.

Re-design of `jepsen/src/jepsen/store.clj` (345 LoC). Layout matches the
reference: ``store/<test-name>/<timestamp>/`` holding history + test +
results, with ``latest`` symlinks (store.clj:235-247) and two-phase saves
(`save_1` after the run, store.clj:279-290; `save_2` after analysis,
store.clj:292-302). JSON/JSONL replaces Fressian/EDN as the portable
serialization; runtime objects are excluded via nonserializable keys
(store.clj:155-163).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import shutil
from pathlib import Path
from typing import Any

from jepsen_tpu import history as history_mod

BASE = Path("store")

NONSERIALIZABLE_KEYS = (
    # Runtime objects (store.clj:155-163): barriers, sessions, live handles
    "db", "os", "net", "client", "checker", "nemesis", "generator", "model",
    "sessions", "barrier", "active-histories", "transport", "remote",
)


def _sanitize(v: Any):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        if isinstance(v, dict):
            return {str(k): _sanitize(x) for k, x in v.items()}
        if isinstance(v, (list, tuple, set, frozenset)):
            return [_sanitize(x) for x in v]
        if isinstance(v, history_mod.Op):
            return _sanitize(v.to_dict())
        return repr(v)


def serializable_test(test: dict) -> dict:
    return {k: _sanitize(v) for k, v in test.items()
            if k not in NONSERIALIZABLE_KEYS and k != "history"}


def dir_name(test: dict) -> str:
    t = test.get("start-time") or _dt.datetime.now()
    if isinstance(t, _dt.datetime):
        return t.strftime("%Y%m%dT%H%M%S.%f")[:-3]
    return str(t)


def path(test: dict, *components, make: bool = False) -> Path:
    """Path within a test's store directory (store.clj:113-142); with
    make=True, creates parent directories (`path!`)."""
    flat: list = []
    for c in components:
        if c is None:
            continue
        if isinstance(c, (list, tuple)):
            flat.extend(str(x) for x in c if x is not None)
        else:
            flat.append(c)
    components = flat
    base = Path(test.get("store-base", BASE))
    p = base / str(test.get("name", "noname")) / dir_name(test)
    for comp in components:
        p = p / str(comp)
    if make:
        target_dir = p if not components else p.parent
        target_dir.mkdir(parents=True, exist_ok=True)
    return p


def update_symlinks(test: dict) -> None:
    """Point store/<name>/latest and store/latest at this run
    (store.clj:235-247)."""
    run_dir = path(test, make=True)
    base = Path(test.get("store-base", BASE))
    for link, target in ((base / str(test.get("name", "noname")) / "latest",
                          run_dir),
                         (base / "latest", run_dir)):
        try:
            if link.is_symlink() or link.exists():
                link.unlink()
            link.symlink_to(target.resolve())
        except OSError:
            pass


def write_history(test: dict) -> None:
    """history.txt (human-readable) + history.jsonl (machine)
    (store.clj:265-277); parallel chunked writing in the reference
    (util.clj:149-170) is replaced by buffered streaming."""
    hist = test.get("history") or []
    p = path(test, "history.jsonl", make=True)
    history_mod.write_history(p, hist)
    with open(path(test, "history.txt"), "w") as fh:
        for op in hist:
            fh.write(f"{op.process!r:<12} {op.type:<8} {op.f!r:<16} "
                     f"{op.value!r}\n")


def write_results(test: dict) -> None:
    with open(path(test, "results.json", make=True), "w") as fh:
        json.dump(_sanitize(test.get("results", {})), fh, indent=2)


def write_test(test: dict) -> None:
    with open(path(test, "test.json", make=True), "w") as fh:
        json.dump(serializable_test(test), fh, indent=2)


def save_1(test: dict) -> dict:
    """Phase 1: after the run, before analysis — history + test
    (store.clj:279-290)."""
    write_history(test)
    write_test(test)
    update_symlinks(test)
    return test


def save_2(test: dict) -> dict:
    """Phase 2: after analysis — results (store.clj:292-302)."""
    write_results(test)
    write_test(test)
    update_symlinks(test)
    return test


def load(name: str, ts: str, base=BASE) -> dict:
    """Reload a saved test for re-analysis (store.clj:165-171)."""
    d = Path(base) / name / ts
    test = json.loads((d / "test.json").read_text())
    hist_path = d / "history.jsonl"
    if hist_path.exists():
        test["history"] = history_mod.read_history(hist_path)
    results = d / "results.json"
    if results.exists():
        test["results"] = json.loads(results.read_text())
    return test


def tests(name: str, base=BASE) -> dict:
    """{timestamp: loader} for each saved run of a test
    (store.clj:214-233)."""
    d = Path(base) / name
    out = {}
    if d.is_dir():
        for ts in sorted(os.listdir(d)):
            if ts != "latest" and (d / ts).is_dir():
                out[ts] = (lambda t=ts: load(name, t, base))
    return out


def all_tests(base=BASE) -> dict:
    base = Path(base)
    out = {}
    if base.is_dir():
        for name in sorted(os.listdir(base)):
            if name != "latest" and (base / name).is_dir():
                out[name] = tests(name, base)
    return out


def delete(name: str, ts: str | None = None, base=BASE) -> None:
    """Delete a run, or every run of a test (store.clj:337-345)."""
    d = Path(base) / name
    if ts:
        d = d / ts
    if d.exists():
        shutil.rmtree(d)


# --- logging (store.clj:304-326: unilog console + per-test jepsen.log) ------

_handler: logging.Handler | None = None


def start_logging(test: dict) -> None:
    global _handler
    stop_logging()
    p = path(test, "jepsen.log", make=True)
    _handler = logging.FileHandler(p)
    _handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(threadName)s %(name)s - %(message)s"))
    root = logging.getLogger()
    root.addHandler(_handler)
    if root.level > logging.INFO or root.level == logging.NOTSET:
        root.setLevel(logging.INFO)


def stop_logging() -> None:
    global _handler
    if _handler is not None:
        logging.getLogger().removeHandler(_handler)
        _handler.close()
        _handler = None
