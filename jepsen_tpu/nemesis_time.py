"""Clock fault nemesis: precise bump/strobe via native programs compiled on
the DB nodes.

Re-design of `jepsen/src/jepsen/nemesis/time.clj` (~125 LoC): uploads the
C++ sources from ``native/`` and compiles them with the node's g++/gcc
(time.clj:12-27 does exactly this with gcc), then drives clock resets,
signed millisecond bumps, and strobe oscillations, plus randomized
generators for each (time.clj:92-125).
"""

from __future__ import annotations

import os.path
import random

from jepsen_tpu import control as c
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu.history import Op

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                          "native")
REMOTE_DIR = "/opt/jepsen"


def compile_tool(src_name: str, bin_name: str) -> None:
    """Upload a C++ source and build it on the node (time.clj:12-27)."""
    with c.su():
        c.exec_("mkdir", "-p", REMOTE_DIR)
    local = os.path.join(NATIVE_DIR, src_name)
    remote_src = f"{REMOTE_DIR}/{src_name}"
    c.upload(local, remote_src)
    with c.su():
        compiler = "g++"
        try:
            c.exec_("which", "g++")
        except c.RemoteError:
            compiler = "gcc"
        c.exec_(compiler, "-O2", "-o", f"{REMOTE_DIR}/{bin_name}",
                remote_src)


def install() -> None:
    """Build both clock tools on the bound node (time.clj:34-42)."""
    compile_tool("bump_time.cc", "bump-time")
    compile_tool("strobe_time.cc", "strobe-time")


def reset_time() -> None:
    """Resynchronize with NTP (time.clj:44-47)."""
    with c.su():
        c.exec_("ntpdate", "-p", "1", "-b", "pool.ntp.org", may_fail=True)


def bump_time(delta_ms: float) -> None:
    """Jump the bound node's clock by delta ms (time.clj:49-52)."""
    with c.su():
        c.exec_(f"{REMOTE_DIR}/bump-time", int(delta_ms))


def strobe_time(delta_ms: float, period_ms: float, duration_s: float):
    """Oscillate the clock by delta every period for duration
    (time.clj:54-58)."""
    with c.su():
        c.exec_(f"{REMOTE_DIR}/strobe-time", int(delta_ms),
                int(period_ms), int(duration_s))


class ClockNemesis(nemesis_ns.Nemesis):
    """Responds to :reset / :bump / :strobe ops (time.clj:60-90).

    - ``{:f :reset,  :value [nodes...]}``
    - ``{:f :bump,   :value {node: delta-ms}}``
    - ``{:f :strobe, :value {node: {delta, period, duration}}}``
    """

    def setup(self, test):
        c.on_nodes(test, lambda t, n: install())
        # Stop ntp daemons so they don't fight the nemesis (time.clj:63-69).
        def stop_ntp(t, n):
            with c.su():
                c.exec_("service", "ntp", "stop", may_fail=True)
        c.on_nodes(test, stop_ntp)
        return self

    def invoke(self, test, op):
        if op.f == "reset":
            nodes = op.value or test["nodes"]
            c.on_nodes(test, lambda t, n: reset_time(), nodes=nodes)
            return op
        if op.f == "bump":
            plan = op.value
            c.on_nodes(test, lambda t, n: bump_time(plan[n]),
                       nodes=list(plan))
            return op
        if op.f == "strobe":
            plan = op.value
            c.on_nodes(
                test,
                lambda t, n: strobe_time(plan[n]["delta"],
                                         plan[n]["period"],
                                         plan[n]["duration"]),
                nodes=list(plan))
            return op
        raise ValueError(f"clock nemesis can't handle {op.f!r}")

    def teardown(self, test):
        c.on_nodes(test, lambda t, n: reset_time())


def clock_nemesis() -> ClockNemesis:
    return ClockNemesis()


# --- randomized generators (time.clj:92-125) --------------------------------

def reset_gen(test, process):
    return Op("info", "reset", None)


def bump_gen(test, process):
    nodes = test["nodes"]
    k = random.randint(1, len(nodes))
    targets = random.sample(nodes, k)
    return Op("info", "bump",
              {n: (random.random() - 0.5) * 2e5 for n in targets})


def strobe_gen(test, process):
    nodes = test["nodes"]
    k = random.randint(1, len(nodes))
    targets = random.sample(nodes, k)
    return Op("info", "strobe",
              {n: {"delta": random.randint(0, 2 ** 8) * 4,
                   "period": random.randint(0, 2 ** 10) + 1,
                   "duration": random.randint(0, 32)}
               for n in targets})


def clock_gen():
    """Mix of reset/bump/strobe faults (time.clj:117-125)."""
    return gen.mix([reset_gen, bump_gen, strobe_gen])
