"""Operation histories — the single interchange format between the harness
half and the analysis half of the framework.

An op is a small record ``{type, f, value, process, time, index}`` exactly as
in the reference (`jepsen/src/jepsen/core.clj:143-217`, indexed at
core.clj:481):

- ``type``    — one of ``invoke`` / ``ok`` / ``fail`` / ``info``
- ``f``       — the logical function (``read``, ``write``, ``cas``,
                ``acquire``, ``add``, ``enqueue`` ...)
- ``value``   — argument or result of the op
- ``process`` — logical process id (int) or ``"nemesis"``
- ``time``    — nanoseconds since the test's relative-time origin
- ``index``   — position in the history

This module also carries the knossos.history API surface the reference relies
on (`knossos.history/index`, `complete`, `pairs`, `processes` — used at
core.clj:481, checker.clj:342, checker/timeline.clj:146-149, generator.clj:53):
those live here natively since knossos is replaced wholesale by
:mod:`jepsen_tpu.lin`.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, replace as _dc_replace
from typing import Any, Iterable, Iterator

INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

OP_TYPES = (INVOKE, OK, FAIL, INFO)

NEMESIS = "nemesis"


@dataclass(frozen=True)
class Op:
    """One history event. Frozen; use :meth:`replace` to derive variants."""

    type: str
    f: Any = None
    value: Any = None
    process: Any = None
    time: int | None = None
    index: int | None = None
    extra: dict = field(default_factory=dict, compare=False)

    def replace(self, **kw) -> "Op":
        extra_updates = {k: v for k, v in kw.items() if k not in _OP_FIELDS}
        base = {k: v for k, v in kw.items() if k in _OP_FIELDS}
        if extra_updates:
            base["extra"] = {**self.extra, **extra_updates}
        return _dc_replace(self, **base)

    def get(self, k, default=None):
        if k in _OP_FIELDS:
            return getattr(self, k)
        return self.extra.get(k, default)

    def __getitem__(self, k):
        v = self.get(k, _MISSING)
        if v is _MISSING:
            raise KeyError(k)
        return v

    # --- predicates (knossos.op/invoke? ok? fail?, used checker.clj:119-151)
    @property
    def is_invoke(self) -> bool:
        return self.type == INVOKE

    @property
    def is_ok(self) -> bool:
        return self.type == OK

    @property
    def is_fail(self) -> bool:
        return self.type == FAIL

    @property
    def is_info(self) -> bool:
        return self.type == INFO

    def to_dict(self) -> dict:
        d = {"type": self.type, "f": self.f, "value": self.value,
             "process": self.process, "time": self.time, "index": self.index}
        d.update(self.extra)
        return d

    @staticmethod
    def from_dict(d: dict) -> "Op":
        extra = {k: v for k, v in d.items() if k not in _OP_FIELDS}
        return Op(type=d.get("type"), f=d.get("f"), value=d.get("value"),
                  process=d.get("process"), time=d.get("time"),
                  index=d.get("index"), extra=extra)


_OP_FIELDS = {"type", "f", "value", "process", "time", "index"}
_MISSING = object()


# --- op constructors (mirroring knossos.core/invoke-op, ok-op used by the
# reference's checker tests, test/jepsen/checker_test.clj:5) ----------------

def invoke_op(process, f, value, **extra) -> Op:
    return Op(INVOKE, f, value, process, extra=extra)


def ok_op(process, f, value, **extra) -> Op:
    return Op(OK, f, value, process, extra=extra)


def fail_op(process, f, value, **extra) -> Op:
    return Op(FAIL, f, value, process, extra=extra)


def info_op(process, f, value, **extra) -> Op:
    return Op(INFO, f, value, process, extra=extra)


def op(d) -> Op:
    return d if isinstance(d, Op) else Op.from_dict(d)


# --- history functions ------------------------------------------------------

def index(history: Iterable[Op]) -> list[Op]:
    """Assign sequential :index to each op (knossos.history/index, applied by
    the reference runner at core.clj:481)."""
    return [o.replace(index=i) if o.index != i else o
            for i, o in enumerate(history)]


def processes(history: Iterable[Op]) -> list:
    """Distinct processes in order of first appearance
    (knossos.history/processes)."""
    seen: dict = {}
    for o in history:
        if o.process not in seen:
            seen[o.process] = True
    return list(seen)


def complete(history: list[Op]) -> list[Op]:
    """Fill in invocation values from their completions.

    Mirrors knossos.history/complete (used by the reference counter checker,
    checker.clj:342): each invocation is matched with the next op by the same
    process; if that completion is :ok, the invocation's value is replaced
    with the completion's value (e.g. a read invoked with value nil completes
    with the observed value).
    """
    out = list(history)
    pending: dict[Any, int] = {}
    for i, o in enumerate(out):
        if o.is_invoke:
            pending[o.process] = i
        elif o.process in pending:
            j = pending.pop(o.process)
            if o.is_ok:
                out[j] = out[j].replace(value=o.value)
    return out


def pair_index(history: list[Op]) -> dict[int, int]:
    """Map from position of each invocation to the position of its completion
    (and back). Positions without a partner are absent."""
    pairs: dict[int, int] = {}
    pending: dict[Any, int] = {}
    for i, o in enumerate(history):
        if o.is_invoke:
            pending[o.process] = i
        elif o.process in pending:
            j = pending.pop(o.process)
            pairs[j] = i
            pairs[i] = j
    return pairs


def invocations(history: Iterable[Op]) -> list[Op]:
    return [o for o in history if o.is_invoke]


# --- codec (history.txt / JSONL persistence; the reference serializes
# histories with Fressian, store.clj:26-111 — we use JSONL, a portable
# equivalent) ----------------------------------------------------------------

def _default(o):
    if isinstance(o, Op):
        return o.to_dict()
    if isinstance(o, (set, frozenset)):
        return {"__set__": sorted(o, key=repr)}
    if isinstance(o, tuple):
        return list(o)
    return repr(o)


def dumps_op(o: Op) -> str:
    return json.dumps(o.to_dict(), default=_default)


def _decode(v):
    if isinstance(v, dict):
        if set(v) == {"__set__"}:
            return frozenset(_decode(x) for x in v["__set__"])
        return {k: _decode(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_decode(x) for x in v]
    return v


def loads_op(s: str) -> Op:
    return Op.from_dict(_decode(json.loads(s)))


def write_history(path, history: Iterable[Op]) -> None:
    with open(path, "w") as fh:
        for o in history:
            fh.write(dumps_op(o))
            fh.write("\n")


def read_history(path) -> list[Op]:
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(loads_op(line))
    return out


class History(list):
    """A list of Ops with convenience constructors."""

    @staticmethod
    def of(*ops) -> "History":
        h = History()
        for o in ops:
            h.append(op(o))
        return index_history(h)


def index_history(h: "History") -> "History":
    return History(index(h))
