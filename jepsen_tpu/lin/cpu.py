"""Host reference implementation of the linearizability search.

The semantic spec for the device kernel (:mod:`jepsen_tpu.lin.bfs`): the same
just-in-time linearization closure (the algorithm family of knossos.linear /
knossos.wgl, which the reference races at checker.clj:90-93), expressed as
Python set operations over ``(bitset, state)`` configs. The frontier only
changes at completion events:

    at return of op s:
        closure: repeatedly linearize any pending op legal in some config
        filter:  keep configs with s linearized (its linearization point
                 must precede its return)
        recycle: clear s's bit (constant across survivors) so the slot can
                 be reused by a later op

    valid iff the frontier never empties.

Also provides the generic-model fallback (models with no device kernel:
sets, queues) and witness linearization reconstruction via shared-structure
cons cells.
"""

from __future__ import annotations

from typing import Any

from jepsen_tpu.history import Op
from jepsen_tpu.lin.prepare import PackedHistory, py_step_fn
from jepsen_tpu.models import is_inconsistent
from jepsen_tpu.models.kernels import NIL, SET_BITS

MAX_REPORT_CONFIGS = 32


def _decode_bitmask(p: PackedHistory, state):
    """Elements of a SET_BITS-per-word bitmask state, uninterned."""
    return (p.unintern[w * SET_BITS + b]
            for w, word in enumerate(state)
            for b in range(SET_BITS) if (word >> b) & 1)


def decode_state(p: PackedHistory, state: tuple) -> Any:
    """Decode a packed model state back to its observable value."""
    if p.kernel is None:
        return state
    if p.kernel.name in ("cas-register", "register"):
        return None if state[0] == int(NIL) else p.unintern[state[0]]
    if p.kernel.name == "mutex":
        return bool(state[0])
    if p.kernel.name == "set":
        return frozenset(_decode_bitmask(p, state))
    if p.kernel.name == "unordered-queue":
        return tuple(sorted(
            (p.unintern[i] for i, c in enumerate(state) for _ in range(c)),
            key=repr))
    if p.kernel.name == "unordered-unique":
        return tuple(sorted(_decode_bitmask(p, state), key=repr))
    if p.kernel.name == "fifo-queue":
        size = state[0]
        return tuple(p.unintern[e] for e in state[1:1 + size])
    return state


def _op_dict(o) -> dict:
    return {"process": o.process, "f": o.f, "value": o.value,
            "index": o.op_index, "ok": o.ok}


def _decode_configs(p: PackedHistory, configs, row: int | None) -> list:
    out = []
    for bits, st in list(configs)[:MAX_REPORT_CONFIGS]:
        pending = []
        if row is not None:
            for j in range(p.window):
                if p.active[row, j] and not (bits >> j) & 1:
                    pending.append(_op_dict(p.ops[int(p.slot_op[row, j])]))
        out.append({"model": decode_state(p, st), "pending": pending})
    return out


def _witness_path(p: PackedHistory, cons) -> list:
    path = []
    while cons is not None:
        op_id, cons = cons
        path.append(_op_dict(p.ops[op_id]))
    path.reverse()
    return path


def _final_paths(p: PackedHistory, seen, order) -> list:
    """knossos-style final-paths: for each config alive when the frontier
    died, its model state and the linearization path that reached it (from
    the search's anchor point)."""
    if order is None:
        return []
    out = []
    for cfg in list(seen)[:MAX_REPORT_CONFIGS]:
        out.append({"model": decode_state(p, cfg[1]),
                    "path": _witness_path(p, order.get(cfg))})
    return out


class Dead(Exception):
    """Internal: the frontier emptied at row ``r``; carries the closure
    set + paths for counterexample reporting."""

    def __init__(self, r, seen, order):
        self.r, self.seen, self.order = r, seen, order


class Cancelled(Exception):
    pass


def search_rows(p: PackedHistory, configs, order, r0: int, r1: int,
                cancel=None, reduce: bool = False):
    """The just-in-time linearization closure over return events
    [r0, r1): from ``configs`` (a set of (bits, state-tuple)), closure +
    filter each row. Returns (configs, order) on survival; raises Dead at
    the row where the frontier empties, Cancelled on a race cancel.
    ``order`` (or None to skip witness tracking) maps config -> cons list
    of op ids, shared-structure, anchored wherever the caller started.

    ``reduce=True`` applies the exact search-space reductions of
    :func:`jepsen_tpu.lin.prepare.reduction_tables` (pure-op saturation +
    canonical chains). Verdict and death row are provably identical to the
    plain search (and parity-fuzzed so); the surviving config SETS are
    canonical/saturated representatives. Witness tracking works in both
    modes: a saturated read's absorption point IS a valid linearization
    point (the read is pending and its value matches there), so absorbed
    ops join the path as they are folded in — the reduced witness is a
    genuine linearization order, just a canonical one."""
    step = py_step_fn(p.kernel.name)
    window = p.window
    if reduce:
        from jepsen_tpu.lin.prepare import reduction_tables

        pure_tbl, pred_tbl = reduction_tables(p)
    for r in range(r0, r1):
        if cancel is not None and cancel.is_set():
            raise Cancelled
        act = p.active[r]
        f_ints = p.slot_f[r].tolist()
        v_tups = [tuple(row) for row in p.slot_v[r].tolist()]
        if reduce:
            pure_r = pure_tbl[r]
            pred_r = pred_tbl[r].tolist()
            pure_mask = 0
            for j in range(window):
                if pure_r[j]:
                    pure_mask |= 1 << j

            track = order is not None

            def saturate(bits, st, path=None):
                for j in range(window):
                    if (pure_mask >> j) & 1 and not (bits >> j) & 1 \
                            and step(st, f_ints[j], v_tups[j])[0]:
                        bits |= 1 << j
                        if track:
                            path = (int(p.slot_op[r, j]), path)
                return bits, path

            if order is None:
                configs = {(saturate(b, st)[0], st) for b, st in configs}
            else:
                sat: dict = {}
                for b, st in configs:
                    b2, path2 = saturate(b, st, order[(b, st)])
                    sat.setdefault((b2, st), path2)
                configs = set(sat)
                order.update(sat)
        seen = set(configs)
        frontier = list(configs)
        while frontier:
            # One row's closure can itself be exponential (2^window waves);
            # poll here too so a competition loser dies promptly.
            if cancel is not None and cancel.is_set():
                raise Cancelled
            new = []
            for ci, cfg in enumerate(frontier):
                if cancel is not None and ci % 4096 == 4095 \
                        and cancel.is_set():
                    raise Cancelled
                bits, st = cfg
                for j in range(window):
                    if act[j] and not (bits >> j) & 1:
                        if reduce and ((pure_mask >> j) & 1 or
                                       (pred_r[j] >= 0 and
                                        not (bits >> pred_r[j]) & 1)):
                            continue
                        ok, st2 = step(st, f_ints[j], v_tups[j])
                        if ok:
                            b2 = bits | (1 << j)
                            path = None if order is None else \
                                (int(p.slot_op[r, j]), order[cfg])
                            if reduce:
                                b2, path = saturate(b2, st2, path)
                            c2 = (b2, st2)
                            if c2 not in seen:
                                seen.add(c2)
                                new.append(c2)
                                if order is not None:
                                    order[c2] = path
            frontier = new
        s = int(p.ret_slot[r])
        mask = 1 << s
        survivors = set()
        # Rebuilt from scratch: after clearing the returned bit a survivor's
        # key can collide with a closure config that never linearized the
        # returner, whose path would be a wrong witness.
        new_order: dict | None = {} if order is not None else None
        for cfg in seen:
            bits, st = cfg
            if bits & mask:
                c2 = (bits & ~mask, st)
                if c2 not in survivors:
                    survivors.add(c2)
                    if new_order is not None:
                        new_order[c2] = order[cfg]
        if not survivors:
            raise Dead(r, seen, order)
        order = new_order
        configs = survivors
    return configs, order


def check_packed(p: PackedHistory, witness: bool = False,
                 cancel=None) -> dict:
    """Decide linearizability on a packed history. ``witness=True`` tracks a
    representative linearization order (cheap cons-cell sharing; first
    discovery of a config wins) and, on an invalid verdict, emits
    knossos-style final-paths. ``cancel`` (a threading.Event) stops the
    search between rows — set by a competition race once the other racer
    has decided.

    The search always runs REDUCED (pure-op saturation + canonical
    chains, see search_rows): verdict and death row are exact, but the
    reported ``configs`` — and the witness order, which threads through
    saturation points — are canonical/saturated representatives of the
    reduced frontier, not the plain frontier knossos would list; the
    result carries ``"reduced": True`` to flag that. (Round 2 forced
    the unreduced search under ``witness``, which made the competition's
    CPU racer grind wide windows for nothing.)"""
    if p.kernel is None:
        return check_generic(p, witness=witness)

    init = (0, tuple(int(x) for x in p.init_state))
    configs = {init}
    order: dict | None = {init: None} if witness else None
    reduce = True
    try:
        configs, order = search_rows(p, configs, order, 0, p.R,
                                     cancel=cancel, reduce=reduce)
    except Cancelled:
        return {"valid?": "unknown", "analyzer": "cpu-jit",
                "error": "cancelled"}
    except Dead as d:
        ret = p.ops[int(p.ret_op[d.r])]
        return {"valid?": False,
                "analyzer": "cpu-jit",
                "reduced": reduce,
                "op": _op_dict(ret),
                "configs": _decode_configs(p, d.seen, d.r),
                "final-paths": _final_paths(p, d.seen, d.order)}

    out = {"valid?": True, "analyzer": "cpu-jit", "reduced": reduce,
           "configs": _decode_configs(p, configs, None)}
    if order is not None and configs:
        some = next(iter(configs))
        out["witness"] = _witness_path(p, order[some])
    return out


def check_generic(p: PackedHistory, witness: bool = False) -> dict:
    """Same search with arbitrary (hashable) Python model objects as state —
    covers models with no device kernel, the analogue of running knossos on
    an arbitrary Model record."""
    init = (0, p.model)
    configs = {init}
    order: dict | None = {init: None} if witness else None

    def shim(o) -> Op:
        return Op("invoke", o.f, o.value, o.process)

    for r in range(p.R):
        act = p.active[r]
        seen = set(configs)
        frontier = list(configs)
        while frontier:
            new = []
            for cfg in frontier:
                bits, st = cfg
                for j in range(p.window):
                    if act[j] and not (bits >> j) & 1:
                        o = p.ops[int(p.slot_op[r, j])]
                        st2 = st.step(shim(o))
                        if not is_inconsistent(st2):
                            c2 = (bits | (1 << j), st2)
                            if c2 not in seen:
                                seen.add(c2)
                                new.append(c2)
                                if order is not None:
                                    order[c2] = (int(p.slot_op[r, j]),
                                                 order[cfg])
            frontier = new
        s = int(p.ret_slot[r])
        mask = 1 << s
        survivors = set()
        # Rebuilt from scratch: after clearing the returned bit a survivor's
        # key can collide with a closure config that never linearized the
        # returner, whose path would be a wrong witness.
        new_order: dict | None = {} if order is not None else None
        for cfg in seen:
            bits, st = cfg
            if bits & mask:
                c2 = (bits & ~mask, st)
                if c2 not in survivors:
                    survivors.add(c2)
                    if new_order is not None:
                        new_order[c2] = order[cfg]
        if not survivors:
            ret = p.ops[int(p.ret_op[r])]
            return {"valid?": False,
                    "analyzer": "cpu-generic",
                    "op": _op_dict(ret),
                    "configs": [{"model": st, "pending": []}
                                for _, st in list(seen)[:MAX_REPORT_CONFIGS]],
                    "final-paths": _final_paths(p, seen, order)}
        order = new_order
        configs = survivors

    out = {"valid?": True, "analyzer": "cpu-generic",
           "configs": [{"model": st, "pending": []}
                       for _, st in list(configs)[:MAX_REPORT_CONFIGS]]}
    if order is not None and configs:
        some = next(iter(configs))
        out["witness"] = _witness_path(p, order[some])
    return out
