"""Pallas TPU kernel for the dense bitmap engine's chunk loop.

The XLA formulation (:mod:`jepsen_tpu.lin.dense`) pays a fixed ~100us
per return event in loop/dispatch overhead — measured flat even on a
64-word bitmap — because every row is dozens of small HLOs round-tripping
HBM. This kernel keeps the ENTIRE frontier bitmap resident in VMEM
scratch across a sequential grid (one program per return event), so a
row costs exactly its vector math:

- Bitmap layout ``u32[2**w / 128, 128]``: a config's bitset B splits as
  (sublane row = B >> 7, lane = B & 127). Linearizing slot j < 7 is a
  LANE roll by 2**j; slot j >= 7 a SUBLANE roll by 2**(j-7) — both
  native VPU data movements, with the source masked to bit-j-clear
  positions so nothing wraps into garbage.
- The model-step tables are compressed into *transition masks*:
  ``mask[r, j, s'] = bitmask of source states s that op (r,j) maps to
  s'`` (inactive slots are all-zero). One u32 per (slot, target-state),
  so the whole per-row table is a [w, ns] block streamed into SMEM by
  the grid pipeline, and the closure's inner loop is
  ``contrib |= ((src & mask) != 0) << s'`` — scalar SMEM reads driving
  pure vector ops, no gathers.
- The closure do-while and the lax.switch return-filter (static roll per
  slot) run inside the kernel; a dead frontier flips an SMEM flag that
  short-circuits every later grid step.

The host-side chunk loop, snapshots, witness replay, and routing all
stay in :mod:`jepsen_tpu.lin.dense` — this module only provides the
drop-in chunk function (``check_packed(..., backend="pallas")``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Lane bits: the low 7 bitset bits live on the 128-lane axis.
LANE_BITS = 7
# Sublane tiling for 32-bit types is 8 rows: minimum bitmap 8*128 words.
MIN_W = LANE_BITS + 3
MAX_PALLAS_W = 18          # 2**18 words = 1 MiB bitmap in VMEM


@partial(jax.jit, static_argnames=("ns", "step_fn"))
def transition_masks(slot_f, slot_v, active, nil_id, *, ns, step_fn):
    """u32[CH, w, ns] transition masks: bit s of mask[r, j, s'] set iff
    op (r, j) is active, legal in state s, and maps s to s'. Built on
    the same shared tables as the XLA backend (dense.transition_tables),
    just pivoted into per-target-state source bitmasks."""
    from jepsen_tpu.lin.dense import transition_tables

    ok, to = transition_tables(slot_f, slot_v, active, nil_id,
                               ns=ns, step_fn=step_fn)
    sid = jnp.arange(ns, dtype=jnp.uint32)
    # mask[r,j,s'] = OR over source s of (ok & to==s') << s
    hit = ok[..., None] & (to[..., None] == sid[None, None, None, :])
    bit = (jnp.uint32(1) << sid)[None, None, :, None]
    return jnp.sum(jnp.where(hit, bit, jnp.uint32(0)), axis=2,
                   dtype=jnp.uint32)


def _row_kernel(n_rows_ref, masks_ref, ret_ref, f_in_ref, f_out_ref,
                done_ref, f_ref, state_ref, *, w, ns):
    """One grid step = one return event. f_ref: VMEM scratch [S,128]
    persisting across steps; state_ref: SMEM [2] = (dead, rows_done)."""
    r = pl.program_id(0)
    S = 1 << (w - LANE_BITS)

    @pl.when(r == 0)
    def _init():
        f_ref[:] = f_in_ref[:]
        state_ref[0] = 0
        state_ref[1] = 0

    lane = lax.broadcasted_iota(jnp.uint32, (S, 128), 1)
    row = lax.broadcasted_iota(jnp.uint32, (S, 128), 0)

    def bit_clear(j):
        if j < LANE_BITS:
            return (lane & (1 << j)) == 0
        return (row & (1 << (j - LANE_BITS))) == 0

    def shift_up(x, j):        # B -> B + 2**j (sources pre-masked)
        if j < LANE_BITS:
            return pltpu.roll(x, 1 << j, 1)
        return pltpu.roll(x, 1 << (j - LANE_BITS), 0)

    def shift_down(x, j):      # B -> B - 2**j
        if j < LANE_BITS:
            return pltpu.roll(x, 128 - (1 << j), 1)
        return pltpu.roll(x, S - (1 << (j - LANE_BITS)), 0)

    @pl.when((r < n_rows_ref[0]) & (state_ref[0] == 0))
    def _step():
        F = f_ref[:]

        def closure_body(c):
            F, _ = c
            F2 = F
            for j in range(w):
                src = jnp.where(bit_clear(j), F2, jnp.uint32(0))
                contrib = jnp.zeros_like(src)
                for sp in range(ns):
                    m = masks_ref[0, j, sp]
                    contrib = contrib | jnp.where(
                        (src & m) != 0, jnp.uint32(1 << sp),
                        jnp.uint32(0))
                F2 = F2 | shift_up(contrib, j)
            return F2, jnp.any(F2 != F)

        # lint: unbounded-ok — monotone OR-accumulated bitmap closure
        # (dense.py's termination argument: <= w+1 passes); a carried
        # counter here would cost Mosaic an extra SMEM carry for a
        # bound that provably never binds.
        F, _ = lax.while_loop(lambda c: c[1], closure_body,
                              closure_body((F, True)))

        # Return filter: keep configs with the returner's bit, clear it.
        def filter_branch(s):
            def br(F):
                keep = jnp.where(bit_clear(s), jnp.uint32(0), F)
                return shift_down(keep, s)
            return br

        F = lax.switch(ret_ref[0, 0, 0],
                       [filter_branch(s) for s in range(w)], F)
        f_ref[:] = F
        dead = jnp.all(F == 0)
        state_ref[0] = jnp.where(dead, 1, 0).astype(jnp.int32)
        state_ref[1] = r + 1

    @pl.when(r == pl.num_programs(0) - 1)
    def _finish():
        f_out_ref[:] = f_ref[:]
        done_ref[0] = state_ref[0]
        done_ref[1] = state_ref[1]


@partial(jax.jit, static_argnames=("w", "ns", "chunk", "interpret"))
def pallas_chunk(F, n_rows, masks, ret_slot, *, w, ns, chunk,
                 interpret=False):
    """Advance the frontier through up to n_rows return events.
    F: u32[2**w] (1D, the dense engine's carry format); masks:
    u32[chunk, w, ns]; ret_slot: i32[chunk].
    Returns (F, rows_done, dead) matching dense._dense_chunk."""
    S = 1 << (w - LANE_BITS)
    F2d = F.reshape(S, 128)
    grid = (chunk,)
    f_out, done = pl.pallas_call(
        partial(_row_kernel, w=w, ns=ns),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),             # n_rows
            pl.BlockSpec((1, w, ns), lambda r: (r, 0, 0),
                         memory_space=pltpu.SMEM),             # masks row
            pl.BlockSpec((1, 1, 1), lambda r: (r, 0, 0),
                         memory_space=pltpu.SMEM),             # ret slot
            pl.BlockSpec(memory_space=pltpu.VMEM),             # F in
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((S, 128), jnp.uint32),
            jax.ShapeDtypeStruct((2,), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((S, 128), jnp.uint32),
            pltpu.SMEM((2,), jnp.int32),
        ],
        interpret=interpret,
    )(n_rows.reshape(1), masks, ret_slot.reshape(-1, 1, 1), F2d)
    return f_out.reshape(-1), done[1], done[0] != 0


def supported_w(w: int) -> int | None:
    """The pallas bitmap width for a dense-plan width, or None when the
    kernel can't take it. Widths below the tiling minimum are padded up
    (extra slots are never active, so the cost is only bitmap size)."""
    if w > MAX_PALLAS_W:
        return None
    return max(w, MIN_W)
