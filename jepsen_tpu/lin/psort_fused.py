"""Fused Pallas expand+dedup CLOSURE FIXPOINT for the compact register
band (the kill-the-tunnel tentpole, pass-chain half).

The sparse engine's just-in-time closure runs as a chain of passes —
expand candidates, sort-dedup, test the fixpoint — and even with the
in-VMEM psort dedup each pass round-trips the cap*(1+M) candidate
array through HBM and pays the stage-overhead floor of its XLA
neighbours (~2.4 ms per lax.sort-sized stage, CLAUDE.md). This module
runs the WHOLE fixpoint as ONE pallas kernel: the frontier stays
resident in VMEM across passes, expansion is per-column bit algebra
driven by host-precomputed scalars (:func:`jepsen_tpu.lin.bfs.
_fused_row_tables` — the register family's mutator step is a value
match, so ok/post per (column, state) collapse to per-column
scalars), and each pass's dedup is the psort bitonic sort pair.

SCOPE (round-5 lore, ISSUE 14): the fused kernel serves the
NON-dominance dedups only — the crash-dom band's dominance dedups
keep the FORCED-LAX chain rule (both round-5 runs that routed them
through pallas kernels killed the worker; see psort
_assert_force_window_interpret_only). Call sites therefore gate on
``crash_dom=False``, and the engine integration lives in
``bfs._search_chunk_keys`` (the healthy compact band's row tiers).

Semantics twin of the unfused chain: one fused fixpoint ==
``_closure_pass_keys_compact`` iterated to convergence (ungrouped,
non-dominance dedup), parity-fuzzed in interpret mode in
``tests/test_lin_psort_fused.py`` — the psort precedent. Every loop
carries its iteration ceiling (``it_max`` — the round-5 invariant,
``make lint`` while-ceiling rule); a ceiling hit with changes pending
reports non-convergence, which the engine maps to an honest overflow.

Layout: the working array is the full candidate space
``[(1+M)*cap]`` (padded to a power of two), viewed ``[SP, 128]``:
block 0 holds the carried (compacted) frontier, block k the
expansions by mutator column k. Each block's base values are the
carried prefix ROLLED down by ``k*cap/128`` sublanes — a native VPU
movement, no gather, no concat (Mosaic legalization lore). ``cap``
must be a LANE multiple power of two (every engine cap is; odd test
caps fall back to the unfused chain).

Env: ``JEPSEN_TPU_PSORT_FUSED`` (doc/env.md) — ``0`` forces the
unfused chain; platform/interpret gating follows ``psort.backend_ok``.
``JEPSEN_TPU_PSORT_FUSED_MAX_N`` (an exponent) raises the candidate-
space bound past the default ``psort.PSORT_MAX_N`` so the PAIR-KEY
in-chunk tiers at the big caps engage the kernel too — see
:func:`max_n`. The raise is env-gated OFF by default and the bench
engages it only behind its small-input smoke probe (fault lore:
rows*cap program complexity is the fault driver; never spend a
multi-hour rung on an unprobed shape).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from jepsen_tpu.lin import psort
from jepsen_tpu.lin.psort import (KEY_FILL, LANE, _bitonic_sort,
                                  _bitonic_sort2, _flat_prev)

# Older jax (this sandbox's 0.4.37) spells pltpu.CompilerParams
# TPUCompilerParams; the driver image has the new name. One alias
# keeps the kernel interpret-testable on both (the psort module
# predates the skew and its parity tests skip at seed instead).
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

# Column-scalar table rows (bfs._fused_row_tables builds them; the
# kernel reads them as SMEM scalars per static column index).
COL_EXP_LO, COL_EXP_HI = 0, 1
COL_PRED_LO, COL_PRED_HI = 2, 3
COL_RV_LO, COL_RV_HI = 4, 5
COL_OR_LO, COL_OR_HI = 6, 7
COL_PRE, COL_FLAGS = 8, 9
N_COL_ROWS = 10
FLAG_ACT, FLAG_WRITE, FLAG_JIT = 1, 2, 4


def enabled() -> bool:
    """Fused-fixpoint gate: ``JEPSEN_TPU_PSORT_FUSED=0`` forces the
    unfused pass chain (fault triage / A-B timing), ``interpret``
    forces the kernel in interpreter mode (CPU parity tests — its own
    knob, so parity runs even where the psort kernels are gated off);
    otherwise the kernel engages on the real TPU backend wherever
    :func:`fits` holds — the psort gating convention."""
    mode = os.environ.get("JEPSEN_TPU_PSORT_FUSED", "1")
    if mode == "0":
        return False
    return mode == "interpret" or psort._on_tpu()


def _interpret() -> bool:
    """Interpreter-mode gate for the pallas_call itself — keyed off
    THIS module's knob (plus the platform), so
    ``JEPSEN_TPU_PSORT_FUSED=interpret`` on a real TPU actually runs
    the interpreter (the documented triage path), independent of
    ``JEPSEN_TPU_PSORT``."""
    return os.environ.get("JEPSEN_TPU_PSORT_FUSED") == "interpret" \
        or not psort._on_tpu()


# Hard ceiling for the env-raised candidate-space bound: 2^21 is the
# largest in-VMEM bitonic sort PROVEN on this chip (psort module
# docstring — the raised scoped-VMEM limit sorts to 2^21). Beyond it
# nothing has run; the knob clamps rather than trusts.
FUSED_MAX_EXP = 21


def max_n() -> int:
    """The fused kernel's candidate-space bound (padded elements).
    Default ``psort.PSORT_MAX_N`` — the proven envelope every rung
    runs inside. ``JEPSEN_TPU_PSORT_FUSED_MAX_N`` (an EXPONENT, the
    DOM_WINDOW convention) raises it so the pair-key in-chunk tiers at
    the big caps engage the kernel, clamped to ``2^FUSED_MAX_EXP``
    (the proven sort bound) — the bench's partitioned ladder sets it
    on its ``fusedtier`` rung only after the small-input smoke leg ran
    the raised shape clean on the chip. Read OUTSIDE jit and passed as
    a static argument (``bfs`` plumbs it through ``use_fused``), so a
    mid-process env change can never hit a stale traced gate."""
    env = os.environ.get("JEPSEN_TPU_PSORT_FUSED_MAX_N", "")
    if not env:
        return psort.PSORT_MAX_N
    return 1 << min(FUSED_MAX_EXP, max(10, int(env)))


def fits(cap: int, M: int, b: int, max_pad: int | None = None) -> bool:
    """Size/shape gate: the candidate space must fit the in-VMEM sort
    bound (``max_pad`` — callers inside jit pass the env-resolved
    :func:`max_n` value; None keeps the proven default), the block
    roll trick needs cap to be a LANE-multiple power of two, and the
    per-column scalar encoding needs the packed state id to fit 6 bits
    (the compact band's own bound)."""
    bound = max_pad if max_pad else psort.PSORT_MAX_N
    return (b <= 6 and cap >= LANE and (cap & (cap - 1)) == 0
            and psort.pad_size(cap * (1 + M)) <= bound)


def _sat_select(sv, live, sat_ref, plane: int, nb: int):
    """2^b-way unrolled select of the saturation mask for each
    config's state id (the in-kernel twin of the engine's sat-table
    branch; bounded by b <= 6)."""
    sat = jnp.zeros_like(sv)
    for s in range(1 << nb):
        sat = sat | jnp.where(live & (sv == jnp.uint32(s)),
                              sat_ref[plane, s], jnp.uint32(0))
    return sat


def _fixpoint_body(scal_ref, cols_ref, sat_ref, *refs, SP, S0, M, K,
                   b, cap, it_max, pair):
    """One whole closure fixpoint in VMEM (module docstring). refs:
    (lo_ref[, hi_ref], out_lo_ref[, out_hi_ref], flags_ref)."""
    if pair:
        lo_ref, hi_ref, out_lo_ref, out_hi_ref, flags_ref = refs
    else:
        lo_ref, out_lo_ref, flags_ref = refs
        hi_ref = out_hi_ref = None
    fill = jnp.uint32(KEY_FILL)
    smask = jnp.uint32((1 << b) - 1)
    logcap = cap.bit_length() - 1
    x0 = lo_ref[:]
    xh0 = hi_ref[:] if pair else x0
    lane = lax.broadcasted_iota(jnp.uint32, x0.shape, 1)
    row = lax.broadcasted_iota(jnp.uint32, x0.shape, 0)
    flat = row * LANE + lane
    blk = flat >> logcap
    blk0 = blk == 0

    def one_pass(x, xh, cnt):
        # Liveness: live keys never collide with KEY_FILL (single key:
        # window+b <= 31 keeps bit 31 clear; pair: the hi payload is
        # <= 28 bits) and dead entries are FILL by compaction.
        live = (xh != fill) if pair else (x != fill)
        sv = x & smask
        # Carried saturation in place (engine: lo1 = lo_in | sat).
        sat_lo = _sat_select(sv, live, sat_ref, 0, b)
        x1 = jnp.where(live, x | sat_lo, x)
        if pair:
            sat_hi = _sat_select(sv, live, sat_ref, 1, b)
            xh1 = jnp.where(live, xh | sat_hi, xh)
        else:
            xh1 = x1
        # Candidates: block 0 = carried; block k = expansion by
        # mutator column k-1, its base values the carried prefix
        # rolled into place (sublane roll — no gather/concat).
        cand = jnp.where(blk0, x1, fill)
        candh = jnp.where(blk0, xh1, fill) if pair else cand
        for kb in range(1, M + 1):
            base = pltpu.roll(x1, kb * S0, 0)
            baseh = pltpu.roll(xh1, kb * S0, 0) if pair else base
            c = kb - 1
            flg = cols_ref[COL_FLAGS, c]
            exp_lo = cols_ref[COL_EXP_LO, c]
            pred_lo = cols_ref[COL_PRED_LO, c]
            rv_lo = cols_ref[COL_RV_LO, c]
            or_lo = cols_ref[COL_OR_LO, c]
            pre = cols_ref[COL_PRE, c]
            blive = (baseh != fill) if pair else (base != fill)
            bsv = base & smask
            okc = ((flg & FLAG_WRITE) != 0) | (bsv == pre)
            already = (base & exp_lo) != 0
            chain = (base & pred_lo) == pred_lo
            jit_ok = ((flg & FLAG_JIT) != 0) | ((rv_lo & ~base) != 0)
            if pair:
                exp_hi = cols_ref[COL_EXP_HI, c]
                pred_hi = cols_ref[COL_PRED_HI, c]
                rv_hi = cols_ref[COL_RV_HI, c]
                or_hi = cols_ref[COL_OR_HI, c]
                already = already | ((baseh & exp_hi) != 0)
                chain = chain & ((baseh & pred_hi) == pred_hi)
                jit_ok = jit_ok | ((rv_hi & ~baseh) != 0)
            legal = blive & ((flg & FLAG_ACT) != 0) & okc \
                & ~already & chain & jit_ok
            newl = (base & ~smask) | or_lo
            sel = blk == jnp.uint32(kb)
            cand = jnp.where(sel, jnp.where(legal, newl, fill), cand)
            if pair:
                newh = baseh | or_hi
                candh = jnp.where(sel, jnp.where(legal, newh, fill),
                                  candh)
        # Sort + adjacent-dup drop + compaction re-sort (the psort
        # dedup semantics; FILL doubles as the invalid flag — bit 31).
        first = flat == 0
        if pair:
            sh, sl = _bitonic_sort2(candh, cand, flat, S=SP, K=K)
            dup = (sh == _flat_prev(sh, 1, SP)) \
                & (sl == _flat_prev(sl, 1, SP))
            keep = (sh >> 31 == 0) & (first | ~dup)
            total = jnp.sum(keep.astype(jnp.int32))
            sh = jnp.where(keep, sh, fill)
            sl = jnp.where(keep, sl, fill)
            sh, sl = _bitonic_sort2(sh, sl, flat, S=SP, K=K)
            changed = jnp.sum((((sl != x) | (sh != xh)) & blk0)
                              .astype(jnp.int32)) > 0
        else:
            s1 = _bitonic_sort(cand, flat, lane, S=SP, K=K)
            dup = s1 == _flat_prev(s1, 1, SP)
            keep = (s1 >> 31 == 0) & (first | ~dup)
            total = jnp.sum(keep.astype(jnp.int32))
            sl = _bitonic_sort(jnp.where(keep, s1, fill), flat, lane,
                               S=SP, K=K)
            sh = sl
            changed = jnp.sum(((sl != x) & blk0)
                              .astype(jnp.int32)) > 0
        changed = changed | (total != cnt)
        return sl, sh, total, changed, total > cap

    def cond(c):
        _, _, _, it, changed, ovf = c
        return changed & ~ovf & (it < it_max)

    def body(c):
        x, xh, cnt, it, _, ovf = c
        x2, xh2, n2, changed, o2 = one_pass(x, xh, cnt)
        return x2, xh2, n2, it + 1, changed, ovf | o2

    x, xh, cnt, it, changed, ovf = lax.while_loop(
        cond, body,
        (x0, xh0, scal_ref[0], jnp.int32(0), jnp.bool_(True),
         jnp.bool_(False)))
    out_lo_ref[:] = x
    if pair:
        out_hi_ref[:] = xh
    flags_ref[0] = (~changed & ~ovf).astype(jnp.int32)
    flags_ref[1] = ovf.astype(jnp.int32)
    flags_ref[2] = it
    flags_ref[3] = cnt


@partial(jax.jit, static_argnames=("cap", "b", "it_max", "pair", "M"))
def _fixpoint_call(lo, hi, count, cols, sats, *, cap, b, it_max, pair,
                   M):
    n_pad = psort.pad_size(cap * (1 + M))
    SP = n_pad // LANE
    S0 = cap // LANE
    K = n_pad.bit_length() - 1
    pad = jnp.full(n_pad - cap, KEY_FILL, jnp.uint32)
    ins = [jnp.stack([count]).astype(jnp.int32),
           cols.astype(jnp.uint32), sats.astype(jnp.uint32),
           jnp.concatenate([lo, pad]).reshape(SP, LANE)]
    out_shape = [jax.ShapeDtypeStruct((SP, LANE), jnp.uint32)]
    aliases = {3: 0}
    if pair:
        ins.append(jnp.concatenate([hi, pad]).reshape(SP, LANE))
        out_shape.append(jax.ShapeDtypeStruct((SP, LANE), jnp.uint32))
        aliases[4] = 1
    out_shape.append(jax.ShapeDtypeStruct((4,), jnp.int32))
    outs = pl.pallas_call(
        partial(_fixpoint_body, SP=SP, S0=S0, M=M, K=K, b=b, cap=cap,
                it_max=it_max, pair=pair),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 3
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * (2 if pair else 1),
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)]
        * (2 if pair else 1)
        + [pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=out_shape,
        input_output_aliases=aliases,
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(*ins)
    if pair:
        out_lo, out_hi, flags = outs
        return (out_lo.reshape(-1)[:cap], out_hi.reshape(-1)[:cap],
                flags)
    out_lo, flags = outs
    return out_lo.reshape(-1)[:cap], None, flags


def fixpoint(lo, hi, count, cols, sats, *, cap, b, it_max):
    """Run one whole closure fixpoint in VMEM. ``lo``/``hi`` are the
    carried key arrays (``[cap]``, KEY_FILL-compacted; ``hi`` None for
    single-word keys), ``cols``/``sats`` the per-row scalar tables
    from ``bfs._fused_row_tables``. Caller must have checked
    :func:`fits`. Returns (lo[cap], hi[cap]|None, count, converged,
    overflow) — non-convergence at the ``it_max`` ceiling is the
    engine's honest-budget-overflow signal, dedup overflow its
    capacity-escalation signal, exactly like the unfused chain."""
    pair = hi is not None
    M = int(cols.shape[1])
    lo2, hi2, flags = _fixpoint_call(lo, hi, count, cols, sats,
                                     cap=cap, b=b, it_max=it_max,
                                     pair=pair, M=M)
    return lo2, hi2, flags[3], flags[0] != 0, flags[1] != 0
