"""Pallas in-VMEM bitonic sort-dedup for the sparse engine's packed keys.

``lax.sort`` on this TPU is stage-overhead-bound: ~2.4 ms for 64k
elements and ~2.5 ms up to ~2M — each of its O(log^2 n) compare-exchange
stages is a separate HBM-round-tripping HLO. The sparse frontier engine
(:mod:`jepsen_tpu.lin.bfs`) pays 4-6 such sorts per return event, which
made the wide-window band (windows 21..64, e.g. cockroach's
concurrency-30 registers) cost tens of ms per event.

This module runs the whole dedup — bitonic sort, adjacent-duplicate
masking, and the compaction re-sort — as ONE pallas kernel with the key
array resident in VMEM, so the ~200 stages are VPU register/VMEM ops
with no per-stage dispatch. Measured on the v5e chip (u32 keys):

=========  ==========  ============
elements   lax.sort    this kernel
=========  ==========  ============
2^16       2.4 ms      0.07 ms
2^17       2.5 ms      0.28 ms
2^18       2.6 ms      0.72 ms
2^19       2.4 ms      1.86 ms
2^20       2.6 ms      3.9 ms (lax wins past here)
=========  ==========  ============

The kernel is the semantics twin of ``bfs._dedup_keys`` (invalid flag in
bit 31, first-of-run survives, KEY_FILL padding/compaction) and is
fuzz-tested against it in ``tests/test_lin_psort.py``. Arrays larger
than :data:`PSORT_MAX_N` (or histories on non-TPU backends, unless
interpret mode is forced for tests) keep the lax.sort path.

Layout: keys reshaped ``[n/128, 128]`` u32; flat index = row*128 + lane.
Bitonic partner ``i ^ j`` for power-of-two j is a lane roll (j < 128)
or a sublane roll (j >= 128) selected by bit j of the flat index — both
native VPU data movements (``pltpu.roll`` with dynamic shifts), driven
by a fori_loop over stages so VMEM holds only ~4 live copies.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
MIN_N = 1024              # (8, 128) u32 tiling minimum
PSORT_MAX_N = 1 << 19     # above this lax.sort is faster (see table)
KEY_FILL = 0xFFFFFFFF     # plain int: used inside kernels as a literal
# Windowed-pairwise dominance distances (shared by the lax and pallas
# dom dedups so their semantics match exactly): after the (group, word)
# sort, every entry is tested against the predecessors at these offsets
# in addition to the group representative. A strict subset sorts
# numerically earlier, so predecessors are the only candidates; the
# window makes the prune near-pairwise ITERATIVELY — each closure pass
# re-dedups, and measured on the 100k partitioned history's blowup row
# the rep-only prune left 389k configs (antichain 9.3k) while rep +
# this window converges to 9.9k within a few passes. The window is
# SIZE-GATED (DOM_WINDOW_MAX_N, padded size): the roll chains at
# multi-million-cell lax dedups inside the nested-while chunk program
# kernel-faulted the axon TPU worker on the 100k partitioned history,
# while the pallas kernels with the window ran clean to 2^18. Pruning
# less at the rare big-tier dedups is sound; the small-tier dedups
# that run every pass keep the frontier collapsed.
# Two distances. Round 4 found 4+ distances kernel-fault in-chunk;
# round 5 briefly widened this to 8 distances believing that lore was
# the group-cycle-orbit bug misattributed, and the widened pallas
# kernels then killed the worker mid-history (probe_r5fc, row ~20k) —
# so the round-4 finding stands for the PALLAS kernels. (1, 2) is the
# proven-safe static window; real pruning strength comes from the
# FORCED lax path (chain scan over 1..DOM_CHAIN + iterated rounds),
# which escalation tiers and host passes always use.
DOM_WINDOW = (1, 2)
DOM_WINDOW_MAX_N = 1 << 18
# Forced-window dedups additionally run a CHAIN scan: a carried copy
# shifted by one more position each step tests every predecessor at
# distances 1..DOM_CHAIN, so in-group dominance pairs up to that span
# are caught (the static DOM_WINDOW misses all but the nearest —
# measured on the 100k partitioned history's wave, rep+(1,2) converge
# to 130k live configs where the true antichain is ~9k), and the whole
# prune+compact runs DOM_ITERS rounds so survivors compact together and
# previously-distant dominators become chain-reachable. In the lax path
# the chain is a fori of rolls; Mosaic cannot legalize that scan, so
# the pallas kernels unroll it statically (~DOM_CHAIN extra vector
# steps per round — still far cheaper than the stage-overhead-bound
# lax.sort at these sizes).
DOM_CHAIN = 128
DOM_ITERS = 2


def dom_window(n: int, force: bool = False) -> tuple:
    """The dominance window for an n-element dedup (empty past the
    size gate — see DOM_WINDOW). ``JEPSEN_TPU_DOM_WINDOW`` overrides:
    ``0`` disables the window entirely (the fault-triage escape
    hatch), any other integer replaces the max-pad EXPONENT (default
    log2(DOM_WINDOW_MAX_N)). ``force`` skips the size gate (not the
    env kill switch): host-sequenced single-pass dispatches keep the
    window engaged at capacities where the nested-while chunk programs
    fault (bfs._host_rows)."""
    env = os.environ.get("JEPSEN_TPU_DOM_WINDOW", "")
    if env == "0":
        return ()
    k = len(DOM_WINDOW)
    if ":" in env:
        env, k = env.split(":")
        k = int(k)
    if force:
        return DOM_WINDOW[:k]
    max_n = (1 << int(env)) if env else DOM_WINDOW_MAX_N
    return DOM_WINDOW[:k] if pad_size(n) <= max_n else ()


def pad_size(n: int) -> int:
    """The kernel size for an n-element dedup: next power of two, at
    least the tiling minimum."""
    return max(MIN_N, 1 << (n - 1).bit_length())


def backend_ok() -> bool:
    """True when this backend should use the in-VMEM kernel at all.
    Decided host-side and passed into the engine programs as a static
    arg, so jit cache keys reflect the routing. ``JEPSEN_TPU_PSORT=0``
    forces the lax path, ``=interpret`` forces the kernel in
    interpreter mode (CPU parity tests)."""
    mode = os.environ.get("JEPSEN_TPU_PSORT", "1")
    if mode == "0":
        return False
    return mode == "interpret" or _on_tpu()


def available(n: int) -> bool:
    """Size gate: the kernel handles n-element dedups up to
    :data:`PSORT_MAX_N` (padded); lax.sort is faster beyond."""
    return pad_size(n) <= PSORT_MAX_N


def _interpret() -> bool:
    return os.environ.get("JEPSEN_TPU_PSORT") == "interpret" or \
        not _on_tpu()


def _on_tpu() -> bool:
    return jax.devices()[0].platform == "tpu"


def _bitonic_sort(x, flat, lane_iota, *, S, K):
    """Full bitonic sort of x ([S, 128] u32, ascending in flat order).
    fori_loop over the K(K+1)/2 stages; partner exchange via dynamic
    lane/sublane rolls."""
    del lane_iota

    def stage(x, k, jj):
        j = jnp.uint32(1) << jj
        jl = jnp.where(jj < 7, j, 0).astype(jnp.int32)
        js = jnp.where(jj < 7, 0, j >> 7).astype(jnp.int32)
        upper = (flat & j) != 0
        p = jnp.where(
            upper,
            pltpu.roll(pltpu.roll(x, jl, 1), js, 0),
            pltpu.roll(pltpu.roll(x, (LANE - jl) % LANE, 1),
                       (S - js) % S, 0))
        desc = ((flat >> (k + 1)) & 1) == 1
        # keep x iff (x is the smaller) == (this position wants smaller)
        keep = (x < p) == (upper == desc)
        return jnp.where(keep | (x == p), x, p)

    def outer(k, x):
        def inner(t, x):
            return stage(x, jnp.uint32(k), jnp.uint32(k - t))
        return lax.fori_loop(0, k + 1, inner, x)

    return lax.fori_loop(0, K, outer, x)


def _dedup_body(key_ref, out_ref, total_ref, *, S, K):
    x = key_ref[:]
    lane = lax.broadcasted_iota(jnp.uint32, x.shape, 1)
    row = lax.broadcasted_iota(jnp.uint32, x.shape, 0)
    flat = row * LANE + lane

    x = _bitonic_sort(x, flat, lane, S=S, K=K)

    # prev[i] = x[i-1]: lane roll +1, wrapping lane 0 to the previous
    # row's lane 127 via a sublane roll.
    a = pltpu.roll(x, 1, 1)
    prev = jnp.where(lane == 0, pltpu.roll(a, 1, 0), a)
    keep = (x >> 31 == 0) & ((flat == 0) | (x != prev))
    total_ref[0] = jnp.sum(keep.astype(jnp.int32))
    x = jnp.where(keep, x, jnp.uint32(KEY_FILL))

    out_ref[:] = _bitonic_sort(x, flat, lane, S=S, K=K)


@partial(jax.jit, static_argnames=("n_pad",))
def _dedup_call(keys, n_pad):
    S = n_pad // LANE
    K = n_pad.bit_length() - 1
    out, total = pl.pallas_call(
        partial(_dedup_body, S=S, K=K),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((S, LANE), jnp.uint32),
                   jax.ShapeDtypeStruct((1,), jnp.int32)],
        input_output_aliases={0: 0},
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(keys.reshape(S, LANE))
    return out.reshape(-1), total[0]


def _assert_cap_contract(n: int, cap: int) -> None:
    """The dedup entry points promise ``keys[cap]`` outputs; that holds
    only when the padded kernel size covers cap (all engine call sites
    pass n >= cap — candidate arrays are cap*(1+M)). Enforce it so a
    future caller cannot silently break the fixed-shape lax.while_loop
    carries in bfs."""
    if pad_size(n) < cap:
        raise ValueError(
            f"psort dedup contract: pad_size({n})={pad_size(n)} < cap "
            f"{cap}; the output could not fill keys[cap]")


def _bitonic_sort2(hi, lo, flat, *, S, K):
    """Bitonic sort of (hi, lo) u32 pairs, ascending by the 64-bit
    lexicographic key. Same stage structure as _bitonic_sort with a
    pair compare-exchange."""
    def stage(hi, lo, k, jj):
        j = jnp.uint32(1) << jj
        jl = jnp.where(jj < 7, j, 0).astype(jnp.int32)
        js = jnp.where(jj < 7, 0, j >> 7).astype(jnp.int32)
        upper = (flat & j) != 0

        def partner(x):
            return jnp.where(
                upper,
                pltpu.roll(pltpu.roll(x, jl, 1), js, 0),
                pltpu.roll(pltpu.roll(x, (LANE - jl) % LANE, 1),
                           (S - js) % S, 0))

        p_hi = partner(hi)
        p_lo = partner(lo)
        desc = ((flat >> (k + 1)) & 1) == 1
        lt = (hi < p_hi) | ((hi == p_hi) & (lo < p_lo))
        eq = (hi == p_hi) & (lo == p_lo)
        keep = (lt == (upper == desc)) | eq
        return (jnp.where(keep, hi, p_hi), jnp.where(keep, lo, p_lo))

    def outer(k, c):
        def inner(t, c):
            return stage(*c, jnp.uint32(k), jnp.uint32(k - t))
        return lax.fori_loop(0, k + 1, inner, c)

    return lax.fori_loop(0, K, outer, (hi, lo))


def _dedup2_body(hi_ref, lo_ref, out_hi_ref, out_lo_ref, total_ref,
                 *, S, K):
    hi = hi_ref[:]
    lo = lo_ref[:]
    lane = lax.broadcasted_iota(jnp.uint32, hi.shape, 1)
    row = lax.broadcasted_iota(jnp.uint32, hi.shape, 0)
    flat = row * LANE + lane

    hi, lo = _bitonic_sort2(hi, lo, flat, S=S, K=K)

    def prev(x):
        a = pltpu.roll(x, 1, 1)
        return jnp.where(lane == 0, pltpu.roll(a, 1, 0), a)

    dup = (hi == prev(hi)) & (lo == prev(lo))
    keep = (hi >> 31 == 0) & ((flat == 0) | ~dup)
    total_ref[0] = jnp.sum(keep.astype(jnp.int32))
    hi = jnp.where(keep, hi, jnp.uint32(KEY_FILL))
    lo = jnp.where(keep, lo, jnp.uint32(KEY_FILL))

    out_hi_ref[:], out_lo_ref[:] = _bitonic_sort2(hi, lo, flat, S=S, K=K)


@partial(jax.jit, static_argnames=("n_pad",))
def _dedup2_call(hi, lo, n_pad):
    S = n_pad // LANE
    K = n_pad.bit_length() - 1
    out_hi, out_lo, total = pl.pallas_call(
        partial(_dedup2_body, S=S, K=K),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((S, LANE), jnp.uint32),
                   jax.ShapeDtypeStruct((S, LANE), jnp.uint32),
                   jax.ShapeDtypeStruct((1,), jnp.int32)],
        input_output_aliases={0: 0, 1: 1},
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(hi.reshape(S, LANE), lo.reshape(S, LANE))
    return out_hi.reshape(-1), out_lo.reshape(-1), total[0]


def dedup_keys2(hi, lo, valid, cap):
    """Pair-key twin of :func:`dedup_keys` for 64-bit packed configs
    (hi, lo u32; invalid flag goes into hi bit 31, so hi's payload must
    stay below 2^31). Returns (hi[cap], lo[cap], count, overflow) with
    survivors ascending by (hi, lo) and KEY_FILL padding."""
    n = hi.shape[0]
    _assert_cap_contract(n, cap)
    n_pad = pad_size(n)
    hi = hi | ((~valid).astype(jnp.uint32) << 31)
    if n_pad > n:
        pad = jnp.full(n_pad - n, KEY_FILL, jnp.uint32)
        hi = jnp.concatenate([hi, pad])
        lo = jnp.concatenate([lo, pad])
    out_hi, out_lo, total = _dedup2_call(hi, lo, n_pad)
    if out_hi.shape[0] > cap:
        out_hi = out_hi[:cap]
        out_lo = out_lo[:cap]
    overflow = total > cap
    count = jnp.minimum(total, cap)
    return out_hi, out_lo, count, overflow


def _flat_prev(x, d, S):
    """Value at flat index i-d (power-of-two d), clamped cyclically —
    callers mask position-0 effects via their start flags."""
    if d < LANE:
        a = pltpu.roll(x, d, 1)
        lane = lax.broadcasted_iota(jnp.uint32, x.shape, 1)
        return jnp.where(lane < d, pltpu.roll(a, 1, 0), a)
    return pltpu.roll(x, d // LANE, 0)


def _dedup_dom_body(masks_ref, a_ref, w_ref, out_ref, total_ref,
                    *, S, K, force=False):
    """Sort (group-part, dominance-word) pairs, drop duplicates and
    dominated entries (see bfs._dedup_keys_dom: the word packs crashed
    bits as-is and read bits complemented, so dominance is a single
    subset test), emit the recombined full keys ascending. a carries
    the invalid flag in bit 31; masks_ref = (cmask, rmask)."""
    a = a_ref[:]
    w = w_ref[:]
    cmask = masks_ref[0]
    rmask = masks_ref[1]
    lane = lax.broadcasted_iota(jnp.uint32, a.shape, 1)
    row = lax.broadcasted_iota(jnp.uint32, a.shape, 0)
    flat = row * LANE + lane
    first = flat == 0

    a, w = _bitonic_sort2(a, w, flat, S=S, K=K)
    keep = first
    for round_ in range(DOM_ITERS if force else 1):
        if round_:
            # Compact survivors (order-preserving re-sort of
            # FILL-masked pairs) so distant dominators become
            # chain-reachable — lax twin: bfs._dedup_keys_dom rounds.
            fill = jnp.uint32(KEY_FILL)
            a = jnp.where(keep, a, fill)
            w = jnp.where(keep, w, fill)
            a, w = _bitonic_sort2(a, w, flat, S=S, K=K)
        pa = _flat_prev(a, 1, S)
        dup = (a == pa) & (w == _flat_prev(w, 1, S)) & ~first
        start = first | (a != pa)
        # Segmented broadcast of each group's representative word (the
        # scan runs on u32 flags: bool-vector rolls don't reliably
        # lower).
        f = w
        done = start.astype(jnp.uint32)
        d = 1
        while d < (1 << K):
            f = jnp.where(done != 0, f, _flat_prev(f, d, S))
            done = done | _flat_prev(done, d, S)
            d <<= 1
        dominated = ((f & ~w) == 0) & (w != f)
        for dd in dom_window(S * LANE, force):
            a_d = _flat_prev(a, dd, S)
            w_d = _flat_prev(w, dd, S)
            dominated = dominated | (
                (flat >= dd) & (a_d == a) & ((w_d & ~w) == 0)
                & (w_d != w))
        if force:
            # Statically-unrolled chain scan over distances
            # 1..DOM_CHAIN (Mosaic cannot legalize the fori the lax
            # twin uses).
            ra, rw = a, w
            for dd in range(1, DOM_CHAIN + 1):
                ra = _flat_prev(ra, 1, S)
                rw = _flat_prev(rw, 1, S)
                dominated = dominated | (
                    (flat >= dd) & (ra == a) & ((rw & ~w) == 0)
                    & (rw != w))
        keep = (a >> 31 == 0) & ~dup & ~dominated
    total_ref[0] = jnp.sum(keep.astype(jnp.int32))
    full = jnp.where(
        keep,
        (a & jnp.uint32(0x7FFFFFFF)) | (w & cmask) | ((~w) & rmask),
        jnp.uint32(KEY_FILL))
    out_ref[:] = _bitonic_sort(full, flat, lane, S=S, K=K)


@partial(jax.jit, static_argnames=("n_pad", "force"))
def _dedup_dom_call(a, w, cmask, rmask, n_pad, force=False):
    S = n_pad // LANE
    K = n_pad.bit_length() - 1
    masks = jnp.stack([cmask, rmask]).astype(jnp.uint32)
    out, total = pl.pallas_call(
        partial(_dedup_dom_body, S=S, K=K, force=force),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((S, LANE), jnp.uint32),
                   jax.ShapeDtypeStruct((1,), jnp.int32)],
        input_output_aliases={1: 0},
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(masks, a.reshape(S, LANE), w.reshape(S, LANE))
    return out.reshape(-1), total[0]


def _assert_force_window_interpret_only(force_window: bool) -> None:
    """``force_window=True`` (the statically-unrolled DOM_CHAIN scan +
    iterated prune rounds) exists ONLY for interpret-mode parity tests
    against the lax chain path. On the real Mosaic backend it is
    compile-pathological (the unrolled 128-distance chain takes 20+
    minutes to compile) and KILLED the TPU worker mid-history in both
    round-5 runs that enabled it (probe_r5fc/fd, rows ~13-20k) — every
    production crash-dom call site therefore hard-codes the forced lax
    path (bfs._dedup_keys_dom/_dedup_keys2_dom with dom_force=True).
    Fail fast so a future caller cannot silently re-enter the
    known-unstable path."""
    if force_window and not _interpret():
        raise RuntimeError(
            "psort force_window dominance dedup must not run on the "
            "real Mosaic backend: it is compile-pathological and "
            "killed the TPU worker in both round-5 runs that enabled "
            "it; use the forced-lax chain path "
            "(bfs._dedup_keys_dom/_dedup_keys2_dom with "
            "dom_force=True) instead")


def dedup_keys_dom(a, w, cmask, rmask, cap, force_window=False):
    """In-VMEM twin of the lax path in ``bfs._dedup_keys_dom``. ``a`` is
    the group part (mutator bits + state) with the invalid flag already
    in bit 31; ``w`` the packed dominance word (crashed bits | inverted
    read bits); ``cmask``/``rmask`` u32 scalars for recombination.
    Returns (keys[cap] full-key ascending, count, overflow).

    ``force_window=True`` is interpret-mode-only (parity tests): see
    :func:`_assert_force_window_interpret_only`."""
    n = a.shape[0]
    _assert_force_window_interpret_only(force_window)
    _assert_cap_contract(n, cap)
    n_pad = pad_size(n)
    if n_pad > n:
        pad = jnp.full(n_pad - n, KEY_FILL, jnp.uint32)
        a = jnp.concatenate([a, pad])
        w = jnp.concatenate([w, jnp.zeros(n_pad - n, jnp.uint32)])
    out, total = _dedup_dom_call(a, w, cmask, rmask, n_pad,
                                 force=force_window)
    if out.shape[0] > cap:
        out = out[:cap]
    return out, jnp.minimum(total, cap), total > cap


def _bitonic_sort4(a, b, c, d, flat, *, S, K):
    """Bitonic sort of (a, b, c, d) u32 quads, ascending by the 128-bit
    lexicographic key. Same stage structure as _bitonic_sort2 with a
    4-word compare-exchange."""
    def stage(a, b, c, d, k, jj):
        j = jnp.uint32(1) << jj
        jl = jnp.where(jj < 7, j, 0).astype(jnp.int32)
        js = jnp.where(jj < 7, 0, j >> 7).astype(jnp.int32)
        upper = (flat & j) != 0

        def partner(x):
            return jnp.where(
                upper,
                pltpu.roll(pltpu.roll(x, jl, 1), js, 0),
                pltpu.roll(pltpu.roll(x, (LANE - jl) % LANE, 1),
                           (S - js) % S, 0))

        pa, pb, pc, pd = partner(a), partner(b), partner(c), partner(d)
        desc = ((flat >> (k + 1)) & 1) == 1
        lt = (a < pa) | ((a == pa) & (
            (b < pb) | ((b == pb) & (
                (c < pc) | ((c == pc) & (d < pd))))))
        eq = (a == pa) & (b == pb) & (c == pc) & (d == pd)
        keep = (lt == (upper == desc)) | eq
        return (jnp.where(keep, a, pa), jnp.where(keep, b, pb),
                jnp.where(keep, c, pc), jnp.where(keep, d, pd))

    def outer(k, q):
        def inner(t, q):
            return stage(*q, jnp.uint32(k), jnp.uint32(k - t))
        return lax.fori_loop(0, k + 1, inner, q)

    return lax.fori_loop(0, K, outer, (a, b, c, d))


def _dedup2_dom_body(masks_ref, a_hi_ref, a_lo_ref, w_hi_ref, w_lo_ref,
                     out_hi_ref, out_lo_ref, total_ref, *, S, K,
                     force=False):
    """Pair-key twin of _dedup_dom_body (see bfs._dedup_keys2_dom): sort
    by (group pair, dominance-word pair), drop duplicates and dominated
    entries, emit recombined full keys ascending by (hi, lo). masks_ref
    = (cmask_hi, cmask_lo, rmask_hi, rmask_lo)."""
    a_hi = a_hi_ref[:]
    a_lo = a_lo_ref[:]
    w_hi = w_hi_ref[:]
    w_lo = w_lo_ref[:]
    cmask_hi = masks_ref[0]
    cmask_lo = masks_ref[1]
    rmask_hi = masks_ref[2]
    rmask_lo = masks_ref[3]
    lane = lax.broadcasted_iota(jnp.uint32, a_hi.shape, 1)
    row = lax.broadcasted_iota(jnp.uint32, a_hi.shape, 0)
    flat = row * LANE + lane

    first = flat == 0
    a_hi, a_lo, w_hi, w_lo = _bitonic_sort4(a_hi, a_lo, w_hi, w_lo,
                                            flat, S=S, K=K)
    keep = first
    for round_ in range(DOM_ITERS if force else 1):
        if round_:
            # Order-preserving compaction between rounds (see
            # _dedup_dom_body).
            fill = jnp.uint32(KEY_FILL)
            a_hi = jnp.where(keep, a_hi, fill)
            a_lo = jnp.where(keep, a_lo, fill)
            w_hi = jnp.where(keep, w_hi, fill)
            w_lo = jnp.where(keep, w_lo, fill)
            a_hi, a_lo, w_hi, w_lo = _bitonic_sort4(
                a_hi, a_lo, w_hi, w_lo, flat, S=S, K=K)
        pah = _flat_prev(a_hi, 1, S)
        pal = _flat_prev(a_lo, 1, S)
        same_a = (a_hi == pah) & (a_lo == pal)
        dup = same_a & (w_hi == _flat_prev(w_hi, 1, S)) & \
            (w_lo == _flat_prev(w_lo, 1, S)) & ~first
        start = first | ~same_a
        fh = w_hi
        fl = w_lo
        done = start.astype(jnp.uint32)
        d = 1
        while d < (1 << K):
            fh = jnp.where(done != 0, fh, _flat_prev(fh, d, S))
            fl = jnp.where(done != 0, fl, _flat_prev(fl, d, S))
            done = done | _flat_prev(done, d, S)
            d <<= 1
        dominated = ((fh & ~w_hi) == 0) & ((fl & ~w_lo) == 0) & \
            ~((w_hi == fh) & (w_lo == fl))
        for dd in dom_window(S * LANE, force):
            ah_d = _flat_prev(a_hi, dd, S)
            al_d = _flat_prev(a_lo, dd, S)
            wh_d = _flat_prev(w_hi, dd, S)
            wl_d = _flat_prev(w_lo, dd, S)
            dominated = dominated | (
                (flat >= dd) & (ah_d == a_hi) & (al_d == a_lo)
                & ((wh_d & ~w_hi) == 0) & ((wl_d & ~w_lo) == 0)
                & ~((wh_d == w_hi) & (wl_d == w_lo)))
        if force:
            # Statically-unrolled chain scan (see _dedup_dom_body).
            rah, ral, rwh, rwl = a_hi, a_lo, w_hi, w_lo
            for dd in range(1, DOM_CHAIN + 1):
                rah = _flat_prev(rah, 1, S)
                ral = _flat_prev(ral, 1, S)
                rwh = _flat_prev(rwh, 1, S)
                rwl = _flat_prev(rwl, 1, S)
                dominated = dominated | (
                    (flat >= dd) & (rah == a_hi) & (ral == a_lo)
                    & ((rwh & ~w_hi) == 0) & ((rwl & ~w_lo) == 0)
                    & ~((rwh == w_hi) & (rwl == w_lo)))
        keep = (a_hi >> 31 == 0) & ~dup & ~dominated
    total_ref[0] = jnp.sum(keep.astype(jnp.int32))
    full_hi = jnp.where(
        keep,
        (a_hi & jnp.uint32(0x7FFFFFFF)) | (w_hi & cmask_hi)
        | ((~w_hi) & rmask_hi),
        jnp.uint32(KEY_FILL))
    full_lo = jnp.where(
        keep, a_lo | (w_lo & cmask_lo) | ((~w_lo) & rmask_lo),
        jnp.uint32(KEY_FILL))
    out_hi_ref[:], out_lo_ref[:] = _bitonic_sort2(full_hi, full_lo,
                                                  flat, S=S, K=K)


@partial(jax.jit, static_argnames=("n_pad", "force"))
def _dedup2_dom_call(a_hi, a_lo, w_hi, w_lo, masks, n_pad, force=False):
    S = n_pad // LANE
    K = n_pad.bit_length() - 1
    out_hi, out_lo, total = pl.pallas_call(
        partial(_dedup2_dom_body, S=S, K=K, force=force),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 4,
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((S, LANE), jnp.uint32),
                   jax.ShapeDtypeStruct((S, LANE), jnp.uint32),
                   jax.ShapeDtypeStruct((1,), jnp.int32)],
        input_output_aliases={1: 0, 2: 1},
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(masks, a_hi.reshape(S, LANE), a_lo.reshape(S, LANE),
      w_hi.reshape(S, LANE), w_lo.reshape(S, LANE))
    return out_hi.reshape(-1), out_lo.reshape(-1), total[0]


def dedup_keys2_dom(a_hi, a_lo, w_hi, w_lo, cmask_hi, cmask_lo,
                    rmask_hi, rmask_lo, cap, force_window=False):
    """In-VMEM twin of the lax path in ``bfs._dedup_keys2_dom``. ``a``
    pair carries group bits (invalid flag already in a_hi bit 31), ``w``
    pair the packed dominance words. Returns (hi[cap], lo[cap], count,
    overflow), survivors full-key ascending by (hi, lo).

    ``force_window=True`` is interpret-mode-only (parity tests): see
    :func:`_assert_force_window_interpret_only`."""
    n = a_hi.shape[0]
    _assert_force_window_interpret_only(force_window)
    _assert_cap_contract(n, cap)
    n_pad = pad_size(n)
    if n_pad > n:
        pad = jnp.full(n_pad - n, KEY_FILL, jnp.uint32)
        zpad = jnp.zeros(n_pad - n, jnp.uint32)
        a_hi = jnp.concatenate([a_hi, pad])
        a_lo = jnp.concatenate([a_lo, pad])
        w_hi = jnp.concatenate([w_hi, zpad])
        w_lo = jnp.concatenate([w_lo, zpad])
    masks = jnp.stack([cmask_hi, cmask_lo, rmask_hi, rmask_lo]) \
        .astype(jnp.uint32)
    out_hi, out_lo, total = _dedup2_dom_call(a_hi, a_lo, w_hi, w_lo,
                                             masks, n_pad,
                                             force=force_window)
    if out_hi.shape[0] > cap:
        out_hi = out_hi[:cap]
        out_lo = out_lo[:cap]
    return out_hi, out_lo, jnp.minimum(total, cap), total > cap


def _compact_body(key_ref, out_ref, total_ref, *, S, K):
    """Compaction-only kernel: callers have already masked dropped
    entries to KEY_FILL and guarantee survivors are DISTINCT (the
    return-event filter drops the same held bit from every survivor —
    injective), so one bitonic sort packs survivors ascending."""
    x = key_ref[:]
    lane = lax.broadcasted_iota(jnp.uint32, x.shape, 1)
    row = lax.broadcasted_iota(jnp.uint32, x.shape, 0)
    flat = row * LANE + lane
    total_ref[0] = jnp.sum((x != jnp.uint32(KEY_FILL)).astype(jnp.int32))
    out_ref[:] = _bitonic_sort(x, flat, lane, S=S, K=K)


@partial(jax.jit, static_argnames=("n_pad",))
def _compact_call(keys, n_pad):
    S = n_pad // LANE
    K = n_pad.bit_length() - 1
    out, total = pl.pallas_call(
        partial(_compact_body, S=S, K=K),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((S, LANE), jnp.uint32),
                   jax.ShapeDtypeStruct((1,), jnp.int32)],
        input_output_aliases={0: 0},
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(keys.reshape(S, LANE))
    return out.reshape(-1), total[0]


def compact_keys(keys, cap):
    """Pack the non-KEY_FILL entries of ``keys`` (distinct by caller
    contract) to an ascending prefix. Returns (keys[cap], count)."""
    n = keys.shape[0]
    _assert_cap_contract(n, cap)
    n_pad = pad_size(n)
    if n_pad > n:
        keys = jnp.concatenate(
            [keys, jnp.full(n_pad - n, KEY_FILL, jnp.uint32)])
    out, total = _compact_call(keys, n_pad)
    return out[:cap], jnp.minimum(total, cap)


def _compact2_body(hi_ref, lo_ref, out_hi_ref, out_lo_ref, total_ref,
                   *, S, K):
    hi = hi_ref[:]
    lo = lo_ref[:]
    lane = lax.broadcasted_iota(jnp.uint32, hi.shape, 1)
    row = lax.broadcasted_iota(jnp.uint32, hi.shape, 0)
    flat = row * LANE + lane
    live = (hi != jnp.uint32(KEY_FILL)) | (lo != jnp.uint32(KEY_FILL))
    total_ref[0] = jnp.sum(live.astype(jnp.int32))
    out_hi_ref[:], out_lo_ref[:] = _bitonic_sort2(hi, lo, flat, S=S, K=K)


@partial(jax.jit, static_argnames=("n_pad",))
def _compact2_call(hi, lo, n_pad):
    S = n_pad // LANE
    K = n_pad.bit_length() - 1
    out_hi, out_lo, total = pl.pallas_call(
        partial(_compact2_body, S=S, K=K),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((S, LANE), jnp.uint32),
                   jax.ShapeDtypeStruct((S, LANE), jnp.uint32),
                   jax.ShapeDtypeStruct((1,), jnp.int32)],
        input_output_aliases={0: 0, 1: 1},
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(hi.reshape(S, LANE), lo.reshape(S, LANE))
    return out_hi.reshape(-1), out_lo.reshape(-1), total[0]


def compact_keys2(hi, lo, cap):
    """Pair twin of :func:`compact_keys`: dropped entries are KEY_FILL
    in BOTH words; survivors distinct. Returns (hi[cap], lo[cap],
    count)."""
    n = hi.shape[0]
    _assert_cap_contract(n, cap)
    n_pad = pad_size(n)
    if n_pad > n:
        pad = jnp.full(n_pad - n, KEY_FILL, jnp.uint32)
        hi = jnp.concatenate([hi, pad])
        lo = jnp.concatenate([lo, pad])
    out_hi, out_lo, total = _compact2_call(hi, lo, n_pad)
    return out_hi[:cap], out_lo[:cap], jnp.minimum(total, cap)


def dedup_keys(key, valid, cap):
    """In-VMEM twin of ``bfs._dedup_keys``: single-u32-key sort-dedup
    (invalid flag in bit 31) with sort-based compaction, in one pallas
    kernel. Returns (keys[cap] ascending + KEY_FILL padding, count,
    overflow). Caller must have checked :func:`available`."""
    n = key.shape[0]
    _assert_cap_contract(n, cap)
    n_pad = pad_size(n)
    key = key | ((~valid).astype(jnp.uint32) << 31)
    if n_pad > n:
        key = jnp.concatenate(
            [key, jnp.full(n_pad - n, KEY_FILL, jnp.uint32)])
    out, total = _dedup_call(key, n_pad)
    if out.shape[0] > cap:
        out = out[:cap]
    overflow = total > cap
    count = jnp.minimum(total, cap)
    return out, count, overflow
